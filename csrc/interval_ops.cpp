// Host-side batched interval copy — the inner loop of the fixed-shape
// micro-batch packer (areal_tpu/models/packing.py).
//
// Role parity: the reference's csrc/interval_op extension (interval_op.cu
// copyDataKernel / slice_intervals / set_intervals) services its NCCL
// param-realloc flat-buffer slicing on GPU. On TPU, resharding is XLA's
// job, so the interval workload that remains is HOST-side: scattering a
// packed 1-D token stream into [R, L] grids (and gathering back) for
// every per-token key of every train step. NumPy does this with one
// Python-dispatched slice assignment per sequence; here it is one C call
// per key with tight memcpy loops.
//
// C ABI only (loaded via ctypes — no pybind11 in the image). All offsets
// are in ELEMENTS; `itemsize` converts to bytes, making the same entry
// point serve any fixed-size dtype (int32/float32/bf16/...).

#include <cstdint>
#include <cstring>

extern "C" {

// dst[rows[i], cols[i] : cols[i]+lens[i]] = src[offs[i] : offs[i]+lens[i]]
// dst is a [R, L, inner] row-major grid; inner elements per position are
// folded into itemsize by the caller.
void scatter_intervals(
    const uint8_t* src,
    uint8_t* dst,
    const int64_t* rows,
    const int64_t* cols,
    const int64_t* lens,
    const int64_t* offs,
    int64_t n_intervals,
    int64_t row_stride_elems,  // L * inner
    int64_t itemsize
) {
    for (int64_t i = 0; i < n_intervals; ++i) {
        std::memcpy(
            dst + (rows[i] * row_stride_elems + cols[i]) * itemsize,
            src + offs[i] * itemsize,
            static_cast<size_t>(lens[i]) * itemsize
        );
    }
}

// out[offs[i] : offs[i]+lens[i]] = src[rows[i], cols[i] : cols[i]+lens[i]]
void gather_intervals(
    const uint8_t* src,
    uint8_t* out,
    const int64_t* rows,
    const int64_t* cols,
    const int64_t* lens,
    const int64_t* offs,
    int64_t n_intervals,
    int64_t row_stride_elems,
    int64_t itemsize
) {
    for (int64_t i = 0; i < n_intervals; ++i) {
        std::memcpy(
            out + offs[i] * itemsize,
            src + (rows[i] * row_stride_elems + cols[i]) * itemsize,
            static_cast<size_t>(lens[i]) * itemsize
        );
    }
}

// O(n log n) first-fit-decreasing bin packing (reference datapack.py FFD
// allocate, reference csrc interval merge's sibling): writes each item's
// bin id into `bin_of` and returns the bin count. Bins are scanned
// first-fit over a running-load array.
int64_t ffd_assign(
    const int64_t* sizes,
    const int64_t* order,   // indices sorted by decreasing size
    int64_t n,
    int64_t capacity,
    int64_t* bin_of,        // out: bin id per item
    int64_t* loads,         // scratch: at least n entries
    int64_t* n_bins_out
) {
    int64_t n_bins = 0;
    for (int64_t k = 0; k < n; ++k) {
        int64_t i = order[k];
        int64_t s = sizes[i];
        int64_t b = -1;
        for (int64_t j = 0; j < n_bins; ++j) {
            if (loads[j] + s <= capacity) { b = j; break; }
        }
        if (b < 0) {
            b = n_bins++;
            loads[b] = 0;
        }
        loads[b] += s;
        bin_of[i] = b;
    }
    *n_bins_out = n_bins;
    return 0;
}

}  // extern "C"
