"""Elastic generation-fleet autoscaling: decision core, cordon-and-drain,
straggler defense, overload backpressure, and the launcher-side executor.

Covers ISSUE 11 (docs/fault_tolerance.md §Autoscaling):
 - FaultInjector latency injection (arm_delay/maybe_delay) with an
   injectable sleeper — deterministic under fake clocks
 - AutoscalerCore: hysteresis, per-direction cooldowns, [min, max]
   bounds, staleness-gate inhibition, overload latch at the max bound
 - StragglerTracker: peer-median scoring (self excluded), slow → cordon
   streaks, the noise floor
 - gserver manager: cordon keeps leases draining while blocking new
   ones, uncordon re-admits through the health gate, eviction of a
   cordoned server still retires its leases, straggler probes
   deprioritize then cordon a slow server, capacity denials carry
   Retry-After only while overloaded, the autoscale tick publishes the
   dynamic-spawn plan and scale-down cordons + WorkerControl-exits a
   drained dynamic victim
 - rollout worker: honors the denial's Retry-After (backpressure)
 - supervisor: an expendable (autoscaler-spawned) server that
   crash-loops is permanently removed WITHOUT escalating, and the
   executor replaces it within the plan's bounds

Every test runs on fake clocks, in-process fakes, or tiny aiohttp fake
servers — zero real sleeps beyond sub-second aiohttp round-trips.
"""

import asyncio
import json

import pytest

from areal_tpu.api.train_config import AutoscaleConfig
from areal_tpu.base import name_resolve, names, network
from areal_tpu.base.retry import FaultInjector
from areal_tpu.system.autoscaler import (
    AutoscaleExecutor,
    AutoscalerCore,
    FleetSignals,
    StragglerTracker,
    publish_plan,
    read_plan,
)
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    _ServerHealth,
)

EXP, TRIAL = "autoscaletest", "t0"


class _Req:
    def __init__(self, d=None, headers=None):
        self._d = d or {}
        self.headers = headers or {}

    async def json(self):
        return self._d


def _cfg(**asc_kw) -> GserverManagerConfig:
    asc = AutoscaleConfig(enabled=True, **asc_kw)
    return GserverManagerConfig(experiment=EXP, trial=TRIAL, autoscale=asc)


def _mgr(**asc_kw) -> GserverManager:
    return GserverManager(_cfg(**asc_kw))


def _add_server(mgr, url, server_id="", routable=True):
    st = _ServerHealth(routable=routable)
    st.server_id = server_id
    mgr.health[url] = st
    if routable:
        mgr.servers.append(url)
        mgr.servers.sort()
        mgr._inflight.setdefault(url, 0)
    return st


# ------------------------------------------------------- retry.py delays


@pytest.mark.autoscale
@pytest.mark.chaos
def test_fault_injector_delay_mode_deterministic():
    slept = []

    async def fake_sleep(secs):
        slept.append(secs)  # deterministic: records, never waits

    inj = FaultInjector(sleeper=fake_sleep)
    inj.arm_delay("decode", 0.8, times=2,
                  when=lambda ctx: ctx.get("server_id") == "gen1")

    async def main():
        # Filtered out: wrong server.
        assert await inj.maybe_delay("decode", server_id="gen0") == 0.0
        assert await inj.maybe_delay("decode", server_id="gen1") == 0.8
        assert await inj.maybe_delay("decode", server_id="gen1") == 0.8
        # times=2 exhausted.
        assert await inj.maybe_delay("decode", server_id="gen1") == 0.0

    asyncio.run(main())
    assert slept == [0.8, 0.8]
    assert inj.fired["decode"] == 2
    # delay_for consumes charges without sleeping (fake-server seam).
    inj.arm_delay("decode", 0.5, times=-1)
    assert inj.delay_for("decode") == 0.5
    assert inj.delay_for("decode") == 0.5
    inj.disarm("decode")
    assert inj.delay_for("decode") == 0.0
    # Failure arming is independent of delay arming.
    inj.arm("decode", times=1)
    with pytest.raises(Exception):
        inj.maybe_fail("decode")


# ------------------------------------------------------- decision core


@pytest.mark.autoscale
def test_core_hysteresis_cooldown_and_bounds():
    t = [0.0]
    cfg = AutoscaleConfig(
        enabled=True, min_servers=1, max_servers=3,
        up_consecutive=2, down_consecutive=2,
        scale_up_cooldown_secs=10.0, scale_down_cooldown_secs=20.0,
        up_utilization=0.8, down_utilization=0.2,
        queue_high=8.0, queue_low=1.0,
    )
    core = AutoscalerCore(cfg, clock=lambda: t[0])
    hot = FleetSignals(current_size=1, utilization=0.95)
    # One hot interval is not enough (hysteresis).
    assert core.observe(hot) is None
    assert core.target == 1
    a = core.observe(hot)
    assert a == {"action": "up", "target": 2,
                 "reason": a["reason"]} and "utilization" in a["reason"]
    # Cooldown holds even under sustained pressure.
    assert core.observe(hot) is None
    assert core.observe(hot) is None
    t[0] = 11.0
    assert core.observe(hot)["target"] == 3
    # Pinned at max: no further growth, ever.
    t[0] = 30.0
    for _ in range(5):
        assert core.observe(hot) is None
    assert core.target == 3
    # Idle fleet scales down after down_consecutive + its own cooldown.
    idle = FleetSignals(current_size=3, utilization=0.0, queue_depth=0.0)
    t[0] = 100.0
    assert core.observe(idle) is None
    a = core.observe(idle)
    assert a["action"] == "down" and core.target == 2
    # A single hot interval resets the down streak.
    assert core.observe(idle) is None
    core.observe(hot)
    t[0] = 200.0
    assert core.observe(idle) is None  # streak restarted
    a = core.observe(idle)
    assert a["action"] == "down" and core.target == 1
    # Floor: never below min_servers.
    t[0] = 300.0
    for _ in range(5):
        assert core.observe(idle) is None
    assert core.target == 1


@pytest.mark.autoscale
def test_core_staleness_gate_inhibits_scale_up_and_overload_latches():
    t = [0.0]
    cfg = AutoscaleConfig(
        enabled=True, min_servers=1, max_servers=2,
        up_consecutive=1, scale_up_cooldown_secs=0.0,
        up_utilization=0.8,
    )
    core = AutoscalerCore(cfg, clock=lambda: t[0])
    # Saturated BUT the staleness gate is closed: the trainer is the
    # bottleneck — more generation capacity would only go off-policy.
    staled = FleetSignals(current_size=1, utilization=1.0, staled=True)
    assert core.observe(staled) is None
    assert core.target == 1 and not core.overloaded
    hot = FleetSignals(current_size=1, utilization=1.0)
    t[0] = 1.0
    assert core.observe(hot)["target"] == 2
    # At max and still saturated: overloaded latches (backpressure on).
    t[0] = 2.0
    assert core.observe(FleetSignals(current_size=2, utilization=1.0)) is None
    assert core.overloaded
    # Pressure gone: the latch clears.
    assert core.observe(FleetSignals(current_size=2, utilization=0.0)) is None
    assert not core.overloaded


@pytest.mark.autoscale
def test_core_wedged_heartbeats_count_against_capacity():
    cfg = AutoscaleConfig(enabled=True, min_servers=1, max_servers=4)
    core = AutoscalerCore(cfg, clock=lambda: 0.0)
    # 3 routable but 2 wedged: effective capacity is 1.
    core.observe(FleetSignals(current_size=3, stale_heartbeats=2))
    assert core.target == 1


# ------------------------------------------------------- straggler scoring


@pytest.mark.autoscale
def test_straggler_tracker_peer_median_scoring():
    tr = StragglerTracker(factor=3.0, min_probes=3, slow_sweeps=2,
                          cordon_sweeps=4, floor_secs=0.002)
    urls = ["a", "b", "c"]
    # Below the noise floor nothing is ever slow, however skewed.
    for _ in range(5):
        tr.observe("a", 0.0001)
        tr.observe("b", 0.0001)
        tr.observe("c", 0.001)
        assert tr.sweep(urls)["c"] == "ok"
    tr = StragglerTracker(factor=3.0, min_probes=3, slow_sweeps=2,
                          cordon_sweeps=4, floor_secs=0.002)
    verdicts = []
    for i in range(8):
        tr.observe("a", 0.010)
        tr.observe("b", 0.012)
        tr.observe("c", 0.100)  # ~9x the peer median
        verdicts.append(tr.sweep(urls)["c"])
    # Not judged before min_probes; then slow after slow_sweeps
    # consecutive over-factor sweeps; cordon after cordon_sweeps.
    assert verdicts[0] == "ok" and verdicts[1] == "ok"
    assert "slow" in verdicts
    assert verdicts[-1] == "cordon"
    assert verdicts.index("slow") < verdicts.index("cordon")
    # The fast peers are never flagged (peer median excludes self, so
    # the straggler cannot drag the baseline toward itself).
    assert tr.sweep(urls)["a"] == "ok" and tr.sweep(urls)["b"] == "ok"
    # A lone server has no peers to be judged against.
    solo = StragglerTracker(min_probes=1)
    solo.observe("x", 5.0)
    assert solo.sweep(["x"])["x"] == "ok"


# ------------------------------------------------------- cordon mechanics


@pytest.mark.autoscale
@pytest.mark.chaos
def test_cordon_blocks_new_leases_drains_existing_then_uncordon():
    async def main():
        mgr = _mgr()
        u1, u2 = "http://s1:1", "http://s2:2"
        _add_server(mgr, u1, "gen0")
        _add_server(mgr, u2, "gen1")
        # A live lease on s1, then cordon it.
        resp = await mgr.handle_schedule_request(_Req())
        lease = json.loads(resp.body.decode())
        victim = lease["url"]
        other = u2 if victim == u1 else u1
        assert mgr.cordon(victim, "preemption notice") is True
        assert mgr.cordon(victim, "again") is False  # idempotent
        st = mgr.health[victim]
        assert st.cordoned and not st.routable
        # New scheduling avoids the cordoned server entirely...
        for _ in range(4):
            r = await mgr.handle_schedule_request(_Req())
            assert json.loads(r.body.decode())["url"] == other
        # ...but the existing lease stays valid (drain, don't kill) and
        # its renewals still work.
        r = await mgr.handle_renew(_Req({"lease_id": lease["lease_id"]}))
        assert json.loads(r.body.decode())["ok"]
        assert mgr._server_draining_load(victim) == 1
        # The health loop never re-admits a cordoned server.
        mgr._admit(victim)
        assert victim not in mgr.servers
        # Release completes the drain.
        await mgr.handle_release(_Req({"lease_id": lease["lease_id"]}))
        assert mgr._server_draining_load(victim) == 0
        # Uncordon does NOT route immediately — re-admission goes back
        # through the health gate (probe + weight reconcile).
        assert mgr.uncordon(victim) is True
        assert victim not in mgr.servers
        assert not mgr.health[victim].cordoned
        mgr._admit(victim)  # the health loop's re-admission path
        assert victim in mgr.servers

    asyncio.run(main())


@pytest.mark.autoscale
@pytest.mark.chaos
def test_evicting_a_cordoned_server_still_retires_its_leases():
    """Deregistration (or death) of a cordoned server must drop its
    draining leases even though cordon already took it out of routing —
    the old _evict early-return would have leaked them until TTL."""

    async def main():
        mgr = _mgr()
        u1, u2 = "http://s1:1", "http://s2:2"
        _add_server(mgr, u1, "gen0")
        _add_server(mgr, u2, "gen1")
        for _ in range(2):
            await mgr.handle_schedule_request(_Req())
        victim = next(u for u, _ in mgr._leases.values())
        mgr.cordon(victim, "preemption")
        assert mgr._server_draining_load(victim) >= 1
        mgr._evict(victim, "deregistered from name_resolve")
        assert mgr._server_draining_load(victim) == 0
        assert all(u != victim for u, _ in mgr._leases.values())

    asyncio.run(main())


@pytest.mark.autoscale
@pytest.mark.chaos
def test_pick_server_deprioritizes_stragglers_until_none_left():
    async def main():
        mgr = _mgr()
        u1, u2 = "http://s1:1", "http://s2:2"
        _add_server(mgr, u1, "gen0")
        _add_server(mgr, u2, "gen1")
        mgr.health[u2].deprioritized = True
        for _ in range(4):
            assert mgr._pick_server() == u1
        # The straggler is still a last resort when it is all we have.
        mgr.servers.remove(u1)
        assert mgr._pick_server() == u2

    asyncio.run(main())


# ------------------------------------------------------- straggler e2e(ish)


def _fake_health_app(state):
    """Minimal generation-server stand-in: /health reports the decode
    EWMA a FaultInjector delay point injects — the same seam the real
    server's _runner folds injected latency through."""
    from aiohttp import web

    async def health(req):
        base = 0.010
        extra = state["inj"].delay_for("decode",
                                       server_id=state["server_id"])
        return web.json_response({
            "ok": True, "version": 0, "server_id": state["server_id"],
            "queue_depth": 0, "decode_ewma_secs": base + extra,
            "ttfc_ewma_secs": 0.0,
        })

    app = web.Application()
    app.router.add_get("/health", health)
    return app


async def _start_app(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    port = network.find_free_port()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner, f"http://127.0.0.1:{port}"


@pytest.mark.autoscale
@pytest.mark.chaos
def test_injected_decode_latency_deprioritizes_then_cordons(
        tmp_name_resolve):
    """THE straggler acceptance path: a server with injected decode
    latency (FaultInjector delay mode) is deprioritized, then cordoned,
    purely from the /health-reported EWMAs — and the fleet keeps routing
    to the healthy peers throughout."""
    import aiohttp

    inj = FaultInjector()
    # Every probe of gen2 reports +200ms decode latency: a straggler.
    inj.arm_delay("decode", 0.200, times=-1,
                  when=lambda ctx: ctx.get("server_id") == "gen2")

    async def main():
        mgr = GserverManager(_cfg(
            straggler_min_probes=2, straggler_slow_sweeps=2,
            straggler_cordon_sweeps=4, straggler_factor=3.0,
        ))
        runners = []
        urls = {}
        try:
            for sid in ("gen0", "gen1", "gen2"):
                runner, url = await _start_app(
                    _fake_health_app({"inj": inj, "server_id": sid})
                )
                runners.append(runner)
                urls[sid] = url
                name_resolve.add(names.gen_servers(EXP, TRIAL, sid), url,
                                 replace=True)
            straggler = urls["gen2"]
            seen = []
            async with aiohttp.ClientSession() as sess:
                for _ in range(8):
                    await mgr.check_fleet(sess)
                    st = mgr.health.get(straggler)
                    seen.append(
                        "cordoned" if (st and st.cordoned)
                        else "slow" if (st and st.deprioritized)
                        else "ok"
                    )
                    if seen[-1] == "cordoned":
                        break
            assert "slow" in seen, seen  # deprioritized first...
            assert seen[-1] == "cordoned", seen  # ...then cordoned
            assert seen.index("slow") < len(seen) - 1
            # Healthy peers were never touched and still route.
            assert sorted(mgr.servers) == sorted(
                [urls["gen0"], urls["gen1"]]
            )
            assert mgr._pick_server() in (urls["gen0"], urls["gen1"])
            assert mgr.health[straggler].cordon_reason.startswith(
                "straggler"
            )
        finally:
            for r in runners:
                await r.cleanup()

    asyncio.run(main())


# ------------------------------------------------------- backpressure


@pytest.mark.autoscale
@pytest.mark.chaos
def test_capacity_denials_carry_retry_after_only_while_overloaded():
    async def main():
        mgr = _mgr(max_servers=1, up_consecutive=1,
                   backpressure_retry_secs=3.5)
        _add_server(mgr, "http://s1:1", "gen0")
        mgr.cfg.max_concurrent_rollouts = 2
        mgr.running_rollouts = 2  # saturated
        # Not overloaded yet: plain capacity denial, clients poll at
        # their default cadence.
        r = await mgr.handle_allocate_rollout(_Req({"n_samples": 1}))
        d = json.loads(r.body.decode())
        assert d == {"allowed": False, "reason": "capacity"}
        # One tick pins the fleet at max under saturation -> overloaded.
        mgr._autoscale_tick()
        assert mgr._overloaded
        r = await mgr.handle_allocate_rollout(_Req({"n_samples": 1}))
        d = json.loads(r.body.decode())
        assert d["reason"] == "capacity" and d["retry_after"] == 3.5
        # Load clears -> the hint disappears with the latch.
        mgr.running_rollouts = 0
        mgr._autoscale_tick()
        mgr.running_rollouts = 2
        r = await mgr.handle_allocate_rollout(_Req({"n_samples": 1}))
        assert "retry_after" not in json.loads(r.body.decode())

    asyncio.run(main())


@pytest.mark.autoscale
@pytest.mark.chaos
def test_rollout_worker_honors_denial_retry_after(monkeypatch):
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )

    w = RolloutWorker.__new__(RolloutWorker)  # skip dataset/agent init
    w.cfg = RolloutWorkerConfig()
    w._mgr_url0 = "http://mgr:1"

    async def fake_post(session, url, payload, timeout_secs=15.0):
        return {"allowed": False, "reason": "capacity", "retry_after": 2.75}

    w._post_json = fake_post
    slept = []

    async def fake_sleep(secs):
        slept.append(secs)

    monkeypatch.setattr(asyncio, "sleep", fake_sleep)

    async def main():
        return await w._rollout_one(None, "q0", None, None, None)

    assert asyncio.run(main()) == "retry"
    assert slept == [2.75]  # the hint, not the 0.5s default


# ------------------------------------------------------- plan + executor


class _FakeSupervisorCounts:
    def __init__(self):
        self.alive = 0
        self._draining = False

    def alive_count(self, kind):
        return self.alive


@pytest.mark.autoscale
def test_plan_roundtrip_and_executor_spawns_with_cooldown(tmp_name_resolve):
    t = [0.0]
    sup = _FakeSupervisorCounts()
    spawned = []

    def spawn(sid):
        spawned.append(sid)
        sup.alive += 1

    ex = AutoscaleExecutor(EXP, TRIAL, sup, spawn,
                           spawn_cooldown_secs=5.0, clock=lambda: t[0])
    assert ex.step() is None  # no plan yet
    publish_plan(EXP, TRIAL, {"target": 3, "dynamic": 2, "ts": 1.0})
    assert read_plan(EXP, TRIAL)["dynamic"] == 2
    assert ex.step() == "dyn1"
    # Cooldown: the second spawn waits even though the plan wants 2.
    assert ex.step() is None
    t[0] = 6.0
    assert ex.step() == "dyn2"
    t[0] = 20.0
    assert ex.step() is None  # satisfied
    assert spawned == ["dyn1", "dyn2"]
    # A removed (crash-looped) server drops the count -> replaced with a
    # FRESH id, never a reused one.
    sup.alive = 1
    assert ex.step() == "dyn3"
    # Draining supervisor: the executor stands down.
    sup.alive = 0
    sup._draining = True
    t[0] = 40.0
    assert ex.step() is None


@pytest.mark.autoscale
@pytest.mark.chaos
def test_autoscale_tick_publishes_plan_and_scale_down_cordons_dynamic(
        tmp_name_resolve, monkeypatch):
    async def main():
        mgr = _mgr(min_servers=1, max_servers=3, up_consecutive=1,
                   scale_up_cooldown_secs=0.0)
        _add_server(mgr, "http://s1:1", "gen0")
        mgr.cfg.max_concurrent_rollouts = 4
        mgr.running_rollouts = 4  # hot
        mgr._autoscale_tick()
        plan = read_plan(EXP, TRIAL)
        # Target grew past the 1 alive baseline -> 1 dynamic wanted.
        assert plan["target"] == 2 and plan["dynamic"] == 1
        assert mgr.autoscaler.target == 2
        # The dynamic server joins; now force a scale-down and verify the
        # victim choice (dynamic before baseline) + the commanded exit.
        _add_server(mgr, "http://s2:2", "dyn1")
        mgr.running_rollouts = 0
        mgr.autoscaler.target = 1
        exits = []
        monkeypatch.setattr(
            mgr, "_command_server_exit",
            lambda sid: exits.append(sid) or True,
        )
        mgr._autoscale_tick()
        st = mgr.health["http://s2:2"]
        assert st.cordoned and st.cordon_reason.startswith("scale-down")
        assert "http://s2:2" not in mgr.servers
        await mgr._drain_cordoned()  # no leases -> drained immediately
        assert exits == ["dyn1"]
        assert st.exit_commanded
        assert read_plan(EXP, TRIAL)["dynamic"] == 0

    asyncio.run(main())


@pytest.mark.autoscale
@pytest.mark.chaos
def test_scale_down_reclaims_cordoned_baseline_before_spawning(
        tmp_name_resolve):
    async def main():
        mgr = _mgr(min_servers=1, max_servers=3)
        _add_server(mgr, "http://s1:1", "gen0")
        _add_server(mgr, "http://s2:2", "gen1")
        mgr.autoscaler.target = 1
        mgr._autoscale_tick()  # cordon one baseline for scale-down
        cordoned = [u for u, st in mgr.health.items() if st.cordoned]
        assert len(cordoned) == 1
        # Pressure returns: reclaim the healthy cordoned baseline (it
        # still holds near-current weights) instead of spawning cold.
        mgr.autoscaler.target = 2
        mgr._autoscale_tick()
        assert not mgr.health[cordoned[0]].cordoned
        assert read_plan(EXP, TRIAL)["dynamic"] == 0

    asyncio.run(main())


# ------------------------------------------------------- flapping server


class _FakeProc:
    _next_pid = [2000]

    def __init__(self):
        _FakeProc._next_pid[0] += 1
        self.pid = _FakeProc._next_pid[0]
        self._alive = True
        self.exitcode = None

    def is_alive(self):
        return self._alive

    def die(self, code):
        self._alive = False
        self.exitcode = code

    def join(self, timeout=None):
        pass

    def terminate(self):
        self.die(-15)

    def kill(self):
        self.die(-9)


@pytest.mark.autoscale
@pytest.mark.chaos
def test_flapping_server_trips_breaker_removed_not_escalated(
        tmp_name_resolve):
    """ISSUE 11 satellite: a generation server that crashes repeatedly
    inside the crash-loop window trips the circuit breaker and is
    PERMANENTLY removed from the fleet — no SupervisorEscalation, no
    whole-run relaunch — and the executor replaces it (fresh spec, fresh
    id) within the plan's bounds."""
    from areal_tpu.system.supervisor import (
        RestartPolicy,
        Supervisor,
        WorkerSpec,
    )

    t = [0.0]
    sup = Supervisor(EXP, TRIAL,
                     policy=RestartPolicy(max_restarts=2, window_secs=100.0,
                                          backoff_base_secs=0.1,
                                          backoff_max_secs=0.1),
                     clock=lambda: t[0])
    sup._make_proc = lambda spec, incarnation: _FakeProc()
    sup.spawn(WorkerSpec(name="genserver_dyn1", kind="gen_server",
                         target=lambda: None, required=False,
                         expendable=True))
    entry = sup._entries["genserver_dyn1"]
    assert sup.alive_count("gen_server") == 1
    # Crash -> respawn (x2), then the breaker trips on the third death.
    for _ in range(2):
        entry.proc.die(1)
        sup.check()  # classify + schedule respawn
        t[0] += 0.2
        sup.check()  # execute the respawn
        assert entry.proc.is_alive()
    entry.proc.die(1)
    sup.check()  # breaker trips: MUST NOT raise SupervisorEscalation
    assert entry.done
    assert sup.alive_count("gen_server") == 0
    assert sup.restart_counts.get("gen_server") == 2
    # The autoscaler replaces the removed server within bounds.
    publish_plan(EXP, TRIAL, {"target": 2, "dynamic": 1, "ts": 1.0})
    spawned = []
    ex = AutoscaleExecutor(EXP, TRIAL, sup, spawned.append,
                           clock=lambda: t[0])
    ex.step()
    assert spawned == ["dyn1"]  # executor ids are its own sequence

    # A NON-expendable stateless worker still escalates on a crash loop
    # (the pre-existing contract is untouched).
    from areal_tpu.system.supervisor import SupervisorEscalation

    sup2 = Supervisor(EXP, TRIAL,
                      policy=RestartPolicy(max_restarts=1,
                                           window_secs=100.0,
                                           backoff_base_secs=0.1),
                      clock=lambda: t[0])
    sup2._make_proc = lambda spec, incarnation: _FakeProc()
    sup2.spawn(WorkerSpec(name="rollout0", kind="rollout",
                          target=lambda: None))
    e2 = sup2._entries["rollout0"]
    e2.proc.die(1)
    sup2.check()
    t[0] += 0.2
    sup2.check()
    e2.proc.die(1)
    with pytest.raises(SupervisorEscalation):
        sup2.check()


# ------------------------------------------------------- live e2e (slow)


@pytest.mark.slow
@pytest.mark.autoscale
@pytest.mark.chaos
@pytest.mark.timeout(900)
def test_autoscale_e2e_load_spike_then_preemption_drain(tmp_path):
    """THE ISSUE 11 acceptance run: a live launcher-supervised async-PPO
    experiment under a synthetic load spike (tiny rollout quota, eager
    thresholds) GROWS the fleet — the manager's plan makes the executor
    spawn dynamic servers that join via discovery + streamed-weight
    admission (no checkpoint round-trip) — then a simulated preemption
    notice cordons two servers, which drain with zero lost rollouts
    (clients fail over), the run completes its full step count, and the
    merged Prometheus scrape shows nonzero autoscale scale-up and
    scale-down counters plus the target/current fleet-size gauges."""
    import threading
    import time as _time
    import urllib.request

    from test_fault_tolerance import (
        _build_supervised_async_cfg,
        _wait_master_step,
    )

    from areal_tpu.apps.launcher import LocalLauncher
    from areal_tpu.base import network as _network
    from areal_tpu.experiments import common as C

    port = _network.find_free_port()
    cfg = _build_supervised_async_cfg(tmp_path, "autoscl",
                                      benchmark_steps=40, http_port=port)
    # Synthetic load spike: a 4-slot rollout quota saturates instantly,
    # and eager thresholds/cooldowns scale within a few 0.5s intervals.
    cfg.autoscale.enabled = True
    cfg.autoscale.min_servers = 1
    cfg.autoscale.max_servers = 3
    cfg.autoscale.interval_secs = 0.5
    cfg.autoscale.up_consecutive = 2
    cfg.autoscale.scale_up_cooldown_secs = 1.0
    cfg.autoscale.up_utilization = 0.75
    cfg.autoscale.drain_timeout_secs = 20.0
    cfg.autoscale.straggler_defense = False  # this run tests elasticity
    C.setup_name_resolve(cfg)
    launcher = LocalLauncher(cfg)
    result, errs = {}, []

    def _run():
        try:
            result.update(launcher.run())
        except BaseException as e:  # noqa: BLE001 — asserted below
            errs.append(e)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    mgr_url = None
    try:
        _wait_master_step("autoscl", "t0", 1)
        mgr_url = name_resolve.wait(
            names.gen_server_manager("autoscl", "t0"), timeout=60
        )

        def fleet():
            with urllib.request.urlopen(
                f"{mgr_url}/metrics.json", timeout=10
            ) as r:
                return json.loads(r.read().decode())

        # ---- scale-up: the fleet grows beyond the 1-server baseline,
        # and the joiner is a supervisor-spawned dynamic server admitted
        # at the CURRENT weight version (streamed reconcile; with
        # weight_sync.transport=stream no realloc checkpoint exists to
        # round-trip through).
        deadline = _time.monotonic() + 240
        grown = None
        while _time.monotonic() < deadline and t.is_alive():
            m = fleet()
            dyn = [
                (u, st) for u, st in m["fleet"].items()
                if st["server_id"].startswith("dyn") and st["routable"]
            ]
            if m["healthy_servers"] >= 2 and dyn:
                grown = m
                break
            _time.sleep(0.5)
        assert grown is not None, "fleet never scaled up"
        assert grown["autoscale"]["target_size"] >= 2
        for u, st in grown["fleet"].items():
            if st["server_id"].startswith("dyn") and st["routable"]:
                assert st["acked_version"] == grown["version"]
        assert any(
            n.startswith("genserver_dyn")
            for n in launcher.supervisor._entries
        )

        # ---- simulated preemption notice on two servers -> cordon.
        m = fleet()
        routable = [u for u, st in m["fleet"].items() if st["routable"]]
        assert len(routable) >= 2
        victims = routable[:2]
        for v in victims:
            body = json.dumps(
                {"url": v, "reason": "preemption notice"}
            ).encode()
            req = urllib.request.Request(
                f"{mgr_url}/cordon", data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read().decode())["ok"]

        # Both drain (leases released or failed over), within the budget.
        deadline = _time.monotonic() + 120
        drained = False
        while _time.monotonic() < deadline and t.is_alive():
            m = fleet()
            states = [m["fleet"].get(v) for v in victims]
            if all(
                st is None or (st["cordoned"] and st["draining"] == 0)
                for st in states
            ):
                drained = True
                break
            _time.sleep(0.5)
        assert drained, "cordoned servers never drained"

        # ---- the merged scrape carries the autoscale counters/gauges.
        scrape = None
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline and t.is_alive():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as r:
                    body = r.read().decode()
                if ("areal_autoscale_scale_up_total" in body
                        and "areal_autoscale_scale_down_total" in body):
                    scrape = body
                    break
            except Exception:  # noqa: BLE001 — aggregator busy
                pass
            _time.sleep(0.3)
        assert scrape is not None, "autoscale metrics never scraped"

        def _total(name):
            return sum(
                float(ln.rpartition(" ")[2])
                for ln in scrape.splitlines()
                if ln.startswith(name) and not ln.startswith("#")
            )

        assert _total("areal_autoscale_scale_up_total") >= 1
        assert _total("areal_autoscale_scale_down_total") >= 2
        assert "areal_autoscale_target_size" in scrape
        assert "areal_autoscale_current_size" in scrape

        # ---- zero lost rollouts: the run completes its full step count
        # (every admitted prompt either finished or failed over — an
        # abandoned rollout would starve the master short of 40 steps).
        t.join(timeout=600)
        assert not t.is_alive(), "experiment never completed"
        assert not errs, errs
        assert result["steps"] == 40
    finally:
        launcher.request_drain()
        t.join(timeout=30)
        if launcher.supervisor is not None:
            launcher.supervisor.shutdown(timeout=10.0, orderly=False)

