"""Math-grader parity corpus (docs/rewards.md §Parity corpus).

~200 (generated answer, ground-truth solutions, expected verdict) fixture
pairs spanning the reference grader's semantic surface — integers,
fractions/decimals, percent scaling, mixed numbers, scientific notation,
sqrt/pi symbolics, units/LaTeX noise, multiple choice, tuples, intervals,
matrices, equations, extraction rules, tolerance — checked into
tests/fixtures/math_parity_corpus.jsonl.

Entries carrying a ``divergence`` field are the documented allowlist of
KNOWN deviations from the reference grader (each records the reference's
verdict in ``reference_expected`` and why ours differs); everything else
must agree exactly. The allowlist is pinned by id here so a new
divergence cannot slip in silently.
"""

import json
import os

import pytest

from areal_tpu.rewards.client import batch_reward
from areal_tpu.rewards.math_verify import verify_math

pytestmark = pytest.mark.rewards

CORPUS = os.path.join(os.path.dirname(__file__), "fixtures",
                      "math_parity_corpus.jsonl")

# The documented allowlist (docs/rewards.md): bracket-type-sensitive
# intervals (two entries) and the 192-char symbolic comparison cap.
KNOWN_DIVERGENCES = {"p082", "p083", "p116"}


def _corpus():
    with open(CORPUS) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_corpus_shape_and_allowlist_pinned():
    entries = _corpus()
    assert len(entries) >= 200
    assert len({e["id"] for e in entries}) == len(entries)
    flagged = {e["id"] for e in entries if "divergence" in e}
    assert flagged == KNOWN_DIVERGENCES, (
        "divergence allowlist drifted — document any new deviation in the "
        "fixture AND docs/rewards.md, then pin it here"
    )
    for e in entries:
        if "divergence" in e:
            # every allowlisted entry records the reference's verdict and
            # actually DIFFERS from ours (else it isn't a divergence)
            assert e["reference_expected"] != e["expected"], e["id"]


def test_math_grader_agrees_on_whole_corpus():
    mism = []
    for e in _corpus():
        got = verify_math(e["generated"], e["solutions"])
        if got != e["expected"]:
            mism.append((e["id"], e.get("note"), e["expected"], got))
    assert not mism, f"{len(mism)} corpus mismatches: {mism[:10]}"


def test_disabled_service_batch_reward_bit_identical():
    """reward_service disabled (the default): batch_reward over the whole
    corpus is bit-identical to direct local grading — the acceptance
    contract for the off-by-default switch."""
    from areal_tpu.rewards import client as rc

    rc.configure_service(None)  # explicit: no service mode
    entries = _corpus()
    tasks = [{"task": "math", "generated": e["generated"],
              "solutions": e["solutions"]} for e in entries]
    got = batch_reward(tasks)
    direct = [verify_math(e["generated"], e["solutions"]) for e in entries]
    assert got == direct
    assert got == [e["expected"] for e in entries]
