"""Async rollout stack test: generation server + gserver manager + rollout
worker + chunked generation with version accounting and the staleness gate.
(The CPU analogue of the reference's tests/system/test_gserver_manager.py +
test_partial_rollout.py.)"""

import asyncio
import os

import numpy as np
import pytest

import jax

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.base import name_resolve, names
from areal_tpu.base.testing import MockTokenizer, make_math_jsonl
from areal_tpu.models import hf as hfmod
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.system.generation_server import (
    GenerationServer,
    GenerationServerConfig,
)
from areal_tpu.system.gserver_manager import GserverManager, GserverManagerConfig
from areal_tpu.system.rollout_worker import RolloutWorker, RolloutWorkerConfig
from areal_tpu.system.streams import ZmqPuller

EXP, TRIAL = "asynctest", "t0"


@pytest.fixture()
def env(tmp_path):
    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=6)
    cfg = tiny_config(vocab_size=258, n_layers=2, hidden_dim=32)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return data_path, cfg, params, str(tmp_path / "realloc")


@pytest.mark.timeout(300)
def test_async_rollout_stack(env):
    data_path, mcfg, params, realloc_dir = env

    async def main():
        server = GenerationServer(
            GenerationServerConfig(
                experiment=EXP, trial=TRIAL, server_id="gen0",
                chunk_tokens=4, prompt_bucket=16, batch_window_ms=2,
            ),
            mcfg, params,
        )
        await server.start()
        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=1,
            train_batch_size=4, max_head_offpolicyness=100,
            realloc_dir=realloc_dir, weight_poll_secs=0.2,
        ))
        await mgr.start()

        puller = ZmqPuller(EXP, TRIAL, "trainer")
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=10),
            group_size=2, chunk_tokens=4, max_concurrent=3,
            tokenizer=MockTokenizer(), max_rollouts=4,
            agent_args={"success_rate_lb": 0.0, "success_rate_ub": 1.0},
        ))
        await worker.run_async()

        # trajectories arrived over the push stream
        from areal_tpu.api.data import SequenceSample

        got = []
        for _ in range(200):
            obj = puller.pull(timeout_ms=50)
            if obj is None and got:
                break
            if obj is not None:
                got.append(SequenceSample.from_json_compatible(obj))
        # ≥ 4 rollouts × group 2 (in-flight rollouts may also complete)
        assert len(got) >= 8 and len(got) % 2 == 0
        t = got[0]
        assert {"packed_input_ids", "prompt_mask", "packed_logprobs",
                "rewards", "version_start", "version_end",
                "seq_no_eos_mask"} <= t.keys
        # chunked: multi-chunk generations happened (max_new_tokens=10, chunk 4)
        glens = [
            int((np.asarray(s.data["prompt_mask"]) == 0).sum()) for s in got
        ]
        assert max(glens) > 4  # at least one crossed a chunk boundary
        assert all(
            int(s.data["version_start"][0]) == 0
            and int(s.data["version_end"][0]) == 0
            for s in got
        )

        # ---- weight update fanout ----
        hfmod.save_hf_checkpoint(
            jax.device_get(server.params), mcfg,
            os.path.join(realloc_dir, "actor", "1"), meta={"version": 1},
        )
        name_resolve.add(
            names.model_version(EXP, TRIAL, "actor"), "1", replace=True
        )
        # Wait for BOTH: the server swaps inside its POST handler, but the
        # manager records the ack (and bumps its version) only when its
        # fanout coroutine resumes — sampling mgr.version the instant the
        # server flips races that one scheduling slot on the shared loop.
        for _ in range(50):
            if server.version == 1 and mgr.version == 1:
                break
            await asyncio.sleep(0.1)
        assert server.version == 1 and mgr.version == 1

        # ---- metric-target discovery (reference controller.py:41-74) ----
        import aiohttp

        async with aiohttp.ClientSession() as sess:
            async with sess.get(f"{mgr._url}/metrics_discovery") as r:
                groups = await r.json()
        roles = {g["labels"]["role"]: g["targets"] for g in groups}
        assert "generation_server" in roles and "gserver_manager" in roles
        assert len(roles["generation_server"]) == 1
        # targets are scrape-able host:port (no scheme)
        assert all("//" not in t for g in groups for t in g["targets"])

        await mgr.stop()
        await server.stop()
        puller.close()

    asyncio.run(main())


@pytest.mark.timeout(120)
def test_staleness_gate(env):
    data_path, mcfg, params, realloc_dir = env

    async def main():
        server = GenerationServer(
            GenerationServerConfig(experiment=EXP, trial=TRIAL,
                                   server_id="gen0"),
            mcfg, params,
        )
        await server.start()
        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=1,
            train_batch_size=2, max_head_offpolicyness=1,
        ))
        await mgr.start()
        import aiohttp

        url = name_resolve.get(names.gen_server_manager(EXP, TRIAL))
        async with aiohttp.ClientSession() as s:
            allowed = 0
            while True:
                async with s.post(f"{url}/allocate_rollout", json={}) as r:
                    d = await r.json()
                if not d["allowed"]:
                    assert d["reason"] == "staleness"
                    break
                allowed += 1
                # report as accepted → counts toward staleness
                async with s.post(f"{url}/finish_rollout",
                                  json={"accepted": True, "n_samples": 1}):
                    pass
                assert allowed < 50
            # (offpolicyness+1+1)*bs samples at version 0: gate closes at
            # expected_version > 1 + 0 → after 4+ accepted with bs=2
            assert allowed >= 4
        await mgr.stop()
        await server.stop()

    asyncio.run(main())


@pytest.mark.timeout(120)
def test_staleness_units_group_allocation(env):
    """Regression (VERDICT r2 weak#2): allocation and release must both be in
    SAMPLE units. With group_size=4, train_batch_size=4 and
    max_head_offpolicyness=0, the 1st prompt (4 samples) is allowed and the
    2nd prompt must be blocked until a train step lands (version bump) —
    both while the first is in flight and after it finishes."""
    data_path, mcfg, params, realloc_dir = env

    async def main():
        server = GenerationServer(
            GenerationServerConfig(experiment=EXP, trial=TRIAL,
                                   server_id="gen0"),
            mcfg, params,
        )
        await server.start()
        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=1,
            train_batch_size=4, max_head_offpolicyness=0,
        ))
        await mgr.start()
        import aiohttp

        group = 4
        url = name_resolve.get(names.gen_server_manager(EXP, TRIAL))
        async with aiohttp.ClientSession() as s:
            async def allocate():
                async with s.post(f"{url}/allocate_rollout",
                                  json={"n_samples": group}) as r:
                    return await r.json()

            d1 = await allocate()
            assert d1["allowed"]
            # 2nd prompt while 1st is in flight: (0 accepted + 4 running)
            # // 4 = 1 > offpolicyness 0 + version 0 → staled.
            d2 = await allocate()
            assert not d2["allowed"] and d2["reason"] == "staleness"
            # Finish the first rollout: release the SAME n allocated, with
            # only 2 of 4 samples accepted — running must drop to 0 (no
            # underflow toward the max(0,..) clamp), accepted counts 2.
            async with s.post(f"{url}/finish_rollout",
                              json={"accepted": True, "n_samples": group,
                                    "n_accepted": 2}):
                pass
            assert mgr.running_rollouts == 0
            assert mgr.accepted_rollouts == 2
            # Still blocked? (2+0)//4 = 0 ≤ 0 → allowed again; allocate and
            # finish fully-accepted to push accounting over the edge.
            d3 = await allocate()
            assert d3["allowed"]
            async with s.post(f"{url}/finish_rollout",
                              json={"accepted": True, "n_samples": group,
                                    "n_accepted": group}):
                pass
            # (6 accepted)//4 = 1 > 0 + version 0 → blocked until train lands.
            d4 = await allocate()
            assert not d4["allowed"] and d4["reason"] == "staleness"
            # Train step lands → version 1 → gate reopens.
            mgr.version = 1
            d5 = await allocate()
            assert d5["allowed"]
        await mgr.stop()
        await server.stop()

    asyncio.run(main())
