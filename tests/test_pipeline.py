"""Pipeline parallelism (parallel/pipeline.py): GPipe micro-batch streaming
over the "pp" mesh axis must be numerically identical to the plain
scan-over-layers forward, including gradients and MoE aux losses.

Parity target: the reference's pipeline_parallel instruction VM + schedules
(realhf/impl/model/parallelism/pipeline_parallel/, pipe_runner.py:148) —
there, correctness is established by comparing pipelined train/forward
against the non-pipelined engine; same strategy here on the 8-CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel import mesh as pmesh
from areal_tpu.parallel import pipeline as ppl
from areal_tpu.parallel import sharding as psh


def _batch(cfg, B=8, T=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    # two documents packed per row, one padded tail row
    seg[:, T // 2:] = 2
    seg[-1, T - 3:] = 0
    return tokens, positions, seg


def test_pick_pp_microbatches_gates():
    cfg = tiny_config(n_layers=4)
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2p2t2"))
    assert ppl.pick_pp_microbatches(None, cfg, 8) is None
    assert ppl.pick_pp_microbatches(m, cfg, 8) == 4  # auto: 2*pp
    assert ppl.pick_pp_microbatches(m, cfg, 6) == 3
    assert ppl.pick_pp_microbatches(m, cfg, 8, requested=2) == 2
    assert ppl.pick_pp_microbatches(m, cfg, 8, requested=3) is None  # 3∤8
    assert ppl.pick_pp_microbatches(m, cfg, 1) is None  # can't fill stages
    # layers must divide across stages
    cfg3 = tiny_config(n_layers=3)
    assert ppl.pick_pp_microbatches(m, cfg3, 8) is None
    # sp meshes fall back to GSPMD layer sharding
    msp = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2s2t2"))
    assert ppl.pick_pp_microbatches(msp, cfg, 8) is None
    # no pp axis
    mnp = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
    assert ppl.pick_pp_microbatches(mnp, cfg, 8) is None


@pytest.mark.parametrize("spec_str", ["p2", "p4", "d2p2t2"])
def test_pipeline_forward_parity(spec_str):
    """Pipelined logits == single-device logits (return_kv=False routes
    through the pipeline when the mesh has pp>1)."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    tokens, positions, seg = _batch(cfg)
    ref, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg, return_kv=False
    )

    m = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec_str))
    sp = psh.shard_params(params, m, cfg)

    def fwd(p, t, pos, s):
        with psh.activation_sharding(m):
            out, _ = transformer.forward(
                p, cfg, t, pos, segment_ids=s, return_kv=False
            )
        return out

    out = jax.jit(fwd)(sp, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pipeline_grad_parity():
    """jax.grad through the pipeline (reverse ppermute schedule) must match
    the non-pipelined gradient."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(1))
    tokens, positions, seg = _batch(cfg, seed=1)

    def loss(p, mesh):
        import contextlib

        ctx = (psh.activation_sharding(mesh) if mesh is not None
               else contextlib.nullcontext())
        with ctx:
            logits, _ = transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg, return_kv=False
            )
        mask = (seg > 0).astype(jnp.float32)
        return jnp.sum(jnp.tanh(logits.astype(jnp.float32)) ** 2
                       * mask[..., None])

    g_ref = jax.jit(lambda p: jax.grad(loss)(p, None))(params)

    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p4"))
    sp = psh.shard_params(params, m, cfg)
    g_pp = jax.jit(lambda p: jax.grad(loss)(p, m))(sp)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=1e-3
        )


def test_pipeline_remat_parity():
    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(2))
    tokens, positions, seg = _batch(cfg, seed=2)
    ref, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg, return_kv=False
    )
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    sp = psh.shard_params(params, m, cfg)

    def fwd(p):
        with psh.activation_sharding(m):
            out, _ = transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg,
                return_kv=False, remat=True,
            )
        return out

    np.testing.assert_allclose(
        np.asarray(jax.jit(fwd)(sp)), np.asarray(ref), atol=2e-4
    )


def test_pipeline_moe_aux_parity():
    """MoE models pipeline too; aux totals must match the scan path
    (bubble steps run garbage and must not pollute the balancing loss)."""
    from areal_tpu.models.config import MoEConfig

    cfg = tiny_config(
        n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2,
        moe=MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0),
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(3))
    tokens, positions, seg = _batch(cfg, seed=3)
    ref, _, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg,
        return_kv=False, return_aux=True,
    )
    # Aux (balancing) losses are nonlinear in the batch, so the pipeline's
    # per-micro-batch aux matches the MICRO-BATCHED reference (what any
    # grad-accumulation engine, the reference's included, optimizes) — not
    # the whole-batch value.
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    n_micro = ppl.pick_pp_microbatches(m, cfg, tokens.shape[0])
    mb = tokens.shape[0] // n_micro
    aux_ref = None
    for i in range(n_micro):
        sl = slice(i * mb, (i + 1) * mb)
        _, _, a = transformer.forward(
            params, cfg, tokens[sl], positions[sl], segment_ids=seg[sl],
            return_kv=False, return_aux=True,
        )
        aux_ref = a if aux_ref is None else {
            k: aux_ref[k] + a[k] for k in a
        }
    aux_ref = {k: v / n_micro for k, v in aux_ref.items()}
    sp = psh.shard_params(params, m, cfg)

    def fwd(p):
        with psh.activation_sharding(m):
            return transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg,
                return_kv=False, return_aux=True,
            )

    out, _, aux = jax.jit(fwd)(sp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3,
                               rtol=2e-3)
    assert set(aux) == set(aux_ref)
    for k in aux_ref:
        np.testing.assert_allclose(
            float(aux[k]), float(aux_ref[k]), atol=1e-4, rtol=2e-3
        )
