"""Pipeline parallelism (parallel/pipeline.py): GPipe micro-batch streaming
over the "pp" mesh axis must be numerically identical to the plain
scan-over-layers forward, including gradients and MoE aux losses.

Parity target: the reference's pipeline_parallel instruction VM + schedules
(realhf/impl/model/parallelism/pipeline_parallel/, pipe_runner.py:148) —
there, correctness is established by comparing pipelined train/forward
against the non-pipelined engine; same strategy here on the 8-CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel import mesh as pmesh
from areal_tpu.parallel import pipeline as ppl
from areal_tpu.parallel import sharding as psh


def _batch(cfg, B=8, T=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    # two documents packed per row, one padded tail row
    seg[:, T // 2:] = 2
    seg[-1, T - 3:] = 0
    return tokens, positions, seg


def test_pick_pp_microbatches_gates():
    cfg = tiny_config(n_layers=4)
    # Mixed (pp + auto axes) meshes only pipeline on jax versions whose
    # shard_map handles partial-manual autodiff (jax.shard_map); older jax
    # keeps the correct GSPMD path there (pipeline.py gate).
    mixed_ok = getattr(jax, "shard_map", None) is not None
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2p2t2")
                        if mixed_ok else pmesh.ParallelSpec.parse("p2"))
    if not mixed_ok:
        mm = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2p2t2"))
        assert ppl.pick_pp_microbatches(mm, cfg, 8) is None
    assert ppl.pick_pp_microbatches(None, cfg, 8) is None
    assert ppl.pick_pp_microbatches(m, cfg, 8) == 4  # auto: 2*pp
    assert ppl.pick_pp_microbatches(m, cfg, 6) == 3
    assert ppl.pick_pp_microbatches(m, cfg, 8, requested=2) == 2
    assert ppl.pick_pp_microbatches(m, cfg, 8, requested=3) is None  # 3∤8
    assert ppl.pick_pp_microbatches(m, cfg, 1) is None  # can't fill stages
    # layers must divide across stages
    cfg3 = tiny_config(n_layers=3)
    assert ppl.pick_pp_microbatches(m, cfg3, 8) is None
    # sp meshes pipeline too (PP∘SP) — when the sequence shards over the
    # ring; without a seq_len (or with an indivisible one) they fall back
    msp = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2s2"))
    assert ppl.pick_pp_microbatches(msp, cfg, 8) is None
    assert ppl.pick_pp_microbatches(msp, cfg, 8, seq_len=31) is None
    assert ppl.pick_pp_microbatches(msp, cfg, 8, seq_len=32) == 4
    # ... pure pp×sp pipelines on every jax; mixing in auto axes needs
    # jax.shard_map (same old-jax gate as d2p2t2 above)
    mspt = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2s2t2"))
    if mixed_ok:
        assert ppl.pick_pp_microbatches(mspt, cfg, 8, seq_len=32) == 4
    else:
        assert ppl.pick_pp_microbatches(mspt, cfg, 8, seq_len=32) is None
    # no pp axis
    mnp = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
    assert ppl.pick_pp_microbatches(mnp, cfg, 8) is None


@pytest.mark.parametrize("spec_str", ["p2", "p4", "d2p2t2"])
def test_pipeline_forward_parity(spec_str):
    """Pipelined logits == single-device logits (return_kv=False routes
    through the pipeline when the mesh has pp>1)."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    tokens, positions, seg = _batch(cfg)
    ref, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg, return_kv=False
    )

    m = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec_str))
    sp = psh.shard_params(params, m, cfg)

    def fwd(p, t, pos, s):
        with psh.activation_sharding(m):
            out, _ = transformer.forward(
                p, cfg, t, pos, segment_ids=s, return_kv=False
            )
        return out

    out = jax.jit(fwd)(sp, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pipeline_grad_parity():
    """jax.grad through the pipeline (reverse ppermute schedule) must match
    the non-pipelined gradient."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(1))
    tokens, positions, seg = _batch(cfg, seed=1)

    def loss(p, mesh):
        import contextlib

        ctx = (psh.activation_sharding(mesh) if mesh is not None
               else contextlib.nullcontext())
        with ctx:
            logits, _ = transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg, return_kv=False
            )
        mask = (seg > 0).astype(jnp.float32)
        return jnp.sum(jnp.tanh(logits.astype(jnp.float32)) ** 2
                       * mask[..., None])

    g_ref = jax.jit(lambda p: jax.grad(loss)(p, None))(params)

    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p4"))
    sp = psh.shard_params(params, m, cfg)
    g_pp = jax.jit(lambda p: jax.grad(loss)(p, m))(sp)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=1e-3
        )


def test_pipeline_remat_parity():
    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(2))
    tokens, positions, seg = _batch(cfg, seed=2)
    ref, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg, return_kv=False
    )
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    sp = psh.shard_params(params, m, cfg)

    def fwd(p):
        with psh.activation_sharding(m):
            out, _ = transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg,
                return_kv=False, remat=True,
            )
        return out

    np.testing.assert_allclose(
        np.asarray(jax.jit(fwd)(sp)), np.asarray(ref), atol=2e-4
    )


def _pipeline_call(cfg, params, batch, mesh, n_micro, schedule,
                   remat=False):
    """Call pipeline_apply_layers directly (both schedules) on the raw
    layer stack — the 1F1B-vs-GPipe harness, bypassing forward()'s head so
    mismatches point at the schedule, not the embedding/norm."""
    tokens, positions, seg = batch
    h = params["embedding"][jnp.asarray(tokens)]
    cos, sin = transformer.rope_tables(
        jnp.asarray(positions), cfg.head_dim, cfg.rotary_base
    )
    return ppl.pipeline_apply_layers(
        cfg, params["layers"], h, cos, sin, jnp.asarray(seg),
        jnp.asarray(positions), mesh, n_micro, remat=remat,
        schedule=schedule,
    )


@pytest.mark.parametrize("remat", [False, True])
def test_1f1b_matches_gpipe_oracle(remat):
    """The hand-written 1F1B custom-vjp backward must reproduce the GPipe
    scan oracle — outputs AND gradients — including with remat and with a
    bubble-heavy schedule (n_micro == pp, steps = 2*pp - 1)."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(4))
    tokens, positions, seg = _batch(cfg, seed=4)
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    sp = psh.shard_params(params, m, cfg)
    n_micro = 2  # == pp: maximal bubble fraction, worst case for masking

    outs, grads = {}, {}
    for sched in ("gpipe", "1f1b"):
        def loss(p):
            with psh.activation_sharding(m):
                out, _ = _pipeline_call(
                    cfg, p, (tokens, positions, seg), m, n_micro, sched,
                    remat=remat,
                )
            mask = (jnp.asarray(seg) > 0).astype(jnp.float32)
            return jnp.sum(
                jnp.tanh(out.astype(jnp.float32)) ** 2 * mask[..., None]
            )

        def fwd(p):
            with psh.activation_sharding(m):
                return _pipeline_call(
                    cfg, p, (tokens, positions, seg), m, n_micro, sched,
                    remat=remat,
                )[0]

        outs[sched] = np.asarray(jax.jit(fwd)(sp))
        grads[sched] = jax.jit(jax.grad(loss))(sp)

    np.testing.assert_allclose(outs["1f1b"], outs["gpipe"], atol=1e-5)
    for a, b in zip(jax.tree.leaves(grads["1f1b"]),
                    jax.tree.leaves(grads["gpipe"])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_1f1b_matches_gpipe_moe_aux():
    """MoE aux totals AND their gradient contributions must agree between
    the schedules (the aux cotangent rides the hand-written backward)."""
    from areal_tpu.models.config import MoEConfig

    cfg = tiny_config(
        n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2,
        moe=MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0),
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(5))
    tokens, positions, seg = _batch(cfg, seed=5)
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    sp = psh.shard_params(params, m, cfg)

    n_micro = 4

    def loss(p, sched):
        out, aux = _pipeline_call(
            cfg, p, (tokens, positions, seg), m, n_micro, sched
        )
        mask = (jnp.asarray(seg) > 0).astype(jnp.float32)
        main = jnp.sum(
            jnp.tanh(out.astype(jnp.float32)) ** 2 * mask[..., None]
        )
        # aux_total enters the loss -> its cotangent must flow through
        # the backward schedule into the router weights.
        return main + 0.1 * jnp.sum(aux["aux_total"]), aux

    # Values + aux: 1F1B vs the GPipe oracle (forward-only on the oracle —
    # jax 0.4.x's experimental shard_map cannot transpose the oracle's
    # psum'd P() aux outputs, one more reason the 1F1B backward is
    # hand-written).
    def fwd(p, sched):
        with psh.activation_sharding(m):
            return loss(p, sched)

    (v1, aux1) = jax.jit(lambda p: fwd(p, "1f1b"))(sp)
    (v2, aux2) = jax.jit(lambda p: fwd(p, "gpipe"))(sp)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    aux1, aux2 = jax.device_get((aux1, aux2))
    assert set(aux1) == set(aux2)
    for k in aux1:
        np.testing.assert_allclose(aux1[k], aux2[k], atol=1e-6, rtol=1e-5)

    # Gradients: 1F1B vs the micro-batched NON-pipelined reference (the
    # same contract the forward-parity oracle test uses for values).
    g1 = jax.jit(jax.grad(
        lambda p: fwd(p, "1f1b")[0], has_aux=False
    ))(sp)

    mb = tokens.shape[0] // n_micro

    def ref_loss(p):
        total = jnp.zeros((), jnp.float32)
        aux_tot = jnp.zeros((), jnp.float32)
        for i in range(n_micro):
            sl = slice(i * mb, (i + 1) * mb)
            h = p["embedding"][jnp.asarray(tokens[sl])]
            cos, sin = transformer.rope_tables(
                jnp.asarray(positions[sl]), cfg.head_dim, cfg.rotary_base
            )
            out, aux = transformer.apply_layer_stack(
                cfg, h, p["layers"], cos, sin, jnp.asarray(seg[sl]),
                jnp.asarray(positions[sl]),
            )
            mask = (jnp.asarray(seg[sl]) > 0).astype(jnp.float32)
            total += jnp.sum(
                jnp.tanh(out.astype(jnp.float32)) ** 2 * mask[..., None]
            )
            aux_tot += jnp.sum(aux["aux_total"].astype(jnp.float32))
        return total + 0.1 * aux_tot / n_micro

    g_ref = jax.jit(jax.grad(ref_loss))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3
        )


def test_1f1b_backward_residuals_scale_with_n_micro():
    """The peak-memory regression test (ISSUE 8): the 1F1B backward's live
    activation set — measured from the ABSTRACT shapes of the real forward
    via jax.eval_shape, no TPU needed — must be exactly n_micro stage
    inputs per stage, independent of ``steps = n_micro + pp - 1``. The
    GPipe scan, by construction, keeps >= steps/n_micro times that (its
    scan saves per-step residuals and stacks [steps, ...] outputs), which
    is what OOM'd cap-4096 PP configs."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(6))

    def measure(spec, B, T, n_micro):
        m = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec))
        sp = psh.shard_params(params, m, cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
        positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
        seg = np.ones((B, T), np.int32)
        h = params["embedding"][jnp.asarray(tokens)]
        cos, sin = transformer.rope_tables(
            jnp.asarray(positions), cfg.head_dim, cfg.rotary_base
        )
        return ppl.backward_residual_bytes(
            cfg, sp["layers"], h, cos, sin, jnp.asarray(seg),
            jnp.asarray(positions), m, n_micro,
        )

    B, T, D = 8, 16, cfg.hidden_dim
    itemsize = 4  # f32 test params/activations
    expected = B * T * D * itemsize  # n_micro * mb * T * D per stage
    got_p2 = measure("p2", B, T, n_micro=4)
    got_p4 = measure("p4", B, T, n_micro=4)
    # Exactly the n_micro stage inputs, nothing stacked by `steps`:
    assert got_p2 == expected
    # ... and INVARIANT to pipeline depth (steps grows 5 -> 7 here):
    assert got_p4 == got_p2
    # The GPipe-scan formulation's boundary working set per stage grows
    # with steps (saved per-step inputs + the [steps, ...] ys stack it
    # slices the output from). At the cap-4096 bench geometry the factor
    # is what pushed PP past the 16G budget:
    for pp, n_micro in ((4, 4), (4, 8)):
        steps = n_micro + pp - 1
        one_f1b = n_micro  # micro-batch-input equivalents per stage
        gpipe = 2 * steps  # per-step saved inputs + stacked ys
        assert gpipe / one_f1b >= 1 + (pp - 1) / n_micro
    # Doubling n_micro at fixed B keeps the residual set pinned at B rows:
    assert measure("p2", B, T, n_micro=8) == expected


@pytest.mark.ring
@pytest.mark.parametrize("ring_schedule", ["zigzag", "naive"])
@pytest.mark.parametrize("sched", ["1f1b", "gpipe"])
def test_ppsp_matches_gspmd_oracle(sched, ring_schedule, monkeypatch):
    """PP∘SP e2e parity: on a pp×sp CPU mesh both pipeline schedules, with
    ring attention running inside each stage (both ring schedules), must
    reproduce the dense scan oracle's loss AND gradients at the existing
    pipeline parity tolerances."""
    monkeypatch.setenv("AREAL_RING_SCHEDULE", ring_schedule)
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(7))
    tokens, positions, seg = _batch(cfg, seed=7)
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2s2"))
    assert ppl.pick_pp_microbatches(m, cfg, tokens.shape[0],
                                    seq_len=tokens.shape[1]) is not None
    sp = psh.shard_params(params, m, cfg)
    mask = (jnp.asarray(seg) > 0).astype(jnp.float32)

    def dense_loss(p):
        h = p["embedding"][jnp.asarray(tokens)]
        cos, sin = transformer.rope_tables(
            jnp.asarray(positions), cfg.head_dim, cfg.rotary_base
        )
        out, _ = transformer.apply_layer_stack(
            cfg, h, p["layers"], cos, sin, jnp.asarray(seg),
            jnp.asarray(positions),
        )
        return jnp.sum(
            jnp.tanh(out.astype(jnp.float32)) ** 2 * mask[..., None]
        )

    def pp_loss(p):
        with psh.activation_sharding(m):
            out, _ = _pipeline_call(
                cfg, p, (tokens, positions, seg), m, 2, sched
            )
        return jnp.sum(
            jnp.tanh(out.astype(jnp.float32)) ** 2 * mask[..., None]
        )

    v_ref, g_ref = jax.jit(jax.value_and_grad(dense_loss))(params)
    v_pp, g_pp = jax.jit(jax.value_and_grad(pp_loss))(sp)
    np.testing.assert_allclose(float(v_pp), float(v_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=1e-3
        )


@pytest.mark.ring
def test_backward_residuals_invariant_to_sp():
    """PP∘SP must not change the 1F1B residual accounting: the per-stage
    saved set is the same n_micro GLOBAL micro-batch inputs whether or not
    the sequence dim shards over a ring (each sp shard holds 1/sp of it,
    but the metric counts the reassembled global buffer)."""
    cfg = tiny_config(n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(6))
    B, T = 8, 16

    def measure(spec):
        m = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec))
        sp = psh.shard_params(params, m, cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
        positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
        seg = np.ones((B, T), np.int32)
        h = params["embedding"][jnp.asarray(tokens)]
        cos, sin = transformer.rope_tables(
            jnp.asarray(positions), cfg.head_dim, cfg.rotary_base
        )
        return ppl.backward_residual_bytes(
            cfg, sp["layers"], h, cos, sin, jnp.asarray(seg),
            jnp.asarray(positions), m, n_micro=4,
        )

    base = measure("p2")
    assert base == B * T * cfg.hidden_dim * 4  # f32 stage inputs
    assert measure("p2s2") == base
    assert measure("p2s4") == base


def test_pipeline_moe_aux_parity():
    """MoE models pipeline too; aux totals must match the scan path
    (bubble steps run garbage and must not pollute the balancing loss)."""
    from areal_tpu.models.config import MoEConfig

    cfg = tiny_config(
        n_layers=4, hidden_dim=32, n_q_heads=4, n_kv_heads=2,
        moe=MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0),
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(3))
    tokens, positions, seg = _batch(cfg, seed=3)
    ref, _, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg,
        return_kv=False, return_aux=True,
    )
    # Aux (balancing) losses are nonlinear in the batch, so the pipeline's
    # per-micro-batch aux matches the MICRO-BATCHED reference (what any
    # grad-accumulation engine, the reference's included, optimizes) — not
    # the whole-batch value.
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("p2"))
    n_micro = ppl.pick_pp_microbatches(m, cfg, tokens.shape[0])
    mb = tokens.shape[0] // n_micro
    aux_ref = None
    for i in range(n_micro):
        sl = slice(i * mb, (i + 1) * mb)
        _, _, a = transformer.forward(
            params, cfg, tokens[sl], positions[sl], segment_ids=seg[sl],
            return_kv=False, return_aux=True,
        )
        aux_ref = a if aux_ref is None else {
            k: aux_ref[k] + a[k] for k in a
        }
    aux_ref = {k: v / n_micro for k, v in aux_ref.items()}
    sp = psh.shard_params(params, m, cfg)

    def fwd(p):
        with psh.activation_sharding(m):
            return transformer.forward(
                p, cfg, tokens, positions, segment_ids=seg,
                return_kv=False, return_aux=True,
            )

    out, _, aux = jax.jit(fwd)(sp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3,
                               rtol=2e-3)
    # The pipeline carries exactly the SCALAR aux keys (_aux_keys): the
    # per-expert expert_load histogram is vector-valued and doesn't ride
    # the scan carries / 1F1B cotangents. The scan path reports it on top.
    assert set(aux) == set(ppl._aux_keys(cfg))
    assert set(aux) < set(aux_ref)
    for k in aux:
        np.testing.assert_allclose(
            float(aux[k]), float(aux_ref[k]), atol=1e-4, rtol=2e-3
        )
