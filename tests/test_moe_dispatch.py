"""Grouped-dispatch and expert-parallel parity tests (models/moe.py).

The sort-based grouped-GEMM path is the production default; the one-hot
einsum path is the retained GShard oracle. Both implement the identical
capacity/drop policy, so forward outputs AND gradients must agree exactly
(up to float reassociation) — including dropped tokens and padding masks.
The expert-parallel all-to-all path must match the replicated layer
numerically on CPU host meshes with a real "ep" axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import moe as moemod
from areal_tpu.models import transformer
from areal_tpu.models.config import MoEConfig, tiny_config
from areal_tpu.parallel import mesh as pmesh

pytestmark = pytest.mark.moe


def _layer_params(rng, D, F, E, shared=None):
    lp = {
        "router": jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.5),
        "e_gate": jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.1),
        "e_up": jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.1),
        "e_down": jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.1),
    }
    if shared:
        lp["s_gate"] = jnp.asarray(
            rng.randn(D, shared).astype(np.float32) * 0.1)
        lp["s_up"] = jnp.asarray(
            rng.randn(D, shared).astype(np.float32) * 0.1)
        lp["s_down"] = jnp.asarray(
            rng.randn(shared, D).astype(np.float32) * 0.1)
    return lp


def _loss_fn(moe, x, mask, dispatch):
    def loss(lp):
        y, aux = moemod.moe_mlp(x, lp, moe, mask=mask, dispatch=dispatch)
        return jnp.sum(y * y) + aux["aux_total"], aux

    return loss


@pytest.mark.parametrize(
    "E,k,cf",
    [(4, 2, 1.0), (8, 2, 2.0), (8, 1, 0.5), (16, 4, 1.5)],
)
def test_grouped_matches_einsum_fwd_and_grad(E, k, cf):
    """Loss, grads, and dropped_frac identical between the grouped path
    and the einsum oracle — across shapes that exercise no-drop, heavy
    drop (cf=0.5), k=1, and k=4, with a packed padding mask and a shared
    expert in the mix."""
    rng = np.random.RandomState(E * 10 + k)
    D, F, B, T = 16, 32, 4, 16
    lp = _layer_params(rng, D, F, E, shared=24)
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    mask = jnp.asarray(  # last 20% of each row is grid padding
        (np.arange(T)[None, :] < int(T * 0.8)).repeat(B, 0))
    moe = MoEConfig(num_experts=E, top_k=k, capacity_factor=cf,
                    aux_loss_coeff=1e-2, z_loss_coeff=1e-3,
                    shared_intermediate_dim=24)

    (lg, ag), gg = jax.value_and_grad(
        _loss_fn(moe, x, mask, "grouped"), has_aux=True)(lp)
    (le, ae), ge = jax.value_and_grad(
        _loss_fn(moe, x, mask, "einsum"), has_aux=True)(lp)

    assert float(lg) == pytest.approx(float(le), rel=1e-5, abs=1e-6)
    assert float(ag["dropped_frac"]) == pytest.approx(
        float(ae["dropped_frac"]), abs=1e-6)
    if cf <= 0.5:  # the tight-capacity cases must actually drop
        assert float(ag["dropped_frac"]) > 0.0
    for name in gg:
        np.testing.assert_allclose(
            np.asarray(gg[name]), np.asarray(ge[name]),
            rtol=2e-4, atol=1e-6, err_msg=f"grad mismatch on {name}")


def test_grouped_is_default_and_env_oracle():
    assert moemod.resolve_dispatch(None) == "grouped"
    assert moemod.resolve_dispatch("einsum") == "einsum"
    with pytest.raises(ValueError, match="unknown MoE dispatch"):
        moemod.resolve_dispatch("scatter")
    old = dict(__import__("os").environ)
    import os

    try:
        os.environ["AREAL_MOE_DISPATCH"] = "einsum"
        assert moemod.resolve_dispatch(None) == "einsum"
        # explicit arg wins over the env var
        assert moemod.resolve_dispatch("grouped") == "grouped"
    finally:
        os.environ.clear()
        os.environ.update(old)


def test_routing_health_aux():
    """expert_load sums to 1 over experts (pre-drop share of routed
    assignments) and expert_load_ratio sits in [1, E]."""
    rng = np.random.RandomState(3)
    D, F, E = 8, 16, 4
    lp = _layer_params(rng, D, F, E)
    x = jnp.asarray(rng.randn(2, 32, D).astype(np.float32))
    moe = MoEConfig(num_experts=E, top_k=2, capacity_factor=2.0)
    _, aux = moemod.moe_mlp(x, lp, moe)
    load = np.asarray(aux["expert_load"])
    assert load.shape == (E,)
    assert float(load.sum()) == pytest.approx(1.0, abs=1e-5)
    ratio = float(aux["expert_load_ratio"])
    assert 1.0 - 1e-5 <= ratio <= E + 1e-5
    assert ratio == pytest.approx(float(load.max() / load.mean()), rel=1e-5)


@pytest.mark.parametrize("spec", ["e2", "d2e2", "e4t2", "d1f1e2"])
def test_ep_matches_replicated(spec):
    """The all-to-all expert-parallel path on a real ep mesh axis matches
    the replicated grouped layer — loss, grads, dropped_frac — in the
    no-drop regime (per-shard capacity changes drop priority, so drops
    are compared structurally elsewhere)."""
    ps = pmesh.ParallelSpec.parse(spec)
    if ps.world_size > len(jax.devices()):
        pytest.skip(f"needs {ps.world_size} devices")
    mesh = pmesh.make_mesh(ps)
    rng = np.random.RandomState(7)
    D, F, E, B, T = 16, 32, 4, 8, 8
    lp = _layer_params(rng, D, F, E)
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    moe = MoEConfig(num_experts=E, top_k=2, capacity_factor=8.0)
    assert moemod.ep_eligible(mesh, moe, B, T)

    def loss_ep(lp):
        y, aux = moemod.moe_mlp(x, lp, moe, mesh=mesh)
        return jnp.sum(y * y) + aux["aux_total"], aux

    (l_ep, a_ep), g_ep = jax.value_and_grad(loss_ep, has_aux=True)(lp)
    (l_ref, a_ref), g_ref = jax.value_and_grad(
        _loss_fn(moe, x, None, "grouped"), has_aux=True)(lp)

    assert float(l_ep) == pytest.approx(float(l_ref), rel=1e-5)
    assert float(a_ep["dropped_frac"]) == pytest.approx(
        float(a_ref["dropped_frac"]), abs=1e-6)
    for name in g_ref:
        np.testing.assert_allclose(
            np.asarray(g_ep[name]), np.asarray(g_ref[name]),
            rtol=2e-4, atol=1e-6, err_msg=f"grad mismatch on {name}")


def test_ep_eligible_gates():
    mesh = pmesh.make_mesh(pmesh.ParallelSpec(ep=2))
    moe = MoEConfig(num_experts=4, top_k=2)
    assert moemod.ep_eligible(mesh, moe, 4, 8)
    # experts must divide over ep
    assert not moemod.ep_eligible(
        mesh, MoEConfig(num_experts=3, top_k=1), 4, 8)
    # batch must divide the data axes (dp*fsdp*ep = 2)
    assert not moemod.ep_eligible(mesh, moe, 3, 8)
    # no mesh / dense model / ep=1 → never
    assert not moemod.ep_eligible(None, moe, 4, 8)
    assert not moemod.ep_eligible(mesh, None, 4, 8)
    dense_mesh = pmesh.make_mesh(pmesh.ParallelSpec(dp=2))
    assert not moemod.ep_eligible(dense_mesh, moe, 4, 8)


def test_init_moe_params_distinct_keys():
    """Every initialized weight draws from its own split — the router must
    not silently share a key with an expert matrix, with or without the
    shared expert in the set (regression: the old code split a fixed
    count and zipped, so adding a weight shifted neighbours' keys)."""
    cfg = tiny_config(moe=dict(num_experts=4, top_k=2))
    p = moemod.init_moe_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    assert set(p) == {"router", "e_gate", "e_up", "e_down"}
    flat = [np.asarray(v).ravel()[:8] for v in p.values()]
    for i in range(len(flat)):
        for j in range(i + 1, len(flat)):
            assert not np.allclose(flat[i], flat[j])
    cfg_s = tiny_config(
        moe=dict(num_experts=4, top_k=2, shared_intermediate_dim=16))
    p_s = moemod.init_moe_params(cfg_s, jax.random.PRNGKey(0), jnp.float32)
    assert {"s_gate", "s_up", "s_down"} <= set(p_s)
    flat_s = [np.asarray(v).ravel()[:8] for v in p_s.values()]
    for i in range(len(flat_s)):
        for j in range(i + 1, len(flat_s)):
            assert not np.allclose(flat_s[i], flat_s[j])


def test_activated_param_count():
    """MoE activated params = total minus the (E - top_k) idle routed
    FFNs per layer; dense configs are unchanged."""
    dense = tiny_config()
    assert transformer.activated_param_count(dense) == \
        transformer.param_count(dense)
    cfg = tiny_config(moe=dict(num_experts=8, top_k=2))
    total = transformer.param_count(cfg)
    act = transformer.activated_param_count(cfg)
    fr = cfg.moe.routed_intermediate_dim or cfg.intermediate_dim
    idle = cfg.n_layers * (cfg.moe.num_experts - cfg.moe.top_k) \
        * 3 * cfg.hidden_dim * fr
    assert act == total - idle
    assert act < total


def test_moe_flops_accounting_activated():
    """monitor.model_flops_per_token counts top_k routed experts + router
    + shared expert, not all num_experts."""
    from areal_tpu.base import monitor

    cfg = tiny_config(moe=dict(num_experts=8, top_k=2,
                               shared_intermediate_dim=16))
    dense = dataclasses.replace(cfg, moe=None)
    f_moe = monitor.model_flops_per_token(cfg, 128.0, backward=False)
    f_dense = monitor.model_flops_per_token(dense, 128.0, backward=False)
    d = cfg.hidden_dim
    fr = cfg.intermediate_dim
    expect_delta = cfg.n_layers * (
        (cfg.moe.top_k * 3 * 2 * d * fr + 2 * d * 8 + 3 * 2 * d * 16)
        - 3 * 2 * d * fr
    )
    assert f_moe - f_dense == pytest.approx(expect_delta)


def test_validate_config_rejects_bad_ep():
    from areal_tpu.api.cli_args import ConfigError, validate_config

    class _NS:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def cfg(alloc, moe=None):
        tiny = {"moe": moe} if moe is not None else {}
        return _NS(mode="local", allocation_mode=alloc, n_nodes=1,
                   n_gpus_per_node=8, actor=_NS(tiny=tiny))

    # ep on the generation side never applies
    with pytest.raises(ConfigError, match="ep"):
        validate_config(cfg("gen.e2+train.d2",
                            moe={"num_experts": 4, "top_k": 2}))
    # train-side ep on a dense model
    with pytest.raises(ConfigError, match="dense"):
        validate_config(cfg("e2"))
    # experts must divide over ep
    with pytest.raises(ConfigError, match="num_experts"):
        validate_config(cfg("e2", moe={"num_experts": 3, "top_k": 1}))
    # capacity_factor must be positive
    with pytest.raises(ConfigError, match="capacity_factor"):
        validate_config(cfg("d2", moe={"num_experts": 4, "top_k": 2,
                                       "capacity_factor": 0.0}))
    # the happy path passes
    validate_config(cfg("e2", moe={"num_experts": 4, "top_k": 2}))
    validate_config(cfg("d2f2t2"))
