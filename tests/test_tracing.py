"""Sample-lineage tracing + flight recorder (base/telemetry.py,
docs/observability.md).

All in-process fakes, zero real sleeps: traces are injected/extracted
through the real helpers, the stitcher is fed directly, flight triggers
are polled explicitly, and the disabled path is asserted byte-identical.
"""

import json
import os

import pytest

from areal_tpu.api.train_config import TelemetryConfig
from areal_tpu.base import name_resolve, names, telemetry

pytestmark = pytest.mark.trace


@pytest.fixture()
def enabled_telemetry(tmp_name_resolve):
    """Process-global telemetry on (no flushing thread activity: huge
    interval), reset afterwards."""
    sink = telemetry.configure(
        "tr", "t0", "rollout", 0,
        TelemetryConfig(enabled=True, flush_interval_secs=3600),
    )
    yield sink
    telemetry.shutdown()


# ---------------------------------------------------------------------------
# context propagation: headers + payload dicts
# ---------------------------------------------------------------------------


def test_header_roundtrip(enabled_telemetry):
    with telemetry.start_trace() as ctx:
        assert ctx is not None and len(ctx.trace_id) == 16
        h = telemetry.inject_headers()
        assert set(h) == {telemetry.TRACE_HEADER}
        got = telemetry.extract_headers(h)
        assert got.trace_id == ctx.trace_id
        # no open span: the original (absent) parent rides along
        assert got.parent_span is None
        with telemetry.span("rollout/generate"):
            h2 = telemetry.inject_headers()
        got2 = telemetry.extract_headers(h2)
        # parent is the GLOBAL span ref of the open span: worker/<id>
        assert got2.parent_span.startswith("rollout:0/")
    # outside the trace: nothing to inject
    assert telemetry.inject_headers() == {}
    assert telemetry.extract_headers({}) is None
    assert telemetry.extract_headers({telemetry.TRACE_HEADER: ""}) is None


def test_payload_roundtrip(enabled_telemetry):
    with telemetry.start_trace() as ctx:
        d = telemetry.inject_payload({"ids": ["a"]})
        assert d[telemetry.TRACE_FIELD]["trace_id"] == ctx.trace_id
    got = telemetry.extract_payload(d)
    assert got.trace_id == ctx.trace_id
    assert telemetry.TRACE_FIELD not in d  # popped: sample parses clean
    assert telemetry.extract_payload({"ids": ["a"]}) is None
    assert telemetry.extract_payload(None) is None


def test_disabled_is_byte_identical(tmp_name_resolve):
    """The acceptance contract: telemetry off ⇒ wire payloads and request
    headers are exactly what a tracing-free build would produce."""
    from areal_tpu.system.streams import _pack

    telemetry.shutdown()
    assert telemetry.inject_headers() == {}
    obj = {"ids": ["q1@0"], "seqlens": [4]}
    ref_bytes = _pack({"ids": ["q1@0"], "seqlens": [4]})
    out = telemetry.inject_payload(obj)
    assert out is obj and telemetry.TRACE_FIELD not in obj
    assert _pack(obj) == ref_bytes
    # start_trace with telemetry disabled allocates nothing
    with telemetry.start_trace() as ctx:
        assert ctx is None
        assert telemetry.inject_headers() == {}
        assert _pack(telemetry.inject_payload(obj)) == ref_bytes


def test_span_adopts_trace_and_remote_parent():
    r = telemetry.TelemetryRegistry()
    ctx = telemetry.TraceContext("t" * 16, parent_span="rollout:0/7")
    with telemetry.trace_scope(ctx):
        with r.span("genserver/decode_chunk"):
            with r.span("inner"):
                pass
    spans = {s["name"]: s for s in r.snapshot()["spans"]}
    root = spans["genserver/decode_chunk"]
    assert root["trace_id"] == "t" * 16
    # local root of the distributed trace links to the REMOTE parent
    assert root["remote_parent"] == "rollout:0/7"
    inner = spans["inner"]
    assert inner["trace_id"] == "t" * 16
    assert inner["parent_id"] == root["span_id"]
    assert "remote_parent" not in inner  # has a local parent instead
    # untraced spans keep the wire format unchanged
    with r.span("plain"):
        pass
    (plain,) = r.snapshot()["spans"]
    assert "trace_id" not in plain and "remote_parent" not in plain


def test_add_span_and_event():
    r = telemetry.TelemetryRegistry()
    ctx = telemetry.TraceContext("abc", parent_span="rollout:1/3")
    sid = r.add_span("genserver/queue_wait", 100.0, 0.25, trace=ctx, cls="x")
    with telemetry.trace_scope(ctx):
        with r.span("rollout/generate"):
            r.event("rollout/failover", attempt=2)
    spans = {s["name"]: s for s in r.snapshot()["spans"]}
    qw = spans["genserver/queue_wait"]
    assert qw["span_id"] == sid and qw["t_start"] == 100.0
    assert qw["dur_secs"] == 0.25 and qw["trace_id"] == "abc"
    assert qw["remote_parent"] == "rollout:1/3"
    ev = spans["rollout/failover"]
    assert ev["dur_secs"] == 0.0 and ev["trace_id"] == "abc"
    assert ev["parent_id"] == spans["rollout/generate"]["span_id"]
    # manual spans feed the duration histograms like context-manager spans
    assert r.snapshot()["hists"]["genserver/queue_wait/secs"]["count"] == 1


def test_spans_dropped_is_a_first_class_counter():
    r = telemetry.TelemetryRegistry(max_spans=3)
    for i in range(8):
        with r.span(f"s{i}"):
            pass
    s = r.snapshot()
    assert s["dropped_spans"] == 5
    assert s["counters"]["telemetry/spans_dropped"] == 5.0
    text = telemetry.render_prometheus(s)
    assert "# TYPE areal_telemetry_spans_dropped_total counter" in text
    assert "areal_telemetry_spans_dropped_total 5" in text


# ---------------------------------------------------------------------------
# Prometheus label escaping (exposition-format edge cases)
# ---------------------------------------------------------------------------


def test_prometheus_label_escaping():
    text = telemetry.render_prometheus(
        {"gauges": {"g": 1.0}},
        labels={"why": 'quote " back \\ slash', "nl": "line1\nline2"},
    )
    # exactly one sample line, with \" , \\ and \n all escaped
    sample = [ln for ln in text.splitlines() if not ln.startswith("#")]
    assert len(sample) == 1
    assert '\n' not in sample[0]  # the newline never splits the line
    assert 'nl="line1\\nline2"' in sample[0]
    assert 'why="quote \\" back \\\\ slash"' in sample[0]


# ---------------------------------------------------------------------------
# flight recorder: ring, dump, on-demand trigger, crash hook
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_dump(tmp_path):
    fr = telemetry.FlightRecorder(maxlen=4)
    r = telemetry.TelemetryRegistry()
    r.flight = fr
    for i in range(9):
        with r.span(f"s{i}"):
            pass
    recs = fr.snapshot()
    assert [x["name"] for x in recs] == ["s5", "s6", "s7", "s8"]
    path = str(tmp_path / "sub" / "flight_rollout0.jsonl")
    n = fr.dump(path, reason="unit")
    assert n == 4
    with open(path) as f:
        lines = [json.loads(ln) for ln in f]
    assert [x["name"] for x in lines[:-1]] == ["s5", "s6", "s7", "s8"]
    assert lines[-1]["kind"] == "dump" and lines[-1]["reason"] == "unit"
    assert lines[-1]["n_records"] == 4


def test_flight_trigger_fans_out_once_per_nonce(tmp_name_resolve, tmp_path):
    reg = telemetry.TelemetryRegistry()
    reg.flight = telemetry.FlightRecorder()
    with reg.span("before_crash"):
        pass
    p = telemetry.TelemetryPusher(reg, "fl", "t", "generation_server", 2,
                                  flush_interval_secs=3600)
    try:
        assert p.check_flight_trigger() is None  # no trigger pending
        out = str(tmp_path / "dumps")
        telemetry.request_flight_dump("fl", "t", out)
        path = p.check_flight_trigger()
        assert path == os.path.join(out, "flight_generation_server2.jsonl")
        assert os.path.exists(path)
        # same nonce again: no re-dump (the flag is NOT consumed — other
        # workers still need to see it — but this worker acted once)
        assert p.check_flight_trigger() is None
        # a NEW trigger fires again
        telemetry.request_flight_dump("fl", "t", out)
        assert p.check_flight_trigger() == path
    finally:
        p.close()


def test_telemetry_instance_flight_dump(tmp_name_resolve, tmp_path):
    cfg = TelemetryConfig(enabled=True, flush_interval_secs=3600,
                          flight_recorder_len=16,
                          flight_dir=str(tmp_path / "fl"))
    t = telemetry.Telemetry("fd", "t", "gserver_manager", 0, cfg=cfg,
                            push=False)
    try:
        t.event("gsmgr/evict", url="http://dead:1", reason="test")
        path = t.flight_dump(reason="evict")
        assert path.endswith("flight_gserver_manager0.jsonl")
        with open(path) as f:
            recs = [json.loads(ln) for ln in f]
        assert recs[0]["name"] == "gsmgr/evict"
        assert recs[-1]["reason"] == "evict"
        # the crash path dumps every live instance
        assert path in telemetry._dump_all_flight("unit")
    finally:
        t.close()


def test_null_sink_flight_api(tmp_name_resolve):
    telemetry.shutdown()
    sink = telemetry.get()
    assert sink.flight_dump() is None
    sink.event("x")  # no-op, no raise
    assert sink.add_span("x", 0.0, 0.0) == 0


# ---------------------------------------------------------------------------
# trace stitching (master side)
# ---------------------------------------------------------------------------


def _span(name, t0, dur, trace_id, **attrs):
    return {"name": name, "span_id": attrs.pop("span_id", 1),
            "parent_id": None, "t_start": t0, "dur_secs": dur,
            "attrs": attrs, "trace_id": trace_id}


def test_stitcher_joins_workers_and_derives_stages(tmp_path):
    traces = str(tmp_path / "traces.jsonl")
    st = telemetry.TraceStitcher(traces, grace_secs=0.0)
    tid = "f" * 16
    # rollout: gate 1s then generate 2s inside a 3s rollout
    st.feed("rollout:0", [
        _span("rollout/gate", 100.0, 1.0, tid, span_id=1),
        _span("rollout/generate", 101.0, 2.0, tid, span_id=2),
        _span("rollout/rollout", 100.0, 3.2, tid, span_id=3),
    ])
    # generation server: two chunks' queue waits
    st.feed("generation_server:0", [
        _span("genserver/queue_wait", 101.1, 0.3, tid, span_id=4),
        _span("genserver/queue_wait", 102.0, 0.2, tid, span_id=5),
        _span("genserver/decode", 102.2, 0.5, tid, span_id=6),
    ])
    assert st.registry.snapshot()["counters"].get("trace/stitched") is None
    # trainer: terminal span 5s after the rollout finished
    st.feed("trainer:0", [
        _span("trainer/train_sample", 108.5, 0.7, tid, span_id=7,
              sample_id="q1@0", weight_version=4),
    ])
    snap = st.registry.snapshot()
    assert snap["counters"]["trace/stitched"] == 1.0
    with open(traces) as f:
        (rec,) = [json.loads(ln) for ln in f]
    assert rec["trace_id"] == tid
    assert rec["sample_id"] == "q1@0" and rec["weight_version"] == 4
    assert set(rec["workers"]) == {"rollout:0", "generation_server:0",
                                   "trainer:0"}
    assert abs(rec["e2e_secs"] - (108.5 + 0.7 - 100.0)) < 1e-6
    stages = rec["stages"]
    assert abs(stages["gate"] - 1.0) < 1e-6
    assert abs(stages["generate"] - 2.0) < 1e-6
    assert abs(stages["queue"] - 0.5) < 1e-6  # both chunk waits summed
    assert abs(stages["train"] - 0.7) < 1e-6
    # train_wait = terminal start − rollout end = 108.5 − 103.2
    assert abs(stages["train_wait"] - 5.3) < 1e-6
    # derived first-class metrics: e2e + per-stage histograms
    hists = snap["hists"]
    assert hists["trace/e2e_secs"]["count"] == 1
    for k in telemetry.TRACE_STAGES:
        assert hists[f"trace/stage_{k}_secs"]["count"] == 1
    # untraced spans never buffer
    st.feed("rollout:0", [{"name": "x", "span_id": 9, "parent_id": None,
                           "t_start": 0.0, "dur_secs": 0.1, "attrs": {}}])
    assert len(st._traces) == 1
    st.close()


def test_stitcher_bounds_unfinished_traces(tmp_path):
    st = telemetry.TraceStitcher(None, max_traces=3)
    for i in range(6):
        st.feed("rollout:0", [_span("rollout/generate", float(i), 0.1,
                                    f"trace{i:02d}")])
    assert len(st._traces) == 3
    assert st.registry.snapshot()["counters"][
        "trace/unstitched_evicted"] == 3.0


def test_stitcher_group_terminals_count_once_and_stitched_age_silently():
    """A group's samples share ONE trace: k terminal spans observe the
    per-sample histograms k times but count ONE completed trace, each
    with its OWN train stage (not the sum); completed traces aging out
    of the LRU are normal turnover, not a loss signal."""
    st = telemetry.TraceStitcher(None, max_traces=2, grace_secs=0.0)
    tid = "g" * 16
    st.feed("rollout:0", [_span("rollout/rollout", 100.0, 2.0, tid,
                                span_id=1)])
    st.feed("trainer:0", [
        _span("trainer/train_sample", 105.0, 0.5, tid, span_id=2,
              sample_id="q1@0", weight_version=2),
        _span("trainer/train_sample", 109.0, 0.25, tid, span_id=3,
              sample_id="q1@1", weight_version=3),
    ])
    snap = st.registry.snapshot()
    assert snap["counters"]["trace/stitched"] == 1.0  # unique traces
    assert snap["hists"]["trace/e2e_secs"]["count"] == 2  # per sample
    # train stage is each terminal's own duration, never the group sum
    assert abs(snap["hists"]["trace/stage_train_secs"]["sum"]
               - (0.5 + 0.25)) < 1e-9
    # a STITCHED trace falling off the LRU is not "unstitched_evicted"
    st.feed("rollout:0", [_span("rollout/generate", 0.0, 0.1, "other1" * 3),
                          _span("rollout/generate", 0.0, 0.1, "other2" * 3)])
    c = st.registry.snapshot()["counters"]
    assert "trace/unstitched_evicted" not in c


def test_stitcher_eviction_spares_traces_awaiting_their_grace():
    """A trace whose terminal already arrived but is still inside the
    stitch grace window must survive LRU pressure — evicting it would
    silently drop a COMPLETED trace and miscount it as unstitched."""
    st = telemetry.TraceStitcher(None, max_traces=2, grace_secs=3600.0)
    done = "done" * 4
    st.feed("trainer:0", [_span("trainer/train_sample", 1.0, 0.1, done,
                                sample_id="s", weight_version=1)])
    # flood with fresh traces: `done` is the LRU victim candidate
    for i in range(4):
        st.feed("rollout:0", [_span("rollout/generate", float(i), 0.1,
                                    f"fresh{i:03d}" * 2)])
    assert done in st._traces  # kept despite the LRU bound
    st.tick(force=True)
    snap = st.registry.snapshot()
    assert snap["counters"]["trace/stitched"] == 1.0
    # the flooded-out traces without terminals are the real losses
    assert snap["counters"]["trace/unstitched_evicted"] >= 2.0


def test_stitcher_grace_defers_until_tick():
    """Terminal spans wait out the sibling workers' flush skew before
    stitching; close()/tick(force=True) never drops stragglers."""
    st = telemetry.TraceStitcher(None, grace_secs=3600.0)
    tid = "d" * 16
    st.feed("trainer:0", [_span("trainer/train_sample", 10.0, 0.1, tid,
                                sample_id="s", weight_version=1)])
    assert "trace/stitched" not in st.registry.snapshot()["counters"]
    # the rollout spans arrive late (slower flush cadence) — and are
    # still part of the stitched record thanks to the grace window
    st.feed("rollout:0", [_span("rollout/rollout", 5.0, 2.0, tid)])
    st.tick()  # grace not elapsed: still deferred
    assert "trace/stitched" not in st.registry.snapshot()["counters"]
    st.tick(force=True)
    snap = st.registry.snapshot()
    assert snap["counters"]["trace/stitched"] == 1.0
    # e2e measured from the LATE-arriving rollout root, not the terminal
    (e2e,) = [snap["hists"]["trace/e2e_secs"]["sum"]]
    assert abs(e2e - (10.0 + 0.1 - 5.0)) < 1e-9


def test_aggregator_exports_stitched_metrics(tmp_name_resolve, tmp_path):
    jsonl = str(tmp_path / "telemetry.jsonl")
    agg = telemetry.TelemetryAggregator("st", "t", jsonl_path=jsonl)
    try:
        # traces.jsonl defaults NEXT TO telemetry.jsonl
        assert agg.traces_path == str(tmp_path / "traces.jsonl")
        tid = "a" * 16
        agg.stitcher.feed("rollout:0",
                          [_span("rollout/generate", 10.0, 1.0, tid)])
        agg.stitcher.feed("trainer:0",
                          [_span("trainer/train_sample", 12.0, 0.5, tid,
                                 sample_id="s", weight_version=1)])
        agg.stitcher.tick(force=True)  # skip the flush-skew grace window
        text = agg.render_prometheus()
        assert "# TYPE areal_trace_e2e_secs histogram" in text
        assert 'areal_trace_e2e_secs_count{worker_index="0",' \
               'worker_kind="aggregator"} 1' in text
        assert "areal_trace_stage_generate_secs_bucket" in text
        assert 'areal_trace_stitched_total{worker_index="0",' \
               'worker_kind="aggregator"} 1' in text
        assert os.path.exists(agg.traces_path)
    finally:
        agg.close()


# ---------------------------------------------------------------------------
# /metrics (Prometheus) vs /metrics.json parity (satellite)
# ---------------------------------------------------------------------------


def _prom_gauges(text, prefix):
    out = {}
    for ln in text.splitlines():
        if ln.startswith("#") or not ln.startswith(prefix):
            continue
        name, _, val = ln.rpartition(" ")
        base = name.partition("{")[0]
        out[base] = float(val)
    return out


def test_gsmgr_metrics_parity(tmp_name_resolve):
    import asyncio

    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
    )

    from areal_tpu.system.gserver_manager import _ServerHealth

    mgr = GserverManager(GserverManagerConfig())
    mgr.servers = ["http://a:1", "http://b:2"]
    mgr.health = {u: _ServerHealth() for u in mgr.servers}
    mgr.version = 7
    mgr.running_rollouts = 5
    mgr.accepted_rollouts = 11
    mgr._inflight = {"http://a:1": 2, "http://b:2": 1}
    mgr.last_sync_fanout_secs = 1.5

    async def both():
        prom = await mgr.handle_metrics(None)
        js = await mgr.handle_metrics_json(None)
        return prom.text, json.loads(js.text)

    prom_text, js = asyncio.run(both())
    g = _prom_gauges(prom_text, "areal_gsmgr_")
    assert g["areal_gsmgr_weight_version"] == js["version"] == 7
    assert g["areal_gsmgr_running_rollouts"] == js["running_rollouts"] == 5
    assert (g["areal_gsmgr_accepted_rollouts"]
            == js["accepted_rollouts"] == 11)
    assert g["areal_gsmgr_healthy_servers"] == js["healthy_servers"] == 2
    assert g["areal_gsmgr_known_servers"] == js["known_servers"] == 2
    assert g["areal_gsmgr_weight_sync_fanout_secs"] == 1.5
    assert js["weight_sync_fanout_secs"] == 1.5
    for c, n in js["inflight_by_class"].items():
        assert g[f"areal_gsmgr_inflight_{c}"] == n
    # every sample line parses as "name{labels} value"
    for ln in prom_text.splitlines():
        if ln and not ln.startswith("#"):
            float(ln.rpartition(" ")[2])


def test_genserver_metrics_parity(tmp_name_resolve):
    import asyncio

    jax = pytest.importorskip("jax")
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )

    cfg = tiny_config(vocab_size=97)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = GenerationServer(
        GenerationServerConfig(experiment="par", trial="t0",
                               chunk_tokens=4, prompt_bucket=8),
        cfg, params,
    )
    srv._tokens_out = 123
    srv._prefill_tokens = 45
    srv.version = 3
    srv._inflight = 2

    async def both():
        prom = await srv.handle_metrics(None)
        js = await srv.handle_metrics_json(None)
        return prom.text, json.loads(js.text)

    prom_text, js = asyncio.run(both())
    g = _prom_gauges(prom_text, "areal_genserver_")
    assert g["areal_genserver_generated_tokens"] == js[
        "generated_tokens"] == 123
    assert g["areal_genserver_prefill_tokens"] == js["prefill_tokens"] == 45
    assert g["areal_genserver_weight_version"] == js["version"] == 3
    assert g["areal_genserver_inflight_requests"] == js[
        "inflight_requests"] == 2
    assert g["areal_genserver_queue_depth"] == js["queue_depth"]
    assert g["areal_genserver_kv_states"] == js["kv_states"]
    assert g["areal_genserver_compiled_shapes"] == js["compiled_shapes"]
    for ln in prom_text.splitlines():
        if ln and not ln.startswith("#"):
            float(ln.rpartition(" ")[2])
