"""Config tree + CLI/YAML merge tests (reference hydra-merge behavior)."""

import dataclasses

import pytest

from areal_tpu.api import cli_args as CA
from areal_tpu.experiments.async_ppo_math_exp import AsyncPPOMATHConfig
from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig
from areal_tpu.experiments.sft_exp import SFTConfig


def test_basic_overrides_types():
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, [
        "experiment_name=myexp",
        "seed=7",
        "group_size=8",
        "ppo.gen.max_new_tokens=4096",
        "ppo.ppo_n_minibatches=4",
        "ppo.disable_value=true",
        "ppo.c_clip=2.5",
        "actor.type._class=qwen3",
        "actor.path=/ckpt/qwen3",
        "dataset.train_bs_n_seqs=32",
        "actor_train.mb_spec.max_tokens_per_mb=32768",
    ])
    assert cfg.experiment_name == "myexp"
    assert cfg.seed == 7 and isinstance(cfg.seed, int)
    assert cfg.group_size == 8
    assert cfg.ppo.gen.max_new_tokens == 4096
    assert cfg.ppo.disable_value is True
    assert cfg.ppo.c_clip == 2.5
    assert cfg.actor.type._class == "qwen3"
    assert cfg.dataset.train_bs_n_seqs == 32
    assert cfg.actor_train.mb_spec.max_tokens_per_mb == 32768


def test_run_async_ppo_sh_knobs_port_verbatim():
    """The exact CLI surface of examples/run_async_ppo.sh must parse."""
    cfg = AsyncPPOMATHConfig()
    CA.apply_overrides(cfg, [
        "n_nodes=1", "n_gpus_per_node=8",
        "allocation_mode=gen.d4+d2f2t2",
        "cluster.fileroot=/tmp/areal_tpu_exps",
        "actor.type._class=qwen3", "actor.path=Qwen/Qwen3-1.7B",
        "ref.type._class=qwen3", "ref.path=Qwen/Qwen3-1.7B",
        "dataset.path=/data/boba.jsonl", "dataset.train_bs_n_seqs=32",
        "group_size=8",
        "ppo.gen.max_new_tokens=4096", "ppo.ppo_n_minibatches=4",
        "actor_train.mb_spec.max_tokens_per_mb=32768",
        "actor_inf.mb_spec.max_tokens_per_mb=32768",
        "max_concurrent_rollouts=16", "max_head_offpolicyness=4",
    ])
    assert cfg.max_head_offpolicyness == 4
    assert cfg.allocation_mode == "gen.d4+d2f2t2"


def test_typo_raises_with_suggestion():
    cfg = PPOMATHConfig()
    with pytest.raises(CA.ConfigError, match="group_size"):
        CA.apply_overrides(cfg, ["goup_size=8"])
    with pytest.raises(CA.ConfigError, match="unknown config key"):
        CA.apply_overrides(cfg, ["ppo.gen.maxnewtoken=1"])
    with pytest.raises(CA.ConfigError, match="key=value"):
        CA.apply_overrides(cfg, ["justaword"])


def test_none_and_dict_leaves():
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, [
        "ppo.behav_imp_weight_cap=none",
        "actor.tiny.vocab_size=258",
        "actor.tiny.seed=0",
    ])
    assert cfg.ppo.behav_imp_weight_cap is None
    assert cfg.actor.tiny == {"vocab_size": 258, "seed": 0}


def test_yaml_round_trip(tmp_path):
    cfg = AsyncPPOMATHConfig()
    CA.apply_overrides(cfg, [
        "trial_name=t0", "group_size=4", "ppo.kl_ctl=0.0",
        "new_tokens_per_chunk=64",
    ])
    p = str(tmp_path / "config.yaml")
    CA.save_yaml(cfg, p)
    cfg2 = AsyncPPOMATHConfig()
    CA.load_yaml(cfg2, p)
    assert cfg2.group_size == 4
    assert cfg2.ppo.kl_ctl == 0.0
    assert cfg2.new_tokens_per_chunk == 64
    assert dataclasses.asdict(cfg2) == dataclasses.asdict(cfg)


def test_yaml_unknown_key_raises(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("ppo:\n  epss_clip: 0.3\n")
    with pytest.raises(CA.ConfigError, match="eps_clip"):
        CA.load_yaml(PPOMATHConfig(), str(p))


def test_sft_config_smoke():
    cfg = SFTConfig()
    CA.apply_overrides(cfg, ["model.path=/x", "dataset.path=/y.jsonl",
                             "dataset.train_bs_n_seqs=16"])
    assert cfg.dataset.train_bs_n_seqs == 16


def test_mode_ray_fails_at_config_parse_time():
    """VERDICT #10: the descoped Ray mode must fail while the operator is
    still at the command line, with guidance toward local/slurm."""
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, ["mode=ray"])
    with pytest.raises(CA.ConfigError, match="slurm"):
        CA.validate_config(cfg)
    # unknown modes get the same parse-time treatment
    cfg2 = PPOMATHConfig()
    CA.apply_overrides(cfg2, ["mode=k8s"])
    with pytest.raises(CA.ConfigError, match="valid modes"):
        CA.validate_config(cfg2)
    # the supported modes validate clean
    for mode in CA.VALID_MODES:
        c = PPOMATHConfig()
        CA.apply_overrides(c, [f"mode={mode}"])
        CA.validate_config(c)


def test_name_resolve_etcd3_fails_at_config_parse_time():
    """ISSUE 11 satellite: no Etcd3NameRecordRepo exists, so
    type='etcd3' must fail with guidance while the operator is still at
    the command line (mirroring the mode=ray fix) instead of a
    NotImplementedError after workers spawned."""
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, ["cluster.name_resolve.type=etcd3"])
    with pytest.raises(CA.ConfigError, match="etcd3"):
        CA.validate_config(cfg)
    # the implemented backends validate clean
    for t in ("memory", "nfs"):
        c = PPOMATHConfig()
        CA.apply_overrides(c, [f"cluster.name_resolve.type={t}"])
        CA.validate_config(c)


def test_autoscale_config_validates_at_parse_time():
    """Bad autoscale bounds/thresholds would flap the fleet (or crash
    the manager's loop) — they fail at validate_config instead."""
    for bad, match in [
        ("autoscale.min_servers=0", "min_servers"),
        ("autoscale.max_servers=1 autoscale.min_servers=2", "max_servers"),
        ("autoscale.interval_secs=0", "interval_secs"),
        ("autoscale.down_utilization=0.9", "thresholds"),
        ("autoscale.straggler_factor=0.5", "straggler_factor"),
    ]:
        cfg = PPOMATHConfig()
        CA.apply_overrides(cfg, ["autoscale.enabled=true"] + bad.split())
        with pytest.raises(CA.ConfigError, match=match):
            CA.validate_config(cfg)
    # defaults validate clean, enabled or not
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, ["autoscale.enabled=true"])
    CA.validate_config(cfg)
    CA.validate_config(PPOMATHConfig())


def test_generation_sp_fails_at_config_parse_time():
    """ISSUE 18 satellite: the decode hot loop never rings
    (allow_ring=False on the decode path), so sp>1 in a generation-side
    allocation spec must fail at parse time with guidance — not surface
    as silently replicated work at server launch."""
    for bad in ("gen.s2d2+d2f2t2", "actor_gen:s2t2,actor_train:p2s2"):
        cfg = AsyncPPOMATHConfig()
        CA.apply_overrides(cfg, [
            "n_nodes=1", "n_gpus_per_node=8", f"allocation_mode={bad}",
        ])
        with pytest.raises(CA.ConfigError, match="never rings"):
            CA.validate_config(cfg)
    # sp on the TRAIN side is the PP∘SP path and validates clean
    cfg = AsyncPPOMATHConfig()
    CA.apply_overrides(cfg, [
        "n_nodes=1", "n_gpus_per_node=8", "allocation_mode=gen.d4+p2s2",
    ])
    CA.validate_config(cfg)


def test_invalid_serving_buckets_fail_at_config_parse_time():
    """Serving bucket configs that would crash every spawned generation
    server's __init__ (row_buckets below the batch size, shape sets over
    max_compiled_shapes) must fail at validate_config instead."""
    cfg = PPOMATHConfig()
    CA.apply_overrides(cfg, [
        "serving.enabled=true", "serving.row_buckets=1,2",
    ])
    with pytest.raises(CA.ConfigError, match="row_buckets"):
        CA.validate_config(cfg)
    cfg2 = PPOMATHConfig()
    CA.apply_overrides(cfg2, [
        "serving.enabled=true", "serving.max_compiled_shapes=4",
    ])
    with pytest.raises(CA.ConfigError, match="max_compiled_shapes"):
        CA.validate_config(cfg2)
    # defaults (serving on, derived buckets) validate clean
    cfg3 = PPOMATHConfig()
    CA.apply_overrides(cfg3, ["serving.enabled=true"])
    CA.validate_config(cfg3)
    # anti-starvation share outside [0, 1] is a config error
    cfg4 = PPOMATHConfig()
    CA.apply_overrides(cfg4, [
        "serving.enabled=true", "serving.min_rollout_share=1.5",
    ])
    with pytest.raises(CA.ConfigError, match="min_rollout_share"):
        CA.validate_config(cfg4)
