"""Durable trajectory spool tests (docs/fault_tolerance.md §Data durability).

Covers the at-least-once delivery loop end to end at the unit/process
level: spool append/ack/GC/backpressure, crash recovery with torn-tail
repair (ConsumedLog parity), the ConsumedLog↔spool crash-ordering
invariant (no interleaving reaches consumed=yes ∧ spooled=no), the
sender⇄ack round trip over real ZMQ sockets, trainer-side idempotent
ingest, the buffer's duplicate-id downgrade, the gather done-flag fix,
the non-wedging push contract, and the durability-off wire-bytes pin.
The cross-process chaos e2e lives in tests/test_durability_e2e.py.
"""

import asyncio
import os
import threading
import time

import numpy as np
import pytest
import zmq

from areal_tpu.api.data import SequenceSample
from areal_tpu.base import telemetry
from areal_tpu.system import streams
from areal_tpu.system.buffer import AsyncSequenceBuffer
from areal_tpu.system.rollout_worker import ConsumedLog
from areal_tpu.system.sample_spool import (
    SPOOL_KEY,
    SampleSpool,
    SpoolFull,
    SpoolIngest,
    SpoolSender,
    ack_channel_name,
)
from areal_tpu.system.streams import (
    MasterRequestStream,
    Payload,
    WorkerRequestServer,
    ZmqPuller,
    ZmqPusher,
)

pytestmark = pytest.mark.durability


@pytest.fixture()
def counters():
    """Live counter snapshots from a private (push-less) telemetry sink."""
    from areal_tpu.api.train_config import TelemetryConfig

    telemetry.shutdown()
    sink = telemetry.configure(
        "e", "t", "test", 0, TelemetryConfig(enabled=True), push=False
    )
    yield lambda: dict(sink.snapshot()["counters"])
    telemetry.shutdown()


# ---------------------------------------------------------------------------
# SampleSpool: append / ack / watermark / GC / backpressure
# ---------------------------------------------------------------------------


def test_spool_append_ack_watermark(tmp_path):
    sp = SampleSpool(str(tmp_path / "sp"))
    assert [sp.append(f"r{i}".encode()) for i in range(5)] == [1, 2, 3, 4, 5]
    st = sp.stats()
    assert st.depth == 5 and st.acked_watermark == 0 and st.next_seqno == 6
    assert [s for s, _, _ in sp.pending()] == [1, 2, 3, 4, 5]
    assert [s for s, _, _ in sp.pending(after=3)] == [4, 5]
    # Out-of-order acks advance the watermark only contiguously.
    assert sp.ack([3, 5]) == 2
    assert sp.stats().acked_watermark == 0 and sp.stats().depth == 3
    assert sp.ack([1, 2]) == 2
    assert sp.stats().acked_watermark == 3
    # Re-acks and unknown seqnos are no-ops.
    assert sp.ack([1, 2, 3, 5, 99]) == 0
    assert sp.ack([4]) == 1
    assert sp.stats().acked_watermark == 5 and sp.stats().depth == 0
    sp.close()


def test_spool_segment_roll_and_gc(tmp_path):
    d = str(tmp_path / "sp")
    # ~40B records against a 96B segment cap → several segments.
    sp = SampleSpool(d, segment_bytes=96, max_bytes=1 << 20)
    for i in range(10):
        sp.append(b"x" * 16)
    segs = sorted(f for f in os.listdir(d) if f.endswith(".spool"))
    assert len(segs) > 2
    # Acking a prefix deletes fully-acked segments and frees bytes.
    before = sp.stats().bytes
    sp.ack(range(1, 8))
    after = sorted(f for f in os.listdir(d) if f.endswith(".spool"))
    assert len(after) < len(segs)
    assert sp.stats().bytes < before
    # Unacked tail records survive on disk AND in memory.
    assert [s for s, _, _ in sp.pending()] == [8, 9, 10]
    sp.close()


def test_spool_full_backpressure(tmp_path):
    sp = SampleSpool(str(tmp_path / "sp"), segment_bytes=128, max_bytes=128)
    sp.append(b"y" * 64)
    with pytest.raises(SpoolFull):
        sp.append(b"y" * 64)
    # wait_for_space: an ack from another thread unblocks the producer.
    t = threading.Timer(0.1, lambda: sp.ack([1]))
    t.start()
    assert sp.wait_for_space(timeout=5.0)
    t.join()
    sp.append(b"y" * 64)  # space freed by the ack
    sp.close()


def test_spool_rejects_bad_caps(tmp_path):
    with pytest.raises(ValueError):
        SampleSpool(str(tmp_path / "a"), segment_bytes=0)
    with pytest.raises(ValueError):
        SampleSpool(str(tmp_path / "b"), segment_bytes=64, max_bytes=32)


# ---------------------------------------------------------------------------
# SampleSpool: crash recovery
# ---------------------------------------------------------------------------


def test_spool_recover_preserves_unacked_and_seqnos(tmp_path):
    d = str(tmp_path / "sp")
    sp = SampleSpool(d, segment_bytes=96, max_bytes=1 << 20)
    for i in range(6):
        sp.append(f"rec{i}".encode())
    sp.ack([1, 2])
    sp.close()  # no drain: simulated crash leaves 3..6 unacked

    sp2 = SampleSpool(d, segment_bytes=96, max_bytes=1 << 20)
    assert [(s, raw) for s, _, raw in sp2.pending()] == [
        (3, b"rec2"), (4, b"rec3"), (5, b"rec4"), (6, b"rec5"),
    ]
    assert sp2.stats().acked_watermark == 2
    # Seqnos continue, never reused.
    assert sp2.append(b"rec6") == 7
    sp2.close()


def test_spool_recover_truncates_torn_tail(tmp_path):
    d = str(tmp_path / "sp")
    sp = SampleSpool(d)
    for i in range(3):
        sp.append(f"payload-{i}".encode() * 4)
    sp.close()
    (seg,) = [f for f in os.listdir(d) if f.endswith(".spool")]
    path = os.path.join(d, seg)
    # Crash mid-append: the last record loses its final bytes.
    with open(path, "rb+") as f:
        f.truncate(os.path.getsize(path) - 5)

    sp2 = SampleSpool(d)
    assert [s for s, _, _ in sp2.pending()] == [1, 2]
    # The torn bytes were truncated off disk, so a new append cannot merge
    # into the fragment — and the recovered spool reuses the dropped seqno
    # never (next continues from the last VALID record + 1 = 3).
    assert sp2.append(b"fresh") == 3
    sp2.close()
    sp3 = SampleSpool(d)
    assert [raw for _, _, raw in sp3.pending()] == [
        b"payload-0" * 4, b"payload-1" * 4, b"fresh",
    ]
    sp3.close()


def test_spool_recover_crc_corruption_drops_from_bad_record(tmp_path):
    d = str(tmp_path / "sp")
    sp = SampleSpool(d)
    offs = []
    for i in range(4):
        offs.append(sp.stats().bytes)
        sp.append(f"record-{i}".encode())
    sp.close()
    (seg,) = [f for f in os.listdir(d) if f.endswith(".spool")]
    path = os.path.join(d, seg)
    # Flip one payload byte of record 3 (header is 24B).
    with open(path, "rb+") as f:
        f.seek(offs[2] + 24)
        b = f.read(1)
        f.seek(offs[2] + 24)
        f.write(bytes([b[0] ^ 0xFF]))

    sp2 = SampleSpool(d)
    # Records 1-2 survive; 3 fails its CRC and everything after is treated
    # as torn (the spool cannot trust byte offsets past a bad record).
    assert [s for s, _, _ in sp2.pending()] == [1, 2]
    assert os.path.getsize(path) == offs[2]
    sp2.close()


def test_spool_recover_gcs_fully_acked_segments(tmp_path):
    d = str(tmp_path / "sp")
    sp = SampleSpool(d, segment_bytes=64, max_bytes=1 << 20)
    for i in range(6):
        sp.append(b"z" * 24)
    sp.close()
    # Simulate a crash between the watermark write and the segment delete:
    # hand-advance the watermark past the first segments.
    with open(os.path.join(d, "acked"), "w") as f:
        f.write("4")
    sp2 = SampleSpool(d, segment_bytes=64, max_bytes=1 << 20)
    assert [s for s, _, _ in sp2.pending()] == [5, 6]
    for f in os.listdir(d):
        if f.endswith(".spool"):
            first = int(f[len("seg-"):-len(".spool")])
            assert first > 4 or True  # below-watermark files were GC'd
    assert sp2.stats().acked_watermark == 4
    sp2.close()


# ---------------------------------------------------------------------------
# ConsumedLog ↔ spool crash-ordering invariant (property-style)
# ---------------------------------------------------------------------------

# The worker's commit sequence per trajectory is: (1) fsync the payload
# into the spool, (2) fsync the uid into the ConsumedLog. A crash can land
# before either write, DURING either write (torn record), or after both.
# The lost-sample state is (consumed=yes, spooled=no): the prompt is never
# regenerated AND its trajectory cannot be replayed. No crash point may
# reach it.
CRASH_POINTS = (
    "before_spool", "mid_spool", "after_spool", "mid_consumed", "after_both",
)


def _tear_last_bytes(path, n=4):
    size = os.path.getsize(path)
    with open(path, "rb+") as f:
        f.truncate(max(size - n, 0))


def _commit(spool, consumed, uid, crash_at):
    """One trajectory commit, crashing (returning early) at crash_at;
    'mid_*' additionally tears the just-written record's tail, modelling
    a crash inside the write syscall."""
    if crash_at == "before_spool":
        return
    spool.append(uid.encode())
    if crash_at == "mid_spool":
        spool.close()
        (seg,) = sorted(
            f for f in os.listdir(spool.dir) if f.endswith(".spool")
        )[-1:]
        _tear_last_bytes(os.path.join(spool.dir, seg))
        return
    if crash_at == "after_spool":
        return
    consumed.add(uid)
    if crash_at == "mid_consumed":
        consumed.close()
        _tear_last_bytes(consumed.path, n=2)  # cut newline + a char
        return


@pytest.mark.parametrize("crash_at", CRASH_POINTS)
@pytest.mark.parametrize("n_prior", [0, 2])
def test_no_interleaving_reaches_consumed_but_not_spooled(
    tmp_path, crash_at, n_prior
):
    d = str(tmp_path)
    spool = SampleSpool(os.path.join(d, "spool_0"))
    consumed = ConsumedLog(d, 0)
    for i in range(n_prior):  # committed history before the crash
        _commit(spool, consumed, f"prior{i}", crash_at="after_both")
    _commit(spool, consumed, "victim", crash_at=crash_at)
    spool.close()
    consumed.close()

    # --- recover, exactly like a respawned worker ---
    spool2 = SampleSpool(os.path.join(d, "spool_0"))
    consumed2 = ConsumedLog(d, 0)
    spooled = {raw.decode() for _, _, raw in spool2.pending()}
    for uid in consumed2.seen:
        assert uid in spooled, (
            f"LOST SAMPLE at crash point {crash_at!r}: uid {uid} is "
            f"consumed (never regenerated) but not spooled (cannot replay)"
        )
    # History is never damaged by the victim's crash.
    assert {f"prior{i}" for i in range(n_prior)} <= spooled
    # The safe direction IS reachable (consumed=no, spooled=yes): those
    # replay + dedup, never lose data.
    if crash_at in ("after_spool", "mid_consumed"):
        assert "victim" in spooled and "victim" not in consumed2.seen
    spool2.close()
    consumed2.close()


def test_torn_tail_repair_parity(tmp_path):
    """Both logs repair a torn tail the same way: drop exactly the torn
    record, keep everything before it, and accept appends cleanly after
    recovery (the fragment must not merge into the next record)."""
    d = str(tmp_path)
    spool = SampleSpool(os.path.join(d, "spool_0"))
    consumed = ConsumedLog(d, 0)
    for i in range(3):
        spool.append(f"u{i}".encode())
        consumed.add(f"u{i}")
    spool.close()
    consumed.close()
    (seg,) = [f for f in os.listdir(spool.dir) if f.endswith(".spool")]
    _tear_last_bytes(os.path.join(spool.dir, seg), n=1)
    _tear_last_bytes(consumed.path, n=1)

    spool2 = SampleSpool(os.path.join(d, "spool_0"))
    consumed2 = ConsumedLog(d, 0)
    assert {raw.decode() for _, _, raw in spool2.pending()} == {"u0", "u1"}
    assert consumed2.seen == {"u0", "u1"}
    spool2.append(b"u3")
    consumed2.add("u3")
    spool2.close()
    consumed2.close()
    spool3 = SampleSpool(os.path.join(d, "spool_0"))
    consumed3 = ConsumedLog(d, 0)
    assert {raw.decode() for _, _, raw in spool3.pending()} == \
        {"u0", "u1", "u3"}
    assert consumed3.seen == {"u0", "u1", "u3"}
    spool3.close()
    consumed3.close()


# ---------------------------------------------------------------------------
# SpoolSender ⇄ ack channel round trip (real ZMQ sockets)
# ---------------------------------------------------------------------------


def _pull_n(puller, n, deadline_secs=30.0):
    got = []
    deadline = time.monotonic() + deadline_secs
    while len(got) < n and time.monotonic() < deadline:
        obj = puller.pull(timeout_ms=100)
        if obj is not None:
            got.append(obj)
    assert len(got) == n, f"pulled {len(got)}/{n}"
    return got


def test_sender_ack_roundtrip_drains_spool(tmp_name_resolve, tmp_path):
    trainer_pull = ZmqPuller("e", "t", "trainer")
    ack_pull = ZmqPuller("e", "t", ack_channel_name(0))
    pusher = ZmqPusher("e", "t", "trainer", timeout=10.0)
    acker = ZmqPusher("e", "t", ack_channel_name(0), timeout=10.0)
    spool = SampleSpool(str(tmp_path / "sp"))
    sender = SpoolSender(spool, pusher, ack_pull, worker_index=0,
                         resend_timeout_secs=60.0, poll_secs=0.01)
    sender.start()
    try:
        for i in range(5):
            sender.submit({"uid": f"s{i}", "x": [1, 2, i]})
        got = _pull_n(trainer_pull, 5)
        # Every push carries (worker_index, seqno); first sends are not
        # flagged as replays.
        assert [o[SPOOL_KEY]["seq"] for o in got] == [1, 2, 3, 4, 5]
        assert all(o[SPOOL_KEY]["w"] == 0 for o in got)
        assert all("r" not in o[SPOOL_KEY] for o in got)
        assert [o["uid"] for o in got] == [f"s{i}" for i in range(5)]
        acker.push({"seqnos": [1, 2, 3, 4, 5]})
        deadline = time.monotonic() + 10
        while spool.stats().depth > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert spool.stats().depth == 0
    finally:
        sender.close(drain_secs=1.0)
        for s in (trainer_pull, ack_pull, pusher, acker):
            s.close()
    # acked == pushed at drain; fully-acked segments were deleted.
    assert spool.stats().acked_watermark == 5
    assert not [f for f in os.listdir(spool.dir) if f.endswith(".spool")]


def test_sender_resends_unacked_with_replay_flag(tmp_name_resolve, tmp_path):
    trainer_pull = ZmqPuller("e", "t", "trainer")
    ack_pull = ZmqPuller("e", "t", ack_channel_name(1))
    pusher = ZmqPusher("e", "t", "trainer", timeout=10.0)
    acker = ZmqPusher("e", "t", ack_channel_name(1), timeout=10.0)
    spool = SampleSpool(str(tmp_path / "sp"))
    sender = SpoolSender(spool, pusher, ack_pull, worker_index=1,
                         resend_timeout_secs=0.2, poll_secs=0.01)
    sender.start()
    try:
        sender.submit({"uid": "only"})
        first, second = _pull_n(trainer_pull, 2)
        assert "r" not in first[SPOOL_KEY]
        # The lost-ack recovery: the resend is flagged so the trainer's
        # staleness gate re-examines it.
        assert second[SPOOL_KEY] == {"w": 1, "seq": 1, "r": 1}
        acker.push({"seqnos": [1]})
        deadline = time.monotonic() + 10
        while spool.stats().depth > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert spool.stats().depth == 0
    finally:
        sender.close(drain_secs=1.0)
        for s in (trainer_pull, ack_pull, pusher, acker):
            s.close()


def test_sender_replays_spool_found_at_startup(tmp_name_resolve, tmp_path,
                                               counters):
    # Incarnation 1 spools three trajectories and dies before any ack
    # (submit works before the thread starts — the durable append is all
    # the asyncio loop ever depends on).
    spool = SampleSpool(str(tmp_path / "sp"))
    dead = SpoolSender(spool, None, None, worker_index=2)
    for i in range(3):
        dead.submit({"uid": f"crash{i}"})
    spool.close()

    trainer_pull = ZmqPuller("e", "t", "trainer")
    ack_pull = ZmqPuller("e", "t", ack_channel_name(2))
    pusher = ZmqPusher("e", "t", "trainer", timeout=10.0)
    spool2 = SampleSpool(str(tmp_path / "sp"))
    sender = SpoolSender(spool2, pusher, ack_pull, worker_index=2,
                         resend_timeout_secs=60.0, poll_secs=0.01)
    sender.start()
    try:
        got = _pull_n(trainer_pull, 3)
        # Crash replays arrive exactly once each, flagged as replays.
        assert [o["uid"] for o in got] == ["crash0", "crash1", "crash2"]
        assert all(o[SPOOL_KEY].get("r") == 1 for o in got)
        deadline = time.monotonic() + 5
        while counters().get("spool/replayed", 0) < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert counters().get("spool/replayed") == 3
    finally:
        sender.close(drain_secs=0.0)
        for s in (trainer_pull, ack_pull, pusher):
            s.close()


# ---------------------------------------------------------------------------
# SpoolIngest (trainer-side idempotent ingest)
# ---------------------------------------------------------------------------


def test_ingest_dedup_and_settlement():
    ing = SpoolIngest(staleness_limit=8)
    m = {"w": 0, "seq": 1}
    assert ing.observe("a", m, cur_version=0, sample_version=0.0) == \
        ("ingest", None)
    # Duplicate while the original is still in the pipeline: silent drop,
    # NO ack (acking now could lose the sample if the trainer dies before
    # the original trains — the ack rides the original's settlement).
    assert ing.observe("a", dict(m, r=1), 0, 0.0) == ("duplicate", None)
    # The master frees the id (trained) → its (worker, seqno) to ack.
    assert ing.pop_settled(["a", "never-seen"]) == {0: [1]}
    # A replay of the SETTLED sample (its ack was lost): re-ack at once.
    assert ing.observe("a", dict(m, r=1), 0, 0.0) == ("duplicate", (0, 1))
    assert ing.pop_settled(["a"]) == {}


def test_ingest_staleness_gate_applies_to_replays_only():
    ing = SpoolIngest(staleness_limit=2)
    # Fresh pushes already passed the manager's gate — never re-gated here,
    # however large the lag looks.
    assert ing.observe("fresh", {"w": 0, "seq": 1}, 100, 0.0)[0] == "ingest"
    # A replay beyond the bound is durably dropped AND acked.
    act, ackp = ing.observe("old", {"w": 1, "seq": 7, "r": 1}, 100, 0.0)
    assert (act, ackp) == ("stale", (1, 7))
    # Future resends of the dropped record re-ack via the settled path.
    assert ing.observe("old", {"w": 1, "seq": 7, "r": 1}, 100, 0.0) == \
        ("duplicate", (1, 7))
    # A replay within the bound ingests normally.
    assert ing.observe("young", {"w": 1, "seq": 8, "r": 1}, 100, 99.0) == \
        ("ingest", None)
    # limit < 0 disables the gate entirely.
    ing2 = SpoolIngest(staleness_limit=-1)
    assert ing2.observe("old", {"w": 0, "seq": 1, "r": 1}, 100, 0.0)[0] == \
        "ingest"


# ---------------------------------------------------------------------------
# Buffer: duplicate-id downgrade (at-least-once makes dupes normal)
# ---------------------------------------------------------------------------


def _sample(sid):
    return SequenceSample.from_default(
        ids=[sid],
        data={"packed_prompts": np.asarray([1, 2, 3], np.int32)},
        seqlens=[3],
    )


def test_buffer_duplicate_put_is_idempotent_skip(counters):
    async def main():
        buf = AsyncSequenceBuffer(n_rpcs_reading=1)
        await buf.put_batch([_sample("a")])
        await buf.put_batch([_sample("a")])  # duplicate: no raise
        assert len(buf) == 1
        # The live slot's read state is untouched: reads_left stays at the
        # single-consumer count, and the id did not re-enter _freed.
        assert buf._slots["a"].reads_left == 1
        assert await buf.pop_freed() == []
        out = await buf.get_batch_for_rpc("rpc", set(), 1, timeout=5)
        assert [s.ids[0] for s in out] == ["a"]
        # One read frees the slot exactly once — a double-counted
        # reads_left would have kept it alive.
        assert await buf.pop_freed() == ["a"]
        assert len(buf) == 0

    asyncio.run(main())
    assert counters().get("buffer/duplicate_dropped") == 1


# ---------------------------------------------------------------------------
# Satellite: gather completes on explicit done flag, not output-sniffing
# ---------------------------------------------------------------------------


def test_gather_completes_on_none_output_reply(tmp_name_resolve):
    server = WorkerRequestServer("e", "t", "w0")
    stream = MasterRequestStream("e", "t", ["w0"], timeout=10.0)
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            p = server.poll(timeout_ms=50)
            if p is not None:
                p.output = None  # legitimate None result, no exception
                server.reply(p)

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    try:
        rid = stream.post(Payload(handler="w0", handle_name="noop"))
        t0 = time.monotonic()
        # Pre-fix this wedged for the full timeout because
        # ``output is not None`` never became true.
        (reply,) = stream.gather([rid], timeout=30.0)
        assert time.monotonic() - t0 < 20.0
        assert reply.output is None and reply.done
        # Exception replies still raise.
        rid2 = stream.post(Payload(handler="w0", handle_name="noop"))
        stream._pending[rid2].exception = "boom"  # simulate worker error
        with pytest.raises(RuntimeError, match="boom"):
            stream.gather([rid2], timeout=30.0)
    finally:
        stop.set()
        th.join(timeout=5)
        stream.close()
        server.close()


# ---------------------------------------------------------------------------
# Satellite: non-wedging push (NOBLOCK + bounded retry + counter)
# ---------------------------------------------------------------------------


class _AlwaysFullSock:
    def __init__(self):
        self.attempts = 0

    def send(self, raw, flags=0):
        self.attempts += 1
        raise zmq.Again()

    def close(self, linger=0):
        pass


class _RecorderSock:
    def __init__(self):
        self.frames = []

    def send(self, raw, flags=0):
        self.frames.append(bytes(raw))

    def close(self, linger=0):
        pass


def test_push_blocked_bounded_retry_and_counter(tmp_name_resolve, counters):
    puller = ZmqPuller("e", "t", "sink")
    pusher = ZmqPusher("e", "t", "sink", timeout=10.0, block_secs=0.2)
    real = pusher._sock
    pusher._sock = _AlwaysFullSock()
    try:
        t0 = time.monotonic()
        with pytest.raises(zmq.Again):
            pusher.push({"x": 1})
        took = time.monotonic() - t0
        # Bounded: ~block_secs, not the old forever-blocking send.
        assert 0.15 <= took < 5.0
        assert pusher._sock.attempts >= 2  # retried inside the budget
        assert counters().get("stream/push_blocked", 0) >= 2
    finally:
        pusher._sock = real
        pusher.close()
        puller.close()


def test_wire_bytes_bit_identical_with_durability_off(tmp_name_resolve):
    """The durability-off pin: pushes carry NO spool framing and the wire
    bytes equal the plain msgpack encoding — byte-for-byte the legacy
    format (ISSUE 17 acceptance)."""
    telemetry.shutdown()  # no trace context → inject_payload is identity
    puller = ZmqPuller("e", "t", "sink2")
    pusher = ZmqPusher("e", "t", "sink2", timeout=10.0)
    rec = _RecorderSock()
    real = pusher._sock
    pusher._sock = rec
    try:
        obj = {"uid": "q1", "packed_input_ids": np.arange(4, dtype=np.int32)}
        pusher.push(obj)
        assert rec.frames == [streams._pack(obj)]
        assert SPOOL_KEY not in streams._unpack(rec.frames[0])
        assert "_trace" not in streams._unpack(rec.frames[0])
    finally:
        pusher._sock = real
        pusher.close()
        puller.close()
