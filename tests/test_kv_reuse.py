"""Persistent-KV chunked decode: parity + server cache behavior.

The core claim: prefill_state + N×decode_chunk == generate_batch (greedy),
so chunk continuations don't need to re-prefill the prefix (VERDICT r1
weakness #3; reference keeps SGLang's radix cache across the
abort/resubmit cycle, patch/sglang/v0.4.6.post4.patch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(vocab_size=97)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts():
    rng = np.random.RandomState(3)
    prompts = [rng.randint(2, 90, n).tolist() for n in (5, 9, 3, 12)]
    return genmod.pad_prompts(prompts, pad_token_id=0, bucket=16)


def test_chunked_decode_matches_one_shot_greedy(model):
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=24)
    key = jax.random.PRNGKey(1)

    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=24, eos_token_id=1, pad_token_id=0,
    )

    state = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
    )
    toks, lps = [], []
    done = jnp.zeros(len(plens), jnp.int32)
    for _ in range(3):  # 3 chunks of 8 == 24
        state, out = genmod.decode_chunk(
            params, cfg, state, done, key, g, n_tokens=8,
            eos_token_id=1, pad_token_id=0,
        )
        toks.append(np.asarray(out["output_ids"]))
        lps.append(np.asarray(out["output_logprobs"]))
        done = done + out["gen_mask"].sum(axis=1).astype(jnp.int32)
    toks = np.concatenate(toks, axis=1)
    lps = np.concatenate(lps, axis=1)

    ref_toks = np.asarray(ref["output_ids"])
    ref_mask = np.asarray(ref["gen_mask"])
    # tokens identical wherever the one-shot path generated a real token
    np.testing.assert_array_equal(toks[ref_mask], ref_toks[ref_mask])
    np.testing.assert_allclose(
        lps[ref_mask], np.asarray(ref["output_logprobs"])[ref_mask],
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_array_equal(
        np.asarray(done), np.asarray(ref["output_lens"])
    )


def test_decode_chunk_rows_at_different_lengths(model):
    """Continuous batching: rows whose prefixes differ in length decode
    together (per-row cache-write slots)."""
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=16)
    key = jax.random.PRNGKey(1)

    # one-shot reference
    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=16, eos_token_id=1, pad_token_id=0,
    )
    # advance row 0 and 2 by one chunk first, then merge all rows
    st = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
    )
    rows = [genmod.slice_state(st, i) for i in range(4)]
    part = genmod.stack_states([rows[0], rows[2]])
    part, out_a = genmod.decode_chunk(
        params, cfg, part, jnp.zeros(2, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    rows[0], rows[2] = genmod.slice_state(part, 0), genmod.slice_state(part, 1)
    merged = genmod.stack_states(rows)
    done = jnp.asarray([8, 0, 8, 0], jnp.int32)
    merged, out_b = genmod.decode_chunk(
        params, cfg, merged, done, key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    ref_toks = np.asarray(ref["output_ids"])
    ref_mask = np.asarray(ref["gen_mask"])
    got = {
        0: np.concatenate([np.asarray(out_a["output_ids"])[0],
                           np.asarray(out_b["output_ids"])[0]]),
        2: np.concatenate([np.asarray(out_a["output_ids"])[1],
                           np.asarray(out_b["output_ids"])[2]]),
        1: np.asarray(out_b["output_ids"])[1],
        3: np.asarray(out_b["output_ids"])[3],
    }
    for r in (0, 2):
        m = ref_mask[r]
        np.testing.assert_array_equal(got[r][: m.sum()], ref_toks[r][m])
    for r in (1, 3):
        m = ref_mask[r][:8]
        np.testing.assert_array_equal(got[r][: m.sum()], ref_toks[r][:8][m])


def test_grow_state_preserves_decode(model):
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=16)
    key = jax.random.PRNGKey(1)
    st = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=32
    )
    st, out1 = genmod.decode_chunk(
        params, cfg, st, jnp.zeros(4, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    st = genmod.grow_state(st, 64)
    st, out2 = genmod.decode_chunk(
        params, cfg, st, jnp.full(4, 8, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=16, eos_token_id=1, pad_token_id=0,
    )
    toks = np.concatenate([np.asarray(out1["output_ids"]),
                           np.asarray(out2["output_ids"])], axis=1)
    m = np.asarray(ref["gen_mask"])
    np.testing.assert_array_equal(toks[m], np.asarray(ref["output_ids"])[m])
