"""Persistent-KV chunked decode: parity + server cache behavior.

The core claim: prefill_state + N×decode_chunk == generate_batch (greedy),
so chunk continuations don't need to re-prefill the prefix (VERDICT r1
weakness #3; reference keeps SGLang's radix cache across the
abort/resubmit cycle, patch/sglang/v0.4.6.post4.patch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(vocab_size=97)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts():
    rng = np.random.RandomState(3)
    prompts = [rng.randint(2, 90, n).tolist() for n in (5, 9, 3, 12)]
    return genmod.pad_prompts(prompts, pad_token_id=0, bucket=16)


def test_chunked_decode_matches_one_shot_greedy(model):
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=24)
    key = jax.random.PRNGKey(1)

    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=24, eos_token_id=1, pad_token_id=0,
    )

    state = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
    )
    toks, lps = [], []
    done = jnp.zeros(len(plens), jnp.int32)
    for _ in range(3):  # 3 chunks of 8 == 24
        state, out = genmod.decode_chunk(
            params, cfg, state, done, key, g, n_tokens=8,
            eos_token_id=1, pad_token_id=0,
        )
        toks.append(np.asarray(out["output_ids"]))
        lps.append(np.asarray(out["output_logprobs"]))
        done = done + out["gen_mask"].sum(axis=1).astype(jnp.int32)
    toks = np.concatenate(toks, axis=1)
    lps = np.concatenate(lps, axis=1)

    ref_toks = np.asarray(ref["output_ids"])
    ref_mask = np.asarray(ref["gen_mask"])
    # tokens identical wherever the one-shot path generated a real token
    np.testing.assert_array_equal(toks[ref_mask], ref_toks[ref_mask])
    np.testing.assert_allclose(
        lps[ref_mask], np.asarray(ref["output_logprobs"])[ref_mask],
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_array_equal(
        np.asarray(done), np.asarray(ref["output_lens"])
    )


def test_decode_chunk_rows_at_different_lengths(model):
    """Continuous batching: rows whose prefixes differ in length decode
    together (per-row cache-write slots)."""
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=16)
    key = jax.random.PRNGKey(1)

    # one-shot reference
    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=16, eos_token_id=1, pad_token_id=0,
    )
    # advance row 0 and 2 by one chunk first, then merge all rows
    st = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
    )
    rows = [genmod.slice_state(st, i) for i in range(4)]
    part = genmod.stack_states([rows[0], rows[2]])
    part, out_a = genmod.decode_chunk(
        params, cfg, part, jnp.zeros(2, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    rows[0], rows[2] = genmod.slice_state(part, 0), genmod.slice_state(part, 1)
    merged = genmod.stack_states(rows)
    done = jnp.asarray([8, 0, 8, 0], jnp.int32)
    merged, out_b = genmod.decode_chunk(
        params, cfg, merged, done, key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    ref_toks = np.asarray(ref["output_ids"])
    ref_mask = np.asarray(ref["gen_mask"])
    got = {
        0: np.concatenate([np.asarray(out_a["output_ids"])[0],
                           np.asarray(out_b["output_ids"])[0]]),
        2: np.concatenate([np.asarray(out_a["output_ids"])[1],
                           np.asarray(out_b["output_ids"])[2]]),
        1: np.asarray(out_b["output_ids"])[1],
        3: np.asarray(out_b["output_ids"])[3],
    }
    for r in (0, 2):
        m = ref_mask[r]
        np.testing.assert_array_equal(got[r][: m.sum()], ref_toks[r][m])
    for r in (1, 3):
        m = ref_mask[r][:8]
        np.testing.assert_array_equal(got[r][: m.sum()], ref_toks[r][:8][m])


def test_extend_state_matches_full_prefill(model):
    """Prefix seeding's primitive: prefill(prefix) + extend(suffix) decodes
    the same greedy tokens as prefill(prefix+suffix) — including when the
    suffix is right-padded to a bucket (garbage slots masked/overwritten)."""
    cfg, params = model
    rng = np.random.RandomState(11)
    common = rng.randint(2, 90, 10).tolist()
    full = common + rng.randint(2, 90, 5).tolist()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=12)
    key = jax.random.PRNGKey(1)

    padded, plens = genmod.pad_prompts([full], 0, bucket=16)
    ref = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
    )
    ref, ref_out = genmod.decode_chunk(
        params, cfg, ref, jnp.zeros(1, jnp.int32), key, g, n_tokens=12,
        eos_token_id=1, pad_token_id=0,
    )

    pc, lc = genmod.pad_prompts([common], 0, bucket=16)
    donor = genmod.prefill_state(
        params, cfg, jnp.asarray(pc), jnp.asarray(lc), S=64
    )
    st = genmod.clone_prefix(donor, len(common))
    suffix = np.asarray(full[len(common):], np.int32)
    T = 8  # padded: 5 real + 3 pad tokens
    padsuf = np.zeros((1, T), np.int32)
    padsuf[0, :len(suffix)] = suffix
    st = genmod.extend_state(
        params, cfg, st, jnp.asarray(padsuf),
        jnp.asarray([len(suffix)], jnp.int32),
    )
    assert int(st["cur_len"][0]) == len(full)
    st, out = genmod.decode_chunk(
        params, cfg, st, jnp.zeros(1, jnp.int32), key, g, n_tokens=12,
        eos_token_id=1, pad_token_id=0,
    )
    np.testing.assert_array_equal(
        np.asarray(out["output_ids"]), np.asarray(ref_out["output_ids"])
    )
    np.testing.assert_allclose(
        np.asarray(out["output_logprobs"]),
        np.asarray(ref_out["output_logprobs"]), rtol=2e-4, atol=2e-4,
    )


def test_row_budget_freezes_state_at_allowance(model):
    """Regression: a row truncated by ``row_budget`` must retain exactly
    the state it had at its allowance — same cur_len AND last_logits as a
    run that stopped there. The pad-token steps after a row finishes must
    not clobber the carried logits: a serving-mode retained state hands
    them to chunk continuations and full-match prefix clones."""
    cfg, params = model
    from areal_tpu.ops.sampling import sampling_from_gconfigs

    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=8)
    key = jax.random.PRNGKey(2)
    sampling = sampling_from_gconfigs([g] * 4)

    def _run(n_tokens, row_budget):
        # fresh prefill per run: decode_chunk_rows donates its state
        st = genmod.prefill_state(
            params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=64
        )
        return genmod.decode_chunk_rows(
            params, cfg, st, jnp.zeros(4, jnp.int32), key, sampling,
            n_tokens=n_tokens, eos_token_id=1, pad_token_id=0,
            row_budget=row_budget,
        )

    long_st, long_out = _run(8, jnp.full(4, 3, jnp.int32))
    short_st, short_out = _run(3, None)
    np.testing.assert_array_equal(
        np.asarray(long_st["cur_len"]), np.asarray(short_st["cur_len"])
    )
    np.testing.assert_allclose(
        np.asarray(long_st["last_logits"]),
        np.asarray(short_st["last_logits"]), rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(long_out["output_ids"])[:, :3],
        np.asarray(short_out["output_ids"]),
    )


def _serving_server(model, prefix_reuse: bool):
    from areal_tpu.api.train_config import ServingConfig
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )

    cfg, params = model
    return GenerationServer(
        GenerationServerConfig(
            experiment="kvreuse", trial="t0", chunk_tokens=6,
            prompt_bucket=8, kv_bucket=32,
            # EOS off the greedy path for these prompts/weights: the donor
            # must run its full allowance so its state is retained.
            eos_token_id=96,
            serving=ServingConfig(
                enabled=True, prefix_reuse=prefix_reuse,
                min_prefix_tokens=4, max_kv_capacity=256,
            ),
        ),
        cfg, params,
    )


def _decode_one(server, prompt, rid, max_tokens=6):
    from areal_tpu.system.generation_server import _Pending

    g = GenerationHyperparameters(greedy=True, max_new_tokens=max_tokens)
    (res,) = server._decode_batch([_Pending(
        prompt=np.asarray(prompt, np.int32), gconfig=g,
        max_tokens=max_tokens, future=None, rid=rid,
    )])
    return res


@pytest.mark.serving
def test_cross_request_prefix_seeding_parity(model):
    """Acceptance (docs/serving.md): greedy outputs are bit-identical with
    serving.prefix_reuse on vs off; the prefill-token counter shows reuse
    actually skipped prefill work; and parity survives donor eviction."""
    rng = np.random.RandomState(5)
    prompt_a = rng.randint(2, 90, 12).tolist()
    # B shares A's first 8 tokens, then diverges.
    prompt_b = prompt_a[:8] + rng.randint(2, 90, 4).tolist()

    on = _serving_server(model, prefix_reuse=True)
    off = _serving_server(model, prefix_reuse=False)

    # Donor request on both servers: full allowance without EOS retains
    # the decode state (and, on the reuse server, indexes it in the trie).
    res_a_on = _decode_one(on, prompt_a, rid="ra")
    res_a_off = _decode_one(off, prompt_a, rid="ra")
    assert res_a_on == res_a_off
    assert on.serving.kv.count == 1

    prefill_on_before = on._prefill_tokens
    prefill_off_before = off._prefill_tokens
    res_b_on = _decode_one(on, prompt_b, rid="rb")
    res_b_off = _decode_one(off, prompt_b, rid="rb")
    # Bit-identical outputs with reuse on vs off.
    assert res_b_on["output_ids"] == res_b_off["output_ids"]
    np.testing.assert_allclose(
        res_b_on["output_logprobs"], res_b_off["output_logprobs"],
        rtol=2e-4, atol=2e-4,
    )
    # Reuse genuinely skipped prefill: only the 4-token suffix was
    # prefilled on the reuse server vs the full 12-token prompt without.
    assert on._prefill_tokens - prefill_on_before == len(prompt_b) - 8
    assert off._prefill_tokens - prefill_off_before == len(prompt_b)

    # Donor evicted: same request (fresh rid) falls back to a full
    # prefill and still produces identical output.
    on.serving.kv.clear()
    prefill_before = on._prefill_tokens
    res_c_on = _decode_one(on, prompt_b, rid="rc")
    assert res_c_on["output_ids"] == res_b_on["output_ids"]
    assert on._prefill_tokens - prefill_before == len(prompt_b)


@pytest.mark.serving
def test_prefix_seeding_savings_gate(model):
    """Seeding is skipped when the bucketed suffix width equals the
    full-prompt prefill width — same padded matmul, so reuse would only
    add clone overhead and a serial B=1 extend. The request rides the
    plain batched prefill and parity still holds."""
    rng = np.random.RandomState(11)
    prompt_a = rng.randint(2, 90, 8).tolist()
    # Shares exactly min_prefix_tokens=4, then diverges by construction;
    # both prompts (and the 4-token suffix) round to the same 8-wide
    # width bucket, so there are no padded-compute savings.
    prompt_b = prompt_a[:4] + [(t + 1) % 90 + 2 for t in prompt_a[4:]]

    on = _serving_server(model, prefix_reuse=True)
    off = _serving_server(model, prefix_reuse=False)
    _decode_one(on, prompt_a, rid="ra")
    _decode_one(off, prompt_a, rid="ra")
    assert on.serving.kv.count == 1

    before = on._prefill_tokens
    res_on = _decode_one(on, prompt_b, rid="rb")
    res_off = _decode_one(off, prompt_b, rid="rb")
    # The savings gate fell back to a full prefill despite the donor.
    assert on._prefill_tokens - before == len(prompt_b)
    assert res_on["output_ids"] == res_off["output_ids"]


@pytest.mark.serving
def test_budget_truncated_donor_parity(model):
    """Regression: a donor retained after exhausting its per-request
    budget BEFORE the static chunk length (serving keeps n == allowance
    rows) must seed an exact-full-match clone bit-identically — its
    last_logits are the ones after its last real token, not after the
    chunk's trailing pad steps."""
    rng = np.random.RandomState(9)
    prompt_a = rng.randint(2, 90, 10).tolist()

    on = _serving_server(model, prefix_reuse=True)
    off = _serving_server(model, prefix_reuse=False)

    # Donor truncated by its own budget (3 < chunk_tokens=6): serving
    # mode retains it as a prefix-reuse donor.
    res_a = _decode_one(on, prompt_a, rid="ra", max_tokens=3)
    _decode_one(off, prompt_a, rid="ra", max_tokens=3)
    assert len(res_a["output_ids"]) == 3
    assert on.serving.kv.count == 1

    # New request = the donor's full retained sequence: exact match, pure
    # clone — the first sampled token comes straight from the donor's
    # retained last_logits.
    prompt_b = prompt_a + res_a["output_ids"]
    prefill_before = on._prefill_tokens
    res_b_on = _decode_one(on, prompt_b, rid="rb", max_tokens=4)
    res_b_off = _decode_one(off, prompt_b, rid="rb", max_tokens=4)
    assert on._prefill_tokens == prefill_before  # zero prefill work
    assert res_b_on["output_ids"] == res_b_off["output_ids"]
    np.testing.assert_allclose(
        res_b_on["output_logprobs"], res_b_off["output_logprobs"],
        rtol=2e-4, atol=2e-4,
    )

    # Regression: the pure-clone row decoded as a single-row group, and a
    # one-state stack_states is the identity on its arrays — the donated
    # decode must not have deleted the donor's retained buffers in place.
    # Drop rb's retained state so the next clone MUST come from the same
    # donor, then decode through it again.
    on.serving.kv.pop("rb")
    res_c_on = _decode_one(on, prompt_b, rid="rc", max_tokens=4)
    assert res_c_on["output_ids"] == res_b_on["output_ids"]


def test_grow_state_preserves_decode(model):
    cfg, params = model
    padded, plens = _prompts()
    g = GenerationHyperparameters(greedy=True, max_new_tokens=16)
    key = jax.random.PRNGKey(1)
    st = genmod.prefill_state(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), S=32
    )
    st, out1 = genmod.decode_chunk(
        params, cfg, st, jnp.zeros(4, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    st = genmod.grow_state(st, 64)
    st, out2 = genmod.decode_chunk(
        params, cfg, st, jnp.full(4, 8, jnp.int32), key, g, n_tokens=8,
        eos_token_id=1, pad_token_id=0,
    )
    ref = genmod.generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(plens), key, g,
        max_new_tokens=16, eos_token_id=1, pad_token_id=0,
    )
    toks = np.concatenate([np.asarray(out1["output_ids"]),
                           np.asarray(out2["output_ids"])], axis=1)
    m = np.asarray(ref["gen_mask"])
    np.testing.assert_array_equal(toks[m], np.asarray(ref["output_ids"])[m])
