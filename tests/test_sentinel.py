"""Training-health sentinel (system/sentinel.py,
docs/observability.md §Alerting).

Fake clocks everywhere: the rule state machine (pending → firing →
resolved), `for:` hold windows, cooldowns, absence-of-signal grace, and
rolling baselines are all driven by injected monotonic/wall clocks —
zero real sleeps. Evidence/inhibit/pause side effects are injected fns
except where the test is specifically about the real wiring
(name-resolve silence + inhibit keys, the aggregator hosting the
engine).
"""

import json
import os
import threading

import pytest

from areal_tpu.api.train_config import SentinelConfig, TelemetryConfig
from areal_tpu.base import name_resolve, names, telemetry
from areal_tpu.system import sentinel as sn
from areal_tpu.system.sentinel import (
    DEFAULT_RULES,
    Sentinel,
    SentinelConfigError,
    parse_duration,
    parse_rules,
    rules_from_config,
)

pytestmark = pytest.mark.sentinel


def make_sentinel(tmp_path, rules, *, cfg=None, stitcher=None,
                  flight=None, inhibit=None, pause=None):
    """A fully fake-clocked sentinel; returns (sentinel, clock_setter,
    wall_setter, captured side effects)."""
    t = {"mono": 0.0, "wall": 1_000.0}
    captured = {"flight": [], "inhibit": [], "pause": 0}

    def _pause():
        captured["pause"] += 1

    s = Sentinel(
        cfg or SentinelConfig(enabled=True, eval_interval_secs=0.1),
        "sentexp", "t0",
        rules=rules,
        stitcher=stitcher,
        alerts_path=str(tmp_path / "alerts.jsonl"),
        evidence_dir=str(tmp_path / "evidence"),
        clock=lambda: t["mono"],
        wall=lambda: t["wall"],
        flight_fn=flight or captured["flight"].append,
        inhibit_fn=inhibit or captured["inhibit"].append,
        pause_fn=pause or _pause,
    )

    def at(mono, wall=None):
        t["mono"] = mono
        if wall is not None:
            t["wall"] = wall

    return s, at, captured


def read_alerts(tmp_path):
    p = tmp_path / "alerts.jsonl"
    if not p.exists():
        return []
    with open(p) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


THRESH = {"id": "kl", "metric": "train/approx_kl", "kind": "threshold",
          "op": "gt", "value": 1.0, "for": 2, "cooldown": 30,
          "severity": "critical"}


# ---------------------------------------------------------------------------
# rule grammar / parse-time validation
# ---------------------------------------------------------------------------


def test_parse_duration_units():
    assert parse_duration(30) == 30.0
    assert parse_duration("30") == 30.0
    assert parse_duration("500ms") == 0.5
    assert parse_duration("5m") == 300.0
    assert parse_duration("1.5h") == 5400.0
    with pytest.raises(ValueError):
        parse_duration("soon")


def test_default_rule_pack_parses():
    rules = rules_from_config(SentinelConfig(enabled=True))
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids)) == len(DEFAULT_RULES)
    assert all(r.severity in sn.SEVERITIES for r in rules)
    assert all(r.metric in sn.METRIC_CATALOG for r in rules)
    # and the pack can be dropped entirely
    assert rules_from_config(
        SentinelConfig(enabled=True, default_rules=False)
    ) == []


def test_parse_rejects_unknown_metric_naming_the_rule():
    with pytest.raises(SentinelConfigError, match="'kl'"):
        parse_rules([dict(THRESH, metric="train/approx_klx")])


def test_parse_rejects_nonpositive_durations():
    with pytest.raises(SentinelConfigError, match="'for'"):
        parse_rules([dict(THRESH, **{"for": 0})])
    with pytest.raises(SentinelConfigError, match="cooldown"):
        parse_rules([dict(THRESH, cooldown=-5)])
    with pytest.raises(SentinelConfigError, match="window"):
        parse_rules([dict(THRESH, window=0)])


def test_parse_rejects_duplicates_and_bad_enums():
    with pytest.raises(SentinelConfigError, match="duplicate"):
        parse_rules([THRESH, dict(THRESH, severity="warn")])
    for field, bad in (("kind", "slope"), ("severity", "fatal"),
                       ("op", "=="), ("agg", "p99"), ("action", "nuke")):
        with pytest.raises(SentinelConfigError, match=field):
            parse_rules([dict(THRESH, **{field: bad})])
    with pytest.raises(SentinelConfigError, match="id"):
        parse_rules([{"metric": "train/approx_kl"}])


def test_validate_config_front_runs_the_rule_pack():
    from areal_tpu.api import cli_args
    from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig

    cfg = PPOMATHConfig()
    cfg.sentinel.enabled = True
    # the sentinel lives in the master's aggregator: telemetry required
    with pytest.raises(cli_args.ConfigError, match="telemetry"):
        cli_args.validate_config(cfg)
    cfg.telemetry.enabled = True
    cli_args.validate_config(cfg)  # default pack is valid
    cfg.sentinel.rules = [{"id": "bad", "metric": "no/such_metric"}]
    with pytest.raises(cli_args.ConfigError, match="'bad'"):
        cli_args.validate_config(cfg)
    # duplicate against the default pack is caught too
    cfg.sentinel.rules = [dict(DEFAULT_RULES[0])]
    with pytest.raises(cli_args.ConfigError, match="duplicate"):
        cli_args.validate_config(cfg)


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_threshold_pending_firing_resolved(tmp_path):
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 0.2}, now=0.0)
    s.tick(0.0)
    assert s.states()["kl"]["state"] == "ok"
    at(1.0)
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 2.0}, now=1.0)
    s.tick(1.0)
    # predicate holds but the `for:` window has not elapsed yet
    assert s.states()["kl"]["state"] == "pending"
    assert read_alerts(tmp_path) == []
    at(3.5, 1010.0)
    s.tick(3.5)
    assert s.states()["kl"]["state"] == "firing"
    recs = read_alerts(tmp_path)
    assert [r["event"] for r in recs] == ["firing"]
    assert recs[0]["rule"] == "kl" and recs[0]["severity"] == "critical"
    assert recs[0]["value"] == 2.0
    snap = s.registry.snapshot()
    assert snap["counters"]["alerts{rule=kl,severity=critical}"] == 1.0
    assert snap["gauges"]["alert_active{rule=kl}"] == 1.0
    # one evidence bundle + the critical autoscale-inhibit hint
    assert len(cap["flight"]) == 1 and len(cap["inhibit"]) == 1
    # recovery resolves the alert
    at(5.0)
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 0.1}, now=5.0)
    s.tick(5.0)
    assert s.states()["kl"]["state"] == "ok"
    assert read_alerts(tmp_path)[-1]["event"] == "resolved"
    assert s.registry.snapshot()["gauges"]["alert_active{rule=kl}"] == 0.0


def test_blip_shorter_than_for_window_never_fires(tmp_path):
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))
    at(1.0)
    s.feed("trainer", {"train/approx_kl": 5.0}, now=1.0)
    s.tick(1.0)
    at(2.0)
    s.feed("trainer", {"train/approx_kl": 0.1}, now=2.0)  # blip over
    s.tick(2.0)
    at(10.0)
    s.tick(10.0)
    assert s.states()["kl"]["state"] == "ok"
    assert read_alerts(tmp_path) == [] and cap["flight"] == []


def test_cooldown_bounds_refires(tmp_path):
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))

    def trip(t0):
        at(t0)
        s.feed("trainer", {"train/approx_kl": 3.0}, now=t0)
        s.tick(t0)
        at(t0 + 2.5)
        s.tick(t0 + 2.5)

    def clear(t0):
        at(t0)
        s.feed("trainer", {"train/approx_kl": 0.0}, now=t0)
        s.tick(t0)

    trip(0.0)
    assert s.states()["kl"]["fires"] == 1
    clear(5.0)
    # re-trip inside the 30s cooldown: held pending, no second fire
    trip(10.0)
    assert s.states()["kl"]["state"] == "pending"
    assert s.states()["kl"]["fires"] == 1
    # past the cooldown it fires again
    at(40.0)
    s.tick(40.0)
    assert s.states()["kl"]["state"] == "firing"
    assert s.states()["kl"]["fires"] == 2


def test_absence_of_signal(tmp_path):
    rules = parse_rules([
        {"id": "stalled", "metric": "train/optimizer_steps",
         "kind": "absence", "for": 60, "cooldown": 60,
         "severity": "critical"},
    ])
    s, at, cap = make_sentinel(tmp_path, rules)
    # never-seen metric gets the startup grace: quiet until `for` elapses
    at(30.0)
    s.tick(30.0)
    assert s.states()["stalled"]["state"] == "ok"
    at(61.0)
    s.tick(61.0)
    assert s.states()["stalled"]["state"] == "firing"
    # a sample arriving resolves it
    at(70.0)
    s.feed("trainer", {"train/optimizer_steps": 12.0}, now=70.0)
    s.tick(70.0)
    assert s.states()["stalled"]["state"] == "ok"
    events = [r["event"] for r in read_alerts(tmp_path)]
    assert events == ["firing", "resolved"]


def test_absence_detects_wedged_but_flushing_producer(tmp_path):
    """Workers flush their full cumulative registry every interval, so a
    wedged trainer keeps DELIVERING train/optimizer_steps — absence must
    key off the value changing, not mere sample arrival."""
    rules = parse_rules([
        {"id": "stalled", "metric": "train/optimizer_steps",
         "kind": "absence", "for": 60, "cooldown": 60,
         "severity": "critical"},
    ])
    s, at, cap = make_sentinel(tmp_path, rules)
    for t in (0.0, 30.0, 59.0):  # healthy: the counter advances
        at(t)
        s.feed("trainer", {}, {"train/optimizer_steps": t + 1}, now=t)
        s.tick(t)
    assert s.states()["stalled"]["state"] == "ok"
    # wedged: snapshots keep arriving but the value never moves
    for t in (70.0, 90.0, 110.0, 125.0):
        at(t)
        s.feed("trainer", {}, {"train/optimizer_steps": 60.0}, now=t)
        s.tick(t)
    assert s.states()["stalled"]["state"] == "firing"
    # the next real optimizer step resolves it
    at(130.0)
    s.feed("trainer", {}, {"train/optimizer_steps": 61.0}, now=130.0)
    s.tick(130.0)
    assert s.states()["stalled"]["state"] == "ok"


def test_departed_worker_sources_expire(tmp_path):
    """A scaled-down/evicted worker's last reading must not pin a
    max-aggregate (and a false alert) forever."""
    rules = parse_rules([
        {"id": "worst", "metric": "rollout/staleness_current",
         "op": "gt", "value": 7.0, "for": 1, "cooldown": 10,
         "agg": "max", "severity": "warn"},
    ])
    cfg = SentinelConfig(enabled=True, eval_interval_secs=0.1,
                         source_expiry_secs=30.0)
    s, at, cap = make_sentinel(tmp_path, rules, cfg=cfg)
    s.feed("rollout:0", {"rollout/staleness_current": 1.0}, now=0.0)
    s.feed("rollout:1", {"rollout/staleness_current": 9.0}, now=0.0)
    s.tick(0.0)
    at(2.0)
    s.tick(2.0)
    assert s.states()["worst"]["state"] == "firing"
    # rollout:1 departs; rollout:0 keeps reporting a healthy value
    for t in (10.0, 20.0, 31.0):
        at(t)
        s.feed("rollout:0", {"rollout/staleness_current": 1.0}, now=t)
        s.tick(t)
    st = s.states()["worst"]
    assert st["state"] == "ok" and st["value"] == 1.0


def test_silence_is_cached_not_polled(tmp_path, tmp_name_resolve,
                                      monkeypatch):
    """An active alert under a long silence must not hit name-resolve
    every tick: the expiry is cached after the first suppressed fire."""
    reads = {"n": 0}
    real_get = name_resolve.get

    def counting_get(key):
        if "sentinel_silence" in key:
            reads["n"] += 1
        return real_get(key)

    monkeypatch.setattr(name_resolve, "get", counting_get)
    name_resolve.add(
        names.sentinel_silence("sentexp", "t0", "kl"),
        json.dumps({"until": 5_000.0}), replace=True,
    )
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))
    at(0.0)
    s.feed("trainer", {"train/approx_kl": 9.0}, now=0.0)
    for t in range(1, 40):
        at(float(t))
        s.tick(float(t))
    assert s.states()["kl"]["state"] == "pending"
    assert reads["n"] == 1  # one real read; the rest served from cache
    assert s.registry.snapshot()["counters"][
        "sentinel/silenced{rule=kl}"] == 1.0


def test_rate_rule_differentiates_counters(tmp_path):
    rules = parse_rules([
        {"id": "failover_storm", "metric": "rollout/failovers",
         "kind": "rate", "op": "gt", "value": 1.0, "for": 1,
         "window": 30, "cooldown": 60, "severity": "warn"},
    ])
    s, at, cap = make_sentinel(tmp_path, rules)
    # slope 0.5/s: below the 1/s threshold
    for i, v in enumerate([0, 5, 10]):
        at(float(i * 10))
        s.feed("rollout", {}, {"rollout/failovers": float(v)},
               now=float(i * 10))
        s.tick(float(i * 10))
    assert s.states()["failover_storm"]["state"] == "ok"
    # slope jumps to 5/s
    at(31.0)
    s.feed("rollout", {}, {"rollout/failovers": 115.0}, now=31.0)
    s.tick(31.0)
    at(33.0)
    s.tick(33.0)
    assert s.states()["failover_storm"]["state"] == "firing"


def test_baseline_deviation(tmp_path):
    rules = parse_rules([
        {"id": "grad_spike", "metric": "train/grad_norm",
         "kind": "baseline", "value": 6.0, "for": 1, "window": 300,
         "cooldown": 60, "severity": "warn"},
    ])
    s, at, cap = make_sentinel(tmp_path, rules)
    # a stable baseline with mild jitter — never fires, even early when
    # there are too few points to judge
    vals = [1.0, 1.1, 0.9, 1.0, 1.05, 0.95, 1.0, 1.1, 0.9, 1.0]
    for i, v in enumerate(vals):
        at(float(i))
        s.feed("trainer", {"train/grad_norm": v}, now=float(i))
        s.tick(float(i))
    assert s.states()["grad_spike"]["state"] == "ok"
    # a 50x outlier is far beyond 6 deviations
    at(11.0)
    s.feed("trainer", {"train/grad_norm": 50.0}, now=11.0)
    s.tick(11.0)
    at(12.5)
    s.tick(12.5)
    assert s.states()["grad_spike"]["state"] == "firing"


def test_goodput_collapse_default_rule(tmp_path):
    """The default-pack goodput_collapse rule (docs/observability.md
    §Goodput) fed the aggregator-derived fleet/goodput series (source
    "fleet:0", exactly how TelemetryAggregator._ingest feeds it): a
    stable busy fleet stays quiet; chips going idle fires warn after the
    rule's for: hold — and the rolling-median baseline survives the
    anomaly's own points (it must not self-clear)."""
    raw = next(r for r in sn.DEFAULT_RULES if r["id"] == "goodput_collapse")
    rules = parse_rules([dict(raw)])
    s, at, cap = make_sentinel(tmp_path, rules)
    # 300s of healthy fleet goodput around 0.8 with mild jitter
    for i in range(300):
        at(float(i))
        s.feed("fleet:0", {"fleet/goodput": 0.8 + 0.01 * (i % 3)},
               now=float(i))
        s.tick(float(i))
    assert s.states()["goodput_collapse"]["state"] == "ok"
    # collapse: the fleet goes near-idle and STAYS there through the
    # 60s for: hold (the 1200s median baseline is still dominated by
    # the healthy history, so the anomaly cannot poison it)
    for i in range(300, 380):
        at(float(i))
        s.feed("fleet:0", {"fleet/goodput": 0.05}, now=float(i))
        s.tick(float(i))
    assert s.states()["goodput_collapse"]["state"] == "firing"
    firing = [r for r in read_alerts(tmp_path)
              if r["event"] == "firing"]
    assert firing and firing[0]["rule"] == "goodput_collapse"
    assert firing[0]["severity"] == "warn"
    assert firing[0]["value"] == 0.05


def test_agg_across_workers_and_label_values(tmp_path):
    rules = parse_rules([
        {"id": "worst", "metric": "rollout/staleness_current",
         "op": "gt", "value": 7.0, "for": 1, "cooldown": 60,
         "agg": "max", "severity": "warn"},
        {"id": "typical", "metric": "rollout/staleness_current",
         "op": "gt", "value": 7.0, "for": 1, "cooldown": 60,
         "agg": "mean", "severity": "warn"},
    ])
    s, at, cap = make_sentinel(tmp_path, rules)
    s.feed("rollout", {"rollout/staleness_current": 1.0}, now=0.0)
    # a second source: same worker kind, different index/labels
    s.feed("rollout2", {"rollout/staleness_current": 9.0}, now=0.0)
    s.tick(0.0)
    at(1.5)
    s.tick(1.5)
    st = s.states()
    # max over sources trips; the mean (5.0) stays under threshold
    assert st["worst"]["state"] == "firing"
    assert st["typical"]["state"] == "ok"
    assert st["worst"]["value"] == 9.0 and st["typical"]["value"] == 5.0


# ---------------------------------------------------------------------------
# silences, evidence, inhibit, pause
# ---------------------------------------------------------------------------


def test_silence_suppresses_fire_until_expiry(tmp_path, tmp_name_resolve):
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))
    name_resolve.add(
        names.sentinel_silence("sentexp", "t0", "kl"),
        json.dumps({"until": 1_500.0}), replace=True,
    )
    at(0.0)
    s.feed("trainer", {"train/approx_kl": 5.0}, now=0.0)
    s.tick(0.0)
    at(3.0)  # wall stays 1000 < 1500: silenced
    s.tick(3.0)
    assert s.states()["kl"]["state"] == "pending"
    assert read_alerts(tmp_path) == [] and cap["flight"] == []
    assert s.registry.snapshot()["counters"][
        "sentinel/silenced{rule=kl}"] >= 1.0
    # silence expires (wall moves past `until`): the held alert fires
    at(4.0, 2_000.0)
    s.tick(4.0)
    assert s.states()["kl"]["state"] == "firing"


def test_evidence_bundle_layout_and_cap(tmp_path):
    class FakeStitcher:
        def recent_trace_ids(self, n):
            return ["trace-a", "trace-b"][:n]

    cfg = SentinelConfig(enabled=True, eval_interval_secs=0.1,
                         max_evidence_bundles=1)
    s, at, cap = make_sentinel(
        tmp_path, parse_rules([THRESH]), cfg=cfg, stitcher=FakeStitcher()
    )
    at(0.0)
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 3.0}, now=0.0)
    s.tick(0.0)
    at(2.5)
    s.tick(2.5)
    bundles = os.listdir(tmp_path / "evidence")
    assert len(bundles) == 1 and bundles[0].startswith("kl-")
    d = tmp_path / "evidence" / bundles[0]
    with open(d / "alert.json") as f:
        alert = json.load(f)
    # the triggering metric window + its per-source readings ride along
    assert alert["rule"] == "kl" and alert["metric_window"]
    assert alert["metric_window"][-1]["value"] == 3.0
    assert "trainer|train/approx_kl{mfc=actor_train}" in alert["sources"]
    with open(d / "traces.json") as f:
        assert json.load(f)["pinned_trace_ids"] == ["trace-a", "trace-b"]
    # the fleet-wide flight dump was requested INTO the bundle
    assert cap["flight"] == [str(d)]
    # a second fire past cooldown skips capture at the bundle cap
    at(5.0)
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 0.0}, now=5.0)
    s.tick(5.0)
    at(40.0)
    s.feed("trainer", {"train/approx_kl{mfc=actor_train}": 3.0}, now=40.0)
    s.tick(40.0)
    at(45.0)
    s.tick(45.0)
    assert s.states()["kl"]["fires"] == 2
    assert len(os.listdir(tmp_path / "evidence")) == 1
    assert s.registry.snapshot()["counters"][
        "sentinel/evidence_skipped"] == 1.0


def test_critical_publishes_autoscale_inhibit(tmp_path, tmp_name_resolve):
    from areal_tpu.system import autoscaler

    # real inhibit_fn (writes names.autoscale_inhibit), fake clocks
    t = {"wall": 1_000.0}
    s = Sentinel(
        SentinelConfig(enabled=True, eval_interval_secs=0.1,
                       inhibit_secs=120.0),
        "sentexp", "t0", rules=parse_rules([THRESH]),
        alerts_path=str(tmp_path / "alerts.jsonl"),
        evidence_dir=None,
        clock=lambda: t.setdefault("mono", 0.0) or t["mono"],
        wall=lambda: t["wall"],
        flight_fn=lambda d: None,
    )
    t["mono"] = 0.0
    s.feed("trainer", {"train/approx_kl": 9.0}, now=0.0)
    s.tick(0.0)
    t["mono"] = 2.5
    s.tick(2.5)
    rec = autoscaler.read_inhibit("sentexp", "t0", wall=lambda: 1_010.0)
    assert rec is not None and rec["rule"] == "kl"
    # expired hints read as absent — a resolved incident cannot pin the
    # fleet forever
    assert autoscaler.read_inhibit("sentexp", "t0",
                                   wall=lambda: 1_200.0) is None
    # and an inhibited signal suppresses every scale-up reason
    core = autoscaler.AutoscalerCore(
        autoscaler.AutoscaleConfig(enabled=True, max_servers=4),
        clock=lambda: 0.0,
    )
    hot = dict(current_size=1, utilization=0.99, queue_depth=50.0)
    assert core._up_reasons(autoscaler.FleetSignals(**hot)) != []
    assert core._up_reasons(
        autoscaler.FleetSignals(**hot, inhibited=True)) == []


def test_pause_action_is_gated_by_allow_pause(tmp_path):
    rule = dict(THRESH, action="pause")
    s, at, cap = make_sentinel(tmp_path, parse_rules([rule]))
    at(0.0)
    s.feed("trainer", {"train/approx_kl": 9.0}, now=0.0)
    s.tick(0.0)
    at(2.5)
    s.tick(2.5)
    assert cap["pause"] == 0  # allow_pause defaults False
    assert read_alerts(tmp_path)[0]["pause_requested"] is False
    cfg = SentinelConfig(enabled=True, eval_interval_secs=0.1,
                         allow_pause=True)
    s2, at2, cap2 = make_sentinel(tmp_path / "p2", parse_rules([rule]),
                                  cfg=cfg)
    at2(0.0)
    s2.feed("trainer", {"train/approx_kl": 9.0}, now=0.0)
    s2.tick(0.0)
    at2(2.5)
    s2.tick(2.5)
    assert cap2["pause"] == 1
    assert read_alerts(tmp_path / "p2")[0]["pause_requested"] is True


# ---------------------------------------------------------------------------
# disabled contract + aggregator hosting
# ---------------------------------------------------------------------------


def test_sentinel_owns_no_threads_or_sockets(tmp_path):
    """The engine is driven entirely by its host's existing loop: even
    ENABLED it spawns nothing — and through a full feed → fire →
    resolve cycle the process thread set is unchanged."""
    before = set(threading.enumerate())
    s, at, cap = make_sentinel(tmp_path, parse_rules([THRESH]))
    at(0.0)
    s.feed("trainer", {"train/approx_kl": 9.0}, now=0.0)
    s.tick(0.0)
    at(2.5)
    s.tick(2.5)
    at(5.0)
    s.feed("trainer", {"train/approx_kl": 0.0}, now=5.0)
    s.tick(5.0)
    s.close()
    assert set(threading.enumerate()) == before


def test_disabled_mode_leaves_aggregator_untouched(tmp_name_resolve,
                                                   tmp_path):
    """sentinel=None (the disabled path): no sentinel row on the merged
    scrape, no alerts families, no alerts.jsonl — bit-identical to a
    build without the sentinel."""
    agg = telemetry.TelemetryAggregator(
        "sentexp", "t0", jsonl_path=str(tmp_path / "telemetry.jsonl")
    )
    try:
        assert agg.sentinel is None
        body = agg.render_prometheus()
        assert "areal_alerts" not in body
        assert "sentinel" not in body
    finally:
        agg.close()
    assert not (tmp_path / "alerts.jsonl").exists()
    # ...and the master constructs no sentinel without the config flag
    from areal_tpu.system.master_worker import MasterWorkerConfig

    assert MasterWorkerConfig().sentinel.enabled is False


def test_aggregator_hosts_sentinel_end_to_end(tmp_name_resolve, tmp_path):
    """The real wiring: a worker's TelemetryPusher flushes a divergence
    gauge into the aggregator; the hosted sentinel trips the rule and the
    MERGED Prometheus endpoint carries areal_alerts_total{rule,severity}
    + areal_alert_active."""
    rules = parse_rules([
        {"id": "kl_hot", "metric": "train/approx_kl", "op": "gt",
         "value": 1.0, "for": 0.05, "cooldown": 60,
         "severity": "critical"},
    ])
    s = Sentinel(
        SentinelConfig(enabled=True, eval_interval_secs=0.01),
        "sentexp", "t0", rules=rules,
        alerts_path=str(tmp_path / "alerts.jsonl"),
        evidence_dir=str(tmp_path / "evidence"),
    )
    agg = telemetry.TelemetryAggregator(
        "sentexp", "t0", jsonl_path=str(tmp_path / "telemetry.jsonl"),
        sentinel=s,
    )
    reg = telemetry.TelemetryRegistry()
    pusher = telemetry.TelemetryPusher(
        reg, "sentexp", "t0", "trainer", 0, flush_interval_secs=60.0
    )
    try:
        # evidence bundles pin recent stitched traces via the REAL
        # stitcher the aggregator handed over
        assert s.stitcher is agg.stitcher
        reg.set_gauge("train/approx_kl{mfc=actor_train}", 4.0)
        assert pusher.flush()
        deadline = telemetry.time.monotonic() + 10
        while telemetry.time.monotonic() < deadline:
            if s.states()["kl_hot"]["state"] == "firing":
                break
            pusher.flush()
            telemetry.time.sleep(0.02)
        assert s.states()["kl_hot"]["state"] == "firing"
        body = agg.render_prometheus()
        assert ('areal_alerts_total{rule="kl_hot",severity="critical",'
                'worker_index="0",worker_kind="sentinel"} 1') in body
        assert 'areal_alert_active{rule="kl_hot"' in body
        recs = read_alerts(tmp_path)
        assert recs and recs[0]["rule"] == "kl_hot"
        assert recs[0].get("evidence_dir")
        # the evidence request armed the fleet-wide flight-dump flag
        raw = name_resolve.get(
            names.flight_dump_trigger("sentexp", "t0"))
        assert json.loads(raw)["dir"] == recs[0]["evidence_dir"]
    finally:
        pusher.close()
        agg.close()


# ---------------------------------------------------------------------------
# jax-free operator CLI (tools/perf_probe.py)
# ---------------------------------------------------------------------------


def test_perf_probe_alerts_and_silence_cli(tmp_path):
    """`alerts` filters a recorded stream and `silence` writes the
    name-resolve key — both exit before perf_probe ever imports jax."""
    import subprocess
    import sys as _sys

    stream = tmp_path / "alerts.jsonl"
    with open(stream, "w") as f:
        f.write(json.dumps({"event": "firing", "rule": "kl_blowup",
                            "severity": "critical", "metric":
                            "train/approx_kl", "value": 2.0,
                            "ts": 1000.0}) + "\n")
        f.write(json.dumps({"event": "firing", "rule": "reward_drift",
                            "severity": "warn", "metric":
                            "train/task_reward", "value": 0.1,
                            "ts": 1001.0}) + "\n")
    env = dict(os.environ,
               AREAL_NAME_RESOLVE_ROOT=str(tmp_path / "nr"),
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [_sys.executable, "tools/perf_probe.py", "alerts", str(stream),
         "critical"],
        capture_output=True, text=True, cwd="/root/repo", env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "kl_blowup" in out.stdout
    assert "reward_drift" not in out.stdout
    assert "(1/2 records" in out.stdout
    out = subprocess.run(
        [_sys.executable, "tools/perf_probe.py", "silence",
         "sentexp", "t0", "kl_blowup", "10m"],
        capture_output=True, text=True, cwd="/root/repo", env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "600s" in out.stdout
    repo = name_resolve.NfsNameRecordRepo(str(tmp_path / "nr"))
    rec = json.loads(repo.get(
        names.sentinel_silence("sentexp", "t0", "kl_blowup")))
    assert rec["duration_secs"] == 600.0


# ---------------------------------------------------------------------------
# training-dynamics export (the series the rules consume)
# ---------------------------------------------------------------------------


def test_actor_loss_emits_divergence_stats():
    import jax.numpy as jnp

    from areal_tpu.algorithms import ppo_functional as F

    lp = jnp.array([[-1.0, -2.0, -1.5, 0.0]])
    old = jnp.array([[-1.2, -1.8, -1.5, 0.0]])
    prox = jnp.array([[-1.1, -1.9, -1.5, 0.0]])
    adv = jnp.array([[0.5, -0.5, 1.0, 0.0]])
    mask = jnp.array([[True, True, True, False]])
    # default loss_scale ⇒ denom = masked token count ⇒ stats are means
    # (the PPO interface passes loss_scale=1 and re-normalizes by the
    # global action-token count instead)
    _, st = F.actor_loss(lp, old, adv, mask, proximal_logprobs=prox,
                         behav_imp_weight_cap=1.05)
    # k1 approx-KL of current vs BEHAVIOUR policy over masked tokens
    assert abs(float(st["approx_kl"]) - (-0.2 + 0.2 + 0.0) / 3) < 1e-6
    # sampled-token entropy estimate: −mean(logprob)
    assert abs(float(st["entropy"]) - 1.5) < 1e-6
    # exp(prox−behav) = e^0.1 ≈ 1.105 > cap at token 0 → 1/3 of the mass
    assert abs(float(st["behav_tail"]) - 1 / 3) < 1e-6
    # without a decoupled center the tail is identically zero
    _, st2 = F.actor_loss(lp, old, adv, mask)
    assert float(st2["behav_tail"]) == 0.0


def test_trainer_exports_train_gauges(tmp_name_resolve):
    from areal_tpu.system.trainer_worker import TrainerWorker

    telemetry.configure("sentexp", "t0", "trainer", 0,
                        TelemetryConfig(enabled=True), push=False)
    try:
        w = TrainerWorker.__new__(TrainerWorker)
        w._export_train_stats("actor_train", {
            "approx_kl": 0.02, "entropy": 3.1, "grad_norm": 1.7,
            "actor_loss": -0.4, "n_ppo_steps": 4.0,
            "bad": float("nan"),  # non-finite values never export
        })
        snap = telemetry.get().snapshot()
        g = snap["gauges"]
        assert g["train/approx_kl{mfc=actor_train}"] == 0.02
        assert g["train/entropy{mfc=actor_train}"] == 3.1
        assert g["train/actor_loss{mfc=actor_train}"] == -0.4
        assert "train/bad{mfc=actor_train}" not in g
        # divergence signatures additionally get a distribution view
        assert snap["hists"]["train/grad_norm_dist{mfc=actor_train}"][
            "count"] == 1
    finally:
        telemetry.shutdown()


# ---------------------------------------------------------------------------
# compile-aware liveness (base/compile_watch.py, ISSUE 20 drive-by)
# ---------------------------------------------------------------------------


def _stalled_rules():
    raw = next(r for r in DEFAULT_RULES if r["id"] == "trainer_stalled")
    return parse_rules([dict(raw)])


def test_trainer_stalled_fires_in_minutes_not_half_an_hour(tmp_path):
    """The drive-by regression: the old fix was a blanket 1800s grace
    that hid every genuinely-wedged trainer for half an hour. With the
    compile observatory the grace is 300s + compile-aware suppression —
    a wedged, NON-compiling trainer alerts in minutes."""
    s, at, cap = make_sentinel(tmp_path, _stalled_rules())
    at(0.0)
    s.feed("trainer", {}, {"train/optimizer_steps": 5.0}, now=0.0)
    s.tick(0.0)
    # wedged from t=0 on; well before the 300s window: quiet
    at(200.0)
    s.feed("trainer", {}, {"train/optimizer_steps": 5.0}, now=200.0)
    s.tick(200.0)
    assert s.states()["trainer_stalled"]["state"] == "ok"
    # past 300s of no progress, no compile in flight: fires — far
    # earlier than the old 1800s blanket grace would have allowed
    at(310.0)
    s.feed("trainer", {}, {"train/optimizer_steps": 5.0}, now=310.0)
    s.tick(310.0)
    assert s.states()["trainer_stalled"]["state"] == "firing"
    recs = read_alerts(tmp_path)
    assert recs and recs[0]["rule"] == "trainer_stalled"
    assert recs[0]["severity"] == "critical"


def test_trainer_stalled_suppressed_while_compile_inflight(tmp_path):
    """A trainer sitting inside a warmup XLA compile makes no optimizer
    steps but is NOT wedged: the live compile/inflight gauge explains
    the absence and the rule must stay quiet until the compile drains
    AND the silence persists."""
    s, at, cap = make_sentinel(tmp_path, _stalled_rules())
    at(0.0)
    s.feed("trainer", {"compile/inflight": 1.0},
           {"train/optimizer_steps": 5.0}, now=0.0)
    s.tick(0.0)
    # 20 minutes inside the compile, zero steps: suppressed throughout
    for t in (200.0, 400.0, 800.0, 1200.0):
        at(t)
        s.feed("trainer", {"compile/inflight": 1.0},
               {"train/optimizer_steps": 5.0}, now=t)
        s.tick(t)
        assert s.states()["trainer_stalled"]["state"] == "ok"
    assert read_alerts(tmp_path) == []
    # the compile drains but the trainer STAYS stuck: once the silence
    # outlives `for:` with no compile in flight, it fires
    at(1210.0)
    s.feed("trainer", {"compile/inflight": 0.0},
           {"train/optimizer_steps": 5.0}, now=1210.0)
    s.tick(1210.0)
    assert s.states()["trainer_stalled"]["state"] == "firing"
    # ...and a compiled-then-progressing trainer would have resolved:
    at(1220.0)
    s.feed("trainer", {"compile/inflight": 0.0},
           {"train/optimizer_steps": 6.0}, now=1220.0)
    s.tick(1220.0)
    assert s.states()["trainer_stalled"]["state"] == "ok"
    events = [r["event"] for r in read_alerts(tmp_path)]
    assert events == ["firing", "resolved"]


def test_name_resolve_inflight_flag_rolls_fire_back(
        tmp_path, tmp_name_resolve):
    """The telemetry-flush gap: a worker wedged INSIDE a compile stops
    flushing metrics (no compile/inflight gauge arrives) but its
    heartbeat thread still rewrites names.compile_inflight. A fresh flag
    rolls the fire back to pending exactly like a silence; a stale flag
    (dead worker's ghost) does not suppress."""
    s, at, cap = make_sentinel(tmp_path, _stalled_rules())
    key = names.compile_inflight("sentexp", "t0", "trainer/0")
    # wall clock starts at 1000.0 in make_sentinel
    name_resolve.add(key, json.dumps({"ts": 995.0}), replace=True,
                     delete_on_exit=False)
    at(310.0)
    s.tick(310.0)
    st = s.states()["trainer_stalled"]
    assert st["state"] == "pending" and st["fires"] == 0
    assert read_alerts(tmp_path) == []
    snap = s.registry.snapshot()
    assert snap["counters"][
        "sentinel/compile_suppressed{rule=trainer_stalled}"] == 1.0
    # the flag goes stale (heartbeat stopped rewriting it >60s ago):
    # a ghost must not suppress — the next tick fires for real
    at(500.0, 1500.0)
    s.tick(500.0)
    st = s.states()["trainer_stalled"]
    assert st["state"] == "firing" and st["fires"] == 1
    assert [r["rule"] for r in read_alerts(tmp_path)] == ["trainer_stalled"]
