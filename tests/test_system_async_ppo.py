"""FULL async-PPO e2e across processes — the AReaL architecture end to end:

  rollout worker → (staleness gate) gserver manager → generation server
       ↓ ZMQ push                                         ↑ weight fanout
  trainer (stream dataset) ← master DFG (ref/prox inf, actor train)
       └── publishes actor weights (disk path + model_version bump) ──┘

CPU analogue of the reference's async experiment e2e tests.
"""

import multiprocessing as mp

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import (
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    WeightUpdateHook,
    build_graph,
)
from areal_tpu.base import name_resolve
from areal_tpu.base.testing import MockTokenizer, make_mixed_jsonl

EXP, TRIAL = "asyncppo", "t0"
TINY = {"vocab_size": 258, "seed": 0}
# Telemetry rides along on the full-loop e2e (docs/observability.md):
# every worker kind pushes snapshots to the master's aggregator. Fast
# flushes so the few-step run lands several snapshots per worker, and a
# proportionally short stitch grace so traces appear on the LIVE merged
# scrape before the short run ends (tiny models can finish all three
# steps inside the default 5 s grace).
TEL = {"enabled": True, "flush_interval_secs": 0.3,
       "stitch_grace_secs": 0.8}


def _tel():
    from areal_tpu.api.train_config import TelemetryConfig

    return TelemetryConfig(**TEL)


def _sentinel(tmp_path):
    """Sentinel armed on the e2e (docs/observability.md §Alerting): the
    DEFAULT rule pack rides along — a healthy run must fire zero critical
    alerts from it — plus one INJECTED anomaly probe. The injection is a
    hair-trigger threshold on the first train step's gradient signature
    (the FaultInjector pattern applied to the rule pack: arm a condition
    no production config would use, observe the full fire → alert →
    evidence pipeline deterministically inside a 3-step run)."""
    from areal_tpu.api.train_config import SentinelConfig

    return SentinelConfig(
        enabled=True, eval_interval_secs=0.1,
        rules=[{
            "id": "e2e_divergence_probe", "metric": "train/grad_norm",
            "kind": "threshold", "op": "gt", "value": 1e-6,
            "for": 0.2, "cooldown": 600, "severity": "critical",
            "description": "e2e-injected divergence probe",
        }],
        alerts_path=str(tmp_path / "alerts.jsonl"),
        evidence_dir=str(tmp_path / "evidence"),
    )


def _goodput():
    from areal_tpu.api.train_config import GoodputConfig

    # Goodput ledger on (docs/observability.md §Goodput): every worker
    # classifies its wall clock, the trainer emits live MFU, the master
    # stitches fleet goodput. CPU has no entry in the peak table — the
    # override keeps train/mfu computable (the degrade-to-TFLOP/s path
    # is unit-tested in tests/test_goodput.py).
    return GoodputConfig(enabled=True, export_interval_secs=0.2,
                         peak_flops_override=1e12)


def _compile_watch():
    from areal_tpu.api.train_config import CompileWatchConfig

    # Compile & HBM observatory on (docs/observability.md §Compile &
    # memory): every chip-bearing worker traces its jit entry points and
    # samples HBM (degrading once on this CPU backend). The LOW storm
    # warmup lets the injected shape churn in the gen fleet cross the
    # stability threshold within the short run.
    return CompileWatchConfig(enabled=True, storm_warmup_calls=4,
                              mem_sample_interval_secs=0.2)


def _serving():
    from areal_tpu.api.train_config import ServingConfig

    # Serving engine on (docs/serving.md): the fleet carries rollout
    # traffic AND the interactive probe below through one server.
    return ServingConfig(enabled=True)


def _reward_cfg():
    from areal_tpu.api.train_config import RewardServiceConfig

    # Sandbox reward service on (docs/rewards.md): code rewards grade in
    # a SEPARATE reward-worker process, never in the rollout process.
    return RewardServiceConfig(enabled=True, n_workers=1)


def _reward_main(nr_root):
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    from areal_tpu.system.reward_worker import RewardWorker, RewardWorkerConfig

    RewardWorker(RewardWorkerConfig(
        experiment=EXP, trial=TRIAL, worker_index=0,
        reward=_reward_cfg(), telemetry=_tel(),
    )).run()


def _gen_fleet_main(nr_root, data_path, realloc_dir, flight_dir):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import asyncio
    import dataclasses as dc

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
    )
    from areal_tpu.system.rollout_worker import RolloutWorker, RolloutWorkerConfig

    # Flight recorder armed (docs/observability.md): killing this process
    # mid-run must leave flight_<worker>.jsonl evidence behind.
    tel = dc.replace(_tel(), flight_dir=flight_dir)

    async def main():
        kw = dict(TINY)
        seed = kw.pop("seed", 0)
        cfg = tiny_config(**kw)
        params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
        server = GenerationServer(
            GenerationServerConfig(
                experiment=EXP, trial=TRIAL, chunk_tokens=4,
                prompt_bucket=16, batch_window_ms=2, telemetry=tel,
                serving=_serving(), goodput=_goodput(),
                compile_watch=_compile_watch(),
            ),
            cfg, params,
        )
        await server.start()

        # Injected recompile storm (ISSUE 20 acceptance): a tiny watched
        # fn on THIS server's per-instance watch is held shape-stable
        # past storm_warmup_calls, then fed a never-before-seen shape
        # every cycle — compile/storm_events climbs at a rate far above
        # the recompile_storm rule's 0.02/s threshold, and the sentinel
        # on the master must fire within the rule's `for:` window.
        import threading
        import time as _time

        import numpy as _np

        def _storm_forever():
            probe = server.compile_watch.wrap("e2e/storm_probe",
                                              lambda x: x)
            stable = _np.zeros((4,), _np.float32)
            i = 0
            while True:
                for _ in range(4):  # re-stabilize past the warmup window
                    probe(stable)
                i += 1
                probe(_np.zeros((4 + i,), _np.float32))
                _time.sleep(0.05)

        threading.Thread(target=_storm_forever, daemon=True).start()
        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=1, train_batch_size=4,
            max_head_offpolicyness=4, realloc_dir=realloc_dir,
            weight_poll_secs=0.2, telemetry=tel,
        ))
        await mgr.start()
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
            group_size=2, chunk_tokens=4, max_concurrent=4,
            tokenizer=MockTokenizer(), max_rollouts=None,
            telemetry=tel, goodput=_goodput(),
            # Reward grading fans out to the reward worker fleet — this
            # process must never execute generated code itself.
            reward_service=_reward_cfg(),
        ))
        await worker.run_async()  # runs until killed

    asyncio.run(main())


def _trainer_main(nr_root, realloc_dir):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import areal_tpu.algorithms.ppo  # noqa: F401
    import areal_tpu.backend.jax_train  # noqa: F401
    from areal_tpu.algorithms.ppo import PPOHyperparameters
    from areal_tpu.api.model import FinetuneSpec, GenerationHyperparameters
    from areal_tpu.backend.jax_train import OptimizerConfig
    from areal_tpu.system.trainer_worker import (
        MFCRuntimeConfig,
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8),
        ppo_n_minibatches=2, group_size=2, kl_ctl=0.05,
        disable_value=True, group_adv_norm=False, adv_norm=True,
        use_decoupled_loss=True, behav_imp_weight_cap=10.0,
    )
    backend_args = {
        "compute_dtype": "float32", "length_bucket": 16, "rows_bucket": 2,
        "seqs_bucket": 4,
        "optimizer": OptimizerConfig(lr=1e-3, lr_scheduler_type="constant",
                                     warmup_steps_proportion=0.0),
    }
    cfg = TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL, handler="trainer",
        models={
            "actor": ModelRoleConfig(init={"tiny": TINY},
                                     backend_args=backend_args),
            "ref": ModelRoleConfig(init={"tiny": TINY},
                                   backend_args=backend_args, train=False),
        },
        mfcs={
            "ref_inf": MFCRuntimeConfig(interface="ref_logprob",
                                        model_name="ref"),
            "actor_inf": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
            "actor_train": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
        },
        batch_size=8,
        ft_spec=FinetuneSpec(1, 32, 8),
        tokenizer=MockTokenizer(),
        stream_dataset=True,
        realloc_dir=realloc_dir,
        telemetry=_tel(),
        goodput=_goodput(),
        compile_watch=_compile_watch(),
    )
    TrainerWorker(cfg).run()


def _build_async_dfg():
    mfcs = [
        MFCDef(
            name="ref_inf", model_name="ref",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ref_logprob"),
            input_keys=("packed_input_ids",),
            output_keys=("packed_ref_logprobs",),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_inf", model_name="actor",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids",),
            output_keys=("prox_logprobs",),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_train", model_name="actor",
            interface_type=MFCInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids", "prompt_mask", "packed_logprobs",
                        "rewards", "packed_ref_logprobs", "prox_logprobs",
                        "seq_no_eos_mask"),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
            post_hooks=[WeightUpdateHook(role="actor")],
        ),
    ]
    return build_graph(mfcs)


@pytest.mark.timeout(600)
def test_async_ppo_full_loop(tmp_path):
    nr_root = str(tmp_path / "nr")
    data_path = str(tmp_path / "math.jsonl")
    realloc_dir = str(tmp_path / "realloc")
    jsonl_path = str(tmp_path / "telemetry.jsonl")
    flight_dir = str(tmp_path / "flight")
    # Mixed math+code training data: code-RL rides the SAME async stack
    # (partial rollout + staleness gate + failover) as math — the
    # Agent/EnvironmentService contract is the extension point, not a
    # math-only special case (docs/rewards.md).
    make_mixed_jsonl(data_path, n_math=6, n_code=2)
    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(nr_root)

    ctx = mp.get_context("spawn")
    trainer = ctx.Process(target=_trainer_main,
                          args=(nr_root, realloc_dir), daemon=True)
    fleet = ctx.Process(target=_gen_fleet_main,
                        args=(nr_root, data_path, realloc_dir, flight_dir),
                        daemon=True)
    # The sixth worker kind: reward grading in its own sandbox process.
    # Started FIRST — it is jax-free and registers in well under the time
    # the fleet takes to come up, so the rollout worker's first grade
    # already finds the fleet.
    reward_proc = ctx.Process(target=_reward_main, args=(nr_root,),
                              daemon=True)
    reward_proc.start()
    trainer.start()
    fleet.start()

    # Mixed-traffic probe (docs/serving.md): while the master drives the
    # rollout workload, a separate thread fires INTERACTIVE requests
    # through the manager's class-aware scheduler at the same fleet —
    # one fleet concurrently serving both classes, end to end.
    import json as _json
    import threading
    import time
    import urllib.request

    interactive_results = []

    def _interactive_probe():
        from areal_tpu.base import names as _names

        def post(url, payload):
            req = urllib.request.Request(
                url, data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return _json.loads(r.read().decode())

        try:
            murl = name_resolve.wait(
                _names.gen_server_manager(EXP, TRIAL), timeout=120
            )
        except Exception as e:  # noqa: BLE001 — surfaced via the assert
            interactive_results.append({"error": str(e)})
            return
        for i in range(3):
            # Per-attempt isolation: urlopen raises HTTPError on any
            # non-2xx (a transient 429/503 while the fleet churns), and
            # one failed attempt must not kill the remaining ones.
            try:
                route = post(f"{murl}/schedule_request",
                             {"class": "interactive"})
                if not route.get("url"):
                    time.sleep(0.2)
                    continue
                out = post(f"{route['url']}/generate", {
                    "prompt_ids": [7, 8, 9, 10 + i],
                    "class": "interactive",
                    "rid": f"interactive{i}",
                    "gconfig": {"max_new_tokens": 4, "greedy": True},
                    "max_tokens": 4,
                })
                post(f"{murl}/release", {"lease_id": route.get("lease_id"),
                                         "url": route["url"]})
                interactive_results.append(out)
            except Exception as e:  # noqa: BLE001 — surfaced via the assert
                interactive_results.append({"error": str(e)})
                time.sleep(0.2)

    probe = threading.Thread(target=_interactive_probe, daemon=True)
    probe.start()

    # The aggregator's merged fleet endpoint closes with the master, so
    # the "real Prometheus scrape carries the stitched prompt→trained
    # histogram" assertion polls it WHILE the run executes and keeps the
    # first body where the derived trace metrics went nonzero.
    from areal_tpu.base import network

    agg_port = network.find_free_port()
    merged_scrape = []
    sentinel_scrape = []
    goodput_scrape = []
    compile_scrape = []
    storm_scrape = []

    def _compile_ready(body):
        # Compile-observatory acceptance in one snapshot: compile events
        # from >= 2 worker kinds, the fleet compile-seconds rollup, and
        # the HBM surface (real gauges on TPU; on this CPU backend the
        # one-time memory_stats degradation counter).
        kinds = set()
        hbm_ok = False
        for ln in body.splitlines():
            if ln.startswith("areal_compile_events_total{"):
                _, _, rest = ln.partition('worker_kind="')
                kinds.add(rest.partition('"')[0])
            elif ln.startswith((
                "areal_hbm_bytes_in_use{",
                "areal_hbm_memory_stats_unavailable_total{",
            )):
                hbm_ok = True
        return (len(kinds - {"fleet"}) >= 2 and hbm_ok
                and 'worker_kind="fleet"' in body)

    def _goodput_ready(body):
        # Goodput acceptance in one snapshot: ledger counters from >= 3
        # worker kinds, a nonzero stitched fleet-goodput gauge, and a
        # live trainer MFU > 0 (docs/observability.md §Goodput).
        kinds = set()
        fleet_ok = mfu_ok = False
        for ln in body.splitlines():
            if ln.startswith("areal_goodput_secs_total{"):
                _, _, rest = ln.partition('worker_kind="')
                kinds.add(rest.partition('"')[0])
            elif ln.startswith("areal_fleet_goodput{") \
                    and "side=" not in ln:
                fleet_ok = float(ln.rpartition(" ")[2]) > 0
            elif ln.startswith("areal_train_mfu"):
                mfu_ok = float(ln.rpartition(" ")[2]) > 0
        return fleet_ok and mfu_ok and len(kinds) >= 3

    def _merged_scrape_probe():
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline \
                and not (merged_scrape and sentinel_scrape
                         and goodput_scrape and compile_scrape
                         and storm_scrape):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{agg_port}/metrics", timeout=5
                ) as r:
                    body = r.read().decode()
                # Capture once the body shows BOTH the stitched trace
                # histogram AND the reward fleet's request counter — the
                # "live merged scrape" acceptance for tracing (PR 7) and
                # the reward service (docs/rewards.md) in one snapshot.
                trace_ok = any(
                    ln.startswith("areal_trace_e2e_secs_count")
                    and float(ln.rpartition(" ")[2]) > 0
                    for ln in body.splitlines()
                )
                if not merged_scrape and trace_ok \
                        and "areal_reward_requests_total" in body:
                    merged_scrape.append(body)
                # Separate capture for the sentinel acceptance: the fired
                # alert appears on the LIVE merged scrape as
                # areal_alerts_total{rule,severity} + areal_alert_active.
                # Keyed on the injected divergence rule specifically — the
                # recompile-storm probe fires its own alert much earlier,
                # so "any areal_alerts_total" would capture too soon.
                if not sentinel_scrape \
                        and 'rule="e2e_divergence_probe"' in body:
                    sentinel_scrape.append(body)
                # Third capture for the goodput-ledger acceptance.
                if not goodput_scrape and _goodput_ready(body):
                    goodput_scrape.append(body)
                # Fourth/fifth: the compile & HBM observatory, and the
                # injected recompile storm's alert on the LIVE scrape.
                if not compile_scrape and _compile_ready(body):
                    compile_scrape.append(body)
                if not storm_scrape \
                        and 'rule="recompile_storm"' in body:
                    storm_scrape.append(body)
            except Exception:  # noqa: BLE001 — aggregator not up yet
                pass
            time.sleep(0.3)

    scraper = threading.Thread(target=_merged_scrape_probe, daemon=True)
    scraper.start()
    try:
        from areal_tpu.system.master_worker import (
            ExperimentSaveEvalControl,
            MasterWorker,
            MasterWorkerConfig,
        )

        import dataclasses as dc

        master = MasterWorker(
            MasterWorkerConfig(
                experiment=EXP, trial=TRIAL, train_batch_size=8,
                exp_ctrl=ExperimentSaveEvalControl(
                    total_train_epochs=10**6, benchmark_steps=3,
                ),
                telemetry=dc.replace(_tel(), jsonl_path=jsonl_path,
                                     http_port=agg_port),
                # Training-health sentinel armed: default pack (must stay
                # quiet on this healthy run) + the injected probe.
                sentinel=_sentinel(tmp_path),
                # Fleet-goodput stitching in the same aggregator.
                goodput=_goodput(),
                # Arms the compile-aware sentinel pack (recompile_storm /
                # hbm_pressure / compile_stall) over the fleet's series.
                compile_watch=_compile_watch(),
            ),
            _build_async_dfg(),
        )
        from areal_tpu.base import names

        # Live pause/resume through the WorkerControlPanel (VERDICT #5 /
        # ISSUE 9 acceptance): once the RUNNING experiment has completed
        # a step, pause master+rollout+trainer (master FIRST — it must
        # park between steps before its data producers freeze), observe
        # the paused states and the frozen step counter, then resume and
        # let the run finish. The master is in-process (this thread runs
        # it), so the probe drives the panel from a side thread.
        pause_report = {}

        def _pause_resume_probe():
            from areal_tpu.system.worker_base import WorkerControlPanel

            panel = WorkerControlPanel(EXP, TRIAL, timeout=10.0)
            try:
                # Trigger on REGISTRATION, not on a step count: warm tiny
                # steps take <0.1s, so step-counter polling can miss the
                # whole run; a pause sent once all three control
                # endpoints exist queues on the master's REP socket and
                # lands at its next step boundary deterministically
                # (registration happens during setup, steps away from
                # benchmark completion).
                deadline = time.monotonic() + 240
                while time.monotonic() < deadline:
                    try:
                        if {"master", "rollout0", "trainer"} <= set(
                            panel.list_workers()
                        ):
                            break
                    except Exception:  # noqa: BLE001 — repo not ready
                        pass
                    time.sleep(0.05)
                else:
                    pause_report["error"] = "workers never registered"
                    return
                paused = {}
                for w in ("master", "rollout0", "trainer"):
                    for _ in range(12):  # busy-in-step commands time out
                        try:
                            paused[w] = panel.pause(w)["state"]
                            break
                        except TimeoutError:
                            pass
                pause_report["paused"] = paused
                s0 = master.step
                pause_report["rollout_state"] = \
                    panel.status("rollout0")["state"]
                # status is served from inside the PAUSED loop
                pause_report["master_state"] = \
                    panel.status("master")["state"]
                time.sleep(1.5)
                pause_report["frozen"] = (master.step == s0)
                pause_report["paused_at"] = s0
                for w in ("master", "rollout0", "trainer"):
                    try:
                        panel.resume(w)
                    except TimeoutError:
                        pass
            finally:
                panel.close()

        pauser = threading.Thread(target=_pause_resume_probe, daemon=True)
        pauser.start()

        result = master.run()
        assert result["steps"] == 3
        # --- pause/resume proven against the RUNNING experiment ---
        pauser.join(timeout=30)
        assert "error" not in pause_report, pause_report
        assert pause_report["paused"] == {
            "master": "paused", "rollout0": "paused", "trainer": "paused",
        }, pause_report
        assert pause_report["master_state"] == "paused"
        assert pause_report["rollout_state"] == "paused"
        assert pause_report["frozen"], pause_report
        # ...and the run ADVANCED past the frozen step after resume_all
        assert result["steps"] > pause_report["paused_at"]
        losses = [s["actor_train/actor_loss"] for s in result["stats"]]
        assert all(np.isfinite(x) for x in losses)
        # the weight-sync circle closed: version reached ≥ 2
        v = int(name_resolve.get(names.model_version(EXP, TRIAL, "actor")))
        assert v >= 2
        # --- unified telemetry landed (docs/observability.md) ---
        # the aggregated jsonl carries spans/metrics from ≥ 3 worker kinds
        import json as _json

        with open(jsonl_path) as f:
            recs = [_json.loads(ln) for ln in f if ln.strip()]
        kinds = {r["worker"].split(":")[0] for r in recs}
        assert len(kinds) >= 3, kinds
        assert any(r["spans"] for r in recs)
        # --- sandbox reward service proven end to end (docs/rewards.md):
        # the SIXTH worker kind pushed telemetry to the aggregator...
        assert "reward" in kinds, kinds
        # ...graded requests (incl. per-kind verdicts for BOTH task
        # kinds of the mixed fixture)...
        reward_counters: dict = {}
        rollout_counters: dict = {}
        for r in recs:
            wk = r["worker"].split(":")[0]
            tgt = reward_counters if wk == "reward" else (
                rollout_counters if wk == "rollout" else None
            )
            if tgt is not None:
                for k, v in (r.get("counters") or {}).items():
                    tgt[k] = tgt.get(k, 0) + v
        assert reward_counters.get("reward/requests", 0) > 0, reward_counters
        assert any(k.startswith("reward/verdicts{task=math")
                   for k in reward_counters), reward_counters
        assert any(k.startswith("reward/verdicts{task=code")
                   for k in reward_counters), reward_counters
        # ...while the ROLLOUT process executed ZERO generated code: every
        # code grade went over HTTP (remote counter), none ran locally.
        assert rollout_counters.get("reward_client/remote", 0) > 0, \
            rollout_counters
        assert not any("local_graded" in k for k in rollout_counters), \
            rollout_counters
        # the reward worker's own Prometheus endpoint serves the verdict
        # surface directly (the fleet-member contract)
        from areal_tpu.base import names as _nm

        (rw_url,) = name_resolve.get_subtree(
            _nm.reward_worker_root(EXP, TRIAL)
        )
        with urllib.request.urlopen(f"{rw_url}/metrics", timeout=10) as r:
            rprom = r.read().decode()
        assert "areal_reward_requests_total" in rprom
        assert 'task="code"' in rprom
        # the interactive probe must have finished BEFORE the scrapes
        # below — its histograms/counters are part of what we assert on.
        probe.join(timeout=60)
        # the generation server (fleet process still alive) serves valid
        # Prometheus text with weight-version + inflight gauges
        (gurl,) = name_resolve.get_subtree(
            names.gen_server_root(EXP, TRIAL)
        )
        with urllib.request.urlopen(f"{gurl}/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "# TYPE areal_genserver_weight_version gauge" in prom
        assert "areal_genserver_weight_version{" in prom
        assert "areal_genserver_inflight_requests{" in prom
        for ln in prom.splitlines():  # every sample line parses
            if ln and not ln.startswith("#"):
                float(ln.rpartition(" ")[2])
        murl = name_resolve.get(names.gen_server_manager(EXP, TRIAL))
        with urllib.request.urlopen(f"{murl}/metrics", timeout=10) as r:
            mprom = r.read().decode()
        assert "areal_gsmgr_healthy_servers 1" in mprom
        # --- mixed traffic proven end to end (docs/serving.md) ---
        ok_interactive = [
            r for r in interactive_results if r.get("output_ids")
        ]
        assert ok_interactive, interactive_results
        # per-class latency SLO histograms present in telemetry output:
        # the interactive probe AND the bulk rollout class both appear.
        assert "areal_serving_interactive_ttfc_secs_bucket" in prom
        assert "areal_serving_rollout_queue_wait_secs_bucket" in prom
        assert "areal_serving_compiled_shapes" in prom
        assert "areal_genserver_kv_states" in prom
        # the manager routed a class-aware interactive lease
        assert "areal_gsmgr_scheduled_interactive_total" in mprom
        # --- sample-lineage tracing landed (docs/observability.md) ---
        # traces.jsonl (default: next to telemetry.jsonl) holds stitched
        # end-to-end timelines whose spans come from ≥3 worker kinds:
        # the rollout worker that originated the trace, the generation
        # server that decoded it, and the trainer's terminal span.
        import os

        traces_path = str(tmp_path / "traces.jsonl")
        assert os.path.exists(traces_path), os.listdir(tmp_path)
        with open(traces_path) as f:
            traces = [_json.loads(ln) for ln in f if ln.strip()]
        assert traces
        kinds_per_trace = [
            {w.split(":")[0] for w in t["workers"]} for t in traces
        ]
        assert any(
            {"rollout", "generation_server", "trainer"} <= ks
            for ks in kinds_per_trace
        ), kinds_per_trace
        full = next(t for t, ks in zip(traces, kinds_per_trace)
                    if {"rollout", "generation_server", "trainer"} <= ks)
        assert full["e2e_secs"] > 0 and full["weight_version"] >= 0
        names_in_trace = {s["name"] for s in full["spans"]}
        assert "rollout/generate" in names_in_trace
        assert "genserver/queue_wait" in names_in_trace
        assert "trainer/train_sample" in names_in_trace
        assert set(full["stages"]) == {"generate", "queue", "gate",
                                       "train_wait", "train"}
        # the REAL merged Prometheus scrape (captured live) carries the
        # prompt→trained latency histogram with nonzero counts
        scraper.join(timeout=60)
        assert merged_scrape, \
            "merged /metrics never showed trace + reward metrics"
        assert "# TYPE areal_trace_e2e_secs histogram" in merged_scrape[0]
        assert "areal_trace_stage_train_wait_secs_bucket" in merged_scrape[0]
        # the LIVE merged scrape carries the reward fleet's counters
        # (acceptance: reward_requests_total on the merged endpoint)
        assert "areal_reward_requests_total" in merged_scrape[0]
        # --- training-health sentinel (docs/observability.md §Alerting) ---
        from areal_tpu.system.sentinel import DEFAULT_RULES

        alerts_path = tmp_path / "alerts.jsonl"
        assert alerts_path.exists(), os.listdir(tmp_path)
        with open(alerts_path) as f:
            alert_recs = [_json.loads(ln) for ln in f if ln.strip()]
        # (1) the DEFAULT pack stayed quiet: zero critical alerts on a
        # healthy run (conservative thresholds are the contract)
        default_ids = {r["id"] for r in DEFAULT_RULES}
        noisy = [r for r in alert_recs
                 if r.get("event") == "firing"
                 and r.get("severity") == "critical"
                 and r.get("rule") in default_ids]
        assert not noisy, noisy
        # (2) the injected anomaly fired its rule within the configured
        # `for:` window and landed in alerts.jsonl...
        probe = [r for r in alert_recs
                 if r.get("event") == "firing"
                 and r.get("rule") == "e2e_divergence_probe"]
        assert probe, alert_recs
        assert probe[0]["severity"] == "critical"
        assert probe[0]["for_secs"] == 0.2
        assert probe[0]["value"] > 1e-6
        # ...and on the LIVE merged Prometheus scrape
        assert sentinel_scrape, \
            "merged /metrics never showed areal_alerts_total"
        assert ('areal_alerts_total{rule="e2e_divergence_probe",'
                'severity="critical"') in sentinel_scrape[0]
        assert "areal_alert_active" in sentinel_scrape[0]
        # (2b) the INJECTED recompile storm (shape churn in the gen
        # fleet) fired the compile pack's rate rule within its `for:`
        # window, landed in alerts.jsonl with an evidence bundle, and
        # hit the live merged scrape.
        storm_recs = [r for r in alert_recs
                      if r.get("event") == "firing"
                      and r.get("rule") == "recompile_storm"]
        assert storm_recs, alert_recs
        assert storm_recs[0]["severity"] == "warn"
        assert storm_recs[0]["metric"] == "compile/storm_events"
        storm_ev = storm_recs[0].get("evidence_dir")
        assert storm_ev and os.path.isdir(storm_ev), storm_recs[0]
        assert storm_scrape, \
            "merged /metrics never showed the recompile_storm alert"
        assert 'areal_alerts_total{rule="recompile_storm"' \
            in storm_scrape[0]
        # --- goodput ledger (docs/observability.md §Goodput) ---
        # The LIVE merged scrape carried goodput_secs_total{state}
        # counters from >= 3 worker kinds, a nonzero stitched
        # fleet-goodput gauge, and train/mfu > 0 from the live trainer
        # (captured by _goodput_ready while the run executed).
        assert goodput_scrape, \
            "merged /metrics never satisfied the goodput acceptance"
        gbody = goodput_scrape[0]
        gkinds = set()
        gstates = set()
        for ln in gbody.splitlines():
            if ln.startswith("areal_goodput_secs_total{"):
                _, _, rest = ln.partition('worker_kind="')
                gkinds.add(rest.partition('"')[0])
                _, _, rest = ln.partition('state="')
                gstates.add(rest.partition('"')[0])
        assert {"trainer", "generation_server", "rollout"} <= gkinds, gkinds
        # the trainer/genserver wall partition surfaced both busy and
        # waiting states, not just one bucket
        assert "compute" in gstates and "idle" in gstates, gstates
        assert 'areal_fleet_goodput{side="trainer"' in gbody
        mfu_lines = [ln for ln in gbody.splitlines()
                     if ln.startswith("areal_train_mfu")]
        assert mfu_lines and float(mfu_lines[0].rpartition(" ")[2]) > 0
        assert "areal_train_achieved_tflops" in gbody
        # the generation server's analytic decode FLOP/s rode along
        assert "areal_genserver_decode_tflops" in gbody
        # --- compile & HBM observatory (docs/observability.md §Compile
        # & memory) --- the LIVE merged scrape carried compile events
        # from >= 2 chip-bearing worker kinds (trainer jit sites and the
        # generation server's prefill/decode wrappers), per-fn compile
        # seconds with the fleet rollup pseudo-worker, and the HBM
        # degradation counter (this CPU backend has no memory_stats —
        # the observatory must say so rather than export empty-chip
        # zeros).
        assert compile_scrape, \
            "merged /metrics never satisfied the compile acceptance"
        cbody = compile_scrape[0]
        ckinds = set()
        for ln in cbody.splitlines():
            if ln.startswith("areal_compile_events_total{"):
                _, _, rest = ln.partition('worker_kind="')
                ckinds.add(rest.partition('"')[0])
        assert {"trainer", "generation_server"} <= ckinds, ckinds
        assert ('areal_compile_secs_total{worker_index="0",'
                'worker_kind="fleet"}') in cbody
        assert 'fn="train/' in cbody  # trainer jit sites labeled per-fn
        assert "areal_compile_distinct_shapes" in cbody
        assert "areal_hbm_memory_stats_unavailable_total" in cbody
        # (3) evidence was captured while the anomaly was live: the
        # bundle holds the alert + triggering metric window + pinned
        # traces, and the fan-out flight-dump trigger pulls rings from
        # the still-running fleet/reward/trainer processes (each worker
        # acts within one telemetry flush interval).
        evidence_dir = probe[0].get("evidence_dir")
        assert evidence_dir and os.path.isdir(evidence_dir), probe[0]
        with open(os.path.join(evidence_dir, "alert.json")) as f:
            ev = _json.load(f)
        assert ev["rule"] == "e2e_divergence_probe"
        assert ev["metric_window"], ev
        assert any(p["value"] > 1e-6 for p in ev["metric_window"])
        assert any(k.startswith("trainer:0|train/grad_norm")
                   for k in ev["sources"])
        assert os.path.exists(os.path.join(evidence_dir, "traces.json"))

        def _flight_kinds():
            return {
                fn[len("flight_"):].rstrip("0123456789.jsonl") or fn
                for fn in os.listdir(evidence_dir)
                if fn.startswith("flight_")
            }

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(_flight_kinds()) < 2:
            time.sleep(0.3)
        assert len(_flight_kinds()) >= 2, os.listdir(evidence_dir)
        # --- flight recorder: killing a generation server mid-run leaves
        # crash evidence (SIGTERM hook dumps each worker's ring) ---
        assert fleet.is_alive()
        fleet.terminate()
        fleet.join(timeout=15)
        flight_files = sorted(os.listdir(str(tmp_path / "flight")))
        assert any(fn.startswith("flight_generation_server")
                   for fn in flight_files), flight_files
        with open(tmp_path / "flight" / flight_files[0]) as f:
            frecs = [_json.loads(ln) for ln in f if ln.strip()]
        assert frecs and frecs[-1]["kind"] == "dump"
        assert frecs[-1]["reason"] == "sigterm"
    finally:
        for p in (trainer, fleet, reward_proc):
            if p.is_alive():
                p.terminate()
        trainer.join(timeout=10)
        fleet.join(timeout=10)
        reward_proc.join(timeout=10)


# ------------------ device-transport weight bump (in-process e2e) ------


@pytest.mark.reshard
@pytest.mark.timeout(120)
def test_device_transport_weight_bump_e2e(tmp_name_resolve):
    """One weight bump over weight_sync.transport=device, end to end on
    CPU meshes: the trainer reshards its live params into the generation
    fleet's layout ON DEVICE and registers the publication; the manager's
    fanout auto-detects the device descriptor over disk; the server's
    swap stays digest-gated and atomic (a forged digest 500s with the old
    pair still live); and the trainer's goodput ledger attributes the
    publish to goodput/secs{state=comm} on the live scrape. In-process by
    construction — the device transport requires publisher and consumers
    to share one JAX runtime (docs/weight_sync.md §device); the
    cross-process fleets above keep using stream/disk."""
    import asyncio
    import json as _json
    import os

    import jax

    import areal_tpu.backend.jax_train  # noqa: F401 — registers "jax_train"
    from areal_tpu.api.model import FinetuneSpec, make_backend
    from areal_tpu.api.train_config import WeightSyncConfig
    from areal_tpu.base import names, telemetry
    from areal_tpu.base.retry import RetryPolicy
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.models.hf import flatten_pytree
    from areal_tpu.parallel import reshard as rsh
    from areal_tpu.system import goodput
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
        _ServerHealth,
    )
    from areal_tpu.system.trainer_worker import (
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    cfg = TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL,
        models={"actor": ModelRoleConfig(
            init={"tiny": {"vocab_size": 258}},
            backend_args={"compute_dtype": "float32", "length_bucket": 16},
        )},
        ft_spec=FinetuneSpec(1, 32, 8),
        realloc_dir="/nonexistent/never/written",
        weight_sync=WeightSyncConfig(transport="device"),
    )
    w = TrainerWorker(cfg)
    for role, rc in cfg.models.items():
        backend = make_backend(rc.backend, train=rc.train, **rc.backend_args)
        w.models[role] = backend.initialize(
            w._model_factory(role, rc), cfg.ft_spec
        )
    # Arm a real ledger on a private registry: _publish_weights_device
    # runs under state("comm"), and the flush below must surface that on
    # the scrape (the worker's own ledger is wired identically in setup()).
    reg = telemetry.TelemetryRegistry()
    w._ledger = goodput.GoodputLedger(reg, export_interval_secs=0.0)

    # Make the bump observable: perturb the trainer's weights away from
    # the generation server's init, and move the version off 0.
    engine = w.models["actor"].module
    engine.params = jax.tree.map(
        lambda x: x * 1.25 if x.dtype == np.float32 else x, engine.params
    )
    w.models["actor"].version.global_step = 3

    mcfg = tiny_config(vocab_size=258)  # same shapes as the tiny actor
    server = GenerationServer(
        GenerationServerConfig(experiment=EXP, trial=TRIAL, chunk_tokens=4,
                               prompt_bucket=16, batch_window_ms=2),
        mcfg, transformer.init_params(mcfg, jax.random.PRNGKey(1)),
    )

    async def main():
        import aiohttp

        url = await server.start()
        try:
            w.publish_weights("actor")
            # discovery: descriptor + version key, no checkpoint anywhere
            desc = _json.loads(name_resolve.get(
                names.weight_device(EXP, TRIAL, "actor")))
            assert desc["version"] == 3 and desc["digest"]
            assert int(name_resolve.get(
                names.model_version(EXP, TRIAL, "actor"))) == 3
            assert not os.path.exists("/nonexistent/never/written")

            mgr = GserverManager(GserverManagerConfig(
                experiment=EXP, trial=TRIAL, fanout_timeout_secs=5.0,
                fanout_retry=RetryPolicy(max_attempts=2,
                                         base_delay_secs=0.01),
            ))
            mgr.servers = [url]
            mgr._inflight = {url: 0}
            mgr.health = {url: _ServerHealth()}
            async with aiohttp.ClientSession() as sess:
                # transport auto-detection routes at the device
                # publication, not the (nonexistent) disk checkpoint
                payload = mgr._update_payload(3, "/unused/disk/path")
                assert payload.get("device") is True
                assert payload["digest"] == desc["digest"]
                acked = await mgr.fanout_weights(sess, 3,
                                                 "/unused/disk/path")
                assert acked == [url] and mgr.version == 3
                assert server.version == 3

                # gen-side params: bit-identical to the trainer's
                # compute-dtype tree
                want = flatten_pytree(w._compute_dtype_params("actor"),
                                      as_numpy=True)
                got = flatten_pytree(server.params, as_numpy=True)
                assert set(got) == set(want)
                for k in want:
                    np.testing.assert_array_equal(got[k], want[k], err_msg=k)

                # digest gate: a forged fanout 500s and the just-swapped
                # (params, version) pair stays live
                async with sess.post(f"{url}/update_weights", json={
                    "device": True, "role": "actor",
                    "version": 3, "digest": "deadbeef",
                }) as r:
                    assert r.status == 500
                async with sess.get(f"{url}/metrics.json") as r:
                    assert (await r.json())["version"] == 3
                after = flatten_pytree(server.params, as_numpy=True)
                for k in want:
                    np.testing.assert_array_equal(after[k], want[k])
        finally:
            await server.stop()

    try:
        asyncio.run(main())
    finally:
        rsh.clear_publication(EXP, TRIAL, "actor")

    # live scrape: the on-device publish accrued into the comm state
    w._ledger.flush()
    body = telemetry.render_prometheus(reg.snapshot(reset=False),
                                       labels={"kind": "trainer"})
    comm = [ln for ln in body.splitlines()
            if ln.startswith("areal_goodput_secs_total")
            and 'state="comm"' in ln]
    assert comm, body
    assert float(comm[0].rpartition(" ")[2]) > 0.0
