"""Chaos e2e for the durable trajectory spool (ISSUE 17 acceptance).

SIGKILL the trainer mid-run of the async-PPO loop with durability ON,
then relaunch the experiment from the recover checkpoint — the PR 9
supervision semantics for the stateful domain (supervisor.py: a trainer
death escalates as SupervisorEscalation, which ``recover_mode=auto``
converts into a whole-experiment relaunch; every worker here is spawned
the way the supervisor would respawn it). The run must complete with

 - every trajectory that was spooled-but-unacked at kill time REPLAYED
   from disk (``spool/replayed`` equals the on-disk unacked count at the
   phase boundary) instead of regenerated,
 - zero regeneration of consumed prompts (the ConsumedLog skiplist:
   no uid ever re-enters generation — pinned by duplicate-free consumed
   logs whose phase-1 prefix is preserved),
 - sample conservation at drain: on each worker,
   acked(watermark) + still-on-disk == appended(next_seqno-1) — nothing
   vanished without being trained or durably dropped,
 - the live merged Prometheus scrape carrying the spool gauges from both
   rollout workers.

Heavy (9 spawned processes across two phases) → slow-marked; the fast
per-component coverage lives in tests/test_sample_spool.py.
"""

import multiprocessing as mp
import os
import shutil
import signal
import time
import urllib.request

import pytest

from areal_tpu.base import name_resolve, names, recover
from areal_tpu.base.testing import MockTokenizer, make_math_jsonl

EXP, TRIAL = "durchaos", "t0"
TINY = {"vocab_size": 258, "seed": 0}
TEL = {"enabled": True, "flush_interval_secs": 0.3}
STEPS = 8  # total steps across both incarnations
BATCH = 8


def _tel():
    from areal_tpu.api.train_config import TelemetryConfig

    return TelemetryConfig(**TEL)


def _durability():
    from areal_tpu.api.train_config import DurabilityConfig

    # Fast resend so a lost ack recovers within the test budget; the
    # staleness gate is effectively open (replays across the restart must
    # train, not drop, for the conservation assertions to be exact).
    return DurabilityConfig(
        enabled=True, resend_timeout_secs=2.0,
        replay_staleness_limit=100000, drain_timeout_secs=1.0,
    )


def _gen_fleet_main(nr_root, realloc_dir):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import asyncio

    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
    )

    async def main():
        kw = dict(TINY)
        seed = kw.pop("seed", 0)
        cfg = tiny_config(**kw)
        params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
        server = GenerationServer(
            GenerationServerConfig(
                experiment=EXP, trial=TRIAL, chunk_tokens=4,
                prompt_bucket=16, batch_window_ms=2, telemetry=_tel(),
            ),
            cfg, params,
        )
        await server.start()
        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=1,
            # Tight staleness gate: the sample bank the workers can run
            # ahead during the first (compile-heavy) step stays below
            # STEPS*BATCH, so the phase-1 master CANNOT finish before the
            # kill lands — the SIGKILL is guaranteed to be mid-run.
            train_batch_size=BATCH, max_head_offpolicyness=2,
            realloc_dir=realloc_dir, weight_poll_secs=0.2, telemetry=_tel(),
        ))
        await mgr.start()
        while True:  # serves until the test terminates the process
            await asyncio.sleep(1.0)

    asyncio.run(main())


def _rollout_main(nr_root, data_path, recover_dir, idx):
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )

    RolloutWorker(RolloutWorkerConfig(
        experiment=EXP, trial=TRIAL, worker_index=idx, n_workers=2,
        dataset_path=data_path,
        gconfig=GenerationHyperparameters(max_new_tokens=8),
        group_size=2, chunk_tokens=4, max_concurrent=3,
        tokenizer=MockTokenizer(), max_rollouts=None, seed=1 + idx,
        recover_dir=recover_dir, telemetry=_tel(),
        durability=_durability(),
    )).run()


def _trainer_main(nr_root, realloc_dir):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import areal_tpu.algorithms.ppo  # noqa: F401
    import areal_tpu.backend.jax_train  # noqa: F401
    from areal_tpu.algorithms.ppo import PPOHyperparameters
    from areal_tpu.api.model import FinetuneSpec, GenerationHyperparameters
    from areal_tpu.backend.jax_train import OptimizerConfig
    from areal_tpu.system.trainer_worker import (
        MFCRuntimeConfig,
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8),
        ppo_n_minibatches=2, group_size=2, kl_ctl=0.05,
        disable_value=True, group_adv_norm=False, adv_norm=True,
        use_decoupled_loss=True, behav_imp_weight_cap=10.0,
    )
    backend_args = {
        "compute_dtype": "float32", "length_bucket": 16, "rows_bucket": 2,
        "seqs_bucket": 4,
        "optimizer": OptimizerConfig(lr=1e-3, lr_scheduler_type="constant",
                                     warmup_steps_proportion=0.0),
    }
    TrainerWorker(TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL, handler="trainer",
        models={
            "actor": ModelRoleConfig(init={"tiny": TINY},
                                     backend_args=backend_args),
            "ref": ModelRoleConfig(init={"tiny": TINY},
                                   backend_args=backend_args, train=False),
        },
        mfcs={
            "ref_inf": MFCRuntimeConfig(interface="ref_logprob",
                                        model_name="ref"),
            "actor_inf": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
            "actor_train": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
        },
        batch_size=BATCH,
        ft_spec=FinetuneSpec(1, 64, BATCH),
        tokenizer=MockTokenizer(),
        stream_dataset=True,
        realloc_dir=realloc_dir,
        telemetry=_tel(),
        durability=_durability(),
    )).run()


def _master_main(nr_root, recover_dir, jsonl_path, agg_port, do_recover):
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import dataclasses as dc

    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.api.dfg import (
        MFCDef,
        MFCInterfaceType,
        ModelInterfaceAbstraction,
        WeightUpdateHook,
        build_graph,
    )
    from areal_tpu.system.master_worker import (
        ExperimentSaveEvalControl,
        MasterWorker,
        MasterWorkerConfig,
    )

    mfcs = [
        MFCDef(
            name="ref_inf", model_name="ref",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ref_logprob"),
            input_keys=("packed_input_ids",),
            output_keys=("packed_ref_logprobs",),
            n_seqs=BATCH, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_inf", model_name="actor",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids",),
            output_keys=("prox_logprobs",),
            n_seqs=BATCH, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_train", model_name="actor",
            interface_type=MFCInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids", "prompt_mask", "packed_logprobs",
                        "rewards", "packed_ref_logprobs", "prox_logprobs",
                        "seq_no_eos_mask"),
            n_seqs=BATCH, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
            post_hooks=[WeightUpdateHook(role="actor")],
        ),
    ]
    MasterWorker(
        MasterWorkerConfig(
            experiment=EXP, trial=TRIAL, train_batch_size=BATCH,
            exp_ctrl=ExperimentSaveEvalControl(
                total_train_epochs=10**6, benchmark_steps=STEPS,
                ckpt_freq_steps=1,
            ),
            telemetry=dc.replace(_tel(), jsonl_path=jsonl_path,
                                 http_port=agg_port),
            durability=_durability(),
            recover_dir=recover_dir, recover=do_recover,
        ),
        build_graph(mfcs),
    ).run()


def _spool_snapshot(recover_dir, tmp_path, tag):
    """Per-worker (pending_count, watermark, next_seqno) read from a COPY
    of the spool directory — opening a live spool would run recovery
    (torn-tail truncation) against files a worker is still writing."""
    from areal_tpu.system.sample_spool import SampleSpool

    out = {}
    for w in (0, 1):
        src = os.path.join(recover_dir, f"spool_{w}")
        if not os.path.isdir(src):
            out[w] = (0, 0, 1)
            continue
        dst = str(tmp_path / f"snap_{tag}_{w}")
        shutil.rmtree(dst, ignore_errors=True)
        shutil.copytree(src, dst)
        sp = SampleSpool(dst)
        st = sp.stats()
        out[w] = (st.depth, st.acked_watermark, st.next_seqno)
        sp.close()
    return out


def _consumed_uids(recover_dir, w):
    path = os.path.join(recover_dir, f"rollout_consumed_{w}.log")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip()]


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.durability
@pytest.mark.timeout(900)
def test_trainer_sigkill_replays_spool_no_sample_loss(tmp_path):
    nr_root = str(tmp_path / "nr")
    data_path = str(tmp_path / "math.jsonl")
    realloc_dir = str(tmp_path / "realloc")
    recover_dir = str(tmp_path / "recover")
    jsonl_path = str(tmp_path / "telemetry.jsonl")
    make_math_jsonl(data_path, n=16)
    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(nr_root)
    os.makedirs(recover_dir, exist_ok=True)

    from areal_tpu.base import network

    agg_port = network.find_free_port()
    ctx = mp.get_context("spawn")

    def spawn(target, *args):
        p = ctx.Process(target=target, args=args, daemon=True)
        p.start()
        return p

    # ---------------- phase 1: run, then SIGKILL the trainer ----------
    trainer = spawn(_trainer_main, nr_root, realloc_dir)
    fleet = spawn(_gen_fleet_main, nr_root, realloc_dir)
    r0 = spawn(_rollout_main, nr_root, data_path, recover_dir, 0)
    r1 = spawn(_rollout_main, nr_root, data_path, recover_dir, 1)
    master = spawn(_master_main, nr_root, recover_dir, jsonl_path,
                   agg_port, False)

    # Live merged-scrape probe: the spool gauges must appear for BOTH
    # rollout workers on the master's aggregated /metrics while phase 1
    # runs (the acceptance's observability leg).
    import threading

    spool_gauge_workers = set()

    def _scrape():
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline \
                and len(spool_gauge_workers) < 2:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{agg_port}/metrics", timeout=5
                ) as r:
                    body = r.read().decode()
                for ln in body.splitlines():
                    if ln.startswith("areal_spool_depth{"):
                        _, _, rest = ln.partition('worker_index="')
                        spool_gauge_workers.add(rest.partition('"')[0])
            except Exception:  # noqa: BLE001 — aggregator not up yet
                pass
            time.sleep(0.3)

    scraper = threading.Thread(target=_scrape, daemon=True)
    scraper.start()

    try:
        # Wait for the first committed step (recover ckpt exists) — the
        # kill must land MID-run, after real training happened.
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            info = recover.load(recover_dir)
            if info is not None and info.last_step_info.global_step >= 1:
                break
            assert master.is_alive(), "master died before step 1"
            time.sleep(0.05)
        else:
            pytest.fail("no recover checkpoint within budget")

        assert trainer.is_alive()
        os.kill(trainer.pid, signal.SIGKILL)
        trainer.join(timeout=15)

        # With the trainer dead nothing acks: the workers keep rolling
        # out and every accepted trajectory accumulates durably in the
        # spool. Wait until unacked records are on disk.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            snap = _spool_snapshot(recover_dir, tmp_path, "probe")
            if sum(d for d, _, _ in snap.values()) > 0:
                break
            time.sleep(0.5)
        else:
            pytest.fail("no unacked spool records accumulated after kill")
    finally:
        # Stateful-domain death ⇒ whole-experiment relaunch (supervisor
        # escalation semantics): tear down every phase-1 process.
        for p in (master, fleet, r0, r1, trainer):
            if p.is_alive():
                p.terminate()
        for p in (master, fleet, r0, r1, trainer):
            p.join(timeout=20)

    scraper.join(timeout=5)

    # Exact phase-boundary truth, read after every phase-1 process died:
    # these records MUST reach the trainer by replay, not regeneration.
    snap1 = _spool_snapshot(recover_dir, tmp_path, "p1")
    n_unacked = sum(d for d, _, _ in snap1.values())
    assert n_unacked > 0
    consumed_p1 = {w: _consumed_uids(recover_dir, w) for w in (0, 1)}

    # ---------------- phase 2: relaunch from the recover ckpt ---------
    # Exactly what run_experiment's relaunch does (apps/launcher.py):
    # clear the dead incarnation's name_resolve subtree so nobody — the
    # workers' telemetry pushers included, which latch their aggregator
    # address on first resolve — can discover a ghost endpoint. All
    # durable state (recover ckpts, spools, consumed logs) is on disk.
    name_resolve.clear_subtree(names.trial_root(EXP, TRIAL))
    trainer = spawn(_trainer_main, nr_root, realloc_dir)
    fleet = spawn(_gen_fleet_main, nr_root, realloc_dir)
    r0 = spawn(_rollout_main, nr_root, data_path, recover_dir, 0)
    r1 = spawn(_rollout_main, nr_root, data_path, recover_dir, 1)
    master = spawn(_master_main, nr_root, recover_dir, jsonl_path,
                   agg_port, True)
    try:
        master.join(timeout=600)
        assert master.exitcode == 0, f"master exit {master.exitcode}"
        info = recover.load(recover_dir)
        assert info is not None \
            and info.last_step_info.global_step == STEPS

        # Clean worker exit: the control-panel exit request drains the
        # spool senders (unacked leftovers stay durably on disk).
        from areal_tpu.system.worker_base import WorkerControlPanel

        panel = WorkerControlPanel(EXP, TRIAL, timeout=10.0)
        try:
            for w in ("rollout0", "rollout1"):
                for _ in range(12):
                    try:
                        panel.exit(w)
                        break
                    except TimeoutError:
                        pass
        finally:
            panel.close()
        r0.join(timeout=60)
        r1.join(timeout=60)
        assert r0.exitcode == 0 and r1.exitcode == 0
    finally:
        for p in (master, fleet, r0, r1, trainer):
            if p.is_alive():
                p.terminate()
            p.join(timeout=20)

    # ---------------- acceptance ----------------
    # (1) The run COMPLETED across the kill: all STEPS steps committed.
    #     (asserted above)
    # (2) The merged scrape carried the spool gauges from ≥2 workers.
    assert spool_gauge_workers >= {"0", "1"}, spool_gauge_workers
    # (3) Crash replay, not regeneration: every record unacked at the
    #     phase boundary was replayed from disk...
    import json

    # Counters in telemetry.jsonl are CUMULATIVE per-process snapshots
    # (one record per flush), so take the per-worker maximum, then sum
    # across workers. Phase-1 incarnations report replayed=0, so the max
    # per worker is exactly its phase-2 final value.
    peak = {}  # (worker, counter) -> max cumulative value seen
    with open(jsonl_path) as f:
        for ln in f:
            if not ln.strip():
                continue
            rec = json.loads(ln)
            src = rec.get("worker")
            for k, v in (rec.get("counters") or {}).items():
                key = (src, k)
                peak[key] = max(peak.get(key, 0.0), v)

    def _total(counter):
        return sum(v for (_, k), v in peak.items() if k == counter)

    replayed = _total("spool/replayed")
    stale_dropped = _total("spool/replay_stale_dropped")
    acked_tel = _total("spool/acked")
    assert replayed == n_unacked, (replayed, n_unacked)
    # ...and with the gate open, every replay TRAINED (none dropped) and
    # acks flowed back.
    assert stale_dropped == 0
    assert acked_tel > 0
    # (4) Zero regenerated: consumed prompts never re-entered generation.
    #     Each consumed log is duplicate-free and phase 2 strictly
    #     appended to the phase-1 prefix.
    for w in (0, 1):
        uids = _consumed_uids(recover_dir, w)
        assert len(uids) == len(set(uids)), f"worker {w} re-consumed a uid"
        assert uids[:len(consumed_p1[w])] == consumed_p1[w]
    # (5) Sample conservation at drain, from disk truth: on each worker
    #     appended == acked (trained or durably dropped) + still-on-disk;
    #     nothing vanished. The acked side only ever advances.
    snap2 = _spool_snapshot(recover_dir, tmp_path, "p2")
    for w in (0, 1):
        depth, watermark, next_seqno = snap2[w]
        appended = next_seqno - 1
        assert appended == watermark + depth, snap2[w]
        assert watermark >= snap1[w][1]
    # The settled count covers at least one full training run's samples
    # minus what is still spooled awaiting a future incarnation.
    assert sum(wm for _, wm, _ in snap2.values()) > 0
