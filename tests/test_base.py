"""Tests for areal_tpu.base (datapack, timeutil, name_resolve, stats_tracker,
recover). Mirrors the reference's tests/distributed/test_name_resolve.py and
unit tests around datapack/freq control."""

import time

import numpy as np
import pytest

from areal_tpu.base import datapack, name_resolve, recover, stats_tracker, timeutil


class TestDatapack:
    def test_contiguous_balanced_partition(self):
        sizes = [5, 1, 1, 1, 5, 1, 1, 1, 5]
        parts = datapack.partition_contiguous_balanced(sizes, 3)
        assert len(parts) == 3
        flat = [i for p in parts for i in p]
        assert flat == list(range(len(sizes)))
        maxsum = max(sum(sizes[i] for i in p) for p in parts)
        assert maxsum <= 8

    def test_partition_exact_groups(self):
        for n, k in [(8, 8), (10, 3), (100, 7), (5, 1)]:
            sizes = np.random.randint(1, 100, size=n)
            parts = datapack.partition_contiguous_balanced(sizes, k)
            assert len(parts) == k
            assert all(len(p) > 0 for p in parts)
            assert [i for p in parts for i in p] == list(range(n))

    def test_ffd(self):
        sizes = [9, 8, 2, 2, 5, 4]
        groups = datapack.ffd_allocate(sizes, capacity=10)
        for g in groups:
            if len(g) > 1:
                assert sum(sizes[i] for i in g) <= 10
        assert sorted(i for g in groups for i in g) == list(range(len(sizes)))

    def test_ffd_oversize_item(self):
        groups = datapack.ffd_allocate([100, 1], capacity=10)
        assert [g for g in groups if 0 in g][0] == [0]

    def test_balanced_groups(self):
        sizes = [10, 1, 1, 1, 1, 10]
        groups = datapack.balanced_groups(sizes, 2)
        sums = [sum(sizes[i] for i in g) for g in groups]
        assert abs(sums[0] - sums[1]) <= 2


class TestFreqCtl:
    def test_step_freq(self):
        ctl = timeutil.FrequencyControl(freq_step=3)
        fires = [ctl.check(0, s) for s in range(1, 10)]
        assert fires == [False, False, True, False, False, True, False, False, True]

    def test_epoch_freq(self):
        ctl = timeutil.FrequencyControl(freq_epoch=2)
        assert not ctl.check(1, 10)
        assert ctl.check(2, 20)
        assert not ctl.check(3, 30)
        assert ctl.check(4, 40)

    def test_state_roundtrip(self):
        ctl = timeutil.FrequencyControl(freq_step=5)
        ctl.check(0, 3)
        state = ctl.state_dict()
        ctl2 = timeutil.FrequencyControl(freq_step=5)
        ctl2.load_state_dict(state)
        assert ctl2.check(0, 5) == ctl.check(0, 5)


class TestNameResolve:
    @pytest.mark.parametrize("repo_cls", ["memory", "nfs"])
    def test_basic(self, repo_cls, tmp_path):
        if repo_cls == "memory":
            repo = name_resolve.MemoryNameRecordRepo()
        else:
            repo = name_resolve.NfsNameRecordRepo(str(tmp_path))
        repo.add("a/b/c", "v1")
        assert repo.get("a/b/c") == "v1"
        with pytest.raises(name_resolve.NameEntryExistsError):
            repo.add("a/b/c", "v2")
        repo.add("a/b/c", "v2", replace=True)
        assert repo.get("a/b/c") == "v2"
        repo.add("a/b/d", "v3")
        assert repo.find_subtree("a/b") == ["a/b/c", "a/b/d"]
        assert sorted(repo.get_subtree("a/b")) == ["v2", "v3"]
        repo.delete("a/b/c")
        with pytest.raises(name_resolve.NameEntryNotFoundError):
            repo.get("a/b/c")
        repo.clear_subtree("a")
        assert repo.find_subtree("a") == []

    def test_wait(self, tmp_path):
        repo = name_resolve.NfsNameRecordRepo(str(tmp_path))
        import threading

        def _add():
            time.sleep(0.2)
            repo.add("x/y", "late")

        threading.Thread(target=_add).start()
        assert repo.wait("x/y", timeout=5) == "late"
        with pytest.raises(TimeoutError):
            repo.wait("x/never", timeout=0.2)

    def test_subentry(self, tmp_path):
        repo = name_resolve.NfsNameRecordRepo(str(tmp_path))
        k1 = repo.add_subentry("servers", "url1")
        k2 = repo.add_subentry("servers", "url2")
        assert k1 != k2
        assert sorted(repo.get_subtree("servers")) == ["url1", "url2"]


class TestStatsTracker:
    def test_avg_with_denominator(self):
        t = stats_tracker.StatsTracker()
        mask = np.array([1, 1, 0, 0], dtype=bool)
        vals = np.array([1.0, 3.0, 100.0, 100.0])
        t.denominator(m=mask)
        t.stat("m", loss=vals)
        out = t.export()
        assert out["loss"] == pytest.approx(2.0)

    def test_scoped(self):
        t = stats_tracker.StatsTracker()
        with t.scope("ppo"):
            with t.scope("actor"):
                t.scalar(lr=0.1)
        out = t.export()
        assert out["ppo/actor/lr"] == pytest.approx(0.1)

    def test_accumulates_across_calls(self):
        t = stats_tracker.StatsTracker()
        t.denominator(m=np.array([True, True]))
        t.stat("m", x=np.array([1.0, 1.0]))
        t.denominator(m=np.array([True, True]))
        t.stat("m", x=np.array([3.0, 3.0]))
        # Note second denominator replaces under same key; entries keep own ref
        out = t.export()
        assert out["x"] == pytest.approx(2.0)

    def test_min_max(self):
        t = stats_tracker.StatsTracker()
        t.denominator(m=np.array([True, True, False]))
        t.stat("m", stats_tracker.ReduceType.MAX, v=np.array([1.0, 5.0, 99.0]))
        out = t.export()
        assert out["v"] == pytest.approx(5.0)

    def test_moving_avg(self):
        t = stats_tracker.StatsTracker()
        t.moving_avg(decay=0.5, tput=100.0)
        t.moving_avg(decay=0.5, tput=200.0)
        out = t.export()
        assert out["tput"] == pytest.approx(150.0)


class TestRecover:
    def test_roundtrip(self, tmp_path):
        info = recover.RecoverInfo(
            recover_start=recover.StepInfo(1, 2, 3),
            last_step_info=recover.StepInfo(1, 1, 2),
            hash_vals_to_ignore=[123, 456],
        )
        recover.dump(str(tmp_path), info)
        loaded = recover.load(str(tmp_path))
        assert loaded.recover_start == recover.StepInfo(1, 2, 3)
        assert loaded.hash_vals_to_ignore == [123, 456]

    def test_discover_ckpt(self, tmp_path):
        for e, es, g in [(1, 1, 1), (1, 2, 2), (2, 1, 3)]:
            d = tmp_path / recover.ckpt_dirname(e, es, g)
            d.mkdir()
            recover.mark_ckpt_complete(str(d))
        (tmp_path / "garbage").mkdir()
        best = recover.discover_ckpt(str(tmp_path))
        assert best.endswith("epoch2epochstep1globalstep3")

    def test_discover_ckpt_skips_incomplete(self, tmp_path):
        """A crash mid-save leaves a dir without the .complete sentinel —
        discovery must fall back to the previous complete checkpoint."""
        ok = tmp_path / recover.ckpt_dirname(1, 1, 1)
        ok.mkdir()
        recover.mark_ckpt_complete(str(ok))
        half = tmp_path / recover.ckpt_dirname(1, 2, 2)  # newer, no marker
        half.mkdir()
        best = recover.discover_ckpt(str(tmp_path))
        assert best.endswith("epoch1epochstep1globalstep1")
        assert recover.ckpt_is_complete(str(ok))
        assert not recover.ckpt_is_complete(str(half))

    def test_load_missing(self, tmp_path):
        assert recover.load(str(tmp_path / "nope")) is None


class TestFFDMinGroups:
    def test_min_groups_splits_multi_item_bins(self):
        groups = datapack.ffd_allocate([10, 3, 3], capacity=10, min_groups=3)
        assert len(groups) == 3
