"""Native host ops (csrc/interval_ops.cpp via ops/native.py): parity with
the pure-NumPy/Python paths they accelerate — the reference's kernel-parity
test strategy (tests/cpp_extensions/test_interval_ops.py) applied to our
host-side interval workload."""

import numpy as np
import pytest

from areal_tpu.base import datapack
from areal_tpu.models import packing
from areal_tpu.ops import native


def _python_ffd(sizes, capacity):
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    bins, loads = [], []
    for i in order:
        s = int(sizes[i])
        for b in range(len(bins)):
            if loads[b] + s <= capacity:
                bins[b].append(i)
                loads[b] += s
                break
        else:
            bins.append([i])
            loads.append(s)
    return bins


needs_native = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain on this host"
)


@needs_native
def test_scatter_gather_parity():
    rng = np.random.default_rng(0)
    for dtype in (np.int32, np.float32, np.float64):
        lens = rng.integers(1, 40, 50)
        total = int(lens.sum())
        packed = rng.integers(0, 1000, total).astype(dtype)
        # random non-overlapping placements in a [8, 512] grid
        rows, cols, offs = [], [], []
        col_cursor = {r: 0 for r in range(8)}
        off = 0
        for ln in lens:
            r = int(rng.integers(0, 8))
            while col_cursor[r] + ln > 512:
                r = (r + 1) % 8
            rows.append(r)
            cols.append(col_cursor[r])
            col_cursor[r] += int(ln)
            offs.append(off)
            off += int(ln)
        out_native = np.zeros((8, 512), dtype)
        assert native.scatter_intervals(
            packed, out_native, rows, cols, lens, offs
        )
        out_ref = np.zeros((8, 512), dtype)
        for r, c, ln, o in zip(rows, cols, lens, offs):
            out_ref[r, c:c + ln] = packed[o:o + ln]
        np.testing.assert_array_equal(out_native, out_ref)

        back = np.zeros(total, dtype)
        assert native.gather_intervals(
            out_native, back, rows, cols, lens, offs
        )
        np.testing.assert_array_equal(back, packed)


@needs_native
def test_ffd_assign_matches_python():
    rng = np.random.default_rng(1)
    for _ in range(10):
        sizes = rng.integers(1, 700, int(rng.integers(64, 400))).tolist()
        cap = int(rng.integers(700, 2000))
        bin_of = native.ffd_assign(sizes, cap)
        ref = _python_ffd(sizes, cap)
        got = [[] for _ in range(int(bin_of.max()) + 1)]
        order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
        for i in order:
            got[int(bin_of[i])].append(i)
        assert got == ref


@needs_native
def test_scatter_gather_bounds_checked():
    """Out-of-range intervals must raise BEFORE the C memcpy runs (the
    NumPy fallback would raise on the same inputs; the raw pointer loop
    would corrupt memory instead)."""
    packed = np.arange(16, dtype=np.int32)
    out = np.zeros((2, 8), np.int32)
    ok = dict(rows=[0], cols=[0], lens=[8], offs=[0])
    assert native.scatter_intervals(packed, out, **ok)
    for bad in (
        dict(ok, rows=[2]),          # row ≥ R
        dict(ok, rows=[-1]),         # negative row
        dict(ok, cols=[4]),          # col+len > L
        dict(ok, lens=[-2]),         # negative length
        dict(ok, offs=[12]),         # off+len > packed size
    ):
        with pytest.raises(ValueError):
            native.scatter_intervals(packed, out, **{
                k: np.asarray(v) for k, v in bad.items()
            })
        with pytest.raises(ValueError):
            native.gather_intervals(out, packed.copy(), **{
                k: np.asarray(v) for k, v in bad.items()
            })


def test_batch_from_packed_uses_native_and_matches():
    """The packer's grid scatter must produce identical grids whether or
    not the native path engaged (it silently falls back without g++)."""
    rng = np.random.default_rng(2)
    seqlens = rng.integers(1, 30, 40).tolist()
    layout = packing.plan_packing(seqlens, length_bucket=16, rows_multiple=2)
    packed = rng.integers(0, 100, sum(seqlens)).astype(np.int32)
    grid = packing.batch_from_packed(packed, layout)
    # reference loop
    ref = np.zeros(layout.shape, np.int32)
    off = 0
    for (row, col), n in zip(layout.placements, layout.seqlens):
        ref[row, col:col + n] = packed[off:off + n]
        off += n
    np.testing.assert_array_equal(grid, ref)
    # round trip
    np.testing.assert_array_equal(
        packing.packed_from_batch(grid, layout), packed
    )


def test_ffd_allocate_native_path_consistency():
    rng = np.random.default_rng(3)
    sizes = rng.integers(1, 500, 200).tolist()
    bins = datapack.ffd_allocate(sizes, 1024)
    # invariants: partition of all indices, loads within capacity
    flat = sorted(i for b in bins for i in b)
    assert flat == list(range(200))
    for b in bins:
        assert sum(sizes[i] for i in b) <= 1024 or len(b) == 1
    # equality with the pure-python reference result
    ref_bins = _python_ffd(sizes, 1024)
    for b in ref_bins:
        b.sort()
    ref_bins.sort(key=lambda g: g[0])
    assert bins == ref_bins
