"""System-fabric e2e: master + trainer in separate processes over ZMQ,
running the full sync-PPO DFG (gen → rew/ref/prox inf → actor train) with
weight publishing. The CPU analogue of the reference's
tests/experiments/test_math_ppo.py (run_test_exp)."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import (
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    WeightUpdateHook,
    build_graph,
)
from areal_tpu.api.model import FinetuneSpec
from areal_tpu.base import name_resolve, names
from areal_tpu.base.testing import MockTokenizer, make_math_jsonl

EXP, TRIAL = "systest", "t0"


def _trainer_main(nr_root, data_path, realloc_dir):
    # runs in a spawned process: force CPU (the image's sitecustomize
    # registers the TPU plugin regardless of JAX_PLATFORMS), then serve
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import areal_tpu.algorithms.ppo  # noqa: F401 — register interfaces
    import areal_tpu.algorithms.reward  # noqa: F401
    import areal_tpu.backend.jax_train  # noqa: F401 — register backends
    import areal_tpu.datasets.jsonl  # noqa: F401 — register datasets
    from areal_tpu.system.trainer_worker import (
        MFCRuntimeConfig,
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    hp_args = {
        "ppo_n_minibatches": 2, "group_size": 2, "kl_ctl": 0.05,
        "disable_value": True, "group_adv_norm": True, "adv_norm": False,
        "use_decoupled_loss": True,
        "gen": {"max_new_tokens": 8},
    }
    # PPOActorInterface accepts hp or flat kwargs; gen passed as dict needs
    # conversion — interface_args carry a ready PPOHyperparameters.
    from areal_tpu.algorithms.ppo import PPOHyperparameters
    from areal_tpu.api.model import GenerationHyperparameters

    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8),
        ppo_n_minibatches=2, group_size=2, kl_ctl=0.05,
        disable_value=True, group_adv_norm=True, adv_norm=False,
        use_decoupled_loss=True,
    )
    backend_args = {
        "compute_dtype": "float32", "length_bucket": 16, "rows_bucket": 2,
        "seqs_bucket": 4,
        "optimizer": {"lr": 1e-3, "lr_scheduler_type": "constant",
                      "warmup_steps_proportion": 0.0},
    }
    from areal_tpu.backend.jax_train import OptimizerConfig

    backend_args["optimizer"] = OptimizerConfig(**backend_args["optimizer"])
    cfg = TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL, handler="trainer",
        models={
            "actor": ModelRoleConfig(
                init={"tiny": {"vocab_size": 258, "seed": 0}},
                backend_args=backend_args),
            "ref": ModelRoleConfig(
                init={"tiny": {"vocab_size": 258, "seed": 0}},
                backend_args=backend_args, train=False),
            "rw": ModelRoleConfig(init={"null": True}, backend="null"),
        },
        mfcs={
            "actor_gen": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
            "rew_inf": MFCRuntimeConfig(
                interface="rw_math_code",
                interface_args={"dataset_path": data_path, "group_size": 2},
                model_name="rw"),
            "ref_inf": MFCRuntimeConfig(
                interface="ref_logprob", model_name="ref"),
            "actor_inf": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
            "actor_train": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
        },
        dataset="math_code_prompt",
        dataset_args={"dataset_path": data_path},
        batch_size=4,
        ft_spec=FinetuneSpec(1, 8, 4),
        tokenizer=MockTokenizer(),
        realloc_dir=realloc_dir,
    )
    TrainerWorker(cfg).run()


def _build_dfg():
    traj_keys = ("packed_input_ids", "prompt_mask", "packed_logprobs",
                 "seq_no_eos_mask", "task_ids", "version_start",
                 "version_end")
    mfcs = [
        MFCDef(
            name="actor_gen", model_name="actor",
            interface_type=MFCInterfaceType.GENERATE,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_prompts", "task_ids"),
            output_keys=traj_keys,
            n_seqs=4, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="rew_inf", model_name="rw",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("rw_math_code"),
            input_keys=("packed_input_ids", "prompt_mask"),
            output_keys=("rewards",),
            n_seqs=8, mb_spec=MicroBatchSpec(),
        ),
        MFCDef(
            name="ref_inf", model_name="ref",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ref_logprob"),
            input_keys=("packed_input_ids",),
            output_keys=("packed_ref_logprobs",),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_inf", model_name="actor",
            interface_type=MFCInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids",),
            output_keys=("prox_logprobs",),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        ),
        MFCDef(
            name="actor_train", model_name="actor",
            interface_type=MFCInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=("packed_input_ids", "prompt_mask", "packed_logprobs",
                        "rewards", "packed_ref_logprobs", "prox_logprobs",
                        "seq_no_eos_mask"),
            n_seqs=8, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
            post_hooks=[WeightUpdateHook(role="actor")],
        ),
    ]
    return build_graph(mfcs)


@pytest.mark.timeout(600)
def test_sync_ppo_through_fabric(tmp_path):
    nr_root = str(tmp_path / "nr")
    data_path = str(tmp_path / "math.jsonl")
    realloc_dir = str(tmp_path / "realloc")
    make_math_jsonl(data_path, n=8)

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(nr_root)

    ctx = mp.get_context("spawn")
    proc = ctx.Process(
        target=_trainer_main, args=(nr_root, data_path, realloc_dir),
        daemon=True,
    )
    proc.start()
    try:
        from areal_tpu.system.master_worker import (
            ExperimentSaveEvalControl,
            MasterWorker,
            MasterWorkerConfig,
        )

        master = MasterWorker(
            MasterWorkerConfig(
                experiment=EXP, trial=TRIAL, trainer_handler="trainer",
                train_batch_size=4,
                exp_ctrl=ExperimentSaveEvalControl(
                    total_train_epochs=10, benchmark_steps=2,
                ),
            ),
            _build_dfg(),
        )
        result = master.run()
        assert result["steps"] == 2
        for st in result["stats"]:
            assert np.isfinite(st["actor_train/actor_loss"])
            assert st["actor_train/n_action_tokens"] > 0
        # weight publishing happened: version key exists + ckpt on disk
        v = name_resolve.get(names.model_version(EXP, TRIAL, "actor"))
        assert int(v) >= 1
        # publish_weights writes the NATIVE pytree format; the json
        # sentinel is written last (models/hf.py save_native_checkpoint).
        assert os.path.exists(os.path.join(realloc_dir, "actor", v,
                                           "areal_tpu_native.json"))
        assert os.path.exists(os.path.join(realloc_dir, "actor", v,
                                           "model.safetensors"))
        proc.join(timeout=30)
        assert proc.exitcode == 0
    finally:
        if proc.is_alive():
            proc.terminate()
