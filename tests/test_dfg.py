"""DFG construction tests — mirrors the reference's tests/data/test_dfg.py.
Builds the 7-node PPO graph shape from SURVEY.md §2.10."""

import pytest

from areal_tpu.api.dfg import (
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    build_graph,
)


def mfc(name, model, itype, inputs, outputs, **kw):
    return MFCDef(
        name=name,
        model_name=model,
        interface_type=itype,
        interface_impl=ModelInterfaceAbstraction("null"),
        input_keys=tuple(inputs),
        output_keys=tuple(outputs),
        **kw,
    )


def ppo_nodes():
    G = MFCInterfaceType.GENERATE
    I = MFCInterfaceType.INFERENCE
    T = MFCInterfaceType.TRAIN_STEP
    return [
        mfc("actor_gen", "actor", G, ["packed_prompts"], ["packed_input_ids", "packed_logprobs", "prompt_mask"]),
        mfc("actor_inf", "actor", I, ["packed_input_ids"], ["proximal_logprobs"]),
        mfc("rew_inf", "reward", I, ["packed_input_ids"], ["rewards"]),
        mfc("ref_inf", "ref", I, ["packed_input_ids"], ["packed_ref_logprobs"]),
        mfc("critic_inf", "critic", I, ["packed_input_ids"], ["values"]),
        mfc(
            "actor_train", "actor", T,
            ["packed_input_ids", "packed_logprobs", "proximal_logprobs", "rewards", "packed_ref_logprobs", "values", "prompt_mask"],
            [],
        ),
        mfc(
            "critic_train", "critic", T,
            ["packed_input_ids", "rewards", "values", "packed_ref_logprobs", "prompt_mask", "packed_logprobs"],
            [],
        ),
    ]


class TestBuildGraph:
    def test_ppo_graph_edges(self):
        g = build_graph(ppo_nodes())
        gen = g.nodes["actor_gen"]
        assert gen.is_src
        assert set(gen.children) == {
            "actor_inf", "rew_inf", "ref_inf", "critic_inf", "actor_train", "critic_train",
        }
        at = g.nodes["actor_train"]
        assert at.is_dst
        assert set(at.parents) == {
            "actor_gen", "actor_inf", "rew_inf", "ref_inf", "critic_inf",
        }

    def test_topological_order(self):
        g = build_graph(ppo_nodes())
        order = g.topological_order()
        assert order[0] == "actor_gen"
        assert set(order[-2:]) == {"actor_train", "critic_train"}

    def test_source_keys_are_dataset_keys(self):
        g = build_graph(ppo_nodes())
        assert g.source_keys == {"packed_prompts"}

    def test_duplicate_producer_rejected(self):
        nodes = ppo_nodes()
        nodes.append(
            mfc("rew_inf2", "reward", MFCInterfaceType.INFERENCE, ["packed_input_ids"], ["rewards"])
        )
        with pytest.raises(ValueError):
            build_graph(nodes)

    def test_cycle_detection(self):
        a = mfc("a", "m", MFCInterfaceType.INFERENCE, ["y"], ["x"])
        b = mfc("b", "m", MFCInterfaceType.INFERENCE, ["x"], ["y"])
        with pytest.raises(ValueError):
            build_graph([a, b])

    def test_output_remap_feeds_consumer(self):
        a = mfc("a", "m", MFCInterfaceType.INFERENCE, ["p"], ["raw"],
                output_key_remap={"raw": "cooked"})
        b = mfc("b", "m", MFCInterfaceType.TRAIN_STEP, ["cooked"], [])
        g = build_graph([a, b])
        assert g.nodes["b"].parents == ["a"]

    def test_model_names(self):
        g = build_graph(ppo_nodes())
        assert g.model_names == {"actor", "critic", "ref", "reward"}
