"""Goodput ledger (system/goodput.py, docs/observability.md §Goodput).

Fake clocks everywhere for the ledger state machine (transitions sum to
wall clock, counters monotonic, export rate-limiting), in-process fakes
for the aggregator fleet stitch, and subprocess smoke for the jax-free
tools/bench_compare.py regression gate. The disabled path is pinned
bit-identical: a null ledger must leave the Prometheus scrape byte-equal
to a build without the ledger.
"""

import json
import os
import subprocess
import sys

import pytest

from areal_tpu.api.train_config import GoodputConfig, TelemetryConfig
from areal_tpu.base import monitor, telemetry
from areal_tpu.system import goodput

pytestmark = pytest.mark.goodput

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def make_ledger(**kw):
    clock = FakeClock()
    reg = telemetry.TelemetryRegistry()
    led = goodput.GoodputLedger(reg, clock=clock,
                                export_interval_secs=0.0, **kw)
    return led, clock, reg


# ---------------------------------------------------------------------------
# ledger state machine
# ---------------------------------------------------------------------------


def test_partition_sums_to_wall_clock():
    led, clock, _ = make_ledger()
    clock.advance(2.0)  # idle (the base state)
    with led.state("compute"):
        clock.advance(3.0)
        with led.state("comm"):  # nested: publish inside an MFC
            clock.advance(1.0)
        clock.advance(0.5)  # back in compute after the nested exit
    clock.advance(1.5)  # idle again
    led.flush()
    t = led.totals()
    assert t["compute"] == pytest.approx(3.5)
    assert t["comm"] == pytest.approx(1.0)
    assert t["idle"] == pytest.approx(3.5)
    assert t["data_wait"] == 0.0
    # THE invariant: a wall-partition ledger's states sum to elapsed wall
    assert sum(t.values()) == pytest.approx(8.0)


def test_state_restored_on_exception():
    led, clock, _ = make_ledger()
    with pytest.raises(RuntimeError):
        with led.state("compute"):
            clock.advance(1.0)
            raise RuntimeError("mfc failed")
    clock.advance(2.0)
    led.flush()
    t = led.totals()
    assert t["compute"] == pytest.approx(1.0)
    assert t["idle"] == pytest.approx(2.0)  # restored despite the raise


def test_exported_counters_monotonic_deltas():
    led, clock, reg = make_ledger()
    with led.state("compute"):
        clock.advance(4.0)
    led.flush()
    c = reg.snapshot()["counters"]
    assert c["goodput/secs{state=compute}"] == pytest.approx(4.0)
    # zero-time states export nothing (no noise families on the scrape)
    assert "goodput/secs{state=data_wait}" not in c
    # more work only ever INCREASES the counter (delta export)
    with led.state("compute"):
        clock.advance(1.0)
    led.flush()
    c2 = reg.snapshot()["counters"]
    assert c2["goodput/secs{state=compute}"] == pytest.approx(5.0)
    assert c2.get("goodput/secs{state=idle}", 0.0) \
        >= c.get("goodput/secs{state=idle}", 0.0)


def test_export_rate_limited_to_interval():
    clock = FakeClock()
    reg = telemetry.TelemetryRegistry()
    led = goodput.GoodputLedger(reg, clock=clock,
                                export_interval_secs=10.0)
    with led.state("compute"):
        clock.advance(1.0)
    # under the interval: accrued host-side, nothing exported yet
    assert "goodput/secs{state=compute}" not in reg.snapshot()["counters"]
    clock.advance(10.0)
    led.poll()
    assert reg.snapshot()["counters"]["goodput/secs{state=compute}"] \
        == pytest.approx(1.0)
    # flush() exports unconditionally (shutdown path)
    with led.state("comm"):
        clock.advance(0.5)
    led.flush()
    assert reg.snapshot()["counters"]["goodput/secs{state=comm}"] \
        == pytest.approx(0.5)


def test_accrual_only_mode_for_concurrent_workers():
    clock = FakeClock()
    reg = telemetry.TelemetryRegistry()
    led = goodput.GoodputLedger(reg, clock=clock,
                                export_interval_secs=0.0,
                                initial_state=None)
    # overlapping task windows (N concurrent rollouts): task-seconds,
    # deliberately NOT clamped to wall clock
    led.add("comm", 3.0)
    led.add("comm", 2.0)
    led.add("data_wait", 4.0)
    clock.advance(1.0)
    led.poll()  # no current state: poll only exports, accrues nothing
    led.flush()
    t = led.totals()
    assert t["comm"] == pytest.approx(5.0)
    assert t["data_wait"] == pytest.approx(4.0)
    assert t["idle"] == 0.0
    c = reg.snapshot()["counters"]
    assert c["goodput/secs{state=comm}"] == pytest.approx(5.0)


def test_overlap_family_kept_out_of_the_partition():
    """Work racing the partition owner (a genserver weight update during
    decode) accrues in goodput/overlap_secs — folding it into the
    partition counters would make states sum past wall clock, deflating
    every rate()-derived fraction and generation-side fleet goodput."""
    led, clock, reg = make_ledger()
    with led.state("compute"):
        clock.advance(4.0)
        led.add_overlap("comm", 2.5)  # overlaps the compute window
    led.flush()
    t = led.totals()
    # the partition still sums to wall clock exactly
    assert sum(t.values()) == pytest.approx(4.0)
    c = reg.snapshot()["counters"]
    assert c["goodput/overlap_secs{state=comm}"] == pytest.approx(2.5)
    assert "goodput/secs{state=comm}" not in c
    # ...and the fleet stitch ignores the overlap family entirely
    fg = goodput.FleetGoodput(clock=FakeClock())
    g = fg.update("generation_server:0", {
        "goodput/secs{state=compute}": 4.0,
        "goodput/overlap_secs{state=comm}": 2.5,
    })
    assert g["fleet/goodput{side=generation}"] == pytest.approx(1.0)


def test_disabled_contract_scrape_bit_identical():
    # the registry a worker would scrape, with ordinary metrics on it
    reg = telemetry.TelemetryRegistry()
    reg.inc("genserver/decode_chunks", 3)
    reg.set_gauge("genserver/weight_version", 2)
    before = telemetry.render_prometheus(reg.snapshot(reset=False))
    led = goodput.make_ledger(GoodputConfig(enabled=False), reg)
    assert led is goodput.NULL_LEDGER
    with led.state("compute"):
        pass
    led.add("comm", 5.0)
    led.enter("data_wait")
    led.poll()
    led.flush()
    assert led.totals() == {}
    after = telemetry.render_prometheus(reg.snapshot(reset=False))
    assert after == before  # byte-equal: zero new families, zero samples
    # an enabled config with a DISABLED telemetry sink also nulls out
    # (nowhere to export — the validate_config contract, belt+braces)
    assert goodput.make_ledger(
        GoodputConfig(enabled=True), telemetry.NULL
    ) is goodput.NULL_LEDGER


# ---------------------------------------------------------------------------
# live MFU: peak resolution + degradation
# ---------------------------------------------------------------------------


def test_resolve_peak_override_and_table():
    assert goodput.resolve_peak_flops(
        GoodputConfig(peak_flops_override=5e12), "TFRT_CPU_0"
    ) == 5e12
    assert goodput.resolve_peak_flops(GoodputConfig(), "TPU v5e") == 197e12
    assert goodput.resolve_peak_flops(GoodputConfig(), "TFRT_CPU_0") is None


def test_mfu_emitter_degrades_on_unknown_peak():
    reg = telemetry.TelemetryRegistry()
    m = goodput.MfuEmitter(reg, None, tflops_name="train/achieved_tflops",
                           mfu_name="train/mfu", context="trainer")
    assert not m._warned
    m.emit(10e12)
    assert m._warned  # warned (once) on the first degraded emit
    m.emit(20e12)
    g = reg.snapshot()["gauges"]
    assert g["train/achieved_tflops"] == pytest.approx(20.0)
    # the satellite contract: NO mfu=0.0 (a hard zero reads as a real
    # collapse to any rolling-baseline sentinel rule)
    assert "train/mfu" not in g


def test_mfu_emitter_with_known_peak():
    reg = telemetry.TelemetryRegistry()
    m = goodput.MfuEmitter(reg, 100e12, tflops_name="train/achieved_tflops",
                           mfu_name="train/mfu")
    m.emit(25e12)
    g = reg.snapshot()["gauges"]
    assert g["train/achieved_tflops"] == pytest.approx(25.0)
    assert g["train/mfu"] == pytest.approx(0.25)
    m.emit(0.0)  # no-op: a zero sample must not zero the gauges
    assert reg.snapshot()["gauges"]["train/mfu"] == pytest.approx(0.25)


def test_bench_flops_accounting_parity():
    """Satellite: bench.py now imports monitor.train_flops_6nt +
    device_peak_flops. Pin both against the 6·N·T formula and the peak
    table bench.py inlined before the dedup — bench output unchanged on
    this fixture geometry."""
    n_params, steps, total, dt, n_chips = 494_032_768, 3, 30_000, 4.2, 1
    # the exact inline accounting deleted from bench.py
    flops_inline = 6.0 * n_params * (steps * total)
    peaks_inline = {
        "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v5": 459e12,
        "v4": 275e12, "v6e": 918e12, "v6": 918e12,
    }
    assert monitor.train_flops_6nt(n_params, steps * total) == flops_inline
    for kind, want in [("TPU v5 lite chip", 197e12), ("tpu v5e", 197e12),
                       ("TPU v5p", 459e12), ("TPU v4 x2", 275e12),
                       ("tpu v6e", 918e12)]:
        inline = next(
            (v for k, v in peaks_inline.items() if k in kind.lower()), None
        )
        assert monitor.device_peak_flops(kind) == inline == want
    assert monitor.device_peak_flops("TFRT_CPU_0") is None
    mfu_old = flops_inline / dt / n_chips / peaks_inline["v5e"]
    mfu_new = (monitor.train_flops_6nt(n_params, steps * total)
               / dt / n_chips / monitor.device_peak_flops("tpu v5e"))
    assert mfu_new == pytest.approx(mfu_old)


def test_validate_config_gates_goodput():
    from areal_tpu.api import cli_args
    from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig

    cfg = PPOMATHConfig()
    cfg.goodput.enabled = True
    with pytest.raises(cli_args.ConfigError, match="telemetry"):
        cli_args.validate_config(cfg)
    cfg.telemetry.enabled = True
    cli_args.validate_config(cfg)
    cfg.goodput.export_interval_secs = 0.0
    with pytest.raises(cli_args.ConfigError, match="export_interval"):
        cli_args.validate_config(cfg)
    cfg.goodput.export_interval_secs = 1.0
    cfg.goodput.peak_flops_override = -1.0
    with pytest.raises(cli_args.ConfigError, match="peak_flops_override"):
        cli_args.validate_config(cfg)


# ---------------------------------------------------------------------------
# fleet stitching
# ---------------------------------------------------------------------------


def test_fleet_goodput_split_and_exclusions():
    fg = goodput.FleetGoodput(clock=FakeClock())
    g = fg.update("trainer:0", {
        "goodput/secs{state=compute}": 8.0,
        "goodput/secs{state=idle}": 2.0,
        "train/tokens": 999.0,  # non-ledger counters are ignored
    })
    assert g["fleet/goodput"] == pytest.approx(0.8)
    assert g["fleet/goodput{side=trainer}"] == pytest.approx(0.8)
    assert "fleet/goodput{side=generation}" not in g
    g = fg.update("generation_server:0", {
        "goodput/secs{state=compute}": 5.0,
        "goodput/secs{state=idle}": 5.0,
    })
    assert g["fleet/goodput"] == pytest.approx(13.0 / 20.0)
    assert g["fleet/goodput{side=trainer}"] == pytest.approx(0.8)
    assert g["fleet/goodput{side=generation}"] == pytest.approx(0.5)
    assert g["fleet/goodput_workers"] == 2.0
    # rollout counters are task-seconds under concurrency — visible
    # per-worker on the scrape but NEVER folded into chip goodput
    g = fg.update("rollout:0", {"goodput/secs{state=comm}": 100.0})
    assert g["fleet/goodput"] == pytest.approx(13.0 / 20.0)
    assert g["fleet/goodput_workers"] == 2.0
    # a snapshot without ledger counters derives nothing
    assert fg.update("trainer:0", {"trainer/store_size": 4.0}) is None
    # the registry mirrors the latest gauges (the aggregator's fleet row)
    assert fg.gauges()["fleet/goodput"] == pytest.approx(13.0 / 20.0)


def test_fleet_goodput_is_windowed_not_since_start():
    """A since-start average's sensitivity decays with run length; the
    stitch must report the LAST WINDOW so a late-run idle fleet moves
    the gauge (and the goodput_collapse rule) immediately."""
    clock = FakeClock()
    fg = goodput.FleetGoodput(clock=clock, window_secs=100.0,
                              expiry_secs=1e9)
    # a long healthy history: fully busy for 10_000s
    busy = 0.0
    for _ in range(100):
        clock.advance(100.0)
        busy += 100.0
        g = fg.update("trainer:0",
                      {"goodput/secs{state=compute}": busy})
    assert g["fleet/goodput"] == pytest.approx(1.0)
    # the fleet goes FULLY idle for one window: the gauge collapses to
    # ~0 even though the since-start average would still read ~0.99
    idle = 0.0
    for _ in range(10):
        clock.advance(10.0)
        idle += 10.0
        g = fg.update("trainer:0", {
            "goodput/secs{state=compute}": busy,
            "goodput/secs{state=idle}": idle,
        })
    assert g["fleet/goodput"] < 0.05, g


def test_fleet_goodput_restart_rebaselines_and_departed_expire():
    clock = FakeClock()
    fg = goodput.FleetGoodput(clock=clock, window_secs=1e9,
                              expiry_secs=60.0)
    fg.update("generation_server:0", {"goodput/secs{state=compute}": 50.0,
                                      "goodput/secs{state=idle}": 50.0})
    clock.advance(10.0)
    g = fg.update("trainer:0", {"goodput/secs{state=compute}": 10.0})
    assert g["fleet/goodput_workers"] == 2.0
    assert g["fleet/goodput{side=generation}"] == pytest.approx(0.5)
    # the gen server RESTARTS (cumulative counters reset backward): its
    # baseline restarts — fresh totals, not bogus negative deltas
    clock.advance(10.0)
    g = fg.update("generation_server:0",
                  {"goodput/secs{state=compute}": 3.0,
                   "goodput/secs{state=idle}": 1.0})
    assert g["fleet/goodput{side=generation}"] == pytest.approx(0.75)
    # ...then it is evicted: past expiry_secs without a report its
    # frozen totals drop out of the fractions entirely
    clock.advance(120.0)
    g = fg.update("trainer:0", {"goodput/secs{state=compute}": 20.0})
    assert g["fleet/goodput_workers"] == 1.0
    assert "fleet/goodput{side=generation}" not in g
    assert g["fleet/goodput"] == pytest.approx(1.0)
    # ...and the registry WITHDRAWS the dead side's gauge (a frozen
    # last value on the scrape would describe a fleet that is gone)
    assert "fleet/goodput{side=generation}" not in fg.gauges()
    assert "fleet/goodput{side=trainer}" in fg.gauges()


def _wait_until(pred, timeout=10.0, interval=0.02):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_aggregator_merges_fleet_goodput_row(tmp_name_resolve, tmp_path):
    """The TelemetryAggregator with a FleetGoodput derives the fleet row
    onto the merged scrape and telemetry.jsonl; without one (the
    disabled default) the same ingest renders zero goodput families."""
    jsonl = str(tmp_path / "telemetry.jsonl")

    class _FakeSentinel:
        # the minimal surface the aggregator touches
        stitcher = object()
        registry = telemetry.TelemetryRegistry()
        feeds = []

        def feed(self, worker, gauges, counters=None):
            self.feeds.append((worker, dict(gauges)))

        def tick(self):
            pass

        def close(self):
            pass

    fake_sentinel = _FakeSentinel()
    agg = telemetry.TelemetryAggregator(
        "gp", "t", jsonl_path=jsonl, goodput=goodput.FleetGoodput(),
        sentinel=fake_sentinel,
    )
    p = None
    try:
        reg = telemetry.TelemetryRegistry()
        reg.inc("goodput/secs{state=compute}", 9.0)
        reg.inc("goodput/secs{state=idle}", 1.0)
        p = telemetry.TelemetryPusher(reg, "gp", "t", "trainer", 0,
                                      flush_interval_secs=3600)
        assert p.flush()
        assert _wait_until(lambda: len(agg.state) == 1)
        text = agg.render_prometheus()
        assert ('areal_goodput_secs_total{state="compute",'
                'worker_index="0",worker_kind="trainer"} 9') in text
        assert ('areal_fleet_goodput{worker_index="0",'
                'worker_kind="fleet"} 0.9') in text
        assert ('areal_fleet_goodput{side="trainer",worker_index="0",'
                'worker_kind="fleet"} 0.9') in text
        # the sentinel feed carries ONLY unlabeled keys: the engine
        # folds {side=...} variants into the same family, and averaging
        # the overall with the per-side splits would mis-weight the
        # sides (and step-change when a side appears/expires)
        fleet_feeds = [g for w, g in fake_sentinel.feeds
                       if w == "fleet:0"]
        assert fleet_feeds, fake_sentinel.feeds
        assert all("{" not in k for g in fleet_feeds for k in g)
        assert any("fleet/goodput" in g for g in fleet_feeds)
    finally:
        if p is not None:
            p.close()
        agg.close()
    # the fleet record landed in telemetry.jsonl alongside the snapshots
    with open(jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    fleet = [r for r in recs if r["worker"] == "fleet:0"]
    assert fleet and fleet[0]["gauges"]["fleet/goodput"] \
        == pytest.approx(0.9)


def test_aggregator_without_goodput_renders_no_fleet_row(tmp_name_resolve):
    agg = telemetry.TelemetryAggregator("gp2", "t", jsonl_path=None)
    p = None
    try:
        reg = telemetry.TelemetryRegistry()
        reg.inc("goodput/secs{state=compute}", 9.0)
        p = telemetry.TelemetryPusher(reg, "gp2", "t", "trainer", 0,
                                      flush_interval_secs=3600)
        assert p.flush()
        assert _wait_until(lambda: len(agg.state) == 1)
        assert "areal_fleet_goodput" not in agg.render_prometheus()
    finally:
        if p is not None:
            p.close()
        agg.close()


# ---------------------------------------------------------------------------
# bench_compare regression gate (jax-free CLI, run as a subprocess)
# ---------------------------------------------------------------------------


BENCH_BASE = {
    "metric": "ppo_trained_tokens_per_sec_per_chip",
    "value": 10000.0, "unit": "tokens/s/chip", "vs_baseline": 0.30,
    "pack_fill": 0.95, "weight_sync_latency_s": 10.0,
    "weight_sync_io_s": 2.0, "weight_sync_transport_s": 8.0,
    "weight_sync_transport_method": "streamed-measured",
    "train_phases": {"fwd_bwd_s": 1.0, "optimizer_s": 0.2},
}


def _bench_compare(*paths, extra=()):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         *map(str, paths), *extra],
        capture_output=True, text=True, timeout=60,
    )


def _write(path, record):
    path.write_text(json.dumps(record))
    return path


def test_bench_compare_passes_within_tolerance(tmp_path):
    a = _write(tmp_path / "r1.json", BENCH_BASE)
    b = _write(tmp_path / "r2.json",
               dict(BENCH_BASE, value=9800.0, pack_fill=0.96))
    r = _bench_compare(a, b)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no regression" in r.stdout


def test_bench_compare_flags_injected_regression(tmp_path):
    a = _write(tmp_path / "r1.json", BENCH_BASE)
    # injected 20% tokens/s drop (tol 5%) + a weight-sync blowup
    b = _write(tmp_path / "r2.json",
               dict(BENCH_BASE, value=8000.0,
                    weight_sync_latency_s=20.0))
    r = _bench_compare(a, b)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "value" in r.stderr
    assert "weight_sync_latency_s" in r.stderr
    # a tolerance override waives the gated fields
    r = _bench_compare(a, b, extra=("--tol", "value=0.5",
                                    "--tol", "weight_sync_latency_s=2.0"))
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_compare_wrapper_form_and_method_discontinuity(tmp_path):
    # driver wrapper form ({"parsed": ...}, what BENCH_r*.json are) +
    # a transport-method change: weight_sync_* numbers measure different
    # things across the discontinuity and must not gate
    a = _write(tmp_path / "r1.json", {"n": 1, "parsed": dict(
        BENCH_BASE, weight_sync_latency_s=500.0,
        weight_sync_transport_method="2x-d2h-extrapolated")})
    b = _write(tmp_path / "r2.json", BENCH_BASE)
    r = _bench_compare(a, b)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "skipped-method-change" in r.stdout
    # train_phases sub-fields flatten and gate (25% tol): a 2x fwd_bwd
    # blowup regresses
    c = _write(tmp_path / "r3.json", dict(
        BENCH_BASE, train_phases={"fwd_bwd_s": 2.0, "optimizer_s": 0.2}))
    r = _bench_compare(b, c)
    assert r.returncode == 1
    assert "train_phases.fwd_bwd_s" in r.stderr


def test_bench_compare_real_trajectory_files():
    """The repo's own BENCH_r* records parse through the gate end to end
    (r04→r05 is the known honesty discontinuity — we only assert the
    tool reads the real files and renders the trajectory, with the
    tolerance widened past the documented method change)."""
    r = _bench_compare(
        os.path.join(REPO, "BENCH_r04.json"),
        os.path.join(REPO, "BENCH_r05.json"),
        extra=("--tol", "default=1.0"),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trajectory" in r.stdout


def test_bench_compare_zero_baseline_still_gates(tmp_path):
    # a zero previous value has no relative scale — a lower-better field
    # going 0 -> 3s must regress, not report "0% change, ok"
    a = _write(tmp_path / "r1.json", dict(BENCH_BASE,
                                          weight_sync_io_s=0.0))
    b = _write(tmp_path / "r2.json", dict(BENCH_BASE,
                                          weight_sync_io_s=3.0))
    r = _bench_compare(a, b)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "weight_sync_io_s" in r.stderr
    # equal zeros are fine
    b = _write(tmp_path / "r2.json", dict(BENCH_BASE,
                                          weight_sync_io_s=0.0))
    assert _bench_compare(a, b).returncode == 0


def test_bench_compare_needs_two_files(tmp_path):
    a = _write(tmp_path / "r1.json", BENCH_BASE)
    r = _bench_compare(a)
    assert r.returncode == 2
