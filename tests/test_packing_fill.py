"""Host-side packing-fill regression (ISSUE 8): the micro-batch packer's
fill on a bench-shaped length distribution must be >= 0.92 — the MFU lever
docs/benchmarks.md "Where the time goes" measured at 0.84 with the coarse
512-bucket candidates — and the finer bucketing must keep the python and
native-C FFD paths bit-identical. CPU-only; no model, no device work
except one tiny engine step that checks the telemetry export."""

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.backend import microbatch as mbu
from areal_tpu.base import datapack


def _bench_batch(seed=0, n_seq=32):
    """The bench.py trajectory distribution, from the canonical shared
    recipe (base/testing.bench_trajectory_dist) so this gate can never
    silently desynchronize from what bench.py actually packs."""
    from areal_tpu.base.testing import bench_trajectory_sample

    return bench_trajectory_sample(seed, n_seq)


@pytest.mark.parametrize("cap", [2048, 4096])
def test_bench_distribution_fill(cap):
    batch, seqlens = _bench_batch()
    mbs = mbu.split_into_microbatches(
        batch, MicroBatchSpec(max_tokens_per_mb=cap),
        length_bucket=512, rows_bucket=4, seqs_bucket=16,
    )
    fill = mbu.pack_fill(mbs)
    assert fill >= 0.92, f"fill {fill:.4f} < 0.92 at cap {cap}"
    # every micro-batch respects the token cap and the lane alignment the
    # flash kernel needs
    for mb in mbs:
        R, L = mb.layout.shape
        assert R * L <= cap
        assert L % 128 == 0


def test_fill_across_distributions():
    """The sweep must not be tuned to one seed: >= 0.92 across seeds and
    batch sizes of the bench-shaped distribution."""
    for seed in range(5):
        for n_seq in (16, 32, 64):
            batch, _ = _bench_batch(seed=seed, n_seq=n_seq)
            mbs = mbu.split_into_microbatches(
                batch, MicroBatchSpec(max_tokens_per_mb=4096),
                length_bucket=512, rows_bucket=4, seqs_bucket=16,
            )
            fill = mbu.pack_fill(mbs)
            assert fill >= 0.92, (seed, n_seq, fill)


def test_scatter_roundtrip_at_fine_buckets():
    """Data integrity is layout-independent: the finer candidate grid must
    still scatter back to the exact input tokens."""
    batch, _ = _bench_batch(seed=3)
    mbs = mbu.split_into_microbatches(
        batch, MicroBatchSpec(max_tokens_per_mb=4096),
        length_bucket=512, rows_bucket=4, seqs_bucket=16,
    )
    outs = [mb.grids["tokens"] for mb in mbs]
    per_sample = mbu.scatter_back(mbs, outs, batch.bs)
    np.testing.assert_array_equal(
        np.concatenate(per_sample), batch.data["packed_input_ids"]
    )


def test_fill_bucket_override_respected():
    batch, _ = _bench_batch()
    mbs = mbu.split_into_microbatches(
        batch, MicroBatchSpec(max_tokens_per_mb=4096),
        length_bucket=512, rows_bucket=4, seqs_bucket=16, fill_bucket=512,
    )
    assert mbs[0].layout.row_len % 512 == 0


def test_ffd_python_native_parity_on_new_bucketing():
    """The 128-grain candidate capacities are new territory for the native
    FFD (csrc/interval_ops.cpp): its bins must stay bit-identical to the
    Python loop at every candidate the sweep can now emit."""
    if datapack._ffd_native([4, 3], 8, force=True) is None:
        pytest.skip("native interval ops unavailable in this build")
    _, seqlens = _bench_batch(seed=1, n_seq=96)
    sizes = seqlens.tolist()
    lo = 128 * ((max(sizes) + 127) // 128)
    for capacity in range(lo, 4096 + 1, 128):
        py = datapack.ffd_allocate(sizes, capacity, use_native=False)
        nat = datapack.ffd_allocate(sizes, capacity, use_native=True)
        assert py == nat, f"FFD parity broke at capacity {capacity}"


def test_pack_fill_telemetry_export():
    """train/pack_fill must land in the telemetry registry when a train
    step runs with telemetry configured (the bench/observability wiring)."""
    import jax

    from areal_tpu.api.model import FinetuneSpec
    from areal_tpu.backend.jax_train import JaxTrainEngine, OptimizerConfig
    from areal_tpu.base import telemetry
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config

    cfg = tiny_config(vocab_size=64)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        cfg, params, opt_cfg=OptimizerConfig(lr=1e-4),
        ft_spec=FinetuneSpec(1, 8, 4), compute_dtype="float32",
        length_bucket=16, rows_bucket=2,
    )
    rng = np.random.RandomState(0)
    lens = rng.randint(4, 20, 8)
    sample = SequenceSample.from_default(
        ids=[f"s{i}" for i in range(8)],
        data={
            "packed_input_ids": rng.randint(
                2, 64, int(lens.sum())
            ).astype(np.int32),
            "loss_mask": np.ones(int(lens.sum()), np.float32),
        },
        seqlens=lens.tolist(),
    )

    import jax.numpy as jnp

    def loss(logits, batch):
        return (jnp.sum(logits.astype(jnp.float32) ** 2) * 1e-6,
                {"n": jnp.sum(batch["segment_ids"] > 0)})

    telemetry.configure("t", "t0", "trainer", 0, push=False)
    try:
        eng.train_batch(
            sample, MicroBatchSpec(max_tokens_per_mb=64), loss,
            lambda mb: float(mb.n_tokens),
        )
        snap = telemetry.get().snapshot(reset=True)
        assert "train/pack_fill" in snap["gauges"]
        assert 0.5 < snap["gauges"]["train/pack_fill"] <= 1.0
    finally:
        telemetry.shutdown()
