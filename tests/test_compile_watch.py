"""Compile & HBM observatory (base/compile_watch.py, system/memwatch.py,
docs/observability.md §Compile & memory).

Fake clocks + fake devices everywhere: compile timing is driven by an
injected monotonic clock the wrapped fn advances, HBM readings come from
injectable device fakes with scripted ``memory_stats()`` dicts — zero
real sleeps, no jax arrays, no backend dependence. The disabled contract
(scrape bit-identical with the observatory off) is pinned here, and the
sentinel's compile/HBM rule pack is validated through the same
``rules_from_config`` path the master runs.
"""

import json

import pytest

from areal_tpu.api.train_config import CompileWatchConfig, SentinelConfig
from areal_tpu.base import compile_watch as cw
from areal_tpu.base import telemetry
from areal_tpu.system import memwatch as mw
from areal_tpu.system.sentinel import (
    COMPILE_RULES,
    DEFAULT_RULES,
    SentinelConfigError,
    parse_rules,
    rules_from_config,
)

pytestmark = pytest.mark.compilewatch


class Arr:
    """Array-like stand-in: compile_watch only reads .shape/.dtype."""

    def __init__(self, shape, dtype="float32"):
        self.shape = shape
        self.dtype = dtype


class FakeDevice:
    """jax device stand-in: memory_stats() returns a mutable dict."""

    def __init__(self, stats):
        self.stats = stats

    def memory_stats(self):
        return self.stats


def make_watch(**kw):
    """(watch, registry, clock dict) with a controllable monotonic."""
    reg = telemetry.TelemetryRegistry()
    t = {"now": 0.0}
    watch = cw.CompileWatch(reg, clock=lambda: t["now"], **kw)
    return watch, reg, t


# ---------------------------------------------------------------------------
# abstract signatures
# ---------------------------------------------------------------------------


def test_abstract_signature_keys_on_shape_dtype_and_statics():
    sig = lambda *a, **k: cw.abstract_signature(a, k)  # noqa: E731
    assert sig(Arr((4, 8))) == sig(Arr((4, 8)))
    assert sig(Arr((4, 8))) != sig(Arr((4, 9)))
    assert sig(Arr((4, 8))) != sig(Arr((4, 8), dtype="bfloat16"))
    # static arg VALUES key the jit cache, so they key the signature too
    assert sig(Arr((4, 8)), 128) != sig(Arr((4, 8)), 256)
    assert sig(x=1, y=2) == sig(y=2, x=1)  # kwargs order-insensitive
    # containers recurse; list vs tuple is a retrace in jax too
    assert sig([Arr((2,))]) != sig((Arr((2,)),))


# ---------------------------------------------------------------------------
# compile-event recording
# ---------------------------------------------------------------------------


def test_compile_events_recorded_with_fake_clock():
    watch, reg, t = make_watch()
    inflight_seen = []

    def fn(x):
        inflight_seen.append(watch.inflight())
        t["now"] += 2.5  # the fake "compile + first dispatch" wall time
        return x

    f = watch.wrap("train/grad", fn)
    f(Arr((4, 128)))
    snap = reg.snapshot(reset=False)
    assert snap["counters"]["compile/events{fn=train/grad}"] == 1.0
    assert snap["counters"]["compile/secs{fn=train/grad}"] == 2.5
    assert snap["gauges"]["compile/distinct_shapes{fn=train/grad}"] == 1.0
    # the gauge pulsed up during the call and is back to 0 after
    assert inflight_seen == [True]
    assert snap["gauges"]["compile/inflight"] == 0.0
    assert not watch.inflight()
    # a known signature is a cache hit: no new compile event
    f(Arr((4, 128)))
    snap = reg.snapshot(reset=False)
    assert snap["counters"]["compile/events{fn=train/grad}"] == 1.0
    # a new shape compiles again and bumps distinct_shapes
    f(Arr((4, 256)))
    snap = reg.snapshot(reset=False)
    assert snap["counters"]["compile/events{fn=train/grad}"] == 2.0
    assert snap["counters"]["compile/secs{fn=train/grad}"] == 5.0
    assert snap["gauges"]["compile/distinct_shapes{fn=train/grad}"] == 2.0
    assert watch.stats()["train/grad"] == {
        "calls": 3.0, "distinct_shapes": 2.0,
    }


def test_wrapper_passes_through_result_and_exceptions():
    watch, reg, t = make_watch()
    f = watch.wrap("train/apply", lambda x, s: (x, s))
    a = Arr((2, 2))
    assert f(a, s=7) == (a, 7)
    assert f.__wrapped__(a, s=7) == (a, 7)

    def boom(x):
        raise RuntimeError("compile blew up")

    g = watch.wrap("train/boom", boom)
    with pytest.raises(RuntimeError, match="blew up"):
        g(Arr((1,)))
    # the inflight gauge must unwind even on an exception mid-compile
    assert not watch.inflight()
    assert reg.snapshot(reset=False)["gauges"]["compile/inflight"] == 0.0


# ---------------------------------------------------------------------------
# recompile-storm detection
# ---------------------------------------------------------------------------


def test_storm_fires_only_after_shape_stability(monkeypatch):
    warned = []
    monkeypatch.setattr(cw.logger, "warning", warned.append)
    watch, reg, t = make_watch(storm_warmup_calls=4)
    f = watch.wrap("gen/prefill", lambda x: x)
    stable = Arr((8, 512))
    f(stable)  # cold-start compile: never a storm
    counters = reg.snapshot(reset=False)["counters"]
    assert "compile/storm_events" not in counters
    # a second new shape BEFORE warmup stability: still churn, not storm
    f(Arr((8, 640)))
    assert "compile/storm_events" not in \
        reg.snapshot(reset=False)["counters"]
    # now hold shape-stable through the warmup window...
    for _ in range(4):
        f(stable)
    # ...then a new shape is exactly the storm signature
    f(Arr((8, 768)))
    counters = reg.snapshot(reset=False)["counters"]
    assert counters["compile/storm_events"] == 1.0
    assert len(warned) == 1 and "recompile storm" in warned[0]
    # the next new shape arrives with calls_since_new_sig reset: no storm
    f(Arr((8, 896)))
    assert reg.snapshot(reset=False)["counters"][
        "compile/storm_events"] == 1.0
    # stability then another new shape storms again (counted, warned once
    # per offending signature)
    for _ in range(4):
        f(stable)
    f(Arr((8, 1024)))
    assert reg.snapshot(reset=False)["counters"][
        "compile/storm_events"] == 2.0


def test_fresh_wrappers_recompiling_known_shapes_are_not_storms():
    """The reshard identity pattern: a NEW jit object per publish group
    recompiles shapes the per-name ledger already saw. That is a real
    compile (events count) but not shape churn (no storm)."""
    watch, reg, t = make_watch(storm_warmup_calls=2)
    shape = Arr((16, 1024))
    for i in range(6):
        f = watch.wrap("reshard/identity", lambda x: x)
        f(shape)
        f(shape)  # warm call on the same wrapper
    snap = reg.snapshot(reset=False)
    # every fresh wrapper's first call recorded as a compile event...
    assert snap["counters"]["compile/events{fn=reshard/identity}"] == 6.0
    # ...but the name-level shape set never grew past 1, and no storm
    assert snap["gauges"][
        "compile/distinct_shapes{fn=reshard/identity}"] == 1.0
    assert "compile/storm_events" not in snap["counters"]


# ---------------------------------------------------------------------------
# persistent-cache accounting
# ---------------------------------------------------------------------------


def test_cache_hit_miss_probing(tmp_path):
    cache = tmp_path / "xla_cache"
    cache.mkdir()
    watch, reg, t = make_watch(cache_dir=str(cache))

    def cold(x):
        # XLA really compiled: it wrote a new persistent-cache entry
        n = len(list(cache.iterdir()))
        (cache / f"entry-{n}").write_text("xla")
        return x

    f = watch.wrap("train/grad", cold)
    f(Arr((4, 128)))
    counters = reg.snapshot(reset=False)["counters"]
    assert counters["compile/cache_misses"] == 1.0
    assert "compile/cache_hits" not in counters
    # a compile that produces no new entry was served from the cache
    g = watch.wrap("train/grad", lambda x: x)
    g(Arr((4, 128)))
    counters = reg.snapshot(reset=False)["counters"]
    assert counters["compile/cache_misses"] == 1.0
    assert counters["compile/cache_hits"] == 1.0


# ---------------------------------------------------------------------------
# MemWatch: HBM gauges, watermarks, degradation
# ---------------------------------------------------------------------------


def make_memwatch(devices, **kw):
    reg = telemetry.TelemetryRegistry()
    t = {"now": 0.0}
    m = mw.MemWatch(reg, devices_fn=lambda: devices,
                    clock=lambda: t["now"], **kw)
    return m, reg, t


def test_memwatch_exports_per_device_gauges_rate_limited():
    d0 = FakeDevice({"bytes_in_use": 100.0, "peak_bytes_in_use": 150.0,
                     "bytes_limit": 1000.0})
    d1 = FakeDevice({"bytes_in_use": 300.0, "peak_bytes_in_use": 300.0,
                     "bytes_limit": 1000.0})
    m, reg, t = make_memwatch([d0, d1], sample_interval_secs=10.0)
    assert m.sample() == 300.0
    gauges = reg.snapshot(reset=False)["gauges"]
    assert gauges["hbm/bytes_in_use{device=0}"] == 100.0
    assert gauges["hbm/peak_bytes{device=0}"] == 150.0
    assert gauges["hbm/limit_bytes{device=1}"] == 1000.0
    assert gauges["hbm/bytes_in_use{device=1}"] == 300.0
    # inside the interval: rate-limited (None), gauges untouched
    d0.stats["bytes_in_use"] = 900.0
    t["now"] = 5.0
    assert m.sample() is None
    assert reg.snapshot(reset=False)["gauges"][
        "hbm/bytes_in_use{device=0}"] == 100.0
    # force bypasses the limiter; peak_gb tracks the high-water mark
    assert m.sample(force=True) == 900.0
    assert m.peak_gb() == 900.0 / (1 << 30)
    # past the interval the limiter opens again
    t["now"] = 16.0
    assert m.sample() == 900.0


def test_memwatch_watermark_sites_are_monotonic_maxima():
    dev = FakeDevice({"bytes_in_use": 100.0, "peak_bytes_in_use": 100.0,
                      "bytes_limit": 1000.0})
    m, reg, t = make_memwatch([dev])
    with m.watermark("weight_stream/gather"):
        dev.stats["bytes_in_use"] = 800.0
    gauges = reg.snapshot(reset=False)["gauges"]
    assert gauges["hbm/watermark_bytes{site=weight_stream/gather}"] == 800.0
    # a later, smaller peak must not lower the recorded high-water mark
    dev.stats["bytes_in_use"] = 200.0
    with m.watermark("weight_stream/gather"):
        pass
    assert reg.snapshot(reset=False)["gauges"][
        "hbm/watermark_bytes{site=weight_stream/gather}"] == 800.0
    assert m.site_peaks() == {"weight_stream/gather": 800.0}


def test_memwatch_degrades_once_without_memory_stats(monkeypatch):
    """CPU-backend contract: one warning + one counter bump, then quiet —
    never fake zero gauges that read as an empty chip."""
    warned = []
    monkeypatch.setattr(mw.logger, "warning", warned.append)

    class CpuDevice:  # no memory_stats attribute at all
        pass

    m, reg, t = make_memwatch([CpuDevice()])
    assert m.sample(force=True) is None
    assert m.sample(force=True) is None
    assert m.sample(force=True) is None
    snap = reg.snapshot(reset=False)
    assert snap["counters"]["hbm/memory_stats_unavailable"] == 1.0
    assert not any(k.startswith("hbm/bytes") for k in snap["gauges"])
    assert len(warned) == 1 and "degraded" in warned[0]
    # degraded watermarks are cheap no-ops, not errors
    with m.watermark("train/fwd_bwd"):
        pass
    assert m.site_peaks() == {}
    assert m.peak_gb() == 0.0


def test_memwatch_skips_devices_that_return_no_stats():
    """Mixed fleets: a device returning None/{} (some runtime versions)
    is skipped while real readings still export."""

    class NoneDevice:
        def memory_stats(self):
            return None

    dev = FakeDevice({"bytes_in_use": 42.0, "bytes_limit": 100.0})
    m, reg, t = make_memwatch([NoneDevice(), dev])
    assert m.sample(force=True) == 42.0
    gauges = reg.snapshot(reset=False)["gauges"]
    # the real device is index 0 of the READINGS, not of the device list
    assert gauges["hbm/bytes_in_use{device=0}"] == 42.0


# ---------------------------------------------------------------------------
# disabled contract: bit-identical scrape
# ---------------------------------------------------------------------------


def test_disabled_keeps_null_sinks_and_scrape_bit_identical():
    reg = telemetry.TelemetryRegistry()
    reg.inc("train/optimizer_steps")
    reg.set_gauge("train/mfu", 0.41)
    before = telemetry.render_prometheus(reg.snapshot(reset=False))

    assert cw.configure(CompileWatchConfig(enabled=False), reg) is cw.NULL
    assert mw.configure(CompileWatchConfig(enabled=False), reg) is mw.NULL
    try:
        assert not cw.enabled() and not mw.enabled()

        def fn(x):
            return x

        # the raw function object comes back — zero per-call overhead
        assert cw.watched_jit("train/grad", fn) is fn
        assert not cw.inflight()
        assert mw.sample(force=True) is None
        with mw.watermark("train/fwd_bwd"):
            pass
        assert mw.peak_gb() == 0.0
    finally:
        cw.shutdown()
        mw.shutdown()
    after = telemetry.render_prometheus(reg.snapshot(reset=False))
    assert after == before
    assert "compile" not in after and "hbm" not in after


def test_configure_enabled_installs_and_shutdown_restores_null():
    reg = telemetry.TelemetryRegistry()
    try:
        watch = cw.configure(
            CompileWatchConfig(enabled=True, storm_warmup_calls=3),
            reg, cache_dir=None,
        )
        assert watch is cw.get() and cw.enabled()
        assert watch.storm_warmup_calls == 3
        m = mw.configure(
            CompileWatchConfig(enabled=True, mem_sample_interval_secs=2.0),
            reg, devices_fn=lambda: [],
        )
        assert m is mw.get() and mw.enabled()
        assert m.sample_interval_secs == 2.0
        wrapped = cw.watched_jit("train/grad", lambda x: x)
        assert wrapped.__wrapped__ is not None
        wrapped(Arr((2, 2)))
        assert reg.snapshot(reset=False)["counters"][
            "compile/events{fn=train/grad}"] == 1.0
    finally:
        cw.shutdown()
        mw.shutdown()
    assert cw.get() is cw.NULL and mw.get() is mw.NULL


# ---------------------------------------------------------------------------
# aggregator: derived utilization + fleet rollups
# ---------------------------------------------------------------------------


def test_derive_hbm_utilization_injects_ratio_per_device():
    payload = {"gauges": {
        "hbm/bytes_in_use{device=0}": 750.0,
        "hbm/limit_bytes{device=0}": 1000.0,
        "hbm/bytes_in_use{device=1}": 100.0,
        "hbm/limit_bytes{device=1}": 400.0,
        "train/mfu": 0.4,
    }, "counters": {}}
    telemetry.TelemetryAggregator._derive_hbm_utilization(payload)
    assert payload["gauges"]["hbm/utilization{device=0}"] == 0.75
    assert payload["gauges"]["hbm/utilization{device=1}"] == 0.25


def test_derive_hbm_utilization_no_hbm_gauges_no_mutation():
    """The merged-scrape bit-identity hinges on the derivation being a
    strict no-op when the observatory exports nothing."""
    payload = {"gauges": {"train/mfu": 0.4, "master/step_secs": 3.0},
               "counters": {"train/optimizer_steps": 12.0}}
    before = json.dumps(payload, sort_keys=True)
    telemetry.TelemetryAggregator._derive_hbm_utilization(payload)
    assert json.dumps(payload, sort_keys=True) == before
    # bytes_in_use without a limit (device never reported one): no ratio
    payload = {"gauges": {"hbm/bytes_in_use{device=0}": 750.0}}
    telemetry.TelemetryAggregator._derive_hbm_utilization(payload)
    assert "hbm/utilization{device=0}" not in payload["gauges"]


# ---------------------------------------------------------------------------
# sentinel: the compile/HBM rule pack
# ---------------------------------------------------------------------------


def test_compile_rule_pack_armed_only_with_the_observatory():
    base = {r.id for r in rules_from_config(SentinelConfig(enabled=True))}
    armed = {r.id for r in rules_from_config(
        SentinelConfig(enabled=True), compile_watch_enabled=True)}
    pack = {r["id"] for r in COMPILE_RULES}
    assert pack == {"recompile_storm", "hbm_pressure", "compile_stall"}
    assert pack & base == set()
    assert pack <= armed
    assert armed - pack == base == {r["id"] for r in DEFAULT_RULES}
    # the pack parses clean: severities, metrics, durations all validated
    by_id = {r.id: r for r in rules_from_config(
        SentinelConfig(enabled=True), compile_watch_enabled=True)}
    assert by_id["recompile_storm"].kind == "rate"
    assert by_id["hbm_pressure"].metric == "hbm/utilization"
    assert by_id["compile_stall"].severity == "critical"


def test_trainer_stalled_carries_compile_unless_guard():
    rules = {r.id: r for r in
             rules_from_config(SentinelConfig(enabled=True))}
    stalled = rules["trainer_stalled"]
    assert stalled.unless_metric == "compile/inflight"
    # the drive-by: a wedged trainer alerts in minutes, not after the old
    # blanket 30-minute grace
    assert stalled.for_secs == 300.0


def test_unless_grammar_is_validated():
    absence = {"id": "r", "metric": "train/optimizer_steps",
               "kind": "absence", "for": 60, "cooldown": 60}
    # valid: absence rule + catalog metric
    [r] = parse_rules([dict(absence, unless="compile/inflight")])
    assert r.unless_metric == "compile/inflight"
    # unless on a non-absence rule is a config error
    with pytest.raises(SentinelConfigError, match="absence"):
        parse_rules([{"id": "r", "metric": "train/approx_kl",
                      "kind": "threshold", "op": "gt", "value": 1.0,
                      "unless": "compile/inflight"}])
    # unknown unless metric is caught with a did-you-mean hint
    with pytest.raises(SentinelConfigError, match="unless"):
        parse_rules([dict(absence, unless="compile/inflite")])


# ---------------------------------------------------------------------------
# config validation (api/cli_args.py)
# ---------------------------------------------------------------------------


def test_validate_config_gates_the_observatory():
    from areal_tpu.api import cli_args
    from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig

    cfg = PPOMATHConfig()
    cfg.compile_watch.enabled = True
    with pytest.raises(cli_args.ConfigError, match="telemetry"):
        cli_args.validate_config(cfg)
    cfg.telemetry.enabled = True
    cli_args.validate_config(cfg)
    cfg.compile_watch.storm_warmup_calls = 0
    with pytest.raises(cli_args.ConfigError, match="storm_warmup"):
        cli_args.validate_config(cfg)
    cfg.compile_watch.storm_warmup_calls = 16
    cfg.compile_watch.mem_sample_interval_secs = -1.0
    with pytest.raises(cli_args.ConfigError, match="mem_sample"):
        cli_args.validate_config(cfg)


def test_validate_config_cross_checks_shape_budgets(monkeypatch):
    """Unified compiled-shape accounting: serving.max_compiled_shapes
    must cover the trainer fill sweep's worst-case candidate count too,
    not only the serving policy's own decode/prefill grids."""
    from areal_tpu.api import cli_args
    from areal_tpu.backend import microbatch
    from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig

    cands = microbatch.worst_case_row_candidates()
    assert cands >= 1
    cfg = PPOMATHConfig()
    cfg.telemetry.enabled = True
    cfg.compile_watch.enabled = True
    cfg.serving.enabled = True
    # generous enough for the serving policy's own worst case AND the
    # trainer sweep: everything validates
    cfg.serving.max_compiled_shapes = 4096
    cli_args.validate_config(cfg)
    # a trainer sweep that outgrows the serving budget is caught at
    # parse time with the sweep's own number in the message
    monkeypatch.setattr(microbatch, "worst_case_row_candidates",
                        lambda: 5000)
    with pytest.raises(cli_args.ConfigError,
                       match="worst-case candidate count"):
        cli_args.validate_config(cfg)
