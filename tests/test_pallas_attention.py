"""Pallas flash attention vs XLA reference parity (the reference repo's
tests/cpp_extensions kernel-parity pattern, on the interpreter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.models import packing
from areal_tpu.ops import attention as attn


def _packed_case(seqlens, Hq=4, Hkv=2, D=128, row_len=None, seed=0):
    rng = np.random.RandomState(seed)
    layout = packing.plan_packing(seqlens, length_bucket=128, row_len=row_len)
    grid = packing.make_grid(layout)
    B, L = layout.shape
    q = rng.randn(B, L, Hq, D).astype(np.float32) * 0.3
    k = rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3
    v = rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3
    return layout, grid, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize(
    "seqlens",
    [[128], [60, 68], [100, 20, 120, 9],
     [300, 340]],  # T=640: 128-aligned but NOT a multiple of 512
)
@pytest.mark.parametrize("D", [64, 128])
def test_flash_matches_reference(seqlens, D):
    from areal_tpu.ops.pallas.flash_attention import flash_attention

    layout, grid, q, k, v = _packed_case(seqlens, D=D)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    ref = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                kv_positions=pos, causal=True,
                                impl="reference")
    with pltpu.force_tpu_interpret_mode():
        out = flash_attention(q, k, v, seg, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)
    # padding query rows are exactly zero
    pad = np.asarray(seg) == 0
    assert (np.asarray(out)[pad] == 0).all()


def test_flash_backward_matches_reference():
    from areal_tpu.ops.pallas.flash_attention import flash_attention

    layout, grid, q, k, v = _packed_case([96, 32], Hq=2, Hkv=2, D=128)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    def loss_ref(q, k, v):
        o = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                  kv_positions=pos, impl="reference")
        return jnp.sum(o * o)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, seg, seg)
        return jnp.sum(o * o)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fl, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2,
            err_msg=f"grad mismatch for {name}",
        )
