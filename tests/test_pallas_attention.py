"""Pallas flash attention vs XLA reference parity (the reference repo's
tests/cpp_extensions kernel-parity pattern, on the interpreter), plus the
block-size autotuning table and the non-128-divisible reference fallback
(both CPU-only — no interpreter needed).

The interpreter parity tests are version-gated: jax 0.4.x ships neither
``pltpu.force_tpu_interpret_mode`` nor a pallas interpreter that can
execute this kernel (``pl.pallas_call(interpret=True)`` dies in its
load-discharge rule on scalar block indices), so they skip there with a
reason instead of erroring — see ops/pallas/flash_attention.interpret_mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import packing
from areal_tpu.ops import attention as attn
from areal_tpu.ops.pallas import flash_attention as fa

_INTERPRET = fa.interpret_mode()
needs_interpreter = pytest.mark.skipif(
    _INTERPRET is None,
    reason="this jax lacks pltpu.force_tpu_interpret_mode and its pallas "
    "interpreter cannot run the TPU flash kernel (jax<=0.4.x)",
)


def _packed_case(seqlens, Hq=4, Hkv=2, D=128, row_len=None, seed=0):
    rng = np.random.RandomState(seed)
    layout = packing.plan_packing(seqlens, length_bucket=128, row_len=row_len)
    grid = packing.make_grid(layout)
    B, L = layout.shape
    q = rng.randn(B, L, Hq, D).astype(np.float32) * 0.3
    k = rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3
    v = rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3
    return layout, grid, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@needs_interpreter
@pytest.mark.parametrize(
    "seqlens",
    [[128], [60, 68], [100, 20, 120, 9],
     [300, 340]],  # T=640: 128-aligned but NOT a multiple of 512
)
@pytest.mark.parametrize("D", [64, 128])
def test_flash_matches_reference(seqlens, D):
    layout, grid, q, k, v = _packed_case(seqlens, D=D)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    ref = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                kv_positions=pos, causal=True,
                                impl="reference")
    with fa.interpret_mode():
        out = fa.flash_attention(q, k, v, seg, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)
    # padding query rows are exactly zero
    pad = np.asarray(seg) == 0
    assert (np.asarray(out)[pad] == 0).all()


@needs_interpreter
def test_flash_backward_matches_reference():
    layout, grid, q, k, v = _packed_case([96, 32], Hq=2, Hkv=2, D=128)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    def loss_ref(q, k, v):
        o = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                  kv_positions=pos, impl="reference")
        return jnp.sum(o * o)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, seg, seg)
        return jnp.sum(o * o)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    with fa.interpret_mode():
        g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fl, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2,
            err_msg=f"grad mismatch for {name}",
        )


# ---------------- block-size autotuning (CPU, no interpreter) ------------


@pytest.fixture(autouse=True)
def _clean_block_state(monkeypatch):
    fa.clear_block_table()
    monkeypatch.delenv("AREAL_FLASH_BLOCKS", raising=False)
    monkeypatch.delenv("AREAL_FLASH_BLOCK_TABLE", raising=False)
    yield
    fa.clear_block_table()


def test_pick_block_sizes_heuristic():
    # the default: largest dividing 128-multiple <= 512
    assert fa.pick_block_sizes(1024, 1024) == (512, 512)
    assert fa.pick_block_sizes(640, 640) == (128, 128)  # 512∤640, 256∤640
    assert fa.pick_block_sizes(384, 768) == (384, 384)
    # no 128-multiple divisor at all -> None (callers fall back)
    assert fa.pick_block_sizes(192, 1024) is None
    assert fa.pick_block_sizes(1024, 100) is None


def test_pick_block_sizes_table_and_env(monkeypatch, tmp_path):
    # runtime-recorded entry wins over the heuristic
    fa.set_block_sizes(1024, 1024, 256, 1024)
    assert fa.pick_block_sizes(1024, 1024) == (256, 1024)
    # ... but snaps down to a legal divisor when the entry is invalid
    fa.set_block_sizes(640, 640, 512, 512)
    assert fa.pick_block_sizes(640, 640) == (128, 128)
    # file-loaded table (the blocksweep output format)
    p = tmp_path / "blocks.json"
    p.write_text('{"2048,2048": [512, 1024]}')
    monkeypatch.setenv("AREAL_FLASH_BLOCK_TABLE", str(p))
    assert fa.pick_block_sizes(2048, 2048) == (512, 1024)
    # env pin beats everything
    monkeypatch.setenv("AREAL_FLASH_BLOCKS", "128,256")
    assert fa.pick_block_sizes(1024, 1024) == (128, 256)
    assert fa.pick_block_sizes(2048, 2048) == (128, 256)
    # a sub-128 pin has no legal divisor: it must land on the heuristic,
    # NOT snap up to a whole-sequence tile (VMEM blowup)
    monkeypatch.setenv("AREAL_FLASH_BLOCKS", "64,64")
    assert fa.pick_block_sizes(1792, 1792) == (256, 256)


def test_blocksweep_candidates_and_record_format():
    """The perf_probe blocksweep pieces that don't need a TPU: candidate
    enumeration respects the kernel's divisibility constraint, and the
    recorded JSON round-trips through pick_block_sizes."""
    import json
    import os
    import sys

    tools_dir = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools_dir)
    try:
        from perf_probe import _blocksweep_candidates
    finally:
        sys.path.remove(tools_dir)

    cands = _blocksweep_candidates(1792, 1792)
    assert (256, 1792) in cands and (1792, 256) in cands
    for bq, bkv in cands:
        assert 1792 % bq == 0 and bq % 128 == 0
        assert 1792 % bkv == 0 and bkv % 128 == 0
    assert _blocksweep_candidates(192, 1792) == []  # no legal bq

    # the exact record the sweep writes is what the table loader reads
    rec = {"1792,1792": [256, 1792]}
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(rec, f)
        path = f.name
    import os

    os.environ["AREAL_FLASH_BLOCK_TABLE"] = path
    try:
        assert fa.pick_block_sizes(1792, 1792) == (256, 1792)
    finally:
        del os.environ["AREAL_FLASH_BLOCK_TABLE"]
        os.unlink(path)


def test_non_divisible_shape_falls_back_to_reference():
    """T=192 has no 128-multiple divisor: the old code raised
    NotImplementedError; now it must produce the reference result (logged
    fallback), bit-matching attention_reference."""
    seqlens = [100, 92]  # packs to one 192-col row with row_len=192
    layout = packing.plan_packing(seqlens, length_bucket=64, row_len=192)
    grid = packing.make_grid(layout)
    B, L = layout.shape
    assert L == 192
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, L, 4, 64).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, L, 2, 64).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, L, 2, 64).astype(np.float32) * 0.3)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    out = fa.flash_attention(q, k, v, seg, seg, q_positions=pos,
                             kv_positions=pos)
    ref = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                kv_positions=pos, impl="reference")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
