"""Serving engine (system/serving.py, docs/serving.md): admission control,
class priority, refcounted KV pinning, bounded compile shapes, class-aware
lease routing, and the 429 backpressure path of the chunked client.

Everything is bounded to seconds: in-process fakes or tiny real models,
zero real sleeps beyond millisecond batch windows.
"""

import asyncio
import time

import numpy as np
import pytest

from areal_tpu.api.train_config import ServingConfig
from areal_tpu.base import name_resolve, names, network
from areal_tpu.system.serving import (
    REQUEST_CLASSES,
    AdmissionReject,
    KVStateStore,
    PrefixTrie,
    PromptTooLong,
    ReqState,
    ServingEngine,
    ServingQueue,
    ShapeBucketPolicy,
    normalize_class,
    policy_from_config,
)

EXP, TRIAL = "servtest", "t0"


def _scfg(**kw) -> ServingConfig:
    # Test servers run kv_bucket=32: keep the derived capacity ladder
    # consistent with the default shape cap.
    kw.setdefault("max_kv_capacity", 256)
    return ServingConfig(enabled=True, **kw)


# ------------------------------------------------------------- shape policy


@pytest.mark.serving
def test_shape_policy_bounded_rounding():
    pol = ShapeBucketPolicy(
        quantum=32, capacity_buckets=[64, 128, 256],
        chunk_buckets=[4, 8], row_buckets=[1, 2, 4], max_shapes=64,
    )
    assert pol.round_capacity(1) == 64
    assert pol.round_capacity(65) == 128
    assert pol.round_capacity(256) == 256
    with pytest.raises(PromptTooLong):
        pol.round_capacity(257)
    assert not pol.fits(257) and pol.fits(256)
    assert pol.round_chunk(3) == 4
    assert pol.round_chunk(5) == 8
    assert pol.round_chunk(100) == 8  # clamped to the largest bucket
    assert pol.round_rows(3) == 4
    assert pol.round_rows(9) == 4  # clamped
    pol.observe("decode", 2, 64, 8)
    pol.observe("decode", 2, 64, 8)  # dedup
    pol.observe("prefill", 2, 16, 64)
    assert pol.distinct_shapes == 2


@pytest.mark.serving
def test_shape_policy_legacy_passthrough():
    pol = ShapeBucketPolicy(quantum=256)
    assert pol.round_capacity(1) == 256
    assert pol.round_capacity(257) == 512  # unbounded multiples
    assert pol.round_chunk(77) == 77
    assert pol.round_rows(13) == 13
    assert pol.fits(10**9)


@pytest.mark.serving
def test_shape_policy_refuses_overwide_buckets():
    with pytest.raises(ValueError, match="max_compiled_shapes"):
        ShapeBucketPolicy(
            quantum=32, capacity_buckets=list(range(64, 64 * 20, 64)),
            chunk_buckets=[2, 4, 8, 16], row_buckets=[1, 2, 4, 8],
            max_shapes=16,
        )


@pytest.mark.serving
def test_policy_from_config_derives_buckets():
    cfg = _scfg(max_kv_capacity=1024, max_compiled_shapes=64)
    pol = policy_from_config(
        cfg, kv_bucket=128, chunk_tokens=16, max_batch_size=8,
        prompt_bucket=128,
    )
    assert pol.capacity_buckets == [128, 256, 512, 1024]
    assert pol.chunk_buckets == [16]
    assert pol.row_buckets == [1, 2, 4, 8]
    # Width ladder: geometric from prompt_bucket, final bucket at the
    # widest prefill that still fits one minimum chunk under the ceiling.
    assert pol.width_buckets == [128, 256, 512, 1008]
    assert pol.round_width(100) == 128
    assert pol.round_width(600) == 1008
    with pytest.raises(PromptTooLong):
        pol.round_width(1009)
    # disabled config -> legacy
    legacy = policy_from_config(
        ServingConfig(), kv_bucket=128, chunk_tokens=16, max_batch_size=8,
        prompt_bucket=128,
    )
    assert legacy.capacity_buckets is None
    assert legacy.round_width(37) == 37  # pass-through


@pytest.mark.serving
def test_policy_refuses_row_buckets_below_batch_size():
    """row_buckets whose max is under max_batch_size would clamp bigger
    drains DOWN — the decode batch then runs at its raw size, compiling
    per exact batch size. Config error, refused at construction."""
    cfg = _scfg(row_buckets=[1, 2, 4])
    with pytest.raises(ValueError, match="row_buckets"):
        policy_from_config(
            cfg, kv_bucket=32, chunk_tokens=4, max_batch_size=8,
            prompt_bucket=8,
        )
    # max bucket == batch size is fine
    policy_from_config(
        cfg, kv_bucket=32, chunk_tokens=4, max_batch_size=4,
        prompt_bucket=8,
    )


@pytest.mark.serving
def test_policy_refuses_degenerate_width_ladder():
    """A chunk bucket at (or near) max_kv_capacity leaves no width room:
    the ladder would collapse to [1] and 413 EVERY request at admission.
    Refused at construction — which validate_config runs at parse time —
    instead of surfacing as a fleet-wide runtime reject."""
    cfg = _scfg(chunk_buckets=[256], max_kv_capacity=256)
    with pytest.raises(ValueError, match="max_kv_capacity"):
        policy_from_config(
            cfg, kv_bucket=32, chunk_tokens=4, max_batch_size=4,
            prompt_bucket=8,
        )
    # One prompt_bucket of room is the floor of validity.
    policy_from_config(
        _scfg(chunk_buckets=[248], max_kv_capacity=256), kv_bucket=32,
        chunk_tokens=4, max_batch_size=4, prompt_bucket=8,
    )


@pytest.mark.serving
def test_policy_total_shape_bound_includes_widths():
    """The cap check covers prefill/extend widths, not just the decode
    product: a config whose decode product fits but whose total worst
    case (decode + prefill + extend) does not must refuse."""
    kw = dict(
        quantum=32, capacity_buckets=[64, 128, 256],
        chunk_buckets=[8], row_buckets=[1, 2, 4],
    )
    # decode product = 9; widths add 3*4*1 + 4*3 = 24 -> 33 total.
    ShapeBucketPolicy(width_buckets=[8, 16, 32, 248], max_shapes=33, **kw)
    with pytest.raises(ValueError, match="max_compiled_shapes"):
        ShapeBucketPolicy(
            width_buckets=[8, 16, 32, 248], max_shapes=32, **kw
        )


# ------------------------------------------------------------- prefix trie


@pytest.mark.serving
def test_prefix_trie_longest_and_prune():
    trie = PrefixTrie()
    trie.insert("a", np.asarray([1, 2, 3, 4]))
    trie.insert("b", np.asarray([1, 2, 9]))
    rid, depth = trie.longest([1, 2, 3, 7, 7])
    assert (rid, depth) == ("a", 3)
    rid, depth = trie.longest([1, 2, 9, 9])
    assert (rid, depth) == ("b", 3)
    assert trie.longest([5, 5]) == (None, 0)
    trie.remove("a", np.asarray([1, 2, 3, 4]))
    rid, depth = trie.longest([1, 2, 3, 7])
    assert (rid, depth) == ("b", 2)  # a's branch pruned, b still covers 1,2
    trie.remove("b", np.asarray([1, 2, 9]))
    assert trie.longest([1, 2]) == (None, 0)
    assert not trie._root.children  # fully pruned


def _state(nbytes_each: int = 8):
    class _Arr:
        def __init__(self, n):
            self.nbytes = n

    return {"kv_k": _Arr(nbytes_each), "kv_v": _Arr(nbytes_each)}


# ------------------------------------------------- KV store: pins + budgets


@pytest.mark.serving
def test_kv_store_refcounted_pin_survives_eviction():
    kv = KVStateStore(slots=2, bytes_budget=1 << 30, prefix_reuse=True)
    for i in range(3):
        kv.put(f"r{i}", ReqState(_state(), cur_len=4, version=0,
                                 tokens=np.asarray([9, 9, 9, i])))
        time.sleep(0.002)  # distinct last_used ordering
    # r0 is LRU; pin it via acquire_prefix and overfill the store.
    got = kv.acquire_prefix([9, 9, 9, 0, 5], version=0, min_len=2)
    assert got is not None
    rid, shared = got
    assert rid == "r0" and shared == 4
    kv.evict()
    # r0 was pinned: eviction must drop the other LRU entries instead.
    assert kv.get("r0") is not None and kv.count <= 2
    kv.release(rid)
    assert kv.get("r0").pins == 0
    kv.get("r0").last_used = 0.0  # age it: acquire bumped recency
    kv.put("r9", ReqState(_state(), cur_len=4, version=0,
                          tokens=np.asarray([1, 1, 1, 1])))
    kv.evict()
    assert kv.count <= 2
    assert kv.get("r0") is None  # released: normal LRU victim


@pytest.mark.serving
def test_kv_store_bytes_budget_and_version_gate():
    kv = KVStateStore(slots=100, bytes_budget=40, prefix_reuse=True)
    for i in range(4):  # 16 bytes each
        kv.put(f"r{i}", ReqState(_state(8), cur_len=2, version=0,
                                 tokens=np.asarray([3, i])))
    kv.evict()
    assert kv.nbytes <= 40 and kv.count == 2
    # version mismatch: no donor even though the trie matches
    assert kv.acquire_prefix([3, 3], version=1, min_len=1) is None
    kv.clear()
    assert kv.count == 0 and kv.acquire_prefix([3, 3], 0, 1) is None


@pytest.mark.serving
def test_acquire_prefix_full_match_clamp():
    kv = KVStateStore(slots=8, bytes_budget=1 << 30, prefix_reuse=True)
    kv.put("d", ReqState(_state(), cur_len=6, version=0,
                         tokens=np.asarray([1, 2, 3, 4, 5, 6])))
    # Query equal to a PREFIX of the donor: must leave >= 1 suffix token
    # to recompute last_logits -> shared clamps to len(query) - 1.
    rid, shared = kv.acquire_prefix([1, 2, 3, 4], version=0, min_len=1)
    assert rid == "d" and shared == 3
    kv.release("d")
    # Query equal to the donor's FULL sequence: exact match, logits usable.
    rid, shared = kv.acquire_prefix([1, 2, 3, 4, 5, 6], version=0, min_len=1)
    assert rid == "d" and shared == 6
    kv.release("d")
    # min_len gate
    assert kv.acquire_prefix([1, 9], version=0, min_len=4) is None


# ----------------------------------------------- queue: admission, priority


@pytest.mark.serving
def test_queue_admission_reject_and_priority_order():
    q = ServingQueue(_scfg(
        queue_limit_rollout=2, queue_limit_interactive=1,
        retry_after_secs=0.7,
    ))
    q.put("r1", "rollout")
    q.put("r2", "rollout")
    with pytest.raises(AdmissionReject) as ei:
        q.put("r3", "rollout")
    assert ei.value.retry_after == pytest.approx(0.7)
    assert ei.value.cls == "rollout" and ei.value.limit == 2
    q.put("e1", "eval")
    q.put("i1", "interactive")
    with pytest.raises(AdmissionReject):
        q.put("i2", "interactive")
    # Priority drain: interactive > eval > rollout, FIFO within a class.
    assert q.drain(10) == ["i1", "e1", "r1", "r2"]
    assert q.empty()


@pytest.mark.serving
def test_queue_rollout_reserved_share_under_contention():
    """Sustained interactive load cannot starve rollout: every drained
    batch reserves min_rollout_share of its slots for waiting rollout
    requests (else training data production stalls while serving SLOs
    look healthy); share=0 restores strict priority."""
    q = ServingQueue(_scfg(min_rollout_share=0.25))
    for i in range(8):
        q.put(f"i{i}", "interactive")
    for i in range(4):
        q.put(f"r{i}", "rollout")
    # 3 interactive by priority + 1 reserved rollout slot, per batch.
    assert q.drain(4) == ["i0", "i1", "i2", "r0"]
    assert q.drain(4) == ["i3", "i4", "i5", "r1"]
    # Reservation never over-pops: once rollout runs dry mid-batch the
    # remaining slots flow back to priority order.
    assert q.drain(8) == ["i6", "i7", "r2", "r3"]

    q0 = ServingQueue(_scfg(min_rollout_share=0.0))
    q0.put("r", "rollout")
    q0.put("i", "interactive")
    assert q0.drain(1) == ["i"]


@pytest.mark.serving
def test_queue_disabled_is_unbounded_fifo():
    q = ServingQueue(ServingConfig(enabled=False, queue_limit_rollout=1))
    for i in range(5):
        q.put(i, "interactive" if i % 2 else "rollout")
    assert q.drain(10) == [0, 1, 2, 3, 4]


@pytest.mark.serving
def test_queue_async_get_wakes_on_put():
    async def main():
        q = ServingQueue(_scfg())
        getter = asyncio.create_task(q.get())
        await asyncio.sleep(0.01)
        q.put("x", "rollout")
        assert await asyncio.wait_for(getter, 2) == "x"

    asyncio.run(main())


@pytest.mark.serving
def test_admit_planned_len_rejects_infeasible_up_front():
    """A chunked client's full remaining budget is feasibility-checked at
    chunk 1 (vLLM's prompt+max_tokens admission): a generation whose
    eventual total sequence cannot fit the widest width bucket 413s now,
    instead of decoding up to the capacity ceiling and abandoning
    mid-flight with every accumulated token discarded."""
    eng = ServingEngine(
        _scfg(), kv_slots=4, kv_bytes_budget=1 << 20, kv_bucket=32,
        chunk_tokens=4, max_batch_size=4, prompt_bucket=8,
    )
    widest = eng.shapes.width_buckets[-1]
    # The prompt alone fits; the planned total cannot.
    with pytest.raises(PromptTooLong):
        eng.admit(object(), "rollout", prompt_len=8,
                  planned_len=widest + 2)
    assert eng.queue.empty()
    # Same prompt with a feasible budget admits (widest prompt_bucket
    # multiple under the width ceiling, worst-case no-EOS final chunk).
    feasible = widest // eng.prompt_bucket * eng.prompt_bucket
    eng.admit(object(), "rollout", prompt_len=8, planned_len=feasible)
    assert eng.queue.depth("rollout") == 1
    # No planned_len (single-shot / third-party client): only the prompt
    # is checked — the pre-existing behavior.
    eng.admit(object(), "interactive", prompt_len=8)
    assert eng.queue.depth("interactive") == 1


@pytest.mark.serving
def test_normalize_class():
    assert normalize_class("interactive") == "interactive"
    assert normalize_class("bogus") == "rollout"
    assert normalize_class(None) == "rollout"


# ------------------------------------------------- real-server integration

jax = pytest.importorskip("jax")


@pytest.fixture(scope="module")
def tiny_model():
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config

    cfg = tiny_config(vocab_size=97)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _server(tiny_model, serving_cfg=None, **kw):
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )

    mcfg, params = tiny_model
    cfg = GenerationServerConfig(
        experiment=EXP, trial=TRIAL, chunk_tokens=4, prompt_bucket=8,
        kv_bucket=32, batch_window_ms=1,
        serving=serving_cfg or ServingConfig(), **kw,
    )
    return GenerationServer(cfg, mcfg, params)


def _gen_body(prompt, rid, cls="rollout", max_tokens=4, greedy=True):
    return {
        "prompt_ids": [int(t) for t in prompt],
        "rid": rid,
        "class": cls,
        "gconfig": {"greedy": greedy, "max_new_tokens": max_tokens},
        "max_tokens": max_tokens,
    }


class _Req:
    def __init__(self, d):
        self._d = d

    async def json(self):
        return self._d


@pytest.mark.serving
@pytest.mark.timeout(120)
def test_admission_reject_http_429(tiny_model):
    """Handler-level: with the runner stopped, the class queue fills to
    its limit and the next request gets 429 + Retry-After, while other
    classes still admit."""
    srv = _server(tiny_model, _scfg(
        queue_limit_rollout=2, retry_after_secs=0.3,
    ))

    async def main():
        hung = [
            asyncio.create_task(srv.handle_generate(
                _Req(_gen_body([5, 6, 7], f"q{i}"))
            ))
            for i in range(2)
        ]
        await asyncio.sleep(0.05)  # both enqueued, nothing drains
        resp = await srv.handle_generate(_Req(_gen_body([5, 6, 7], "q2")))
        assert resp.status == 429
        # RFC 9110 delay-seconds: integer header, precise float in body.
        assert resp.headers["Retry-After"] == "1"
        assert b'"retry_after": 0.3' in resp.body
        assert b"admission" in resp.body
        # higher-priority class has its own (non-full) queue
        resp2_task = asyncio.create_task(srv.handle_generate(
            _Req(_gen_body([5, 6, 7], "q3", cls="interactive"))
        ))
        await asyncio.sleep(0.05)
        assert srv._queue.depth("interactive") == 1
        for t in hung + [resp2_task]:
            t.cancel()
        await asyncio.gather(*hung, resp2_task, return_exceptions=True)

    asyncio.run(main())


@pytest.mark.serving
@pytest.mark.timeout(120)
def test_prompt_too_long_413(tiny_model):
    srv = _server(tiny_model, _scfg(max_kv_capacity=64))

    async def main():
        resp = await srv.handle_generate(
            _Req(_gen_body(list(range(2, 70)), "long"))
        )
        assert resp.status == 413
        assert b"prompt_too_long" in resp.body

    asyncio.run(main())


@pytest.mark.serving
@pytest.mark.timeout(300)
def test_class_priority_under_contention(tiny_model):
    """A rollout backlog deeper than one batch is queued before an
    interactive request arrives; the interactive request still rides the
    FIRST formed batch (priority drain) and completes before the backlog
    clears."""
    srv = _server(tiny_model, _scfg(), max_batch_size=2)

    async def main():
        order = []

        async def one(body, tag):
            resp = await srv.handle_generate(_Req(body))
            assert resp.status == 200
            order.append(tag)

        tasks = [
            asyncio.create_task(one(_gen_body([2, 3, 4], f"r{i}"), f"r{i}"))
            for i in range(5)
        ]
        await asyncio.sleep(0.05)  # all rollouts enqueued (runner not up)
        tasks.append(asyncio.create_task(one(
            _gen_body([2, 3, 4], "i0", cls="interactive"), "i0"
        )))
        await asyncio.sleep(0.05)
        srv._runner_task = asyncio.create_task(srv._runner())
        await asyncio.gather(*tasks)
        srv._runner_task.cancel()
        await asyncio.gather(srv._runner_task, return_exceptions=True)
        # interactive arrived LAST but finished in the first decode batch
        assert order.index("i0") < 2, order
        assert order.index("i0") < order.index("r4")

    asyncio.run(main())


@pytest.mark.serving
@pytest.mark.timeout(300)
def test_randomized_shape_bound_and_prometheus_scrape(tmp_path, tiny_model):
    """Acceptance: a randomized mixed-class workload keeps the distinct
    compiled-shape count <= the configured cap, and the gauge (plus the
    kv_states/kv_bytes gauges and per-class SLO histograms) is visible in
    a REAL Prometheus scrape of a running generation server."""
    from areal_tpu.api.train_config import TelemetryConfig

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    scfg = _scfg(max_kv_capacity=128, max_compiled_shapes=48)
    srv = _server(
        tiny_model, scfg, max_batch_size=4,
        telemetry=TelemetryConfig(enabled=True, flush_interval_secs=30),
    )

    async def main():
        import aiohttp

        url = await srv.start()
        rng = np.random.RandomState(7)
        async with aiohttp.ClientSession() as sess:
            async def one(i):
                cls = REQUEST_CLASSES[i % 3]
                plen = int(rng.randint(3, 40))
                budget = int(rng.randint(1, 7))
                body = _gen_body(
                    rng.randint(2, 90, plen).tolist(), f"w{i}", cls=cls,
                    max_tokens=budget, greedy=False,
                )
                async with sess.post(f"{url}/generate", json=body) as r:
                    assert r.status == 200
                    await r.json()

            for start in range(0, 24, 8):  # waves -> varied batch mixes
                await asyncio.gather(
                    *[one(i) for i in range(start, start + 8)]
                )
            assert srv.serving.shapes.distinct_shapes <= \
                scfg.max_compiled_shapes
            # The scrape must go over the real socket (acceptance: gauge
            # visible in a REAL Prometheus scrape) — aiohttp, because a
            # blocking urllib call on the loop would deadlock the server.
            async with sess.get(f"{url}/metrics") as r:
                assert r.status == 200
                prom = await r.text()
        await srv.stop()
        return prom

    prom = asyncio.run(main())
    assert "# TYPE areal_serving_compiled_shapes gauge" in prom
    assert "areal_genserver_kv_states" in prom
    assert "areal_genserver_kv_bytes" in prom
    # per-class SLO histograms through the telemetry registry
    for cls in REQUEST_CLASSES:
        assert f"areal_serving_{cls}_queue_wait_secs_bucket" in prom
        assert f"areal_serving_{cls}_ttfc_secs_bucket" in prom
    for ln in prom.splitlines():  # every sample line parses
        if ln and not ln.startswith("#"):
            float(ln.rpartition(" ")[2])


# ------------------------------------------------ manager: class routing


@pytest.mark.serving
def test_manager_class_aware_routing():
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
    )

    mgr = GserverManager(GserverManagerConfig(experiment=EXP, trial=TRIAL))
    mgr.servers = ["http://a", "http://b"]
    mgr._inflight = {u: 0 for u in mgr.servers}

    import json

    async def call(handler, body):
        return json.loads((await handler(_Req(body))).text)

    async def main():
        # Load server a with rollout traffic via round-robin.
        r1 = await call(mgr.handle_schedule_request, {"class": "rollout"})
        assert r1["class"] == "rollout"
        # Interactive routes by least interactive+eval load: the two
        # requests must land on DIFFERENT servers.
        i1 = await call(mgr.handle_schedule_request,
                        {"class": "interactive"})
        i2 = await call(mgr.handle_schedule_request,
                        {"class": "interactive"})
        assert {i1["url"], i2["url"]} == {"http://a", "http://b"}
        # Per-class bookkeeping visible in /metrics.json
        mj = await call(mgr.handle_metrics_json, {})
        assert mj["inflight_by_class"]["interactive"] == 2
        assert mj["inflight_by_class"]["rollout"] == 1
        # Release by lease drops the right class count.
        await call(mgr.handle_release, {"lease_id": i1["lease_id"]})
        mj = await call(mgr.handle_metrics_json, {})
        assert mj["inflight_by_class"]["interactive"] == 1
        # Legacy empty-body schedule still works (defaults to rollout).
        r2 = await call(mgr.handle_schedule_request, {})
        assert r2["class"] == "rollout"

    asyncio.run(main())


@pytest.mark.serving
def test_manager_ambiguous_by_url_release_keeps_class_gauge_in_step():
    """Legacy by-url release with MULTIPLE leases on the url retires no
    lease (guessing could delete another client's) but still decrements
    _inflight — the per-class gauge must move with it, not drift above
    the real load until TTL expiry."""
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
    )

    mgr = GserverManager(GserverManagerConfig(experiment=EXP, trial=TRIAL))
    mgr.servers = ["http://a"]
    mgr._inflight = {u: 0 for u in mgr.servers}

    import json

    async def call(handler, body):
        return json.loads((await handler(_Req(body))).text)

    async def main():
        r1 = await call(mgr.handle_schedule_request, {"class": "rollout"})
        r2 = await call(mgr.handle_schedule_request, {"class": "rollout"})
        assert r1["url"] == r2["url"] == "http://a"
        assert mgr._inflight["http://a"] == 2
        await call(mgr.handle_release, {"url": "http://a"})
        await call(mgr.handle_release, {"url": "http://a"})
        assert mgr._inflight["http://a"] == 0
        mj = await call(mgr.handle_metrics_json, {})
        assert mj["inflight_by_class"]["rollout"] == 0

    asyncio.run(main())


# ------------------------------------------------ client: 429 backpressure


@pytest.mark.serving
@pytest.mark.timeout(120)
def test_client_backs_off_on_429_without_burning_failover():
    """A 429 from admission control honors Retry-After on its own budget:
    the chunk completes after the throttle clears and n_failovers stays 0."""
    from aiohttp import web

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.retry import RetryPolicy
    from areal_tpu.system.partial_rollout import PartialRolloutClient

    state = {"n": 0}

    async def fake_generate(request):
        state["n"] += 1
        if state["n"] <= 2:
            return web.json_response(
                {"ok": False, "reason": "admission", "retry_after": 0.01},
                status=429,
            )
        return web.json_response({
            "output_ids": [7, 1], "output_logprobs": [-0.1, -0.2],
            "finished": True, "version": 0,
        })

    async def main():
        import aiohttp

        app = web.Application()
        app.router.add_post("/generate", fake_generate)
        gen_runner = web.AppRunner(app)
        await gen_runner.setup()
        gport = network.find_free_port()
        await web.TCPSite(gen_runner, "127.0.0.1", gport).start()
        gurl = f"http://127.0.0.1:{gport}"

        mgr_app = web.Application()

        async def sched(request):
            d = await request.json()
            assert d.get("class") == "interactive"
            return web.json_response({"url": gurl, "version": 0})

        async def ok(request):
            return web.json_response({"ok": True})

        mgr_app.router.add_post("/schedule_request", sched)
        mgr_app.router.add_post("/release", ok)
        mgr_app.router.add_post("/renew", ok)
        mgr_runner = web.AppRunner(mgr_app)
        await mgr_runner.setup()
        mport = network.find_free_port()
        await web.TCPSite(mgr_runner, "127.0.0.1", mport).start()

        async with aiohttp.ClientSession() as sess:
            client = PartialRolloutClient(
                f"http://127.0.0.1:{mport}", sess, chunk_tokens=4,
                retry=RetryPolicy(max_attempts=2, base_delay_secs=0.01),
                request_class="interactive",
            )
            res = await client.generate_one(
                [2, 3], GenerationHyperparameters(max_new_tokens=4)
            )
        assert res.output_ids == [7, 1]
        assert client.n_failovers == 0 and client.n_abandoned == 0
        assert state["n"] == 3
        await gen_runner.cleanup()
        await mgr_runner.cleanup()

    asyncio.run(main())


@pytest.mark.serving
@pytest.mark.timeout(120)
def test_client_clamps_oversized_retry_after_to_budget():
    """The server-supplied Retry-After is operator-set and unbounded; one
    oversized hint must not sleep a rollout past the no_server_wait_secs
    abandonment ceiling. With retry_after=3600 and a 0.2 s budget the
    client abandons in well under a second."""
    from aiohttp import web

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.retry import RetryPolicy
    from areal_tpu.system.partial_rollout import (
        GenerationAbandonedError,
        PartialRolloutClient,
    )

    async def always_429(request):
        return web.json_response(
            {"ok": False, "reason": "admission", "retry_after": 3600.0},
            status=429,
        )

    async def main():
        import aiohttp

        app = web.Application()
        app.router.add_post("/generate", always_429)
        gen_runner = web.AppRunner(app)
        await gen_runner.setup()
        gport = network.find_free_port()
        await web.TCPSite(gen_runner, "127.0.0.1", gport).start()
        gurl = f"http://127.0.0.1:{gport}"

        mgr_app = web.Application()

        async def sched(request):
            return web.json_response({"url": gurl, "version": 0})

        async def ok(request):
            return web.json_response({"ok": True})

        mgr_app.router.add_post("/schedule_request", sched)
        mgr_app.router.add_post("/release", ok)
        mgr_app.router.add_post("/renew", ok)
        mgr_runner = web.AppRunner(mgr_app)
        await mgr_runner.setup()
        mport = network.find_free_port()
        await web.TCPSite(mgr_runner, "127.0.0.1", mport).start()

        async with aiohttp.ClientSession() as sess:
            client = PartialRolloutClient(
                f"http://127.0.0.1:{mport}", sess, chunk_tokens=4,
                retry=RetryPolicy(max_attempts=2, base_delay_secs=0.01),
                no_server_wait_secs=0.2,
            )
            t0 = time.monotonic()
            with pytest.raises(GenerationAbandonedError):
                await client.generate_one(
                    [2, 3], GenerationHyperparameters(max_new_tokens=4)
                )
            assert time.monotonic() - t0 < 5.0
        assert client.n_abandoned == 1 and client.n_failovers == 0
        await gen_runner.cleanup()
        await mgr_runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------ config surface


@pytest.mark.serving
def test_serving_config_cli_overrides():
    from areal_tpu.api import cli_args as CA

    cfg = CA.BaseExperimentConfig()
    CA.apply_overrides(cfg, [
        "serving.enabled=true",
        "serving.chunk_buckets=8,16",
        "serving.queue_limit_interactive=7",
        "serving.max_compiled_shapes=32",
    ])
    assert cfg.serving.enabled is True
    assert cfg.serving.chunk_buckets == [8, 16]
    assert cfg.serving.queue_limit_interactive == 7
    d = CA.to_yaml_dict(cfg)
    assert d["serving"]["max_compiled_shapes"] == 32
