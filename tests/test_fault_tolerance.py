"""Generation-fleet fault tolerance: chaos + regression tests.

Covers the failure-recovery subsystem (docs/fault_tolerance.md):
 - retry policy / fault injector primitives (base/retry.py)
 - lease release/expiry accounting (no double decrement)
 - weight fanout with an unresponsive server: bounded by the per-server
   timeout budget, dead server evicted, version still advances
 - health-check eviction and re-admission with weight reconcile
 - client chunk failover: replay from accumulated tokens on a new route
 - rollout abandonment: clean /finish_rollout, worker survives
 - full chaos run: one of two real generation servers killed mid-run
 - launcher-level supervision (system/supervisor.py): SIGKILL respawn,
   unexpected-clean-exit detection, backoff + crash-loop circuit
   breaker, ghost-key clearing, graceful drain, liveness leases
   (name_resolve keepalive + heartbeats), crash-safe ConsumedLog

Every test is bounded to seconds: failures come from the FaultInjector,
tiny aiohttp fakes, in-process fake workers, or fake clocks/processes —
never from real TTLs or long sleeps. The two launcher-level e2e chaos
runs (SIGKILL mid-experiment, SIGTERM drain + resume) spawn a complete
async-PPO experiment and are behind the ``slow`` marker like the other
full-experiment launches.
"""

import asyncio
import os
import signal
import threading
import time

import pytest

from areal_tpu.base import name_resolve, names, network
from areal_tpu.base.retry import (
    FaultInjected,
    FaultInjector,
    RetryPolicy,
    aretry,
)
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    _ServerHealth,
)
from areal_tpu.system.partial_rollout import (
    GenerationAbandonedError,
    NoHealthyServersError,
    PartialRolloutClient,
)

EXP, TRIAL = "faulttest", "t0"


class _Req:
    """Minimal aiohttp-request stand-in for direct handler calls."""

    def __init__(self, d=None):
        self._d = d or {}

    async def json(self):
        return self._d


def _mgr(**kw) -> GserverManager:
    cfg = GserverManagerConfig(experiment=EXP, trial=TRIAL, **kw)
    return GserverManager(cfg)


async def _start_app(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    port = network.find_free_port()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner, f"http://127.0.0.1:{port}"


# ---------------------------------------------------------------- retry.py


@pytest.mark.chaos
def test_retry_policy_delays_capped():
    p = RetryPolicy(max_attempts=5, base_delay_secs=0.1, max_delay_secs=0.5,
                    multiplier=2.0)
    assert p.delay(1) == pytest.approx(0.1)
    assert p.delay(2) == pytest.approx(0.2)
    assert p.delay(3) == pytest.approx(0.4)
    assert p.delay(4) == pytest.approx(0.5)  # capped
    assert p.delay(10) == pytest.approx(0.5)


@pytest.mark.chaos
def test_aretry_retries_then_succeeds_and_gives_up():
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ValueError("boom")
        return "ok"

    pol = RetryPolicy(max_attempts=3, base_delay_secs=0.001)
    assert asyncio.run(aretry(flaky, pol)) == "ok"
    assert calls["n"] == 3

    async def dead():
        raise ValueError("always")

    with pytest.raises(ValueError):
        asyncio.run(aretry(dead, pol))


@pytest.mark.chaos
def test_fault_injector_arming():
    inj = FaultInjector()
    inj.arm("p", times=2)
    with pytest.raises(FaultInjected):
        inj.maybe_fail("p")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("p")
    inj.maybe_fail("p")  # exhausted: no-op
    assert inj.fired["p"] == 2
    # predicate-gated, unlimited until disarm
    inj.arm("q", times=-1, when=lambda ctx: ctx.get("url") == "dead")
    inj.maybe_fail("q", url="alive")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("q", url="dead")
    inj.disarm("q")
    inj.maybe_fail("q", url="dead")


# ------------------------------------------------------- lease accounting


@pytest.mark.chaos
def test_release_by_url_drops_lease_no_double_decrement():
    """Regression: the legacy by-url /release decremented inflight but left
    the lease alive, so its later TTL expiry decremented the SAME slot a
    second time — corrupting inflight while another request was running."""

    async def main():
        mgr = _mgr(lease_ttl_secs=60.0)
        url = "http://127.0.0.1:7777"
        mgr.servers = [url]
        mgr._inflight = {url: 0}
        mgr.health = {url: _ServerHealth()}

        await mgr.handle_schedule_request(_Req())  # request A
        assert mgr._inflight[url] == 1 and len(mgr._leases) == 1
        lease_a = next(iter(mgr._leases))

        # client releases A by url (legacy path, no lease_id)
        await mgr.handle_release(_Req({"url": url}))
        assert mgr._inflight[url] == 0
        assert lease_a not in mgr._leases  # the fix: lease retired too

        await mgr.handle_schedule_request(_Req())  # request B, in flight
        assert mgr._inflight[url] == 1

        # Force lease-expiry sweep. With the orphaned lease A still alive
        # (old bug) this would decrement B's slot to 0 while B is running.
        mgr._expire_leases()
        assert mgr._inflight[url] == 1

        # B's own expiry still works exactly once.
        lid_b = next(iter(mgr._leases))
        u, _ = mgr._leases[lid_b]
        mgr._leases[lid_b] = (u, time.monotonic() - 1)
        mgr._expire_leases()
        assert mgr._inflight[url] == 0 and not mgr._leases

    asyncio.run(main())


@pytest.mark.chaos
def test_release_by_lease_id_after_eviction_is_harmless():
    async def main():
        mgr = _mgr()
        url = "http://127.0.0.1:7777"
        mgr.servers = [url]
        mgr._inflight = {url: 0}
        mgr.health = {url: _ServerHealth()}
        await mgr.handle_schedule_request(_Req())
        lid = next(iter(mgr._leases))
        mgr._evict(url, "test")
        assert not mgr._leases and url not in mgr._inflight
        # late release from the client of the evicted server: no KeyError,
        # no negative counts
        await mgr.handle_release(_Req({"lease_id": lid, "url": url}))
        await mgr.handle_release(_Req({"url": url}))

    asyncio.run(main())


# ------------------------------------------------------------ weight fanout


@pytest.mark.chaos
def test_fanout_evicts_unresponsive_server_within_budget():
    """One acking server + one that accepts but never replies: the fanout
    must finish within the per-server timeout budget, evict the hung
    server (dropping its leases), bump the version, and route only to the
    survivor."""
    from aiohttp import web

    async def main():
        acks = []

        async def ok_update(req):
            acks.append(await req.json())
            return web.json_response({"ok": True})

        async def hang(req):
            await asyncio.sleep(60)

        live_app = web.Application()
        live_app.router.add_post("/update_weights", ok_update)
        live_runner, live_url = await _start_app(live_app)
        hung_app = web.Application()
        hung_app.router.add_post("/update_weights", hang)
        hung_runner, hung_url = await _start_app(hung_app)
        try:
            mgr = _mgr(
                fanout_timeout_secs=0.4,
                fanout_retry=RetryPolicy(max_attempts=2,
                                         base_delay_secs=0.05),
            )
            mgr.servers = sorted([live_url, hung_url])
            mgr._inflight = {u: 0 for u in mgr.servers}
            mgr.health = {u: _ServerHealth() for u in mgr.servers}
            # an in-flight lease on the hung server must drain on eviction
            while True:
                await mgr.handle_schedule_request(_Req())
                if any(u == hung_url for u, _ in mgr._leases.values()):
                    break

            import aiohttp

            budget = mgr.cfg.fanout_retry.max_attempts * (
                mgr.cfg.fanout_timeout_secs
                + mgr.cfg.fanout_retry.max_delay_secs
            )
            t0 = time.monotonic()
            async with aiohttp.ClientSession() as sess:
                acked = await mgr.fanout_weights(sess, 1, "/tmp/unused")
            elapsed = time.monotonic() - t0
            assert elapsed < budget + 1.0

            assert acked == [live_url]
            assert mgr.version == 1  # acked servers ⇒ version advanced
            assert [d["version"] for d in acks] == [1]
            assert hung_url not in mgr.servers
            assert not mgr.health[hung_url].routable
            assert all(u != hung_url for u, _ in mgr._leases.values())
            assert hung_url not in mgr._inflight
            for _ in range(4):  # no further routing to the evicted server
                assert mgr._pick_server() == live_url
        finally:
            await live_runner.cleanup()
            await hung_runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_fanout_total_failure_holds_version_and_fleet():
    """If NO server acks, the failure is systemic (bad/late weight path) —
    the version must NOT advance and the fleet must NOT be mass-evicted
    (that would drop every lease and flap); the watcher retries next poll
    and genuinely dead servers are the health loop's responsibility."""

    async def main():
        import aiohttp

        mgr = _mgr(
            fanout_timeout_secs=0.2,
            fanout_retry=RetryPolicy(max_attempts=1, base_delay_secs=0.01),
        )
        dead = "http://127.0.0.1:1"
        mgr.servers = [dead]
        mgr._inflight = {dead: 0}
        mgr.health = {dead: _ServerHealth()}
        async with aiohttp.ClientSession() as sess:
            acked = await mgr.fanout_weights(sess, 5, "/tmp/unused")
        assert acked == [] and mgr.version == 0
        assert mgr.servers == [dead]  # fleet held, not mass-evicted

    asyncio.run(main())


# --------------------------------------------- health eviction/re-admission


@pytest.mark.chaos
def test_health_eviction_and_readmission_with_reconcile(tmp_name_resolve):
    """/health failures evict after the threshold; a recovered server is
    re-admitted only after its weights are reconciled to the manager's
    current version; a newly registered server joins through the same
    gate."""
    from aiohttp import web

    async def main():
        state = {"alive": True, "version": 0, "updates": []}

        async def health(req):
            if not state["alive"]:
                return web.Response(status=500)
            return web.json_response({"ok": True,
                                      "version": state["version"]})

        async def update(req):
            d = await req.json()
            state["updates"].append(d)
            state["version"] = d["version"]
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_get("/health", health)
        app.router.add_post("/update_weights", update)
        runner, url = await _start_app(app)
        name_resolve.add(names.gen_servers(EXP, TRIAL, "flaky"), url,
                         replace=True)
        try:
            import aiohttp

            mgr = _mgr(health_failure_threshold=2,
                       health_check_timeout_secs=0.5)
            mgr.servers = [url]
            mgr._inflight = {url: 0}
            mgr.health = {url: _ServerHealth()}

            async def settle(pred, sweeps=20):
                # re-admission reconciles run detached from the sweep;
                # sweep + poll until the predicate holds
                for _ in range(sweeps):
                    await mgr.check_fleet(sess)
                    for _ in range(20):
                        if pred():
                            return True
                        await asyncio.sleep(0.02)
                return pred()

            timeout = aiohttp.ClientTimeout(total=0.5)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                await mgr.check_fleet(sess)
                assert url in mgr.servers  # healthy: stays

                state["alive"] = False
                await mgr.check_fleet(sess)
                assert url in mgr.servers  # 1 failure < threshold
                await mgr.check_fleet(sess)
                assert url not in mgr.servers  # threshold hit: evicted
                assert not mgr.health[url].routable

                # manager moved on to v3 while the server was down
                mgr.version = 3
                state["alive"] = True
                assert await settle(lambda: url in mgr.servers)
                # re-admitted AND reconciled to v3 before routing
                assert state["updates"][-1]["version"] == 3
                assert mgr.health[url].acked_version == 3
                assert mgr._inflight[url] == 0

                # a brand-new registration joins through the health gate
                app2 = web.Application()
                app2.router.add_get("/health", health)
                app2.router.add_post("/update_weights", update)
                runner2, url2 = await _start_app(app2)
                try:
                    name_resolve.add(
                        names.gen_servers(EXP, TRIAL, "late"), url2,
                        replace=True,
                    )
                    assert await settle(lambda: url2 in mgr.servers)

                    # deregistration prunes the health map entirely
                    name_resolve.delete(names.gen_servers(EXP, TRIAL,
                                                          "late"))
                    await mgr.check_fleet(sess)
                    assert url2 not in mgr.servers
                    assert url2 not in mgr.health
                finally:
                    await runner2.cleanup()
        finally:
            await runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------------- client failover


def _fake_gen_app(state):
    """Deterministic fake generation server: token i of a request is
    100+tokens_done+i, so replay-from-accumulated is directly observable
    in the output sequence."""
    from aiohttp import web

    async def generate(req):
        d = await req.json()
        td = int(d["tokens_done"])
        mt = int(d["max_tokens"])
        state["calls"].append(td)
        toks = list(range(100 + td, 100 + td + mt))
        return web.json_response({
            "output_ids": toks, "output_logprobs": [0.0] * mt,
            "finished": False, "version": 0,
        })

    async def health(req):
        return web.json_response({"ok": True, "version": 0})

    app = web.Application()
    app.router.add_post("/generate", generate)
    app.router.add_get("/health", health)
    return app


@pytest.mark.chaos
def test_client_failover_replays_from_accumulated_tokens(tmp_name_resolve):
    """A chunk failure mid-generation re-schedules and RESUMES: the final
    token sequence is contiguous (no lost or repeated tokens) and the
    failed chunk was re-requested at the same tokens_done."""
    from areal_tpu.api.model import GenerationHyperparameters

    async def main():
        import aiohttp

        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, max_head_offpolicyness=100,
                   health_check_interval_secs=30.0)
        mgr_url = await mgr.start()
        try:
            inj = FaultInjector()
            # fail exactly one attempt, at the second chunk boundary
            inj.arm("generate", times=1,
                    when=lambda ctx: ctx["tokens_done"] == 4)
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=4, base_delay_secs=0.01),
                    fault_injector=inj,
                )
                res = await client.generate_one(
                    [1, 2, 3],
                    GenerationHyperparameters(max_new_tokens=8),
                )
            assert res.output_ids == list(range(100, 108))
            assert client.n_failovers == 1 and inj.fired["generate"] == 1
            assert state["calls"] == [0, 4]  # chunk 2 replayed at td=4
            assert res.n_chunks == 2
            # quota accounting survived the failover: no leaked leases
            assert not mgr._leases
            assert all(v == 0 for v in mgr._inflight.values())
        finally:
            await mgr.stop()
            await runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_generation_abandoned_after_max_attempts(tmp_name_resolve):
    async def main():
        import aiohttp

        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, health_check_interval_secs=30.0)
        mgr_url = await mgr.start()
        try:
            from areal_tpu.api.model import GenerationHyperparameters

            inj = FaultInjector()
            inj.arm("generate", times=-1)  # fleet permanently dead
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=3, base_delay_secs=0.01),
                    fault_injector=inj,
                )
                with pytest.raises(GenerationAbandonedError):
                    await client.generate_one(
                        [1, 2], GenerationHyperparameters(max_new_tokens=8)
                    )
            assert inj.fired["generate"] == 3
            assert client.n_abandoned == 1
            # every scheduled route was released on its failure
            assert all(v == 0 for v in mgr._inflight.values())
            assert not mgr._leases
        finally:
            await mgr.stop()
            await runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_empty_fleet_waits_on_own_budget_not_failover_attempts():
    """An all-evicted fleet returns 503s in milliseconds; those must burn
    the (longer) no-server wait budget, not the chunk-failover attempts —
    and a fleet gap longer than the budget abandons with a clear error."""
    from areal_tpu.api.model import GenerationHyperparameters

    async def main():
        import aiohttp

        mgr = _mgr()  # zero servers: /schedule_request 503s immediately
        runner, mgr_url = await _start_app(mgr.build_app())
        try:
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=3, base_delay_secs=0.01,
                                      max_delay_secs=0.05),
                    no_server_wait_secs=0.2,
                )
                with pytest.raises(NoHealthyServersError):
                    await client._schedule()
                t0 = time.monotonic()
                with pytest.raises(GenerationAbandonedError,
                                   match="no routable"):
                    await client.generate_one(
                        [1, 2], GenerationHyperparameters(max_new_tokens=8)
                    )
                # waited out the no-server budget (not the ~30ms the three
                # failover attempts would have taken)
                assert time.monotonic() - t0 >= 0.2
        finally:
            await runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------ rollout worker survival


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_rollout_worker_abandons_cleanly_never_crashes(tmp_path):
    """With every /generate chunk failing, the worker must abandon each
    rollout after the retry budget — reporting a correct /finish_rollout so
    running_rollouts drains to 0 — and run_async must return, not raise."""
    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.testing import MockTokenizer, make_math_jsonl
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )
    from areal_tpu.system.streams import ZmqPuller

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=4)

    async def main():
        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, max_head_offpolicyness=100,
                   health_check_interval_secs=30.0)
        await mgr.start()
        puller = ZmqPuller(EXP, TRIAL, "trainer")  # pusher blocks without it
        inj = FaultInjector()
        inj.arm("generate", times=-1)
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
            group_size=2, chunk_tokens=4, max_concurrent=2,
            tokenizer=MockTokenizer(), max_rollouts=2,
            retry=RetryPolicy(max_attempts=2, base_delay_secs=0.01),
        ), fault_injector=inj)
        await worker.run_async()  # must NOT raise
        assert worker._abandoned >= 2 and worker._pushed == 0
        # in-flight rollouts beyond max_rollouts drain on the same loop
        for _ in range(200):
            if mgr.running_rollouts == 0 and not mgr._leases:
                break
            await asyncio.sleep(0.05)
        assert mgr.running_rollouts == 0  # no leaked quota
        assert not mgr._leases
        await mgr.stop()
        await runner.cleanup()
        puller.close()

    asyncio.run(main())


@pytest.mark.chaos
@pytest.mark.timeout(300)
def test_chaos_kill_one_of_two_servers_mid_run(tmp_path):
    """THE acceptance chaos run: two real generation servers, one killed
    mid-generation. Interrupted rollouts fail over to the survivor, every
    trajectory is delivered, running_rollouts returns to 0, the worker
    never raises, and the dead server is evicted from routing."""
    import jax

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.testing import MockTokenizer, make_math_jsonl
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )
    from areal_tpu.system.streams import ZmqPuller

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=6)
    mcfg = tiny_config(vocab_size=258, n_layers=2, hidden_dim=32)
    params = transformer.init_params(mcfg, jax.random.PRNGKey(0))

    async def main():
        servers = []
        for sid in ("gen0", "gen1"):
            s = GenerationServer(
                GenerationServerConfig(
                    experiment=EXP, trial=TRIAL, server_id=sid,
                    chunk_tokens=4, prompt_bucket=16, batch_window_ms=2,
                ),
                mcfg, params,
            )
            await s.start()
            servers.append(s)
        victim_url = name_resolve.get(names.gen_servers(EXP, TRIAL, "gen0"))

        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=2,
            train_batch_size=4, max_head_offpolicyness=100,
            realloc_dir=str(tmp_path / "realloc"), weight_poll_secs=5.0,
            health_check_interval_secs=0.1, health_check_timeout_secs=0.5,
            health_failure_threshold=2,
        ))
        await mgr.start()

        puller = ZmqPuller(EXP, TRIAL, "trainer")
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
            group_size=2, chunk_tokens=4, max_concurrent=2,
            tokenizer=MockTokenizer(), max_rollouts=6,
            retry=RetryPolicy(max_attempts=10, base_delay_secs=0.02,
                              max_delay_secs=0.5),
            agent_args={"success_rate_lb": 0.0, "success_rate_ub": 1.0},
        ))
        run_task = asyncio.create_task(worker.run_async())

        # let the run make progress, then crash gen0 mid-generation
        while worker._done < 1:
            await asyncio.sleep(0.05)
            assert not run_task.done() or run_task.exception() is None
        await servers[0].stop(abort=True)

        await run_task  # the worker must complete WITHOUT raising

        # all 6 rollouts delivered (failover, not loss): ≥ 6 × group 2
        assert worker._done >= 6 and worker._abandoned == 0
        assert worker._pushed >= 12
        got = 0
        for _ in range(400):
            if puller.pull(timeout_ms=20) is not None:
                got += 1
            elif got >= 12:
                break
        assert got >= 12  # every trajectory arrived over the push stream

        # in-flight rollouts beyond max_rollouts drain on the same loop
        for _ in range(400):
            if mgr.running_rollouts == 0:
                break
            await asyncio.sleep(0.05)
        assert mgr.running_rollouts == 0  # quota fully drained

        # the dead server ends up evicted from routing (health loop)
        for _ in range(100):
            if victim_url not in mgr.servers:
                break
            await asyncio.sleep(0.1)
        assert victim_url not in mgr.servers
        assert not mgr.health[victim_url].routable
        # survivor still routable
        assert len(mgr.servers) == 1

        await mgr.stop()
        await servers[1].stop()
        puller.close()

    asyncio.run(main())


# ----------------------------------------------------------- reward client


@pytest.mark.chaos
def test_batch_reward_event_loop_contract(monkeypatch):
    """The async rollout path awaits abatch_reward (grading never blocks
    the loop); the SYNC form now refuses to run on a running loop — the
    old silent dedicated-thread bridge blocked every in-flight rollout.
    With an unreachable service both forms fall back to local grading
    with identical results."""
    from areal_tpu.rewards import client as rclient

    monkeypatch.setenv(rclient.SERVICE_ENV, "127.0.0.1:9")
    tasks = [{"task": "math", "generated": "\\boxed{4}",
              "solutions": ["4"]}] * 2

    sync_scores = rclient.batch_reward(tasks, max_retries=0)
    assert len(sync_scores) == 2

    async def inside_loop():
        with pytest.raises(RuntimeError, match="abatch_reward"):
            rclient.batch_reward(tasks, max_retries=0)
        return await rclient.abatch_reward(tasks, max_retries=0)

    async_scores = asyncio.run(inside_loop())
    assert async_scores == sync_scores


# ------------------------------------------------- supervision (ISSUE 9)


def _child_sleep_forever():
    while True:
        time.sleep(0.5)


def _child_exit_zero():
    pass  # immediate clean exit


def _child_exit_three():
    import sys

    sys.exit(3)


class _FakeProc:
    """Process stand-in for deterministic supervisor state-machine tests
    (no spawns, no sleeps)."""

    _next_pid = [1000]

    def __init__(self):
        _FakeProc._next_pid[0] += 1
        self.pid = _FakeProc._next_pid[0]
        self._alive = True
        self.exitcode = None

    def is_alive(self):
        return self._alive

    def die(self, code):
        self._alive = False
        self.exitcode = code

    def join(self, timeout=None):
        pass

    def terminate(self):
        self.die(-15)

    def kill(self):
        self.die(-9)


def _fake_supervisor(clock, **policy_kw):
    from areal_tpu.system.supervisor import RestartPolicy, Supervisor

    sup = Supervisor("supfake", "t0",
                     policy=RestartPolicy(**policy_kw), clock=clock)
    sup._make_proc = lambda spec, incarnation: _FakeProc()
    return sup


@pytest.mark.chaos
def test_supervisor_backoff_and_circuit_breaker(tmp_name_resolve):
    """Deaths of a stateless worker schedule respawns with exponential
    backoff; exceeding max_restarts inside the rolling window opens the
    circuit breaker (SupervisorEscalation); restarts outside the window
    are pruned and do not count."""
    from areal_tpu.system.supervisor import SupervisorEscalation, WorkerSpec

    t = [0.0]
    sup = _fake_supervisor(lambda: t[0], max_restarts=2, window_secs=100.0,
                           backoff_base_secs=1.0, backoff_max_secs=8.0,
                           backoff_multiplier=2.0)
    sup.spawn(WorkerSpec(name="rollout0", kind="rollout",
                         target=_child_sleep_forever))
    e = sup._entries["rollout0"]
    p1 = e.proc

    p1.die(-9)  # SIGKILL
    sup.check()
    assert e.respawn_due == pytest.approx(1.0)  # base backoff
    t[0] = 0.5
    sup.check()
    assert e.proc is p1  # not due yet: no respawn
    t[0] = 1.0
    sup.check()
    assert e.proc is not p1 and e.proc.is_alive()
    assert sup.restart_counts == {"rollout": 1}

    e.proc.die(1)
    sup.check()
    assert e.respawn_due == pytest.approx(1.0 + 2.0)  # doubled
    t[0] = 3.0
    sup.check()
    assert sup.restart_counts == {"rollout": 2}

    # third death inside the window: 2 restarts == max_restarts -> open
    e.proc.die(1)
    with pytest.raises(SupervisorEscalation, match="crash-loop"):
        sup.check()

    # outside the window the history is pruned: a fresh death respawns
    sup2 = _fake_supervisor(lambda: t[0], max_restarts=1, window_secs=10.0,
                            backoff_base_secs=0.5, backoff_max_secs=8.0)
    sup2.spawn(WorkerSpec(name="gen_fleet", kind="gen_fleet",
                          target=_child_sleep_forever))
    e2 = sup2._entries["gen_fleet"]
    t[0] = 0.0
    e2.proc.die(-9)
    sup2.check()
    t[0] = 0.5
    sup2.check()
    assert sup2.restart_counts == {"gen_fleet": 1}
    t[0] = 50.0  # window long gone
    e2.proc.die(-9)
    sup2.check()  # would escalate if the old restart still counted
    t[0] = 50.5
    sup2.check()
    assert sup2.restart_counts == {"gen_fleet": 2}


@pytest.mark.chaos
def test_supervisor_failure_domains_and_clean_exit(tmp_name_resolve):
    """Failure-domain classification: trainer (stateful) death escalates
    immediately — including an unexpected CLEAN exit, which previously
    went unnoticed while the master blocked on data-wait forever; a
    required stateless worker's clean exit is respawned; an optional
    worker's clean exit is ignored; drain suppresses everything."""
    from areal_tpu.system.supervisor import SupervisorEscalation, WorkerSpec

    t = [0.0]
    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    sup.spawn(WorkerSpec(name="trainer", kind="trainer",
                         target=_child_sleep_forever))
    sup._entries["trainer"].proc.die(0)  # clean but unrequested
    with pytest.raises(SupervisorEscalation, match="stateful"):
        sup.check()

    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    sup.spawn(WorkerSpec(name="rollout0", kind="rollout",
                         target=_child_sleep_forever))
    sup._entries["rollout0"].proc.die(0)  # early clean exit: a failure
    sup.check()
    assert sup._entries["rollout0"].respawn_due is not None

    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    sup.spawn(WorkerSpec(name="aux", kind="rollout",
                         target=_child_sleep_forever, required=False))
    sup._entries["aux"].proc.die(0)  # optional: done, not a failure
    sup.check()
    assert sup._entries["aux"].respawn_due is None
    assert sup.restart_counts == {}

    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    sup.spawn(WorkerSpec(name="trainer", kind="trainer",
                         target=_child_sleep_forever))
    sup.begin_drain()
    sup._entries["trainer"].proc.die(-15)
    sup.check()  # expected death during drain: no escalation


@pytest.mark.chaos
def test_supervisor_clears_ghost_keys_on_respawn(tmp_name_resolve):
    """A gen-fleet respawn must clear the dead incarnation's discovery
    keys (manager URL, server urls, heartbeats) BEFORE the new process
    binds fresh ones — nothing may resolve a corpse in the gap."""
    from areal_tpu.base import name_resolve, names
    from areal_tpu.system.supervisor import WorkerSpec
    from areal_tpu.system.worker_base import worker_control_key

    t = [0.0]
    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    exp, trial = "supfake", "t0"
    name_resolve.add(names.gen_server_manager(exp, trial),
                     "http://127.0.0.1:1", replace=True)
    name_resolve.add(names.gen_servers(exp, trial, "gen0"),
                     "http://127.0.0.1:2", replace=True)
    name_resolve.add(names.worker_heartbeat(exp, trial, "gserver_manager"),
                     "{}", replace=True)
    name_resolve.add(names.worker_heartbeat(exp, trial, "genserver_gen0"),
                     "{}", replace=True)
    name_resolve.add(names.worker_heartbeat(exp, trial, "rollout0"),
                     "{}", replace=True)  # another worker's: must survive
    name_resolve.add(worker_control_key(exp, trial, "gen_fleet"),
                     "tcp://127.0.0.1:3", replace=True)

    sup.spawn(WorkerSpec(name="gen_fleet", kind="gen_fleet",
                         target=_child_sleep_forever))
    sup._entries["gen_fleet"].proc.die(-9)
    sup.check()
    t[0] = 1.0
    sup.check()  # respawn happens here

    for key in (
        names.gen_server_manager(exp, trial),
        names.gen_servers(exp, trial, "gen0"),
        names.worker_heartbeat(exp, trial, "gserver_manager"),
        names.worker_heartbeat(exp, trial, "genserver_gen0"),
        worker_control_key(exp, trial, "gen_fleet"),
    ):
        with pytest.raises(name_resolve.NameEntryNotFoundError):
            name_resolve.get(key)
    # the rollout worker's heartbeat was not collateral damage
    assert name_resolve.get(
        names.worker_heartbeat(exp, trial, "rollout0")
    ) == "{}"


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_supervisor_respawns_sigkilled_process(tmp_name_resolve):
    """End to end with REAL processes: SIGKILL a supervised child; the
    supervisor detects the death on its next sweep, backs off, respawns a
    fresh incarnation, and counts the restart."""
    from areal_tpu.system.supervisor import (
        RestartPolicy,
        Supervisor,
        WorkerSpec,
    )

    sup = Supervisor("supreal", "t0", policy=RestartPolicy(
        max_restarts=3, window_secs=60.0, backoff_base_secs=0.05,
        backoff_max_secs=0.2,
    ))
    sup.spawn(WorkerSpec(name="rollout0", kind="rollout",
                         target=_child_sleep_forever))
    e = sup._entries["rollout0"]
    pid1 = e.proc.pid
    deadline = time.monotonic() + 30
    while not e.proc.is_alive() and time.monotonic() < deadline:
        time.sleep(0.02)
    os.kill(pid1, signal.SIGKILL)
    while time.monotonic() < deadline:
        sup.check()
        if e.proc.pid != pid1 and e.proc.is_alive():
            break
        time.sleep(0.02)
    try:
        assert e.proc.pid != pid1 and e.proc.is_alive()
        assert sup.restart_counts == {"rollout": 1}
        assert e.incarnation == 2
    finally:
        sup.shutdown(timeout=5.0, orderly=False)


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_supervisor_escalates_real_crash_loop(tmp_name_resolve):
    """A child that exits 3 on every start trips the circuit breaker
    after max_restarts respawns."""
    from areal_tpu.system.supervisor import (
        RestartPolicy,
        Supervisor,
        SupervisorEscalation,
        WorkerSpec,
    )

    sup = Supervisor("supreal2", "t0", policy=RestartPolicy(
        max_restarts=1, window_secs=60.0, backoff_base_secs=0.02,
        backoff_max_secs=0.05,
    ))
    sup.spawn(WorkerSpec(name="rollout0", kind="rollout",
                         target=_child_exit_three))
    deadline = time.monotonic() + 60
    try:
        with pytest.raises(SupervisorEscalation, match="crash-loop"):
            while time.monotonic() < deadline:
                sup.check()
                time.sleep(0.02)
            pytest.fail("circuit breaker never opened")
        assert sup.restart_counts == {"rollout": 1}  # 1 respawn, then open
    finally:
        sup.shutdown(timeout=5.0, orderly=False)


# ------------------------------------------------------- graceful drain


def _fake_ctrl_worker(exp, trial, name, events, stop_evt, commands=None):
    """In-process fake worker: serves a WorkerControl loop and records
    lifecycle events. `commands` maps custom cmd -> result."""
    from areal_tpu.system.worker_base import WorkerControl, WorkerState

    ctrl = WorkerControl(exp, trial, name)
    for cmd, result in (commands or {}).items():
        ctrl.on_command(
            cmd,
            lambda payload, c=cmd, r=result: events.append((name, c)) or r,
        )
    last_state = None
    while not stop_evt.is_set():
        ctrl.step()
        if ctrl.state != last_state:
            events.append((name, ctrl.state.value))
            last_state = ctrl.state
        if ctrl.should_exit:
            break
        time.sleep(0.005)
    ctrl.close()


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_graceful_drain_sequence(tmp_name_resolve):
    """drain_experiment against in-process fakes: master paused FIRST
    (so it never starts another step), rollouts paused, an out-of-band
    checkpoint lands while the master is paused, then everyone exits in
    order. Zero real processes, zero long sleeps."""
    from areal_tpu.system.supervisor import drain_experiment

    exp, trial = "drainfake", "t0"
    events, stop = [], threading.Event()
    threads = [
        threading.Thread(
            target=_fake_ctrl_worker,
            args=(exp, trial, "master", events, stop),
            kwargs={"commands": {"checkpoint": {"saved": True,
                                                "dir": "/tmp/ck"}}},
            daemon=True,
        ),
        threading.Thread(
            target=_fake_ctrl_worker,
            args=(exp, trial, "rollout0", events, stop), daemon=True,
        ),
    ]
    for t in threads:
        t.start()
    try:
        from areal_tpu.system.worker_base import WorkerControlPanel

        wait_panel = WorkerControlPanel(exp, trial, timeout=2.0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if set(wait_panel.list_workers()) == {"master", "rollout0"}:
                break
            time.sleep(0.02)
        wait_panel.close()
        report = drain_experiment(exp, trial, timeout=20.0)
        assert report["paused"]["master"]["state"] == "paused"
        assert report["paused"]["rollout0"]["state"] == "paused"
        assert report["checkpoint"]["ok"]
        assert report["checkpoint"]["result"] == {"saved": True,
                                                  "dir": "/tmp/ck"}
        assert set(report["exited"]) == {"master", "rollout0"}
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive()
        # the checkpoint executed while the master was PAUSED (between
        # steps) and before its exit
        midx = [i for i, e in enumerate(events) if e[0] == "master"]
        mevents = [events[i][1] for i in midx]
        assert "checkpoint" in mevents
        assert mevents.index("checkpoint") < mevents.index("exiting")
        from areal_tpu.base import name_resolve as nr
        from areal_tpu.base import names as _names
        import json as _json

        phase = _json.loads(nr.get(_names.drain_status(exp, trial)))
        assert phase["phase"] == "done"
    finally:
        stop.set()


# ------------------------------------------------ liveness leases


@pytest.mark.chaos
def test_name_resolve_keepalive_lease_expiry_and_touch(tmp_name_resolve):
    """Both repo backends: a key registered with keepalive_ttl expires
    once unheartbeaten (get/find purge it); touch() extends the lease;
    re-registration without a lease sheds the old TTL."""
    from areal_tpu.base.name_resolve import (
        MemoryNameRecordRepo,
        NameEntryNotFoundError,
    )

    repos = [MemoryNameRecordRepo(), name_resolve.DEFAULT_REPO]
    for repo in repos:
        repo.add("lease/a", "v1", keepalive_ttl=0.15, replace=True)
        repo.add("lease/b", "v2", replace=True)  # no lease: immortal
        assert repo.get("lease/a") == "v1"
        # touch keeps it alive past the original deadline
        for _ in range(3):
            time.sleep(0.08)
            repo.touch("lease/a")
        assert repo.get("lease/a") == "v1"
        time.sleep(0.25)  # no heartbeat: lease lapses
        with pytest.raises(NameEntryNotFoundError):
            repo.get("lease/a")
        with pytest.raises(NameEntryNotFoundError):
            repo.touch("lease/a")
        assert repo.find_subtree("lease") == ["lease/b"]
        assert repo.get("lease/b") == "v2"
        # an expired slot is re-registerable even without replace=True
        repo.add("lease/a", "v3", keepalive_ttl=0.15)
        # re-registration WITHOUT a ttl must not inherit the old lease
        repo.add("lease/a", "v4", replace=True)
        time.sleep(0.25)
        assert repo.get("lease/a") == "v4"
        repo.delete("lease/a")
        repo.delete("lease/b")


@pytest.mark.chaos
def test_worker_control_heartbeat_and_incarnation(tmp_name_resolve,
                                                  monkeypatch):
    """A supervised worker (env-stamped TTL + incarnation) keeps its
    control advertisement leased via the heartbeat thread, publishes a
    heartbeat key the panel can age, and reports its incarnation in
    status; close() withdraws both keys."""
    from areal_tpu.system import worker_base as wb

    monkeypatch.setenv(wb.ENV_INCARNATION, "3")
    monkeypatch.setenv(wb.ENV_KEEPALIVE_TTL, "0.3")
    exp, trial = "hbexp", "t0"
    stop = threading.Event()

    def worker():
        ctrl = wb.WorkerControl(exp, trial, "w0")
        while not stop.is_set():
            ctrl.step()
            if ctrl.should_exit:
                break
            time.sleep(0.01)
        ctrl.close()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    panel = wb.WorkerControlPanel(exp, trial, timeout=5.0)
    try:
        st = panel.status("w0")
        assert st["incarnation"] == 3
        hbs = panel.heartbeats()
        assert hbs["w0"]["incarnation"] == 3
        assert hbs["w0"]["age_secs"] < 5.0
        # the lease outlives its TTL because the heartbeat touches it
        time.sleep(0.6)
        assert panel.list_workers() == ["w0"]
        panel.exit("w0")
        t.join(timeout=5)
        assert not t.is_alive()
        # close() withdrew advertisement + heartbeat
        assert panel.list_workers() == []
        assert panel.heartbeats() == {}
    finally:
        stop.set()
        panel.close()


# ------------------------------------------------ crash-safe ConsumedLog


@pytest.mark.chaos
def test_consumed_log_fsync_and_torn_tail(tmp_path):
    """Every append reaches disk before add() returns (no buffered FH
    loss), and a torn tail (crash mid-append: final line without its
    newline) is dropped by the reader instead of being treated as a
    consumed uid — the prompt re-trains once, which is the safe
    direction."""
    from areal_tpu.system.rollout_worker import ConsumedLog

    log = ConsumedLog(str(tmp_path), worker_index=0)
    log.add("q1")
    log.add("q2")
    # durable WITHOUT close(): a SIGKILL after add() must lose nothing
    with open(log.path) as f:
        assert f.read() == "q1\nq2\n"
    # simulate a crash mid-append: torn record without its newline
    with open(log.path, "a") as f:
        f.write("q3@r")
    log2 = ConsumedLog(str(tmp_path), worker_index=0)
    assert "q1" in log2 and "q2" in log2
    assert "q3@r" not in log2 and "q3@r1" not in log2
    # the reader REPAIRED the file (fragment truncated), so appends after
    # a torn tail start on a fresh line instead of merging into it
    log2.add("q4")
    log3 = ConsumedLog(str(tmp_path), worker_index=0)
    assert log3.seen == {"q1", "q2", "q4"}
    log.close()
    log2.close()


# ------------------------------------- run_experiment relaunch hygiene


@pytest.mark.chaos
def test_run_experiment_relaunch_backoff_and_subtree_clear(
    tmp_name_resolve, monkeypatch
):
    """The auto-recover relaunch loop backs off between attempts and
    clears the dead incarnation's name_resolve subtree so the relaunch
    cannot discover stale endpoints."""
    import types

    from areal_tpu.apps import launcher as L

    cfg = types.SimpleNamespace(
        experiment_name="rx", trial_name="t0", mode="local",
        recover_mode="auto", recover_retries=2, serving=None,
        fault_tolerance=types.SimpleNamespace(
            relaunch_backoff_secs=0.2, relaunch_backoff_max_secs=1.0,
        ),
    )
    name_resolve.add("areal_tpu/rx/t0/stream/trainer", "tcp://dead:1",
                     replace=True)
    calls = {"n": 0}
    sleeps = []

    class _FakeLauncher:
        def __init__(self, exp_cfg, force_cpu=None):
            pass

        def run(self):
            calls["n"] += 1
            if calls["n"] == 1:
                # the stale key must still be visible to attempt 1
                assert name_resolve.get(
                    "areal_tpu/rx/t0/stream/trainer"
                ) == "tcp://dead:1"
                raise RuntimeError("worker died")
            # attempt 2: the subtree was cleared before the relaunch
            with pytest.raises(name_resolve.NameEntryNotFoundError):
                name_resolve.get("areal_tpu/rx/t0/stream/trainer")
            return {"steps": 7}

    monkeypatch.setattr(L, "LocalLauncher", _FakeLauncher)
    monkeypatch.setattr(L.time, "sleep", lambda s: sleeps.append(s))
    result = L.run_experiment(cfg)
    assert result == {"steps": 7}
    assert calls["n"] == 2
    assert sleeps == [pytest.approx(0.2)]
    assert cfg.recover_mode == "resume"


# ------------------------------------ launcher-level e2e (slow suite)


def _build_supervised_async_cfg(tmp_path, exp_name, benchmark_steps,
                                http_port=0):
    """A complete tiny async-PPO experiment config routed through the
    REAL launcher (supervisor, liveness leases, graceful drain) — the
    in-process analogue of test_entry_scripts' CLI launches."""
    from areal_tpu.base.testing import make_math_jsonl
    from areal_tpu.experiments.async_ppo_math_exp import AsyncPPOMATHConfig

    data_path = str(tmp_path / "math.jsonl")
    if not os.path.exists(data_path):
        make_math_jsonl(data_path, n=8)
    cfg = AsyncPPOMATHConfig(
        experiment_name=exp_name, trial_name="t0", mock_tokenizer=True,
    )
    cfg.cluster.fileroot = str(tmp_path / "exps")
    cfg.actor.tiny = {"vocab_size": 258, "seed": 0}
    cfg.ref.tiny = {"vocab_size": 258, "seed": 0}
    cfg.dataset.path = data_path
    cfg.dataset.train_bs_n_seqs = 4
    cfg.group_size = 2
    import dataclasses as _dc

    cfg.ppo.gen = _dc.replace(cfg.ppo.gen, max_new_tokens=8)
    cfg.ppo.ppo_n_minibatches = 2
    cfg.ppo.kl_ctl = 0.05
    cfg.ppo.disable_value = True
    cfg.ppo.use_decoupled_loss = True
    cfg.exp_ctrl.benchmark_steps = benchmark_steps
    cfg.exp_ctrl.total_train_epochs = 10**6
    cfg.max_head_offpolicyness = 4
    cfg.max_concurrent_rollouts = 4
    cfg.new_tokens_per_chunk = 4
    cfg.gen_batch_window_ms = 2
    cfg.gen_prompt_bucket = 16
    cfg.telemetry.enabled = True
    cfg.telemetry.flush_interval_secs = 0.3
    cfg.telemetry.http_port = http_port
    cfg.fault_tolerance.backoff_base_secs = 0.2
    cfg.fault_tolerance.backoff_max_secs = 1.0
    cfg.fault_tolerance.keepalive_ttl_secs = 10.0
    return cfg


def _wait_master_step(exp, trial, min_step, deadline_secs=420):
    """Poll the master's control status until its step counter reaches
    min_step (commands time out while it is busy inside a step)."""
    from areal_tpu.system.worker_base import WorkerControlPanel

    panel = WorkerControlPanel(exp, trial, timeout=3.0)
    try:
        deadline = time.monotonic() + deadline_secs
        while time.monotonic() < deadline:
            try:
                st = panel.status("master")
                if st.get("step", 0) >= min_step:
                    return st["step"]
            except TimeoutError:
                pass
            time.sleep(0.25)
    finally:
        panel.close()
    raise AssertionError(f"master never reached step {min_step}")


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.timeout(900)
def test_chaos_e2e_sigkill_rollout_and_fleet_no_relaunch(tmp_path):
    """THE ISSUE 9 acceptance chaos run: SIGKILL one rollout worker AND
    the gen-fleet process during a live launcher-supervised async-PPO
    experiment. The supervisor respawns both in place (rejoining through
    name_resolve + the manager's re-admission/weight-reconcile), the
    experiment completes with ZERO whole-experiment relaunches, and the
    per-kind supervisor restart counters are visible on the merged
    Prometheus scrape."""
    import urllib.request

    from areal_tpu.apps.launcher import LocalLauncher
    from areal_tpu.base import network as _network
    from areal_tpu.experiments import common as C

    port = _network.find_free_port()
    # Enough steps that the run genuinely DEPENDS on the killed workers:
    # warm tiny-model steps take <1s, so a short run would complete
    # before the chaos window opens; with 40 steps the master stalls on
    # data-wait while the fleet is down and only finishes because the
    # respawns restore the flow.
    cfg = _build_supervised_async_cfg(tmp_path, "supchaos",
                                      benchmark_steps=40, http_port=port)
    C.setup_name_resolve(cfg)
    launcher = LocalLauncher(cfg)
    result, errs = {}, []

    def _run():
        try:
            result.update(launcher.run())
        except BaseException as e:  # noqa: BLE001 — asserted below
            errs.append(e)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    try:
        _wait_master_step("supchaos", "t0", 1)
        sup = launcher.supervisor

        # SIGKILL the rollout worker; wait for its respawn
        e_roll = sup._entries["rollout0"]
        pid = e_roll.proc.pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if e_roll.proc.pid != pid and e_roll.proc.is_alive():
                break
            time.sleep(0.1)
        assert e_roll.proc.pid != pid and e_roll.proc.is_alive()

        # SIGKILL the whole gen-fleet process (servers + manager)
        e_fleet = sup._entries["gen_fleet"]
        pid = e_fleet.proc.pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if e_fleet.proc.pid != pid and e_fleet.proc.is_alive():
                break
            time.sleep(0.1)
        assert e_fleet.proc.pid != pid and e_fleet.proc.is_alive()

        # the restart counters reach the merged Prometheus scrape while
        # the run is still alive (the aggregator dies with the master)
        scrape = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and t.is_alive():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as r:
                    body = r.read().decode()
                if (
                    'areal_supervisor_restarts_total{'
                    in body
                    and 'worker_kind="rollout"' in body
                    and 'worker_kind="gen_fleet"' in body
                ):
                    scrape = body
                    break
            except Exception:  # noqa: BLE001 — aggregator busy
                pass
            time.sleep(0.3)
        assert scrape is not None, "supervisor metrics never scraped"

        t.join(timeout=700)
        assert not t.is_alive(), "experiment never completed"
        assert not errs, errs  # zero escalations / whole-run relaunches
        assert result["steps"] == 40
        assert launcher.supervisor.restart_counts == {
            "rollout": 1, "gen_fleet": 1,
        }
    finally:
        launcher.request_drain()
        t.join(timeout=30)
        if launcher.supervisor is not None:
            launcher.supervisor.shutdown(timeout=10.0, orderly=False)


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.timeout(900)
def test_drain_e2e_sigterm_then_resume(tmp_path):
    """THE ISSUE 9 acceptance drain run: a graceful drain mid-step (the
    SIGTERM path — request_drain() is the handler's body) produces a
    COMPLETE (.complete-marked) recover checkpoint and clean worker
    exits; relaunching with recover_mode=resume continues from the
    drained step to completion without re-training consumed prompts."""
    from areal_tpu.apps.launcher import LocalLauncher, run_experiment
    from areal_tpu.base import recover
    from areal_tpu.experiments import common as C

    cfg = _build_supervised_async_cfg(tmp_path, "supdrain",
                                      benchmark_steps=40)
    C.setup_name_resolve(cfg)
    paths = C.experiment_paths(cfg)
    launcher = LocalLauncher(cfg)
    result, errs = {}, []

    def _run():
        try:
            result.update(launcher.run())
        except BaseException as e:  # noqa: BLE001 — asserted below
            errs.append(e)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    _wait_master_step("supdrain", "t0", 1)
    launcher.request_drain()  # == the SIGTERM handler's body
    t.join(timeout=420)
    assert not t.is_alive(), "drain never completed"
    assert not errs, errs
    drained_steps = result["steps"]
    assert 1 <= drained_steps < 40  # exited early, cleanly

    # a COMPLETE out-of-band recover checkpoint exists at the drained step
    info = recover.load(paths["recover"])
    assert info is not None
    assert info.last_step_info.global_step == drained_steps
    ckpt = recover.discover_ckpt(paths["recover"])
    assert ckpt is not None
    assert os.path.exists(os.path.join(ckpt, recover.CKPT_COMPLETE_MARKER))

    # consumed-uid log survived the drain (fsynced appends)
    consumed_path = os.path.join(paths["recover"], "rollout_consumed_0.log")
    assert os.path.exists(consumed_path)
    with open(consumed_path) as f:
        consumed_before = {ln.strip() for ln in f if ln.strip()}
    assert consumed_before

    # resume: the relaunch restores the drained step and finishes the
    # remaining steps; consumed prompts are not re-trained (the log only
    # GROWS — a re-train would require re-consuming one of them, which
    # the skiplist forbids by construction)
    cfg.recover_mode = "resume"
    result2 = run_experiment(cfg)
    assert result2["steps"] == 40
    with open(consumed_path) as f:
        consumed_after = {ln.strip() for ln in f if ln.strip()}
    assert consumed_before <= consumed_after


@pytest.mark.chaos
def test_supervisor_honors_shutdown_markers(tmp_name_resolve):
    """A commanded teardown (master's end-of-run marker, or an EXTERNAL
    drain's phase record) makes subsequent deaths expected — the
    trainer's commanded exit during the master's teardown tail must not
    escalate a successful run. Markers older than the supervisor (a
    previous incarnation's) do NOT suppress detection."""
    import json as _json

    from areal_tpu.system.supervisor import SupervisorEscalation, WorkerSpec

    t = [0.0]
    sup = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    sup.spawn(WorkerSpec(name="trainer", kind="trainer",
                         target=_child_sleep_forever))
    name_resolve.add(
        names.experiment_status("supfake", "t0"),
        _json.dumps({"status": "finishing", "ts": time.time() + 1}),
        replace=True,
    )
    sup._entries["trainer"].proc.die(0)
    sup.check()  # expected: no escalation
    assert sup._entries["trainer"].done
    name_resolve.delete(names.experiment_status("supfake", "t0"))

    # stale marker from a PREVIOUS trial incarnation: detection stays on
    sup2 = _fake_supervisor(lambda: t[0], backoff_base_secs=0.1)
    name_resolve.add(
        names.experiment_status("supfake", "t0"),
        _json.dumps({"status": "finishing", "ts": time.time() - 3600}),
        replace=True,
    )
    sup2.spawn(WorkerSpec(name="trainer", kind="trainer",
                          target=_child_sleep_forever))
    sup2._entries["trainer"].proc.die(0)
    with pytest.raises(SupervisorEscalation):
        sup2.check()
    name_resolve.delete(names.experiment_status("supfake", "t0"))


@pytest.mark.chaos
def test_heartbeat_reregisters_lapsed_lease(tmp_name_resolve):
    """A lease that lapsed (stall/purge longer than the TTL) is
    RE-REGISTERED by the next beat when the value was recorded — a live
    worker must never stay deregistered because one heartbeat was
    late."""
    from areal_tpu.system.worker_base import HeartbeatThread

    hb = HeartbeatThread("hbre", "t0", "w0", interval=0.05)
    try:
        name_resolve.add("hbre/k", "addr", keepalive_ttl=5.0, replace=True)
        hb.lease("hbre/k", "addr", 5.0)
        name_resolve.delete("hbre/k")  # simulate an expiry purge
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                if name_resolve.get("hbre/k") == "addr":
                    break
            except name_resolve.NameEntryNotFoundError:
                pass
            time.sleep(0.02)
        assert name_resolve.get("hbre/k") == "addr"
    finally:
        hb.close()
