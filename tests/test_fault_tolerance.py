"""Generation-fleet fault tolerance: chaos + regression tests.

Covers the failure-recovery subsystem (docs/fault_tolerance.md):
 - retry policy / fault injector primitives (base/retry.py)
 - lease release/expiry accounting (no double decrement)
 - weight fanout with an unresponsive server: bounded by the per-server
   timeout budget, dead server evicted, version still advances
 - health-check eviction and re-admission with weight reconcile
 - client chunk failover: replay from accumulated tokens on a new route
 - rollout abandonment: clean /finish_rollout, worker survives
 - full chaos run: one of two real generation servers killed mid-run

Every test is bounded to seconds: failures come from the FaultInjector or
from tiny aiohttp fakes, never from real TTLs or long sleeps.
"""

import asyncio
import os
import time

import pytest

from areal_tpu.base import name_resolve, names, network
from areal_tpu.base.retry import (
    FaultInjected,
    FaultInjector,
    RetryPolicy,
    aretry,
)
from areal_tpu.system.gserver_manager import (
    GserverManager,
    GserverManagerConfig,
    _ServerHealth,
)
from areal_tpu.system.partial_rollout import (
    GenerationAbandonedError,
    NoHealthyServersError,
    PartialRolloutClient,
)

EXP, TRIAL = "faulttest", "t0"


class _Req:
    """Minimal aiohttp-request stand-in for direct handler calls."""

    def __init__(self, d=None):
        self._d = d or {}

    async def json(self):
        return self._d


def _mgr(**kw) -> GserverManager:
    cfg = GserverManagerConfig(experiment=EXP, trial=TRIAL, **kw)
    return GserverManager(cfg)


async def _start_app(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    port = network.find_free_port()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner, f"http://127.0.0.1:{port}"


# ---------------------------------------------------------------- retry.py


@pytest.mark.chaos
def test_retry_policy_delays_capped():
    p = RetryPolicy(max_attempts=5, base_delay_secs=0.1, max_delay_secs=0.5,
                    multiplier=2.0)
    assert p.delay(1) == pytest.approx(0.1)
    assert p.delay(2) == pytest.approx(0.2)
    assert p.delay(3) == pytest.approx(0.4)
    assert p.delay(4) == pytest.approx(0.5)  # capped
    assert p.delay(10) == pytest.approx(0.5)


@pytest.mark.chaos
def test_aretry_retries_then_succeeds_and_gives_up():
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ValueError("boom")
        return "ok"

    pol = RetryPolicy(max_attempts=3, base_delay_secs=0.001)
    assert asyncio.run(aretry(flaky, pol)) == "ok"
    assert calls["n"] == 3

    async def dead():
        raise ValueError("always")

    with pytest.raises(ValueError):
        asyncio.run(aretry(dead, pol))


@pytest.mark.chaos
def test_fault_injector_arming():
    inj = FaultInjector()
    inj.arm("p", times=2)
    with pytest.raises(FaultInjected):
        inj.maybe_fail("p")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("p")
    inj.maybe_fail("p")  # exhausted: no-op
    assert inj.fired["p"] == 2
    # predicate-gated, unlimited until disarm
    inj.arm("q", times=-1, when=lambda ctx: ctx.get("url") == "dead")
    inj.maybe_fail("q", url="alive")
    with pytest.raises(FaultInjected):
        inj.maybe_fail("q", url="dead")
    inj.disarm("q")
    inj.maybe_fail("q", url="dead")


# ------------------------------------------------------- lease accounting


@pytest.mark.chaos
def test_release_by_url_drops_lease_no_double_decrement():
    """Regression: the legacy by-url /release decremented inflight but left
    the lease alive, so its later TTL expiry decremented the SAME slot a
    second time — corrupting inflight while another request was running."""

    async def main():
        mgr = _mgr(lease_ttl_secs=60.0)
        url = "http://127.0.0.1:7777"
        mgr.servers = [url]
        mgr._inflight = {url: 0}
        mgr.health = {url: _ServerHealth()}

        await mgr.handle_schedule_request(_Req())  # request A
        assert mgr._inflight[url] == 1 and len(mgr._leases) == 1
        lease_a = next(iter(mgr._leases))

        # client releases A by url (legacy path, no lease_id)
        await mgr.handle_release(_Req({"url": url}))
        assert mgr._inflight[url] == 0
        assert lease_a not in mgr._leases  # the fix: lease retired too

        await mgr.handle_schedule_request(_Req())  # request B, in flight
        assert mgr._inflight[url] == 1

        # Force lease-expiry sweep. With the orphaned lease A still alive
        # (old bug) this would decrement B's slot to 0 while B is running.
        mgr._expire_leases()
        assert mgr._inflight[url] == 1

        # B's own expiry still works exactly once.
        lid_b = next(iter(mgr._leases))
        u, _ = mgr._leases[lid_b]
        mgr._leases[lid_b] = (u, time.monotonic() - 1)
        mgr._expire_leases()
        assert mgr._inflight[url] == 0 and not mgr._leases

    asyncio.run(main())


@pytest.mark.chaos
def test_release_by_lease_id_after_eviction_is_harmless():
    async def main():
        mgr = _mgr()
        url = "http://127.0.0.1:7777"
        mgr.servers = [url]
        mgr._inflight = {url: 0}
        mgr.health = {url: _ServerHealth()}
        await mgr.handle_schedule_request(_Req())
        lid = next(iter(mgr._leases))
        mgr._evict(url, "test")
        assert not mgr._leases and url not in mgr._inflight
        # late release from the client of the evicted server: no KeyError,
        # no negative counts
        await mgr.handle_release(_Req({"lease_id": lid, "url": url}))
        await mgr.handle_release(_Req({"url": url}))

    asyncio.run(main())


# ------------------------------------------------------------ weight fanout


@pytest.mark.chaos
def test_fanout_evicts_unresponsive_server_within_budget():
    """One acking server + one that accepts but never replies: the fanout
    must finish within the per-server timeout budget, evict the hung
    server (dropping its leases), bump the version, and route only to the
    survivor."""
    from aiohttp import web

    async def main():
        acks = []

        async def ok_update(req):
            acks.append(await req.json())
            return web.json_response({"ok": True})

        async def hang(req):
            await asyncio.sleep(60)

        live_app = web.Application()
        live_app.router.add_post("/update_weights", ok_update)
        live_runner, live_url = await _start_app(live_app)
        hung_app = web.Application()
        hung_app.router.add_post("/update_weights", hang)
        hung_runner, hung_url = await _start_app(hung_app)
        try:
            mgr = _mgr(
                fanout_timeout_secs=0.4,
                fanout_retry=RetryPolicy(max_attempts=2,
                                         base_delay_secs=0.05),
            )
            mgr.servers = sorted([live_url, hung_url])
            mgr._inflight = {u: 0 for u in mgr.servers}
            mgr.health = {u: _ServerHealth() for u in mgr.servers}
            # an in-flight lease on the hung server must drain on eviction
            while True:
                await mgr.handle_schedule_request(_Req())
                if any(u == hung_url for u, _ in mgr._leases.values()):
                    break

            import aiohttp

            budget = mgr.cfg.fanout_retry.max_attempts * (
                mgr.cfg.fanout_timeout_secs
                + mgr.cfg.fanout_retry.max_delay_secs
            )
            t0 = time.monotonic()
            async with aiohttp.ClientSession() as sess:
                acked = await mgr.fanout_weights(sess, 1, "/tmp/unused")
            elapsed = time.monotonic() - t0
            assert elapsed < budget + 1.0

            assert acked == [live_url]
            assert mgr.version == 1  # acked servers ⇒ version advanced
            assert [d["version"] for d in acks] == [1]
            assert hung_url not in mgr.servers
            assert not mgr.health[hung_url].routable
            assert all(u != hung_url for u, _ in mgr._leases.values())
            assert hung_url not in mgr._inflight
            for _ in range(4):  # no further routing to the evicted server
                assert mgr._pick_server() == live_url
        finally:
            await live_runner.cleanup()
            await hung_runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_fanout_total_failure_holds_version_and_fleet():
    """If NO server acks, the failure is systemic (bad/late weight path) —
    the version must NOT advance and the fleet must NOT be mass-evicted
    (that would drop every lease and flap); the watcher retries next poll
    and genuinely dead servers are the health loop's responsibility."""

    async def main():
        import aiohttp

        mgr = _mgr(
            fanout_timeout_secs=0.2,
            fanout_retry=RetryPolicy(max_attempts=1, base_delay_secs=0.01),
        )
        dead = "http://127.0.0.1:1"
        mgr.servers = [dead]
        mgr._inflight = {dead: 0}
        mgr.health = {dead: _ServerHealth()}
        async with aiohttp.ClientSession() as sess:
            acked = await mgr.fanout_weights(sess, 5, "/tmp/unused")
        assert acked == [] and mgr.version == 0
        assert mgr.servers == [dead]  # fleet held, not mass-evicted

    asyncio.run(main())


# --------------------------------------------- health eviction/re-admission


@pytest.mark.chaos
def test_health_eviction_and_readmission_with_reconcile(tmp_name_resolve):
    """/health failures evict after the threshold; a recovered server is
    re-admitted only after its weights are reconciled to the manager's
    current version; a newly registered server joins through the same
    gate."""
    from aiohttp import web

    async def main():
        state = {"alive": True, "version": 0, "updates": []}

        async def health(req):
            if not state["alive"]:
                return web.Response(status=500)
            return web.json_response({"ok": True,
                                      "version": state["version"]})

        async def update(req):
            d = await req.json()
            state["updates"].append(d)
            state["version"] = d["version"]
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_get("/health", health)
        app.router.add_post("/update_weights", update)
        runner, url = await _start_app(app)
        name_resolve.add(names.gen_servers(EXP, TRIAL, "flaky"), url,
                         replace=True)
        try:
            import aiohttp

            mgr = _mgr(health_failure_threshold=2,
                       health_check_timeout_secs=0.5)
            mgr.servers = [url]
            mgr._inflight = {url: 0}
            mgr.health = {url: _ServerHealth()}

            async def settle(pred, sweeps=20):
                # re-admission reconciles run detached from the sweep;
                # sweep + poll until the predicate holds
                for _ in range(sweeps):
                    await mgr.check_fleet(sess)
                    for _ in range(20):
                        if pred():
                            return True
                        await asyncio.sleep(0.02)
                return pred()

            timeout = aiohttp.ClientTimeout(total=0.5)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                await mgr.check_fleet(sess)
                assert url in mgr.servers  # healthy: stays

                state["alive"] = False
                await mgr.check_fleet(sess)
                assert url in mgr.servers  # 1 failure < threshold
                await mgr.check_fleet(sess)
                assert url not in mgr.servers  # threshold hit: evicted
                assert not mgr.health[url].routable

                # manager moved on to v3 while the server was down
                mgr.version = 3
                state["alive"] = True
                assert await settle(lambda: url in mgr.servers)
                # re-admitted AND reconciled to v3 before routing
                assert state["updates"][-1]["version"] == 3
                assert mgr.health[url].acked_version == 3
                assert mgr._inflight[url] == 0

                # a brand-new registration joins through the health gate
                app2 = web.Application()
                app2.router.add_get("/health", health)
                app2.router.add_post("/update_weights", update)
                runner2, url2 = await _start_app(app2)
                try:
                    name_resolve.add(
                        names.gen_servers(EXP, TRIAL, "late"), url2,
                        replace=True,
                    )
                    assert await settle(lambda: url2 in mgr.servers)

                    # deregistration prunes the health map entirely
                    name_resolve.delete(names.gen_servers(EXP, TRIAL,
                                                          "late"))
                    await mgr.check_fleet(sess)
                    assert url2 not in mgr.servers
                    assert url2 not in mgr.health
                finally:
                    await runner2.cleanup()
        finally:
            await runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------------- client failover


def _fake_gen_app(state):
    """Deterministic fake generation server: token i of a request is
    100+tokens_done+i, so replay-from-accumulated is directly observable
    in the output sequence."""
    from aiohttp import web

    async def generate(req):
        d = await req.json()
        td = int(d["tokens_done"])
        mt = int(d["max_tokens"])
        state["calls"].append(td)
        toks = list(range(100 + td, 100 + td + mt))
        return web.json_response({
            "output_ids": toks, "output_logprobs": [0.0] * mt,
            "finished": False, "version": 0,
        })

    async def health(req):
        return web.json_response({"ok": True, "version": 0})

    app = web.Application()
    app.router.add_post("/generate", generate)
    app.router.add_get("/health", health)
    return app


@pytest.mark.chaos
def test_client_failover_replays_from_accumulated_tokens(tmp_name_resolve):
    """A chunk failure mid-generation re-schedules and RESUMES: the final
    token sequence is contiguous (no lost or repeated tokens) and the
    failed chunk was re-requested at the same tokens_done."""
    from areal_tpu.api.model import GenerationHyperparameters

    async def main():
        import aiohttp

        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, max_head_offpolicyness=100,
                   health_check_interval_secs=30.0)
        mgr_url = await mgr.start()
        try:
            inj = FaultInjector()
            # fail exactly one attempt, at the second chunk boundary
            inj.arm("generate", times=1,
                    when=lambda ctx: ctx["tokens_done"] == 4)
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=4, base_delay_secs=0.01),
                    fault_injector=inj,
                )
                res = await client.generate_one(
                    [1, 2, 3],
                    GenerationHyperparameters(max_new_tokens=8),
                )
            assert res.output_ids == list(range(100, 108))
            assert client.n_failovers == 1 and inj.fired["generate"] == 1
            assert state["calls"] == [0, 4]  # chunk 2 replayed at td=4
            assert res.n_chunks == 2
            # quota accounting survived the failover: no leaked leases
            assert not mgr._leases
            assert all(v == 0 for v in mgr._inflight.values())
        finally:
            await mgr.stop()
            await runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_generation_abandoned_after_max_attempts(tmp_name_resolve):
    async def main():
        import aiohttp

        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, health_check_interval_secs=30.0)
        mgr_url = await mgr.start()
        try:
            from areal_tpu.api.model import GenerationHyperparameters

            inj = FaultInjector()
            inj.arm("generate", times=-1)  # fleet permanently dead
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=3, base_delay_secs=0.01),
                    fault_injector=inj,
                )
                with pytest.raises(GenerationAbandonedError):
                    await client.generate_one(
                        [1, 2], GenerationHyperparameters(max_new_tokens=8)
                    )
            assert inj.fired["generate"] == 3
            assert client.n_abandoned == 1
            # every scheduled route was released on its failure
            assert all(v == 0 for v in mgr._inflight.values())
            assert not mgr._leases
        finally:
            await mgr.stop()
            await runner.cleanup()

    asyncio.run(main())


@pytest.mark.chaos
def test_empty_fleet_waits_on_own_budget_not_failover_attempts():
    """An all-evicted fleet returns 503s in milliseconds; those must burn
    the (longer) no-server wait budget, not the chunk-failover attempts —
    and a fleet gap longer than the budget abandons with a clear error."""
    from areal_tpu.api.model import GenerationHyperparameters

    async def main():
        import aiohttp

        mgr = _mgr()  # zero servers: /schedule_request 503s immediately
        runner, mgr_url = await _start_app(mgr.build_app())
        try:
            async with aiohttp.ClientSession() as sess:
                client = PartialRolloutClient(
                    mgr_url, sess, chunk_tokens=4,
                    retry=RetryPolicy(max_attempts=3, base_delay_secs=0.01,
                                      max_delay_secs=0.05),
                    no_server_wait_secs=0.2,
                )
                with pytest.raises(NoHealthyServersError):
                    await client._schedule()
                t0 = time.monotonic()
                with pytest.raises(GenerationAbandonedError,
                                   match="no routable"):
                    await client.generate_one(
                        [1, 2], GenerationHyperparameters(max_new_tokens=8)
                    )
                # waited out the no-server budget (not the ~30ms the three
                # failover attempts would have taken)
                assert time.monotonic() - t0 >= 0.2
        finally:
            await runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------ rollout worker survival


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_rollout_worker_abandons_cleanly_never_crashes(tmp_path):
    """With every /generate chunk failing, the worker must abandon each
    rollout after the retry budget — reporting a correct /finish_rollout so
    running_rollouts drains to 0 — and run_async must return, not raise."""
    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.testing import MockTokenizer, make_math_jsonl
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )
    from areal_tpu.system.streams import ZmqPuller

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=4)

    async def main():
        state = {"calls": []}
        runner, url = await _start_app(_fake_gen_app(state))
        name_resolve.add(names.gen_servers(EXP, TRIAL, "gen0"), url,
                         replace=True)
        mgr = _mgr(n_servers=1, max_head_offpolicyness=100,
                   health_check_interval_secs=30.0)
        await mgr.start()
        puller = ZmqPuller(EXP, TRIAL, "trainer")  # pusher blocks without it
        inj = FaultInjector()
        inj.arm("generate", times=-1)
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
            group_size=2, chunk_tokens=4, max_concurrent=2,
            tokenizer=MockTokenizer(), max_rollouts=2,
            retry=RetryPolicy(max_attempts=2, base_delay_secs=0.01),
        ), fault_injector=inj)
        await worker.run_async()  # must NOT raise
        assert worker._abandoned >= 2 and worker._pushed == 0
        # in-flight rollouts beyond max_rollouts drain on the same loop
        for _ in range(200):
            if mgr.running_rollouts == 0 and not mgr._leases:
                break
            await asyncio.sleep(0.05)
        assert mgr.running_rollouts == 0  # no leaked quota
        assert not mgr._leases
        await mgr.stop()
        await runner.cleanup()
        puller.close()

    asyncio.run(main())


@pytest.mark.chaos
@pytest.mark.timeout(300)
def test_chaos_kill_one_of_two_servers_mid_run(tmp_path):
    """THE acceptance chaos run: two real generation servers, one killed
    mid-generation. Interrupted rollouts fail over to the survivor, every
    trajectory is delivered, running_rollouts returns to 0, the worker
    never raises, and the dead server is evicted from routing."""
    import jax

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.base.testing import MockTokenizer, make_math_jsonl
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )
    from areal_tpu.system.rollout_worker import (
        RolloutWorker,
        RolloutWorkerConfig,
    )
    from areal_tpu.system.streams import ZmqPuller

    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(
        str(tmp_path / "nr")
    )
    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=6)
    mcfg = tiny_config(vocab_size=258, n_layers=2, hidden_dim=32)
    params = transformer.init_params(mcfg, jax.random.PRNGKey(0))

    async def main():
        servers = []
        for sid in ("gen0", "gen1"):
            s = GenerationServer(
                GenerationServerConfig(
                    experiment=EXP, trial=TRIAL, server_id=sid,
                    chunk_tokens=4, prompt_bucket=16, batch_window_ms=2,
                ),
                mcfg, params,
            )
            await s.start()
            servers.append(s)
        victim_url = name_resolve.get(names.gen_servers(EXP, TRIAL, "gen0"))

        mgr = GserverManager(GserverManagerConfig(
            experiment=EXP, trial=TRIAL, n_servers=2,
            train_batch_size=4, max_head_offpolicyness=100,
            realloc_dir=str(tmp_path / "realloc"), weight_poll_secs=5.0,
            health_check_interval_secs=0.1, health_check_timeout_secs=0.5,
            health_failure_threshold=2,
        ))
        await mgr.start()

        puller = ZmqPuller(EXP, TRIAL, "trainer")
        worker = RolloutWorker(RolloutWorkerConfig(
            experiment=EXP, trial=TRIAL, dataset_path=data_path,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
            group_size=2, chunk_tokens=4, max_concurrent=2,
            tokenizer=MockTokenizer(), max_rollouts=6,
            retry=RetryPolicy(max_attempts=10, base_delay_secs=0.02,
                              max_delay_secs=0.5),
            agent_args={"success_rate_lb": 0.0, "success_rate_ub": 1.0},
        ))
        run_task = asyncio.create_task(worker.run_async())

        # let the run make progress, then crash gen0 mid-generation
        while worker._done < 1:
            await asyncio.sleep(0.05)
            assert not run_task.done() or run_task.exception() is None
        await servers[0].stop(abort=True)

        await run_task  # the worker must complete WITHOUT raising

        # all 6 rollouts delivered (failover, not loss): ≥ 6 × group 2
        assert worker._done >= 6 and worker._abandoned == 0
        assert worker._pushed >= 12
        got = 0
        for _ in range(400):
            if puller.pull(timeout_ms=20) is not None:
                got += 1
            elif got >= 12:
                break
        assert got >= 12  # every trajectory arrived over the push stream

        # in-flight rollouts beyond max_rollouts drain on the same loop
        for _ in range(400):
            if mgr.running_rollouts == 0:
                break
            await asyncio.sleep(0.05)
        assert mgr.running_rollouts == 0  # quota fully drained

        # the dead server ends up evicted from routing (health loop)
        for _ in range(100):
            if victim_url not in mgr.servers:
                break
            await asyncio.sleep(0.1)
        assert victim_url not in mgr.servers
        assert not mgr.health[victim_url].routable
        # survivor still routable
        assert len(mgr.servers) == 1

        await mgr.stop()
        await servers[1].stop()
        puller.close()

    asyncio.run(main())


# ----------------------------------------------------------- reward client


@pytest.mark.chaos
def test_batch_reward_callable_from_running_event_loop(monkeypatch):
    """Regression: _batch_remote used asyncio.run(), which raises
    RuntimeError from threads that already run a loop (the async rollout
    path). With an unreachable service it must fall back to local grading —
    from sync AND async contexts."""
    from areal_tpu.rewards import client as rclient

    monkeypatch.setenv(rclient.SERVICE_ENV, "127.0.0.1:9")
    tasks = [{"task": "math", "generated": "\\boxed{4}",
              "solutions": ["4"]}] * 2

    sync_scores = rclient.batch_reward(tasks, max_retries=0)
    assert len(sync_scores) == 2

    async def inside_loop():
        return rclient.batch_reward(tasks, max_retries=0)

    async_scores = asyncio.run(inside_loop())
    assert async_scores == sync_scores
