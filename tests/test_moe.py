"""MoE layer + transformer/engine integration tests.

Parity targets: realhf/impl/model/modules/moe/ (router aux losses, capacity
drop, experts) and ReaLMoEConfig (realhf/api/core/model_api.py:294). The
default dispatch is the sort-based grouped-GEMM path; the one-hot einsum
path (GShard layout) is kept as the parity oracle — grouped-vs-einsum and
expert-parallel parity live in tests/test_moe_dispatch.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import moe as moemod
from areal_tpu.models import transformer
from areal_tpu.models.config import MoEConfig, TransformerConfig, tiny_config


def _moe_cfg(**kw):
    d = dict(num_experts=4, top_k=2, capacity_factor=2.0)
    d.update(kw)
    return MoEConfig(**d)


def test_single_expert_matches_dense():
    """E=1, k=1, ample capacity: MoE must reduce exactly to the dense MLP
    (norm_topk_prob renormalizes the single gate weight to 1)."""
    rng = np.random.RandomState(0)
    D, F, N = 16, 32, 24
    x = jnp.asarray(rng.randn(2, N // 2, D).astype(np.float32))
    wg = jnp.asarray(rng.randn(D, F).astype(np.float32) * 0.1)
    wu = jnp.asarray(rng.randn(D, F).astype(np.float32) * 0.1)
    wd = jnp.asarray(rng.randn(F, D).astype(np.float32) * 0.1)
    lp = {
        "router": jnp.zeros((D, 1)),
        "e_gate": wg[None], "e_up": wu[None], "e_down": wd[None],
    }
    moe = MoEConfig(num_experts=1, top_k=1, capacity_factor=1.0)
    y, aux = moemod.moe_mlp(x, lp, moe)
    dense = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-5)
    assert float(aux["dropped_frac"]) == 0.0


def test_capacity_drop_and_losses():
    rng = np.random.RandomState(1)
    D, E = 8, 4
    x = jnp.asarray(rng.randn(1, 64, D).astype(np.float32))
    lp = {
        "router": jnp.asarray(rng.randn(D, E).astype(np.float32)),
        "e_gate": jnp.asarray(rng.randn(E, D, 16).astype(np.float32) * 0.1),
        "e_up": jnp.asarray(rng.randn(E, D, 16).astype(np.float32) * 0.1),
        "e_down": jnp.asarray(rng.randn(E, 16, D).astype(np.float32) * 0.1),
    }
    # Tight capacity: with skewed routing some (token, expert) slots drop.
    moe = MoEConfig(num_experts=E, top_k=2, capacity_factor=0.5,
                    aux_loss_coeff=1e-2, z_loss_coeff=1e-3)
    y, aux = moemod.moe_mlp(x, lp, moe)
    assert y.shape == x.shape
    assert float(aux["dropped_frac"]) > 0.0
    # Perfectly-balanced routing gives load_balance == 1; any routing >= 1.
    assert float(aux["load_balance_loss"]) >= 1.0 - 1e-5
    assert float(aux["z_loss"]) > 0.0
    assert float(aux["aux_total"]) == pytest.approx(
        1e-2 * float(aux["load_balance_loss"]) + 1e-3 * float(aux["z_loss"]),
        rel=1e-5,
    )


def test_forward_returns_layer_mean_aux():
    cfg = tiny_config(moe=_moe_cfg())
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(2, 128, (2, 16)))
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16))
    seg = jnp.ones((2, 16), jnp.int32)
    out, _, aux = transformer.forward(
        params, cfg, tokens, pos, segment_ids=seg, return_aux=True
    )
    assert out.shape == (2, 16, 128)
    for k in ("aux_total", "load_balance_loss", "z_loss", "dropped_frac"):
        assert np.isfinite(float(aux[k])), k
    # Dense models return an empty aux dict.
    dcfg = tiny_config()
    dparams = transformer.init_params(dcfg, jax.random.PRNGKey(0))
    _, _, daux = transformer.forward(
        dparams, dcfg, tokens, pos, segment_ids=seg, return_aux=True
    )
    assert daux == {}


def test_router_gets_gradient_from_aux_loss():
    """Without the aux loss the router would get zero gradient from a
    loss that ignores the output; aux_total must flow to router weights."""
    cfg = tiny_config(moe=_moe_cfg(aux_loss_coeff=1e-2))
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(2, 128, (1, 16)))
    pos = jnp.broadcast_to(jnp.arange(16), (1, 16))
    seg = jnp.ones((1, 16), jnp.int32)

    def loss(p):
        _, _, aux = transformer.forward(
            p, cfg, tokens, pos, segment_ids=seg, return_aux=True
        )
        return aux["aux_total"]

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["layers"]["router"]).sum()) > 0.0


def test_engine_train_step_moe_stats():
    """The training engine surfaces moe_* stats and the loss is finite."""
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import FinetuneSpec, Model
    from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
    from areal_tpu.algorithms.sft import SFTInterface

    cfg = tiny_config(moe=_moe_cfg())
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    model = Model("actor", (cfg, params), tokenizer=None)
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
        compute_dtype="float32", length_bucket=16, rows_bucket=2,
        seqs_bucket=4,
    )
    model = backend.initialize(model, FinetuneSpec(1, 8, 4))
    rng = np.random.RandomState(0)
    seqlens = [12, 9, 15, 7]
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[str(i) for i in range(4)],
        data={
            "packed_input_ids": rng.randint(2, 128, total).astype(np.int32),
            "prompt_mask": np.concatenate(
                [np.r_[np.ones(3, np.int32), np.zeros(n - 3, np.int32)]
                 for n in seqlens]),
        },
        seqlens=seqlens,
    )
    iface = SFTInterface()
    before = jax.device_get(model.module.params["layers"]["router"])
    stats = iface.train_step(model, batch, MicroBatchSpec(max_tokens_per_mb=64))
    assert np.isfinite(stats["loss"])
    assert "moe_aux_total" in stats and np.isfinite(stats["moe_aux_total"])
    after = jax.device_get(model.module.params["layers"]["router"])
    assert not np.allclose(before, after)  # router trained


def test_moe_generation_parity_with_forward():
    """Chunked decode must agree with a full packed forward for MoE models
    (greedy argmax over the same prompt)."""
    from areal_tpu.models import generate as genmod
    from areal_tpu.api.model import GenerationHyperparameters

    cfg = tiny_config(moe=_moe_cfg(capacity_factor=4.0))
    params = transformer.init_params(cfg, jax.random.PRNGKey(1))
    prompt = np.array([[5, 6, 7, 8]], np.int32)
    out = genmod.generate_batch(
        params, cfg, jnp.asarray(prompt), jnp.asarray([4]),
        jax.random.PRNGKey(0),
        GenerationHyperparameters(greedy=True, max_new_tokens=4),
        max_new_tokens=4, eos_token_id=1, pad_token_id=0,
    )
    toks = np.asarray(out["output_ids"])[0]
    # Teacher-force the generated tokens through the packed forward: each
    # next-token argmax must match (KV-cache path == full-context path).
    full = np.concatenate([prompt[0], toks])
    T = len(full)
    logits, _ = transformer.forward(
        params, cfg, jnp.asarray(full[None]),
        jnp.arange(T)[None], segment_ids=jnp.ones((1, T), jnp.int32),
    )
    for i in range(4):
        assert int(jnp.argmax(logits[0, 3 + i])) == int(toks[i])


def test_router_jitter_rng_path():
    """Router input jitter (input_jitter_eps > 0): an rng key perturbs the
    routing, rng=None routes on the clean input (inference contract), and
    eps=0 with a key is bit-identical to the no-key path."""
    rng = np.random.RandomState(0)
    D, F, E = 16, 32, 4
    x = jnp.asarray(rng.randn(2, 12, D).astype(np.float32))
    lp = {
        "router": jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.5),
        "e_gate": jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.1),
        "e_up": jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.1),
        "e_down": jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.1),
    }
    moe_j = _moe_cfg(input_jitter_eps=0.2)
    y_clean, _ = moemod.moe_mlp(x, lp, moe_j)  # rng=None: jitter off
    y_clean2, _ = moemod.moe_mlp(x, lp, moe_j)
    assert jnp.array_equal(y_clean, y_clean2)  # deterministic without a key
    y_a, _ = moemod.moe_mlp(x, lp, moe_j, rng=jax.random.PRNGKey(1))
    y_b, _ = moemod.moe_mlp(x, lp, moe_j, rng=jax.random.PRNGKey(2))
    assert not jnp.array_equal(y_a, y_b)  # different keys, different jitter
    assert not jnp.array_equal(y_a, y_clean)
    # eps=0: the key is dead weight, output bit-identical to no-key
    moe_0 = _moe_cfg(input_jitter_eps=0.0)
    y0, _ = moemod.moe_mlp(x, lp, moe_0)
    y0k, _ = moemod.moe_mlp(x, lp, moe_0, rng=jax.random.PRNGKey(1))
    assert jnp.array_equal(y0, y0k)


def test_forward_threads_jitter_rng():
    """transformer.forward(rng=...) reaches the per-layer routers on the
    training path (return_kv=False, the one train steps run): outputs
    differ across keys, and rng=None keeps today's bit-identical scan."""
    cfg = tiny_config(moe=_moe_cfg(input_jitter_eps=0.2))
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.randint(2, 64, (2, 8)).astype(np.int32))
    pos = jnp.tile(jnp.arange(8), (2, 1))
    seg = jnp.ones((2, 8), jnp.int32)

    def fwd(rng=None):
        out, _ = transformer.forward(params, cfg, toks, pos,
                                     segment_ids=seg, return_kv=False,
                                     rng=rng)
        return out

    assert jnp.array_equal(fwd(), fwd())  # no key → deterministic
    j1 = fwd(jax.random.PRNGKey(1))
    j2 = fwd(jax.random.PRNGKey(2))
    assert not jnp.array_equal(j1, j2)
    # the KV-returning (inference) path ignores the jitter by design
    kv1, _ = transformer.forward(params, cfg, toks, pos, segment_ids=seg)
    kv2, _ = transformer.forward(params, cfg, toks, pos, segment_ids=seg)
    assert jnp.array_equal(kv1, kv2)


def test_engine_train_step_with_jitter():
    """input_jitter_eps > 0 trains end to end through the engine: the train
    step threads a per-micro-batch key (backend/jax_train.py) instead of
    raising, the loss is finite, and the router still learns."""
    from areal_tpu.algorithms.sft import SFTInterface
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import FinetuneSpec, Model
    from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig

    cfg = tiny_config(moe=_moe_cfg(input_jitter_eps=0.1))
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    model = Model("actor", (cfg, params), tokenizer=None)
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
        compute_dtype="float32", length_bucket=16, rows_bucket=2,
        seqs_bucket=4,
    )
    model = backend.initialize(model, FinetuneSpec(1, 8, 4))
    rng = np.random.RandomState(0)
    seqlens = [12, 9, 15, 7]
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[str(i) for i in range(4)],
        data={
            "packed_input_ids": rng.randint(2, 128, total).astype(np.int32),
            "prompt_mask": np.concatenate(
                [np.r_[np.ones(3, np.int32), np.zeros(n - 3, np.int32)]
                 for n in seqlens]),
        },
        seqlens=seqlens,
    )
    iface = SFTInterface()
    before = jax.device_get(model.module.params["layers"]["router"])
    stats = iface.train_step(model, batch,
                             MicroBatchSpec(max_tokens_per_mb=64))
    assert np.isfinite(stats["loss"])
    assert "moe_aux_total" in stats and np.isfinite(stats["moe_aux_total"])
    after = jax.device_get(model.module.params["layers"]["router"])
    assert not np.allclose(before, after)
