"""SequenceSample invariants — mirrors the reference's
tests/data/test_sequence_gather_split.py."""

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample


def make_sample(bs=6, seed=0):
    rng = np.random.default_rng(seed)
    seqlens = rng.integers(3, 17, size=bs).tolist()
    total = sum(seqlens)
    data = {
        "packed_input_ids": rng.integers(0, 100, size=total).astype(np.int32),
        "rewards": rng.normal(size=bs).astype(np.float32),
    }
    ids = [f"s{i}" for i in range(bs)]
    return SequenceSample.from_default(ids, data, seqlens), seqlens


class TestConstruction:
    def test_from_default_infers_seqlens(self):
        s, seqlens = make_sample()
        assert s.seqlens["packed_input_ids"] == [[x] for x in seqlens]
        assert s.seqlens["rewards"] == [[1]] * s.bs

    def test_duplicate_ids_rejected(self):
        with pytest.raises(ValueError):
            SequenceSample(
                ids=["a", "a"],
                keys={"x"},
                seqlens={"x": [[1], [1]]},
                data={"x": np.zeros(2)},
            )

    def test_bad_data_length_rejected(self):
        with pytest.raises(ValueError):
            SequenceSample(
                ids=["a"],
                keys={"x"},
                seqlens={"x": [[3]]},
                data={"x": np.zeros(2)},
            )


class TestSplitGather:
    def test_split_gather_roundtrip(self):
        s, _ = make_sample(bs=8)
        parts, groups = s.split(k=3)
        regathered = SequenceSample.gather(parts)
        # Order may differ; select back to original order and compare.
        back = regathered.select_ids(s.ids)
        np.testing.assert_array_equal(
            back.data["packed_input_ids"], s.data["packed_input_ids"]
        )
        np.testing.assert_array_equal(back.data["rewards"], s.data["rewards"])
        assert back.seqlens == s.seqlens

    def test_split_balanced(self):
        s, seqlens = make_sample(bs=16)
        parts, groups = s.split(k=4)
        sums = [sum(sum(x) for x in p.seqlens["packed_input_ids"]) for p in parts]
        assert max(sums) - min(sums) <= max(seqlens)

    def test_split_mb_spec_token_cap(self):
        s, _ = make_sample(bs=10)
        parts, _ = s.split(mb_spec=MicroBatchSpec(n_mbs=1, max_tokens_per_mb=32))
        for p in parts:
            if p.bs > 1:
                assert p.total_lens().sum() <= 32

    def test_select_idx_slices_all_keys(self):
        s, seqlens = make_sample(bs=5)
        sub = s.select_idx([1, 3])
        assert sub.ids == ["s1", "s3"]
        assert sub.data["packed_input_ids"].shape[0] == seqlens[1] + seqlens[3]
        assert sub.data["rewards"].shape[0] == 2

    def test_meta_drops_data(self):
        s, _ = make_sample()
        m = s.meta()
        assert m.data is None
        assert m.keys == s.keys
        # meta split still works (master-side dispatch is metadata-only)
        parts, _ = m.split(k=2)
        assert sum(p.bs for p in parts) == s.bs


class TestUpdateRemap:
    def test_update_merges_new_keys(self):
        s, seqlens = make_sample(bs=4)
        other = SequenceSample.from_default(
            ids=list(reversed(s.ids)),
            data={"logprobs": np.arange(sum(seqlens), dtype=np.float32)},
            seqlens=list(reversed(seqlens)),
        )
        s.update_(other)
        assert "logprobs" in s.keys
        # update_ reorders `other` to self's id order
        assert s.seqlens["logprobs"] == [[x] for x in seqlens]

    def test_remap(self):
        s, _ = make_sample()
        s.remap_keys_({"rewards": "scores"})
        assert "scores" in s.keys and "rewards" not in s.keys


class TestCodec:
    def test_json_roundtrip(self):
        s, _ = make_sample()
        s.metadata["birth_time"] = [0.5] * s.bs
        d = s.as_json_compatible()
        import json

        s2 = SequenceSample.from_json_compatible(json.loads(json.dumps(d)))
        np.testing.assert_array_equal(
            s2.data["packed_input_ids"], s.data["packed_input_ids"]
        )
        assert s2.data["packed_input_ids"].dtype == np.int32
        assert s2.metadata["birth_time"] == s.metadata["birth_time"]

    def test_cu_seqlens(self):
        s, seqlens = make_sample(bs=3)
        cu = s.cu_seqlens()
        np.testing.assert_array_equal(cu, np.concatenate([[0], np.cumsum(seqlens)]))


def test_split_k_greater_than_bs_returns_exactly_k():
    s, _ = make_sample(bs=2)
    parts, groups = s.split(k=4)
    assert len(parts) == 4
    assert sum(p.bs for p in parts) == 2
    empty = [p for p in parts if p.bs == 0]
    assert len(empty) == 2
    assert SequenceSample.gather(parts).bs == 2
