"""Logits parity vs HuggingFace transformers on CPU — mirrors the reference's
tests/model/test_cpu_inference.py (ReaLModel vs HF parity).

Covers llama (GQA), qwen2 (attention bias), qwen3 (qk-norm), packed
multi-document batches, and greedy-generation parity incl. KV-cache decode.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from areal_tpu.models import hf as hf_conv
from areal_tpu.models.config import tiny_config
from areal_tpu.models.packing import (
    batch_from_packed,
    make_grid,
    packed_from_batch,
    plan_packing,
)
from areal_tpu.models.transformer import forward, init_params, param_count


def tiny_hf_model(model_type="llama", vocab=97, hidden=48, layers=3, heads=4, kv=2):
    import torch
    import transformers

    torch.manual_seed(0)
    common = dict(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=hidden * 2,
        num_hidden_layers=layers,
        num_attention_heads=heads,
        num_key_value_heads=kv,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    if model_type == "llama":
        cfg = transformers.LlamaConfig(**common)
    elif model_type == "qwen2":
        cfg = transformers.Qwen2Config(**common)
    elif model_type == "qwen3":
        cfg = transformers.Qwen3Config(**common, head_dim=hidden // heads)
    elif model_type == "mistral":
        cfg = transformers.MistralConfig(**common, sliding_window=None)
    elif model_type == "gemma":
        common["num_key_value_heads"] = kv
        cfg = transformers.GemmaConfig(**common, head_dim=hidden // heads)
    elif model_type == "gpt2":
        cfg = transformers.GPT2Config(
            vocab_size=vocab, n_embd=hidden, n_layer=layers, n_head=heads,
            n_positions=256, n_inner=hidden * 2,
        )
    elif model_type == "mixtral":
        cfg = transformers.MixtralConfig(
            **common, num_local_experts=4, num_experts_per_tok=2,
        )
    elif model_type == "qwen3_moe":
        cfg = transformers.Qwen3MoeConfig(
            **common, head_dim=hidden // heads, num_experts=4,
            num_experts_per_tok=2, moe_intermediate_size=hidden * 2,
            decoder_sparse_step=1, mlp_only_layers=[],
        )
    else:
        raise ValueError(model_type)
    model = transformers.AutoModelForCausalLM.from_config(cfg)
    model.eval()
    return model


def hf_logits(model, input_ids: np.ndarray) -> np.ndarray:
    import torch

    with torch.no_grad():
        out = model(input_ids=torch.from_numpy(input_ids.astype(np.int64)))
    return out.logits.float().numpy()


@pytest.mark.parametrize(
    "family",
    ["llama", "qwen2", "qwen3", "mistral", "gemma", "gpt2", "mixtral",
     "qwen3_moe"],
)
def test_logits_parity(family):
    model = tiny_hf_model(family)
    cfg, params, _ = hf_conv.load_hf_model(model)
    rng = np.random.default_rng(0)
    B, T = 2, 24
    ids = rng.integers(0, cfg.vocab_size, size=(B, T)).astype(np.int32)

    ours, _ = forward(
        params,
        cfg,
        jnp.asarray(ids),
        jnp.broadcast_to(jnp.arange(T)[None], (B, T)),
        segment_ids=jnp.ones((B, T), jnp.int32),
    )
    theirs = hf_logits(model, ids)
    # MoE token-choice order can differ at float ties; widen tolerance a hair.
    tol = dict(atol=2e-4, rtol=2e-3)
    if family in ("mixtral", "qwen3_moe"):
        tol = dict(atol=1e-3, rtol=5e-3)
    np.testing.assert_allclose(np.asarray(ours), theirs, **tol)


@pytest.mark.parametrize("family", ["qwen2", "gpt2", "mixtral"])
def test_safetensors_checkpoint_roundtrip(family, tmp_path):
    """save_hf_checkpoint output must load BOTH in transformers
    (AutoModelForCausalLM — the VERDICT r2 'npz not safetensors' gap) and
    via load_hf_checkpoint, with identical logits."""
    import transformers

    model = tiny_hf_model(family)
    cfg, params, _ = hf_conv.load_hf_model(model)
    out = str(tmp_path / "ckpt")
    hf_conv.save_hf_checkpoint(params, cfg, out, meta={"version": 3})

    # 1. HF tooling loads it.
    reloaded = transformers.AutoModelForCausalLM.from_pretrained(out)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, size=(1, 16)).astype(np.int32)
    np.testing.assert_allclose(
        hf_logits(reloaded, ids), hf_logits(model, ids), atol=1e-4, rtol=1e-3
    )

    # 2. Our loader round-trips bit-exact.
    cfg2, params2 = hf_conv.load_hf_checkpoint(out)
    assert cfg2 == cfg
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("family", ["qwen2", "mixtral"])
def test_native_checkpoint_roundtrip(family, tmp_path):
    """The weight-SYNC format (save_native_checkpoint): bit-exact pytree
    round-trip with dtype preserved, no HF-layout conversion, detected by
    load_checkpoint_auto via its sentinel."""
    import jax

    model = tiny_hf_model(family)
    cfg, params, _ = hf_conv.load_hf_model(model)
    out = str(tmp_path / "sync")
    hf_conv.save_native_checkpoint(params, cfg, out, meta={"version": 7})
    assert hf_conv.is_native_checkpoint(out)
    cfg2, params2 = hf_conv.load_checkpoint_auto(out)
    assert cfg2 == cfg
    la = jax.tree_util.tree_leaves(params)
    lb = jax.tree_util.tree_leaves(params2)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_packed_multi_document_matches_separate():
    """Packing several docs into one row must give identical logits to running
    each doc alone — validates segment masking + per-doc positions."""
    model = tiny_hf_model("llama")
    cfg, params, _ = hf_conv.load_hf_model(model)
    rng = np.random.default_rng(1)
    seqlens = [7, 12, 5, 9]
    packed = rng.integers(0, cfg.vocab_size, size=sum(seqlens)).astype(np.int32)

    layout = plan_packing(seqlens, length_bucket=16)
    grid = make_grid(layout)
    tokens = batch_from_packed(packed, layout)
    out, _ = forward(
        params,
        cfg,
        jnp.asarray(tokens),
        jnp.asarray(grid["positions"]),
        segment_ids=jnp.asarray(grid["segment_ids"]),
    )
    packed_out = packed_from_batch(np.asarray(out), layout)

    off = 0
    for sl in seqlens:
        doc = packed[off : off + sl][None]
        solo, _ = forward(
            params,
            cfg,
            jnp.asarray(doc),
            jnp.arange(sl)[None],
            segment_ids=jnp.ones((1, sl), jnp.int32),
        )
        np.testing.assert_allclose(
            packed_out[off : off + sl], np.asarray(solo)[0], atol=1e-4, rtol=1e-3
        )
        off += sl


def test_greedy_generation_matches_hf():
    """Greedy decode (prefill + KV cache loop) vs HF .generate on ragged
    prompts — validates cache writes, masks, and RoPE positions end-to-end."""
    import torch

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.models.generate import generate_batch, pad_prompts

    model = tiny_hf_model("llama")
    cfg, params, _ = hf_conv.load_hf_model(model)
    rng = np.random.default_rng(2)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=n).tolist() for n in (5, 11, 8)
    ]
    N = 12
    eos = 0  # random model is unlikely to emit token 0 greedily for long

    padded, lens = pad_prompts(prompts, pad_token_id=0, bucket=4)
    out = generate_batch(
        params,
        cfg,
        jnp.asarray(padded),
        jnp.asarray(lens),
        key=__import__("jax").random.key(0),
        gconfig=GenerationHyperparameters(greedy=True),
        max_new_tokens=N,
        eos_token_id=eos,
        pad_token_id=0,
    )
    ours = np.asarray(out["output_ids"])

    for i, p in enumerate(prompts):
        with torch.no_grad():
            hf_out = model.generate(
                torch.tensor([p]),
                max_new_tokens=N,
                do_sample=False,
                eos_token_id=eos,
                pad_token_id=0,
            )
        ref = hf_out[0, len(p) :].numpy()
        n = min(len(ref), int(out["output_lens"][i]))
        np.testing.assert_array_equal(ours[i, :n], ref[:n])


def test_critic_head_shape():
    cfg = tiny_config(is_critic=True)
    import jax

    params = init_params(cfg, jax.random.key(0))
    B, T = 2, 8
    vals, _ = forward(
        params,
        cfg,
        jnp.zeros((B, T), jnp.int32),
        jnp.broadcast_to(jnp.arange(T)[None], (B, T)),
        segment_ids=jnp.ones((B, T), jnp.int32),
    )
    assert vals.shape == (B, T)


def test_param_count_matches_tree():
    import jax

    cfg = tiny_config()
    params = init_params(cfg, jax.random.key(0))
    n = sum(x.size for x in jax.tree.leaves(params))
    assert n == param_count(cfg)


def test_hf_roundtrip():
    model = tiny_hf_model("qwen2")
    cfg, params, _ = hf_conv.load_hf_model(model)
    sd = hf_conv.params_to_hf_state_dict(params, cfg)
    params2 = hf_conv.params_from_hf_state_dict(sd, cfg)
    import jax

    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mistral_sliding_window_parity():
    """Sliding-window masking must match HF mistral on sequences longer than
    the window."""
    import torch
    import transformers

    torch.manual_seed(0)
    cfg_hf = transformers.MistralConfig(
        vocab_size=97, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        sliding_window=8, max_position_embeddings=256,
    )
    model = transformers.AutoModelForCausalLM.from_config(cfg_hf)
    model.eval()
    cfg, params, _ = hf_conv.load_hf_model(model)
    assert cfg.sliding_window == 8
    rng = np.random.default_rng(3)
    B, T = 1, 24  # longer than the window
    ids = rng.integers(0, cfg.vocab_size, size=(B, T)).astype(np.int32)
    ours, _ = forward(
        params, cfg, jnp.asarray(ids),
        jnp.broadcast_to(jnp.arange(T)[None], (B, T)),
        segment_ids=jnp.ones((B, T), jnp.int32),
    )
    theirs = hf_logits(model, ids)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


def test_min_new_tokens_suppresses_eos():
    import jax

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.models.generate import generate_batch, pad_prompts
    from areal_tpu.models.config import tiny_config
    from areal_tpu.models.transformer import init_params

    cfg = tiny_config(vocab_size=16)
    params = init_params(cfg, jax.random.key(0))
    prompts = [[1, 2, 3]]
    padded, lens = pad_prompts(prompts, pad_token_id=0, bucket=4)
    # With every token equally likely, eos would normally appear early.
    out = generate_batch(
        params, cfg, jnp.asarray(padded), jnp.asarray(lens),
        key=jax.random.key(5),
        gconfig=GenerationHyperparameters(min_new_tokens=10, temperature=5.0),
        max_new_tokens=12, eos_token_id=3, pad_token_id=0,
    )
    ids = np.asarray(out["output_ids"])[0]
    assert not np.any(ids[:10] == 3)
