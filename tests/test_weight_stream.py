"""Streamed weight sync: manifest/chunk protocol, integrity gates, atomic
swap, fault-tolerance integration, and disk-fallback parity.

Covers the subsystem in ``system/weight_stream.py`` plus its wiring through
``trainer_worker`` / ``generation_server`` / ``gserver_manager``
(docs/weight_sync.md):

 - manifest round-trip: a pytree published over the stream arrives
   bit-identical, shapes/dtypes preserved (bf16 stays 2 bytes)
 - torn/corrupted/reordered streams are rejected by checksum + digest
   verification and the server's live params are never touched
 - atomic (params, version) swap under a concurrent /generate load: the
   version visible via /metrics only changes after a complete verified
   manifest applied
 - a server failing mid-stream surfaces a non-200 ack, so the manager's
   existing eviction/retry machinery owns it (PR 2 guarantees)
 - disk-fallback parity: both transports deliver the same pytree bytes
"""

import asyncio
import json
import threading
import time

import jax
import numpy as np
import pytest

from areal_tpu.base import name_resolve, names, network
from areal_tpu.models.hf import flatten_pytree, unflatten_pytree
from areal_tpu.system.weight_stream import (
    WeightStreamConsumer,
    WeightStreamError,
    WeightStreamPublisher,
)

EXP, TRIAL = "wstest", "t0"


def _tree(seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return {
        "embedding": rng.randn(64, 16).astype(dtype),
        "layers": {
            "wq": rng.randn(2, 16, 16).astype(dtype),
            "ln1": rng.randn(2, 16).astype(dtype),
        },
        "final_ln": rng.randn(16).astype(dtype),
    }


def _publish(tree, version=1, **kw) -> WeightStreamPublisher:
    pub = WeightStreamPublisher(EXP, TRIAL, "actor", **kw)
    pub.publish(sorted(flatten_pytree(tree).items()), version)
    return pub


# ------------------------------------------------------------ round trip


def test_manifest_roundtrip_bitexact(tmp_name_resolve):
    tree = _tree()
    pub = _publish(tree, version=3, chunk_bytes=1024)  # force multi-chunk
    consumer = WeightStreamConsumer(pub.endpoint)
    try:
        manifest, flat = consumer.fetch(3)
        assert manifest["version"] == 3
        assert manifest["total_bytes"] == sum(
            v.nbytes for v in flatten_pytree(tree, as_numpy=True).values()
        )
        # multi-chunk actually exercised (embedding is 4096 bytes)
        assert max(t["n_chunks"] for t in manifest["tensors"]) > 1
        got = unflatten_pytree(dict(flat))
        for k, want in flatten_pytree(tree, as_numpy=True).items():
            have = np.asarray(flat[k])
            assert have.dtype == want.dtype and have.shape == want.shape
            np.testing.assert_array_equal(have, want)
        assert set(flatten_pytree(got)) == set(flatten_pytree(tree))
        # endpoint is discoverable through the names schema
        assert name_resolve.get(
            names.weight_stream(EXP, TRIAL, "actor")
        ) == pub.endpoint
    finally:
        consumer.close()
        pub.close()


def test_bf16_wire_format_preserved(tmp_name_resolve):
    import ml_dtypes

    tree = {"w": np.arange(32, dtype=np.float32).astype(ml_dtypes.bfloat16)}
    pub = _publish(tree)
    consumer = WeightStreamConsumer(pub.endpoint)
    try:
        _, flat = consumer.fetch(1)
        assert flat["w"].dtype == ml_dtypes.bfloat16  # 2 bytes on the wire
        np.testing.assert_array_equal(flat["w"], tree["w"])
    finally:
        consumer.close()
        pub.close()


def test_jax_leaves_gathered_lazily(tmp_name_resolve):
    """Publishing device arrays works: the gather thread performs the d2h
    and the consumer sees the same values."""
    tree = jax.tree.map(jax.numpy.asarray, _tree(seed=7))
    pub = _publish(tree)
    consumer = WeightStreamConsumer(pub.endpoint)
    try:
        _, flat = consumer.fetch(1)
        for k, v in flatten_pytree(tree, as_numpy=True).items():
            np.testing.assert_array_equal(np.asarray(flat[k]), v)
    finally:
        consumer.close()
        pub.close()


def test_unknown_version_and_replay(tmp_name_resolve):
    pub = _publish(_tree(), version=5)
    c1 = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    c2 = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    try:
        with pytest.raises(WeightStreamError, match="not cached"):
            c1.fetch_manifest(4)
        # per-server replay: two consumers fetch the same publish
        _, f1 = c1.fetch(5)
        _, f2 = c2.fetch(5)
        for k in f1:
            np.testing.assert_array_equal(f1[k], f2[k])
    finally:
        c1.close()
        c2.close()
        pub.close()


# ------------------------------------------------------- integrity gates


def test_corrupted_chunk_rejected(tmp_name_resolve):
    """Bytes corrupted in the publisher cache AFTER checksumming must fail
    the consumer's wire CRC check — the swap never happens."""
    pub = _publish(_tree(), chunk_bytes=1024)
    assert pub.wait_complete(1, timeout=10)
    entry = pub._cache[1]
    entry.arrays[0] = entry.arrays[0].copy()
    entry.arrays[0].reshape(-1).view(np.uint8)[3] ^= 0xFF
    consumer = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    try:
        with pytest.raises(WeightStreamError, match="checksum mismatch"):
            consumer.fetch(1)
    finally:
        consumer.close()
        pub.close()


def test_reordered_stream_rejected(tmp_name_resolve):
    """Replies arriving out of request order (swapped chunk coordinates)
    must abort: the echoed (tensor, chunk) is verified per reply."""
    pub = _publish(_tree(), chunk_bytes=512)
    assert pub.wait_complete(1, timeout=10)
    orig = pub._handle

    def swapped(frames):
        reply = orig(frames)
        if frames[0] == b"chunk":
            meta = json.loads(reply[1])
            meta["chunk"] += 1  # lie about which chunk this is
            reply[1] = json.dumps(meta).encode()
        return reply

    pub._handle = swapped
    consumer = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    try:
        with pytest.raises(WeightStreamError, match="out-of-order"):
            consumer.fetch(1)
    finally:
        consumer.close()
        pub.close()


def test_digest_catches_divergent_crcs(tmp_name_resolve):
    """Even if per-chunk checks were fooled, the final digest compare
    against the publisher's complete CRC list gates the swap."""
    pub = _publish(_tree(), chunk_bytes=1024)
    assert pub.wait_complete(1, timeout=10)
    consumer = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    try:
        manifest = consumer.fetch_manifest(1)
        list(consumer.iter_tensors(1, manifest))
        consumer._local_crcs[0][0] ^= 1  # simulate a silently-wrong chunk
        with pytest.raises(WeightStreamError, match="digest mismatch"):
            consumer.verify_digest(1)
    finally:
        consumer.close()
        pub.close()


def test_consumer_death_midstream_leaves_publisher_serving(tmp_name_resolve):
    """A server dying mid-stream must not wedge the publisher: a fresh
    consumer completes a full verified fetch afterwards."""
    pub = _publish(_tree(), chunk_bytes=256)
    dead = WeightStreamConsumer(pub.endpoint, timeout_secs=5)
    manifest = dead.fetch_manifest(1)
    it = dead.iter_tensors(1, manifest)
    next(it)  # pull one tensor, leave requests in flight...
    dead.close()  # ...and die
    survivor = WeightStreamConsumer(pub.endpoint, timeout_secs=10)
    try:
        _, flat = survivor.fetch(1)
        assert set(flat) == set(flatten_pytree(_tree()))
    finally:
        survivor.close()
        pub.close()


# ------------------------------------------- server swap atomicity (e2e)


def _tiny_server(**kw):
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.system.generation_server import (
        GenerationServer,
        GenerationServerConfig,
    )

    mcfg = tiny_config(vocab_size=258, n_layers=2, hidden_dim=32)
    params = transformer.init_params(mcfg, jax.random.PRNGKey(0))
    cfg = GenerationServerConfig(
        experiment=EXP, trial=TRIAL, chunk_tokens=4, prompt_bucket=16,
        batch_window_ms=2, **kw,
    )
    return GenerationServer(cfg, mcfg, params), mcfg


@pytest.mark.timeout(120)
def test_atomic_swap_under_concurrent_generate(tmp_name_resolve):
    """POST /update_weights with a stream payload while /generate traffic
    is in flight: every response is tagged with a version the server
    actually held (old or new, never torn), and /metrics flips to the new
    version exactly when the verified swap lands."""
    import aiohttp

    async def main():
        server, mcfg = _tiny_server()
        url = await server.start()
        new_params = jax.tree.map(
            lambda x: x + 0.01 if x.dtype == np.float32 else x, server.params
        )
        pub = WeightStreamPublisher(EXP, TRIAL, "actor")
        pub.publish(sorted(flatten_pytree(new_params).items()), 1)
        try:
            async with aiohttp.ClientSession() as sess:
                versions = []

                async def update():
                    await asyncio.sleep(0.05)
                    async with sess.post(f"{url}/update_weights", json={
                        "endpoint": pub.endpoint, "version": 1,
                    }) as r:
                        assert r.status == 200
                        assert (await r.json())["version"] == 1

                async with sess.get(f"{url}/metrics.json") as r:
                    assert (await r.json())["version"] == 0
                upd = asyncio.create_task(update())
                # keep /generate traffic flowing until the swap landed AND
                # at least one post-swap response was observed
                for _ in range(400):
                    async with sess.post(f"{url}/generate", json={
                        "prompt_ids": [3, 4, 5], "max_tokens": 4,
                    }) as r:
                        assert r.status == 200
                        versions.append((await r.json())["version"])
                    if upd.done() and versions[-1] == 1:
                        break
                await upd
                assert set(versions) <= {0, 1}  # never a torn in-between
                assert versions[-1] == 1  # post-swap traffic sees v1
                async with sess.get(f"{url}/metrics.json") as r:
                    assert (await r.json())["version"] == 1
            # swapped weights match the published tree bit-exactly
            for k, v in flatten_pytree(new_params, as_numpy=True).items():
                np.testing.assert_array_equal(
                    np.asarray(flatten_pytree(server.params)[k]), v
                )
        finally:
            pub.close()
            await server.stop()

    asyncio.run(main())


@pytest.mark.timeout(120)
def test_failed_stream_keeps_old_weights_and_500s(tmp_name_resolve):
    """A dead endpoint (server died mid-stream analogue) must yield a
    non-200 ack with the OLD version still live — the manager's existing
    retry/evict machinery takes it from there."""
    import aiohttp

    async def main():
        server, _ = _tiny_server()
        url = await server.start()
        before = flatten_pytree(server.params, as_numpy=True)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.post(f"{url}/update_weights", json={
                    "endpoint": "tcp://127.0.0.1:1",
                    "version": 1, "timeout": 1,
                }) as r:
                    assert r.status == 500
                    body = await r.json()
                    assert body["ok"] is False and body["version"] == 0
                async with sess.get(f"{url}/metrics.json") as r:
                    assert (await r.json())["version"] == 0
            after = flatten_pytree(server.params, as_numpy=True)
            for k in before:
                np.testing.assert_array_equal(before[k], after[k])
        finally:
            await server.stop()

    asyncio.run(main())


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_fanout_stream_payload_and_eviction(tmp_name_resolve):
    """Manager fanout in stream mode: with the publisher endpoint
    registered, acked servers get the endpoint payload; a server that
    fails its stream is evicted while the version still bumps over the
    acker (the PR 2 guarantee, unchanged by the new transport)."""
    from aiohttp import web

    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
        _ServerHealth,
    )
    from areal_tpu.base.retry import RetryPolicy

    async def _start_app(app):
        runner = web.AppRunner(app)
        await runner.setup()
        port = network.find_free_port()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return runner, f"http://127.0.0.1:{port}"

    async def main():
        import aiohttp

        pub = _publish(_tree(), version=7)
        payloads = []

        async def ok_update(req):
            payloads.append(await req.json())
            return web.json_response({"ok": True})

        async def bad_update(req):
            # stream consumption failed server-side (mid-stream death)
            return web.json_response({"ok": False}, status=500)

        ok_app = web.Application()
        ok_app.router.add_post("/update_weights", ok_update)
        ok_runner, ok_url = await _start_app(ok_app)
        bad_app = web.Application()
        bad_app.router.add_post("/update_weights", bad_update)
        bad_runner, bad_url = await _start_app(bad_app)
        try:
            mgr = GserverManager(GserverManagerConfig(
                experiment=EXP, trial=TRIAL,
                fanout_timeout_secs=2.0,
                fanout_retry=RetryPolicy(max_attempts=2,
                                         base_delay_secs=0.01),
            ))
            mgr.servers = sorted([ok_url, bad_url])
            mgr._inflight = {u: 0 for u in mgr.servers}
            mgr.health = {u: _ServerHealth() for u in mgr.servers}
            async with aiohttp.ClientSession() as sess:
                acked = await mgr.fanout_weights(sess, 7, "/unused/disk/path")
            assert acked == [ok_url]
            assert mgr.version == 7
            # stream payload (endpoint), not the disk path
            assert payloads and payloads[0]["endpoint"] == pub.endpoint
            assert "path" not in payloads[0]
            assert bad_url not in mgr.servers  # evicted, not silently stale
            assert not mgr.health[bad_url].routable
        finally:
            pub.close()
            await ok_runner.cleanup()
            await bad_runner.cleanup()

    asyncio.run(main())


# ------------------------------------------------------ transport parity


@pytest.mark.timeout(120)
def test_disk_and_stream_transports_deliver_identical_pytrees(
    tmp_name_resolve, tmp_path
):
    """The same publish through both transports ends in byte-identical
    server params (the fallback is a true fallback)."""
    import aiohttp

    from areal_tpu.models import hf as hfmod

    async def main():
        server_a, mcfg = _tiny_server(server_id="gen0")
        server_b, _ = _tiny_server(server_id="gen1")
        url_a = await server_a.start()
        url_b = await server_b.start()
        new_params = jax.tree.map(
            lambda x: x * 1.25 if x.dtype == np.float32 else x,
            server_a.params,
        )
        # disk publish (trainer _save_role fmt="native" analogue)
        disk_dir = str(tmp_path / "v1")
        hfmod.save_native_checkpoint(
            jax.tree.map(np.asarray, new_params), mcfg, disk_dir
        )
        pub = WeightStreamPublisher(EXP, TRIAL, "actor")
        pub.publish(sorted(flatten_pytree(new_params).items()), 1)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.post(f"{url_a}/update_weights", json={
                    "endpoint": pub.endpoint, "version": 1,
                }) as r:
                    assert r.status == 200
                async with sess.post(f"{url_b}/update_weights", json={
                    "path": disk_dir, "version": 1,
                }) as r:
                    assert r.status == 200
            fa = flatten_pytree(server_a.params, as_numpy=True)
            fb = flatten_pytree(server_b.params, as_numpy=True)
            assert set(fa) == set(fb)
            for k in fa:
                assert fa[k].dtype == fb[k].dtype
                np.testing.assert_array_equal(fa[k], fb[k])
            assert server_a.version == server_b.version == 1
        finally:
            pub.close()
            await server_a.stop()
            await server_b.stop()

    asyncio.run(main())


# ------------------------------------------------- trainer-side publish


@pytest.mark.timeout(120)
def test_trainer_stream_publish_end_to_end(tmp_name_resolve):
    """TrainerWorker with weight_sync.transport=stream publishes an
    endpoint + version (no realloc dir write), and a consumer pulls the
    actor weights in the engine's compute dtype."""
    import os

    import areal_tpu.backend.jax_train  # noqa: F401 — registers "jax_train"
    from areal_tpu.api.model import FinetuneSpec
    from areal_tpu.api.train_config import WeightSyncConfig
    from areal_tpu.system.trainer_worker import (
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    cfg = TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL,
        models={"actor": ModelRoleConfig(
            init={"tiny": {"vocab_size": 258}},
            backend_args={"compute_dtype": "float32", "length_bucket": 16},
        )},
        ft_spec=FinetuneSpec(1, 32, 8),
        realloc_dir="/nonexistent/never/written",
        weight_sync=WeightSyncConfig(transport="stream"),
    )
    w = TrainerWorker(cfg)
    for role, rc in cfg.models.items():
        model = w._model_factory(role, rc)
        from areal_tpu.api.model import make_backend

        backend = make_backend(rc.backend, train=rc.train, **rc.backend_args)
        w.models[role] = backend.initialize(model, cfg.ft_spec)
    w.publish_weights("actor")
    try:
        assert not os.path.exists("/nonexistent/never/written")
        v = int(name_resolve.get(names.model_version(EXP, TRIAL, "actor")))
        endpoint = name_resolve.get(names.weight_stream(EXP, TRIAL, "actor"))
        consumer = WeightStreamConsumer(endpoint, timeout_secs=30)
        try:
            _, flat = consumer.fetch(v)
        finally:
            consumer.close()
        want = flatten_pytree(w.models["actor"].module.params, as_numpy=True)
        assert set(flat) == set(want)
        for k in want:
            np.testing.assert_array_equal(np.asarray(flat[k]), want[k])
    finally:
        for pub in w._weight_publishers.values():
            pub.close()
