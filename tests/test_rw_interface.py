"""Learned reward-model training (algorithms/rw.py): Bradley-Terry loss
over the RewardModelingPairedDataset — the pairing survives packing, the
loss optimizes, and the serving path scores flat sequences."""

import json

import jax
import numpy as np

from areal_tpu.algorithms.rw import (
    RewardModelingInterface,
    flatten_pairs,
)
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import FinetuneSpec, Model
from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
from areal_tpu.base.testing import MockTokenizer
from areal_tpu.datasets.jsonl import RewardModelingPairedDataset
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config

MBS = MicroBatchSpec(max_tokens_per_mb=4096)


def _paired_jsonl(path, n=16):
    """Learnable signal: positive answers end in 'G', negatives in 'B'."""
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "query_id": f"q{i}",
                "prompt": f"question {i}: ",
                "pos_answers": [f"answer {i} G", f"alt {i} G"],
                "neg_answers": [f"answer {i} B", f"alt {i} B"],
            }) + "\n")


def _rm_model(seed=0):
    cfg = tiny_config(vocab_size=258, n_layers=2, hidden_dim=32,
                      is_critic=True)
    params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
    model = Model("rm", (cfg, params), tokenizer=MockTokenizer())
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=5e-3, lr_scheduler_type="constant",
                                  warmup_steps_proportion=0.0),
        compute_dtype="float32", length_bucket=16, rows_bucket=2,
        seqs_bucket=4,
    )
    return backend.initialize(model, FinetuneSpec(1, 64, 8))


def test_flatten_pairs_layout(tmp_path):
    p = tmp_path / "rw.jsonl"
    _paired_jsonl(str(p), n=4)
    ds = RewardModelingPairedDataset(dataset_path=str(p),
                                     tokenizer=MockTokenizer())
    batch = SequenceSample.gather([ds[i] for i in range(4)])
    flat = flatten_pairs(batch)
    # 4 prompts x 2 pairs x 2 answers
    assert flat.bs == 16
    signs = flat.data["_pair_sign"].reshape(-1)
    idxs = flat.data["_pair_idx"].reshape(-1)
    assert (signs > 0).sum() == 8 and (signs < 0).sum() == 8
    # every pair id appears exactly once with each sign
    for pid in np.unique(idxs):
        ss = signs[idxs == pid]
        assert sorted(ss.tolist()) == [-1.0, 1.0]


def test_rw_training_learns_preference(tmp_path):
    p = tmp_path / "rw.jsonl"
    _paired_jsonl(str(p), n=16)
    ds = RewardModelingPairedDataset(dataset_path=str(p),
                                     tokenizer=MockTokenizer())
    model = _rm_model()
    iface = RewardModelingInterface()
    batch = SequenceSample.gather([ds[i] for i in range(len(ds))])
    first = None
    for _ in range(15):
        stats = iface.train_step(model, batch, MBS)
        assert stats["orphan_pairs"] == 0.0
        assert stats["n_pairs"] == 32.0
        first = first or stats
    assert stats["loss"] < first["loss"]
    assert stats["pairwise_accuracy"] >= 0.9
    assert stats["pos_minus_neg"] > 0

    # serving path: flat sequences -> scores, pos > neg for a seen pair
    tok = MockTokenizer()
    seqs = [tok.encode("question 3: answer 3 G"),
            tok.encode("question 3: answer 3 B")]
    flat = SequenceSample.gather([
        SequenceSample.from_default(
            ids=[f"s{i}"],
            data={"packed_input_ids": np.asarray(s, np.int32)},
            seqlens=[len(s)],
        ) for i, s in enumerate(seqs)
    ])
    out = iface.inference(model, flat, MBS)
    assert out.data["scores"][0] > out.data["scores"][1]
