"""Test harness configuration.

Mirrors the reference's CPU-only test strategy (SURVEY.md §4): all tests run
on a virtual 8-device CPU platform so multi-chip sharding is exercised without
TPU hardware. Must set env vars BEFORE jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize may have force-registered a TPU plugin and set
# jax_platforms before this conftest runs; override back to CPU (the backend
# is created lazily, so this takes effect as long as no array was built yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    from areal_tpu.base import seeding

    seeding.set_random_seed(1)
    np.random.seed(1)
    yield


@pytest.fixture()
def tmp_name_resolve(tmp_path):
    from areal_tpu.base import name_resolve

    old = name_resolve.DEFAULT_REPO
    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(str(tmp_path / "nr"))
    yield name_resolve.DEFAULT_REPO
    name_resolve.DEFAULT_REPO = old
