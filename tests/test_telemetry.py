"""Unified telemetry layer (base/telemetry.py, docs/observability.md).

All in-process fakes, zero real sleeps: pushers are flushed explicitly
(``flush()``) instead of waiting out their interval, the aggregator is
polled with short bounded waits, and the profiler watcher gets injected
start/stop functions.
"""

import json
import threading
import time

import pytest

from areal_tpu.api.train_config import TelemetryConfig
from areal_tpu.base import name_resolve, names, telemetry

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    r = telemetry.TelemetryRegistry()
    r.inc("a")
    r.inc("a", 2.5)
    r.set_gauge("g", 7)
    r.set_gauge("g", 3)  # last write wins
    r.observe("h", 0.02, buckets=(0.01, 0.1, 1.0))
    r.observe("h", 0.5)
    r.observe("h", 99.0)  # lands in the +Inf bucket
    s = r.snapshot()
    assert s["counters"]["a"] == 3.5
    assert s["gauges"]["g"] == 3.0
    h = s["hists"]["h"]
    assert h["buckets"] == [0.01, 0.1, 1.0]
    assert h["counts"] == [0, 1, 1, 1]
    assert h["count"] == 3 and abs(h["sum"] - 99.52) < 1e-9
    # metrics are CUMULATIVE: a draining snapshot does not reset them
    r.snapshot(reset=True)
    assert r.snapshot()["counters"]["a"] == 3.5


def test_snapshot_reset_drains_only_spans():
    r = telemetry.TelemetryRegistry()
    with r.span("s"):
        pass
    r.inc("c")
    s1 = r.snapshot(reset=True)
    assert len(s1["spans"]) == 1
    s2 = r.snapshot(reset=True)
    assert s2["spans"] == [] and s2["counters"]["c"] == 1.0


def test_span_nesting_parent_ids():
    r = telemetry.TelemetryRegistry()
    with r.span("outer", k="v") as attrs:
        attrs["added"] = 1
        with r.span("mid"):
            with r.span("leaf"):
                pass
        with r.span("mid2"):
            pass
    spans = {s["name"]: s for s in r.snapshot()["spans"]}
    assert spans["outer"]["parent_id"] is None
    assert spans["mid"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["leaf"]["parent_id"] == spans["mid"]["span_id"]
    assert spans["mid2"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["outer"]["attrs"] == {"k": "v", "added": 1}
    assert spans["outer"]["dur_secs"] >= spans["mid"]["dur_secs"]
    # every span also lands in a duration histogram
    assert r.snapshot()["hists"]["outer/secs"]["count"] == 1


def test_span_nesting_is_thread_local():
    r = telemetry.TelemetryRegistry()
    seen = {}

    def worker():
        with r.span("in_thread"):
            pass
        seen["done"] = True

    with r.span("main"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans = {s["name"]: s for s in r.snapshot()["spans"]}
    # the thread's span must NOT inherit the main thread's open span
    assert spans["in_thread"]["parent_id"] is None
    assert seen["done"]


def test_span_buffer_bounded():
    r = telemetry.TelemetryRegistry(max_spans=4)
    for i in range(10):
        with r.span(f"s{i}"):
            pass
    s = r.snapshot()
    assert len(s["spans"]) == 4
    assert s["dropped_spans"] == 6
    assert [x["name"] for x in s["spans"]] == ["s6", "s7", "s8", "s9"]


# ---------------------------------------------------------------------------
# disabled-by-default contract
# ---------------------------------------------------------------------------


def test_disabled_default_is_noop():
    telemetry.shutdown()
    assert not telemetry.enabled()
    sink = telemetry.get()
    assert sink is telemetry.NULL
    assert sink.registry is None and sink.pusher is None  # no sockets
    # module API is callable and inert
    telemetry.inc("x")
    telemetry.set_gauge("y", 1)
    telemetry.observe("z", 0.1)
    with telemetry.span("s") as attrs:
        assert attrs == {}
    assert telemetry.get().snapshot()["counters"] == {}


def test_configure_with_disabled_config_keeps_null(tmp_name_resolve):
    out = telemetry.configure("e", "t", "trainer", 0,
                              TelemetryConfig(enabled=False))
    assert out is telemetry.NULL
    assert not telemetry.enabled()
    # no aggregator endpoint, no pusher socket was created
    with pytest.raises(Exception):
        name_resolve.get(names.telemetry_aggregator("e", "t"))


# ---------------------------------------------------------------------------
# Prometheus rendering
# ---------------------------------------------------------------------------


def test_prometheus_rendering():
    r = telemetry.TelemetryRegistry()
    r.inc("reqs.ok", 5)
    r.set_gauge("queue/depth", 2)
    r.observe("lat", 0.3, buckets=(0.1, 1.0))
    r.observe("lat", 5.0)
    text = telemetry.render_prometheus(
        r.snapshot(),
        extra_gauges={"weight_version": 3, "skipped_none": None,
                      "skipped_str": "nope"},
        labels={"server_id": "gen0"},
    )
    lines = text.splitlines()
    assert '# TYPE areal_weight_version gauge' in lines
    assert 'areal_weight_version{server_id="gen0"} 3' in lines
    assert '# TYPE areal_reqs_ok_total counter' in lines
    assert 'areal_reqs_ok_total{server_id="gen0"} 5' in lines
    assert 'areal_queue_depth{server_id="gen0"} 2' in lines
    # histogram: cumulative buckets, +Inf, sum, count
    assert 'areal_lat_bucket{le="0.1",server_id="gen0"} 0' in lines
    assert 'areal_lat_bucket{le="1",server_id="gen0"} 1' in lines
    assert 'areal_lat_bucket{le="+Inf",server_id="gen0"} 2' in lines
    assert 'areal_lat_sum{server_id="gen0"} 5.3' in lines
    assert 'areal_lat_count{server_id="gen0"} 2' in lines
    # nothing for the unrepresentable extra gauges
    assert "skipped" not in text
    # every sample line is "name{labels} value"
    for ln in lines:
        if ln.startswith("#"):
            continue
        name, _, val = ln.rpartition(" ")
        float(val)
        assert name and " " not in name


def test_prometheus_inline_label_suffix():
    """Registry keys may carry an inline label suffix
    (`supervisor/restarts{worker_kind=rollout}`): samples of the same
    family render under ONE # TYPE line with merged labels — the idiom
    the supervisor uses for per-kind restart counters (ISSUE 9
    acceptance: supervisor_restarts_total{worker_kind=...})."""
    r = telemetry.TelemetryRegistry()
    r.inc("supervisor/restarts{worker_kind=rollout}", 2)
    r.inc("supervisor/restarts{worker_kind=gen_fleet}")
    r.set_gauge("supervisor/crash_loop_open{worker_kind=rollout}", 0)
    text = telemetry.render_prometheus(r.snapshot(),
                                       labels={"host": "h0"})
    lines = text.splitlines()
    assert lines.count("# TYPE areal_supervisor_restarts_total counter") == 1
    assert ('areal_supervisor_restarts_total'
            '{host="h0",worker_kind="rollout"} 2') in lines
    assert ('areal_supervisor_restarts_total'
            '{host="h0",worker_kind="gen_fleet"} 1') in lines
    assert ('areal_supervisor_crash_loop_open'
            '{host="h0",worker_kind="rollout"} 0') in lines


def _fake_agg_render(snap):
    """Render one worker's snapshot through the aggregator's merged
    exposition path without constructing a live aggregator."""
    import types

    from areal_tpu.base import telemetry as T

    empty = {"counters": {}, "gauges": {}, "hists": {}}
    fake = types.SimpleNamespace(
        merged=lambda: {"master:0": snap},
        stitcher=types.SimpleNamespace(registry=types.SimpleNamespace(
            snapshot=lambda reset=False: empty,
        )),
        sentinel=None,
    )
    return T.TelemetryAggregator.render_prometheus(fake)


def test_aggregator_exposition_inline_labels():
    r = telemetry.TelemetryRegistry()
    r.inc("supervisor/restarts{worker_kind=rollout}", 3)
    text = _fake_agg_render(r.snapshot())
    lines = text.splitlines()
    # worker_kind from the key WINS over the identity label (master:0)
    assert ('areal_supervisor_restarts_total'
            '{worker_index="0",worker_kind="rollout"} 3') in lines
    assert lines.count("# TYPE areal_supervisor_restarts_total counter") == 1


# ---------------------------------------------------------------------------
# aggregator merge across fake workers
# ---------------------------------------------------------------------------


def _wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_aggregator_merges_fake_workers(tmp_name_resolve, tmp_path):
    jsonl = str(tmp_path / "telemetry.jsonl")
    agg = telemetry.TelemetryAggregator("e", "t", jsonl_path=jsonl)
    pushers = []
    try:
        for kind, idx in [("trainer", 0), ("rollout", 0), ("rollout", 1),
                          ("gserver_manager", 0)]:
            reg = telemetry.TelemetryRegistry()
            reg.inc(f"{kind}/work", idx + 1)
            reg.set_gauge("up", 1)
            with reg.span(f"{kind}/step"):
                pass
            # Huge interval: the thread never fires on its own; we flush
            # explicitly (zero real sleeps in the push path).
            p = telemetry.TelemetryPusher(reg, "e", "t", kind, idx,
                                          flush_interval_secs=3600)
            assert p.flush()
            pushers.append(p)
        assert _wait_until(lambda: len(agg.state) == 4)
        merged = agg.merged()
        assert set(merged) == {"trainer:0", "rollout:0", "rollout:1",
                               "gserver_manager:0"}
        assert merged["rollout:1"]["counters"]["rollout/work"] == 2.0
        assert merged["trainer:0"]["n_spans"] == 1
        # second flush from one worker UPDATES its key (no duplication)
        pushers[0].registry.inc("trainer/work")
        assert pushers[0].flush()
        assert _wait_until(
            lambda: agg.merged()["trainer:0"]["counters"]["trainer/work"]
            == 2.0
        )
        assert len(agg.merged()) == 4
    finally:
        for p in pushers:
            p.close()
        agg.close()
    # jsonl: one line per received snapshot, each tagged with its worker
    with open(jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert len(recs) >= 5
    kinds = {r["worker"].split(":")[0] for r in recs}
    assert {"trainer", "rollout", "gserver_manager"} <= kinds
    span_recs = [r for r in recs if r["spans"]]
    assert span_recs and all("dur_secs" in s for r in span_recs
                             for s in r["spans"])
    # merged fleet view renders as labeled Prometheus text
    # (endpoint deregistered on close, but rendering is pure)


def test_aggregator_prometheus_view(tmp_name_resolve):
    agg = telemetry.TelemetryAggregator("e2", "t", jsonl_path=None)
    pushers = []
    try:
        for idx in (0, 1):
            reg = telemetry.TelemetryRegistry()
            reg.set_gauge("depth", 4 + idx)
            reg.observe("lat", 0.2)
            p = telemetry.TelemetryPusher(reg, "e2", "t", "rollout", idx,
                                          flush_interval_secs=3600)
            assert p.flush()
            pushers.append(p)
        assert _wait_until(lambda: len(agg.state) == 2)
        text = agg.render_prometheus()
        assert 'areal_depth{worker_index="0",worker_kind="rollout"} 4' \
            in text
        assert 'areal_depth{worker_index="1",worker_kind="rollout"} 5' \
            in text
        # one exposition: same-family samples from both workers share ONE
        # TYPE line (expfmt consumers reject duplicate TYPE lines)
        assert text.count("# TYPE areal_depth gauge") == 1
        assert text.count("# TYPE areal_lat histogram") == 1
        lines = text.splitlines()
        i = lines.index("# TYPE areal_depth gauge")
        assert lines[i + 1].startswith("areal_depth{")
        assert lines[i + 2].startswith("areal_depth{")
    finally:
        for p in pushers:
            p.close()
        agg.close()


def test_pusher_backlog_preserves_spans(tmp_name_resolve):
    """A backlogged aggregator (PUSH queue full → zmq.Again) must not
    lose spans: the unsent snapshot is retained and the registry is not
    drained again until it goes out."""
    name_resolve.add(names.telemetry_aggregator("bk", "t"),
                     "tcp://127.0.0.1:1")  # nobody listening: queue fills
    reg = telemetry.TelemetryRegistry()
    p = telemetry.TelemetryPusher(reg, "bk", "t", "trainer", 0,
                                  flush_interval_secs=3600)
    ok = True
    for i in range(200):
        with reg.span(f"s{i}"):
            pass
        ok = p.flush()
        if not ok:
            break
    assert not ok, "send queue never filled"
    assert p._pending is not None  # the failed snapshot is retained
    with reg.span("kept"):
        pass
    assert p.flush() is False  # still backlogged: registry NOT drained
    snap = reg.snapshot(reset=False)
    assert any(s["name"] == "kept" for s in snap["spans"])
    p.close()


def test_pusher_without_aggregator_is_lossless_noop(tmp_name_resolve):
    """No aggregator registered: flush() reports False and nothing
    raises; metrics keep accumulating locally."""
    reg = telemetry.TelemetryRegistry()
    p = telemetry.TelemetryPusher(reg, "nowhere", "t", "trainer", 0,
                                  flush_interval_secs=3600)
    reg.inc("c")
    assert p.flush() is False
    assert reg.snapshot()["counters"]["c"] == 1.0
    p.close()


# ---------------------------------------------------------------------------
# profiler-trigger plumbing
# ---------------------------------------------------------------------------


def test_profiler_trigger_roundtrip(tmp_name_resolve, tmp_path):
    calls = []
    w = telemetry.ProfilerTriggerWatcher(
        "e", "t", poll_secs=0.0,
        start_fn=lambda d: calls.append(("start", d)),
        stop_fn=lambda: calls.append(("stop",)),
    )
    w.poll()  # no trigger pending: no-op
    assert calls == [] and not w.capturing
    out = str(tmp_path / "prof")
    telemetry.request_profiler_capture("e", "t", out, secs=0.0)
    w.poll()  # picks up the trigger, starts the capture
    assert calls == [("start", out)] and w.capturing
    st = telemetry.read_profiler_status("e", "t")
    assert st["state"] == "capturing" and st["dir"] == out
    # the trigger was consumed exactly once
    with pytest.raises(Exception):
        name_resolve.get(names.profiler_trigger("e", "t"))
    w.poll()  # secs=0: the window already elapsed → stop + status
    assert calls[-1] == ("stop",) and not w.capturing
    assert telemetry.read_profiler_status("e", "t")["state"] == "done"


def test_profiler_trigger_failure_reports_status(tmp_name_resolve, tmp_path):
    def boom(d):
        raise RuntimeError("no profiler here")

    w = telemetry.ProfilerTriggerWatcher("e", "t", poll_secs=0.0,
                                         start_fn=boom,
                                         stop_fn=lambda: None)
    telemetry.request_profiler_capture("e", "t", str(tmp_path), secs=1.0)
    w.poll()
    st = telemetry.read_profiler_status("e", "t")
    assert st["state"] == "failed" and "no profiler" in st["error"]
    assert not w.capturing  # watcher stays usable for the next trigger


# ---------------------------------------------------------------------------
# thread-safe StatsTracker (satellite: export vs concurrent recording)
# ---------------------------------------------------------------------------


def test_stats_tracker_concurrent_export():
    from areal_tpu.base.stats_tracker import StatsTracker

    tr = StatsTracker()
    stop = threading.Event()
    errors = []

    def record():
        i = 0
        while not stop.is_set():
            try:
                with tr.scope("w"):
                    tr.scalar(x=float(i))
                    tr.moving_avg(y=float(i))
                i += 1
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(e)
                return

    threads = [threading.Thread(target=record) for _ in range(4)]
    for t in threads:
        t.start()
    total = 0
    for _ in range(200):
        out = tr.export(reset=True)
        # scoped keys never tear across threads (thread-local scope stack)
        assert all(k.startswith("w/") for k in out)
        total += len(out)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    assert total > 0
