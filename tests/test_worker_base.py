"""Worker lifecycle FSM + control channel (system/worker_base.py; reference
worker_base.py:474 configure→running→paused→exiting semantics)."""

import threading
import time

from areal_tpu.system.worker_base import (
    WorkerControl,
    WorkerControlPanel,
    WorkerState,
)

EXP, TRIAL = "wbexp", "t0"


def _loop_worker(name, counter, stop_evt, reconfigured):
    ctrl = WorkerControl(EXP, TRIAL, name)
    ctrl.on_reconfigure(lambda payload: reconfigured.append(payload) or "ok")
    while not stop_evt.is_set():
        ctrl.step(lambda: {"count": counter[0]})
        if ctrl.should_exit:
            break
        counter[0] += 1
        time.sleep(0.005)
    ctrl.close()


def test_pause_resume_status_exit(tmp_name_resolve):
    counter = [0]
    stop = threading.Event()
    reconf = []
    t = threading.Thread(
        target=_loop_worker, args=("w0", counter, stop, reconf), daemon=True
    )
    t.start()
    panel = WorkerControlPanel(EXP, TRIAL)
    try:
        st = panel.status("w0")
        assert st["ok"] and st["state"] == WorkerState.RUNNING.value
        assert st["worker"] == "w0" and "uptime_s" in st

        # pause: the loop must stop advancing
        assert panel.pause("w0")["state"] == WorkerState.PAUSED.value
        time.sleep(0.05)
        frozen = panel.status("w0")["count"]
        time.sleep(0.1)
        assert panel.status("w0")["count"] == frozen
        assert panel.status("w0")["state"] == WorkerState.PAUSED.value

        # reconfigure works while paused (the reference's reason for pause)
        r = panel.reconfigure("w0", {"lr": 1e-4})
        assert r["ok"] and r["result"] == "ok"
        assert reconf == [{"lr": 1e-4}]

        # resume: it advances again
        assert panel.resume("w0")["state"] == WorkerState.RUNNING.value
        time.sleep(0.1)
        assert panel.status("w0")["count"] > frozen

        # discovery
        assert panel.list_workers() == ["w0"]

        # exit: thread drains
        panel.exit("w0")
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        stop.set()
        panel.close()


def test_panel_recovers_after_timeout(tmp_name_resolve):
    """A command that times out (worker busy in a long step) must not
    brick the panel's REQ socket — the next command reconnects."""
    import pytest

    counter = [0]
    stop = threading.Event()
    hold = threading.Event()

    def slow_worker():
        ctrl = WorkerControl(EXP, TRIAL, "slow")
        ctrl.step()  # register + enter RUNNING
        hold.wait(timeout=30)  # simulate a long step: control unserved
        while not stop.is_set():
            ctrl.step(lambda: {"count": counter[0]})
            if ctrl.should_exit:
                break
            time.sleep(0.005)
        ctrl.close()

    t = threading.Thread(target=slow_worker, daemon=True)
    t.start()
    panel = WorkerControlPanel(EXP, TRIAL, timeout=0.5)
    try:
        with pytest.raises(TimeoutError):
            panel.status("slow")  # worker is "busy"; 0.5s timeout fires
        hold.set()  # step finishes; control served again
        time.sleep(0.1)
        st = panel.status("slow")  # fresh socket; must work
        assert st["ok"] and st["state"] == "running"
        panel.exit("slow")
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        stop.set()
        hold.set()
        panel.close()


def test_consumed_log_roundtrip(tmp_path):
    """Async-recovery skiplist (rollout_worker.ConsumedLog): a restarted
    worker must skip uids consumed before the crash."""
    from areal_tpu.system.rollout_worker import ConsumedLog

    log = ConsumedLog(str(tmp_path), worker_index=2)
    assert "q1" not in log
    log.add("q1")
    log.add("q2@r1")
    log.add("q1")  # idempotent
    assert "q1" in log and "q2@r1" in log

    # "restart": a fresh instance reads the same file
    log2 = ConsumedLog(str(tmp_path), worker_index=2)
    assert "q1" in log2 and "q2@r1" in log2 and "q3" not in log2
    # a different worker index has its own log
    other = ConsumedLog(str(tmp_path), worker_index=3)
    assert "q1" not in other
    # no recover dir -> in-memory only
    mem = ConsumedLog("", worker_index=0)
    mem.add("x")
    assert "x" in mem


def test_custom_command_served_even_while_paused(tmp_name_resolve):
    """on_command handlers (e.g. the master's out-of-band `checkpoint`)
    are dispatched from within step() — including from the PAUSED loop,
    which is exactly where the graceful drain invokes them."""
    calls = []
    stop = threading.Event()

    def worker():
        ctrl = WorkerControl(EXP, TRIAL, "cmd0")
        ctrl.on_command("checkpoint",
                        lambda p: calls.append(p) or {"saved": True})
        while not stop.is_set():
            ctrl.step()
            if ctrl.should_exit:
                break
            time.sleep(0.005)
        ctrl.close()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    panel = WorkerControlPanel(EXP, TRIAL)
    try:
        r = panel.command("cmd0", "checkpoint", payload={"k": 1})
        assert r["ok"] and r["result"] == {"saved": True}
        assert calls == [{"k": 1}]
        # while paused, the command is still served (pause loop)
        assert panel.pause("cmd0")["state"] == WorkerState.PAUSED.value
        r = panel.command("cmd0", "checkpoint")
        assert r["ok"]
        assert panel.status("cmd0")["state"] == WorkerState.PAUSED.value
        # unknown commands still error cleanly
        r = panel.command("cmd0", "no_such_cmd")
        assert not r["ok"] and "unknown command" in r["error"]
        panel.exit("cmd0")
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        stop.set()
        panel.close()


def test_freq_ctl_state_roundtrip():
    """RecoverInfo freq-ctl states: a restored controller keeps its
    last-fired anchors instead of re-firing immediately."""
    from areal_tpu.base.timeutil import FrequencyControl

    c = FrequencyControl(freq_step=5)
    assert not c.check(epochs=0, steps=3)
    assert c.check(epochs=0, steps=5)
    st = c.state_dict()
    c2 = FrequencyControl(freq_step=5)
    c2.load_state_dict(st)
    assert not c2.check(epochs=0, steps=6)
    assert c2.check(epochs=0, steps=10)


def test_multiple_workers_discovered(tmp_name_resolve):
    stop = threading.Event()
    threads = []
    for i in range(3):
        t = threading.Thread(
            target=_loop_worker, args=(f"w{i}", [0], stop, []), daemon=True
        )
        t.start()
        threads.append(t)
    panel = WorkerControlPanel(EXP, TRIAL)
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(panel.list_workers()) == 3:
                break
            time.sleep(0.02)
        assert panel.list_workers() == ["w0", "w1", "w2"]
        states = panel.pause_all()
        assert all(v["state"] == "paused" for v in states.values())
        states = panel.resume_all()
        assert all(v["state"] == "running" for v in states.values())
        for w in panel.list_workers():
            panel.exit(w)
        for t in threads:
            t.join(timeout=5)
            assert not t.is_alive()
    finally:
        stop.set()
        panel.close()
