"""Dataset loaders + reward verifier tests (mirrors the reference's
tests/data + tests/reward suites)."""

import json

import numpy as np
import pytest

from areal_tpu.base.testing import (
    MockTokenizer,
    make_code_jsonl,
    make_math_jsonl,
    make_sft_jsonl,
)
from areal_tpu.datasets.jsonl import (
    MathCodePromptDataset,
    PromptAnswerDataset,
    PromptDataset,
    RewardModelingPairedDataset,
    load_shuffle_split,
)
from areal_tpu.rewards import code_verify, math_verify
from areal_tpu.rewards.client import batch_reward


@pytest.fixture()
def tok():
    return MockTokenizer()


def test_load_shuffle_split_disjoint_and_complete():
    data = [{"i": i} for i in range(103)]
    shards = [load_shuffle_split(data, seed=7, dp_rank=r, dp_size=4) for r in range(4)]
    seen = [d["i"] for s in shards for d in s]
    assert sorted(seen) == list(range(103))
    # deterministic
    again = load_shuffle_split(data, seed=7, dp_rank=2, dp_size=4)
    assert [d["i"] for d in again] == [d["i"] for d in shards[2]]
    # different seed shuffles differently
    other = load_shuffle_split(data, seed=8, dp_rank=2, dp_size=4)
    assert [d["i"] for d in other] != [d["i"] for d in shards[2]]


def test_prompt_and_sft_datasets(tmp_path, tok):
    p = tmp_path / "math.jsonl"
    make_math_jsonl(str(p), n=10)
    ds = PromptDataset(dataset_path=str(p), tokenizer=tok)
    assert len(ds) == 10
    s = ds[0]
    assert s.keys == {"packed_prompts"}
    assert s.data["packed_prompts"].dtype == np.int32

    sp = tmp_path / "sft.jsonl"
    make_sft_jsonl(str(sp), n=8)
    sft = PromptAnswerDataset(dataset_path=str(sp), tokenizer=tok)
    s = sft[0]
    assert s.keys == {"packed_input_ids", "prompt_mask"}
    m = s.data["prompt_mask"]
    assert m[0] == 1 and m[-1] == 0  # prompt prefix masked, answer not
    assert len(s.data["packed_input_ids"]) == len(m)


def test_paired_dataset(tok, tmp_path):
    p = tmp_path / "rw.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({
            "query_id": "r0", "prompt": "Q: ",
            "pos_answers": ["good", "better"], "neg_answers": ["bad", "worse"],
        }) + "\n")
    ds = RewardModelingPairedDataset(dataset_path=str(p), tokenizer=tok)
    s = ds[0]
    assert len(s.seqlens["packed_input_ids"][0]) == 4  # 2 pairs × (pos, neg)
    assert s.metadata["n_pairs"] == [2]


def test_math_code_dataset_validation_and_filter(tmp_path, tok):
    p = tmp_path / "mc.jsonl"
    recs = make_math_jsonl(str(p), n=12)
    # append one invalid record (no solutions list)
    with open(p, "a") as f:
        f.write(json.dumps({"query_id": "bad", "prompt": "x", "task": "math"}) + "\n")
    ds = MathCodePromptDataset(dataset_path=str(p), tokenizer=tok,
                               filter_threshold=0.9, max_filter_percentage=0.5)
    assert len(ds) == 12  # invalid dropped
    s = ds[0]
    assert "task_ids" in s.keys
    # mark half the prompts as "too easy" (score 1.0 > threshold 0.9)
    easy = [str(r["query_id"]) for r in recs[:6]]
    ds.filter({q: 1.0 for q in easy})
    assert len(ds) <= 12 and len(ds) >= 6


def test_math_extract_and_equal():
    assert math_verify.extract_answer("so \\boxed{42} done") == "42"
    assert math_verify.extract_answer("nested \\boxed{\\frac{1}{2}}") == "\\frac{1}{2}"
    assert math_verify.extract_answer("the answer is 3/4.") == "3/4"
    assert math_verify.extract_answer("The answer is 2.5") == "2.5"
    assert math_verify.extract_answer("answer is 1,000.") == "1,000"
    assert math_verify.extract_answer("the answer is 5, which is prime") == "5"
    assert math_verify.extract_answer("blah 7 blah 9") == "9"
    assert math_verify.math_equal("\\frac{1}{2}", "0.5")
    assert math_verify.math_equal("1,000", "1000")
    assert math_verify.math_equal("50%", "1/2")
    assert math_verify.math_equal("-\\frac{2}{4}", "-0.5")
    assert not math_verify.math_equal("0.5", "0.51")
    assert math_verify.verify_math("answer: \\boxed{8}", ["\\boxed{8}"]) == 1.0
    assert math_verify.verify_math("I think \\boxed{7}", ["\\boxed{8}"]) == 0.0


def test_math_equal_deep_semantics():
    """Reference math_parser.py:497 semantic surface: MC letters, the
    percentage triplet, tuples/intervals, matrices, equations, symbolic."""
    me = math_verify.math_equal
    # multiple choice: last standalone letter wins
    assert me("The answer is (C)", "C")
    assert me("A or maybe B", "B")
    assert not me("The answer is (C)", "D")
    # percentage triplet: ref accepted at 1x, /100, *100
    assert me("0.5", "50")
    assert me("50", "0.5")
    # mixed numbers + \tfrac
    assert me("1\\frac{1}{2}", "1.5")
    assert me("\\tfrac{3}{4}", "0.75")
    assert not me("12/5", "1.4")  # NOT a mixed number
    # scientific notation
    assert me("1.5e3", "1500")
    # tuples / intervals: element-wise, order-sensitive
    assert me("(1, 2)", "(1,2)")
    assert me("(\\frac{1}{2}, 3)", "(0.5, 3)")
    assert not me("(1, 2)", "(2, 1)")
    assert me("[0, \\pi)", "[0,pi)")
    # endpoint inclusion matters: same content, different bracket types
    assert not me("(0,1]", "[0,1)")
    assert not me("(0, 1)", "[0, 1]")
    assert me("(0,1]", "(0, 1]")
    # matrices, element-wise
    assert me(
        "\\begin{pmatrix}1 & 2\\\\3 & 4\\end{pmatrix}",
        "\\begin{bmatrix}1 & 2.0\\\\3 & 4\\end{bmatrix}",
    )
    assert not me(
        "\\begin{pmatrix}1 & 2\\\\3 & 4\\end{pmatrix}",
        "\\begin{pmatrix}1 & 2\\\\3 & 5\\end{pmatrix}",
    )
    # equations
    assert me("x = 5", "5")
    assert me("5", "y=5")
    assert me("x + y = 3", "y + x = 3")
    # symbolic
    assert me("\\frac{\\sqrt{2}}{2}", "1/\\sqrt{2}")
    assert me("2x + x", "3x")
    assert not me("2x", "3x")


def test_code_verify_stdin(tmp_path):
    gen = "```python\nx = int(input())\nprint(x + 3)\n```"
    io = {"inputs": ["1\n", "5\n"], "outputs": ["4\n", "8\n"]}
    assert code_verify.verify_code(gen, io) == 1.0
    bad = "```python\nx = int(input())\nprint(x + 4)\n```"
    assert code_verify.verify_code(bad, io) == 0.0


def test_code_verify_fn_name():
    gen = "```python\ndef add(a, b):\n    return a + b\n```"
    io = {"inputs": [json.dumps([1, 2]), json.dumps([5, 6])],
          "outputs": [json.dumps(3), json.dumps(11)], "fn_name": "add"}
    assert code_verify.verify_code(gen, io) == 1.0


def test_batch_reward_local_dispatch():
    tasks = [
        {"task": "math", "generated": "\\boxed{4}", "solutions": ["\\boxed{4}"]},
        {"task": "math", "generated": "\\boxed{5}", "solutions": ["\\boxed{4}"]},
    ]
    assert batch_reward(tasks) == [1.0, 0.0]


class TestSandboxHardening:
    """reference testing_util.py:702-760 reliability_guard parity: untrusted
    code is boxed by rlimits + an os/builtins disarm preamble."""

    IO = '{"inputs": ["1\\n"], "outputs": ["1\\n"]}'

    def test_normal_solution_still_passes(self):
        gen = "```python\nprint(input())\n```"
        assert code_verify.verify_code(gen, self.IO) == 1.0

    def test_memory_hog_killed(self):
        gen = "```python\nx = bytearray(8 * 1024**3)\nprint(input())\n```"
        assert code_verify.verify_code(gen, self.IO, timeout=20.0) == 0.0

    def test_os_system_disarmed(self):
        gen = (
            "```python\nimport os\nos.system('echo pwned')\n"
            "print(input())\n```"
        )
        assert code_verify.verify_code(gen, self.IO) == 0.0

    def test_subprocess_disarmed(self):
        gen = (
            "```python\nimport subprocess\n"
            "subprocess.run(['echo', 'hi'])\nprint(input())\n```"
        )
        assert code_verify.verify_code(gen, self.IO) == 0.0

    def test_cpu_spin_killed(self):
        gen = "```python\nwhile True: pass\n```"
        assert code_verify.verify_code(gen, self.IO, timeout=3.0) == 0.0

    def test_file_write_confined_to_scratch(self, tmp_path):
        marker = tmp_path / "escape.txt"
        gen = (
            "```python\n"
            "open('escape.txt', 'w').write('x')\n"  # lands in scratch cwd
            "print(input())\n```"
        )
        assert code_verify.verify_code(gen, self.IO) == 1.0
        assert not marker.exists()
