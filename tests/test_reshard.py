"""Mesh→mesh on-device reshard tests (parallel/reshard.py).

The resharding core behind the ``device`` weight-sync transport and
heterogeneous per-MFC meshes: plan correctness (zero-copy recognition,
transfer-group bounding), value preservation across layout changes on the
8-virtual-device CPU platform, and the publish/consume registry's
version + digest gates.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.parallel import reshard as rsh
from areal_tpu.parallel.mesh import ParallelSpec, make_mesh

pytestmark = pytest.mark.reshard


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "embedding": jnp.asarray(rng.randn(32, 8).astype(np.float32)),
        "layers": {
            "wq": jnp.asarray(rng.randn(2, 8, 8).astype(np.float32)),
            "w_up": jnp.asarray(rng.randn(2, 8, 16).astype(np.float32)),
        },
        "final_ln": jnp.asarray(rng.randn(8).astype(np.float32)),
    }


def _shardings(mesh):
    return {
        "embedding": NamedSharding(mesh, P("fsdp", "tp")),
        "layers": {
            "wq": NamedSharding(mesh, P(None, "fsdp", "tp")),
            "w_up": NamedSharding(mesh, P(None, "fsdp", "tp")),
        },
        "final_ln": NamedSharding(mesh, P()),
    }


def _place(tree, shardings):
    placed = jax.tree.map(jax.device_put, tree, shardings)
    jax.block_until_ready(placed)
    return placed


def _assert_trees_equal(a, b):
    fa, fb = rsh._flatten(a), rsh._flatten(b)
    assert set(fa) == set(fb)
    for name in fa:
        np.testing.assert_array_equal(np.asarray(fa[name]),
                                      np.asarray(fb[name]), err_msg=name)


@pytest.mark.parametrize("src,dst", [
    ("d4", "t4"),           # dp → tp
    ("t4", "d4"),           # tp → dp
    ("f4", "d1"),           # fsdp → replicated-ish single device spec
    ("f2t2", "d2f2"),       # mixed 2D → 2D
])
def test_reshard_values_survive_layout_change(src, dst):
    tree = _tree()
    src_placed = _place(tree, _shardings(make_mesh(ParallelSpec.parse(src))))
    dst_sh = _shardings(make_mesh(ParallelSpec.parse(dst)))
    out, plan = rsh.reshard_pytree(src_placed, dst_sh)
    assert plan.n_moved > 0
    _assert_trees_equal(out, tree)
    # every leaf actually landed in the target sharding
    for name, leaf in rsh._flatten(out).items():
        want = rsh._flatten(dst_sh)[name]
        assert leaf.sharding.is_equivalent_to(want, len(leaf.shape)), name


def test_same_spec_is_zero_copy_noop():
    mesh = make_mesh(ParallelSpec.parse("f2t2"))
    placed = _place(_tree(), _shardings(mesh))
    out, plan = rsh.reshard_pytree(placed, _shardings(mesh))
    assert plan.n_moved == 0 and not plan.groups
    # identical leaves are passed through as the SAME array objects
    fo, fp = rsh._flatten(out), rsh._flatten(placed)
    for name in fp:
        assert fo[name] is fp[name], name


def test_plan_groups_bound_bytes():
    mesh = make_mesh(ParallelSpec.parse("d4"))
    tgt = make_mesh(ParallelSpec.parse("t4"))
    tree = {f"w{i}": jnp.zeros((16, 8), jnp.float32) for i in range(10)}
    sh_src = {k: NamedSharding(mesh, P("dp", None)) for k in tree}
    sh_dst = {k: NamedSharding(tgt, P(None, "tp")) for k in tree}
    placed = _place(tree, sh_src)
    leaf_bytes = 16 * 8 * 4
    plan = rsh.plan_reshard(rsh._flatten(placed), sh_dst,
                            group_bytes=2 * leaf_bytes)
    assert plan.n_moved == 10
    assert len(plan.groups) == 5  # 2 leaves per group at a 2-leaf budget
    for g in plan.groups:
        assert sum(rsh._leaf_nbytes(placed[n]) for n in g) <= 2 * leaf_bytes
    out = rsh.execute_reshard(rsh._flatten(placed), sh_dst, plan)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(tree[k]))


def test_plan_rejects_tree_mismatch():
    mesh = make_mesh(ParallelSpec.parse("d2"))
    a = {"x": jnp.zeros((4,))}
    with pytest.raises(ValueError, match="differ"):
        rsh.plan_reshard(a, {"y": NamedSharding(mesh, P())})


def test_host_path_matches_device_path():
    src_placed = _place(_tree(), _shardings(make_mesh(ParallelSpec.parse("d4"))))
    dst_sh = _shardings(make_mesh(ParallelSpec.parse("t4")))
    via_dev, _ = rsh.reshard_pytree(src_placed, dst_sh)
    via_host = rsh.reshard_via_host(src_placed, dst_sh)
    _assert_trees_equal(via_dev, via_host)


def test_manifest_digest_is_stable_and_version_bound():
    flat = rsh._flatten(_place(_tree(), _shardings(
        make_mesh(ParallelSpec.parse("d2")))))
    m = rsh.build_manifest(flat)
    assert rsh.manifest_digest(m, 3) == rsh.manifest_digest(m, 3)
    assert rsh.manifest_digest(m, 3) != rsh.manifest_digest(m, 4)


def test_publish_consume_roundtrip(tmp_name_resolve):
    from areal_tpu.base import name_resolve, names

    tree = _tree(seed=7)
    src = _place(tree, _shardings(make_mesh(ParallelSpec.parse("f2t2"))))
    live = _place(_tree(seed=8), _shardings(make_mesh(ParallelSpec.parse("d4"))))
    pub = rsh.publish_device(
        "exp", "t0", "actor", src,
        target_shardings=rsh.shardings_of(live), version=5,
    )
    # discovery key carries the out-of-band version + digest
    desc = json.loads(name_resolve.get(names.weight_device("exp", "t0", "actor")))
    assert desc["version"] == 5 and desc["digest"] == pub.digest

    got = rsh.consume_device("exp", "t0", "actor", 5, pub.digest, live)
    _assert_trees_equal(got, tree)  # publisher's values, consumer's layout
    for name, leaf in rsh._flatten(got).items():
        live_leaf = rsh._flatten(live)[name]
        assert leaf.sharding.is_equivalent_to(
            live_leaf.sharding, len(leaf.shape)), name

    with pytest.raises(rsh.DeviceReshardError, match="version skew"):
        rsh.consume_device("exp", "t0", "actor", 6, pub.digest, live)
    with pytest.raises(rsh.DeviceReshardError, match="digest"):
        rsh.consume_device("exp", "t0", "actor", 5, "deadbeef", live)
    with pytest.raises(rsh.DeviceReshardError, match="tree mismatch"):
        rsh.consume_device("exp", "t0", "actor", 5, pub.digest,
                           {"other": live["embedding"]})

    rsh.clear_publication("exp", "t0", "actor")
    assert rsh.lookup_publication("exp", "t0", "actor") is None
    with pytest.raises(rsh.DeviceReshardError, match="no device publication"):
        rsh.consume_device("exp", "t0", "actor", 5, pub.digest, live)


def test_consume_missing_publication_raises(tmp_name_resolve):
    live = _place(_tree(), _shardings(make_mesh(ParallelSpec.parse("d2"))))
    with pytest.raises(rsh.DeviceReshardError, match="share one JAX runtime"):
        rsh.consume_device("nope", "t0", "actor", 1, "0" * 8, live)


def test_consume_casts_to_live_dtype(tmp_name_resolve):
    tree = _tree(seed=3)
    bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tree)
    src = _place(bf16, _shardings(make_mesh(ParallelSpec.parse("d2"))))
    live = _place(tree, _shardings(make_mesh(ParallelSpec.parse("t2"))))
    pub = rsh.publish_device("exp", "t1", "actor", src,
                             target_shardings=rsh.shardings_of(src), version=1)
    got = rsh.consume_device("exp", "t1", "actor", 1, pub.digest, live)
    for name, leaf in rsh._flatten(got).items():
        assert leaf.dtype == jnp.float32, name
    rsh.clear_publication("exp", "t1", "actor")


def test_latest_wins_registry(tmp_name_resolve):
    src = _place(_tree(), _shardings(make_mesh(ParallelSpec.parse("d2"))))
    rsh.publish_device("exp", "t2", "actor", src,
                       target_shardings=rsh.shardings_of(src), version=1)
    pub2 = rsh.publish_device("exp", "t2", "actor", src,
                              target_shardings=rsh.shardings_of(src),
                              version=2)
    assert rsh.lookup_publication("exp", "t2", "actor").version == 2
    # the old fanout (v1) now fails the version gate instead of swapping
    with pytest.raises(rsh.DeviceReshardError, match="version skew"):
        rsh.consume_device("exp", "t2", "actor", 1, pub2.digest, src)
    rsh.clear_publication("exp", "t2", "actor")
