"""Device-side advantage prep (make_advantage_prep over an uploaded
UniformBatch) must match the host path (compute_advantages_and_returns +
normalize_advantages) exactly, and the uniform train path must take the
same optimizer step as the legacy per-micro-batch path."""

import copy

import numpy as np
import pytest

import jax

from areal_tpu.algorithms.ppo import (
    PPOActorInterface,
    PPOHyperparameters,
    attach_keys,
    compute_advantages_and_returns,
    normalize_advantages,
)
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import FinetuneSpec, Model
from areal_tpu.backend import microbatch as mbu
from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config


def _make_batch(n_seq=9, vocab=128, seed=0, with_values=False, with_ref=True):
    rng = np.random.RandomState(seed)
    plens = rng.randint(2, 6, n_seq)
    glens = rng.randint(4, 12, n_seq)
    seqlens = (plens + glens).astype(int)
    total = int(seqlens.sum())
    pmask = np.concatenate([
        np.concatenate([np.ones(p, np.int32), np.zeros(g, np.int32)])
        for p, g in zip(plens, glens)
    ])
    data = {
        "packed_input_ids": rng.randint(2, vocab, total).astype(np.int32),
        "prompt_mask": pmask,
        "packed_logprobs": np.where(
            pmask == 0, -rng.rand(total), 0.0).astype(np.float32),
        "rewards": rng.randn(n_seq).astype(np.float32),
        "seq_no_eos_mask": (rng.rand(n_seq) < 0.3).astype(np.float32),
    }
    if with_ref:
        data["packed_ref_logprobs"] = np.where(
            pmask == 0, -rng.rand(total), 0.0).astype(np.float32)
    if with_values:
        data["values"] = rng.randn(total).astype(np.float32)
    return SequenceSample.from_default(
        ids=[f"s{i}" for i in range(n_seq)],
        data=data,
        seqlens=seqlens.tolist(),
    )


def _engine(vocab=128, seed=0):
    cfg = tiny_config(vocab_size=vocab)
    params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
    model = Model("actor", (cfg, params), tokenizer=None)
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
        compute_dtype="float32", length_bucket=16, rows_bucket=2,
        seqs_bucket=4,
    )
    return backend.initialize(model, FinetuneSpec(1, 8, 4))


@pytest.mark.parametrize("kl_coef", [0.0, 0.1])
@pytest.mark.parametrize("with_values", [False, True])
def test_device_prep_matches_host_path(kl_coef, with_values):
    hp = PPOHyperparameters(adv_norm=True, kl_ctl=kl_coef,
                            disable_value=not with_values)
    batch = _make_batch(with_values=with_values)
    # Host path.
    extra = compute_advantages_and_returns(batch, hp, kl_coef)
    host_kl = extra.pop("_mean_kl")
    host = attach_keys(batch, extra)
    normalize_advantages(host, hp)

    # Device path on an uploaded uniform batch.
    model = _engine()
    eng = model.module
    iface = PPOActorInterface(hp)
    ub = eng.upload_uniform(batch, MicroBatchSpec(max_tokens_per_mb=64))
    scalars = eng.run_prep(
        ub, iface._prep_fn, iface._prep_fn, scalars={"kl_coef": kl_coef}
    )
    assert float(scalars["_mean_kl"]) == pytest.approx(host_kl, abs=1e-5)

    # Scatter device grids back into packed order and compare.
    adv_grid = np.asarray(ub.grids["advantages"])
    per_mb = [
        adv_grid[i * ub.R : (i + 1) * ub.R] for i in range(ub.n_mbs)
    ]
    packed = np.concatenate(
        mbu.scatter_back(ub.mbs, per_mb, batch.bs)
    )
    np.testing.assert_allclose(
        packed, host.data["advantages"], atol=1e-4, rtol=1e-4
    )


def test_train_step_uniform_matches_legacy_params():
    """The fast path and the legacy path must produce the same updated
    parameters for the same inputs (same grads → same adamw step)."""
    # One minibatch: with k>1 the two paths partition differently (token-
    # balanced vs contiguous-rows), which is a legitimate semantic
    # difference; with k=1 both take one step over identical data.
    hp = PPOHyperparameters(ppo_n_minibatches=1, adv_norm=True, kl_ctl=0.0,
                            disable_value=True)
    batch = _make_batch()
    spec = MicroBatchSpec(max_tokens_per_mb=64)

    m1 = _engine()
    i1 = PPOActorInterface(copy.deepcopy(hp))
    s1 = i1.train_step(m1, batch, spec)  # fast path (upload_uniform exists)

    m2 = _engine()
    i2 = PPOActorInterface(copy.deepcopy(hp))
    # Force the legacy path by hiding upload_uniform.
    eng2 = m2.module
    legacy = type("L", (), {})()
    for attr in ("train_batch", "forward", "params", "cfg", "opt_state"):
        setattr(legacy, attr, getattr(eng2, attr))
    legacy.train_batch = eng2.train_batch
    m2.module = legacy
    s2 = i2.train_step(m2, batch, spec)
    m2.module = eng2  # engine still holds the updated params

    for a, b in zip(
        jax.tree_util.tree_leaves(m1.module.params),
        jax.tree_util.tree_leaves(eng2.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-5
        )
    assert s1["mean_kl"] == pytest.approx(s2["mean_kl"], abs=1e-6)
    assert s1["n_action_tokens"] == s2["n_action_tokens"]


def test_fast_path_takes_n_minibatch_steps():
    """Advisor r3 (high): with the default MicroBatchSpec the packer puts
    the whole batch in one uniform micro-batch, which silently collapsed
    ppo_n_minibatches optimizer steps into one. The fast path must request
    at least ppo_n_minibatches micro-batches from the packer."""
    hp = PPOHyperparameters(ppo_n_minibatches=4, adv_norm=True, kl_ctl=0.0,
                            disable_value=True)
    batch = _make_batch(n_seq=16)
    model = _engine()
    iface = PPOActorInterface(hp)
    stats = iface.train_step(model, batch, MicroBatchSpec())
    assert stats["n_ppo_steps"] == 4.0
