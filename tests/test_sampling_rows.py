"""Per-row sampling parity: sample_token_rows with homogeneous params must
match the scalar sample_token path exactly (same warped distribution, same
greedy tokens), and heterogeneous rows must each honor their own params.
(Backs the generation server's mixed-gconfig batching, VERDICT r2 weak#9.)"""

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.ops.sampling import (
    sample_token,
    sample_token_rows,
    sampling_from_gconfigs,
    warp_logits,
    warp_logits_rows,
)


def _rand_logits(key, b=6, v=97):
    return jax.random.normal(key, (b, v)) * 3.0


def test_warp_rows_matches_scalar():
    key = jax.random.PRNGKey(0)
    logits = _rand_logits(key)
    for g in [
        GenerationHyperparameters(temperature=1.0, top_k=0, top_p=1.0),
        GenerationHyperparameters(temperature=0.7, top_k=5, top_p=1.0),
        GenerationHyperparameters(temperature=1.3, top_k=0, top_p=0.9),
        GenerationHyperparameters(temperature=0.5, top_k=11, top_p=0.8),
    ]:
        ref = warp_logits(logits, g)
        got = warp_logits_rows(
            logits,
            jnp.full((logits.shape[0],), g.temperature),
            jnp.full((logits.shape[0],), g.top_k, jnp.int32),
            jnp.full((logits.shape[0],), g.top_p),
        )
        # Same kept set (finite mask) and same values where kept.
        np.testing.assert_array_equal(
            np.asarray(ref) > -1e29, np.asarray(got) > -1e29
        )
        keep = np.asarray(ref) > -1e29
        np.testing.assert_allclose(
            np.asarray(ref)[keep], np.asarray(got)[keep], rtol=1e-6
        )


def test_greedy_rows_match_scalar():
    key = jax.random.PRNGKey(1)
    logits = _rand_logits(key)
    g = GenerationHyperparameters(greedy=True, temperature=0.8, top_k=7)
    tok_ref, lp_ref = sample_token(logits, key, g)
    s = sampling_from_gconfigs([g] * logits.shape[0])
    tok_got, lp_got = sample_token_rows(logits, key, s)
    np.testing.assert_array_equal(np.asarray(tok_ref), np.asarray(tok_got))
    np.testing.assert_allclose(
        np.asarray(lp_ref), np.asarray(lp_got), rtol=1e-6
    )


def test_heterogeneous_rows_honor_own_params():
    key = jax.random.PRNGKey(2)
    logits = _rand_logits(key, b=3)
    gs = [
        GenerationHyperparameters(greedy=True, temperature=1.0),
        GenerationHyperparameters(greedy=True, temperature=1.0, top_k=1),
        # Sampling row with tiny temperature → near-argmax.
        GenerationHyperparameters(temperature=1e-4),
    ]
    s = sampling_from_gconfigs(gs)
    toks, lps = sample_token_rows(logits, key, s)
    argmax = np.asarray(jnp.argmax(logits, axis=-1))
    np.testing.assert_array_equal(np.asarray(toks), argmax)
    # top_k=1 row has logprob ~0 (certain)
    assert abs(float(lps[1])) < 1e-5
