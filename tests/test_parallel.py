"""Mesh / sharding tests on the virtual 8-device CPU platform.

Mirrors the role of the reference's multi-process CPU comm tests
(tests/comm/, SURVEY.md §4) — but GSPMD needs no processes: correctness is
(a) spec parsing, (b) sharded forward == single-device forward, (c) grads
flow under sharding constraints.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel import mesh as pmesh
from areal_tpu.parallel import sharding as psh


def test_parallel_spec_parse():
    s = pmesh.ParallelSpec.parse("d2t4")
    assert (s.dp, s.tp) == (2, 4) and s.world_size == 8
    s = pmesh.ParallelSpec.parse("d2f2s2t1")
    assert (s.dp, s.fsdp, s.sp, s.tp) == (2, 2, 2, 1)
    # reference spelling: m = model(tensor) parallel
    s = pmesh.ParallelSpec.parse("d4p2m1")
    assert (s.dp, s.pp, s.tp) == (4, 2, 1)
    with pytest.raises(ValueError):
        pmesh.ParallelSpec.parse("d2d4")
    with pytest.raises(ValueError):
        pmesh.ParallelSpec.parse("x3")


def test_allocation_mode_parse():
    am = pmesh.AllocationMode.parse("d2t2")
    assert not am.decoupled and am.global_spec.tp == 2
    am = pmesh.AllocationMode.parse("gen.d4+train.d2t2")
    assert am.decoupled and am.gen_spec.dp == 4 and am.global_spec.tp == 2
    am = pmesh.AllocationMode.parse("sglang.d4m1p1+d2m2p2")
    assert am.decoupled and am.gen_spec.dp == 4
    assert am.global_spec.tp == 2 and am.global_spec.pp == 2
    am = pmesh.AllocationMode.parse("actor_gen:d4t2,actor_train:f4t2")
    assert am.per_mfc["actor_gen"].dp == 4 and am.global_spec.fsdp == 4


def test_allocation_mode_parse_per_mfc_edge_cases():
    # round trip: every named MFC keeps its own spec, str() re-parses
    am = pmesh.AllocationMode.parse("actor_train:f2t2,ref_inf:d2,rew_inf:d1")
    assert sorted(am.per_mfc) == ["actor_train", "ref_inf", "rew_inf"]
    for name, spec in am.per_mfc.items():
        assert pmesh.ParallelSpec.parse(str(spec)) == spec, name
    # actor_train steers the global spec; actor_gen becomes gen_spec
    am = pmesh.AllocationMode.parse("ref_inf:d2,actor_train:f4t2,actor_gen:d4")
    assert am.global_spec.fsdp == 4 and am.decoupled and am.gen_spec.dp == 4
    # whitespace around entries and names is tolerated
    am = pmesh.AllocationMode.parse("  actor_train:f2t2 , ref_inf:d2  ")
    assert am.per_mfc["ref_inf"].dp == 2
    # decoupled '+' forms with and without engine prefixes
    am = pmesh.AllocationMode.parse("d4+f2t4")
    assert am.decoupled and am.gen_spec.dp == 4 and am.global_spec.tp == 4
    # duplicate MFC names are an error, not a silent overwrite
    with pytest.raises(ValueError, match="duplicate MFC 'ref_inf'"):
        pmesh.AllocationMode.parse("ref_inf:d2,ref_inf:d4")
    # malformed entries name the offending part
    with pytest.raises(ValueError, match="malformed per-MFC"):
        pmesh.AllocationMode.parse("actor_train:f2t2,ref_inf:")
    with pytest.raises(ValueError, match="malformed per-MFC"):
        pmesh.AllocationMode.parse(":d2")


def test_spec_for_role_resolution():
    from areal_tpu.experiments import common as C

    am = pmesh.AllocationMode.parse("actor_train:f2t2,ref_inf:d2")
    assert str(C.spec_for_role(am, "actor")) == "f2t2"
    assert str(C.spec_for_role(am, "ref")) == "d2"
    # roles without an override inherit the global (= actor_train) spec
    assert str(C.spec_for_role(am, "critic")) == "f2t2"
    # the train MFC wins over the inf MFC for the same role
    am = pmesh.AllocationMode.parse("actor_inf:d4,actor_train:f2t2")
    assert str(C.spec_for_role(am, "actor")) == "f2t2"


def test_make_mesh_axes():
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
    assert m.axis_names == pmesh.AXIS_ORDER
    assert m.shape["dp"] == 2 and m.shape["fsdp"] == 2 and m.shape["tp"] == 2
    assert m.shape["pp"] == 1 and m.shape["sp"] == 1


@pytest.mark.parametrize("spec_str", ["d2f2t2", "d1f2s2t2", "f2t4"])
def test_sharded_forward_matches_single_device(spec_str):
    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))

    B, T = 4, 16
    tokens = np.random.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    ref, _ = transformer.forward(params, cfg, tokens, positions, segment_ids=seg)

    spec = pmesh.ParallelSpec.parse(spec_str)
    m = pmesh.make_mesh(spec)
    sp = psh.shard_params(params, m, cfg)
    shardings = psh.named_shardings(m, psh.param_partition_specs(cfg))
    # Every param leaf must have been placed with its spec.
    jax.tree.map(lambda x, s: x.sharding == s or pytest.fail(), sp, shardings)

    def fwd(p, t, pos, s):
        with psh.activation_sharding(m):
            out, _ = transformer.forward(p, cfg, t, pos, segment_ids=s)
        return out

    out = jax.jit(fwd)(sp, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_sharded_grad_runs():
    cfg = tiny_config()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
    sp = psh.shard_params(params, m, cfg)
    B, T = 4, 8
    tokens = jnp.zeros((B, T), jnp.int32)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32), (B, 1))
    seg = jnp.ones((B, T), jnp.int32)

    def loss(p):
        with psh.activation_sharding(m):
            logits, _ = transformer.forward(p, cfg, tokens, positions, segment_ids=seg)
        return jnp.mean(logits**2)

    g = jax.jit(jax.grad(loss))(sp)
    assert jnp.isfinite(jax.tree.reduce(lambda a, b: a + jnp.sum(b), g, 0.0))


@pytest.mark.reshard
def test_per_mfc_submesh_reshard_matches_colocated():
    """Heterogeneous per-MFC meshes (e.g. actor_train:f2t2,ref_inf:d2):
    params trained on the actor's f2t2 mesh, moved across the MFC
    boundary by parallel/reshard.py onto ref's own d2 sub-mesh, must
    produce the same forward outputs as the colocated single-mesh run."""
    from areal_tpu.parallel import reshard as rsh

    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    B, T = 4, 16
    tokens = np.random.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    ref_out, _ = transformer.forward(
        params, cfg, tokens, positions, segment_ids=seg
    )

    actor_mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("f2t2"))
    sp = psh.shard_params(params, actor_mesh, cfg)

    ref_mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2"))
    dst = psh.named_shardings(ref_mesh, psh.param_partition_specs(cfg))
    moved, plan = rsh.reshard_pytree(sp, dst)
    assert plan.n_moved > 0

    def fwd(p, t, pos, s):
        with psh.activation_sharding(ref_mesh):
            out, _ = transformer.forward(p, cfg, t, pos, segment_ids=s)
        return out

    out = jax.jit(fwd)(moved, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-4)


def test_shard_params_gpt2_family_on_mesh():
    """Advisor r3 (medium): param_partition_specs must cover the
    final_ln_b (norm_type='layer') and pos_embedding (learned) keys the
    GPT-2 codec creates, or shard_params tree-maps mismatched trees."""
    import jax

    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.parallel import sharding

    cfg = tiny_config(
        norm_type="layer", pos_embedding="learned", mlp_type="plain",
        use_attention_bias=True, use_attn_output_bias=True,
        max_position_embeddings=64,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    m = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
    sharded = sharding.shard_params(params, m, cfg)
    assert sharded["final_ln_b"].shape == params["final_ln_b"].shape
    assert sharded["pos_embedding"].sharding.mesh.shape == m.shape
