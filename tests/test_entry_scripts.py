"""Entry-script e2e: `python training/main_{sync,async}_ppo.py --backend=tpu
key=value...` must launch the complete experiment — config merge → experiment
setup → launcher → workers → master loop — on CPU with tiny models.

This is the BASELINE.json requirement ("training/main_async_ppo.py and
main_sync_ppo.py launch unchanged with --backend=tpu") exercised for real.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_entry(script, tmp_path, extra, timeout=420):
    from areal_tpu.base.testing import make_math_jsonl

    data_path = str(tmp_path / "math.jsonl")
    make_math_jsonl(data_path, n=8)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    args = [
        sys.executable, os.path.join(REPO, "training", script),
        "--backend=tpu",
        "experiment_name=entrytest", "trial_name=t0",
        f"cluster.fileroot={tmp_path}/exps",
        "mock_tokenizer=true",
        "actor.tiny.vocab_size=258", "actor.tiny.seed=0",
        "ref.tiny.vocab_size=258", "ref.tiny.seed=0",
        f"dataset.path={data_path}",
        "dataset.train_bs_n_seqs=4",
        "group_size=2",
        "ppo.gen.max_new_tokens=8",
        "ppo.ppo_n_minibatches=2",
        "ppo.kl_ctl=0.05",
        "ppo.disable_value=true",
        "ppo.use_decoupled_loss=true",
        "exp_ctrl.benchmark_steps=2",
        "exp_ctrl.total_train_epochs=1000000",
    ] + extra
    return subprocess.run(
        args, cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout,
    )


# The two full-experiment launch tests are the heaviest e2e variants in
# the suite (each spawns a complete experiment as a subprocess; the sync
# one exceeds its own 420 s cap on a loaded CI box) and duplicate the
# in-process coverage of test_system_{sync,async}_ppo through the CLI
# layer — tier-1 keeps the cheap CLI checks below, the launches run in
# the full (slow-inclusive) suite.
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_main_sync_ppo_launches(tmp_path):
    r = _run_entry("main_sync_ppo.py", tmp_path, [])
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "experiment finished: steps=2" in r.stdout + r.stderr
    # merged config was persisted next to the run
    assert os.path.exists(
        tmp_path / "exps" / "logs" / "entrytest" / "t0" / "config.yaml"
    )


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_main_async_ppo_launches(tmp_path):
    r = _run_entry("main_async_ppo.py", tmp_path, [
        "max_head_offpolicyness=4",
        "max_concurrent_rollouts=4",
        "new_tokens_per_chunk=4",
        "gen_batch_window_ms=2",
        "gen_prompt_bucket=16",
    ])
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "experiment finished: steps=2" in r.stdout + r.stderr


def test_entry_help_flag():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "training", "main_async_ppo.py"),
         "--help"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0
    assert "max_head_offpolicyness" in r.stdout
    assert "allocation_mode" in r.stdout


def test_entry_rejects_unknown_backend():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "training", "main_sync_ppo.py"),
         "--backend=cuda"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode != 0
