"""Slurm scheduler client + remote worker entry (reference
scheduler/slurm/client.py:78, apps/remote.py:54). No slurm binary exists on
the test host, so the subprocess runner is faked and asserted against."""

import os
import subprocess

import pytest

from areal_tpu.apps.slurm import (
    SlurmClient,
    SlurmJobSpec,
    SlurmLauncher,
    build_job_specs,
    render_sbatch_script,
)
from areal_tpu.experiments.async_ppo_math_exp import AsyncPPOMATHConfig
from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig


class FakeSlurm:
    """Scripted sbatch/squeue/scancel."""

    def __init__(self):
        self.submitted = []
        self.cancelled = []
        self.next_id = 100
        self.states = {}  # job id -> state
        self.squeue_calls = 0
        self.sacct_states = {}  # job id -> terminal state for purged jobs

    def __call__(self, cmd, capture_output=True, text=True, timeout=None):
        prog = cmd[0]
        if prog == "sbatch":
            jid = str(self.next_id)
            self.next_id += 1
            self.submitted.append(cmd[-1])
            self.states[jid] = "RUNNING"
            return subprocess.CompletedProcess(cmd, 0, stdout=jid + "\n",
                                               stderr="")
        if prog == "squeue":
            self.squeue_calls += 1
            # All jobs drop off squeue (= left the queue) on the 2nd poll.
            if self.squeue_calls >= 2:
                lines = []
            else:
                lines = [f"{j} {s}" for j, s in self.states.items()]
            return subprocess.CompletedProcess(
                cmd, 0, stdout="\n".join(lines) + "\n", stderr="")
        if prog == "sacct":
            lines = [f"{j}|{s}" for j, s in self.sacct_states.items()]
            return subprocess.CompletedProcess(
                cmd, 0, stdout="\n".join(lines) + "\n", stderr="")
        if prog == "scancel":
            self.cancelled.append(cmd[1])
            return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")
        raise AssertionError(f"unexpected command {cmd}")


def test_render_sbatch_script_structure(tmp_path):
    spec = SlurmJobSpec(
        name="exp-trainer", cmd="python -m areal_tpu.apps.remote --role "
        "trainer", ntasks=4, nodes=4, tpus_per_task=4, cpus_per_task=8,
        mem_per_task_mb=65536, env={"AREAL_CACHE_ROOT": "/data"},
        exclusive=True,
    )
    s = render_sbatch_script(spec, str(tmp_path))
    assert "#SBATCH --ntasks=4" in s
    assert "#SBATCH --nodes=4" in s
    assert "#SBATCH --gres=tpu:4" in s
    assert "#SBATCH --exclusive" in s
    assert "export AREAL_CACHE_ROOT='/data'" in s
    assert s.rstrip().endswith(
        "srun python -m areal_tpu.apps.remote --role trainer")


def test_build_job_specs_decoupled():
    cfg = AsyncPPOMATHConfig(
        experiment_name="e2e", allocation_mode="gen.d4+d2f2t2",
        n_gpus_per_node=8, n_rollout_workers=3,
    )
    specs = {s.name: s for s in build_job_specs(cfg, "/run/config.yaml")}
    assert set(specs) == {"e2e-master", "e2e-trainer", "e2e-gen",
                          "e2e-rollout"}
    assert specs["e2e-trainer"].ntasks == 1  # 8 chips fit one host
    assert specs["e2e-trainer"].tpus_per_task == 8
    assert specs["e2e-gen"].tpus_per_task == 4
    assert specs["e2e-rollout"].ntasks == 3
    assert "--experiment-cls async-ppo-math" in specs["e2e-master"].cmd
    assert "--config /run/config.yaml" in specs["e2e-master"].cmd


def test_build_job_specs_multihost_trainer():
    cfg = PPOMATHConfig(
        experiment_name="big", allocation_mode="d16f2t4",  # 128 chips
        n_gpus_per_node=8,
    )
    specs = {s.name: s for s in build_job_specs(cfg, "/c.yaml")}
    t = specs["big-trainer"]
    assert t.ntasks == 16 and t.nodes == 16  # one SPMD process per host
    assert t.tpus_per_task == 8
    assert "big-gen" not in specs  # colocated sync mode


def test_slurm_client_submit_wait_cancel(tmp_path):
    fake = FakeSlurm()
    client = SlurmClient(str(tmp_path), runner=fake)
    jid = client.submit(SlurmJobSpec(name="j1", cmd="echo hi"))
    assert jid == "100"
    assert os.path.exists(tmp_path / "j1.sbatch")
    st = client.wait(poll_secs=0.01, until_done="j1", timeout=5)
    assert st["j1"] == "COMPLETED"
    client.cancel_all()
    assert fake.cancelled == ["100"]


def test_slurm_client_failure_raises(tmp_path):
    fake = FakeSlurm()

    def runner(cmd, **kw):
        r = fake(cmd, **kw)
        if cmd[0] == "squeue":
            jid = list(fake.states)[0]
            r = subprocess.CompletedProcess(
                cmd, 0, stdout=f"{jid} FAILED\n", stderr="")
        return r

    client = SlurmClient(str(tmp_path), runner=runner)
    client.submit(SlurmJobSpec(name="bad", cmd="false"))
    with pytest.raises(RuntimeError, match="failed"):
        client.wait(poll_secs=0.01, timeout=5)


def test_states_uses_sacct_for_purged_jobs(tmp_path):
    """A job that crashed and aged out of squeue (MinJobAge) must not read
    as COMPLETED — sacct has the terminal state."""
    fake = FakeSlurm()
    client = SlurmClient(str(tmp_path), runner=fake)
    client.submit(SlurmJobSpec(name="dead", cmd="false"))
    fake.squeue_calls = 1  # next squeue poll returns nothing
    fake.sacct_states[client.jobs["dead"]] = "OUT_OF_MEMORY"
    assert client.states()["dead"] == "OUT_OF_MEMORY"
    with pytest.raises(RuntimeError, match="failed"):
        client.wait(poll_secs=0.01, timeout=5)


def test_states_tolerates_squeue_invalid_job_id(tmp_path):
    """squeue exits nonzero when all listed ids were purged — that is
    normal completion, not an error."""
    fake = FakeSlurm()

    def runner(cmd, **kw):
        if cmd[0] == "squeue":
            return subprocess.CompletedProcess(
                cmd, 1, stdout="",
                stderr="slurm_load_jobs error: Invalid job id specified\n")
        return fake(cmd, **kw)

    client = SlurmClient(str(tmp_path), runner=runner)
    client.submit(SlurmJobSpec(name="ok", cmd="true"))
    assert client.states()["ok"] == "COMPLETED"


def test_states_per_id_retry_when_batched_squeue_rejected():
    """One purged id makes the batched `squeue -j a,b` exit nonzero while
    saying nothing about the others — a still-RUNNING job must not read as
    COMPLETED (per-id retry)."""
    fake = FakeSlurm()

    def runner(cmd, **kw):
        if cmd[0] == "squeue":
            jid = cmd[2]
            if "," in jid:  # batched query: rejected
                return subprocess.CompletedProcess(
                    cmd, 1, stdout="",
                    stderr="slurm_load_jobs error: Invalid job id specified\n")
            if jid == live_id:
                return subprocess.CompletedProcess(
                    cmd, 0, stdout=f"{jid} RUNNING\n", stderr="")
            return subprocess.CompletedProcess(
                cmd, 1, stdout="", stderr="Invalid job id specified\n")
        return fake(cmd, **kw)

    client = SlurmClient("/tmp/slurmlog", runner=runner)
    client.submit(SlurmJobSpec(name="gone", cmd="true"))
    client.submit(SlurmJobSpec(name="live", cmd="sleep 100"))
    live_id = client.jobs["live"]
    fake.sacct_states[client.jobs["gone"]] = "COMPLETED"
    st = client.states()
    assert st["live"] == "RUNNING"
    assert st["gone"] == "COMPLETED"


def test_rollout_cmd_has_no_index_flag():
    """--index $SLURM_PROCID would be expanded by the batch shell (PROCID=0
    there) before srun fans out; the index must come from the env inside
    each task instead (remote.py defaults it from SLURM_PROCID)."""
    cfg = AsyncPPOMATHConfig(
        experiment_name="e2e", allocation_mode="gen.d4+d2f2t2",
        n_rollout_workers=3,
    )
    specs = {s.name: s for s in build_job_specs(cfg, "/c.yaml")}
    assert "--index" not in specs["e2e-rollout"].cmd
    assert "SLURM_PROCID" not in specs["e2e-rollout"].cmd


def test_slurm_launcher_end_to_end(tmp_path, tmp_name_resolve):
    fake = FakeSlurm()
    cfg = AsyncPPOMATHConfig(
        experiment_name="slurmexp", trial_name="t0",
        allocation_mode="gen.d1+d1", mode="slurm",
    )
    cfg.cluster.fileroot = str(tmp_path)
    result = SlurmLauncher(cfg, runner=fake).run()
    assert len(fake.submitted) == 4
    # teardown cancelled every job
    assert sorted(fake.cancelled) == sorted(result["slurm_jobs"].values())
    # config.yaml dumped for the remote workers
    cfg_files = list(tmp_path.rglob("config.yaml"))
    assert cfg_files, "config.yaml must be dumped next to the run"


def test_remote_entry_role_dispatch(tmp_path, tmp_name_resolve):
    """remote.py reconstructs the config and refuses unknown roles/indices
    (full role execution is covered by the entry-script e2e tests)."""
    from areal_tpu.api import cli_args as CA
    from areal_tpu.apps import remote

    cfg = AsyncPPOMATHConfig(
        experiment_name="remexp", trial_name="t1", n_rollout_workers=2,
        allocation_mode="gen.d1+d1",
    )
    cfg.cluster.fileroot = str(tmp_path)
    path = str(tmp_path / "config.yaml")
    CA.save_yaml(cfg, path)
    built = remote.build_config("async-ppo-math", path)
    assert built.experiment_name == "remexp"
    assert built.n_rollout_workers == 2
    with pytest.raises(SystemExit):
        remote.run_role(built, "rollout", index=7)
    with pytest.raises(SystemExit):
        remote.run_role(built, "nonsense")
