"""PPO math parity tests (mirrors tests/cpp_extensions/test_cugae.py and
tests/data/test_dual_clip.py in the reference)."""

import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.algorithms import ppo_functional as F
from areal_tpu.models import packing


def _grid_from_packed(seqlens, *arrays, row_len=32):
    layout = packing.plan_packing(seqlens, row_len=row_len)
    grid = packing.make_grid(layout)
    outs = [packing.batch_from_packed(a, layout) for a in arrays]
    return layout, grid, outs


@pytest.mark.parametrize("gamma,lam", [(1.0, 1.0), (0.99, 0.95)])
@pytest.mark.parametrize("use_bootstrap", [False, True])
def test_gae_grid_matches_packed_numpy(gamma, lam, use_bootstrap):
    rng = np.random.RandomState(0)
    seqlens = [5, 9, 3, 14, 1]
    total = sum(seqlens)
    rewards = rng.randn(total).astype(np.float32)
    values = rng.randn(total).astype(np.float32)
    bs = rng.rand(len(seqlens)).astype(np.float32) if use_bootstrap else None

    adv_ref, ret_ref = F.gae_packed_np(
        rewards, values, seqlens, bootstrap=bs, gamma=gamma, lam=lam
    )

    layout, grid, (r_g, v_g) = _grid_from_packed(seqlens, rewards, values)
    boot_g = None
    if use_bootstrap:
        boot_g = np.zeros(layout.shape, np.float32)
        for i, ((row, col), n) in enumerate(zip(layout.placements, layout.seqlens)):
            boot_g[row, col + n - 1] = bs[i]
    adv, ret = F.gae_grid(
        jnp.asarray(r_g), jnp.asarray(v_g), jnp.asarray(grid["segment_ids"]),
        bootstrap=None if boot_g is None else jnp.asarray(boot_g),
        gamma=gamma, lam=lam,
    )
    np.testing.assert_allclose(
        packing.packed_from_batch(np.asarray(adv), layout), adv_ref, atol=1e-4
    )
    np.testing.assert_allclose(
        packing.packed_from_batch(np.asarray(ret), layout), ret_ref, atol=1e-4
    )


def test_gae_independent_of_packing():
    """Two sequences in one row must not leak advantage across the boundary."""
    seqlens = [4, 4]
    rewards = np.array([0, 0, 0, 1, 0, 0, 0, 1], np.float32)
    values = np.zeros(8, np.float32)
    layout, grid, (r_g, v_g) = _grid_from_packed(seqlens, rewards, values, row_len=8)
    assert layout.n_rows == 1  # both sequences share the row
    adv, _ = F.gae_grid(jnp.asarray(r_g), jnp.asarray(v_g),
                        jnp.asarray(grid["segment_ids"]))
    flat = packing.packed_from_batch(np.asarray(adv), layout)
    np.testing.assert_allclose(flat[:4], flat[4:], atol=1e-6)


def test_actor_loss_standard_vs_decoupled_reduction():
    rng = np.random.RandomState(1)
    shape = (2, 8)
    lp = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    old = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    adv = jnp.asarray(rng.randn(*shape).astype(np.float32))
    mask = jnp.asarray(rng.rand(*shape) > 0.3)
    l_std, _ = F.actor_loss(lp, old, adv, mask, eps_clip=0.2)
    # proximal == behaviour ⇒ decoupled loss equals standard PPO
    l_dec, _ = F.actor_loss(lp, old, adv, mask, eps_clip=0.2, proximal_logprobs=old)
    np.testing.assert_allclose(float(l_std), float(l_dec), rtol=1e-5)


def test_actor_loss_dual_clip_bounds_negative_adv():
    # Huge ratio and negative advantage: dual clip caps the loss.
    lp = jnp.full((1, 1), 3.0)
    old = jnp.zeros((1, 1))
    adv = jnp.full((1, 1), -1.0)
    mask = jnp.ones((1, 1), bool)
    l_noclip, _ = F.actor_loss(lp, old, adv, mask, eps_clip=0.2)
    l_dual, st = F.actor_loss(lp, old, adv, mask, eps_clip=0.2, c_clip=5.0)
    assert float(l_dual) == pytest.approx(5.0)  # -adv * c_clip
    assert float(l_noclip) > float(l_dual)
    assert float(st["dual_clip_ratio"]) == 1.0


def test_actor_loss_behav_cap_drops_tokens():
    lp = jnp.zeros((1, 2))
    behav = jnp.asarray([[0.0, -5.0]])  # second token: behav weight e^5 ≈ 148
    prox = jnp.zeros((1, 2))
    adv = jnp.ones((1, 2))
    mask = jnp.ones((1, 2), bool)
    l_cap, _ = F.actor_loss(
        lp, behav, adv, mask, proximal_logprobs=prox, behav_imp_weight_cap=10.0
    )
    l_first_only, _ = F.actor_loss(
        lp[:, :1], behav[:, :1], adv[:, :1], mask[:, :1], proximal_logprobs=prox[:, :1]
    )
    # Capped token contributes 0; denominator still counts both tokens.
    np.testing.assert_allclose(float(l_cap), float(l_first_only) / 2, rtol=1e-5)


def test_critic_loss_clip():
    v = jnp.full((1, 1), 2.0)
    old = jnp.zeros((1, 1))
    ret = jnp.full((1, 1), 2.0)
    mask = jnp.ones((1, 1), bool)
    # clipped prediction (0.2) is far from the target ⇒ max picks clipped loss
    loss, st = F.critic_loss(v, old, ret, mask, value_eps_clip=0.2, loss_fn="mse")
    assert float(loss) == pytest.approx(0.5 * 1.8**2)
    assert float(st["value_clip_ratio"]) == 1.0


def test_masked_normalization():
    x = jnp.asarray(np.random.RandomState(2).randn(4, 8).astype(np.float32))
    mask = jnp.asarray(np.random.RandomState(3).rand(4, 8) > 0.4)
    y = F.masked_normalization(x, mask)
    yn = np.asarray(y)[np.asarray(mask)]
    assert abs(yn.mean()) < 1e-3 and abs(yn.std() - 1.0) < 5e-2
    assert (np.asarray(y)[~np.asarray(mask)] == 0).all()


def test_kl_controllers():
    c = F.FixedKLController(0.1)
    c.update(10.0, 1)
    assert c.value == 0.1
    a = F.AdaptiveKLController(init_kl_coef=0.1, target=1.0, horizon=100)
    a.update(2.0, 10)  # kl above target → coef grows
    assert a.value > 0.1
    a2 = F.AdaptiveKLController(init_kl_coef=0.1, target=1.0, horizon=100)
    a2.update(0.1, 10)  # below target → shrinks
    assert a2.value < 0.1
