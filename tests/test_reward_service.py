"""Sandboxed reward service (docs/rewards.md): the sixth worker kind.

In-process fleets (real aiohttp sockets on loopback, no subprocess
workers) + chaos on injected graders, so the whole suite runs in seconds:

 - service grades math/code over HTTP with per-kind verdict telemetry;
 - client fanout spreads a batch across replicas with bounded concurrency;
 - fleet unreachable  -> local-fallback parity with the legacy path;
 - mid-batch worker death -> retry lands on the surviving replica;
 - grade timeout -> 0.0 verdict + reward_timeouts_total incremented;
 - unsupported language -> 0.0 verdict, no sandbox spawn;
 - disabled config -> batch_reward bit-identical to the legacy local path.
"""

import asyncio
import json

import pytest

from areal_tpu.api.train_config import RewardServiceConfig, TelemetryConfig
from areal_tpu.base import name_resolve

pytestmark = pytest.mark.rewards

EXP, TRIAL = "rewardsvc", "t0"

MATH_OK = {"task": "math", "generated": "\\boxed{4}",
           "solutions": ["\\boxed{4}"]}
MATH_BAD = {"task": "math", "generated": "\\boxed{5}",
            "solutions": ["\\boxed{4}"]}
CODE_IO = json.dumps({"inputs": ["1\n"], "outputs": ["1\n"]})
CODE_OK = {"task": "code", "generated": "```python\nprint(input())\n```",
           "input_output": CODE_IO}
CODE_BAD = {"task": "code", "generated": "```python\nprint('x')\n```",
            "input_output": CODE_IO}


@pytest.fixture(autouse=True)
def _mem_repo():
    old = name_resolve.DEFAULT_REPO
    name_resolve.DEFAULT_REPO = name_resolve.MemoryNameRecordRepo()
    yield
    name_resolve.DEFAULT_REPO = old


@pytest.fixture(autouse=True)
def _clear_service_mode():
    from areal_tpu.rewards import client as rc

    yield
    rc.configure_service(None)


def _worker(index=0, cfg=None, telemetry_enabled=False, grade_fn=None):
    from areal_tpu.system.reward_worker import RewardWorker, RewardWorkerConfig

    return RewardWorker(RewardWorkerConfig(
        experiment=EXP, trial=TRIAL, worker_index=index,
        reward=cfg or RewardServiceConfig(enabled=True),
        telemetry=TelemetryConfig(enabled=telemetry_enabled,
                                  flush_interval_secs=3600),
    ), grade_fn=grade_fn)


async def _http_json(url, payload=None):
    import aiohttp

    async with aiohttp.ClientSession() as s:
        if payload is None:
            async with s.get(url) as r:
                return r.status, await r.json()
        async with s.post(url, json=payload) as r:
            return r.status, await r.json()


def test_service_grades_math_and_code_over_http():
    async def main():
        w = _worker(telemetry_enabled=True)
        url = await w.start()
        try:
            _, out = await _http_json(f"{url}/math_verify", MATH_OK)
            assert out == {"score": 1.0, "verdict": "pass"}
            _, out = await _http_json(f"{url}/math_verify", MATH_BAD)
            assert out == {"score": 0.0, "verdict": "fail"}
            _, out = await _http_json(f"{url}/code_verify", CODE_OK)
            assert out == {"score": 1.0, "verdict": "pass"}
            _, out = await _http_json(f"{url}/batch_reward",
                                      {"tasks": [MATH_OK, CODE_BAD]})
            assert out["scores"] == [1.0, 0.0]
            assert out["verdicts"] == ["pass", "fail"]
            _, health = await _http_json(f"{url}/health")
            assert health["ok"] and health["graded_total"] == 5
            # Prometheus exposition: requests counter + per-kind verdict
            # labels + latency histogram (the PR 4 registry contract).
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(f"{url}/metrics") as r:
                    prom = await r.text()
            assert "areal_reward_requests_total" in prom
            assert 'task="math"' in prom and 'verdict="pass"' in prom
            assert "areal_reward_grade_latency_secs_bucket" in prom
            for ln in prom.splitlines():
                if ln and not ln.startswith("#"):
                    float(ln.rpartition(" ")[2])  # every sample parses
        finally:
            await w.stop()

    asyncio.run(main())


def test_client_fanout_spreads_over_fleet():
    async def main():
        from areal_tpu.rewards import client as rc

        cfg = RewardServiceConfig(enabled=True, n_workers=2,
                                  max_concurrency=4)
        w0, w1 = _worker(0, cfg), _worker(1, cfg)
        await w0.start()
        await w1.start()
        try:
            rc.configure_service(cfg, EXP, TRIAL)
            tasks = [MATH_OK, MATH_BAD] * 8
            scores = await rc.abatch_reward(tasks)
            assert scores == [1.0, 0.0] * 8
            # both replicas actually graded (round-robin fanout)
            assert w0.service._graded > 0 and w1.service._graded > 0
            assert w0.service._graded + w1.service._graded == 16
        finally:
            await w0.stop()
            await w1.stop()

    asyncio.run(main())


def test_fleet_unreachable_local_fallback_parity():
    """The fleet never came up: every task degrades to local grading and
    the outputs match the legacy local path exactly."""

    async def main():
        from areal_tpu.rewards import client as rc

        cfg = RewardServiceConfig(enabled=True, max_retries=1,
                                  retry_base_delay_secs=0.01,
                                  retry_max_delay_secs=0.01)
        # no worker registered; also point at a dead URL to exercise the
        # connect-refused path, not just the empty-fleet path
        client = rc.configure_service(
            cfg, EXP, TRIAL, urls=["http://127.0.0.1:9"]
        )
        tasks = [MATH_OK, MATH_BAD, CODE_OK, CODE_BAD]
        scores = await rc.abatch_reward(tasks)
        assert scores == [1.0, 0.0, 1.0, 0.0]
        assert client is rc.service_client()
        return scores

    scores = asyncio.run(main())
    # parity: identical to the legacy local path, bit for bit
    from areal_tpu.rewards import client as rc

    rc.configure_service(None)
    assert rc.batch_reward([MATH_OK, MATH_BAD, CODE_OK, CODE_BAD]) == scores


def test_cold_start_registration_race_retries_before_fallback():
    """Fleet resolves EMPTY on the first attempt (workers still
    registering at launch): the client burns its retry budget with
    backoff instead of immediately executing code locally — the worker
    that registers during the backoff window gets the task."""

    async def main():
        from areal_tpu.rewards import client as rc

        cfg = RewardServiceConfig(enabled=True, max_retries=3,
                                  retry_base_delay_secs=0.05,
                                  retry_max_delay_secs=0.1)
        rc.configure_service(cfg, EXP, TRIAL)
        w = _worker(cfg=cfg)

        async def register_late():
            await asyncio.sleep(0.02)
            await w.start()

        reg = asyncio.create_task(register_late())
        try:
            scores = await rc.abatch_reward([CODE_OK])
            await reg
            assert scores == [1.0]
            # graded by the FLEET (after the race), never locally
            assert w.service._graded == 1
        finally:
            await w.stop()

    asyncio.run(main())


def test_mid_batch_worker_death_retries_on_survivor():
    """One replica dies mid-batch: its in-flight tasks retry on the
    surviving replica; every score still lands."""

    async def main():
        from areal_tpu.rewards import client as rc

        cfg = RewardServiceConfig(enabled=True, n_workers=2, max_retries=2,
                                  retry_base_delay_secs=0.01,
                                  retry_max_delay_secs=0.02,
                                  max_concurrency=2)
        w0, w1 = _worker(0, cfg), _worker(1, cfg)
        u0 = await w0.start()
        await w1.start()
        killed = asyncio.Event()

        async def kill_w0_soon():
            # Let a couple of requests land, then die abruptly (socket
            # closed + deregistered — the respawn-in-place contract's
            # "dead" half).
            while w0.service._graded < 2:
                await asyncio.sleep(0.005)
            await w0.stop()
            killed.set()

        try:
            client = rc.configure_service(cfg, EXP, TRIAL)
            assert u0 in client.refresh()
            killer = asyncio.create_task(kill_w0_soon())
            tasks = [MATH_OK, MATH_BAD] * 12
            scores = await rc.abatch_reward(tasks)
            await killer
            assert killed.is_set()
            assert scores == [1.0, 0.0] * 12
            # the survivor picked up the dead replica's share
            assert w1.service._graded > 0
            # and the fleet view no longer contains the dead URL
            assert u0 not in client.refresh()
        finally:
            await w1.stop()

    asyncio.run(main())


def test_timeout_returns_zero_verdict_and_counter():
    """A grade overrunning grade_timeout_secs: 0.0 score, verdict
    "timeout", reward_timeouts_total incremented — the slot is released,
    later grades proceed."""

    async def main():
        import threading

        release = threading.Event()

        def slow_grade(task):
            if task.get("generated") == "SLOW":
                release.wait(5.0)  # far beyond the budget below
            return {"score": 1.0, "verdict": "pass"}

        cfg = RewardServiceConfig(enabled=True, grade_timeout_secs=0.05)
        w = _worker(cfg=cfg, telemetry_enabled=True, grade_fn=slow_grade)
        url = await w.start()
        try:
            _, out = await _http_json(
                f"{url}/math_verify", {"task": "math", "generated": "SLOW"}
            )
            assert out == {"score": 0.0, "verdict": "timeout"}
            # the slot is free again: a fast grade completes normally
            _, out = await _http_json(
                f"{url}/math_verify", {"task": "math", "generated": "ok"}
            )
            assert out == {"score": 1.0, "verdict": "pass"}
            assert w.service._timeouts == 1
            assert w.telemetry.registry.snapshot(reset=False)[
                "counters"]["reward/timeouts"] == 1
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(f"{url}/metrics") as r:
                    prom = await r.text()
            assert "areal_reward_timeouts_total" in prom
        finally:
            release.set()
            await w.stop()

    asyncio.run(main())


def test_task_budget_floors_code_worst_case():
    """grade_timeout_secs bounds a WEDGED grader; a code task's budget
    floors at its legal worst case (per-case timeout x max cases) on
    BOTH sides (server grade + client HTTP timeout share the helper)."""
    from areal_tpu.rewards.service import task_budget_secs

    assert task_budget_secs({"task": "math"}, 30.0) == 30.0
    assert task_budget_secs({"task": "code", "timeout": 8.0}, 30.0) \
        == 8.0 * 16 + 5.0
    # short per-case timeouts keep the configured bound
    assert task_budget_secs({"task": "code", "timeout": 0.1}, 30.0) == 30.0
    # the floor scales with the cases the task ACTUALLY carries (a hung
    # single-case pass-rate task pins its slot ~13s, not ~133s)
    one_case = json.dumps({"inputs": ["1\n"], "outputs": ["1\n"]})
    assert task_budget_secs(
        {"task": "code", "timeout": 8.0, "input_output": one_case}, 5.0
    ) == 8.0 * 1 + 5.0
    many = json.dumps({"inputs": ["1\n"] * 500, "outputs": ["1\n"] * 500})
    assert task_budget_secs(
        {"task": "code", "timeout": 8.0, "input_output": many}, 5.0
    ) == 8.0 * 16 + 5.0


def test_sample_cases_honors_cap_for_every_length():
    from areal_tpu.rewards.code_verify import sample_cases

    for n in (1, 15, 16, 17, 31, 32, 33, 500):
        got = sample_cases([str(i) for i in range(n)],
                           [str(i) for i in range(n)], 16)
        assert len(got) <= 16, (n, len(got))
        assert got[0] == ("0", "0")  # deterministic, starts at case 0
    assert sample_cases([], [], 16) == []


def test_wedged_grader_pool_self_heals():
    """wait_for cannot kill a wedged grader THREAD: once every pool
    thread is a zombie, the pool is replaced wholesale so new grades
    run promptly instead of timing out in executor-queue wait forever."""

    async def main():
        import threading
        import time as _time

        release = threading.Event()

        def grade(task):
            if task.get("generated") == "WEDGE":
                release.wait(10.0)
            return {"score": 1.0, "verdict": "pass"}

        cfg = RewardServiceConfig(enabled=True, pool_size=2, max_inflight=2,
                                  grade_timeout_secs=0.05)
        w = _worker(cfg=cfg, grade_fn=grade)
        url = await w.start()
        pool0 = w.service._pool
        try:
            outs = await asyncio.gather(*[
                _http_json(f"{url}/math_verify",
                           {"task": "math", "generated": "WEDGE"})
                for _ in range(2)
            ])
            assert all(o[1]["verdict"] == "timeout" for o in outs)
            # every thread wedged -> the pool was swapped out
            assert w.service._pool is not pool0
            # ...and a fresh grade completes fast on the new pool
            t0 = _time.monotonic()
            _, out = await _http_json(
                f"{url}/math_verify", {"task": "math", "generated": "ok"}
            )
            assert out["verdict"] == "pass"
            # generous bound (CI boxes run suites concurrently): the
            # point is "well under the 10s wedge", not raw speed
            assert _time.monotonic() - t0 < 5.0
        finally:
            release.set()
            await w.stop()

    asyncio.run(main())


def test_self_heal_triggers_at_admission_limit():
    """max_inflight < pool_size: the replacement trigger must use the
    CLAMPED admission bound — at max_inflight zombies every admittable
    slot is withheld, and a pool_size-based trigger would never fire
    (permanent deadlock behind sem.acquire)."""

    async def main():
        import threading
        import time as _time

        release = threading.Event()

        def grade(task):
            if task.get("generated") == "WEDGE":
                release.wait(10.0)
            return {"score": 1.0, "verdict": "pass"}

        cfg = RewardServiceConfig(enabled=True, pool_size=8, max_inflight=1,
                                  grade_timeout_secs=0.05)
        w = _worker(cfg=cfg, grade_fn=grade)
        url = await w.start()
        try:
            _, out = await _http_json(
                f"{url}/math_verify", {"task": "math", "generated": "WEDGE"}
            )
            assert out["verdict"] == "timeout"
            t0 = _time.monotonic()
            _, out = await _http_json(
                f"{url}/math_verify", {"task": "math", "generated": "ok"}
            )
            assert out["verdict"] == "pass"
            assert _time.monotonic() - t0 < 5.0  # admitted, not deadlocked
        finally:
            release.set()
            await w.stop()

    asyncio.run(main())


def test_unsupported_language_verdict():
    from areal_tpu.rewards.service import grade_task

    task = {"task": "code", "generated": "```cpp\nint main(){}\n```",
            "input_output": CODE_IO, "language": "cpp"}
    assert grade_task(task) == {"score": 0.0,
                                "verdict": "unsupported_language"}
    # allowed list narrower than GRADERS also gates
    assert grade_task({**CODE_OK, "language": "python"}, languages=[]) \
        == {"score": 0.0, "verdict": "unsupported_language"}


def test_inflight_cap_bounds_concurrency():
    async def main():
        import threading

        peak = {"v": 0, "cur": 0}
        lock = threading.Lock()

        def counting_grade(task):
            with lock:
                peak["cur"] += 1
                peak["v"] = max(peak["v"], peak["cur"])
            import time as _t

            _t.sleep(0.02)
            with lock:
                peak["cur"] -= 1
            return {"score": 1.0, "verdict": "pass"}

        cfg = RewardServiceConfig(enabled=True, max_inflight=2, pool_size=8)
        w = _worker(cfg=cfg, grade_fn=counting_grade)
        url = await w.start()
        try:
            outs = await asyncio.gather(*[
                _http_json(f"{url}/math_verify",
                           {"task": "math", "generated": "x"})
                for _ in range(10)
            ])
            assert all(o[1]["score"] == 1.0 for o in outs)
            assert peak["v"] <= 2  # admission bound, not pool size
        finally:
            await w.stop()

    asyncio.run(main())


def test_batch_reward_sync_on_running_loop_raises():
    """The old loop-blocking bridge is gone: sync batch_reward on a
    running loop raises, pointing at the real async entrypoint."""
    from areal_tpu.rewards.client import batch_reward

    async def main():
        with pytest.raises(RuntimeError, match="abatch_reward"):
            batch_reward([MATH_OK])

    asyncio.run(main())


def test_agent_env_awaits_async_grading():
    """The math/code env grades through abatch_reward on the caller's
    loop — no dedicated-thread bridge (the satellite contract)."""
    from areal_tpu.agents.math_single_step import MathCodeSingleStepEnv

    env = MathCodeSingleStepEnv({
        "q1": {"task": "math", "solutions": ["\\boxed{4}"]},
    })

    async def main():
        _, scores, done, _ = await env.step(("q1", ["\\boxed{4}", "no"]))
        return scores, done

    scores, done = asyncio.run(main())
    assert scores == [1.0, 0.0] and done


def test_code_agent_format_gate_and_pass_rate():
    from areal_tpu.agents.code_single_step import CodeSingleStepEnv

    io = json.dumps({"inputs": ["1\n", "2\n"], "outputs": ["1\n", "2\n"]})
    id2info = {"c1": {"task": "code", "input_output": io}}

    async def main():
        env = CodeSingleStepEnv(id2info)
        _, scores, _, _ = await env.step(
            ("c1", ["```python\nprint(input())\n```", "just prose"])
        )
        assert scores == [1.0, 0.0]  # prose gated without a sandbox spawn
        env_pr = CodeSingleStepEnv(id2info, pass_rate_reward=True)
        # echoes the input only when it is "1": passes 1 of 2 cases
        half = ("```python\nx=input()\nprint(x if x=='1' else 'no')\n```")
        _, scores, _, _ = await env_pr.step(("c1", [half]))
        assert scores == [pytest.approx(0.5)]

    asyncio.run(main())


def test_worker_control_and_lease_registration():
    """run_async serves WorkerControl (the sixth worker kind speaks the
    same lifecycle language as the other five) and withdraws discovery
    on exit."""

    async def main():
        from areal_tpu.base import names
        from areal_tpu.system.reward_worker import resolve_fleet
        from areal_tpu.system.worker_base import WorkerControlPanel

        cfg = RewardServiceConfig(enabled=True)
        from areal_tpu.system.reward_worker import (
            RewardWorker,
            RewardWorkerConfig,
        )

        w = RewardWorker(RewardWorkerConfig(
            experiment=EXP, trial=TRIAL, worker_index=0, reward=cfg,
            keepalive_ttl_secs=30.0,
        ))
        task = asyncio.create_task(w.run_async())
        deadline = asyncio.get_event_loop().time() + 10
        while not resolve_fleet(EXP, TRIAL):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.01)
        url = resolve_fleet(EXP, TRIAL)[0]
        _, health = await _http_json(f"{url}/health")
        assert health["ok"]

        def panel_cmds():
            panel = WorkerControlPanel(EXP, TRIAL, timeout=5.0)
            try:
                st = panel.status("reward0")
                assert st["ok"] and st["url"] == url
                # liveness heartbeat under the LAUNCHER's worker name
                # (supervisor respawn purge keys on it)
                assert "reward0" in panel.heartbeats()
                panel.exit("reward0")
            finally:
                panel.close()

        await asyncio.to_thread(panel_cmds)
        await asyncio.wait_for(task, timeout=10)
        assert resolve_fleet(EXP, TRIAL) == []  # discovery withdrawn

    asyncio.run(main())
