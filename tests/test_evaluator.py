"""AutomaticEvaluator (reference scheduler/evaluator.py:160) + the offline
eval harness (apps/eval_ckpt.py)."""

import json
import os

import jax
import numpy as np
import pytest

from areal_tpu.api.cli_args import AutomaticEvaluatorConfig
from areal_tpu.apps.evaluator import (
    AutomaticEvaluator,
    discover_new_steps,
)


def _fake_ckpt(root, role, step):
    d = os.path.join(root, role, f"step{step}")
    os.makedirs(d, exist_ok=True)
    # save_hf_checkpoint writes areal_tpu_config.json last — it is the
    # completeness sentinel discover_new_steps gates on.
    with open(os.path.join(d, "areal_tpu_config.json"), "w") as f:
        json.dump({}, f)
    return d


def test_discover_new_steps_orders_and_dedups(tmp_path):
    root = str(tmp_path)
    _fake_ckpt(root, "actor", 20)
    _fake_ckpt(root, "actor", 5)
    # incomplete save (no areal_tpu_config.json) must be skipped
    os.makedirs(os.path.join(root, "actor", "step99"))
    seen = set()
    steps = discover_new_steps(root, "actor", seen)
    assert [s.step for s in steps] == [5, 20]
    assert discover_new_steps(root, "actor", seen) == []
    _fake_ckpt(root, "actor", 99)  # completes later
    assert [s.step for s in discover_new_steps(root, "actor", seen)] == [99]


def test_evaluator_runs_injected_eval_and_logs(tmp_path):
    root = str(tmp_path)
    _fake_ckpt(root, "actor", 1)
    _fake_ckpt(root, "actor", 2)
    ran = []

    class Writer:
        """Mirrors MetricWriter's API (base/monitor.py:115) — the
        evaluator must call write(stats, step), not a log() that only a
        fake would have."""

        logged = []

        def write(self, metrics, step):
            self.logged.append((step, metrics))

    def run_eval(step):
        ran.append(step.step)
        return {"accuracy": 0.5 + step.step / 10, "n": 4}

    ev = AutomaticEvaluator(
        AutomaticEvaluatorConfig(max_concurrent_jobs=10),
        save_dir=root, dataset_path="unused.jsonl",
        metric_writer=Writer(), run_eval=run_eval,
    )
    assert ev.poll_once() == 2
    assert ran == [1, 2]
    assert Writer.logged[0] == (1, {"eval/accuracy": 0.6, "eval/n": 4})
    # a failing eval is contained
    _fake_ckpt(root, "actor", 3)

    def boom(step):
        raise RuntimeError("no")

    ev._run_eval = boom
    assert ev.poll_once() == 0
    assert ev.steps[-1].status == "failed"


def test_pass_at_k_estimators():
    """Unbiased pass@k (Codex eq. 1) + pass^k sanity: closed-form values
    and the degenerate edges."""
    from areal_tpu.apps.eval_ckpt import pass_at_k, pass_hat_k

    # all correct / none correct
    assert pass_at_k(4, 4, 4) == 1.0 and pass_at_k(4, 0, 4) == 0.0
    assert pass_hat_k(4, 4, 4) == 1.0 and pass_hat_k(4, 0, 1) == 0.0
    # n=4, c=2, k=1: plain accuracy 0.5
    assert pass_at_k(4, 2, 1) == 0.5
    # n=4, c=2, k=2: 1 - C(2,2)/C(4,2) = 1 - 1/6
    assert pass_at_k(4, 2, 2) == 1.0 - 1.0 / 6.0
    # pass^2 with c=2 of 4: C(2,2)/C(4,2) = 1/6
    assert pass_hat_k(4, 2, 2) == 1.0 / 6.0
    # pass@k is monotone in k; pass^k anti-monotone
    assert pass_at_k(8, 3, 4) >= pass_at_k(8, 3, 2) >= pass_at_k(8, 3, 1)
    assert pass_hat_k(8, 3, 1) >= pass_hat_k(8, 3, 2) >= pass_hat_k(8, 3, 3)


@pytest.mark.rewards
def test_eval_ckpt_pass_at_k_mixed_tasks(tmp_path):
    """--k 4 over a mixed math+code set emits pass@1/pass@4/pass^4 for
    BOTH task kinds (the acceptance-criteria eval shape)."""
    from areal_tpu.apps.eval_ckpt import evaluate_checkpoint
    from areal_tpu.base.testing import make_mixed_jsonl
    from areal_tpu.models import hf as hfmod
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config

    cfg = tiny_config(vocab_size=258)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    hfmod.save_hf_checkpoint(jax.device_get(params), cfg, ckpt)
    data = str(tmp_path / "mixed.jsonl")
    make_mixed_jsonl(data, n_math=3, n_code=1)
    result = evaluate_checkpoint(
        ckpt, data, max_gen_tokens=8, batch_size=4,
        mock_tokenizer=True, k=4, temperature=0.8,
    )
    assert result["n"] == 4 and result["k"] == 4
    for key in ("pass@1", "pass@4", "pass^4",
                "math/pass@1", "math/pass@4", "math/pass^4",
                "code/pass@1", "code/pass@4", "code/pass^4"):
        assert key in result, sorted(result)
        assert 0.0 <= result[key] <= 1.0
    assert result["math/n"] == 3 and result["code/n"] == 1
    # estimator coherence on the real output
    assert result["pass@4"] >= result["pass@1"] >= result["pass^4"]
    assert result["accuracy"] == result["pass@1"]


def test_eval_ckpt_harness_end_to_end(tmp_path):
    """Full in-process run of the offline harness on a tiny checkpoint
    (subprocess form is exercised by the evaluator's default runner in
    real deployments)."""
    from areal_tpu.apps.eval_ckpt import evaluate_checkpoint
    from areal_tpu.models import hf as hfmod
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config

    cfg = tiny_config(vocab_size=258)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    hfmod.save_hf_checkpoint(jax.device_get(params), cfg, ckpt)
    data = tmp_path / "eval.jsonl"
    with open(data, "w") as f:
        for i in range(3):
            f.write(json.dumps({
                "query_id": f"q{i}", "prompt": f"1+{i}=?",
                "solutions": [f"\\boxed{{{1 + i}}}"],
            }) + "\n")
    result = evaluate_checkpoint(
        ckpt, str(data), max_gen_tokens=8, batch_size=2,
        mock_tokenizer=True,
    )
    assert result["n"] == 3
    assert 0.0 <= result["accuracy"] <= 1.0
    assert np.isfinite(result["eval_secs"])
