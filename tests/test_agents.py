"""Agent-level tests with fake obs/act queues (the reference's strategy in
tests/agent/test_math_single_step_agent.py — drive collect_trajectory
directly, no rollout worker / generation fleet)."""

import asyncio

import numpy as np
import pytest

from areal_tpu.agents.math_multi_turn import MathMultiTurnAgent
from areal_tpu.api.agent import EnvironmentService
from areal_tpu.api.data import SequenceSample
from areal_tpu.base.testing import MockTokenizer


class ScriptedEnv(EnvironmentService):
    """Grades turn t with the scripted verdict list."""

    def __init__(self, verdicts):
        self.verdicts = list(verdicts)
        self.calls = 0

    async def step(self, action):
        ok = self.verdicts[min(self.calls, len(self.verdicts) - 1)]
        self.calls += 1
        return None, [1.0 if ok else 0.0], True, {}


def _prompt(tok, text="solve 1+1", qid="q0"):
    ids = tok.encode(text)
    return SequenceSample.from_default(
        ids=[qid],
        data={"packed_prompts": np.asarray(ids, np.int32)},
        seqlens=[len(ids)],
    )


def _fake_turn_sample(qid, turn, prompt_ids, gen_ids):
    toks = np.concatenate([prompt_ids, gen_ids]).astype(np.int32)
    P = len(prompt_ids)
    return SequenceSample.from_default(
        ids=[f"{qid}@t{turn}@0"],
        data={
            "packed_input_ids": toks,
            "prompt_mask": np.concatenate(
                [np.ones(P, np.int32), np.zeros(len(gen_ids), np.int32)]
            ),
            "packed_logprobs": np.zeros(len(toks), np.float32),
            "seq_no_eos_mask": np.asarray([0.0], np.float32),
            "version_start": np.asarray([0], np.int32),
            "version_end": np.asarray([0], np.int32),
        },
        seqlens=[len(toks)],
    )


async def _drive(agent, env, prompt, gen_text, tok, max_rounds=10):
    """Bridge like rollout_worker._rollout_one: serve obs until the agent
    returns; each act is a fake one-sample generation result."""
    obs_q: asyncio.Queue = asyncio.Queue()
    act_q: asyncio.Queue = asyncio.Queue()
    task = asyncio.create_task(
        agent.collect_trajectory(prompt, env, obs_q, act_q)
    )
    seen_obs = []
    for turn in range(max_rounds):
        get_obs = asyncio.create_task(obs_q.get())
        done, _ = await asyncio.wait(
            {task, get_obs}, return_when=asyncio.FIRST_COMPLETED
        )
        if get_obs not in done:
            get_obs.cancel()
            break
        qid, token_ids, gconfig = get_obs.result()
        seen_obs.append(list(token_ids))
        await act_q.put([_fake_turn_sample(
            qid, turn, np.asarray(token_ids, np.int32),
            np.asarray(tok.encode(gen_text), np.int32),
        )])
    return await task, seen_obs


def test_multi_turn_retries_until_success_and_discounts():
    tok = MockTokenizer()
    agent = MathMultiTurnAgent(
        tokenizer=tok, num_turns=4, turn_level_discount=0.5,
    )
    env = ScriptedEnv([False, False, True])
    out, seen = asyncio.run(_drive(agent, env, _prompt(tok), "ans", tok))
    # stopped at the first success: 3 turns, one sample each
    assert len(out) == 3 and env.calls == 3
    # turn t+1's context contains turn t's full sequence plus feedback
    assert len(seen) == 3
    for a, b in zip(seen, seen[1:]):
        assert len(b) > len(a)
        assert b[: len(a)] == a
    # feedback text is the retry verdict for failed turns
    assert "wrong" in tok.decode(seen[1][len(seen[0]) :])
    # rewards: raw per-turn (-1, -1, +1), discounted backwards with 0.5:
    # r2=+1, r1=-1+0.5*1=-0.5, r0=-1+0.5*(-0.5)=-1.25
    rs = [float(t.data["rewards"][0]) for t in out]
    assert rs == pytest.approx([-1.25, -0.5, 1.0])


def test_multi_turn_runs_all_turns_when_never_correct():
    tok = MockTokenizer()
    agent = MathMultiTurnAgent(
        tokenizer=tok, num_turns=3, turn_level_discount=1.0,
    )
    env = ScriptedEnv([False, False, False])
    out, seen = asyncio.run(_drive(agent, env, _prompt(tok), "nope", tok))
    assert len(out) == 3 and len(seen) == 3
    rs = [float(t.data["rewards"][0]) for t in out]
    assert rs == pytest.approx([-3.0, -2.0, -1.0])
    # every turn sample keeps the trajectory key layout (trainable as-is)
    for t in out:
        assert "packed_input_ids" in t.data and "rewards" in t.data
        assert t.data["prompt_mask"].sum() > 0


def test_multi_turn_stop_on_success_disabled():
    tok = MockTokenizer()
    agent = MathMultiTurnAgent(
        tokenizer=tok, num_turns=3, stop_on_success=False,
    )
    env = ScriptedEnv([True, True, True])
    out, _ = asyncio.run(_drive(agent, env, _prompt(tok), "yes", tok))
    assert len(out) == 3


def test_multi_turn_answer_log(tmp_path):
    tok = MockTokenizer()
    agent = MathMultiTurnAgent(
        tokenizer=tok, num_turns=2, answer_save_path=str(tmp_path),
    )
    env = ScriptedEnv([False, True])
    asyncio.run(_drive(agent, env, _prompt(tok, qid="q7"), "x", tok))
    assert (tmp_path / "q7.jsonl").exists()
    lines = (tmp_path / "q7.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
