"""Multi-host runtime tests: two jax.distributed processes × 4 virtual CPU
devices jointly execute the PPO actor train step over one global 8-device
mesh and must reproduce the single-process loss (reference analogue:
multi-process gloo tests via LocalMultiProcessTest, testing.py:137)."""

import json
import os
import subprocess
import sys

import pytest

_CHILD = r"""
import json, os, sys
import numpy as np

rank = int(sys.argv[1]); world = int(sys.argv[2]); nr_dir = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
    + os.environ.get("NDEV", "4"))
sys.path.insert(0, os.environ["REPO"])

import jax
jax.config.update("jax_platforms", "cpu")

from areal_tpu.base import name_resolve
name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(nr_dir)

from areal_tpu.parallel import distributed as dist
dist.initialize("mh", "t", rank, world, group="test", local_device_count=None)
assert jax.device_count() == 8, jax.device_count()
assert jax.process_count() == world

# Broadcast check: follower receives rank 0's object.
obj = dist.broadcast_pyobj({"batch_seed": 7} if rank == 0 else None)
assert obj == {"batch_seed": 7}

from areal_tpu.algorithms.ppo import PPOActorInterface, PPOHyperparameters
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import FinetuneSpec, Model
from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel import mesh as pmesh

mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2f2t2"))
cfg = tiny_config(vocab_size=128)
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
model = Model("actor", (cfg, params))
backend = JaxTrainBackend(
    optimizer=OptimizerConfig(lr=1e-4, lr_scheduler_type="constant",
                              warmup_steps_proportion=0.0),
    mesh=mesh, compute_dtype="float32", length_bucket=16, rows_bucket=2,
    seqs_bucket=4,
)
model = backend.initialize(model, FinetuneSpec(1, 16, 8))
iface = PPOActorInterface(PPOHyperparameters(
    ppo_n_minibatches=1, disable_value=True, kl_ctl=0.0))

rng = np.random.RandomState(obj["batch_seed"])
n_seq = 8
plens = rng.randint(3, 6, n_seq); glens = rng.randint(4, 9, n_seq)
seqlens = (plens + glens).astype(int); total = int(seqlens.sum())
pmask = np.concatenate([
    np.concatenate([np.ones(p, np.int32), np.zeros(g, np.int32)])
    for p, g in zip(plens, glens)])
batch = SequenceSample.from_default(
    ids=[f"d{i}" for i in range(n_seq)],
    data={
        "packed_input_ids": rng.randint(2, 128, total).astype(np.int32),
        "prompt_mask": pmask,
        "packed_logprobs": np.where(pmask == 0, -1.0, 0.0).astype(np.float32),
        "rewards": rng.rand(n_seq).astype(np.float32),
        "seq_no_eos_mask": np.zeros(n_seq, np.float32),
    },
    seqlens=seqlens.tolist(),
)
stats = iface.train_step(model, batch, MicroBatchSpec())

# Checkpoint collective: every rank gathers, rank 0 writes.
ck = os.path.join(nr_dir, "ck")
model.module.save_train_state(ck)
if rank == 0:
    assert os.path.exists(os.path.join(ck, "params.safetensors"))
    print("RESULT " + json.dumps({"loss": stats["actor_loss"]}))
"""


@pytest.mark.timeout(300)
def test_two_process_spmd_matches_single(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(world):
        procs = []
        for r in range(world):
            env = dict(
                os.environ, REPO=repo,
                JAX_PLATFORMS="cpu",
                NDEV=str(8 // world),
                XLA_FLAGS=f"--xla_force_host_platform_device_count={8 // world}",
            )
            procs.append(subprocess.Popen(
                [sys.executable, str(script), str(r), str(world),
                 str(tmp_path / f"nr{world}")],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            ))
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for p, o in zip(procs, outs):
            assert p.returncode == 0, o[-3000:]
        line = [ln for ln in outs[0].splitlines() if ln.startswith("RESULT ")]
        assert line, outs[0][-3000:]
        return json.loads(line[0][len("RESULT "):])

    one = run(1)
    two = run(2)
    assert two["loss"] == pytest.approx(one["loss"], abs=1e-5)


def test_chip_assignment_math():
    from areal_tpu.apps.launcher import derive_chip_assignment

    # Sync / no allocation mode: trainer owns every chip.
    assert derive_chip_assignment("", 4) == {
        "trainer": [0, 1, 2, 3], "gen": []}
    assert derive_chip_assignment("d2t2", 4) == {
        "trainer": [0, 1, 2, 3], "gen": []}
    # Decoupled: disjoint partitions.
    asg = derive_chip_assignment("gen.d2+d2t2", 8)
    assert asg == {"trainer": [0, 1, 2, 3], "gen": [4, 5]}
    assert not set(asg["trainer"]) & set(asg["gen"])
    # Impossible layout fails fast with an actionable message.
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="1 trainer \\+ 1 generation"):
        derive_chip_assignment("gen.d1+d1", 1)
