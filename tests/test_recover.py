"""Checkpoint/resume e2e: kill the sync loop mid-run, resume, and continue
to the target step count without retraining consumed samples.

Parity: reference tests/system/test_buffer_recover.py + base/recover.py —
the recover checkpoint carries optimizer state, interface state (kl ctl),
model versions, and the dataset cursor.
"""

import json
import multiprocessing as mp
import os

import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import (
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    WeightUpdateHook,
    build_graph,
)
from areal_tpu.base import name_resolve, recover
from areal_tpu.base.testing import MockTokenizer, make_math_jsonl

EXP, TRIAL = "recovertest", "t0"


def _trainer_main(nr_root, data_path, realloc_dir):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import name_resolve as nr

    nr.DEFAULT_REPO = nr.NfsNameRecordRepo(nr_root)
    import areal_tpu.algorithms.reward  # noqa: F401
    import areal_tpu.datasets.jsonl  # noqa: F401
    from areal_tpu.algorithms.ppo import PPOHyperparameters
    from areal_tpu.api.model import FinetuneSpec, GenerationHyperparameters
    from areal_tpu.backend.jax_train import OptimizerConfig
    from areal_tpu.system.trainer_worker import (
        MFCRuntimeConfig,
        ModelRoleConfig,
        TrainerWorker,
        TrainerWorkerConfig,
    )

    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8),
        ppo_n_minibatches=2, group_size=1, kl_ctl=0.0,
        disable_value=True, adv_norm=True,
    )
    backend_args = {
        "compute_dtype": "float32", "length_bucket": 16, "rows_bucket": 2,
        "seqs_bucket": 4,
        "optimizer": OptimizerConfig(lr=1e-3, lr_scheduler_type="constant",
                                     warmup_steps_proportion=0.0),
    }
    cfg = TrainerWorkerConfig(
        experiment=EXP, trial=TRIAL, handler="trainer",
        models={
            "actor": ModelRoleConfig(
                init={"tiny": {"vocab_size": 258, "seed": 0}},
                backend_args=backend_args),
            "rw": ModelRoleConfig(init={"null": True}, backend="null"),
        },
        mfcs={
            "actor_gen": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
            "rew_inf": MFCRuntimeConfig(
                interface="rw_math_code",
                interface_args={"dataset_path": data_path, "group_size": 1},
                model_name="rw"),
            "actor_train": MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor"),
        },
        dataset="math_code_prompt",
        dataset_args={"dataset_path": data_path},
        batch_size=4,
        ft_spec=FinetuneSpec(1, 16, 4),
        tokenizer=MockTokenizer(),
        realloc_dir=realloc_dir,
    )
    TrainerWorker(cfg).run()


def _dfg():
    traj_keys = ("packed_input_ids", "prompt_mask", "packed_logprobs",
                 "seq_no_eos_mask", "task_ids", "version_start",
                 "version_end")
    return build_graph([
        MFCDef(name="actor_gen", model_name="actor",
               interface_type=MFCInterfaceType.GENERATE,
               interface_impl=ModelInterfaceAbstraction("ppo_actor"),
               input_keys=("packed_prompts", "task_ids"),
               output_keys=traj_keys, n_seqs=4,
               mb_spec=MicroBatchSpec(max_tokens_per_mb=512)),
        MFCDef(name="rew_inf", model_name="rw",
               interface_type=MFCInterfaceType.INFERENCE,
               interface_impl=ModelInterfaceAbstraction("rw_math_code"),
               input_keys=("packed_input_ids", "prompt_mask"),
               output_keys=("rewards",), n_seqs=4, mb_spec=MicroBatchSpec()),
        MFCDef(name="actor_train", model_name="actor",
               interface_type=MFCInterfaceType.TRAIN_STEP,
               interface_impl=ModelInterfaceAbstraction("ppo_actor"),
               input_keys=("packed_input_ids", "prompt_mask",
                           "packed_logprobs", "rewards", "seq_no_eos_mask"),
               n_seqs=4, mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
               post_hooks=[WeightUpdateHook(role="actor")]),
    ])


def _run_master(recover_dir, benchmark_steps, do_recover):
    from areal_tpu.system.master_worker import (
        ExperimentSaveEvalControl,
        MasterWorker,
        MasterWorkerConfig,
    )

    master = MasterWorker(
        MasterWorkerConfig(
            experiment=EXP, trial=TRIAL, train_batch_size=4,
            exp_ctrl=ExperimentSaveEvalControl(
                total_train_epochs=10**6, benchmark_steps=benchmark_steps,
                ckpt_freq_steps=1,
            ),
            recover_dir=recover_dir, recover=do_recover,
        ),
        _dfg(),
    )
    return master.run()


@pytest.mark.timeout(600)
def test_kill_and_resume_continues_run(tmp_path):
    nr_root = str(tmp_path / "nr")
    data_path = str(tmp_path / "math.jsonl")
    realloc_dir = str(tmp_path / "realloc")
    recover_dir = str(tmp_path / "recover")
    make_math_jsonl(data_path, n=16)
    name_resolve.DEFAULT_REPO = name_resolve.NfsNameRecordRepo(nr_root)
    ctx = mp.get_context("spawn")

    # ---- run 1: stops after 2 steps ("the crash") ----
    proc = ctx.Process(target=_trainer_main,
                       args=(nr_root, data_path, realloc_dir), daemon=True)
    proc.start()
    try:
        r1 = _run_master(recover_dir, benchmark_steps=2, do_recover=False)
        assert r1["steps"] == 2
        proc.join(timeout=30)
    finally:
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=10)

    info = recover.load(recover_dir)
    assert info is not None and info.last_step_info.global_step == 2
    ckpt = recover.discover_ckpt(recover_dir)
    assert ckpt is not None
    with open(os.path.join(ckpt, "trainer_state.json")) as f:
        st1 = json.load(f)
    assert st1["meta"]["versions"]["actor"] == 2
    assert st1["meta"]["epoch_pos"] == 8  # 2 steps x 4 prompts consumed

    # ---- run 2: fresh processes, resume to step 4 total ----
    proc = ctx.Process(target=_trainer_main,
                       args=(nr_root, data_path, realloc_dir), daemon=True)
    proc.start()
    try:
        r2 = _run_master(recover_dir, benchmark_steps=4, do_recover=True)
        # resumed at step 2 → only 2 MORE steps ran
        assert r2["steps"] == 4
        assert len(r2["stats"]) == 2
        for st in r2["stats"]:
            assert np.isfinite(st["actor_train/actor_loss"])
        proc.join(timeout=30)
    finally:
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=10)

    ckpt = recover.discover_ckpt(recover_dir)
    with open(os.path.join(ckpt, "trainer_state.json")) as f:
        st2 = json.load(f)
    # version continued (2→4, not reset to 2) and the dataset cursor moved
    # past the first run's samples (8→16): consumed data was NOT retrained.
    assert st2["meta"]["versions"]["actor"] == 4
    assert st2["meta"]["epoch_pos"] == 16
    assert st2["meta"]["epoch"] == 0
