"""GAE value-alignment parity vs the REFERENCE convention.

An independent numpy implementation of the reference's GAE pairing
(``pygae1d_nolp_misalign``, ``realhf/impl/model/utils/ppo_functional.py:292``
with the value/reward setup of ``ppo_interface.py:555-640``): for a sequence
of L tokens with P prompt tokens,

 - per-step rewards live on "short1" slots t ∈ [0, L−1), where slot t is the
   reward for emitting token t+1 (KL penalty on action slots, task score on
   the last slot),
 - values are full-length (one per token, conditioning on that token), with
   the EOS value zeroed for terminated sequences,
 - δ_t = r_t + γ·V[t+1]·(boot if t last) − V[t],  adv_t = δ_t + γλ·adv_{t+1},
 - the advantage at short1 slot t pairs with the action logprob of token
   t+1; returns_t = adv_t + V[t] targets the PRE-action value.

This must equal ``compute_advantages_and_returns`` (full-length layout:
advantage for token t stored at slot t) — the round-1 bug paired r_t with
V[t] instead of V[t−1], which this test is designed to catch.
"""

import numpy as np

from areal_tpu.algorithms.ppo import (
    PPOHyperparameters,
    compute_advantages_and_returns,
)
from areal_tpu.api.data import SequenceSample


def reference_gae_full_layout(
    seqlens, prompt_lens, behav_lp, ref_lp, values, scores, no_eos,
    kl_coef, gamma, lam,
):
    """Returns (adv, ret) as full-length packed arrays (slot t = token t;
    zeros on prompt slots), computed with the reference convention."""
    adv_out = np.zeros(sum(seqlens), np.float64)
    ret_out = np.zeros(sum(seqlens), np.float64)
    off = 0
    for i, (L, P) in enumerate(zip(seqlens, prompt_lens)):
        v = values[off : off + L].astype(np.float64).copy()
        if not no_eos[i]:
            v[L - 1] = 0.0  # zero the EOS-token value when terminated
        # short1 rewards: KL on action slots, task score on the last slot.
        r = np.zeros(L - 1, np.float64)
        for t in range(L - 1):
            tok = t + 1  # token emitted by action at short1 slot t
            if tok >= P:  # action token → KL penalty applies
                r[t] = -kl_coef * (behav_lp[off + tok] - ref_lp[off + tok])
        r[L - 2] += scores[i]
        adv = np.zeros(L - 1, np.float64)
        lastgaelam = 0.0
        for t in reversed(range(L - 1)):
            nxt = v[t + 1]
            if t == L - 2 and not no_eos[i]:
                nxt = 0.0  # terminated: no bootstrap beyond EOS
            delta = r[t] + gamma * nxt - v[t]
            lastgaelam = delta + gamma * lam * lastgaelam
            adv[t] = lastgaelam
        # map short1 slot t → full slot t+1 (the token the action emitted)
        for t in range(L - 1):
            if t + 1 >= P:
                adv_out[off + t + 1] = adv[t]
                ret_out[off + t + 1] = adv[t] + v[t]
        off += L
    return adv_out, ret_out


def _build_sample(rng, n_seq=6):
    plens = rng.randint(2, 5, n_seq)
    glens = rng.randint(3, 9, n_seq)
    seqlens = (plens + glens).astype(int)
    total = int(seqlens.sum())
    pmask, behav, ref = [], [], []
    for p, g in zip(plens, glens):
        pmask.append(np.concatenate([np.ones(p, np.int32), np.zeros(g, np.int32)]))
        lp = np.zeros(p + g, np.float32)
        lp[p:] = -rng.rand(g)  # behaviour logprobs on action slots
        behav.append(lp)
        rlp = np.zeros(p + g, np.float32)
        rlp[p:] = -rng.rand(g)
        ref.append(rlp)
    pmask = np.concatenate(pmask)
    behav = np.concatenate(behav).astype(np.float32)
    ref = np.concatenate(ref).astype(np.float32)
    values = rng.randn(total).astype(np.float32)
    scores = rng.randn(n_seq).astype(np.float32)
    no_eos = rng.randint(0, 2, n_seq).astype(np.float32)
    sample = SequenceSample.from_default(
        ids=[f"s{i}" for i in range(n_seq)],
        data={
            "packed_input_ids": rng.randint(2, 100, total).astype(np.int32),
            "prompt_mask": pmask,
            "packed_logprobs": behav,
            "packed_ref_logprobs": ref,
            "values": values,
            "rewards": scores,
            "seq_no_eos_mask": no_eos,
        },
        seqlens=seqlens.tolist(),
    )
    return sample, seqlens, plens, behav, ref, values, scores, no_eos


def test_gae_matches_reference_value_alignment():
    rng = np.random.RandomState(3)
    sample, seqlens, plens, behav, ref, values, scores, no_eos = _build_sample(rng)
    kl_coef, gamma, lam = 0.2, 0.97, 0.93
    hp = PPOHyperparameters(
        discount=gamma, gae_lambda=lam, reward_output_scaling=1.0,
        max_reward_clip=100.0,
    )
    out = compute_advantages_and_returns(sample, hp, kl_coef)
    adv_ref, ret_ref = reference_gae_full_layout(
        seqlens, plens, behav, ref, values, scores,
        no_eos=(no_eos > 0), kl_coef=kl_coef, gamma=gamma, lam=lam,
    )
    np.testing.assert_allclose(out["advantages"], adv_ref, atol=2e-4)
    np.testing.assert_allclose(out["returns"], ret_ref, atol=2e-4)


def test_gae_action_dependent_baseline_is_gone():
    """With γ=λ=1, no KL and zero score, the advantage at the FIRST action
    slot must be −V[P−1] (pre-action baseline), not −V[P]."""
    rng = np.random.RandomState(0)
    P, G = 3, 4
    L = P + G
    values = rng.randn(L).astype(np.float32)
    sample = SequenceSample.from_default(
        ids=["a"],
        data={
            "packed_input_ids": rng.randint(2, 50, L).astype(np.int32),
            "prompt_mask": np.concatenate(
                [np.ones(P, np.int32), np.zeros(G, np.int32)]
            ),
            "packed_logprobs": np.zeros(L, np.float32),
            "packed_ref_logprobs": np.zeros(L, np.float32),
            "values": values,
            "rewards": np.zeros(1, np.float32),
            "seq_no_eos_mask": np.zeros(1, np.float32),  # terminated
        },
        seqlens=[L],
    )
    hp = PPOHyperparameters(discount=1.0, gae_lambda=1.0)
    out = compute_advantages_and_returns(sample, hp, kl_coef=0.0)
    # telescoping: adv at first action slot = sum(deltas) = −V[P−1]
    np.testing.assert_allclose(out["advantages"][P], -values[P - 1], atol=1e-5)
    # and the return target for the first action is V[P−1] + adv = 0 here;
    # more usefully: ret[t] − adv[t] must equal V[t−1] on every action slot.
    for t in range(P, L):
        np.testing.assert_allclose(
            out["returns"][t] - out["advantages"][t], values[t - 1], atol=1e-5
        )
