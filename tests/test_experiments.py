"""Experiment-definition tests: DFG pruning + worker-config generation
(reference tests/experiments semantics for PPOMATHConfig/AsyncPPOMATHConfig)."""

from areal_tpu.api import cli_args as CA
from areal_tpu.api.dfg import MFCInterfaceType
from areal_tpu.experiments.async_ppo_math_exp import AsyncPPOMATHConfig
from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig


def _tiny(cfg):
    CA.apply_overrides(cfg, [
        "trial_name=t0",
        "mock_tokenizer=true",
        "actor.tiny.vocab_size=258",
        "ref.tiny.vocab_size=258",
        "dataset.path=/tmp/none.jsonl",
        "dataset.train_bs_n_seqs=4",
        "group_size=2",
    ])
    return cfg


def test_sync_full_dfg_grpo_decoupled():
    cfg = _tiny(PPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.disable_value=true", "ppo.use_decoupled_loss=true",
        "ppo.kl_ctl=0.05",
    ])
    dfg = cfg.build_dfg(4)
    names = set(dfg.nodes)
    assert names == {"actor_gen", "rew_inf", "ref_inf", "actor_inf",
                     "actor_train"}
    # flattened group sizes: downstream nodes see n_prompts*group_size
    assert dfg.nodes["actor_gen"].n_seqs == 4
    assert dfg.nodes["actor_train"].n_seqs == 8
    assert "prox_logprobs" in dfg.nodes["actor_train"].input_keys
    assert "packed_ref_logprobs" in dfg.nodes["actor_train"].input_keys


def test_sync_dfg_pruning():
    # kl_ctl=0 drops ref_inf; no recompute/decoupled drops actor_inf;
    # critic on → critic nodes present.
    cfg = _tiny(PPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.kl_ctl=0.0", "ppo.disable_value=false",
        "ppo.use_decoupled_loss=false", "ppo.recompute_logprob=false",
        "critic.tiny.vocab_size=258",
    ])
    dfg = cfg.build_dfg(4)
    names = set(dfg.nodes)
    assert names == {"actor_gen", "rew_inf", "critic_inf", "critic_train",
                     "actor_train"}
    assert "values" in dfg.nodes["actor_train"].input_keys
    assert dfg.nodes["critic_train"].interface_type == MFCInterfaceType.TRAIN_STEP


def test_sync_dfg_fused_rew_ref():
    """fuse_rew_ref=True replaces rew_inf + ref_inf with ONE fused node on
    the ref model (reference fuse_rew_ref semantics); the rew model role
    disappears from the trainer config."""
    cfg = _tiny(PPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.disable_value=true", "ppo.kl_ctl=0.05", "fuse_rew_ref=true",
    ])
    dfg = cfg.build_dfg(4)
    names = set(dfg.nodes)
    assert "rew_inf" not in names and "ref_inf" not in names
    assert "fused_rew_ref_inf" in names
    node = dfg.nodes["fused_rew_ref_inf"]
    assert set(node.output_keys) == {"rewards", "packed_ref_logprobs"}
    assert "packed_ref_logprobs" in dfg.nodes["actor_train"].input_keys
    tc = cfg.build_trainer_config()
    assert "rew" not in tc.models and "ref" in tc.models
    assert tc.mfcs["fused_rew_ref_inf"].interface == "fused_forward"


def test_async_dfg_has_no_gen_or_rew():
    cfg = _tiny(AsyncPPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.disable_value=true", "ppo.use_decoupled_loss=true",
        "ppo.kl_ctl=0.05",
    ])
    dfg = cfg.build_dfg(4, async_mode=True)
    assert set(dfg.nodes) == {"ref_inf", "actor_inf", "actor_train"}


def test_initial_setup_generates_worker_configs():
    cfg = _tiny(AsyncPPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.disable_value=true", "ppo.use_decoupled_loss=true",
        "ppo.kl_ctl=0.05", "allocation_mode=gen.d2+d4",
        "n_rollout_workers=2", "max_concurrent_rollouts=8",
        "max_head_offpolicyness=4", "new_tokens_per_chunk=16",
    ])
    setup = cfg.initial_setup()
    assert len(setup["gen_servers"]) == 2  # gen.d2 → 2 dp replicas
    assert setup["gserver_manager"].n_servers == 2
    assert setup["gserver_manager"].max_head_offpolicyness == 4
    assert len(setup["rollout_workers"]) == 2
    rw = setup["rollout_workers"][0]
    assert rw.max_concurrent == 4  # 8 // 2 workers
    assert rw.chunk_tokens == 16
    assert rw.gconfig.n == 2  # group_size
    # async-recovery skiplist must be wired to the recover dir (advisor r5)
    assert rw.recover_dir and rw.recover_dir == setup["master"].recover_dir
    trainer = setup["trainer"]
    assert trainer.stream_dataset is True
    assert set(trainer.models) == {"actor", "ref"}
    assert set(trainer.mfcs) == {"ref_inf", "actor_inf", "actor_train"}
    # tiny models get CPU-scale backend args
    assert trainer.models["actor"].backend_args["length_bucket"] == 16
    # async mode counts flattened TRAJECTORIES: 4 prompts x group_size 2
    assert setup["master"].train_batch_size == 8
    assert setup["gserver_manager"].train_batch_size == 8


def test_sync_initial_setup_with_parallel_spec():
    cfg = _tiny(PPOMATHConfig())
    CA.apply_overrides(cfg, [
        "ppo.disable_value=true", "allocation_mode=d2f2t2",
        "ppo.kl_ctl=0.0",
    ])
    setup = cfg.initial_setup()
    assert setup["trainer"].models["actor"].backend_args["parallel_spec"] == \
        "d2f2t2"
    assert set(setup["trainer"].mfcs) == {"actor_gen", "rew_inf",
                                          "actor_train"}
