"""Ring attention (context parallelism) parity + integration tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import packing, transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.ops import attention as attn
from areal_tpu.parallel import mesh as pmesh
from areal_tpu.parallel import sharding as psh
from areal_tpu.parallel.ring import ring_attention


def _case(seqlens, Hq, Hkv, D, row_len, seed=0):
    rng = np.random.RandomState(seed)
    # min 2 rows so the batch dim divides the dp×fsdp mesh axes
    layout = packing.plan_packing(seqlens, row_len=row_len, min_rows=2)
    grid = packing.make_grid(layout)
    B, L = layout.shape
    q = jnp.asarray(rng.randn(B, L, Hq, D).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3)
    return grid, q, k, v


@pytest.mark.parametrize("spec", ["s4", "d2s2t2", "s8"])
@pytest.mark.parametrize("seqlens,row_len", [([32], 32), ([20, 9, 3], 32)])
def test_ring_matches_reference(spec, seqlens, row_len):
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec))
    grid, q, k, v = _case(seqlens, Hq=4, Hkv=2, D=16, row_len=row_len)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])
    ref = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                kv_positions=pos, impl="reference")
    out = jax.jit(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh)
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_flow():
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("s4"))
    grid, q, k, v = _case([16, 12], Hq=2, Hkv=2, D=8, row_len=32)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seg, mesh) ** 2)

    def loss_ref(q, k, v):
        o = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                  kv_positions=pos, impl="reference")
        return jnp.sum(o**2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"grad {name}")


def test_transformer_forward_with_sp_mesh():
    """Full model forward under an sp>1 mesh dispatches to ring attention
    and matches the unsharded result."""
    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    B, T = 2, 32
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    ref, _ = transformer.forward(params, cfg, tokens, positions,
                                 segment_ids=seg)

    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2s2t2"))
    sp = psh.shard_params(params, mesh, cfg)

    def fwd(p, t, pos, s):
        with psh.activation_sharding(mesh):
            out, _ = transformer.forward(p, cfg, t, pos, segment_ids=s)
        return out

    out = jax.jit(fwd)(sp, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
