"""Ring attention (context parallelism) parity + integration tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models import packing, transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.ops import attention as attn
from areal_tpu.parallel import mesh as pmesh
from areal_tpu.parallel import ring as ring_mod
from areal_tpu.parallel import sharding as psh
from areal_tpu.parallel.ring import ring_attention

pytestmark = pytest.mark.ring


def _case(seqlens, Hq, Hkv, D, row_len, seed=0):
    rng = np.random.RandomState(seed)
    # min 2 rows so the batch dim divides the dp×fsdp mesh axes
    layout = packing.plan_packing(seqlens, row_len=row_len, min_rows=2)
    grid = packing.make_grid(layout)
    B, L = layout.shape
    q = jnp.asarray(rng.randn(B, L, Hq, D).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32) * 0.3)
    return grid, q, k, v


@pytest.mark.parametrize("schedule", ["zigzag", "naive"])
@pytest.mark.parametrize("spec", ["s4", "d2s2t2", "s8"])
@pytest.mark.parametrize("seqlens,row_len", [([32], 32), ([20, 9, 3], 32)])
def test_ring_matches_reference(spec, seqlens, row_len, schedule):
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec))
    grid, q, k, v = _case(seqlens, Hq=4, Hkv=2, D=16, row_len=row_len)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])
    ref = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                kv_positions=pos, impl="reference")
    out = jax.jit(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh,
                                          schedule=schedule)
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("schedule", ["zigzag", "naive"])
def test_ring_gradients_flow(schedule):
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("s4"))
    grid, q, k, v = _case([16, 12], Hq=2, Hkv=2, D=8, row_len=32)
    seg = jnp.asarray(grid["segment_ids"])
    pos = jnp.asarray(grid["positions"])

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, seg, mesh, schedule=schedule) ** 2
        )

    def loss_ref(q, k, v):
        o = attn.packed_attention(q, k, v, seg, seg, q_positions=pos,
                                  kv_positions=pos, impl="reference")
        return jnp.sum(o**2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"grad {name}")


def test_zigzag_permutation_roundtrip():
    for T, n in [(16, 2), (32, 4), (64, 8)]:
        fwd = np.asarray(ring_mod.zigzag_permutation(T, n))
        inv = np.asarray(ring_mod.inverse_permutation(fwd))
        assert sorted(fwd.tolist()) == list(range(T))
        np.testing.assert_array_equal(fwd[inv], np.arange(T))
        np.testing.assert_array_equal(inv[fwd], np.arange(T))
        # Rank r holds chunks (r, 2n-1-r) of the 2n global chunks — one
        # early, one late, so causal work balances across the ring.
        c = T // (2 * n)
        chunk_of = fwd.reshape(n, 2, c) // c
        for r in range(n):
            assert chunk_of[r, 0, 0] == r
            assert chunk_of[r, 1, 0] == 2 * n - 1 - r


def test_zigzag_matches_naive_oracle():
    """The balanced schedule and the contiguous v1 oracle agree to float
    round-off on packed multi-document rows."""
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("s4"))
    grid, q, k, v = _case([20, 9, 3], Hq=4, Hkv=2, D=16, row_len=32)
    seg = jnp.asarray(grid["segment_ids"])
    out_zz = jax.jit(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh,
                                          schedule="zigzag")
    )(q, k, v, seg)
    out_nv = jax.jit(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh,
                                          schedule="naive")
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out_zz), np.asarray(out_nv),
                               atol=1e-6)


def test_resolve_schedule_env_and_downgrades(monkeypatch):
    monkeypatch.setenv("AREAL_RING_SCHEDULE", "naive")
    assert ring_mod.resolve_schedule(None, 32, 4) == "naive"
    monkeypatch.delenv("AREAL_RING_SCHEDULE")
    assert ring_mod.resolve_schedule(None, 32, 4) == "zigzag"
    with pytest.raises(ValueError):
        ring_mod.resolve_schedule("bogus", 32, 4)
    # Downgrades to the oracle when zig-zag's preconditions fail.
    assert ring_mod.resolve_schedule("zigzag", 30, 4) == "naive"
    assert ring_mod.resolve_schedule("zigzag", 32, 4,
                                     causal=False) == "naive"
    assert ring_mod.resolve_schedule("zigzag", 32, 1) == "naive"


@pytest.mark.parametrize("spec,n", [("s4", 4), ("s8", 8)])
def test_zigzag_skip_ratio_structural(spec, n):
    """Causal skip proven structurally: the trace-time area counters show
    exactly (n+1)/2n of the naive per-step attention work executes."""
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse(spec))
    grid, q, k, v = _case([32], Hq=2, Hkv=2, D=8, row_len=32)
    seg = jnp.asarray(grid["segment_ids"])
    ring_mod.reset_ring_counters()
    jax.jit(
        lambda q, k, v, s: ring_attention(q, k, v, s, mesh,
                                          schedule="zigzag")
    )(q, k, v, seg)
    assert ring_mod.ring_counters()["naive_area"] > 0
    assert ring_mod.ring_skip_ratio() == pytest.approx((n + 1) / (2 * n))


def test_transformer_forward_with_sp_mesh():
    """Full model forward under an sp>1 mesh dispatches to ring attention
    and matches the unsharded result."""
    cfg = tiny_config(n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    B, T = 2, 32
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
    positions = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    seg = np.ones((B, T), np.int32)
    ref, _ = transformer.forward(params, cfg, tokens, positions,
                                 segment_ids=seg)

    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse("d2s2t2"))
    sp = psh.shard_params(params, mesh, cfg)

    def fwd(p, t, pos, s):
        with psh.activation_sharding(mesh):
            out, _ = transformer.forward(p, cfg, t, pos, segment_ids=s)
        return out

    out = jax.jit(fwd)(sp, tokens, positions, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
