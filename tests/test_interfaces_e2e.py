"""Single-process end-to-end slices (SURVEY §7 minimum slice):
SFT loop, and sync-PPO: generate → reward → ref/critic inf → actor/critic
train. Mirrors the reference's tests/experiments e2e suite, without the
worker fabric (that layer gets its own tests)."""

import numpy as np
import pytest

import jax

from areal_tpu.algorithms.ppo import (
    PPOActorInterface,
    PPOCriticInterface,
    PPOHyperparameters,
    LogprobInterface,
    attach_keys,
)
from areal_tpu.algorithms.reward import MultiTaskRewardInterface
from areal_tpu.algorithms.sft import SFTInterface
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    FinetuneSpec,
    GenerationHyperparameters,
    Model,
)
from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
from areal_tpu.base.testing import MockTokenizer, make_math_jsonl, make_sft_jsonl
from areal_tpu.datasets.jsonl import MathCodePromptDataset, PromptAnswerDataset
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config


MBS = MicroBatchSpec(max_tokens_per_mb=512)


def _make_model(name, vocab=258, is_critic=False, seed=0, train=True):
    cfg = tiny_config(vocab_size=vocab, n_layers=2, hidden_dim=32,
                      is_critic=is_critic)
    params = transformer.init_params(cfg, jax.random.PRNGKey(seed))
    model = Model(name, (cfg, params), tokenizer=MockTokenizer(vocab))
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant",
                                  warmup_steps_proportion=0.0),
        compute_dtype="float32", length_bucket=32, rows_bucket=2,
        seqs_bucket=4, train=train,
    )
    return backend.initialize(model, FinetuneSpec(1, 64, 8))


def test_sft_e2e(tmp_path):
    path = tmp_path / "sft.jsonl"
    make_sft_jsonl(str(path), n=16)
    tok = MockTokenizer()
    ds = PromptAnswerDataset(dataset_path=str(path), tokenizer=tok)
    model = _make_model("sft")
    iface = SFTInterface()
    batch = SequenceSample.gather([ds[i] for i in range(8)])
    first = iface.train_step(model, batch, MBS)
    for _ in range(6):
        last = iface.train_step(model, batch, MBS)
    assert last["ppl"] < first["ppl"]
    ev = iface.inference(model, batch, MBS)
    assert "eval_nll" in ev.keys and ev.bs == 8


@pytest.fixture()
def math_env(tmp_path):
    path = tmp_path / "math.jsonl"
    make_math_jsonl(str(path), n=8)
    tok = MockTokenizer()
    ds = MathCodePromptDataset(dataset_path=str(path), tokenizer=tok)
    return ds, tok, str(path)


def test_sync_ppo_e2e(math_env):
    ds, tok, path = math_env
    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8, temperature=1.0),
        group_size=2, ppo_n_minibatches=2, kl_ctl=0.05,
        adv_norm=True, value_norm=True,
    )
    actor = _make_model("actor", seed=0)
    critic = _make_model("critic", is_critic=True, seed=1)
    ref = _make_model("ref", seed=0, train=False)
    rw_model = Model("rw", None, tokenizer=tok)

    actor_i = PPOActorInterface(hp)
    critic_i = PPOCriticInterface(hp)
    ref_i = LogprobInterface()
    rw_i = MultiTaskRewardInterface(dataset_path=path, group_size=hp.group_size)

    prompts = SequenceSample.gather([ds[i] for i in range(4)])

    # --- one full PPO step over the 7-node DFG, in-process ---
    traj = actor_i.generate(actor, prompts, MBS)
    assert traj.bs == 8  # 4 prompts × group 2
    assert {"packed_input_ids", "prompt_mask", "packed_logprobs",
            "seq_no_eos_mask", "version_start"} <= traj.keys

    rew = rw_i.inference(rw_model, traj, MBS)
    traj.update_(rew)
    refs = ref_i.inference(ref, traj, MBS)
    traj.update_(refs)
    vals = critic_i.inference(critic, traj, MBS)
    traj.update_(vals)
    prox = actor_i.inference(actor, traj, MBS)
    traj.update_(prox)

    astats = actor_i.train_step(actor, traj, MBS)
    cstats = critic_i.train_step(critic, traj, MBS)
    assert np.isfinite(astats["actor_loss"])
    assert np.isfinite(cstats["critic_loss"])
    assert astats["n_action_tokens"] > 0
    assert actor.version.global_step == 1

    # behaviour == current policy ⇒ importance weight ≈ 1 on the 1st minibatch
    assert 0.5 < astats["importance_weight"] < 2.0


def test_fused_rew_ref_interface(math_env):
    """FusedForwardInterface (reference fused_interface.py "fused-
    threading"): ref-logprob + reward children run concurrently on one MFC
    and their outputs merge — equal to running them sequentially."""
    from areal_tpu.algorithms.fused import FusedForwardInterface

    ds, tok, path = math_env
    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=8), group_size=2,
    )
    actor = _make_model("actor_f", seed=0)
    ref = _make_model("ref_f", seed=0, train=False)
    actor_i = PPOActorInterface(hp)
    prompts = SequenceSample.gather([ds[i] for i in range(3)])
    traj = actor_i.generate(actor, prompts, MBS)

    fused = FusedForwardInterface(interfaces={
        "rew": ("rw_math_code", {"dataset_path": path, "group_size": 2}),
        "ref": ("ref_logprob", {}),
    })
    out = fused.inference(ref, traj, MBS)
    assert {"rewards", "packed_ref_logprobs"} <= out.keys
    assert out.bs == traj.bs
    # parity with the unfused children
    seq = LogprobInterface().inference(ref, traj, MBS)
    np.testing.assert_allclose(
        out.data["packed_ref_logprobs"], seq.data["packed_ref_logprobs"],
        atol=1e-5,
    )
    rw = MultiTaskRewardInterface(dataset_path=path, group_size=2).inference(
        Model("rw", None, tokenizer=tok), traj, MBS
    )
    np.testing.assert_array_equal(out.data["rewards"], rw.data["rewards"])


def test_ppo_decoupled_and_grpo_paths(math_env):
    ds, tok, path = math_env
    hp = PPOHyperparameters(
        gen=GenerationHyperparameters(max_new_tokens=6),
        group_size=2, ppo_n_minibatches=1,
        disable_value=True, group_adv_norm=True, adv_norm=False,
        use_decoupled_loss=True, behav_imp_weight_cap=10.0,
        kl_ctl=0.0, use_adaptive_kl_ctl=True,
    )
    actor = _make_model("actor2", seed=2)
    actor_i = PPOActorInterface(hp)
    rw_i = MultiTaskRewardInterface(dataset_path=path, group_size=2)

    prompts = SequenceSample.gather([ds[i] for i in range(3)])
    traj = actor_i.generate(actor, prompts, MBS)
    traj.update_(rw_i.inference(Model("rw", None, tokenizer=tok), traj, MBS))
    traj.update_(actor_i.inference(actor, traj, MBS))  # prox_logprobs
    # GRPO: no critic values anywhere
    assert "values" not in traj.keys
    stats = actor_i.train_step(actor, traj, MBS)
    assert np.isfinite(stats["actor_loss"])


def test_attach_keys_non_mutating():
    s = SequenceSample.from_default(
        ids=["a"], data={"packed_input_ids": np.arange(4, dtype=np.int32)},
        seqlens=[4],
    )
    s2 = attach_keys(s, {"advantages": np.ones(4, np.float32)})
    assert "advantages" in s2.keys and "advantages" not in s.keys
