"""Train-engine tests (role of the reference's mock_train-backed tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import FinetuneSpec, GenerationHyperparameters
from areal_tpu.backend import microbatch as mbu
from areal_tpu.backend.jax_train import (
    JaxTrainEngine,
    OptimizerConfig,
    build_lr_schedule,
)
from areal_tpu.models import transformer
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel import mesh as pmesh


def _sample(rng, n, vocab=64, minlen=4, maxlen=20):
    lens = rng.randint(minlen, maxlen, n)
    toks = rng.randint(2, vocab, int(lens.sum())).astype(np.int32)
    mask = rng.rand(int(lens.sum())) > 0.2
    return SequenceSample.from_default(
        ids=[f"s{i}" for i in range(n)],
        data={
            "packed_input_ids": toks,
            "loss_mask": mask.astype(np.float32),
        },
        seqlens=lens.tolist(),
    )


def _ce_loss(logits, batch):
    """Next-token CE summed over masked positions."""
    tokens = batch["tokens"]
    seg = batch["segment_ids"]
    labels = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
    nxt_seg = jnp.concatenate([seg[:, 1:], jnp.zeros_like(seg[:, :1])], axis=1)
    valid = (nxt_seg == seg) & (seg > 0)  # next token exists in same doc
    lm = batch["loss_mask"]
    lmask = jnp.concatenate([lm[:, 1:], jnp.zeros_like(lm[:, :1])], axis=1)
    w = valid * lmask
    lp = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(lp, labels[..., None], axis=-1)[..., 0]
    loss = -jnp.sum(tok_lp * w)
    return loss, {"n_valid": jnp.sum(w)}


def _weight(mb):
    return float(mb.grids["loss_mask"].sum())


def test_microbatch_split_and_scatter_roundtrip():
    rng = np.random.RandomState(0)
    s = _sample(rng, 9)
    mbs = mbu.split_into_microbatches(
        s, MicroBatchSpec(max_tokens_per_mb=64), length_bucket=16, rows_bucket=2
    )
    assert len(mbs) >= 2
    # reconstruct tokens via scatter_back on the token grids themselves
    outs = [mb.grids["tokens"] for mb in mbs]
    per_sample = mbu.scatter_back(mbs, outs, s.bs)
    flat = np.concatenate(per_sample)
    np.testing.assert_array_equal(flat, s.data["packed_input_ids"])


def test_lr_schedule_shapes():
    cfg = OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.1,
                          lr_scheduler_type="cosine", min_lr_ratio=0.1)
    sched = build_lr_schedule(cfg, 100)
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(10)) == pytest.approx(1e-3, rel=1e-2)
    assert float(sched(100)) == pytest.approx(1e-4, rel=1e-2)


def test_chunked_logprob_head_parity():
    """The chunked-logprob head (engine._forward_token_logprobs) must match
    the full-logits path exactly — outputs AND gradients — for every chunk
    size, including C == L (checkpoint-only) and C < L (lax.map)."""
    cfg = tiny_config(vocab_size=64)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    R, L = 2, 32
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 64, (R, L)), jnp.int32),
        "positions": jnp.tile(jnp.arange(L, dtype=jnp.int32), (R, 1)),
        "segment_ids": jnp.asarray(
            np.where(np.arange(L) < 28, 1, 0)[None].repeat(R, 0), jnp.int32
        ),
    }
    from areal_tpu.algorithms import ppo_functional as F

    def full_lp(eng, p):
        logits = eng._model_forward(p, batch)
        return F.token_logprobs_from_logits(
            logits, batch["tokens"], batch["segment_ids"]
        )

    ref_eng = JaxTrainEngine(cfg, params, compute_dtype="float32",
                             logprob_chunk=None)
    ref = full_lp(ref_eng, ref_eng.params)
    g_ref = jax.grad(lambda p: jnp.sum(full_lp(ref_eng, p) ** 2))(
        ref_eng.params
    )
    for chunk in (8, 16, 32, 64):
        eng = JaxTrainEngine(cfg, params, compute_dtype="float32",
                             logprob_chunk=chunk)
        lp, aux = eng._forward_token_logprobs(eng.params, batch)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        g = jax.grad(
            lambda p: jnp.sum(eng._forward_token_logprobs(p, batch)[0] ** 2)
        )(eng.params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


def test_scale_by_adam_mixed_matches_optax():
    """The mixed-dtype Adam (backend.scale_by_adam_mixed) with f32 moments
    must match optax.adamw exactly; bf16 moments track within bf16 noise."""
    import optax

    from areal_tpu.backend.jax_train import scale_by_adam_mixed

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4).astype(np.float32)),
              "b": jnp.asarray(rng.randn(4).astype(np.float32))}
    grads_seq = [
        {"w": jnp.asarray(rng.randn(8, 4).astype(np.float32) * 0.1),
         "b": jnp.asarray(rng.randn(4).astype(np.float32) * 0.1)}
        for _ in range(5)
    ]
    ref = optax.chain(
        optax.scale_by_adam(b1=0.9, b2=0.95, eps=1e-5),
        optax.add_decayed_weights(0.05),
        optax.scale_by_learning_rate(1e-3),
    )
    ours = optax.chain(
        scale_by_adam_mixed(0.9, 0.95, 1e-5),
        optax.add_decayed_weights(0.05),
        optax.scale_by_learning_rate(1e-3),
    )
    bf = optax.chain(
        scale_by_adam_mixed(0.9, 0.95, 1e-5, mu_dtype="bfloat16",
                            nu_dtype="bfloat16"),
        optax.add_decayed_weights(0.05),
        optax.scale_by_learning_rate(1e-3),
    )
    p_ref, p_ours, p_bf = params, params, params
    s_ref, s_ours, s_bf = ref.init(params), ours.init(params), bf.init(params)
    for g in grads_seq:
        u, s_ref = ref.update(g, s_ref, p_ref)
        p_ref = optax.apply_updates(p_ref, u)
        u, s_ours = ours.update(g, s_ours, p_ours)
        p_ours = optax.apply_updates(p_ours, u)
        u, s_bf = bf.update(g, s_bf, p_bf)
        p_bf = optax.apply_updates(p_bf, u)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_ours)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # bf16-moment trajectory stays close (state rounding only)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_bf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=2e-2)
    # storage dtypes honored
    assert str(jax.tree.leaves(s_bf[0].mu)[0].dtype) == "bfloat16"
    assert str(jax.tree.leaves(s_bf[0].nu)[0].dtype) == "bfloat16"
    assert str(jax.tree.leaves(s_ours[0].mu)[0].dtype) == "float32"


@pytest.mark.parametrize("mesh_spec", [None, "d2f2t2"])
def test_train_batch_reduces_loss(mesh_spec):
    rng = np.random.RandomState(1)
    cfg = tiny_config(vocab_size=64)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    mesh = pmesh.make_mesh(pmesh.ParallelSpec.parse(mesh_spec)) if mesh_spec else None
    eng = JaxTrainEngine(
        cfg, params,
        opt_cfg=OptimizerConfig(lr=1e-2, lr_scheduler_type="constant",
                                warmup_steps_proportion=0.0),
        ft_spec=FinetuneSpec(1, 64, 8),
        mesh=mesh, compute_dtype="float32", length_bucket=16, rows_bucket=2,
    )
    s = _sample(rng, 8)
    spec = MicroBatchSpec(max_tokens_per_mb=64)
    losses = [
        eng.train_batch(s, spec, _ce_loss, _weight)["loss"] for _ in range(8)
    ]
    assert losses[-1] < losses[0] * 0.9, losses
    assert eng.opt_step_count == 8


@pytest.mark.ring
def test_train_batch_ppsp_matches_dense():
    """Step-0 train_batch parity, PP∘SP (p2s2) vs dense.

    Regression pin for the sp-sharded loss miscompile: jax 0.4.x GSPMD
    summed per-shard partials of a next-token-shift concatenate along an
    sp-sharded dim, so on pp×sp meshes the CE mask came back doubled and
    every position invalid (loss -0.0, n_valid 0). The engine now keeps
    the sequence dim unsharded outside manual regions; this test fails
    if that regresses.
    """
    rng = np.random.RandomState(1)
    cfg = tiny_config(vocab_size=64)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    # 8 seqs of exactly 16 tokens -> packer picks [R=8, L=16]: pp=2
    # engages (8 % 2 == 0) and ring engages (16 % 2*sp == 0).
    s = _sample(rng, 8, minlen=16, maxlen=17)
    spec = MicroBatchSpec(max_tokens_per_mb=128)
    stats = {}
    for label, mesh_spec in [("p2s2", "p2s2"), (None, None)]:
        mesh = (pmesh.make_mesh(pmesh.ParallelSpec.parse(mesh_spec))
                if mesh_spec else None)
        eng = JaxTrainEngine(
            cfg, jax.tree.map(jnp.copy, params),  # train_batch donates
            opt_cfg=OptimizerConfig(lr=1e-2, lr_scheduler_type="constant",
                                    warmup_steps_proportion=0.0),
            ft_spec=FinetuneSpec(1, 64, 8),
            mesh=mesh, compute_dtype="float32",
            length_bucket=16, rows_bucket=4,
        )
        stats[label] = eng.train_batch(s, spec, _ce_loss, _weight)
    assert stats["p2s2"]["n_valid"] == stats[None]["n_valid"] > 0
    np.testing.assert_allclose(stats["p2s2"]["loss"], stats[None]["loss"],
                               rtol=1e-4)
    np.testing.assert_allclose(stats["p2s2"]["grad_norm"],
                               stats[None]["grad_norm"], rtol=1e-3)


def test_forward_logprobs_match_direct():
    rng = np.random.RandomState(2)
    cfg = tiny_config(vocab_size=32)
    params = transformer.init_params(cfg, jax.random.PRNGKey(1))
    eng = JaxTrainEngine(cfg, params, compute_dtype="float32",
                         length_bucket=16, rows_bucket=1)
    s = _sample(rng, 5, vocab=32)

    def logprob_hook(logits, batch):
        tokens = batch["tokens"]
        labels = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        lp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(lp, labels[..., None], axis=-1)[..., 0]

    per_sample = eng.forward(s, MicroBatchSpec(max_tokens_per_mb=48),
                             post_hook=logprob_hook)
    assert len(per_sample) == 5
    # check one sample against direct single-sequence forward
    i = 3
    toks = s.data["packed_input_ids"][
        s.offsets("packed_input_ids")[i] : s.offsets("packed_input_ids")[i]
        + s.total_lens()[i]
    ]
    T = len(toks)
    logits, _ = transformer.forward(
        jax.tree.map(jnp.asarray, params), cfg,
        jnp.asarray(toks[None]), jnp.arange(T)[None],
        segment_ids=jnp.ones((1, T), jnp.int32),
    )
    lp = jax.nn.log_softmax(logits[0], axis=-1)
    want = np.asarray(
        jnp.take_along_axis(lp[:-1], jnp.asarray(toks[1:, None]), axis=-1)[..., 0]
    )
    np.testing.assert_allclose(per_sample[i][: T - 1], want, atol=2e-3)


def test_generate_smoke():
    cfg = tiny_config(vocab_size=32)
    params = transformer.init_params(cfg, jax.random.PRNGKey(2))
    eng = JaxTrainEngine(cfg, params, compute_dtype="float32")
    prompts = np.array([3, 4, 5, 6, 7, 8], np.int32)
    s = SequenceSample.from_default(
        ids=["p0", "p1"],
        data={"packed_prompts": prompts},
        seqlens=[2, 4],
    )
    out = eng.generate(
        s, MicroBatchSpec(),
        GenerationHyperparameters(max_new_tokens=8, greedy=True, n=2),
        key=jax.random.PRNGKey(0), eos_token_id=1, pad_token_id=0,
    )
    assert out["output_ids"].shape == (4, 8)  # 2 prompts × n=2
    assert (out["output_lens"] >= 0).all() and (out["output_lens"] <= 8).all()
