"""Perf probe for the bench workload: isolates device kernel time from host
dispatch/packing overhead and sweeps the knobs that plausibly gate MFU.

Usage: python tools/perf_probe.py [probe ...]
Probes: e2e, grad, phases, mbsweep, remat, trace  (default: e2e grad)

Standalone probes (docs/benchmarks.md Tools):
  packfill [cap ...]                  HOST-ONLY (no TPU, no jax): packing
                                      fill of the bench-shaped length
                                      distribution at each token cap
                                      (default 2048 4096 8192), new
                                      128-grain sweep vs the coarse
                                      512-bucket candidates
  blocksweep [T] [S] [out.json]       sweep flash-attention (block_q,
                                      block_kv) at a geometry (default
                                      the bench grid, 1792x1792) and
                                      record the winner to out.json
                                      (default profiles/flash_blocks.json;
                                      load it via AREAL_FLASH_BLOCK_TABLE)
                                      — needs a real TPU: the kernel has
                                      no interpreter on this jax
  reshard-bench [src] [dst] [mb] [layers] [dim]
                                      time the mesh→mesh on-device
                                      reshard (parallel/reshard.py):
                                      build a synthetic stacked-layer
                                      tree, move it src-spec → dst-spec
                                      (default f2t2 → d4) and report the
                                      plan plus per-transfer-group
                                      throughput at the given group
                                      budget (default 64 MB); runs on
                                      CPU test meshes or real chips
                                      (docs/weight_sync.md §device)
  ring-bench [sp,sp,...] [seq,seq,...]
                                      sweep ring attention v2
                                      (parallel/ring.py) over
                                      (sp, seq_len): fwd+bwd step time
                                      zigzag vs the naive v1 oracle plus
                                      the structural causal-skip ratio
                                      ((n+1)/2n at sp=n); runs on CPU
                                      host meshes (JAX_PLATFORMS=cpu +
                                      --xla_force_host_platform_device_
                                      count=N) or real chips
                                      (docs/parallelism.md §PP∘SP)
  moe-bench [E,E,...] [k,k,...] [cf,cf,...]
                                      sweep MoE dispatch (models/moe.py)
                                      over (num_experts, top_k,
                                      capacity_factor): one MoE layer's
                                      fwd+bwd step time, sort-based
                                      grouped path (default) vs the
                                      one-hot einsum oracle, plus the
                                      routed dropped fraction; runs on
                                      CPU or real chips
                                      (docs/parallelism.md §Expert
                                      parallelism)

Live-fleet commands (docs/observability.md; name-resolve root via
AREAL_NAME_RESOLVE_ROOT when not the default):
  scrape <url>                        GET a worker's /metrics (Prometheus
                                      text or JSON) and pretty-print it
  scrape <exp> <trial>                same, against the aggregator's
                                      MERGED fleet endpoint (resolved via
                                      name-resolve; fails with a clear
                                      message when telemetry is disabled
                                      or http_port is 0)
  trace <traces.jsonl> <trace_id>     print a stitched sample-lineage
                                      trace as a critical-path timeline
                                      (docs/observability.md)
  flight-dump <exp> <trial> <dir>     ask EVERY live worker to dump its
                                      flight-recorder ring to
                                      <dir>/flight_<worker>.jsonl
  fleet-status <exp> <trial>          supervision view of a live run:
                                      per-worker heartbeat ages +
                                      incarnations (name-resolve
                                      liveness leases), the drain phase,
                                      the autoscale plan (target/dynamic
                                      fleet size, overload flag), the
                                      per-server fleet map (routable /
                                      cordoned / deprioritized,
                                      draining lease counts), and the
                                      supervisor restart / crash-loop
                                      counters from the merged
                                      Prometheus scrape
                                      (docs/fault_tolerance.md)
  cordon <exp> <trial> <server> [why] preemption-notice hook: cordon one
                                      generation server (server_id like
                                      gen1/dyn2, or its url) — it stops
                                      receiving leases, inflight
                                      rollouts drain or fail over, and
                                      a drained dynamic server exits
                                      via WorkerControl
                                      (docs/fault_tolerance.md
                                      §Autoscaling)
  uncordon <exp> <trial> <server>     lift a cordon; the server
                                      re-admits through the health gate
                                      (probe + weight reconcile)
  drain <exp> <trial>                 graceful preemption drain of a
                                      LIVE run: pause the rollout fleet,
                                      dump an out-of-band recover
                                      checkpoint via the master's
                                      control channel, then exit the
                                      workers in order (the launcher
                                      tears down the rest when the
                                      master returns) —
                                      docs/operations.md runbook
  decode-bench <server_url> [n_requests] [max_tokens]
                                      drive a LIVE generation server with
                                      a mixed-class synthetic workload
                                      (rollout/interactive/eval) and
                                      report tokens/s, per-class latency,
                                      queue depth, and the distinct
                                      compiled-shape count (VERDICT #9,
                                      docs/serving.md)
  reward-bench <exp> <trial> [n]      fan N mixed math/code tasks at a
                                      LIVE reward fleet (discovered via
                                      name-resolve) and report p50/p99
                                      grade latency per task kind plus
                                      the fleet-side verdict distribution
                                      from the merged Prometheus scrape
                                      (docs/rewards.md); also accepts one
                                      worker url: reward-bench <url> [n]
  goodput <exp> <trial> [window_s]    live goodput view of a run: per-
                                      worker compute/comm/data_wait/idle
                                      time-in-state fractions over a
                                      short live window (two scrapes of
                                      areal_goodput_secs_total diffed;
                                      default 5s — a since-start split
                                      would dilute a live stall by the
                                      run's whole history), plus the
                                      stitched fleet-goodput gauges and
                                      live MFU (docs/observability.md
                                      §Goodput); also accepts one
                                      worker url: goodput <url>
  spool-status <exp> <trial>          durable-spool view of a LIVE run
                                      (docs/fault_tolerance.md §Data
                                      durability): per-rollout-worker
                                      depth / bytes / oldest-unacked age
                                      from the merged Prometheus scrape,
                                      plus the fleet delivery totals
                                      (appended / acked / replayed /
                                      resent / stale-dropped) and the
                                      trainer-side dedup counters — the
                                      first stop of the "did we lose
                                      samples?" runbook
                                      (docs/operations.md)
  compile-status <exp> <trial>        compile-observatory view of a LIVE
                                      run (docs/observability.md §Compile
                                      & memory): per-jit-entry-point
                                      compile counts / seconds / distinct
                                      compiled shapes fleet-wide, the
                                      persistent-cache hit ratio,
                                      recompile-storm events, and which
                                      workers are compiling RIGHT NOW —
                                      the first stop of the "my run is
                                      wedged in warmup / my step got
                                      slow" runbook (docs/operations.md)
  mem-status <exp> <trial>            HBM watermark view of a LIVE run:
                                      per-worker per-device bytes-in-use
                                      / peak / limit / utilization plus
                                      the allocation-site high-water
                                      marks (weight publish/consume,
                                      shadow swap, fwd+bwd) —
                                      docs/weight_sync.md §HBM headroom
  alerts <exp> <trial> [severity] [rule]
                                      training-health sentinel view of a
                                      LIVE run: alert totals + active
                                      alerts from the merged Prometheus
                                      scrape, optionally filtered by
                                      severity (info|warn|critical) or
                                      rule id (docs/observability.md
                                      §Alerting)
  alerts <alerts.jsonl> [severity] [rule]
                                      same filters over a run's recorded
                                      alert stream (works after the run
                                      is dead — post-mortem triage)
  silence <exp> <trial> <rule> <dur>  silence one sentinel rule for a
                                      duration ("30s"/"10m"/"1h"): it
                                      keeps evaluating but neither fires
                                      nor captures evidence until the
                                      silence expires
  profile-trigger <exp> <trial> <dir> [secs]
                                      ask the live trainer for an
                                      on-demand jax.profiler capture
  profile-status <exp> <trial>        last capture outcome

Writes findings to stdout; `trace` saves a jax.profiler trace under
profiles/ for offline inspection.
"""

import sys
import time

sys.path.insert(0, ".")


def scrape(url: str) -> None:
    """Fetch + pretty-print a worker's /metrics endpoint. Prometheus text
    renders as an aligned table (histograms summarized as count/mean);
    JSON (e.g. /metrics.json) pretty-prints as-is."""
    import json as _json
    import urllib.error
    import urllib.request

    if not url.startswith("http"):
        url = f"http://{url}"
    if "/metrics" not in url:
        url = url.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read().decode()
    except (urllib.error.URLError, OSError) as e:
        sys.exit(f"scrape: cannot reach {url}: {e}\n"
                 f"(is the worker up, and telemetry enabled?)")
    if "json" in ctype:
        print(_json.dumps(_json.loads(body), indent=2, sort_keys=True))
        return
    rows = []
    hist = {}
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        base, _, labels = name.partition("{")
        labels = ("{" + labels) if labels else ""
        # Key histograms by (family, labels): the master's merged endpoint
        # carries one series per worker — dropping labels would silently
        # overwrite worker 0's sum/count with worker 1's.
        if base.endswith("_sum"):
            hist.setdefault(base[:-4] + labels, {})["sum"] = float(val)
        elif base.endswith("_count"):
            hist.setdefault(base[:-6] + labels, {})["count"] = float(val)
        elif base.endswith("_bucket"):
            continue  # summarized via _sum/_count
        else:
            rows.append((base + labels, float(val)))
    for h, d in sorted(hist.items()):
        n = d.get("count", 0)
        mean = (d.get("sum", 0.0) / n) if n else 0.0
        rows.append((f"{h} (hist)", f"n={n:g} mean={mean:.4g}"))
    w = max((len(r[0]) for r in rows), default=0)
    for k, v in sorted(rows):
        print(f"  {k:<{w}}  {v if isinstance(v, str) else f'{v:g}'}")


def decode_bench(server_url: str, n_requests: int = 24,
                 max_tokens: int = 32) -> None:
    """Decode-throughput probe against a LIVE generation server (the
    probe half of VERDICT #9): fire a mixed-class synthetic workload with
    randomized prompt lengths/budgets, then report client-side tokens/s
    + per-class latency and the server's own queue/shape counters from
    ``/metrics.json``. jax-free: run it from any host that can reach the
    server."""
    import asyncio
    import json as _json
    import random
    import time as _time
    import urllib.request

    import aiohttp

    url = server_url if server_url.startswith("http") \
        else f"http://{server_url}"
    rng = random.Random(0)
    classes = ["rollout", "rollout", "interactive", "eval"]

    async def one(session, i):
        cls = classes[i % len(classes)]
        plen = rng.randint(4, 48)
        budget = rng.randint(4, max_tokens)
        body = {
            "prompt_ids": [rng.randint(2, 90) for _ in range(plen)],
            "class": cls,
            "rid": f"bench{i}",
            "gconfig": {"max_new_tokens": budget, "greedy": False},
            "max_tokens": budget,
        }
        t0 = _time.monotonic()
        async with session.post(f"{url}/generate", json=body) as r:
            if r.status != 200:
                # 429 = admission backpressure, 413 = over capacity, 5xx =
                # server trouble: all reported, none kill the bench.
                return f"{cls}:http{r.status}", None, 0
            out = await r.json()
        return cls, _time.monotonic() - t0, len(out["output_ids"])

    async def run():
        async with aiohttp.ClientSession() as session:
            t0 = _time.monotonic()
            res = await asyncio.gather(
                *[one(session, i) for i in range(n_requests)]
            )
            return res, _time.monotonic() - t0

    results, wall = asyncio.run(run())
    tokens = sum(n for _, _, n in results)
    errs = sorted(c for c, dt, _ in results if dt is None)
    print(f"[decode-bench] {n_requests} requests "
          f"({len(errs)} non-200: {', '.join(errs) or 'none'}), "
          f"{tokens} tokens in {wall:.2f}s -> "
          f"{tokens / max(wall, 1e-9):,.0f} tok/s")
    for cls in ("interactive", "eval", "rollout"):
        lats = [dt for c, dt, _ in results if c == cls and dt is not None]
        if lats:
            lats.sort()
            print(f"[decode-bench] {cls:<12} n={len(lats)} "
                  f"mean={sum(lats) / len(lats) * 1e3:.0f}ms "
                  f"p95={lats[int(0.95 * (len(lats) - 1))] * 1e3:.0f}ms")
    with urllib.request.urlopen(f"{url}/metrics.json", timeout=10) as r:
        m = _json.loads(r.read().decode())
    print(f"[decode-bench] server: tokens_per_sec={m['tokens_per_sec']:.0f} "
          f"compiled_shapes={m.get('compiled_shapes')} "
          f"kv_states={m.get('kv_states')} "
          f"queue_depth={m.get('queue_depth')} "
          f"prefill_tokens={m.get('prefill_tokens')}")


def reward_bench(exp_or_url: str, trial: str = "",
                 n_tasks: int = 32) -> None:
    """Grade-latency probe against a LIVE reward fleet (docs/rewards.md):
    fan a mixed math/code synthetic workload through the real fanout
    client (bounded concurrency + retry across replicas), report client-
    side p50/p99 per task kind, then the fleet's own verdict counters
    from the merged Prometheus scrape (falling back to per-worker
    /metrics when the aggregator endpoint is absent). jax-free."""
    import asyncio
    import json as _json
    import random
    import time as _time
    import urllib.request

    from areal_tpu.api.train_config import RewardServiceConfig
    from areal_tpu.rewards.client import RewardServiceClient

    if exp_or_url.startswith("http"):
        urls = [exp_or_url.rstrip("/")]
    else:
        from areal_tpu.system.reward_worker import resolve_fleet

        urls = resolve_fleet(exp_or_url, trial)
        if not urls:
            sys.exit(
                f"reward-bench: no reward workers registered for "
                f"{exp_or_url}/{trial}.\nEither the fleet is down or the "
                f"service is disabled — relaunch with "
                f"reward_service.enabled=true, or probe one worker "
                f"directly: reward-bench <url>."
            )
    print(f"[reward-bench] fleet: {len(urls)} worker(s)")
    rng = random.Random(0)
    tasks = []
    for i in range(n_tasks):
        if i % 4 == 3:  # 1/4 code, 3/4 math — roughly the mixed-data shape
            k = rng.randint(1, 9)
            ok = rng.random() < 0.5
            code = (f"```python\nx = int(input())\nprint(x + "
                    f"{k if ok else k + 1})\n```")
            tasks.append({"task": "code", "generated": code,
                          "input_output": _json.dumps({
                              "inputs": ["1\n", "2\n"],
                              "outputs": [f"{1 + k}\n", f"{2 + k}\n"],
                          })})
        else:
            v = rng.randint(0, 999)
            guess = v if rng.random() < 0.5 else v + 1
            tasks.append({"task": "math",
                          "generated": f"\\boxed{{{guess}}}",
                          "solutions": [f"\\boxed{{{v}}}"]})

    # local_fallback OFF: a dead fleet must surface as 0.0-scored errors
    # and missing verdict counters, not silently benchmark local grading
    # on the operator's machine.
    client = RewardServiceClient(
        RewardServiceConfig(enabled=True, local_fallback=False), urls=urls
    )
    lats = {"math": [], "code": []}

    async def run():
        import aiohttp

        sem = asyncio.Semaphore(16)

        async def one(session, t):
            t0 = _time.monotonic()
            s = await client.grade_one(session, t, sem)
            lats[t["task"]].append(_time.monotonic() - t0)
            return s

        async with aiohttp.ClientSession() as session:
            t0 = _time.monotonic()
            scores = await asyncio.gather(
                *[one(session, t) for t in tasks]
            )
            return scores, _time.monotonic() - t0

    scores, wall = asyncio.run(run())
    print(f"[reward-bench] {n_tasks} tasks in {wall:.2f}s -> "
          f"{n_tasks / max(wall, 1e-9):.1f} grades/s, "
          f"mean score {sum(scores) / len(scores):.3f}")
    for kind in ("math", "code"):
        ls = sorted(lats[kind])
        if ls:
            print(f"[reward-bench] {kind:<5} n={len(ls)} "
                  f"p50={ls[len(ls) // 2] * 1e3:.1f}ms "
                  f"p99={ls[min(int(0.99 * len(ls)), len(ls) - 1)] * 1e3:.1f}ms")
    # fleet-side verdict distribution: merged scrape when available,
    # per-worker /metrics otherwise
    bodies = []
    if trial:
        from areal_tpu.base import name_resolve, names

        try:
            murl = name_resolve.get(names.telemetry_http(exp_or_url, trial))
            with urllib.request.urlopen(f"{murl}/metrics", timeout=10) as r:
                bodies = [("merged", r.read().decode())]
        except Exception:  # noqa: BLE001 — aggregator absent: per-worker
            pass
    if not bodies:
        for u in urls:
            try:
                with urllib.request.urlopen(f"{u}/metrics", timeout=10) as r:
                    bodies.append((u, r.read().decode()))
            except Exception as e:  # noqa: BLE001 — worker died mid-bench
                print(f"[reward-bench] scrape {u} failed: {e}")
    verdicts = {}
    for src, body in bodies:
        for ln in body.splitlines():
            if ln.startswith("areal_reward_verdicts_total{"):
                labels, _, val = ln.rpartition(" ")
                verdicts[labels] = verdicts.get(labels, 0.0) + float(val)
    if verdicts:
        print(f"[reward-bench] fleet verdicts "
              f"({'merged scrape' if bodies[0][0] == 'merged' else 'per-worker'}):")
        for k, v in sorted(verdicts.items()):
            print(f"  {k} {v:g}")
    else:
        print("[reward-bench] no verdict counters scraped "
              "(telemetry disabled on the fleet?)")


def scrape_fleet(experiment: str, trial: str) -> None:
    """Resolve + scrape the aggregator's MERGED fleet /metrics (the
    telemetry.http_port endpoint). jax-free; fails with an actionable
    message — not a traceback — when telemetry is off."""
    from areal_tpu.base import name_resolve, names

    try:
        url = name_resolve.get(names.telemetry_http(experiment, trial))
    except Exception:  # noqa: BLE001 — key absent: telemetry off/no port
        sys.exit(
            f"scrape: no merged telemetry endpoint registered for "
            f"{experiment}/{trial}.\nEither telemetry is disabled or the "
            f"aggregator has no HTTP port — relaunch with "
            f"telemetry.enabled=true telemetry.http_port=<port>, or "
            f"scrape a worker endpoint directly: scrape <url>."
        )
    print(f"[scrape] merged fleet endpoint {url}")
    scrape(url)


def print_trace(traces_path: str, trace_id: str) -> None:
    """Reconstruct one stitched trace from ``traces.jsonl`` as a
    chronological critical-path timeline: per-span offset from the
    prompt's admission, duration, owning worker — then the derived stage
    decomposition (generate/queue/gate/train-wait/train)."""
    import json as _json

    try:
        with open(traces_path) as f:
            recs = [_json.loads(ln) for ln in f if ln.strip()]
    except OSError as e:
        sys.exit(f"trace: cannot read {traces_path}: {e}")
    hits = [r for r in recs if r.get("trace_id") == trace_id]
    if not hits:
        known = {r.get("trace_id") for r in recs}
        sys.exit(f"trace: {trace_id!r} not in {traces_path} "
                 f"({len(known)} trace ids present)")
    # The LAST record is the most complete view (each trained sample of
    # the group re-stitches the trace with everything seen so far).
    rec = hits[-1]
    spans = sorted(rec.get("spans", []), key=lambda s: s["t_start"])
    t0 = rec.get("t_start", spans[0]["t_start"] if spans else 0.0)
    print(f"trace {trace_id}  sample={rec.get('sample_id')}  "
          f"weight_version={rec.get('weight_version')}  "
          f"e2e={rec.get('e2e_secs', 0):.3f}s  "
          f"workers={','.join(rec.get('workers', []))}")
    w = max((len(s['name']) for s in spans), default=0)
    for s in spans:
        off = s["t_start"] - t0
        attrs = s.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items())
                         if k not in ("error",))
        print(f"  +{off:8.3f}s  {s['name']:<{w}}  "
              f"{s['dur_secs'] * 1e3:9.1f}ms  [{s.get('worker', '?')}]"
              f"{('  ' + extra) if extra else ''}")
    stages = rec.get("stages") or {}
    if stages:
        print("  stages: " + "  ".join(
            f"{k}={v:.3f}s" for k, v in stages.items()
        ))


def flight_dump(experiment: str, trial: str, out_dir: str) -> None:
    from areal_tpu.base import telemetry

    nonce = telemetry.request_flight_dump(experiment, trial, out_dir)
    print(f"flight-dump trigger {nonce} set for {experiment}/{trial}: "
          f"every worker dumps flight_<worker>.jsonl into {out_dir} "
          f"within one telemetry flush interval (~2s at defaults)")


def spool_status(experiment: str, trial: str) -> None:
    """Durable-spool delivery view of a live run (jax-free), from the
    merged Prometheus scrape: per-rollout-worker spool depth, on-disk
    bytes and oldest-unacked age, plus the fleet-wide delivery ledger.
    ``appended == acked`` (and depth 0 everywhere) means every spooled
    trajectory settled — trained or durably dropped; a growing
    oldest-unacked age means the ack path is wedged
    (docs/operations.md runbook: "Did we lose samples?")."""
    import re
    import urllib.request

    from areal_tpu.base import name_resolve, names

    try:
        url = name_resolve.get(names.telemetry_http(experiment, trial))
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            body = r.read().decode()
    except Exception as e:  # noqa: BLE001 — aggregator absent / dead run
        sys.exit(
            f"spool-status: cannot scrape the merged telemetry endpoint "
            f"for {experiment}/{trial}: {e}\nNeeds telemetry.enabled=true "
            f"+ telemetry.http_port on the master. For a dead run, read "
            f"the spool directories under recover_dir/spool_<worker> "
            f"directly (docs/fault_tolerance.md §Data durability)."
        )
    lab_re = re.compile(r'(\w+)="([^"]*)"')
    gauges = {}  # worker_index -> {metric: value}
    totals = {}  # counter family -> summed value
    gauge_families = {
        "areal_spool_depth": "depth",
        "areal_spool_bytes": "bytes",
        "areal_spool_oldest_unacked_age_secs": "oldest_unacked_s",
    }
    counter_families = (
        "areal_spool_appended_total", "areal_spool_acked_total",
        "areal_spool_replayed_total", "areal_spool_resent_total",
        "areal_spool_replay_stale_dropped_total",
        "areal_spool_duplicate_dropped_total",
        "areal_spool_backpressure_waits_total",
        "areal_stream_push_blocked_total",
        "areal_buffer_duplicate_dropped_total",
    )
    for ln in body.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name, _, val = ln.rpartition(" ")
        base, _, rest = name.partition("{")
        if base in gauge_families:
            labels = dict(lab_re.findall(rest))
            w = labels.get("worker_index", "?")
            gauges.setdefault(w, {})[gauge_families[base]] = float(val)
        elif base in counter_families:
            totals[base] = totals.get(base, 0.0) + float(val)
    if not gauges and not totals:
        sys.exit(
            "spool-status: no spool metrics on the merged scrape — the "
            "durable spool is off (durability.enabled=false) or no "
            "rollout worker has flushed telemetry yet."
        )
    if gauges:
        print("per-worker spool state:")
        print(f"  {'worker':>6}  {'depth':>7}  {'bytes':>12}  "
              f"{'oldest unacked':>14}")
        for w in sorted(gauges, key=lambda x: (len(x), x)):
            g = gauges[w]
            print(f"  {w:>6}  {g.get('depth', 0):>7g}  "
                  f"{g.get('bytes', 0):>12g}  "
                  f"{g.get('oldest_unacked_s', 0):>13.1f}s")
    if totals:
        print("fleet delivery totals:")
        width = max(len(k) for k in totals)
        for k in counter_families:
            if k in totals:
                print(f"  {k:<{width}}  {totals[k]:g}")
        appended = totals.get("areal_spool_appended_total", 0.0)
        acked = totals.get("areal_spool_acked_total", 0.0)
        in_flight = sum(g.get("depth", 0) for g in gauges.values())
        if appended:
            print(f"  settled {acked:g}/{appended:g} "
                  f"({in_flight:g} durably queued on disk)")


def _merged_metric_rows(experiment: str, trial: str, command: str):
    """Fetch the aggregator's merged Prometheus scrape and parse it into
    ``(base_name, labels_dict, value)`` rows (jax-free). Shared by the
    compile/HBM observatory commands."""
    import re
    import urllib.request

    from areal_tpu.base import name_resolve, names

    try:
        url = name_resolve.get(names.telemetry_http(experiment, trial))
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            body = r.read().decode()
    except Exception as e:  # noqa: BLE001 — aggregator absent / dead run
        sys.exit(
            f"{command}: cannot scrape the merged telemetry endpoint for "
            f"{experiment}/{trial}: {e}\nNeeds telemetry.enabled=true + "
            f"telemetry.http_port on the master."
        )
    lab_re = re.compile(r'(\w+)="([^"]*)"')
    rows = []
    for ln in body.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name, _, val = ln.rpartition(" ")
        base, _, rest = name.partition("{")
        try:
            rows.append((base, dict(lab_re.findall(rest)), float(val)))
        except ValueError:
            continue
    return rows


def compile_status(experiment: str, trial: str) -> None:
    """Compile observatory view of a live run (jax-free), from the merged
    Prometheus scrape: per-jit-entry-point compile counts / total compile
    seconds / distinct compiled shapes across the fleet, the persistent-
    cache hit ratio, recompile-storm events, and which workers have a
    compile in flight RIGHT NOW — the first stop of the "my run is wedged
    in warmup / my step got slow" runbook (docs/operations.md)."""
    rows = _merged_metric_rows(experiment, trial, "compile-status")
    per_fn = {}  # fn -> {events, secs, shapes}
    inflight = []
    storms = cache_hits = cache_misses = 0.0
    for base, labels, val in rows:
        worker = (f"{labels.get('worker_kind', '?')}:"
                  f"{labels.get('worker_index', '?')}")
        fn = labels.get("fn", "?")
        if base == "areal_compile_events_total":
            per_fn.setdefault(fn, {})["events"] = \
                per_fn.get(fn, {}).get("events", 0.0) + val
        elif base == "areal_compile_secs_total" \
                and labels.get("worker_kind") != "fleet":
            per_fn.setdefault(fn, {})["secs"] = \
                per_fn.get(fn, {}).get("secs", 0.0) + val
        elif base == "areal_compile_distinct_shapes":
            d = per_fn.setdefault(fn, {})
            d["shapes"] = max(d.get("shapes", 0.0), val)
        elif base == "areal_compile_inflight" and val > 0:
            inflight.append(worker)
        elif base == "areal_compile_storm_events_total":
            storms += val
        elif base == "areal_compile_cache_hits_total":
            cache_hits += val
        elif base == "areal_compile_cache_misses_total":
            cache_misses += val
    if not per_fn:
        sys.exit(
            "compile-status: no compile metrics on the merged scrape — "
            "the observatory is off (compile_watch.enabled=false) or no "
            "watched jit entry point has compiled yet."
        )
    w = max(len(fn) for fn in per_fn)
    print("per-entry-point compile activity (fleet-wide):")
    print(f"  {'fn':<{w}}  {'compiles':>8}  {'secs':>8}  {'shapes':>6}")
    for fn in sorted(per_fn):
        d = per_fn[fn]
        print(f"  {fn:<{w}}  {d.get('events', 0):>8g}  "
              f"{d.get('secs', 0):>8.1f}  {d.get('shapes', 0):>6g}")
    total = cache_hits + cache_misses
    if total:
        print(f"persistent cache: {cache_hits:g} hits / "
              f"{cache_misses:g} misses "
              f"({100.0 * cache_hits / total:.0f}% hit)")
    if storms:
        print(f"RECOMPILE STORMS: {storms:g} storm event(s) — a stable "
              f"entry point saw new shapes after warmup. Check shape "
              f"bucketing (serving.max_compiled_shapes, "
              f"docs/serving.md) and the sentinel's recompile_storm "
              f"alert evidence.")
    if inflight:
        print(f"compiling NOW: {', '.join(sorted(inflight))} — absence "
              f"alerts (trainer_stalled) are suppressed while these "
              f"workers compile.")
    else:
        print("no compiles in flight.")


def mem_status(experiment: str, trial: str) -> None:
    """HBM watermark view of a live run (jax-free), from the merged
    Prometheus scrape: per-worker per-device bytes-in-use / peak / limit
    plus the high-water marks recorded around the big allocators (weight
    publish/consume, shadow swap, fwd+bwd) — the capacity-planning view
    of docs/weight_sync.md §HBM headroom."""
    rows = _merged_metric_rows(experiment, trial, "mem-status")
    devs = {}   # (worker, device) -> {in_use, peak, limit, util}
    marks = {}  # (worker, site) -> bytes
    degraded = 0.0
    fields = {
        "areal_hbm_bytes_in_use": "in_use",
        "areal_hbm_peak_bytes": "peak",
        "areal_hbm_limit_bytes": "limit",
        "areal_hbm_utilization": "util",
    }
    for base, labels, val in rows:
        worker = (f"{labels.get('worker_kind', '?')}:"
                  f"{labels.get('worker_index', '?')}")
        if base in fields and labels.get("worker_index") != "fleet":
            key = (worker, labels.get("device", "?"))
            devs.setdefault(key, {})[fields[base]] = val
        elif base == "areal_hbm_watermark_bytes":
            marks[(worker, labels.get("site", "?"))] = val
        elif base == "areal_hbm_memory_stats_unavailable_total":
            degraded += val
    if not devs and not marks and not degraded:
        sys.exit(
            "mem-status: no HBM metrics on the merged scrape — the "
            "observatory is off (compile_watch.enabled=false) or no "
            "worker has sampled device memory yet."
        )
    gib = float(1 << 30)
    if devs:
        print("per-device HBM:")
        print(f"  {'worker':<14}  {'dev':>3}  {'in use':>9}  "
              f"{'peak':>9}  {'limit':>9}  {'util':>5}")
        for (worker, dev) in sorted(devs):
            d = devs[(worker, dev)]
            limit = d.get("limit", 0.0)
            util = d.get("util", (d.get("in_use", 0.0) / limit)
                         if limit else 0.0)
            print(f"  {worker:<14}  {dev:>3}  "
                  f"{d.get('in_use', 0) / gib:>8.2f}G  "
                  f"{d.get('peak', 0) / gib:>8.2f}G  "
                  f"{limit / gib:>8.2f}G  "
                  f"{100.0 * util:>4.0f}%")
    if marks:
        print("allocation-site high-water marks:")
        w = max(len(s) for (_, s) in marks)
        for (worker, site) in sorted(marks, key=lambda k: (k[1], k[0])):
            print(f"  {site:<{w}}  {marks[(worker, site)] / gib:>8.2f}G  "
                  f"[{worker}]")
    if degraded:
        print(f"note: {degraded:g} worker(s) run on devices without "
              f"memory_stats() (CPU backend) — HBM gauges absent there "
              f"by design.")


def fleet_status(experiment: str, trial: str) -> None:
    """Supervision view of a live run (jax-free): heartbeat ages and
    incarnations from the name-resolve liveness keys, the graceful-drain
    phase, and the supervisor restart counters filtered out of the
    merged Prometheus scrape (when telemetry is up)."""
    import json as _json
    import urllib.request

    from areal_tpu.base import name_resolve, names
    from areal_tpu.system.worker_base import WorkerControlPanel

    panel = WorkerControlPanel(experiment, trial, timeout=2.0)
    try:
        hbs = panel.heartbeats()
        if hbs:
            print("heartbeats (liveness leases):")
            w = max(len(k) for k in hbs)
            for worker, d in sorted(hbs.items()):
                age = d.get("age_secs")
                print(f"  {worker:<{w}}  "
                      f"age={'?' if age is None else f'{age:.1f}s'}  "
                      f"incarnation={d.get('incarnation', '?')}  "
                      f"pid={d.get('pid', '?')}")
        else:
            print("no heartbeats registered (run not supervised, or "
                  "fault_tolerance.keepalive_ttl_secs=0)")
        workers = panel.list_workers()
        print(f"control endpoints: {', '.join(workers) or 'none'}")
    finally:
        panel.close()
    try:
        d = _json.loads(name_resolve.get(
            names.drain_status(experiment, trial)
        ))
        print(f"drain phase: {d.get('phase')} "
              f"(at {time.strftime('%H:%M:%S', time.localtime(d.get('ts', 0)))})")
    except Exception:  # noqa: BLE001 — no drain ever requested
        print("drain phase: none")
    try:
        plan = _json.loads(name_resolve.get(
            names.autoscale_plan(experiment, trial)
        ))
        print(f"autoscale plan: target={plan.get('target')} "
              f"dynamic={plan.get('dynamic')} "
              f"overloaded={plan.get('overloaded')}")
    except Exception:  # noqa: BLE001 — autoscale disabled / no plan yet
        print("autoscale plan: none (autoscale disabled?)")
    # Per-server fleet map from the manager (jax-free JSON endpoint):
    # who is routable / cordoned / deprioritized, and what is draining.
    try:
        mgr = name_resolve.get(names.gen_server_manager(experiment, trial))
        with urllib.request.urlopen(f"{mgr.rstrip('/')}/metrics.json",
                                    timeout=10) as r:
            m = _json.loads(r.read().decode())
        asc = m.get("autoscale") or {}
        print(f"fleet: {m.get('healthy_servers')}/{m.get('known_servers')} "
              f"routable, {asc.get('cordoned', 0)} cordoned"
              + (f", target {asc.get('target_size')}"
                 if asc.get("enabled") else ""))
        for u, st in sorted((m.get("fleet") or {}).items()):
            state = ("cordoned" if st.get("cordoned")
                     else "routable" if st.get("routable")
                     else "evicted")
            extra = []
            if st.get("server_id"):
                extra.append(st["server_id"])
            if st.get("deprioritized"):
                extra.append("deprioritized(straggler)")
            if st.get("cordoned"):
                extra.append(f"reason={st.get('cordon_reason', '?')}")
                extra.append(f"draining={st.get('draining', 0)}")
            if st.get("evicted_reason") and state == "evicted":
                extra.append(st["evicted_reason"])
            print(f"  {u}  {state}" + ("  [" + ", ".join(extra) + "]"
                                       if extra else ""))
    except Exception as e:  # noqa: BLE001 — manager down
        print(f"fleet map: manager unreachable ({e})")
    try:
        url = name_resolve.get(names.telemetry_http(experiment, trial))
        with urllib.request.urlopen(f"{url.rstrip('/')}/metrics",
                                    timeout=10) as r:
            body = r.read().decode()
        lines = [ln for ln in body.splitlines()
                 if "areal_supervisor_" in ln and not ln.startswith("#")]
        if lines:
            print("supervisor metrics (merged scrape):")
            for ln in lines:
                print(f"  {ln}")
        else:
            print("supervisor metrics: none yet (no restarts)")
    except Exception:  # noqa: BLE001 — telemetry off / no http port
        print("supervisor metrics: merged scrape unavailable "
              "(telemetry disabled or no http_port)")


def _manager_url(experiment: str, trial: str) -> str:
    from areal_tpu.base import name_resolve, names

    try:
        return name_resolve.get(names.gen_server_manager(experiment, trial))
    except Exception as e:  # noqa: BLE001 — run down / wrong root
        sys.exit(f"cannot resolve the gserver manager for "
                 f"{experiment}/{trial}: {e}\n(is the run up, and "
                 f"AREAL_NAME_RESOLVE_ROOT pointing at its store?)")


def cordon(experiment: str, trial: str, server: str,
           reason: str = "operator request", un: bool = False) -> None:
    """Cordon (or uncordon) one generation server of a live run — the
    operator's preemption-notice hook (docs/fault_tolerance.md
    §Autoscaling). ``server`` is a server_id (e.g. gen1, dyn2) or a full
    http url; the cordoned server stops receiving leases, its inflight
    rollouts drain, and the autoscale loop reaps a drained dynamic
    server via a WorkerControl-commanded exit."""
    import json as _json
    import urllib.error
    import urllib.request

    url = _manager_url(experiment, trial)
    key = "url" if server.startswith("http") else "server_id"
    body = _json.dumps(
        {key: server, "reason": reason}
    ).encode()
    verb = "uncordon" if un else "cordon"
    req = urllib.request.Request(
        f"{url.rstrip('/')}/{verb}", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            d = _json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        sys.exit(f"{verb} {server}: manager said {e.code} "
                 f"({e.read().decode()[:200]})")
    print(_json.dumps(d, indent=2, sort_keys=True))
    if not un and d.get("ok"):
        print(f"{d.get('url')} cordoned; {d.get('draining', 0)} leases "
              f"draining — watch `fleet-status {experiment} {trial}`")


def drain(experiment: str, trial: str) -> None:
    """Trigger the graceful-drain sequence against a live run — the same
    path the launcher's SIGTERM handler drives (docs/operations.md)."""
    import json as _json

    from areal_tpu.system.supervisor import drain_experiment

    report = drain_experiment(experiment, trial)
    print(_json.dumps(report, indent=2, sort_keys=True))
    ck = report.get("checkpoint") or {}
    res = ck.get("result") or {}
    if res.get("saved"):
        print(f"recover checkpoint: {res.get('dir')} "
              f"(step {res.get('step')})")
    else:
        print("WARNING: no recover checkpoint was written "
              f"({ck.get('error') or res.get('reason') or 'master absent'})")


def alerts(exp_or_path: str, trial: str = "", severity: str = "",
           rule: str = "") -> None:
    """Training-health alert view (jax-free): either tail/filter a run's
    ``alerts.jsonl`` (post-mortem), or pull the live alert counters off
    the merged Prometheus scrape (docs/observability.md §Alerting)."""
    import json as _json
    import os as _os
    import urllib.request

    # File mode only for an actual alert-stream file: a directory named
    # after the experiment (launchers create <exp>/ log dirs in cwd)
    # must still route to the live merged scrape.
    if _os.path.isfile(exp_or_path) or exp_or_path.endswith(".jsonl"):
        # file mode: positional args shift left (no trial)
        severity, rule = trial, severity
        try:
            with open(exp_or_path) as f:
                recs = [_json.loads(ln) for ln in f if ln.strip()]
        except OSError as e:
            sys.exit(f"alerts: cannot read {exp_or_path}: {e}")
        shown = 0
        for r in recs:
            if severity and r.get("severity") != severity:
                continue
            if rule and r.get("rule") != rule:
                continue
            shown += 1
            ts = time.strftime("%H:%M:%S", time.localtime(r.get("ts", 0)))
            extra = ""
            if r.get("event") == "firing":
                extra = (f"  {r.get('metric')}={r.get('value')}"
                         + (f"  evidence={r['evidence_dir']}"
                            if r.get("evidence_dir") else ""))
            print(f"{ts}  {r.get('severity', '?'):<8} "
                  f"{r.get('event', '?'):<9} {r.get('rule', '?')}{extra}")
        print(f"({shown}/{len(recs)} records"
              + (f", severity={severity}" if severity else "")
              + (f", rule={rule}" if rule else "") + ")")
        return
    from areal_tpu.base import name_resolve, names

    try:
        url = name_resolve.get(names.telemetry_http(exp_or_path, trial))
    except Exception:  # noqa: BLE001 — telemetry off / no http port
        sys.exit(
            f"alerts: no merged telemetry endpoint for "
            f"{exp_or_path}/{trial}.\nEither the run is down or telemetry "
            f"has no http_port — read the recorded stream instead: "
            f"alerts <log-dir>/alerts.jsonl"
        )
    with urllib.request.urlopen(f"{url.rstrip('/')}/metrics",
                                timeout=10) as r:
        body = r.read().decode()
    lines = []
    for ln in body.splitlines():
        if not (ln.startswith("areal_alerts_total")
                or ln.startswith("areal_alert_active")
                or ln.startswith("areal_sentinel_")):
            continue
        # Only alerts_total carries a severity label — filtering the
        # active/sentinel lines on it would hide every live alert.
        if severity and ln.startswith("areal_alerts_total") \
                and f'severity="{severity}"' not in ln:
            continue
        if rule and f'rule="{rule}"' not in ln:
            continue
        lines.append(ln)
    if not lines:
        print("no sentinel metrics on the scrape "
              "(sentinel disabled, or no rule matched the filters)")
    for ln in lines:
        print(f"  {ln}")
    # active operator silences ride along — an alert that "never fires"
    # is often just silenced
    try:
        now = time.time()
        for key in name_resolve.find_subtree(
                names.sentinel_silence_root(exp_or_path, trial)):
            d = _json.loads(name_resolve.get(key))
            if float(d.get("until", 0)) > now:
                print(f"  silenced: {d.get('rule')} for another "
                      f"{float(d['until']) - now:.0f}s")
    except Exception:  # noqa: BLE001 — no silences registered
        pass


def silence(experiment: str, trial: str, rule: str, duration: str) -> None:
    """Silence one sentinel rule for a duration — it keeps evaluating
    (state machine advances) but fires are suppressed until expiry."""
    import json as _json

    from areal_tpu.base import name_resolve, names
    from areal_tpu.system.sentinel import parse_duration

    try:
        secs = parse_duration(duration)
    except ValueError as e:
        sys.exit(f"silence: {e}")
    until = time.time() + secs
    name_resolve.add(
        names.sentinel_silence(experiment, trial, rule),
        _json.dumps({"rule": rule, "until": until,
                     "ts": time.time(), "duration_secs": secs}),
        replace=True, delete_on_exit=False,
    )
    print(f"silenced sentinel rule {rule!r} for {secs:g}s "
          f"(until {time.strftime('%H:%M:%S', time.localtime(until))}); "
          f"fires are suppressed and counted as "
          f"areal_sentinel_silenced_total")


def goodput_view(exp_or_url: str, trial: str = "",
                 window_secs: float = 5.0) -> None:
    """Live goodput ledger view (jax-free): per-worker time-in-state
    fractions over a SHORT LIVE WINDOW — two scrapes of
    ``areal_goodput_secs_total`` ``window_secs`` apart, diffed — plus
    the fleet-goodput and live MFU gauges, off the merged scrape (or
    one worker's /metrics when given a url). Windowed on purpose: a
    since-start cumulative split dilutes a live stall by the whole
    run's history (the same reason areal_fleet_goodput is windowed —
    docs/observability.md §Goodput); workers whose counters did not
    move inside the window fall back to their cumulative split, marked
    ``(cum)``."""
    import re as _re
    import urllib.error
    import urllib.request

    if exp_or_url.startswith("http"):
        url = exp_or_url.rstrip("/")
    else:
        from areal_tpu.base import name_resolve, names

        try:
            url = name_resolve.get(names.telemetry_http(exp_or_url, trial))
        except Exception:  # noqa: BLE001 — telemetry off / no http port
            sys.exit(
                f"goodput: no merged telemetry endpoint for "
                f"{exp_or_url}/{trial}.\nEither the run is down or "
                f"telemetry has no http_port — relaunch with "
                f"telemetry.enabled=true goodput.enabled=true "
                f"telemetry.http_port=<port>, or probe one worker: "
                f"goodput <url>."
            )
    if "/metrics" not in url:
        url = url.rstrip("/") + "/metrics"
    lab_re = _re.compile(r'(\w+)="([^"]*)"')

    def fetch():
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                body = r.read().decode()
        except (urllib.error.URLError, OSError) as e:
            sys.exit(f"goodput: cannot reach {url}: {e}")
        per_worker: dict = {}
        overlap: dict = {}
        extras = []
        for ln in body.splitlines():
            counters = ln.startswith("areal_goodput_secs_total{")
            is_overlap = ln.startswith("areal_goodput_overlap_secs_total{")
            if counters or is_overlap:
                name, _, val = ln.rpartition(" ")
                labels = dict(lab_re.findall(name))
                worker = (
                    f"{labels.get('worker_kind', labels.get('server_id', '?'))}"
                    f":{labels.get('worker_index', '')}"
                ).rstrip(":")
                state = labels.get("state", "?")
                tgt = overlap if is_overlap else per_worker
                tgt.setdefault(worker, {})[state] = \
                    tgt.get(worker, {}).get(state, 0.0) + float(val)
            elif (ln.startswith("areal_fleet_goodput")
                  or ln.startswith("areal_train_mfu")
                  or ln.startswith("areal_train_achieved_tflops")
                  or ln.startswith("areal_genserver_decode_mfu")
                  or ln.startswith("areal_genserver_decode_tflops")
                  or ln.startswith("areal_genserver_prefill_tflops")):
                extras.append(ln)
        return per_worker, overlap, extras

    first, _, _ = fetch()
    if not first:
        print("no goodput counters on the scrape "
              "(goodput.enabled=false, or no ledger export yet)")
        return
    time.sleep(max(window_secs, 0.1))
    cum, overlap, extras = fetch()
    if not cum:
        # The aggregator restarted inside the sampling window and the
        # fresh one has no state yet — same friendly exit as fetch one.
        print("no goodput counters on the second scrape "
              "(aggregator restarted mid-window? retry)")
        return
    states = ("compute", "comm", "data_wait", "idle")
    w = max(len(k) for k in cum)
    print(f"  last {window_secs:g}s window "
          f"((cum) = counters idle in the window, since-start split):")
    print(f"  {'worker':<{w}}  {'total_s':>9}  "
          + "  ".join(f"{s:>9}" for s in states))
    for worker, totals in sorted(cum.items()):
        base = first.get(worker, {})
        delta = {s: max(v - base.get(s, 0.0), 0.0)
                 for s, v in totals.items()}
        row, mark = (delta, "") if sum(delta.values()) > 0 \
            else (totals, " (cum)")
        total = sum(row.values())
        fracs = "  ".join(
            f"{row.get(s, 0.0) / total:>8.1%}" if total > 0
            else f"{'-':>9}" for s in states
        )
        print(f"  {worker:<{w}}  {sum(totals.values()):>9.1f}  "
              f"{fracs}{mark}")
    print("  (rollout rows are task-seconds under concurrency, not a "
          "wall partition — docs/observability.md §Goodput)")
    if overlap:
        print("overlap (work racing the owner's partition, e.g. weight "
              "updates during decode — not in the fractions above):")
        for worker, totals in sorted(overlap.items()):
            split = "  ".join(f"{s}={v:.1f}s"
                              for s, v in sorted(totals.items()))
            print(f"  {worker:<{w}}  {split}")
    if extras:
        print("gauges:")
        for ln in sorted(extras):
            print(f"  {ln}")


def profile_trigger(experiment: str, trial: str, out_dir: str,
                    secs: float = 5.0) -> None:
    from areal_tpu.base import telemetry

    telemetry.request_profiler_capture(experiment, trial, out_dir, secs)
    print(f"profiler trigger set for {experiment}/{trial}: "
          f"{secs}s -> {out_dir} (trainer picks it up within ~1s; check "
          f"with `profile-status {experiment} {trial}`)")


def profile_status(experiment: str, trial: str) -> None:
    from areal_tpu.base import telemetry

    st = telemetry.read_profiler_status(experiment, trial)
    print(st if st is not None else "no capture recorded")


def packfill(caps=None) -> None:
    """Host-only packing-fill probe (ISSUE 8 / ROADMAP item 1): what fill
    the micro-batch packer achieves on the bench trajectory distribution
    at each token cap — the padding factor the reported MFU divides by.
    No TPU and no jax needed; safe to run anywhere."""
    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.backend import microbatch as mbu
    from areal_tpu.base.testing import bench_trajectory_sample

    caps = [int(c) for c in caps] if caps else [2048, 4096, 8192]
    n_seq = 32
    batch, seqlens = bench_trajectory_sample(0, n_seq)
    print(f"[packfill] {n_seq} bench-shaped seqs, "
          f"{int(seqlens.sum())} tokens, lens "
          f"{int(seqlens.min())}..{int(seqlens.max())}")
    for cap in caps:
        spec = MicroBatchSpec(max_tokens_per_mb=cap)
        for label, fb in (("fine(128)", None), ("coarse(512)", 512)):
            mbs = mbu.split_into_microbatches(
                batch, spec, length_bucket=512, rows_bucket=4,
                seqs_bucket=16, fill_bucket=fb,
            )
            R, L = mbs[0].layout.shape
            print(f"[packfill] cap={cap:<6} {label:<12} "
                  f"n_mbs={len(mbs):<3} R={R:<2} L={L:<5} "
                  f"fill={mbu.pack_fill(mbs):.4f}")


def _blocksweep_candidates(T: int, S: int):
    """All (block_q, block_kv) the kernel accepts at this geometry:
    128-multiples dividing the respective dim, bounded to keep q/kv tiles
    within a sane VMEM envelope. Pure + CPU-testable."""
    from areal_tpu.ops.pallas.flash_attention import LANE

    def divs(n):
        return [b for b in range(LANE, min(n, 2048) + 1, LANE) if n % b == 0]

    return [(bq, bkv) for bq in divs(T) for bkv in divs(S)]


def blocksweep(T: int = 1792, S: int = 1792, out_path: str = None,
               Hq: int = 14, Hkv: int = 2, D: int = 64, B: int = 2) -> None:
    """Sweep flash-attention block sizes at a (T, S) geometry — default
    the bench grid after the r08 fill sweep (L=1792, R=2, Qwen2.5-0.5B
    heads) — timing fwd+bwd per candidate, and record the winner as a
    geometry-keyed JSON table consumable via AREAL_FLASH_BLOCK_TABLE."""
    import json as _json
    import os as _os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.ops.pallas import flash_attention as fa

    if jax.default_backend() != "tpu":
        sys.exit(
            "blocksweep: needs a real TPU — the Pallas kernel has no "
            "working interpreter on this jax version "
            "(ops/pallas/flash_attention.interpret_mode). Run on the "
            "bench chip; results land in the JSON table for "
            "AREAL_FLASH_BLOCK_TABLE."
        )
    # A leftover env pin/table would override every per-candidate
    # set_block_sizes below — the sweep would time one config N times and
    # record a meaningless winner. Clear both for the sweep's lifetime.
    for var in ("AREAL_FLASH_BLOCKS", "AREAL_FLASH_BLOCK_TABLE"):
        if _os.environ.pop(var, None) is not None:
            print(f"[blocksweep] ignoring {var} for the sweep", flush=True)
    fa.clear_block_table()
    cands = _blocksweep_candidates(T, S)
    if not cands:
        sys.exit(f"blocksweep: no 128-multiple blocks divide T={T} S={S}")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, Hq, D).astype(np.float32) * 0.3,
                    jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32) * 0.3,
                    jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32) * 0.3,
                    jnp.bfloat16)
    # bench-like packing: two docs per row
    seg = np.ones((B, T), np.int32)
    seg[:, T // 2:] = 2
    pos = np.concatenate([np.arange(T // 2), np.arange(T - T // 2)])
    pos = np.tile(pos, (B, 1)).astype(np.int32)
    seg, pos = jnp.asarray(seg), jnp.asarray(pos)

    def run(bq, bkv):
        fa.set_block_sizes(T, S, bq, bkv)

        def loss(q):
            o = fa.flash_attention(q, k, v, seg, seg, q_positions=pos,
                                   kv_positions=pos)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss))
        g(q).block_until_ready()  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            out = g(q)
        out.block_until_ready()
        return (time.perf_counter() - t0) / 10

    results = []
    for bq, bkv in cands:
        try:
            dt = run(bq, bkv)
        except Exception as e:  # noqa: BLE001 — kernel may reject a combo
            print(f"[blocksweep] bq={bq:<5} bkv={bkv:<5} FAILED: "
                  f"{type(e).__name__}", flush=True)
            continue
        results.append((dt, bq, bkv))
        print(f"[blocksweep] bq={bq:<5} bkv={bkv:<5} {dt * 1e3:8.2f} ms",
              flush=True)
    fa.clear_block_table()
    if not results:
        sys.exit("blocksweep: every candidate failed")
    results.sort()
    dt, bq, bkv = results[0]
    heur = fa.pick_block_sizes(T, S)
    print(f"[blocksweep] winner: bq={bq} bkv={bkv} ({dt * 1e3:.2f} ms; "
          f"heuristic default was {heur})")
    out_path = out_path or _os.path.join("profiles", "flash_blocks.json")
    _os.makedirs(_os.path.dirname(out_path) or ".", exist_ok=True)
    table = {}
    if _os.path.exists(out_path):
        try:
            with open(out_path) as f:
                table = _json.load(f)
        except (OSError, ValueError):
            pass
    table[f"{T},{S}"] = [bq, bkv]
    with open(out_path, "w") as f:
        _json.dump(table, f, indent=1, sort_keys=True)
    print(f"[blocksweep] recorded to {out_path} "
          f"(use: AREAL_FLASH_BLOCK_TABLE={out_path})")


def reshard_bench(src_spec: str = "f2t2", dst_spec: str = "d4",
                  group_mb: int = 64, n_layers: int = 8,
                  dim: int = 1024) -> None:
    """Time the mesh→mesh on-device reshard (parallel/reshard.py) between
    two ParallelSpecs on whatever devices this process has (CPU test
    meshes under JAX_PLATFORMS=cpu, real chips otherwise): per
    transfer-group dispatch→barrier latency and MB/s, plus the end-to-end
    publish figure the ``device`` weight-sync transport would pay."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.parallel import mesh as pm
    from areal_tpu.parallel import reshard as rsh
    from areal_tpu.parallel import sharding as psh

    src = pm.ParallelSpec.parse(src_spec)
    dst = pm.ParallelSpec.parse(dst_spec)
    n_dev = len(jax.devices())
    for label, spec in (("src", src), ("dst", dst)):
        if spec.world_size > n_dev:
            sys.exit(f"reshard-bench: {label} spec '{spec}' needs "
                     f"{spec.world_size} devices, have {n_dev} "
                     f"(JAX_PLATFORMS=cpu + "
                     f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                     f"for a host-mesh dry run)")
    src_mesh, dst_mesh = pm.make_mesh(src), pm.make_mesh(dst)
    # Transformer-shaped synthetic tree: a stacked layer dict sharded the
    # way training shards it, so the plan exercises the real per-leaf
    # PartitionSpecs rather than a flat blob.
    tree = {
        "layers": {
            "wq": jnp.zeros((n_layers, dim, dim), jnp.bfloat16),
            "wo": jnp.zeros((n_layers, dim, dim), jnp.bfloat16),
            "w_up": jnp.zeros((n_layers, dim, 4 * dim), jnp.bfloat16),
            "w_down": jnp.zeros((n_layers, 4 * dim, dim), jnp.bfloat16),
        },
        "embedding": jnp.zeros((4096, dim), jnp.bfloat16),
    }
    specs = jax.tree.map(lambda _: None, tree)
    specs["layers"] = {
        "wq": psh.P(None, "fsdp", "tp"), "wo": psh.P(None, "tp", "fsdp"),
        "w_up": psh.P(None, "fsdp", "tp"),
        "w_down": psh.P(None, "tp", "fsdp"),
    }
    specs["embedding"] = psh.P("fsdp", "tp")
    src_sh = jax.tree.map(
        lambda s: jax.sharding.NamedSharding(src_mesh, s or psh.P()), specs,
        is_leaf=lambda x: x is None or isinstance(x, psh.P),
    )
    dst_sh = jax.tree.map(
        lambda s: jax.sharding.NamedSharding(dst_mesh, s or psh.P()), specs,
        is_leaf=lambda x: x is None or isinstance(x, psh.P),
    )
    tree = jax.tree.map(jax.device_put, tree, src_sh)
    jax.block_until_ready(tree)
    flat_src = rsh._flatten(tree)
    flat_dst = rsh._flatten(dst_sh)
    plan = rsh.plan_reshard(flat_src, flat_dst,
                            group_bytes=int(group_mb) << 20)
    print(f"[reshard-bench] {src} -> {dst} on {n_dev} "
          f"{jax.devices()[0].platform} devices: "
          f"{plan.total_bytes >> 20} MB total, plan {plan.describe()}")
    t_all = time.perf_counter()
    for gi, group in enumerate(plan.groups):
        g_bytes = sum(rsh._leaf_nbytes(flat_src[n]) for n in group)
        t0 = time.perf_counter()
        rsh._move_group(group, flat_src, flat_dst)
        dt = time.perf_counter() - t0
        print(f"[reshard-bench] group {gi}: {len(group)} leaves, "
              f"{g_bytes >> 20:>5} MB, {dt * 1e3:8.2f} ms, "
              f"{g_bytes / dt / 2 ** 20:10.1f} MB/s")
    dt_all = time.perf_counter() - t_all
    t0 = time.perf_counter()
    _, plan2 = rsh.reshard_pytree(tree, dst_sh, group_mb=int(group_mb))
    dt_pub = time.perf_counter() - t0
    mbs = plan.moved_bytes / 2 ** 20
    print(f"[reshard-bench] grouped total: {dt_all * 1e3:.2f} ms "
          f"({mbs / max(dt_all, 1e-9):.1f} MB/s moved); "
          f"end-to-end reshard_pytree: {dt_pub * 1e3:.2f} ms "
          f"(zero-copy leaves: {len(plan2.identical)})")


def ring_bench(sp_list=None, seq_list=None, reps: int = 3) -> None:
    """Sweep ring attention v2 (parallel/ring.py) over (sp, seq_len) on
    whatever devices this process has (host meshes under JAX_PLATFORMS=cpu
    + XLA_FLAGS=--xla_force_host_platform_device_count=N, real chips
    otherwise): fwd+bwd step time for the zig-zag schedule vs the
    contiguous v1 oracle, plus the structural causal-skip ratio from the
    trace-time area counters ((n+1)/2n at sp=n)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.parallel import mesh as pm
    from areal_tpu.parallel import ring as ring_mod

    n_dev = len(jax.devices())
    sp_list = sp_list or [s for s in (1, 2, 4, 8) if s <= n_dev]
    seq_list = seq_list or [1024, 2048, 4096]
    Hq, Hkv, Dh = 4, 2, 64
    print(f"[ring-bench] {n_dev} {jax.devices()[0].platform} devices; "
          f"B=1 Hq={Hq} Hkv={Hkv} Dh={Dh}; fwd+bwd attention step, "
          f"zigzag (active) vs naive (v1 oracle)")
    print(f"[ring-bench] {'sp':>3} {'seq_len':>8} {'zigzag_ms':>10} "
          f"{'naive_ms':>9} {'speedup':>8} {'skip_ratio':>10}")
    rng = np.random.RandomState(0)
    for sp in sp_list:
        mesh = pm.make_mesh(pm.ParallelSpec(sp=sp))
        for T in seq_list:
            if T % max(2 * sp, 1):
                continue
            q = jnp.asarray(rng.randn(1, T, Hq, Dh).astype(np.float32) * .1)
            k = jnp.asarray(rng.randn(1, T, Hkv, Dh).astype(np.float32) * .1)
            v = jnp.asarray(rng.randn(1, T, Hkv, Dh).astype(np.float32) * .1)
            seg = jnp.ones((1, T), jnp.int32)
            res = {}
            for sched in ("zigzag", "naive"):
                def loss(q, k, v, sched=sched):
                    o = ring_mod.ring_attention(q, k, v, seg, mesh,
                                                schedule=sched)
                    return jnp.sum(o * o)

                f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                ring_mod.reset_ring_counters()
                jax.block_until_ready(f(q, k, v))  # compile; fill counters
                ratio = ring_mod.ring_skip_ratio()
                t0 = time.perf_counter()
                for _ in range(reps):
                    g = f(q, k, v)
                jax.block_until_ready(g)
                res[sched] = ((time.perf_counter() - t0) / reps * 1e3, ratio)
            zz, nv = res["zigzag"], res["naive"]
            print(f"[ring-bench] {sp:>3} {T:>8} {zz[0]:>10.2f} "
                  f"{nv[0]:>9.2f} {nv[0] / max(zz[0], 1e-9):>7.2f}x "
                  f"{zz[1]:>10.3f}")


def moe_bench(e_list=None, k_list=None, cf_list=None, reps: int = 3,
              n_tokens: int = 4096, dim: int = 256) -> None:
    """Sweep the MoE dispatch paths (models/moe.py) over (num_experts,
    top_k, capacity_factor): one MoE layer's fwd+bwd step time for the
    sort-based grouped-GEMM path (the default) vs the one-hot einsum
    oracle (AREAL_MOE_DISPATCH=einsum), plus the fraction of routed
    assignments dropped at the capacity boundary. The einsum oracle pays
    O(tokens x E x capacity) ~ O(k*cf*tokens^2) one-hot dispatch/combine
    contractions plus dense [E, C] buffers; grouped replaces them with a
    sort + ragged GEMMs. Caveat: ragged_dot's CPU lowering scales with E,
    so host-mesh sweeps understate the grouped win at large E — the TPU
    kernel does not."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.models import config as mcfg
    from areal_tpu.models import moe as moe_mod

    e_list = e_list or [4, 8, 16, 32]
    k_list = k_list or [2]
    cf_list = cf_list or [1.0, 2.0]
    print(f"[moe-bench] {len(jax.devices())} "
          f"{jax.devices()[0].platform} devices; tokens={n_tokens} "
          f"dim={dim} ffn={dim * 2}; fwd+bwd one MoE layer, "
          f"grouped (active) vs einsum (oracle)")
    print(f"[moe-bench] {'E':>4} {'top_k':>5} {'cap_f':>5} "
          f"{'grouped_ms':>10} {'einsum_ms':>10} {'speedup':>8} "
          f"{'dropped':>8}")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, n_tokens // 8, dim)
                    .astype(np.float32) * 0.1)
    for E in e_list:
        for k in k_list:
            if k > E:
                continue
            for cf in cf_list:
                moe = mcfg.MoEConfig(num_experts=E, top_k=k,
                                     capacity_factor=cf,
                                     routed_intermediate_dim=dim * 2)
                tcfg = mcfg.tiny_config(hidden_dim=dim, n_q_heads=4,
                                        n_kv_heads=2, moe=_dc.asdict(moe))
                stacked = moe_mod.init_moe_params(
                    _dc.replace(tcfg, n_layers=1), jax.random.PRNGKey(0),
                    jnp.float32)
                lp = {name: w[0] for name, w in stacked.items()}
                res = {}
                for disp in ("grouped", "einsum"):
                    def loss(lp, x, disp=disp):
                        y, aux = moe_mod.moe_mlp(x, lp, moe, dispatch=disp)
                        return jnp.sum(y * y), aux["dropped_frac"]

                    f = jax.jit(jax.grad(loss, has_aux=True))
                    _, dropped = f(lp, x)
                    jax.block_until_ready(dropped)  # compile
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        g, dropped = f(lp, x)
                    jax.block_until_ready(g)
                    res[disp] = ((time.perf_counter() - t0) / reps * 1e3,
                                 float(dropped))
                gr, ei = res["grouped"], res["einsum"]
                print(f"[moe-bench] {E:>4} {k:>5} {cf:>5.2f} "
                      f"{gr[0]:>10.2f} {ei[0]:>10.2f} "
                      f"{ei[0] / max(gr[0], 1e-9):>7.2f}x {gr[1]:>8.3f}")


def _dispatch_fleet_commands(argv) -> bool:
    if not argv or argv[0] not in ("scrape", "decode-bench", "trace",
                                   "flight-dump", "packfill", "blocksweep",
                                   "profile-trigger", "profile-status",
                                   "fleet-status", "drain", "cordon",
                                   "uncordon", "reward-bench", "alerts",
                                   "silence", "goodput", "reshard-bench",
                                   "ring-bench", "moe-bench",
                                   "spool-status", "compile-status",
                                   "mem-status"):
        return False
    cmd = argv[0]
    try:
        if cmd == "fleet-status":
            fleet_status(argv[1], argv[2])
        elif cmd == "spool-status":
            spool_status(argv[1], argv[2])
        elif cmd == "compile-status":
            compile_status(argv[1], argv[2])
        elif cmd == "mem-status":
            mem_status(argv[1], argv[2])
        elif cmd == "cordon":
            cordon(argv[1], argv[2], argv[3],
                   " ".join(argv[4:]) or "operator request")
        elif cmd == "uncordon":
            cordon(argv[1], argv[2], argv[3], un=True)
        elif cmd == "drain":
            drain(argv[1], argv[2])
        elif cmd == "scrape":
            if len(argv) > 2:
                scrape_fleet(argv[1], argv[2])
            else:
                scrape(argv[1])
        elif cmd == "trace":
            print_trace(argv[1], argv[2])
        elif cmd == "flight-dump":
            flight_dump(argv[1], argv[2], argv[3])
        elif cmd == "decode-bench":
            decode_bench(
                argv[1],
                int(argv[2]) if len(argv) > 2 else 24,
                int(argv[3]) if len(argv) > 3 else 32,
            )
        elif cmd == "reward-bench":
            if argv[1].startswith("http"):
                reward_bench(argv[1],
                             n_tasks=int(argv[2]) if len(argv) > 2 else 32)
            else:
                reward_bench(argv[1], argv[2],
                             int(argv[3]) if len(argv) > 3 else 32)
        elif cmd == "packfill":
            packfill(argv[1:])
        elif cmd == "blocksweep":
            blocksweep(
                int(argv[1]) if len(argv) > 1 else 1792,
                int(argv[2]) if len(argv) > 2 else 1792,
                argv[3] if len(argv) > 3 else None,
            )
        elif cmd == "alerts":
            alerts(argv[1],
                   argv[2] if len(argv) > 2 else "",
                   argv[3] if len(argv) > 3 else "",
                   argv[4] if len(argv) > 4 else "")
        elif cmd == "silence":
            silence(argv[1], argv[2], argv[3], argv[4])
        elif cmd == "goodput":
            if argv[1].startswith("http"):
                goodput_view(argv[1], window_secs=(
                    float(argv[2]) if len(argv) > 2 else 5.0))
            else:
                goodput_view(argv[1], argv[2], window_secs=(
                    float(argv[3]) if len(argv) > 3 else 5.0))
        elif cmd == "reshard-bench":
            reshard_bench(
                argv[1] if len(argv) > 1 else "f2t2",
                argv[2] if len(argv) > 2 else "d4",
                int(argv[3]) if len(argv) > 3 else 64,
                int(argv[4]) if len(argv) > 4 else 8,
                int(argv[5]) if len(argv) > 5 else 1024,
            )
        elif cmd == "ring-bench":
            ring_bench(
                [int(x) for x in argv[1].split(",")] if len(argv) > 1
                else None,
                [int(x) for x in argv[2].split(",")] if len(argv) > 2
                else None,
            )
        elif cmd == "moe-bench":
            moe_bench(
                [int(x) for x in argv[1].split(",")] if len(argv) > 1
                else None,
                [int(x) for x in argv[2].split(",")] if len(argv) > 2
                else None,
                [float(x) for x in argv[3].split(",")] if len(argv) > 3
                else None,
            )
        elif cmd == "profile-trigger":
            profile_trigger(argv[1], argv[2], argv[3],
                            float(argv[4]) if len(argv) > 4 else 5.0)
        elif cmd == "profile-status":
            profile_status(argv[1], argv[2])
    except IndexError:
        print(f"missing operand for {cmd!r}\n\n{__doc__}", file=sys.stderr)
        sys.exit(1)
    return True


if _dispatch_fleet_commands(sys.argv[1:]):
    sys.exit(0)

import jax
import jax.numpy as jnp
import numpy as np


def build(remat=True, length_bucket=512, rows_bucket=4, seqs_bucket=16,
          attn_impl="auto"):
    from areal_tpu.algorithms.ppo import PPOActorInterface, PPOHyperparameters
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import FinetuneSpec, Model
    from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        n_layers=24, hidden_dim=896, n_q_heads=14, n_kv_heads=2, head_dim=64,
        intermediate_dim=4864, vocab_size=151936, rotary_base=1e6,
        tie_word_embeddings=True, use_attention_bias=True, dtype="bfloat16",
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    model = Model("actor", (cfg, params), tokenizer=None)
    backend = JaxTrainBackend(
        optimizer=OptimizerConfig(lr=1e-5, lr_scheduler_type="constant",
                                  warmup_steps_proportion=0.0),
        compute_dtype="bfloat16", length_bucket=length_bucket,
        rows_bucket=rows_bucket, seqs_bucket=seqs_bucket, remat=remat,
        attn_impl=attn_impl,
    )
    model = backend.initialize(model, FinetuneSpec(1, 512, 64))
    hp = PPOHyperparameters(ppo_n_minibatches=1, adv_norm=True,
                            kl_ctl=0.0, disable_value=True)
    iface = PPOActorInterface(hp)

    rng = np.random.RandomState(0)
    n_seq = 32
    plens = rng.randint(200, 257, n_seq)
    glens = rng.randint(512, 769, n_seq)
    seqlens = (plens + glens).astype(int)
    total = int(seqlens.sum())
    toks = rng.randint(2, cfg.vocab_size, total).astype(np.int32)
    pmask, lps = [], []
    for p, g in zip(plens, glens):
        pmask.append(np.concatenate([np.ones(p, np.int32), np.zeros(g, np.int32)]))
        lps.append(np.concatenate([np.zeros(p, np.float32),
                                   -rng.rand(g).astype(np.float32)]))
    batch = SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seq)],
        data={
            "packed_input_ids": toks,
            "prompt_mask": np.concatenate(pmask),
            "packed_logprobs": np.concatenate(lps),
            "rewards": rng.rand(n_seq).astype(np.float32),
            "seq_no_eos_mask": np.zeros(n_seq, np.float32),
        },
        seqlens=seqlens.tolist(),
    )
    return cfg, model, iface, batch, total


PEAK = 197e12  # v5e bf16


def report(tag, total, dt, steps, cfg_nparams, remat):
    tps = steps * total / dt
    mfu = 6.0 * cfg_nparams * total * steps / dt / PEAK
    print(f"[{tag}] {tps:,.0f} tok/s  step={dt/steps*1e3:.0f}ms  "
          f"MFU(6N)={mfu:.3f}", flush=True)


def main():
    probes = sys.argv[1:] or ["e2e", "grad"]
    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.backend import microbatch as mbu
    from areal_tpu.models import transformer

    spec = MicroBatchSpec(max_tokens_per_mb=4096)

    if "e2e" in probes or "grad" in probes or "trace" in probes:
        cfg, model, iface, batch, total = build()
        nparams = transformer.param_count(cfg)
        eng = model.module
        iface.train_step(model, batch, spec)  # compile
        jax.block_until_ready(eng.params)

        if "e2e" in probes:
            t0 = time.perf_counter()
            for _ in range(3):
                iface.train_step(model, batch, spec)
            jax.block_until_ready(eng.params)
            report("e2e remat=T mb=4096", total, time.perf_counter() - t0, 3,
                   nparams, True)

        if "grad" in probes or "trace" in probes:
            # Device-only: one microbatch's grad step, timed in a tight loop
            # with a single final sync → pure kernel throughput.
            from areal_tpu.algorithms import ppo as ppomod
            extra = ppomod.compute_advantages_and_returns(batch, iface.hp, 0.0)
            extra.pop("_mean_kl")
            b2 = ppomod.attach_keys(batch, extra)
            ppomod.normalize_advantages(b2, iface.hp)
            mbs = mbu.split_into_microbatches(
                b2, spec, length_bucket=512, rows_bucket=4, seqs_bucket=16)
            gfn = eng._get_grad_fn(iface._loss_fn, with_carry=False)
            dbs = [eng._device_batch(mb) for mb in mbs]
            ntok = sum(mb.n_tokens for mb in mbs)
            ncells = sum(int(np.prod(mb.grids["tokens"].shape)) for mb in mbs)
            print(f"[pack] {len(mbs)} mbs, fill={ntok/ncells:.2f} "
                  f"({ntok} tok / {ncells} cells)", flush=True)
            denom = jnp.asarray(1000.0, jnp.float32)
            one = jnp.asarray(1.0, jnp.float32)
            for db in dbs:
                gfn(eng.params, db, denom, one, one)  # compile each shape
            jax.block_until_ready(eng.params)

            if "grad" in probes:
                t0 = time.perf_counter()
                outs = None
                for _ in range(3):
                    for db in dbs:
                        outs = gfn(eng.params, db, denom, one, one)
                jax.block_until_ready(outs)
                report("grad-only (fwd+bwd, no opt)", ntok,
                       time.perf_counter() - t0, 3, nparams, True)

            if "trace" in probes:
                import os
                os.makedirs("profiles", exist_ok=True)
                with jax.profiler.trace("profiles/bench_step"):
                    iface.train_step(model, batch, spec)
                    jax.block_until_ready(eng.params)
                print("[trace] saved to profiles/bench_step", flush=True)

    if "phases" in probes:
        cfg, model, iface, batch, total = build()
        eng = model.module
        iface.train_step(model, batch, spec)
        jax.block_until_ready(eng.params)
        from areal_tpu.algorithms import ppo as ppomod
        t = {}
        for _ in range(3):
            t0 = time.perf_counter()
            extra = ppomod.compute_advantages_and_returns(batch, iface.hp, 0.0)
            extra.pop("_mean_kl")
            b2 = ppomod.attach_keys(batch, extra)
            ppomod.normalize_advantages(b2, iface.hp)
            t["adv+norm"] = t.get("adv+norm", 0) + time.perf_counter() - t0
            t0 = time.perf_counter()
            mbs = mbu.split_into_microbatches(
                b2, spec, length_bucket=512, rows_bucket=4, seqs_bucket=16)
            t["split+pack"] = t.get("split+pack", 0) + time.perf_counter() - t0
            t0 = time.perf_counter()
            dbs = [eng._device_batch(mb) for mb in mbs]
            t["transfer"] = t.get("transfer", 0) + time.perf_counter() - t0
            gfn = eng._get_grad_fn(iface._loss_fn, with_carry=False)
            t0 = time.perf_counter()
            denom = jnp.asarray(1000.0, jnp.float32)
            one = jnp.asarray(1.0, jnp.float32)
            o = None
            ga = None
            for db in dbs:
                loss, stats, grads = gfn(eng.params, db, denom, one, one)
                ga = grads if ga is None else jax.tree.map(jnp.add, ga, grads)
                o = loss
            jax.block_until_ready(o)
            t["grad+acc"] = t.get("grad+acc", 0) + time.perf_counter() - t0
            t0 = time.perf_counter()
            jax.block_until_ready(ga)
            t["acc_drain"] = t.get("acc_drain", 0) + time.perf_counter() - t0
        for k, v in t.items():
            print(f"[phase] {k}: {v/3*1e3:.0f}ms", flush=True)

    if "remat" in probes:
        cfg, model, iface, batch, total = build(remat=False)
        nparams = transformer.param_count(cfg)
        iface.train_step(model, batch, spec)
        jax.block_until_ready(model.module.params)
        t0 = time.perf_counter()
        for _ in range(3):
            iface.train_step(model, batch, spec)
        jax.block_until_ready(model.module.params)
        report("e2e remat=F mb=4096", total, time.perf_counter() - t0, 3,
               nparams, False)

    if "mbsweep" in probes:
        for cap in (8192, 16384, 32768):
            cfg, model, iface, batch, total = build()
            nparams = transformer.param_count(cfg)
            sp = MicroBatchSpec(max_tokens_per_mb=cap)
            iface.train_step(model, batch, sp)
            jax.block_until_ready(model.module.params)
            t0 = time.perf_counter()
            for _ in range(3):
                iface.train_step(model, batch, sp)
            jax.block_until_ready(model.module.params)
            report(f"e2e remat=T mb={cap}", total, time.perf_counter() - t0,
                   3, nparams, True)


if __name__ == "__main__":
    main()
