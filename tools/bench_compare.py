"""Bench regression gate: diff BENCH_r*.json rounds field-by-field.

jax-free (stdlib only) — runnable in CI and on any operator laptop.

Usage:
  python tools/bench_compare.py BENCH_r04.json BENCH_r05.json [more...]
         [--tol field=frac ...] [--quiet]

Accepts two or more bench records, oldest first. Each file is either the
driver wrapper form (``{"parsed": {...}}`` — what the BENCH_r* files in
this repo are) or a bare bench.py JSON line. Prints the trajectory table
across every file, then gates the NEWEST round against its predecessor
with per-field relative tolerances:

  field                      direction  default tolerance
  value (tokens/s/chip)      higher     5%
  vs_baseline (MFU proxy)    higher     5%
  pack_fill                  higher     2%
  warmup_compile_s           lower      50% (persistent-cache-sensitive)
  hbm_peak_gb                lower      10% (n/a on CPU rounds)
  weight_sync_latency_s      lower      15%
  weight_sync_io_s           lower      25%
  weight_sync_transport_s    lower      25%
  weight_sync_device_s       lower      25%
  spool_append_ms            lower      50%
  spool_ack_ms               lower      50%
  ring_step_ms               lower      25%
  ring_naive_step_ms         lower      25%
  ring_skip_ratio            lower      0% (structural — must not grow)
  moe_step_ms                lower      25%
  moe_einsum_step_ms         lower      25%
  train_phases.*             lower      25%

Exit status 0 when every comparable field is within tolerance, 1 on any
regression — wire it after bench.py so a perf PR cannot land a silent
step backward on the BENCH_r* trajectory (docs/benchmarks.md).

Caveats the gate understands:
 - a field missing from either round (method additions like
   ``train_phases``, telemetry-off runs) is reported ``n/a`` and never
   gates;
 - when ``weight_sync_transport_method`` differs between the two gated
   rounds, every ``weight_sync_*`` field is skipped — the numbers
   measure different things across a method discontinuity
   (docs/benchmarks.md "Reading the numbers across rounds");
 - likewise when ``ring_schedule_method`` differs (ring schedule or sp
   width changed), every ``ring_*`` field is skipped;
 - likewise when ``moe_dispatch_method`` differs (grouped/einsum method
   or bench MoE shape changed), every ``moe_*`` field is skipped.

``--tol field=frac`` overrides a tolerance (e.g. ``--tol value=0.10``,
``--tol train_phases.fwd_bwd_s=0.5``); ``--tol default=frac`` sets the
fallback for fields without a specific entry.
"""

from __future__ import annotations

import json
import math
import sys
from typing import Dict, List, Optional, Tuple

# field -> (direction, default relative tolerance). "higher" means bigger
# is better (a drop beyond tolerance regresses); "lower" the opposite.
FIELDS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.05),
    "vs_baseline": ("higher", 0.05),
    "pack_fill": ("higher", 0.02),
    # Compile & HBM observatory (ISSUE 20): warmup trace wall clock is
    # persistent-cache-sensitive (warm cache collapses it), hence the wide
    # tolerance; the HBM peak only emits on backends with memory_stats()
    # (n/a on CPU rounds).
    "warmup_compile_s": ("lower", 0.50),
    "hbm_peak_gb": ("lower", 0.10),
    "weight_sync_latency_s": ("lower", 0.15),
    "weight_sync_io_s": ("lower", 0.25),
    "weight_sync_transport_s": ("lower", 0.25),
    "weight_sync_device_s": ("lower", 0.25),
    # Durable-spool per-record overhead (fsync-bound → wide tolerance on
    # shared CI disks; docs/fault_tolerance.md §Data durability).
    "spool_append_ms": ("lower", 0.50),
    "spool_ack_ms": ("lower", 0.50),
    # Long-context ring attention (ISSUE 18): one attention layer's
    # fwd+bwd step time at the bench's long-context shape, active schedule
    # vs the contiguous oracle, plus the structural causal-skip ratio
    # ((n+1)/2n at sp=n — lower means more skipped work). Skipped across
    # a ring_schedule_method discontinuity like weight_sync_*.
    "ring_step_ms": ("lower", 0.25),
    "ring_naive_step_ms": ("lower", 0.25),
    "ring_skip_ratio": ("lower", 0.0),
    # MoE dispatch (ISSUE 19): one MoE layer's fwd+bwd step time under
    # the sort-based grouped path (the default) and the one-hot einsum
    # oracle, at the bench's E=8 shape. Skipped across a
    # moe_dispatch_method discontinuity like weight_sync_* / ring_*.
    "moe_step_ms": ("lower", 0.25),
    "moe_einsum_step_ms": ("lower", 0.25),
}
TRAIN_PHASE_SPEC = ("lower", 0.25)
METHOD_FIELD = "weight_sync_transport_method"
RING_METHOD_FIELD = "ring_schedule_method"
MOE_METHOD_FIELD = "moe_dispatch_method"


def load_bench(path: str) -> Dict[str, object]:
    """One bench record, flattened: wrapper files yield their ``parsed``
    dict; ``train_phases`` sub-fields flatten to ``train_phases.<k>``."""
    with open(path) as f:
        d = json.load(f)
    if isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    flat: Dict[str, object] = {}
    for k, v in d.items():
        if k == "train_phases" and isinstance(v, dict):
            for pk, pv in v.items():
                flat[f"train_phases.{pk}"] = pv
        else:
            flat[k] = v
    return flat


def field_spec(field: str,
               tol_overrides: Dict[str, float]) -> Optional[Tuple[str, float]]:
    """(direction, tolerance) for a field, or None for ungated fields
    (unit, metric, method strings...)."""
    if field.startswith("train_phases."):
        direction, tol = TRAIN_PHASE_SPEC
    elif field in FIELDS:
        direction, tol = FIELDS[field]
    else:
        return None
    tol = tol_overrides.get(field, tol_overrides.get("default", tol))
    return direction, tol


def compare(prev: Dict[str, object], cur: Dict[str, object],
            tol_overrides: Optional[Dict[str, float]] = None
            ) -> List[Dict[str, object]]:
    """Gate ``cur`` against ``prev``; one row per gated field:
    {field, prev, cur, change, tol, status} with status in
    ok | regression | improved | n/a | skipped-method-change."""
    tol_overrides = tol_overrides or {}
    method_changed = (
        prev.get(METHOD_FIELD) is not None
        and cur.get(METHOD_FIELD) is not None
        and prev.get(METHOD_FIELD) != cur.get(METHOD_FIELD)
    )
    ring_method_changed = (
        prev.get(RING_METHOD_FIELD) is not None
        and cur.get(RING_METHOD_FIELD) is not None
        and prev.get(RING_METHOD_FIELD) != cur.get(RING_METHOD_FIELD)
    )
    moe_method_changed = (
        prev.get(MOE_METHOD_FIELD) is not None
        and cur.get(MOE_METHOD_FIELD) is not None
        and prev.get(MOE_METHOD_FIELD) != cur.get(MOE_METHOD_FIELD)
    )
    rows: List[Dict[str, object]] = []
    for field in sorted(set(prev) | set(cur)):
        spec = field_spec(field, tol_overrides)
        if spec is None:
            continue
        direction, tol = spec
        pv, cv = prev.get(field), cur.get(field)
        row: Dict[str, object] = {
            "field": field, "prev": pv, "cur": cv, "tol": tol,
            "direction": direction,
        }
        if not isinstance(pv, (int, float)) \
                or not isinstance(cv, (int, float)):
            row["status"] = "n/a"
            rows.append(row)
            continue
        if (method_changed and field.startswith("weight_sync")) or \
                (ring_method_changed and field.startswith("ring_")) or \
                (moe_method_changed and field.startswith("moe_")):
            row["status"] = "skipped-method-change"
            rows.append(row)
            continue
        base = abs(float(pv))
        if base > 0:
            change = (float(cv) - float(pv)) / base
        elif cv == pv:
            change = 0.0
        else:
            # A zero baseline has no relative scale: any move off 0 in
            # the bad direction must still gate (a lower-better field
            # going 0 -> 3s is a regression, not "0% change").
            change = math.inf if float(cv) > float(pv) else -math.inf
        row["change"] = change
        bad = (-change if direction == "higher" else change) > tol
        good = (change if direction == "higher" else -change) > 0
        row["status"] = ("regression" if bad
                         else "improved" if good else "ok")
        rows.append(row)
    return rows


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return "-" if v is None else str(v)


def print_trajectory(paths: List[str],
                     records: List[Dict[str, object]]) -> None:
    fields = sorted(
        {f for r in records for f in r
         if field_spec(f, {}) is not None}
    )
    name_w = max(len(f) for f in fields) if fields else 8
    col_w = max(max((len(p) for p in paths), default=10), 10)
    print("trajectory:")
    print("  " + " " * name_w + "  "
          + "  ".join(f"{p[-col_w:]:>{col_w}}" for p in paths))
    for f in fields:
        vals = "  ".join(f"{_fmt(r.get(f)):>{col_w}}" for r in records)
        print(f"  {f:<{name_w}}  {vals}")


def main(argv: List[str]) -> int:
    paths: List[str] = []
    tol_overrides: Dict[str, float] = {}
    quiet = False
    it = iter(argv)
    for a in it:
        if a == "--tol":
            try:
                k, _, v = next(it).partition("=")
                tol_overrides[k] = float(v)
            except (StopIteration, ValueError):
                print("bench_compare: --tol expects field=frac",
                      file=sys.stderr)
                return 2
        elif a == "--quiet":
            quiet = True
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            paths.append(a)
    if len(paths) < 2:
        print("bench_compare: need at least two BENCH_r*.json files "
              "(oldest first)\n", file=sys.stderr)
        print(__doc__, file=sys.stderr)
        return 2
    try:
        records = [load_bench(p) for p in paths]
    except (OSError, ValueError) as e:
        print(f"bench_compare: cannot read bench record: {e}",
              file=sys.stderr)
        return 2
    if not quiet:
        print_trajectory(paths, records)
    prev, cur = records[-2], records[-1]
    rows = compare(prev, cur, tol_overrides)
    regressions = [r for r in rows if r["status"] == "regression"]
    if not quiet:
        print(f"\ngate: {paths[-1]} vs {paths[-2]}")
        for r in rows:
            ch = r.get("change")
            ch_s = f"{ch:+.1%}" if isinstance(ch, float) else "  -  "
            mark = {"regression": "REGRESSION", "improved": "improved",
                    "ok": "ok"}.get(str(r["status"]), str(r["status"]))
            print(f"  {r['field']:<26} {_fmt(r['prev']):>10} -> "
                  f"{_fmt(r['cur']):>10}  {ch_s:>8}  "
                  f"(tol {r['tol']:.0%}, {r['direction']} better)  {mark}")
    if regressions:
        names = ", ".join(str(r["field"]) for r in regressions)
        print(f"\nbench_compare: REGRESSION in {len(regressions)} "
              f"field(s): {names}", file=sys.stderr)
        return 1
    print("\nbench_compare: no regression "
          f"({sum(1 for r in rows if r['status'] in ('ok', 'improved'))} "
          f"fields gated)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
