"""Benchmark: PPO trained-tokens/sec on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

AREAL_TELEMETRY=1 additionally enables the in-process telemetry registry
(base/telemetry.py, no pusher/sockets) and emits the trainer step-phase
breakdown — split_pack / fwd_bwd / optimizer seconds per timed step — as
a "train_phases" field, so the BENCH trajectory records where each step's
wall clock went instead of one opaque scalar. Telemetry stays OFF by
default: the headline number always measures the uninstrumented path
(enabling it adds a device sync between fwd-bwd and optimizer to make
the split honest).

Protocol (mirrors the reference's "effective trained tokens/sec",
benchmark/verl_v0_3_0_post1_76084d3/README.md:27-34): time full PPO actor
train steps — micro-batched forward+backward+optimizer over packed
variable-length trajectories — and divide the trajectory token count by
wall clock. Model: Qwen2.5-0.5B geometry (the largest BASELINE-family model
whose params+Adam+logits fit one 16G chip) in bf16. vs_baseline is
measured/analytic-roofline (MFU proxy) since the reference publishes no
absolute tokens/sec (BASELINE.md).
"""

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main():
    from areal_tpu.base import telemetry

    use_telemetry = os.environ.get("AREAL_TELEMETRY", "") not in ("", "0")
    if use_telemetry:
        # Local registry only — no aggregator exists here, so no pusher.
        telemetry.configure("bench", "b0", "trainer", 0, push=False)
    from areal_tpu.algorithms.ppo import (
        PPOActorInterface,
        PPOHyperparameters,
    )
    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.api.model import FinetuneSpec, Model
    from areal_tpu.backend.jax_train import JaxTrainBackend, OptimizerConfig
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig

    # Qwen2.5-0.5B geometry (24 layers, d=896, 14q/2kv heads, ffn 4864) —
    # the largest BASELINE-family model whose params+Adam+logits fit one
    # 16G-HBM chip; multi-chip configs scale via the same engine's mesh.
    cfg = TransformerConfig(
        n_layers=24, hidden_dim=896, n_q_heads=14, n_kv_heads=2, head_dim=64,
        intermediate_dim=4864, vocab_size=151936, rotary_base=1e6,
        tie_word_embeddings=True, use_attention_bias=True, dtype="bfloat16",
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    model = Model("actor", (cfg, params), tokenizer=None)
    del params  # the engine upcasts to f32 masters; don't pin the bf16 tree
    backend = JaxTrainBackend(
        # bf16 Adam moments: on this 16G chip the f32-master + f32-moment
        # layout doesn't leave room for the no-remat activation budget;
        # bf16 moments (math still f32 per step) restore it.
        optimizer=OptimizerConfig(lr=1e-5, lr_scheduler_type="constant",
                                  warmup_steps_proportion=0.0,
                                  mu_dtype="bfloat16", nu_dtype="bfloat16"),
        compute_dtype="bfloat16", length_bucket=512, rows_bucket=4,
        seqs_bucket=16,
        # r08 config: the cap-4096 + "dots"-remat + chunked-logprob combo
        # (ROADMAP item 1 retry). The r05 sweep measured cap-4096 dots ≈
        # cap-2048 no-remat within noise — but at the packer's old 0.84
        # fill; the 128-grain fill sweep (backend/microbatch.py) packs the
        # same trajectories at ≥0.96, so the 4096 cap now buys ~14% more
        # real tokens per padded FLOP. "dots" keeps matmul outputs and
        # recomputes only elementwise/norm in backward; the chunked head
        # drops the [R, L, V] logits grid that no longer fits at L≈1792.
        remat="dots", logprob_chunk=512,
    )
    model = backend.initialize(model, FinetuneSpec(1, 512, 64))
    # HONESTY NOTE vs BENCH_r04: r4's engine silently trained fully in
    # bf16 — params, Adam moments, updates (optax weak-type chain) — which
    # is lighter AND faster but rounds away updates smaller than ~4e-3
    # relative (bf16 mantissa), a silent quality bug for PPO-scale lrs.
    # The engine now keeps explicit f32 masters (backend/jax_train.py);
    # the bench measures the CORRECT training path. r05-r07 ran it at the
    # cap-2048 no-remat config (the best fit then); r08 moves to
    # cap-4096 + "dots"-remat + chunked-logprob, which the "dots" remat
    # fits in the same budget (see the backend block above).

    hp = PPOHyperparameters(ppo_n_minibatches=1, adv_norm=True,
                            kl_ctl=0.0, disable_value=True)
    iface = PPOActorInterface(hp)

    # Synthetic rollout batch: 32 trajectories, 256-token prompt + ~768 gen
    # (canonical recipe: base/testing.bench_trajectory_dist — shared with
    # perf_probe packfill and the packing-fill test gate).
    from areal_tpu.base.testing import bench_trajectory_dist

    n_seq = 32
    rng, plens, glens = bench_trajectory_dist(0, n_seq)
    seqlens = (plens + glens).astype(int)
    total = int(seqlens.sum())
    toks = rng.randint(2, cfg.vocab_size, total).astype(np.int32)
    pmask, lps = [], []
    for p, g in zip(plens, glens):
        pmask.append(np.concatenate([np.ones(p, np.int32), np.zeros(g, np.int32)]))
        lps.append(np.concatenate([np.zeros(p, np.float32),
                                   -rng.rand(g).astype(np.float32)]))
    batch = SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seq)],
        data={
            "packed_input_ids": toks,
            "prompt_mask": np.concatenate(pmask),
            "packed_logprobs": np.concatenate(lps),
            "rewards": rng.rand(n_seq).astype(np.float32),
            "seq_no_eos_mask": np.zeros(n_seq, np.float32),
        },
        seqlens=seqlens.tolist(),
    )
    spec = MicroBatchSpec(max_tokens_per_mb=4096)

    # Achieved packing fill (host-only, same packer the train step runs,
    # parameterized from the SAME backend fields so it cannot desync from
    # the engine's layout): the padding factor the reported MFU divides
    # by — tracked in the output so BENCH_r* records the fill lever
    # alongside tokens/s.
    from areal_tpu.backend import microbatch as mbu

    pack_mbs = mbu.split_into_microbatches(
        batch, spec, length_bucket=backend.length_bucket,
        rows_bucket=backend.rows_bucket, seqs_bucket=backend.seqs_bucket,
        fill_bucket=backend.fill_bucket,
    )
    pack_fill = mbu.pack_fill(pack_mbs)
    del pack_mbs

    # Warmup/compile wall clock as a first-class bench field: the trace
    # cost every fresh launch pays before step 1. Cache-sensitive — a warm
    # persistent cache (apps/launcher.py) collapses it — so the
    # bench_compare gate carries a wide tolerance (docs/benchmarks.md).
    t0 = time.perf_counter()
    iface.train_step(model, batch, spec)  # warmup/compile
    jax.block_until_ready(model.module.params)
    warmup_compile_s = time.perf_counter() - t0
    telemetry.get().snapshot(reset=True)  # drop warmup-step spans
    t0 = time.perf_counter()
    steps = 3
    for _ in range(steps):
        iface.train_step(model, batch, spec)
    jax.block_until_ready(model.module.params)
    dt = time.perf_counter() - t0

    # Trainer step-phase breakdown from the timed steps' telemetry spans
    # (backend/jax_train.py train_batch instrumentation).
    train_phases = None
    if use_telemetry:
        spans = telemetry.get().snapshot(reset=True)["spans"]
        agg = {}
        for s in spans:
            if s["name"].startswith("train/"):
                agg[s["name"]] = agg.get(s["name"], 0.0) + s["dur_secs"]
        train_phases = {
            k.split("/", 1)[1] + "_s": round(v / steps, 4)
            for k, v in sorted(agg.items())
        }

    n_chips = jax.device_count()
    tokens_per_sec_chip = steps * total / dt / n_chips

    # Device-memory high-water mark over the timed PPO steps (the whole
    # process so far, which the train loop dominates) — the same
    # allocator counter system/memwatch.py exports live as hbm/peak_bytes.
    # CPU backends have no memory_stats(); the field is then omitted and
    # bench_compare reports it n/a (docs/benchmarks.md).
    hbm_peak_gb = None
    try:
        peaks = [
            (d.memory_stats() or {}).get("peak_bytes_in_use", 0)
            for d in jax.local_devices()
        ]
        if any(peaks):
            hbm_peak_gb = max(peaks) / float(1 << 30)
    except Exception:  # noqa: BLE001 — backend-dependent, best-effort
        pass

    # North-star metric #2 (BASELINE.json): trainer→rollout weight-sync
    # latency, measured through the STREAMED transport (the production
    # path since this round, docs/weight_sync.md): the trainer-side
    # WeightStreamPublisher gathers bf16 tensors d2h in a background
    # thread while a consumer (standing in for one generation server)
    # pulls the chunks over ZMQ and device_puts each tensor as it lands —
    # the checkpoint round-trip through the filesystem is gone, and BOTH
    # host↔device legs are measured directly (r05's disk path measured d2h
    # and extrapolated h2d as symmetric; see docs/benchmarks.md for the
    # method discontinuity).
    import jax.numpy as jnp

    from areal_tpu.models.hf import flatten_pytree
    from areal_tpu.system.weight_stream import (
        WeightStreamConsumer,
        WeightStreamPublisher,
    )

    eng = model.module
    publisher = None
    consumer = None
    try:
        t0 = time.perf_counter()
        # Publish in the compute dtype (bf16), cast on device — mirrors
        # trainer_worker._publish_weights_stream: half the d2h/wire/h2d
        # bytes vs shipping the f32 masters.
        pub = jax.tree.map(
            lambda x: x.astype(eng.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            eng.params,
        )
        old_flat = flatten_pytree(pub)  # device refs, no transfer
        publisher = WeightStreamPublisher("bench", "b0", "actor")
        publisher.publish(sorted(old_flat.items()), version=1)
        consumer = WeightStreamConsumer(publisher.endpoint)
        manifest = consumer.fetch_manifest(1)
        shadow = {}
        for name, arr in consumer.iter_tensors(1, manifest):
            old = old_flat[name]
            # Async dispatch: h2d of tensor i−1 overlaps the wire transfer
            # of tensor i and the publisher's d2h gather of tensor i+1.
            shadow[name] = jax.device_put(
                np.asarray(arr, dtype=old.dtype), old.sharding
            )
        consumer.verify_digest(1)
        assert set(shadow) == set(old_flat)
        jax.block_until_ready(list(shadow.values()))
        weight_sync_s = time.perf_counter() - t0
        # "io" = the host-side CPU work the framework controls (checksums,
        # framing, reassembly) — the analogue of r05's serialize+disk leg;
        # everything else is d2h/wire/h2d transport, pipelined.
        weight_sync_io_s = consumer.checksum_secs
        weight_sync_transport_s = weight_sync_s - weight_sync_io_s
    finally:
        if consumer is not None:
            consumer.close()
        if publisher is not None:
            publisher.close()

    # The device transport measured next to it (same params, same chip):
    # reshard-in-place publish + digest-gated consume through the
    # in-process registry (parallel/reshard.py) — no d2h, no wire, no h2d.
    # On a colocated single mesh the publish is a zero-copy plan walk, so
    # this number is the transport's floor; heterogeneous layouts add the
    # grouped on-device moves (tools/perf_probe.py reshard-bench sweeps
    # those).
    from areal_tpu.parallel import reshard as rsh

    t0 = time.perf_counter()
    dev_pub = rsh.publish_device(
        "bench", "b0", "actor", pub,
        target_shardings=rsh.shardings_of(pub), version=1,
    )
    got = rsh.consume_device(
        "bench", "b0", "actor", 1, dev_pub.digest, pub
    )
    jax.block_until_ready(got)
    weight_sync_device_s = time.perf_counter() - t0
    rsh.clear_publication("bench", "b0", "actor")

    # Durable-spool overhead (host-only, no sockets): the per-trajectory
    # cost the rollout worker pays when durability is on — msgpack-frame
    # each bench trajectory the way ZmqPusher wires it, append (CRC +
    # fsync) to a SampleSpool, then ack the batch (watermark write + GC).
    # Reported per record so the number is workload-size independent;
    # gated by tools/bench_compare.py (docs/fault_tolerance.md §Data
    # durability).
    import shutil
    import tempfile

    from areal_tpu.system import streams
    from areal_tpu.system.sample_spool import SampleSpool

    frames = []
    off = 0
    for i, (p, g) in enumerate(zip(plens, glens)):
        ln = int(p + g)
        single = SequenceSample.from_default(
            ids=[f"b{i}"],
            data={
                "packed_input_ids": toks[off:off + ln],
                "prompt_mask": np.concatenate(
                    [np.ones(p, np.int32), np.zeros(g, np.int32)]),
                "packed_logprobs": lps[i],
                "rewards": rng.rand(1).astype(np.float32),
                "seq_no_eos_mask": np.zeros(1, np.float32),
            },
            seqlens=[ln],
        )
        frames.append(streams._pack(single.as_json_compatible()))
        off += ln
    spool_dir = tempfile.mkdtemp(prefix="bench_spool_")
    try:
        spool = SampleSpool(spool_dir)
        t0 = time.perf_counter()
        seqnos = [spool.append(raw) for raw in frames]
        spool_append_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        spool.ack(seqnos)
        spool_ack_s = time.perf_counter() - t0
        spool.close()
    finally:
        shutil.rmtree(spool_dir, ignore_errors=True)
    spool_append_ms = spool_append_s / len(frames) * 1e3
    spool_ack_ms = spool_ack_s / len(frames) * 1e3

    # Long-context ring-attention row (ISSUE 18): one attention layer's
    # fwd+bwd step time at long context under the active ring schedule
    # (zig-zag + causal-skip + double-buffered ppermute) vs the contiguous
    # v1 oracle (AREAL_RING_SCHEDULE=naive), on an sp=<all local chips>
    # ring. The skip ratio comes from the trace-time area counters
    # (parallel/ring.py), so it is structural — (n+1)/2n at sp=n — not a
    # timing artifact. On one chip the ring is degenerate (sp=1, both
    # schedules identical); the fields still emit so the BENCH trajectory
    # has the row, and `perf_probe ring-bench` sweeps the multi-shard
    # shapes on host devices. See docs/benchmarks.md for the method note.
    from areal_tpu.parallel import mesh as pmesh_mod
    from areal_tpu.parallel import ring as ring_mod

    ring_sp = n_chips
    ring_seq = 4096
    ring_mesh = pmesh_mod.make_mesh(pmesh_mod.ParallelSpec(sp=ring_sp))
    rngr = np.random.RandomState(0)
    rq = jnp.asarray(rngr.randn(1, ring_seq, cfg.n_q_heads, cfg.head_dim)
                     .astype(np.float32) * 0.1)
    rk = jnp.asarray(rngr.randn(1, ring_seq, cfg.n_kv_heads, cfg.head_dim)
                     .astype(np.float32) * 0.1)
    rv = jnp.asarray(rngr.randn(1, ring_seq, cfg.n_kv_heads, cfg.head_dim)
                     .astype(np.float32) * 0.1)
    rseg = jnp.ones((1, ring_seq), jnp.int32)

    def ring_step_time(schedule):
        def loss(q, k, v):
            o = ring_mod.ring_attention(q, k, v, rseg, ring_mesh,
                                        schedule=schedule)
            return jnp.sum(o * o)

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        ring_mod.reset_ring_counters()
        jax.block_until_ready(f(rq, rk, rv))  # compile; fills counters
        ratio = ring_mod.ring_skip_ratio()
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            g = f(rq, rk, rv)
        jax.block_until_ready(g)
        return (time.perf_counter() - t0) / reps * 1e3, ratio

    ring_sched = ring_mod.resolve_schedule(None, ring_seq, ring_sp)
    ring_step_ms, ring_skip = ring_step_time(ring_sched)
    ring_naive_step_ms, _ = ring_step_time("naive")

    # MoE dispatch row (ISSUE 19): one MoE layer's fwd+bwd step time under
    # the sort-based grouped compute path (default) vs the one-hot einsum
    # oracle (AREAL_MOE_DISPATCH=einsum), at E=8 experts on this host. The
    # headline PPO loop above stays DENSE — this row isolates the dispatch
    # method exactly like the ring row isolates the attention schedule;
    # `perf_probe moe-bench` sweeps (E, top_k, capacity_factor) shapes.
    # See docs/benchmarks.md for the method note.
    from areal_tpu.models import config as mcfg_mod
    from areal_tpu.models import moe as moe_mod

    moe_cfg = mcfg_mod.MoEConfig(
        num_experts=8, top_k=2, capacity_factor=2.0,
        routed_intermediate_dim=cfg.intermediate_dim,
    )
    moe_tcfg = dataclasses.replace(cfg, n_layers=1, moe=moe_cfg)
    moe_dim = cfg.hidden_dim
    moe_tokens = 4096
    stacked = moe_mod.init_moe_params(
        moe_tcfg, jax.random.PRNGKey(0), jnp.float32)
    moe_params = {k: v[0] for k, v in stacked.items()}  # layer 0 of 1
    mx = jnp.asarray(rngr.randn(8, moe_tokens // 8, moe_dim)
                     .astype(np.float32) * 0.1)

    def moe_step_time(dispatch):
        def loss(lp, x):
            y, _ = moe_mod.moe_mlp(x, lp, moe_cfg, dispatch=dispatch)
            return jnp.sum(y * y)

        f = jax.jit(jax.grad(loss))
        jax.block_until_ready(f(moe_params, mx))  # compile
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            g = f(moe_params, mx)
        jax.block_until_ready(g)
        return (time.perf_counter() - t0) / reps * 1e3

    moe_step_ms = moe_step_time("grouped")
    moe_einsum_step_ms = moe_step_time("einsum")

    # Roofline context over the bf16 peak of one chip. The 6·N·T train
    # FLOPs estimate and the per-generation peak table live in
    # base/monitor.py — ONE accounting shared with the live trainer's
    # train/achieved_tflops + train/mfu gauges (system/goodput.py), so
    # the bench number and the live gauges can never drift apart.
    from areal_tpu.base import monitor

    # Activated params, not total: for MoE geometries only top_k of the
    # expert FFNs run per token, and 6·N·T over total params would claim
    # FLOPs that never execute (dense configs: identical to param_count).
    n_params = transformer.activated_param_count(cfg)
    flops = monitor.train_flops_6nt(n_params, steps * total)
    peak = monitor.device_peak_flops(str(jax.devices()[0]))
    mfu = (flops / dt / n_chips / peak) if peak else 0.0

    out = {
        "metric": "ppo_trained_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4),
        "pack_fill": round(pack_fill, 4),
        "warmup_compile_s": round(warmup_compile_s, 3),
        "weight_sync_latency_s": round(weight_sync_s, 3),
        "weight_sync_io_s": round(weight_sync_io_s, 3),
        "weight_sync_transport_s": round(weight_sync_transport_s, 3),
        "weight_sync_device_s": round(weight_sync_device_s, 3),
        "spool_append_ms": round(spool_append_ms, 3),
        "spool_ack_ms": round(spool_ack_ms, 3),
        "ring_seq_len": ring_seq,
        "ring_sp": ring_sp,
        "ring_step_ms": round(ring_step_ms, 3),
        "ring_naive_step_ms": round(ring_naive_step_ms, 3),
        "ring_skip_ratio": round(ring_skip, 4),
        # Discontinuity key for the ring_* fields (bench_compare skips
        # them when the schedule method changes, like weight_sync_*).
        "ring_schedule_method": f"{ring_sched}-sp{ring_sp}",
        "moe_num_experts": moe_cfg.num_experts,
        "moe_top_k": moe_cfg.top_k,
        "moe_capacity_factor": moe_cfg.capacity_factor,
        "moe_step_ms": round(moe_step_ms, 3),
        "moe_einsum_step_ms": round(moe_einsum_step_ms, 3),
        # Discontinuity key for the moe_* fields (bench_compare skips
        # them when the dispatch method changes).
        "moe_dispatch_method": "grouped-vs-einsum",
        # METHOD CHANGE vs r6: the device transport (on-device reshard
        # publish + digest-gated consume) is measured ALONGSIDE the
        # streamed path — weight_sync_latency_s still names the streamed
        # number (r6 continuity), weight_sync_device_s is the new
        # transport. See docs/benchmarks.md for the discontinuity note.
        "weight_sync_transport_method": "streamed+device-measured",
    }
    if hbm_peak_gb is not None:
        out["hbm_peak_gb"] = round(hbm_peak_gb, 3)
    if train_phases is not None:
        # Phase fields are a measurement-method ADDITION (AREAL_TELEMETRY=1
        # runs only): phases sum to ~the per-step wall clock; the headline
        # tokens/s stays defined by the uninstrumented default run.
        out["train_phases"] = train_phases
    print(json.dumps(out))


if __name__ == "__main__":
    main()
