"""areal_tpu — a TPU-native asynchronous RL (PPO) training framework.

Brand-new JAX/XLA/Pallas implementation with the capabilities of the AReaL
reference system (structural blueprint in /root/repo/SURVEY.md). The compute
path is GSPMD/pjit over `jax.sharding.Mesh`; the system fabric (workers,
streams, staleness control) is asyncio/ZMQ Python.
"""

__version__ = "0.1.0"
