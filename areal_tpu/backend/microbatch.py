"""SequenceSample → fixed-shape device micro-batches.

The jit boundary of every engine call. Replaces the reference's dynamic
varlen micro-batching (``SequenceSample.split`` + flash-attn cu_seqlens) with
bucketed [B, L] grids (models/packing.py) so XLA sees a small, stable set of
shapes (SURVEY §7 hard-part 6: recompilation churn).

Key-layout contract (deviation from the reference, by design): every
per-token key of a sample has the SAME per-sample seqlens as the main token
key (``packed_input_ids``) — logprobs/masks/etc are full-length with unused
slots zeroed — so one PackLayout serves all keys. Scalar keys (one value per
sample, e.g. rewards) ride along as [n_seqs] vectors plus (row, last_col)
index arrays into the grid.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.base import datapack
from areal_tpu.models import packing


@dataclasses.dataclass
class MicroBatch:
    layout: packing.PackLayout
    # [B, L] grids: always "tokens", "segment_ids", "positions"; plus one per
    # extra token-aligned key.
    grids: Dict[str, np.ndarray]
    # [S] per-sequence vectors (scalar keys), padded to the seqs bucket.
    scalars: Dict[str, np.ndarray]
    # [S] grid coordinates per sequence (padded entries point at (0, 0)).
    seq_rows: np.ndarray
    seq_first_cols: np.ndarray
    seq_last_cols: np.ndarray
    # [S] 1.0 for real sequences, 0.0 for bucket padding.
    seq_mask: np.ndarray
    # indices into the parent sample for scatter-back (real sequences only)
    sample_indices: List[int]

    @property
    def n_seqs(self) -> int:
        return len(self.sample_indices)

    @property
    def n_tokens(self) -> int:
        return int(sum(self.layout.seqlens))


# The fill sweep below bounds its candidate row lengths to
# ``min(cap, max(2*base, 64*fill_bucket))`` stepped by ``fill_bucket`` —
# at most this many distinct L values regardless of the token budget.
FILL_SWEEP_MAX_CANDIDATES = 64


def worst_case_row_candidates(
    length_bucket: int = 128,
    fill_bucket: Optional[int] = None,
    max_tokens_per_mb: Optional[int] = None,
) -> int:
    """Upper bound on distinct candidate row lengths the fill sweep in
    :func:`split_into_microbatches` can ever emit — i.e. the worst-case
    contribution of trainer ``[R, L]`` packed grids to the
    ``compile/distinct_shapes`` family. Pure arithmetic (no jax): shared
    by ``cli_args.validate_config``'s cross-check against
    ``serving.max_compiled_shapes`` so the parse-time check and the
    runtime sweep agree by construction."""
    if fill_bucket is None:
        fill_bucket = min(length_bucket, 128)
    fill_bucket = max(int(fill_bucket), 1)
    n = FILL_SWEEP_MAX_CANDIDATES
    if max_tokens_per_mb:
        # cap also bounds hi: at most ceil(cap / fill_bucket) multiples fit.
        n = min(n, -(-int(max_tokens_per_mb) // fill_bucket))
    return max(n, 1)


def split_into_microbatches(
    sample: SequenceSample,
    mb_spec: MicroBatchSpec,
    token_key: str = "packed_input_ids",
    length_bucket: int = 128,
    rows_bucket: int = 8,
    seqs_bucket: int = 8,
    row_len: Optional[int] = None,
    fill_bucket: Optional[int] = None,
) -> List[MicroBatch]:
    """Pack ``sample`` into micro-batches of IDENTICAL ``[R, L]`` grid shape.

    Pack-then-split (not split-then-pack): sequences are FFD-packed into
    rows of a single row length L, and rows are grouped R-per-micro-batch
    so every micro-batch compiles to the same shape. L is chosen from the
    multiples of ``fill_bucket`` that fit the longest sequence by
    minimizing total padded cells (measured r3: the old per-mb
    round_up(max_len) layout reached only 0.67 fill on ~1k-token rollouts
    — a third of the MXU work was padding).

    ``fill_bucket`` (default ``min(length_bucket, 128)``) is the candidate
    row-length granularity — decoupled from ``length_bucket`` in round 8
    because stepping candidates by a coarse 512 bucket was itself a fill
    ceiling: at the bench distribution (~700-1000-token trajectories) the
    only coarse candidates were 1536/2048-token rows at ≤0.85 fill, while
    the 128-grain sweep finds rows ≥0.92 full under a cap-4096 budget. 128
    is the floor the Pallas flash kernel's lane width imposes on row
    lengths. The rows-per-micro-batch choice is swept as well (the old
    fixed ``cap // L`` wasted up to R-1 padding rows in the last
    micro-batch). Finer candidates mean the compiled [R, L] shape tracks
    the length distribution more closely — more distinct shapes across
    drifting distributions; raise ``fill_bucket`` back toward
    ``length_bucket`` to trade fill for shape stability.

    ``rows_bucket`` is kept for API compatibility; uniform grouping already
    pins the compiled shape set.
    """
    if sample.bs == 0:
        return []
    if fill_bucket is None:
        fill_bucket = min(length_bucket, 128)
    seqlens = [int(x) for x in sample.total_lens(token_key)]
    total = sum(seqlens)
    cap = int(mb_spec.max_tokens_per_mb or total)
    base = packing.round_up(max(seqlens), fill_bucket)
    cap = max(cap, base)
    if row_len is not None:
        L0 = packing.round_up(row_len, length_bucket)
        if max(seqlens) > L0:
            raise ValueError(
                f"sequence of length {max(seqlens)} exceeds row_len {L0}"
            )
        cands = [L0]
    else:
        # Bound the sweep: rows much longer than a few multiples of the
        # longest sequence stop improving fill, and an uncapped token
        # budget must not turn into an O(total/fill_bucket) FFD sweep.
        hi = min(cap, max(2 * base, 64 * fill_bucket))
        cands = list(range(base, hi + 1, fill_bucket))
    min_mbs = mb_spec.n_mbs or 1
    best = None
    for L in cands:
        rows = datapack.ffd_allocate(seqlens, L)
        # Rows per micro-batch: bounded by the token cap AND small enough
        # that >= mb_spec.n_mbs groups come out (the documented minimum);
        # swept downward because ceil(len(rows)/R) rounding can pad the
        # last micro-batch with up to R-1 dead rows.
        max_R = max(min(cap // L, len(rows) // min_mbs), 1)
        for R in range(max_R, 0, -1):
            n_mbs = -(-len(rows) // R)
            cells = n_mbs * R * L
            # Strict < keeps the FIRST optimum: the smaller row length
            # (less per-row causal attention waste) and, within one L, the
            # larger R (fewer dispatches) for the same padded-cell count.
            if best is None or cells < best[0]:
                best = (cells, L, R, rows)
    _, L, R, rows = best
    out = []
    for m in range(0, len(rows), R):
        grp = rows[m : m + R]
        idxs = [i for r in grp for i in r]
        if not idxs:
            continue
        placements: List[Tuple[int, int]] = [None] * len(idxs)  # type: ignore
        sub_pos = {g: p for p, g in enumerate(idxs)}
        for row, r in enumerate(grp):
            col = 0
            for i in r:
                placements[sub_pos[i]] = (row, col)
                col += seqlens[i]
        layout = packing.PackLayout(
            n_rows=R, row_len=L, placements=placements,
            seqlens=[seqlens[i] for i in idxs],
        )
        out.append(
            make_microbatch(
                sample.select_idx(idxs), token_key=token_key,
                length_bucket=length_bucket, rows_bucket=rows_bucket,
                seqs_bucket=seqs_bucket, layout=layout, sample_indices=idxs,
            )
        )
    return out


def pack_fill(mbs: List[MicroBatch]) -> float:
    """Achieved packing fill of a micro-batch split: real tokens over
    allocated [R, L] cells — the padding factor the reported MFU divides
    by. Exported as the ``train/pack_fill`` telemetry gauge and in
    bench.py output (ISSUE 8 / ROADMAP item 1)."""
    ntok = sum(mb.n_tokens for mb in mbs)
    ncells = sum(int(np.prod(mb.layout.shape)) for mb in mbs)
    return (ntok / ncells) if ncells else 0.0


def make_microbatch(
    sample: SequenceSample,
    token_key: str = "packed_input_ids",
    length_bucket: int = 128,
    rows_bucket: int = 8,
    seqs_bucket: int = 8,
    row_len: Optional[int] = None,
    sample_indices: Optional[Sequence[int]] = None,
    layout: Optional[packing.PackLayout] = None,
) -> MicroBatch:
    assert sample.data is not None, "micro-batching needs materialized data"
    seqlens = [int(x) for x in sample.total_lens(token_key)]
    if layout is None:
        layout = packing.plan_packing(
            seqlens, length_bucket=length_bucket, rows_multiple=rows_bucket,
            row_len=row_len,
        )
    grid = packing.make_grid(layout)
    grids: Dict[str, np.ndarray] = {
        "tokens": packing.batch_from_packed(
            sample.data[token_key].astype(np.int32), layout
        ),
        "segment_ids": grid["segment_ids"],
        "positions": grid["positions"],
    }
    scalars: Dict[str, np.ndarray] = {}
    total = sum(seqlens)
    for k in sample.keys:
        if k == token_key or sample.data.get(k) is None:
            continue
        v = sample.data[k]
        if v.shape[0] == total and [sum(s) for s in sample.seqlens[k]] == seqlens:
            grids[k] = packing.batch_from_packed(v, layout)
        elif v.shape[0] == sample.bs:
            scalars[k] = v
        else:
            raise ValueError(
                f"key {k}: leading dim {v.shape[0]} is neither token-aligned "
                f"({total}) nor per-sample ({sample.bs}); pad per-token keys "
                "to full length (see module docstring)"
            )
    # Bucket the sequence count too: without this, every distinct n_seqs
    # would recompile the jitted step (the [S]-shaped arrays below are jit
    # inputs), re-introducing the churn the [B, L] bucketing removes.
    n = len(seqlens)
    S = packing.round_up(max(n, 1), seqs_bucket)
    rows = np.zeros(S, np.int32)
    firsts = np.zeros(S, np.int32)
    lasts = np.zeros(S, np.int32)
    seq_mask = np.zeros(S, np.float32)
    rows[:n] = [p[0] for p in layout.placements]
    firsts[:n] = [p[1] for p in layout.placements]
    lasts[:n] = [p[1] + sl - 1 for p, sl in zip(layout.placements, layout.seqlens)]
    seq_mask[:n] = 1.0
    for k, v in scalars.items():
        pad = np.zeros((S,) + v.shape[1:], v.dtype)
        pad[:n] = v
        scalars[k] = pad
    return MicroBatch(
        layout=layout,
        grids=grids,
        scalars=scalars,
        seq_rows=rows,
        seq_first_cols=firsts,
        seq_last_cols=lasts,
        seq_mask=seq_mask,
        sample_indices=list(sample_indices) if sample_indices is not None else
        list(range(sample.bs)),
    )


def scatter_back(
    mbs: List[MicroBatch],
    per_mb_grids: List[np.ndarray],  # [B, L, ...] device outputs per micro-batch
    n_samples: int,
) -> List[np.ndarray]:
    """Undo the micro-batch split: per-sample packed arrays in the ORIGINAL
    sample order (inverse of split_into_microbatches)."""
    out: List[Optional[np.ndarray]] = [None] * n_samples
    for mb, g in zip(mbs, per_mb_grids):
        g = np.asarray(g)
        for i, (placement, n) in enumerate(zip(mb.layout.placements, mb.layout.seqlens)):
            row, col = placement
            out[mb.sample_indices[i]] = g[row, col : col + n]
    missing = [i for i, v in enumerate(out) if v is None]
    if missing:
        raise ValueError(f"samples {missing} appear in no micro-batch")
    return out  # type: ignore
