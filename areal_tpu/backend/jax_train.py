"""The optax/GSPMD training engine — areal_tpu's Megatron-backend equivalent.

Parity target: ``realhf/impl/model/backend/megatron.py`` (ReaLMegatronEngine:
microbatched train_batch/forward/generate with global token normalization,
grad-norm stats, lr scheduling) and ``inference.py`` (PipelinableInference-
Engine). TPU-first differences:

 - No DDP/ZeRO wrapper classes: params/opt-state sharding IS the
   PartitionSpec tree (parallel/sharding.py); XLA emits the reduce-scatters
   Megatron's DistributedOptimizer hand-codes.
 - No pipeline-schedule VM (instruction.py/pipe_runner.py): micro-batches
   exist only to bound activation HBM; each one is a full jitted step and
   gradients accumulate across them on device.
 - Mixed precision: params live in f32 (or cfg dtype), compute is cast per
   step to ``compute_dtype`` (bf16 on the MXU); no loss scaling needed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    FinetuneSpec,
    GenerationHyperparameters,
    Model,
    ModelBackend,
    TrainableEngine,
    register_backend,
)
from areal_tpu.backend import microbatch as mbu
from areal_tpu.base import logging
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer
from areal_tpu.models.config import TransformerConfig
from areal_tpu.parallel import sharding as psh

logger = logging.getLogger("backend.jax")


@dataclasses.dataclass
class OptimizerConfig:
    """Reference cli_args.py:173 (OptimizerConfig)."""

    type: str = "adamw"
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    warmup_steps_proportion: float = 0.02
    lr_scheduler_type: str = "constant"  # constant | cosine | linear
    gradient_clipping: float = 1.0


def build_lr_schedule(cfg: OptimizerConfig, total_steps: int):
    """Warmup + {constant,cosine,linear} decay to min_lr_ratio·lr (parity:
    thirdparty/megatron lr_schduler.py used by the reference backend)."""
    total_steps = max(total_steps, 1)
    warmup = int(cfg.warmup_steps_proportion * total_steps)
    floor = cfg.lr * cfg.min_lr_ratio
    if cfg.lr_scheduler_type == "cosine":
        decay = optax.cosine_decay_schedule(
            cfg.lr, max(total_steps - warmup, 1), alpha=cfg.min_lr_ratio
        )
    elif cfg.lr_scheduler_type == "linear":
        decay = optax.linear_schedule(
            cfg.lr, floor, max(total_steps - warmup, 1)
        )
    else:
        decay = optax.constant_schedule(cfg.lr)
    if warmup > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.lr, warmup), decay], [warmup]
        )
    return decay


def build_optimizer(
    cfg: OptimizerConfig, total_steps: int
) -> Tuple[optax.GradientTransformation, Callable]:
    sched = build_lr_schedule(cfg, total_steps)
    assert cfg.type in ("adamw", "sgd"), cfg.type
    if cfg.type == "adamw":
        opt = optax.adamw(
            sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
            weight_decay=cfg.weight_decay,
        )
    else:
        opt = optax.sgd(sched)
    chain = [opt]
    if cfg.gradient_clipping and cfg.gradient_clipping > 0:
        chain = [optax.clip_by_global_norm(cfg.gradient_clipping)] + chain
    return optax.chain(*chain), sched


# Loss functions receive (logits, batch) and return (loss_sum, stats-sums).
LossFn = Callable[[jnp.ndarray, Dict[str, jnp.ndarray]], Tuple[jnp.ndarray, Dict]]


class JaxTrainEngine(TrainableEngine):
    """Owns (params, opt_state) on an optional mesh and the jitted steps."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        opt_cfg: Optional[OptimizerConfig] = None,
        ft_spec: Optional[FinetuneSpec] = None,
        mesh=None,
        compute_dtype: str = "bfloat16",
        length_bucket: int = 128,
        rows_bucket: int = 8,
        seqs_bucket: int = 8,
        attn_impl: str = "auto",
        remat: bool = False,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.length_bucket = length_bucket
        self.rows_bucket = rows_bucket
        self.seqs_bucket = seqs_bucket
        self.attn_impl = attn_impl
        self.remat = remat
        if mesh is not None:
            params = psh.shard_params(params, mesh, cfg)
        else:
            params = jax.tree.map(jnp.asarray, params)
        self.params = params
        self.opt_cfg = opt_cfg
        self.tx = None
        self.opt_state = None
        self.lr_schedule = None
        self.opt_step_count = 0
        if opt_cfg is not None:
            total = ft_spec.total_train_steps if ft_spec is not None else 1000
            self.tx, self.lr_schedule = build_optimizer(opt_cfg, total)
            self.opt_state = jax.jit(self.tx.init)(self.params)
        self._grad_fns: Dict[int, Callable] = {}
        self._fwd_fns: Dict[int, Callable] = {}
        self._apply_fn = None

    # -------------- internals --------------

    def _mesh_ctx(self):
        if self.mesh is not None:
            return psh.activation_sharding(self.mesh)
        import contextlib

        return contextlib.nullcontext()

    def _cast(self, params):
        cd = self.compute_dtype

        def c(x):
            return x.astype(cd) if jnp.issubdtype(x.dtype, jnp.floating) else x

        return jax.tree.map(c, params)

    def _model_forward(self, params, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        out, _ = transformer.forward(
            self._cast(params),
            self.cfg,
            batch["tokens"],
            batch["positions"],
            segment_ids=batch["segment_ids"],
            attn_impl=self.attn_impl,
            remat=self.remat,
            return_kv=False,
        )
        # Critic values [B, L] are cheap in f32; lm logits [B, L, V] stay in
        # the compute dtype — loss fns upcast per-element inside fused
        # reductions (see ppo_functional.gather_logprobs).
        return out.astype(jnp.float32) if self.cfg.is_critic else out

    def _get_grad_fn(self, loss_fn: LossFn) -> Callable:
        key = id(loss_fn)
        if key not in self._grad_fns:

            def f(params, batch, denom):
                def lf(p):
                    out = self._model_forward(p, batch)
                    loss_sum, stats = loss_fn(out, batch)
                    return loss_sum / jnp.maximum(denom, 1.0), stats

                (loss, stats), grads = jax.value_and_grad(lf, has_aux=True)(params)
                return loss, stats, grads

            self._grad_fns[key] = jax.jit(f)
        return self._grad_fns[key]

    def _get_apply_fn(self) -> Callable:
        if self._apply_fn is None:

            def f(params, opt_state, grads):
                updates, new_opt = self.tx.update(grads, opt_state, params)
                gnorm = optax.global_norm(grads)
                return optax.apply_updates(params, updates), new_opt, gnorm

            # Donate old params/opt_state/grads: the update is in-place in HBM.
            self._apply_fn = jax.jit(f, donate_argnums=(0, 1, 2))
        return self._apply_fn

    def _device_batch(self, mb: mbu.MicroBatch) -> Dict[str, jnp.ndarray]:
        d: Dict[str, jnp.ndarray] = {}
        for k, v in mb.grids.items():
            d[k] = jnp.asarray(v)
        for k, v in mb.scalars.items():
            d[k] = jnp.asarray(v)
        d["seq_rows"] = jnp.asarray(mb.seq_rows)
        d["seq_first_cols"] = jnp.asarray(mb.seq_first_cols)
        d["seq_last_cols"] = jnp.asarray(mb.seq_last_cols)
        d["seq_mask"] = jnp.asarray(mb.seq_mask)
        return d

    # -------------- TrainableEngine API --------------

    def train_batch(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: LossFn,
        loss_weight_fn: Callable[[mbu.MicroBatch], float],
        token_normalize_scope: str = "global",
        version_steps: int = 0,
    ) -> Dict[str, float]:
        """Grad-accumulate over micro-batches, single optimizer step.

        ``loss_fn`` must return the SUM of per-token losses; it is divided by
        the total ``loss_weight_fn`` mass of the whole batch ("global" scope,
        reference megatron.py:410-494) or of each micro-batch ("mb")."""
        assert self.tx is not None, "engine built without an optimizer"
        mbs = mbu.split_into_microbatches(
            input_, mb_spec, length_bucket=self.length_bucket,
            rows_bucket=self.rows_bucket, seqs_bucket=self.seqs_bucket,
        )
        weights = [float(loss_weight_fn(mb)) for mb in mbs]
        total_w = sum(weights)
        grad_fn = self._get_grad_fn(loss_fn)

        grads_acc = None
        loss_acc = None
        stats_acc: Dict[str, Any] = {}
        for mb, w in zip(mbs, weights):
            denom = total_w if token_normalize_scope == "global" else w
            batch = self._device_batch(mb)
            with self._mesh_ctx():
                loss, stats, grads = grad_fn(
                    self.params, batch, jnp.asarray(denom, jnp.float32)
                )
            if token_normalize_scope != "global":
                # mb scope: each micro-batch normalized by itself; average.
                loss = loss / len(mbs)
                grads = jax.tree.map(lambda g: g / len(mbs), grads)
            grads_acc = (
                grads
                if grads_acc is None
                else jax.tree.map(jnp.add, grads_acc, grads)
            )
            # Keep scalars on device: a float() here would sync the host
            # into every micro-batch and stall the pipeline.
            loss_acc = loss if loss_acc is None else loss_acc + loss
            for k, v in stats.items():
                stats_acc[k] = stats_acc[k] + v if k in stats_acc else v

        self.params, self.opt_state, gnorm = self._get_apply_fn()(
            self.params, self.opt_state, grads_acc
        )
        # optax evaluated the schedule at the PRE-increment count.
        applied_lr = float(self.lr_schedule(self.opt_step_count))
        self.opt_step_count += 1
        # Engine bookkeeping keys are written AFTER the user stats and would
        # clobber same-named loss_fn stats — keep them namespaced.
        out = {k: float(v) for k, v in stats_acc.items()}
        out["loss"] = float(loss_acc) if loss_acc is not None else 0.0
        out["grad_norm"] = float(gnorm)
        out["lr"] = applied_lr
        out["total_tokens"] = float(sum(mb.n_tokens for mb in mbs))
        out["loss_weight"] = total_w
        return out

    def forward(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
    ) -> List[np.ndarray]:
        """Micro-batched inference. ``post_hook(out, batch) -> [B, L, ...]``
        maps raw model output (logits/values) to the per-token quantity —
        applied on device so [B, L, V] logits never reach the host. Returns
        per-sample packed arrays in input order."""
        mbs = mbu.split_into_microbatches(
            input_, mb_spec, length_bucket=self.length_bucket,
            rows_bucket=self.rows_bucket, seqs_bucket=self.seqs_bucket,
        )
        key = id(post_hook)
        if key not in self._fwd_fns:

            def f(params, batch):
                out = self._model_forward(params, batch)
                return post_hook(out, batch) if post_hook is not None else out

            self._fwd_fns[key] = jax.jit(f)
        fn = self._fwd_fns[key]
        outs = []
        for mb in mbs:
            with self._mesh_ctx():
                outs.append(np.asarray(fn(self.params, self._device_batch(mb))))
        return mbu.scatter_back(mbs, outs, input_.bs)

    def generate(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        gconfig: GenerationHyperparameters,
        key: Optional[jax.Array] = None,
        prompt_key: str = "packed_prompts",
        eos_token_id: int = 1,
        pad_token_id: int = 0,
    ) -> Dict[str, np.ndarray]:
        """In-process generation (the reference's non-SGLang path). Groups of
        ``gconfig.n`` samples per prompt are produced by repeating prompts."""
        assert input_.data is not None
        if key is None:
            key = jax.random.PRNGKey(self.opt_step_count)
        offs = input_.offsets(prompt_key)
        lens = input_.total_lens(prompt_key)
        prompts = [
            input_.data[prompt_key][o : o + l] for o, l in zip(offs, lens)
        ]
        if gconfig.n > 1:
            prompts = [p for p in prompts for _ in range(gconfig.n)]
        padded, plens = genmod.pad_prompts(prompts, pad_token_id)
        with self._mesh_ctx():
            out = genmod.generate_batch(
                self.params if self.compute_dtype == jnp.float32
                else self._cast(self.params),
                self.cfg,
                jnp.asarray(padded),
                jnp.asarray(plens),
                key,
                gconfig,
                max_new_tokens=gconfig.max_new_tokens,
                eos_token_id=eos_token_id,
                pad_token_id=pad_token_id,
                attn_impl=self.attn_impl,
            )
        return {k: np.asarray(v) for k, v in out.items()}


# ---------------- backend registration ----------------


@dataclasses.dataclass
class JaxTrainBackend(ModelBackend):
    """Builds a JaxTrainEngine for a Model whose ``module`` is a
    (TransformerConfig, params) pair (what models/hf.py loaders return)."""

    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    mesh: Any = None
    compute_dtype: str = "bfloat16"
    length_bucket: int = 128
    rows_bucket: int = 8
    seqs_bucket: int = 8
    attn_impl: str = "auto"
    remat: bool = False
    train: bool = True

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        cfg, params = model.module
        engine = JaxTrainEngine(
            cfg,
            params,
            opt_cfg=self.optimizer if self.train else None,
            ft_spec=spec,
            mesh=self.mesh,
            compute_dtype=self.compute_dtype,
            length_bucket=self.length_bucket,
            rows_bucket=self.rows_bucket,
            seqs_bucket=self.seqs_bucket,
            attn_impl=self.attn_impl,
            remat=self.remat,
        )
        model.module = engine
        return model


register_backend("jax_train", JaxTrainBackend)
register_backend(
    "jax_inference",
    lambda **kw: JaxTrainBackend(train=False, **kw),
)
