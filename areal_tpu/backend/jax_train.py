"""The optax/GSPMD training engine — areal_tpu's Megatron-backend equivalent.

Parity target: ``realhf/impl/model/backend/megatron.py`` (ReaLMegatronEngine:
microbatched train_batch/forward/generate with global token normalization,
grad-norm stats, lr scheduling) and ``inference.py`` (PipelinableInference-
Engine). TPU-first differences:

 - No DDP/ZeRO wrapper classes: params/opt-state sharding IS the
   PartitionSpec tree (parallel/sharding.py); XLA emits the reduce-scatters
   Megatron's DistributedOptimizer hand-codes.
 - No pipeline-schedule VM (instruction.py/pipe_runner.py): micro-batches
   exist only to bound activation HBM; each one is a full jitted step and
   gradients accumulate across them on device.
 - Mixed precision: params live in f32 (or cfg dtype), compute is cast per
   step to ``compute_dtype`` (bf16 on the MXU); no loss scaling needed.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    FinetuneSpec,
    GenerationHyperparameters,
    Model,
    ModelBackend,
    TrainableEngine,
    register_backend,
)
from areal_tpu.backend import microbatch as mbu
from areal_tpu.base import compile_watch, logging, telemetry
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer
from areal_tpu.models.config import TransformerConfig
from areal_tpu.parallel import pipeline as ppl
from areal_tpu.parallel import sharding as psh
from areal_tpu.system import memwatch

logger = logging.getLogger("backend.jax")

# Canonical home is the dependency-free api.train_config; re-exported here
# because this module historically defined it.
from areal_tpu.api.train_config import OptimizerConfig  # noqa: E402,F401


def build_lr_schedule(cfg: OptimizerConfig, total_steps: int):
    """Warmup + {constant,cosine,linear} decay to min_lr_ratio·lr (parity:
    thirdparty/megatron lr_schduler.py used by the reference backend)."""
    total_steps = max(total_steps, 1)
    warmup = int(cfg.warmup_steps_proportion * total_steps)
    floor = cfg.lr * cfg.min_lr_ratio
    if cfg.lr_scheduler_type == "cosine":
        decay = optax.cosine_decay_schedule(
            cfg.lr, max(total_steps - warmup, 1), alpha=cfg.min_lr_ratio
        )
    elif cfg.lr_scheduler_type == "linear":
        decay = optax.linear_schedule(
            cfg.lr, floor, max(total_steps - warmup, 1)
        )
    else:
        decay = optax.constant_schedule(cfg.lr)
    if warmup > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.lr, warmup), decay], [warmup]
        )
    return decay


def scale_by_adam_mixed(
    b1: float, b2: float, eps: float,
    mu_dtype: Optional[str] = None, nu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """optax.scale_by_adam with BOTH moment storage dtypes configurable
    (optax only exposes mu_dtype). The moment math always runs in f32 —
    only the carried state is cast — so bf16 storage adds rounding noise
    to the state, not to any single update's arithmetic. Reuses optax's
    ScaleByAdamState so checkpointed optimizer trees stay compatible."""

    def _cast(tree, dtype):
        if dtype is None:
            return tree
        dt = jnp.dtype(dtype)
        return jax.tree.map(lambda x: x.astype(dt), tree)

    def init(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params
        )
        nu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params
        )
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu
        )

    def update(updates, state, params=None):
        del params
        f32 = jnp.float32
        mu = jax.tree.map(
            lambda g, m: b1 * m.astype(f32) + (1 - b1) * g.astype(f32),
            updates, state.mu,
        )
        nu = jax.tree.map(
            lambda g, n: b2 * n.astype(f32) + (1 - b2) * g.astype(f32) ** 2,
            updates, state.nu,
        )
        count = state.count + 1
        bc1 = 1 - b1 ** count.astype(f32)
        bc2 = 1 - b2 ** count.astype(f32)
        out = jax.tree.map(
            lambda m, n: (m / bc1) / (jnp.sqrt(n / bc2) + eps), mu, nu
        )
        return out, optax.ScaleByAdamState(
            count=count, mu=_cast(mu, mu_dtype), nu=_cast(nu, nu_dtype)
        )

    return optax.GradientTransformation(init, update)


def build_optimizer(
    cfg: OptimizerConfig, total_steps: int
) -> Tuple[optax.GradientTransformation, Callable]:
    sched = build_lr_schedule(cfg, total_steps)
    assert cfg.type in ("adamw", "sgd"), cfg.type
    if cfg.type == "adamw":
        opt = optax.chain(
            scale_by_adam_mixed(
                cfg.beta1, cfg.beta2, cfg.eps,
                mu_dtype=getattr(cfg, "mu_dtype", None),
                nu_dtype=getattr(cfg, "nu_dtype", None),
            ),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale_by_learning_rate(sched),
        )
    else:
        opt = optax.sgd(sched)
    chain = [opt]
    if cfg.gradient_clipping and cfg.gradient_clipping > 0:
        chain = [optax.clip_by_global_norm(cfg.gradient_clipping)] + chain
    return optax.chain(*chain), sched


# Loss functions receive (logits, batch) and return (loss_sum, stats-sums).
LossFn = Callable[[jnp.ndarray, Dict[str, jnp.ndarray]], Tuple[jnp.ndarray, Dict]]


@dataclasses.dataclass
class UniformBatch:
    """A whole batch resident on device as one [n_mbs·R, L] grid set.

    ``grids``: per-token keys (+ prep outputs); ``seq``: [n_mbs, S] stacked
    per-micro-batch sequence arrays (grid coordinates, masks, scalar keys).
    Host-side layouts stay in ``mbs`` for weights/scatter-back."""

    mbs: List[mbu.MicroBatch]
    R: int
    L: int
    S: int
    grids: Dict[str, jnp.ndarray]
    seq: Dict[str, jnp.ndarray]

    @property
    def n_mbs(self) -> int:
        return len(self.mbs)


class JaxTrainEngine(TrainableEngine):
    """Owns (params, opt_state) on an optional mesh and the jitted steps."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        opt_cfg: Optional[OptimizerConfig] = None,
        ft_spec: Optional[FinetuneSpec] = None,
        mesh=None,
        compute_dtype: str = "bfloat16",
        length_bucket: int = 128,
        rows_bucket: int = 8,
        seqs_bucket: int = 8,
        attn_impl: str = "auto",
        remat: bool = False,
        logprob_chunk: Optional[int] = 512,
        fill_bucket: Optional[int] = None,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.length_bucket = length_bucket
        self.rows_bucket = rows_bucket
        self.seqs_bucket = seqs_bucket
        # Candidate row-length granularity for the packer's fill sweep
        # (None = packer default, min(length_bucket, 128)).
        self.fill_bucket = fill_bucket
        self.attn_impl = attn_impl
        self.remat = remat
        # Column-chunk size for the chunked-logprob head (None disables);
        # only used by losses/hooks that declare wants_token_logprobs.
        self.logprob_chunk = logprob_chunk
        if mesh is not None:
            params = psh.shard_params(params, mesh, cfg)
        else:
            params = jax.tree.map(jnp.asarray, params)
        if opt_cfg is not None:
            # EXPLICIT f32 master params when training. Without this the
            # first optimizer step silently promotes bf16 params to f32
            # anyway (optax's f32 lr scalar infects the update), costing a
            # retrace and a failed-donation copy on step one — and hiding
            # the master-dtype decision. f32 masters are also the quality
            # choice: bf16's ~3 significant digits round away small
            # Adam updates (the reference's Megatron DistributedOptimizer
            # keeps f32 masters for the same reason). Compute still runs
            # in compute_dtype via _cast. (No buffer donation here: the
            # caller's tree must stay valid — callers that need the
            # transient peak gone should drop their reference, as
            # bench.py does.)
            params = jax.tree.map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                params,
            )
        self.params = params
        self.opt_cfg = opt_cfg
        self.tx = None
        self.opt_state = None
        self.lr_schedule = None
        self.opt_step_count = 0
        if opt_cfg is not None:
            total = ft_spec.total_train_steps if ft_spec is not None else 1000
            self.tx, self.lr_schedule = build_optimizer(opt_cfg, total)
            self.opt_state = compile_watch.watched_jit(
                "train/opt_init", jax.jit(self.tx.init)
            )(self.params)
        self._grad_fns: Dict[int, Callable] = {}
        self._fwd_fns: Dict[int, Callable] = {}
        self._apply_fn = None
        # Static gate for MoE router input jitter: train steps thread a
        # per-micro-batch rng key through the batch dict iff this is set
        # (key presence is part of the jit trace, so the gate must not
        # flip per step — it is fixed by the model config).
        self._router_jitter = (
            cfg.moe is not None and cfg.moe.input_jitter_eps > 0
        )

    # -------------- internals --------------

    def _mesh_ctx(self):
        if self.mesh is not None:
            rules = None
            if self.mesh.shape.get("sp", 1) > 1:
                # jax 0.4.x GSPMD miscompiles concatenate/shift ops that
                # get partitioned along a sharded dim (per-shard partials
                # come back summed — a next-token shift mask doubled). So
                # outside manual regions the sequence dim stays UNSHARDED:
                # the ring/pipeline shard_maps reshard at their boundary,
                # sp still shards every transformer layer — only
                # embed/head/loss replicate over the ring.
                rules = psh.rules_without_axes(("sp",))
            return psh.activation_sharding(self.mesh, rules)
        import contextlib

        return contextlib.nullcontext()

    def _unshard_sp(self, x, vocab_tp: bool = False):
        """Gather the sequence dim off the sp ring at the model boundary.

        jax 0.4.x GSPMD miscompiles shift/concat ops along an sp-sharded
        dim (a next-token shift mask came back with every value doubled —
        per-shard partials summed — on pp×sp meshes). Loss and logprob
        code shifts along the sequence dim constantly, so model outputs
        must leave the model with seq unsharded; dp/fsdp/tp stay."""
        if self.mesh is None or self.mesh.shape.get("sp", 1) <= 1:
            return x
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        tail = ["tp" if vocab_tp else None] * (x.ndim - 2)
        spec = P(psh.DATA_AXES, None, *tail)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec)
        )

    def _cast(self, params):
        cd = self.compute_dtype

        def c(x):
            return x.astype(cd) if jnp.issubdtype(x.dtype, jnp.floating) else x

        return jax.tree.map(c, params)

    def _model_forward(
        self, params, batch: Dict[str, jnp.ndarray], with_aux: bool = False
    ):
        out, _, aux = transformer.forward(
            self._cast(params),
            self.cfg,
            batch["tokens"],
            batch["positions"],
            segment_ids=batch["segment_ids"],
            attn_impl=self.attn_impl,
            remat=self.remat,
            return_kv=False,
            return_aux=True,
            rng=batch.get("rng"),
        )
        # Critic values [B, L] are cheap in f32; lm logits [B, L, V] stay in
        # the compute dtype — loss fns upcast per-element inside fused
        # reductions (see ppo_functional.gather_logprobs).
        out = out.astype(jnp.float32) if self.cfg.is_critic else out
        out = self._unshard_sp(out, vocab_tp=not self.cfg.is_critic)
        return (out, aux) if with_aux else out

    def _forward_token_logprobs(self, params, batch: Dict[str, jnp.ndarray],
                                loss_batch=None):
        """[R, L] per-token logprobs with a CHUNKED head: the [R, L, V]
        logits grid never materializes (at a 152k vocab it is the single
        biggest activation, ~2.4GB at [8,1024] incl. its cotangent — the
        reason remat had to be on). Each column-chunk computes its logits
        and gathers its scores under jax.checkpoint, so backward recomputes
        chunk logits instead of storing them — the head matmul is redone
        once (~25% of forward FLOPs at 0.5B) to free the grid; role parity:
        the reference's fused vocab-parallel cross entropy
        (tensor_parallel/modules.py:1060) exists for the same reason.

        ``loss_batch``: the sp-decoupled duplicate of ``batch`` (see
        _get_grad_fn) — label shifts and score masking read from it so
        sharding propagation from the model's sp constraints can never
        reach the shift ops."""
        from areal_tpu.algorithms import ppo_functional as F

        lb = batch if loss_batch is None else loss_batch
        cast = self._cast(params)
        h, _, aux = transformer.forward(
            cast, self.cfg,
            batch["tokens"], batch["positions"],
            segment_ids=batch["segment_ids"],
            attn_impl=self.attn_impl, remat=self.remat,
            return_kv=False, return_aux=True, return_hidden=True,
            rng=batch.get("rng"),
        )
        h = self._unshard_sp(h)
        R, L, D = h.shape
        labels = F.next_token_labels(lb["tokens"])
        C = self.logprob_chunk or L
        if L % C != 0:
            C = L  # bucketing guarantees divisibility in practice

        @jax.checkpoint
        def chunk_scores(h_c, lab_c):
            logits_c = transformer.apply_head(cast, self.cfg, h_c)
            from areal_tpu.ops.xent import gather_logprobs

            return gather_logprobs(logits_c, lab_c)

        if C == L:
            s = chunk_scores(h, labels)
        else:
            n = L // C
            hs = h.reshape(R, n, C, D).transpose(1, 0, 2, 3)
            ls = labels.reshape(R, n, C).transpose(1, 0, 2)
            s = jax.lax.map(lambda args: chunk_scores(*args), (hs, ls))
            s = s.transpose(1, 0, 2).reshape(R, L)
        return F.shift_mask_scores(s, lb["segment_ids"]), aux

    def _use_chunked_logprobs(self, fn) -> bool:
        return (
            self.logprob_chunk is not None
            and not self.cfg.is_critic
            and bool(getattr(fn, "wants_token_logprobs", False))
        )

    def _get_grad_fn(self, loss_fn: LossFn, with_carry: bool) -> Callable:
        """Fused grad + accumulate step, one dispatch per micro-batch.

        ``with_carry``: the (loss, stats, grads) accumulators from the
        previous micro-batch ride through the jit (donated) and the adds
        happen on device — eager tree-map adds between dispatches cost
        ~300ms/step through a remote-device tunnel (measured r3).

        ``scale`` multiplies this micro-batch's loss/grads ("mb" normalize
        scope passes 1/n_mbs); ``aux_scale`` multiplies the MoE balancing
        loss so its total contribution over the whole batch equals one
        aux_total regardless of the micro-batch count.

        Keyed by the function OBJECT (keeps it alive): an id() key could
        be reused by a new closure after GC and silently run stale code.

        ``loss_batch`` is the SAME device buffers as ``batch``, passed as a
        second jit parameter: on sp>1 meshes the model constrains its
        inputs over "sp", and jax 0.4.x GSPMD then miscompiles shift /
        concat ops along the sp-sharded dim in downstream code (next-token
        shift masks came back with per-shard partials summed). Loss fns
        shift along seq constantly. Two HLO parameters are invisible to
        sharding propagation, so loss code reading ``loss_batch`` (and
        model output passed through _unshard_sp) carries no sp pressure —
        zero-copy at call time, the arrays are fed twice.
        """
        key = (loss_fn, with_carry)
        use_lp = self._use_chunked_logprobs(loss_fn)
        if key not in self._grad_fns:

            def f(params, batch, loss_batch, denom, scale, aux_scale,
                  carry=None):
                def lf(p):
                    if use_lp:
                        out, aux = self._forward_token_logprobs(
                            p, batch, loss_batch
                        )
                    else:
                        out, aux = self._model_forward(p, batch, with_aux=True)
                    loss_sum, stats = loss_fn(out, loss_batch)
                    loss = loss_sum / jnp.maximum(denom, 1.0)
                    if aux:
                        # MoE balancing losses (reference utils/moe.py aux
                        # tracker), surfaced under a reserved "moe_" prefix
                        # (train_batch divides the stats by the mb count).
                        loss = loss + aux["aux_total"] * aux_scale
                        stats = dict(stats, **{
                            f"moe_{k}": v for k, v in aux.items()
                        })
                    return loss, stats

                (loss, stats), grads = jax.value_and_grad(lf, has_aux=True)(params)
                loss = loss * scale
                # Cast the scale into each leaf's dtype: a f32 scalar would
                # silently promote bf16 grads to f32 (2x grad + carry HBM).
                grads = jax.tree.map(
                    lambda g: g * scale.astype(g.dtype), grads
                )
                if carry is not None:
                    c_loss, c_stats, c_grads = carry
                    loss = loss + c_loss
                    stats = {
                        k: stats[k] + c_stats[k] if k in c_stats else stats[k]
                        for k in stats
                    }
                    grads = jax.tree.map(jnp.add, grads, c_grads)
                return loss, stats, grads

            donate = (6,) if with_carry else ()
            self._grad_fns[key] = compile_watch.watched_jit(
                "train/grad", jax.jit(f, donate_argnums=donate)
            )
        return self._grad_fns[key]

    def _get_apply_fn(self, skip_rule) -> Callable:
        """Optimizer update with donated buffers and an optional on-device
        early-stop gate.

        ``skip_rule=(num_key, den_key)``: if given, the update is SKIPPED
        (params returned unchanged) when stats[num]/stats[den] > cap — the
        reference's early-stop checks the importance ratio BEFORE stepping
        (ppo_interface.py:735-760).

        Measured note (r2): a single-dispatch lax.scan over stacked
        micro-batches was tried here and LOST ~40% throughput on v5e — the
        param-sized grad carry through the while loop costs more than the
        per-micro-batch dispatches it saves. The per-micro-batch loop with
        async dispatch (no host syncs until the final stats fetch) is the
        fast path on TPU.
        """
        key = ("apply", skip_rule)
        if key in self._grad_fns:
            return self._grad_fns[key]

        def f(params, opt_state, grads, stats, cap):
            gnorm = optax.global_norm(grads)
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if skip_rule is not None:
                num, den = skip_rule
                ratio = stats[num] / jnp.maximum(stats[den], 1.0)
                apply = (cap <= 0.0) | (ratio <= cap)
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(apply, new, old),
                    new_params, params,
                )
                new_opt = jax.tree.map(
                    lambda new, old: jnp.where(apply, new, old)
                    if hasattr(new, "dtype") else new,
                    new_opt, opt_state,
                )
            else:
                apply = jnp.asarray(True)
            return new_params, new_opt, gnorm, apply

        # Donate params + opt_state (aliased into new_params/new_opt) AND
        # grads: no output aliases the grad buffers (XLA warns they are
        # "not usable" as outputs), but donating them still lets the
        # optimizer's f32 transients reuse those 2 bytes/param in place —
        # measured on the 16G bench chip, withdrawing the grads donation
        # OOMs the apply step.
        self._grad_fns[key] = compile_watch.watched_jit(
            "train/apply", jax.jit(f, donate_argnums=(0, 1, 2))
        )
        return self._grad_fns[key]

    # -------------- upload-once uniform batches --------------
    #
    # Through a remote-device transport (and on any host, as a pipelining
    # win) per-micro-batch h2d transfers are the enemy: a PPO step was
    # spending more wall clock on ~70 small transfers + eager dispatches
    # than on compute (measured r3: 102ms RTT, ~6.5ms/dispatch). The
    # uniform packer (backend/microbatch.py) makes every micro-batch the
    # same [R, L] shape, so the WHOLE batch uploads once as [n_mbs*R, L]
    # grids and each grad step slices its rows on device by a traced index.

    def upload_uniform(
        self, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> "UniformBatch":
        with telemetry.span("train/split_pack"):
            mbs = mbu.split_into_microbatches(
                input_, mb_spec, length_bucket=self.length_bucket,
                rows_bucket=self.rows_bucket, seqs_bucket=self.seqs_bucket,
                fill_bucket=self.fill_bucket,
            )
            telemetry.set_gauge("train/pack_fill", mbu.pack_fill(mbs))
        R, L = mbs[0].layout.shape
        pp_on, ring_on = ppl.pp_engagement(self.mesh, self.cfg, R, L)
        telemetry.set_gauge("train/pp_engaged", pp_on)
        telemetry.set_gauge("train/ring_engaged", ring_on)
        telemetry.set_gauge("train/moe_ep_engaged",
                            self._ep_engagement(R, L, pp_on))
        S = max(len(mb.seq_mask) for mb in mbs)
        S = mbu.packing.round_up(S, self.seqs_bucket)
        grids: Dict[str, jnp.ndarray] = {}
        for k in mbs[0].grids:
            grids[k] = jnp.asarray(
                np.concatenate([mb.grids[k] for mb in mbs], axis=0)
            )
        seq: Dict[str, jnp.ndarray] = {}

        def pad_stack(key, getter, dtype=None):
            rows = []
            for mb in mbs:
                v = np.asarray(getter(mb))
                pad = np.zeros((S,) + v.shape[1:], v.dtype)
                pad[: len(v)] = v
                rows.append(pad)
            seq[key] = jnp.asarray(np.stack(rows))

        pad_stack("seq_rows", lambda mb: mb.seq_rows)
        pad_stack("seq_first_cols", lambda mb: mb.seq_first_cols)
        pad_stack("seq_last_cols", lambda mb: mb.seq_last_cols)
        pad_stack("seq_mask", lambda mb: mb.seq_mask)
        for k in mbs[0].scalars:
            pad_stack(k, lambda mb, k=k: mb.scalars[k])
        return UniformBatch(mbs=mbs, R=R, L=L, S=S, grids=grids, seq=seq)

    def run_prep(
        self,
        ub: "UniformBatch",
        prep_fn: Callable,
        prep_key: object,
        scalars: Optional[Dict[str, float]] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Run a jitted full-batch preprocessing step on device:
        ``prep_fn(grids, seq, R, scalars) -> (extra_grids, out_scalars)``.
        The extra grids are merged into ``ub.grids`` (available to loss
        fns); the returned scalars stay on device for the end-of-step fetch.
        ``scalars`` are dynamic device args (e.g. an adaptive KL coef) so
        their drift never retraces."""
        key = ("prep", prep_key, ub.n_mbs, ub.R)
        if key not in self._grad_fns:
            self._grad_fns[key] = compile_watch.watched_jit(
                "train/prep",
                jax.jit(
                    lambda grids, seq, sc: prep_fn(grids, seq, ub.R, sc)
                ),
            )
        sc = {
            k: jnp.asarray(v, jnp.float32) for k, v in (scalars or {}).items()
        }
        with self._mesh_ctx():
            extra, out_scalars = self._grad_fns[key](ub.grids, ub.seq, sc)
        ub.grids.update(extra)
        return out_scalars

    def _get_sliced_grad_fn(
        self, loss_fn: LossFn, with_carry: bool, R: int
    ) -> Callable:
        """Like _get_grad_fn but takes the FULL uploaded batch and a traced
        micro-batch index; slices its rows/seq-entries on device. ``R`` (rows
        per micro-batch) is part of the cache key: two packings can share the
        total grid shape while slicing differently."""
        key = (loss_fn, with_carry, "sliced", R)
        use_lp = self._use_chunked_logprobs(loss_fn)
        if key not in self._grad_fns:

            def f(params, grids, seq, loss_grids, loss_seq, mb_idx, denom,
                  scale, aux_scale, carry=None):
                # loss_grids/loss_seq are the same buffers as grids/seq fed
                # as separate jit params — the sp-decoupling described in
                # _get_grad_fn; the loss-side slice is re-done from them.
                def slice_mb(gs, sq):
                    b = {
                        k: jax.lax.dynamic_slice_in_dim(g, mb_idx * R, R, 0)
                        for k, g in gs.items()
                    }
                    for k, v in sq.items():
                        b[k] = jax.lax.dynamic_index_in_dim(
                            v, mb_idx, 0, keepdims=False
                        )
                    return b

                batch = slice_mb(grids, seq)
                loss_batch = slice_mb(loss_grids, loss_seq)

                def lf(p):
                    if use_lp:
                        out, aux = self._forward_token_logprobs(
                            p, batch, loss_batch
                        )
                    else:
                        out, aux = self._model_forward(p, batch, with_aux=True)
                    loss_sum, stats = loss_fn(out, loss_batch)
                    loss = loss_sum / jnp.maximum(denom, 1.0)
                    if aux:
                        loss = loss + aux["aux_total"] * aux_scale
                        stats = dict(stats, **{
                            f"moe_{k}": v for k, v in aux.items()
                        })
                    return loss, stats

                (loss, stats), grads = jax.value_and_grad(lf, has_aux=True)(params)
                loss = loss * scale
                grads = jax.tree.map(
                    lambda g: g * scale.astype(g.dtype), grads
                )
                if carry is not None:
                    c_loss, c_stats, c_grads = carry
                    loss = loss + c_loss
                    stats = {
                        k: stats[k] + c_stats[k] if k in c_stats else stats[k]
                        for k in stats
                    }
                    grads = jax.tree.map(jnp.add, grads, c_grads)
                return loss, stats, grads

            donate = (9,) if with_carry else ()
            self._grad_fns[key] = compile_watch.watched_jit(
                "train/grad_sliced", jax.jit(f, donate_argnums=donate)
            )
        return self._grad_fns[key]

    def train_uniform(
        self,
        ub: "UniformBatch",
        loss_fn: LossFn,
        loss_weight_fn: Callable[[mbu.MicroBatch], float],
        mb_indices: Optional[List[int]] = None,
        token_normalize_scope: str = "global",
        skip_update_rule: Optional[Tuple[str, str, float]] = None,
        extra_fetch: Optional[Dict[str, jnp.ndarray]] = None,
    ) -> Dict[str, float]:
        """One optimizer step over the micro-batches ``mb_indices`` (default
        all) of an uploaded batch: n_mbs grad dispatches + 1 apply + ONE
        host sync. See train_batch for semantics."""
        assert self.tx is not None, "engine built without an optimizer"
        idxs = list(mb_indices) if mb_indices is not None else list(range(ub.n_mbs))
        weights = [float(loss_weight_fn(ub.mbs[i])) for i in idxs]
        total_w = sum(weights)
        rule = None
        cap = 0.0
        if skip_update_rule is not None and skip_update_rule[2]:
            rule = (skip_update_rule[0], skip_update_rule[1])
            cap = float(skip_update_rule[2])
        glob = token_normalize_scope == "global"
        scale = 1.0 if glob else 1.0 / len(idxs)
        aux_scale = (1.0 / len(idxs)) if glob else 1.0
        carry = None
        seq = ub.seq
        if self._router_jitter:
            # Stacked per-mb jitter keys ride the seq dict: the sliced grad
            # fn's dynamic_index_in_dim over axis 0 hands each micro-batch
            # its own [2] key (same derivation as train_batch: one base key
            # per optimizer step). ub.seq itself stays untouched so the
            # run_prep jit (keyed on the seq structure) never retraces.
            seq = dict(
                ub.seq,
                rng=jax.random.split(
                    jax.random.PRNGKey(self.opt_step_count), ub.n_mbs
                ),
            )
        with telemetry.span("train/fwd_bwd", n_mbs=len(idxs)), \
                memwatch.watermark("train/fwd_bwd"):
            for i, w in zip(idxs, weights):
                denom = total_w if glob else w
                fn = self._get_sliced_grad_fn(
                    loss_fn, with_carry=carry is not None, R=ub.R
                )
                args = [
                    self.params, ub.grids, seq, dict(ub.grids), dict(seq),
                    jnp.asarray(i, jnp.int32),
                    jnp.asarray(denom, jnp.float32),
                    jnp.asarray(scale, jnp.float32),
                    jnp.asarray(aux_scale, jnp.float32),
                ]
                if carry is not None:
                    args.append(carry)
                with self._mesh_ctx():
                    carry = fn(*args)
            if telemetry.enabled():
                # Honest fwd-bwd/optimizer split; without telemetry this
                # sync does not exist (one-host-sync-per-step contract).
                jax.block_until_ready(carry)
        loss_acc, stats_acc, grads_acc = carry
        with telemetry.span("train/optimizer"):
            with self._mesh_ctx():
                self.params, self.opt_state, gnorm, applied = \
                    self._get_apply_fn(rule)(
                        self.params, self.opt_state, grads_acc,
                        dict(stats_acc), jnp.asarray(cap, jnp.float32),
                    )
            applied_lr = float(self.lr_schedule(self.opt_step_count))
            fetched = jax.device_get({
                **stats_acc, **(extra_fetch or {}), "loss": loss_acc,
                "grad_norm": gnorm, "update_applied": applied,
            })
        if bool(fetched["update_applied"]):
            self.opt_step_count += 1
        out = self._finish_stats(fetched, len(idxs))
        out["lr"] = applied_lr
        out["total_tokens"] = float(sum(ub.mbs[i].n_tokens for i in idxs))
        out["loss_weight"] = total_w
        telemetry.inc("train/tokens", out["total_tokens"])
        telemetry.inc("train/optimizer_steps",
                      1.0 if bool(fetched["update_applied"]) else 0.0)
        return out

    def _ep_engagement(self, batch: int, seq_len: int, pp_on: float) -> float:
        """0/1 gauge: will the MoE all-to-all expert-parallel path engage
        for this shape? Mirrors the forward gate (transformer._block):
        never inside pipeline stages (already-manual regions — there GSPMD
        alone handles the ep-sharded weights), otherwise moe.ep_eligible
        on the engine mesh."""
        from areal_tpu.models import moe as moe_mod

        if pp_on:
            return 0.0
        return float(moe_mod.ep_eligible(
            self.mesh, getattr(self.cfg, "moe", None), batch, seq_len
        ))

    # Per-expert routed-load shares cluster around 1/E — log-ish buckets.
    _EXPERT_LOAD_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.02, 0.05,
                            0.1, 0.2, 0.5, 1.0)

    def _finish_stats(self, fetched: Dict[str, Any],
                      n_mbs: int) -> Dict[str, float]:
        """Host-side stat post-processing shared by train_batch and the
        uniform path. Vector-valued stats — the [E] ``moe_expert_load``
        histogram — are split off BEFORE scalar conversion (float() on a
        vector raises) and published as a telemetry distribution; "moe_"
        stats are per-mb means accumulated as sums, so divide by the mb
        count; the routing-health scalars also land on the scrape as
        ``train/moe_*`` gauges (docs/observability.md; the sentinel
        ``expert_collapse`` rule baselines the load ratio)."""
        n_mbs = max(n_mbs, 1)
        vec = {k: v for k, v in fetched.items()
               if getattr(v, "ndim", 0) > 0 and np.size(v) > 1}
        out = {k: float(v) for k, v in fetched.items() if k not in vec}
        for k in out:
            if k.startswith("moe_"):
                out[k] /= n_mbs
        load = vec.get("moe_expert_load")
        if load is not None:
            for share in np.asarray(load, np.float64).reshape(-1) / n_mbs:
                telemetry.observe("train/moe_expert_load_dist",
                                  float(share),
                                  buckets=self._EXPERT_LOAD_BUCKETS)
        for stat, gauge in (
            ("moe_dropped_frac", "train/moe_dropped_frac"),
            ("moe_expert_load_ratio", "train/moe_expert_load_ratio"),
        ):
            if stat in out:
                telemetry.set_gauge(gauge, out[stat])
        return out

    def _device_batch(self, mb: mbu.MicroBatch) -> Dict[str, jnp.ndarray]:
        d: Dict[str, jnp.ndarray] = {}
        for k, v in mb.grids.items():
            d[k] = jnp.asarray(v)
        for k, v in mb.scalars.items():
            d[k] = jnp.asarray(v)
        d["seq_rows"] = jnp.asarray(mb.seq_rows)
        d["seq_first_cols"] = jnp.asarray(mb.seq_first_cols)
        d["seq_last_cols"] = jnp.asarray(mb.seq_last_cols)
        d["seq_mask"] = jnp.asarray(mb.seq_mask)
        return d

    # -------------- TrainableEngine API --------------

    def train_batch(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: LossFn,
        loss_weight_fn: Callable[[mbu.MicroBatch], float],
        token_normalize_scope: str = "global",
        version_steps: int = 0,
        skip_update_rule: Optional[Tuple[str, str, float]] = None,
    ) -> Dict[str, float]:
        """Grad-accumulate over micro-batches, single optimizer step — one
        jitted dispatch (scan over stacked micro-batches, donated buffers).

        ``loss_fn`` must return the SUM of per-token losses; it is divided by
        the total ``loss_weight_fn`` mass of the whole batch ("global" scope,
        reference megatron.py:410-494) or of each micro-batch ("mb").

        ``skip_update_rule=(num_key, den_key, cap)``: skip the optimizer
        update when stats[num]/stats[den] > cap (the reference's PPO
        early-stop checks the importance ratio BEFORE stepping). The
        returned stats carry ``update_applied`` ∈ {0.0, 1.0}."""
        assert self.tx is not None, "engine built without an optimizer"
        with telemetry.span("train/split_pack"):
            mbs = mbu.split_into_microbatches(
                input_, mb_spec, length_bucket=self.length_bucket,
                rows_bucket=self.rows_bucket, seqs_bucket=self.seqs_bucket,
                fill_bucket=self.fill_bucket,
            )
            telemetry.set_gauge("train/pack_fill", mbu.pack_fill(mbs))
        mb_rows, mb_len = mbs[0].layout.shape
        pp_on, ring_on = ppl.pp_engagement(self.mesh, self.cfg, mb_rows,
                                           mb_len)
        telemetry.set_gauge("train/pp_engaged", pp_on)
        telemetry.set_gauge("train/ring_engaged", ring_on)
        telemetry.set_gauge("train/moe_ep_engaged",
                            self._ep_engagement(mb_rows, mb_len, pp_on))
        weights = [float(loss_weight_fn(mb)) for mb in mbs]
        total_w = sum(weights)
        rule = None
        cap = 0.0
        if skip_update_rule is not None and skip_update_rule[2]:
            rule = (skip_update_rule[0], skip_update_rule[1])
            cap = float(skip_update_rule[2])

        n_mbs = len(mbs)
        glob = token_normalize_scope == "global"
        scale = 1.0 if glob else 1.0 / n_mbs
        aux_scale = (1.0 / n_mbs) if glob else 1.0
        carry = None
        # Router jitter: one deterministic base key per optimizer step,
        # folded with the micro-batch index so every mb perturbs the router
        # input independently (moe_mlp). batch["rng"] is only present when
        # the model config enables jitter — key presence is trace-static.
        jitter_key = (
            jax.random.PRNGKey(self.opt_step_count)
            if self._router_jitter else None
        )
        with telemetry.span("train/fwd_bwd", n_mbs=n_mbs), \
                memwatch.watermark("train/fwd_bwd"):
            for i, (mb, w) in enumerate(zip(mbs, weights)):
                denom = total_w if glob else w
                batch = self._device_batch(mb)
                if jitter_key is not None:
                    batch["rng"] = jax.random.fold_in(jitter_key, i)
                grad_fn = self._get_grad_fn(loss_fn,
                                            with_carry=carry is not None)
                args = [
                    self.params, batch, dict(batch),
                    jnp.asarray(denom, jnp.float32),
                    jnp.asarray(scale, jnp.float32),
                    jnp.asarray(aux_scale, jnp.float32),
                ]
                if carry is not None:
                    args.append(carry)
                with self._mesh_ctx():
                    carry = grad_fn(*args)
            if telemetry.enabled():
                # Drain the async dispatch so the fwd-bwd/optimizer split is
                # honest; without telemetry nothing syncs here (no passive
                # overhead on the hot path).
                jax.block_until_ready(carry)
        loss_acc, stats_acc, grads_acc = carry

        with telemetry.span("train/optimizer"):
            with self._mesh_ctx():
                self.params, self.opt_state, gnorm, applied = \
                    self._get_apply_fn(rule)(
                        self.params, self.opt_state, grads_acc,
                        dict(stats_acc), jnp.asarray(cap, jnp.float32),
                    )
            # optax evaluated the schedule at the PRE-increment count.
            applied_lr = float(self.lr_schedule(self.opt_step_count))
            # ONE host round trip for all scalars (each float() would be a
            # separate device→host sync — expensive through the tunnel).
            fetched = jax.device_get({
                **stats_acc, "loss": loss_acc, "grad_norm": gnorm,
                "update_applied": applied,
            })
        # A skipped (early-stopped) update must not advance the LR schedule:
        # optax's internal count is an array leaf and was reverted by the
        # gate; keep the host-side mirror in lockstep (reference
        # abandon-minibatch semantics).
        if bool(fetched["update_applied"]):
            self.opt_step_count += 1
        # Engine bookkeeping keys are written AFTER the user stats and would
        # clobber same-named loss_fn stats — keep them namespaced.
        out = self._finish_stats(fetched, len(mbs))
        out["lr"] = applied_lr
        out["total_tokens"] = float(sum(mb.n_tokens for mb in mbs))
        out["loss_weight"] = total_w
        telemetry.inc("train/tokens", out["total_tokens"])
        telemetry.inc("train/optimizer_steps",
                      1.0 if bool(fetched["update_applied"]) else 0.0)
        return out

    # -------------- train-state checkpointing --------------
    #
    # Parity: the reference saves optimizer shards alongside weights
    # (megatron.py:711-760) so a recovered run continues the SAME
    # optimization trajectory. Leaves are saved positionally (tree_flatten
    # order) — the restoring engine always has the identical structure.

    def save_train_state(self, ckpt_dir: str) -> None:
        from safetensors.numpy import save_file

        from areal_tpu.parallel import distributed as dist

        # Multi-host: every process joins the gather collective; only
        # process 0 touches the filesystem. safetensors (not npz): npz
        # cannot round-trip bf16 leaves (the mixed-dtype Adam moments).
        host_params = dist.allgather_params(self.params)
        host_opt = (
            dist.allgather_params(self.opt_state)
            if self.opt_state is not None else None
        )
        if jax.process_index() != 0:
            return
        os.makedirs(ckpt_dir, exist_ok=True)
        p_leaves = jax.tree_util.tree_leaves(host_params)
        save_file(
            {f"p{i}": np.ascontiguousarray(x) for i, x in
             enumerate(p_leaves)},
            os.path.join(ckpt_dir, "params.safetensors"),
        )
        if host_opt is not None:
            o_leaves = jax.tree_util.tree_leaves(host_opt)
            save_file(
                {
                    **{f"o{i}": np.ascontiguousarray(x)
                       for i, x in enumerate(o_leaves)},
                    "opt_step_count": np.asarray(self.opt_step_count),
                },
                os.path.join(ckpt_dir, "opt_state.safetensors"),
            )

    @staticmethod
    def _load_leaf_file(path: str) -> Dict[str, np.ndarray]:
        from safetensors.numpy import load_file

        if os.path.exists(path):
            return load_file(path)
        legacy = path.replace(".safetensors", ".npz")
        if os.path.exists(legacy):  # pre-r5 checkpoints
            with np.load(legacy) as z:
                return {k: z[k] for k in z.files}
        raise FileNotFoundError(path)

    @staticmethod
    def _restore_leaf(v, o):
        """Restore one checkpoint leaf in the live leaf's image: dtype,
        SHAPE (safetensors round-trips 0-d scalars as (1,)), and —
        critically — COMMITMENT. Live opt_state leaves are uncommitted
        (jit re-places them next to the sharded params); committing them
        to their current single device on restore pins them there, and
        the next meshed train step dies with "incompatible devices"
        (params on the whole mesh vs opt leaves on device 0)."""
        arr = np.asarray(v).astype(o.dtype).reshape(o.shape)
        if getattr(o, "_committed", True):
            return jax.device_put(arr, o.sharding)
        return jax.device_put(arr)  # device=None: stays uncommitted

    def load_train_state(self, ckpt_dir: str) -> None:
        z = self._load_leaf_file(os.path.join(ckpt_dir, "params.safetensors"))
        leaves = [z[f"p{i}"] for i in range(len(z))]
        treedef = jax.tree_util.tree_structure(self.params)
        old = jax.tree_util.tree_leaves(self.params)
        self.params = jax.tree_util.tree_unflatten(treedef, [
            self._restore_leaf(v, o) for v, o in zip(leaves, old)
        ])
        try:
            z = self._load_leaf_file(
                os.path.join(ckpt_dir, "opt_state.safetensors")
            )
        except FileNotFoundError:
            z = None
        if self.opt_state is not None and z is not None:
            self.opt_step_count = int(z.pop("opt_step_count"))
            o_leaves = [z[f"o{i}"] for i in range(len(z))]
            treedef = jax.tree_util.tree_structure(self.opt_state)
            old = jax.tree_util.tree_leaves(self.opt_state)
            assert len(old) == len(o_leaves), (
                f"optimizer state leaf count changed: ckpt {len(o_leaves)} "
                f"vs live {len(old)}"
            )
            self.opt_state = jax.tree_util.tree_unflatten(treedef, [
                self._restore_leaf(v, o) for v, o in zip(o_leaves, old)
            ])

    def forward(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
    ) -> List[np.ndarray]:
        """Micro-batched inference. ``post_hook(out, batch) -> [B, L, ...]``
        maps raw model output (logits/values) to the per-token quantity —
        applied on device so [B, L, V] logits never reach the host. Returns
        per-sample packed arrays in input order."""
        mbs = mbu.split_into_microbatches(
            input_, mb_spec, length_bucket=self.length_bucket,
            rows_bucket=self.rows_bucket, seqs_bucket=self.seqs_bucket,
            fill_bucket=self.fill_bucket,
        )
        telemetry.set_gauge("infer/pack_fill", mbu.pack_fill(mbs))
        use_lp = self._use_chunked_logprobs(post_hook)
        # use_lp is part of the key: id() of a GC'd hook can be reused by a
        # new hook with a different wants_token_logprobs, which would route
        # through the wrong logprob head via the stale cached jit.
        key = (id(post_hook), use_lp)
        if key not in self._fwd_fns:

            def f(params, batch, loss_batch):
                # loss_batch: sp-decoupled duplicate (see _get_grad_fn) —
                # the post hook is user code that shifts along seq.
                if use_lp:
                    out, _ = self._forward_token_logprobs(
                        params, batch, loss_batch
                    )
                else:
                    out = self._model_forward(params, batch)
                return (post_hook(out, loss_batch)
                        if post_hook is not None else out)

            self._fwd_fns[key] = compile_watch.watched_jit(
                "train/forward", jax.jit(f)
            )
        fn = self._fwd_fns[key]
        outs = []
        for mb in mbs:
            db = self._device_batch(mb)
            with self._mesh_ctx():
                outs.append(np.asarray(fn(self.params, db, dict(db))))
        return mbu.scatter_back(mbs, outs, input_.bs)

    def generate(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        gconfig: GenerationHyperparameters,
        key: Optional[jax.Array] = None,
        prompt_key: str = "packed_prompts",
        eos_token_id: int = 1,
        pad_token_id: int = 0,
    ) -> Dict[str, np.ndarray]:
        """In-process generation (the reference's non-SGLang path). Groups of
        ``gconfig.n`` samples per prompt are produced by repeating prompts."""
        assert input_.data is not None
        if key is None:
            key = jax.random.PRNGKey(self.opt_step_count)
        offs = input_.offsets(prompt_key)
        lens = input_.total_lens(prompt_key)
        prompts = [
            input_.data[prompt_key][o : o + l] for o, l in zip(offs, lens)
        ]
        if gconfig.n > 1:
            prompts = [p for p in prompts for _ in range(gconfig.n)]
        padded, plens = genmod.pad_prompts(prompts, pad_token_id)
        with self._mesh_ctx():
            out = genmod.generate_batch(
                self.params if self.compute_dtype == jnp.float32
                else self._cast(self.params),
                self.cfg,
                jnp.asarray(padded),
                jnp.asarray(plens),
                key,
                gconfig,
                max_new_tokens=gconfig.max_new_tokens,
                eos_token_id=eos_token_id,
                pad_token_id=pad_token_id,
                attn_impl=self.attn_impl,
            )
        return {k: np.asarray(v) for k, v in out.items()}


# ---------------- backend registration ----------------


@dataclasses.dataclass
class JaxTrainBackend(ModelBackend):
    """Builds a JaxTrainEngine for a Model whose ``module`` is a
    (TransformerConfig, params) pair (what models/hf.py loaders return)."""

    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    mesh: Any = None
    # Picklable alternative to ``mesh`` for configs that cross process
    # boundaries (the experiments layer): a ParallelSpec string like
    # "d2f2t2"; the mesh is built lazily in the hosting process.
    parallel_spec: Optional[str] = None
    compute_dtype: str = "bfloat16"
    length_bucket: int = 128
    rows_bucket: int = 8
    seqs_bucket: int = 8
    attn_impl: str = "auto"
    remat: bool = False
    logprob_chunk: Optional[int] = 512
    fill_bucket: Optional[int] = None
    train: bool = True

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        if self.mesh is None and self.parallel_spec:
            from areal_tpu.parallel import mesh as pmesh

            ps = pmesh.ParallelSpec.parse(self.parallel_spec)
            if ps.world_size > 1:
                self.mesh = pmesh.make_mesh(ps)
        cfg, params = model.module
        engine = JaxTrainEngine(
            cfg,
            params,
            opt_cfg=self.optimizer if self.train else None,
            ft_spec=spec,
            mesh=self.mesh,
            compute_dtype=self.compute_dtype,
            length_bucket=self.length_bucket,
            rows_bucket=self.rows_bucket,
            seqs_bucket=self.seqs_bucket,
            attn_impl=self.attn_impl,
            remat=self.remat,
            logprob_chunk=self.logprob_chunk,
            fill_bucket=self.fill_bucket,
        )
        model.module = engine
        return model


register_backend("jax_train", JaxTrainBackend)
register_backend(
    "jax_inference",
    lambda **kw: JaxTrainBackend(train=False, **kw),
)
