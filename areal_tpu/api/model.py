"""Model / backend / interface abstractions and registries.

Parity target: ``realhf/api/core/model_api.py:339-945`` — the triad:
 - ``Model``: bundles params + tokenizer + version for one role shard;
 - ``ModelBackend``: wraps a model into a ``TrainableEngine`` (optimizer,
   jitted train/forward/generate steps);
 - ``ModelInterface``: the algorithm (sft/ppo_actor/ppo_critic/reward)
   operating on an engine + a SequenceSample.

Everything is wired through string registries so system workers never import
implementation classes directly (reference model_api.py:899-956).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from areal_tpu.api.data import MicroBatchSpec, SequenceSample


@dataclasses.dataclass(frozen=True)
class GenerationHyperparameters:
    """Sampling config (reference cli_args.py:531)."""

    n: int = 1
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    temperature: float = 1.0


@dataclasses.dataclass
class FinetuneSpec:
    total_train_epochs: int = 1
    dataset_size: int = 0
    train_batch_size: int = 1

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch

    @property
    def steps_per_epoch(self) -> int:
        return max(
            1, (self.dataset_size + self.train_batch_size - 1) // self.train_batch_size
        )


@dataclasses.dataclass
class ModelVersion:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0


class Model:
    """A live model shard: params pytree + config + tokenizer + version."""

    def __init__(self, name: str, module: Any, tokenizer: Any = None):
        self.name = name
        self.module = module  # backend-specific (e.g. TrainState pytree)
        self.tokenizer = tokenizer
        self.version = ModelVersion()

    def inc_version(self):
        self.version.global_step += 1
        self.version.epoch_step += 1


class TrainableEngine:
    """What a backend produces. Parity: PipelinableEngine
    (reference model_api.py:514) minus torch pipelining — on TPU a single
    jitted step over the mesh subsumes micro-batch scheduling, but we keep
    the micro-batch loop for HBM control."""

    def train_batch(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: Callable,
        loss_weight_fn: Callable,
        token_normalize_scope: str = "global",
        version_steps: int = 0,
    ) -> Dict[str, float]:
        raise NotImplementedError()

    def forward(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
    ):
        raise NotImplementedError()

    def generate(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        gconfig: GenerationHyperparameters,
    ):
        raise NotImplementedError()


class ModelBackend:
    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        raise NotImplementedError()

    def destroy(self, model: Model) -> None:
        pass

    def save(self, model: Model, save_dir: str) -> None:
        raise NotImplementedError()

    def load(self, model: Model, load_dir: str) -> None:
        raise NotImplementedError()


class ModelInterface:
    """Algorithm-level operations. Every method is optional (reference
    model_api.py:759)."""

    def generate(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample | None:
        raise NotImplementedError()

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample | None:
        raise NotImplementedError()

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        raise NotImplementedError()

    def save(self, model: Model, save_dir: str) -> None:
        pass

    # Recover/EMA support
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        pass


# ---------------- registries ----------------

_MODEL_REGISTRY: Dict[str, Callable] = {}
_BACKEND_REGISTRY: Dict[str, Callable] = {}
_INTERFACE_REGISTRY: Dict[str, Callable] = {}
_DATASET_REGISTRY: Dict[str, Callable] = {}
_AGENT_REGISTRY: Dict[str, Callable] = {}
_ENV_REGISTRY: Dict[str, Callable] = {}


def _make(registry: Dict[str, Callable], kind: str, name: str, *args, **kwargs):
    if name not in registry:
        raise KeyError(f"unknown {kind} '{name}'; known: {sorted(registry)}")
    return registry[name](*args, **kwargs)


def register_model(name: str, cls: Callable) -> None:
    _MODEL_REGISTRY[name] = cls


def make_model(name: str, *args, **kwargs):
    return _make(_MODEL_REGISTRY, "model", name, *args, **kwargs)


def register_backend(name: str, cls: Callable) -> None:
    _BACKEND_REGISTRY[name] = cls


def make_backend(name: str, *args, **kwargs) -> ModelBackend:
    return _make(_BACKEND_REGISTRY, "backend", name, *args, **kwargs)


def register_interface(name: str, cls: Callable) -> None:
    _INTERFACE_REGISTRY[name] = cls


def make_interface(name: str, *args, **kwargs) -> ModelInterface:
    return _make(_INTERFACE_REGISTRY, "interface", name, *args, **kwargs)


def register_dataset(name: str, cls: Callable) -> None:
    _DATASET_REGISTRY[name] = cls


def make_dataset(name: str, *args, **kwargs):
    return _make(_DATASET_REGISTRY, "dataset", name, *args, **kwargs)


def register_agent(name: str, cls: Callable) -> None:
    _AGENT_REGISTRY[name] = cls


def make_agent(name: str, *args, **kwargs):
    return _make(_AGENT_REGISTRY, "agent", name, *args, **kwargs)


def register_env(name: str, cls: Callable) -> None:
    _ENV_REGISTRY[name] = cls


def make_env(name: str, *args, **kwargs):
    return _make(_ENV_REGISTRY, "env", name, *args, **kwargs)
