"""Dependency-free leaf config dataclasses shared by the config tree.

These used to live in ``backend/jax_train.py`` and
``system/master_worker.py``, which made ``api.cli_args`` (and therefore
every process that merely parses configs — ``--help``, CPU-only manager /
rollout children) import jax+optax at startup (advisor r2). They are
re-exported from their original homes for compatibility.

Parity targets: reference ``cli_args.py:173`` (OptimizerConfig) and
``cli_args.py:702`` (ExperimentSaveEvalControl).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class OptimizerConfig:
    """Reference cli_args.py:173 (OptimizerConfig)."""

    type: str = "adamw"
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    warmup_steps_proportion: float = 0.02
    lr_scheduler_type: str = "constant"  # constant | cosine | linear
    gradient_clipping: float = 1.0
    # Adam moment storage dtypes (master params are always f32). bf16
    # moments halve optimizer HBM (the update math still runs in f32 per
    # step), but a bf16 default would silently lossy-cast f32 optimizer
    # states on resume — so BOTH default to exact f32; HBM-constrained
    # configs (bench.py on a 16G chip) opt into bf16 explicitly.
    mu_dtype: Optional[str] = "float32"
    nu_dtype: Optional[str] = "float32"


@dataclasses.dataclass
class WeightSyncConfig:
    """Trainer→generation-fleet weight transport (docs/weight_sync.md).

    ``stream`` publishes per-tensor chunks over ZMQ straight from the
    trainer's host cache (system/weight_stream.py) — no checkpoint
    round-trip through the filesystem; ``disk`` is the legacy fallback
    (native-pytree checkpoint under the realloc dir)."""

    transport: str = "stream"  # stream | disk
    # Wire chunk size (MB) for the streamed transport; smaller chunks
    # pipeline finer, larger chunks amortize framing.
    chunk_mb: int = 32
    # In-flight chunk requests per consuming server.
    pipeline_depth: int = 4


@dataclasses.dataclass
class TelemetryConfig:
    """Unified telemetry layer (base/telemetry.py, docs/observability.md).

    Off by default: with ``enabled=False`` every instrumented call site
    routes to a shared no-op sink — no ZMQ sockets, no HTTP servers, no
    span allocation — so the hot paths carry no passive overhead."""

    enabled: bool = False
    # Worker→aggregator snapshot push cadence.
    flush_interval_secs: float = 2.0
    # Aggregated per-snapshot stream; defaults under the experiment log
    # dir (<log>/telemetry.jsonl) when the experiment tree wires it.
    jsonl_path: Optional[str] = None
    # >0: the master's aggregator serves the merged fleet state as
    # Prometheus text on this plain-HTTP port (GET /metrics).
    http_port: int = 0
    # Span buffer bound per process between flushes (oldest drop first).
    max_buffered_spans: int = 4096


@dataclasses.dataclass
class ExperimentSaveEvalControl:
    """Reference cli_args.py:702."""

    total_train_epochs: int = 1
    benchmark_steps: Optional[int] = None  # stop after N train steps
    save_freq_steps: Optional[int] = None
    ckpt_freq_steps: Optional[int] = None
    ckpt_freq_secs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
