"""Dependency-free leaf config dataclasses shared by the config tree.

These used to live in ``backend/jax_train.py`` and
``system/master_worker.py``, which made ``api.cli_args`` (and therefore
every process that merely parses configs — ``--help``, CPU-only manager /
rollout children) import jax+optax at startup (advisor r2). They are
re-exported from their original homes for compatibility.

Parity targets: reference ``cli_args.py:173`` (OptimizerConfig) and
``cli_args.py:702`` (ExperimentSaveEvalControl).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class OptimizerConfig:
    """Reference cli_args.py:173 (OptimizerConfig)."""

    type: str = "adamw"
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    warmup_steps_proportion: float = 0.02
    lr_scheduler_type: str = "constant"  # constant | cosine | linear
    gradient_clipping: float = 1.0
    # Adam moment storage dtypes (master params are always f32). bf16
    # moments halve optimizer HBM (the update math still runs in f32 per
    # step), but a bf16 default would silently lossy-cast f32 optimizer
    # states on resume — so BOTH default to exact f32; HBM-constrained
    # configs (bench.py on a 16G chip) opt into bf16 explicitly.
    mu_dtype: Optional[str] = "float32"
    nu_dtype: Optional[str] = "float32"


@dataclasses.dataclass
class WeightSyncConfig:
    """Trainer→generation-fleet weight transport (docs/weight_sync.md).

    ``stream`` publishes per-tensor chunks over ZMQ straight from the
    trainer's host cache (system/weight_stream.py) — no checkpoint
    round-trip through the filesystem; ``disk`` is the legacy fallback
    (native-pytree checkpoint under the realloc dir); ``device`` keeps
    the weights on device end to end — the trainer reshards its live
    params into the generation fleet's layout (parallel/reshard.py) and
    servers swap them in with zero host hops. ``device`` requires the
    trainer and generation fleet to share one JAX runtime."""

    transport: str = "stream"  # stream | disk | device
    # Wire chunk size (MB) for the streamed transport; smaller chunks
    # pipeline finer, larger chunks amortize framing.
    chunk_mb: int = 32
    # In-flight chunk requests per consuming server.
    pipeline_depth: int = 4
    # Device transport: transfer-group byte budget (MB) for the mesh→mesh
    # reshard — peak extra HBM during a publish is ~one group of
    # target-layout leaves (docs/weight_sync.md §HBM headroom).
    transfer_group_mb: int = 64
    # Device transport: the generation fleet's ParallelSpec (e.g. "d4t2").
    # None publishes in the ungridded single-device layout — correct for
    # un-meshed generation servers; decoupled experiments thread
    # AllocationMode.gen_spec through here automatically.
    gen_parallel_spec: Optional[str] = None


@dataclasses.dataclass
class TelemetryConfig:
    """Unified telemetry layer (base/telemetry.py, docs/observability.md).

    Off by default: with ``enabled=False`` every instrumented call site
    routes to a shared no-op sink — no ZMQ sockets, no HTTP servers, no
    span allocation — so the hot paths carry no passive overhead."""

    enabled: bool = False
    # Worker→aggregator snapshot push cadence.
    flush_interval_secs: float = 2.0
    # Aggregated per-snapshot stream; defaults under the experiment log
    # dir (<log>/telemetry.jsonl) when the experiment tree wires it.
    jsonl_path: Optional[str] = None
    # >0: the master's aggregator serves the merged fleet state as
    # Prometheus text on this plain-HTTP port (GET /metrics).
    http_port: int = 0
    # Span buffer bound per process between flushes (oldest drop first).
    max_buffered_spans: int = 4096
    # ---- sample-lineage tracing + flight recorder ----
    # Stitched end-to-end traces (one JSON line per trained sample);
    # defaults next to telemetry.jsonl when unset.
    traces_path: Optional[str] = None
    # How long a terminal span waits for sibling workers' slower span
    # flushes before the trace is stitched. Should exceed
    # flush_interval_secs; lower it together with the flush interval.
    stitch_grace_secs: float = 5.0
    # Per-worker crash-evidence ring of recent span/event records
    # (0 disables the ring entirely).
    flight_recorder_len: int = 512
    # Where flight_<worker>.jsonl dumps land on crash/SIGTERM/eviction.
    # None: no crash hooks are installed (on-demand dumps still work —
    # the trigger request carries its own directory).
    flight_dir: Optional[str] = None


@dataclasses.dataclass
class GoodputConfig:
    """Goodput ledger (system/goodput.py, docs/observability.md §Goodput).

    Off by default: with ``enabled=False`` every instrumented worker gets
    the shared null ledger — no per-transition clock reads, no counters,
    no MFU math — so the hot paths carry zero new work and the Prometheus
    scrape is bit-identical to a build without the ledger. Enabled
    (requires ``telemetry.enabled``), each worker classifies its wall
    clock into ``compute / comm / data_wait / idle`` monotonic counters
    (``goodput_secs_total{state=...}`` on the scrape, so Prometheus
    ``rate()`` yields live utilization fractions), the trainer and
    generation servers export live achieved-TFLOP/s + MFU gauges against
    the per-generation peak table (``base/monitor.py``), and the master's
    TelemetryAggregator stitches fleet goodput (useful chip-seconds /
    total chip-seconds, split trainer vs generation side) onto the merged
    scrape and ``telemetry.jsonl``."""

    enabled: bool = False
    # Minimum interval between counter exports from a ledger into its
    # telemetry registry (transitions between exports only accrue
    # host-side floats).
    export_interval_secs: float = 1.0
    # Override the per-chip peak FLOP/s used for live MFU gauges; 0 =
    # auto-detect from the device kind (monitor.device_peak_flops). On an
    # unknown device kind the MFU gauges degrade to achieved-TFLOP/s-only
    # with a one-time warning — set this to restore MFU (e.g. CPU tests,
    # unlisted hardware).
    peak_flops_override: float = 0.0


@dataclasses.dataclass
class CompileWatchConfig:
    """Compile & HBM observatory (base/compile_watch.py +
    system/memwatch.py, docs/observability.md §Compile & memory).

    Off by default: with ``enabled=False`` every ``watched_jit`` site
    gets the raw jitted function back (zero wrappers, zero per-call
    work), no device memory_stats poll ever runs, and the Prometheus
    scrape is bit-identical to a build without the observatory. Enabled
    (requires ``telemetry.enabled``), every chip-bearing worker records
    per-function compile events (trigger shapes, elapsed seconds,
    cumulative counts, a recompile-storm detector), publishes the
    compile-inflight flag its HeartbeatThread exports so sentinel absence
    rules become compile-aware, samples per-device HBM gauges with
    high-water marks around the big allocators, and the master derives
    fleet rollups plus the recompile_storm / hbm_pressure / compile_stall
    sentinel rules."""

    enabled: bool = False
    # Calls without a new compiled shape before a function counts as
    # shape-STABLE; a new shape after that is a storm event (the signal
    # the recompile_storm sentinel rule rates). Lower it in tests.
    storm_warmup_calls: int = 16
    # Min interval between device memory_stats polls (samples piggyback
    # on worker cadences — the trainer step loop, the generation
    # server's metrics endpoint — so this bounds poll cost, not wakeups).
    mem_sample_interval_secs: float = 10.0


@dataclasses.dataclass
class SentinelConfig:
    """Training-health sentinel (system/sentinel.py,
    docs/observability.md §Alerting).

    Off by default: nothing is constructed — zero threads, sockets, or
    allocations, and the merged Prometheus scrape is bit-identical to a
    build without the sentinel. Enabled (requires ``telemetry.enabled``),
    the master's TelemetryAggregator hosts a rule engine that evaluates a
    declarative rule pack (threshold / rate-of-change /
    rolling-baseline-deviation / absence-of-signal predicates, each with
    a ``for:`` hold duration, severity, and per-rule cooldown) over the
    merged fleet telemetry and the trainer's per-step training-dynamics
    series. Firing alerts land in ``alerts.jsonl``, export as
    ``areal_alerts_total{rule,severity}`` / ``areal_alert_active`` on the
    merged scrape, and capture evidence (fleet flight dumps, pinned trace
    ids, the triggering metric window, optional profiler capture) into
    ``evidence/<rule>-<ts>/`` while the anomaly is still live."""

    enabled: bool = False
    # Rule evaluation cadence inside the aggregator's ingest loop.
    eval_interval_secs: float = 1.0
    # Include the built-in divergence-signature rule pack
    # (system/sentinel.DEFAULT_RULES; table in docs/observability.md).
    default_rules: bool = True
    # Extra rules (dicts in the rule grammar; validated at parse time —
    # unknown metrics, non-positive durations, and duplicate ids are
    # rejected with an error naming the rule). Primarily set via YAML.
    rules: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # A source (one worker's reading of a metric) that has not reported
    # a value within this window is dropped from rule aggregation — a
    # scaled-down/evicted worker's last gauge must not pin a max/sum
    # aggregate (and a false alert) forever.
    source_expiry_secs: float = 120.0
    # Alert stream; defaults next to telemetry.jsonl.
    alerts_path: Optional[str] = None
    # Per-alert evidence bundles; defaults to <log>/evidence.
    evidence_dir: Optional[str] = None
    # Hard cap on bundles per run (beyond it alerts still fire and
    # export, but capture is skipped and counted).
    max_evidence_bundles: int = 8
    # Critical alerts also request an on-demand jax.profiler capture on
    # the trainer into the bundle (off by default: a capture costs real
    # trainer time exactly when the run is struggling).
    profile_on_critical: bool = False
    profile_secs: float = 5.0
    # How many recent stitched trace ids to pin into each bundle.
    pinned_traces: int = 8
    # Rules with action=pause may command a master pause at the next
    # step boundary (WorkerControl panel). Off by default — an operator
    # must opt into the sentinel stopping a run.
    allow_pause: bool = False
    # Critical alerts publish an autoscale-inhibit hint so the fleet
    # does not scale up into a diverging run (system/autoscaler).
    autoscale_inhibit: bool = True
    inhibit_secs: float = 300.0


@dataclasses.dataclass
class ServingConfig:
    """Generation-fleet serving engine (system/serving.py, docs/serving.md).

    Off by default, like telemetry: with ``enabled=False`` the generation
    server behaves exactly like the legacy rollout-only decode loop — one
    FIFO queue without admission limits, no cross-request KV reuse, and
    the legacy unbounded ``kv_bucket``-multiple capacity rounding. The
    distinct-compiled-shapes gauge is tracked either way."""

    enabled: bool = False
    # ---- admission control (per request class; 0 = unbounded) ----
    # Bounded queues replace unbounded pending growth: a full class queue
    # rejects with HTTP 429 + a Retry-After hint instead of absorbing an
    # arbitrarily deep backlog the SLOs could never recover from.
    queue_limit_rollout: int = 512
    queue_limit_interactive: int = 64
    queue_limit_eval: int = 128
    retry_after_secs: float = 0.5
    # Fraction of each drained batch reserved for the lowest-priority
    # class (rollout) while it has waiters, clamped to [0, 1]. Strict
    # priority alone would let sustained interactive/eval load starve
    # rollouts indefinitely and stall training data production; 0
    # restores strict priority.
    min_rollout_share: float = 0.25
    # ---- cross-request prefix-reuse KV ----
    # Seed a new request's decode state from another request's retained
    # KV when their token prefixes overlap (system prompts, shared
    # few-shot preambles, group sampling over one prompt).
    prefix_reuse: bool = True
    # Shared prefixes shorter than this re-prefill: the clone/extend
    # dispatch costs more than the prefill it would save.
    min_prefix_tokens: int = 4
    # ---- bounded compile shapes (VERDICT #9) ----
    # Decode chunk lengths are rounded UP to one of these buckets (empty =
    # a factor-4 geometric ladder down from chunk_tokens, so small-budget
    # batches scan a small chunk); per-row budgets stop shorter requests
    # early so rounding up never over-generates.
    chunk_buckets: List[int] = dataclasses.field(default_factory=list)
    # Decode/prefill batch rows are padded up to one of these buckets
    # (empty = powers of two up to max_batch_size).
    row_buckets: List[int] = dataclasses.field(default_factory=list)
    # KV capacities are kv_bucket * 2^k up to this ceiling; prompts that
    # cannot fit are rejected at admission (HTTP 413) instead of minting a
    # fresh compiled shape per length.
    max_kv_capacity: int = 16384
    # Hard cap on the distinct-compiled-shapes gauge. The policy refuses
    # (at construction) bucket configs whose WORST-CASE shape count —
    # decode (rows x capacities x chunks) + prefill (rows x widths x
    # chunks) + suffix-extend (widths x capacities) — exceeds it, so the
    # gauge can never pass the cap at runtime. The default ladders
    # (geometric capacities/rows/widths, 4-bucket chunk ladder) come to
    # ~480 worst-case; observed counts run far lower.
    max_compiled_shapes: int = 512


@dataclasses.dataclass
class RewardServiceConfig:
    """Sandboxed reward service — the sixth worker kind
    (system/reward_worker.py + rewards/service.py, docs/rewards.md).

    Off by default: with ``enabled=False`` reward grading runs exactly the
    legacy local path (rewards/math_verify.py / rewards/code_verify.py on
    the calling worker's thread pool) — bit-identical outputs, no sockets.
    Enabled, the launcher spawns ``n_workers`` CPU reward workers; each
    hosts an HTTP sandbox fleet member that grades math/code tasks in
    rlimit-guarded subprocess pools, and the rollout/trainer reward paths
    fan out to them (rewards/client.py) with bounded in-flight
    concurrency, capped-exponential retry across surviving replicas, and
    partial-batch degradation to local grading when the fleet is
    unreachable (parity: the reference's 3k-LoC functioncall service,
    ``functioncall/base/call.py:81-235``)."""

    enabled: bool = False
    # Sandbox fleet size (one reward worker process each; CPU-only).
    n_workers: int = 1
    # Fixed port of worker 0 (workers i bind port+i); 0 = random ports,
    # discovered through name_resolve either way.
    port: int = 0
    # ---- worker-side grading ----
    # Concurrent grading slots per worker, clamped to pool_size at
    # runtime (an admitted task must start grading immediately so the
    # wall budget never times executor-queue wait).
    max_inflight: int = 16
    # Grader threads per worker; each code grade additionally runs its
    # own rlimit-guarded subprocess (rewards/code_verify.py).
    pool_size: int = 8
    # Server-side wall budget per task: a grade that overruns returns a
    # 0.0 verdict with verdict="timeout" and bumps reward_timeouts_total.
    # Bounds a WEDGED grader: code tasks floor at their legal worst case
    # (per-case timeout x sampled cases) so slow-but-correct programs
    # never get spurious timeout verdicts (rewards/service.py).
    grade_timeout_secs: float = 30.0
    # Languages this fleet will grade; tasks in other languages return a
    # 0.0 verdict with verdict="unsupported_language" (per-task dispatch:
    # rewards/code_verify.py GRADERS — C++/bash slot in there).
    languages: List[str] = dataclasses.field(
        default_factory=lambda: ["python"]
    )
    # ---- client-side fanout (rewards/client.py) ----
    # In-flight request cap across one batch fanout.
    max_concurrency: int = 64
    # Per-task HTTP timeout (covers queue wait + grading on the worker).
    request_timeout_secs: float = 120.0
    # Retries per task across surviving replicas before degrading.
    max_retries: int = 2
    retry_base_delay_secs: float = 0.2
    retry_max_delay_secs: float = 2.0
    # Degrade to local grading when the fleet is unreachable / a task's
    # retry budget is exhausted. False: failed tasks score 0.0 instead of
    # executing untrusted code in the calling process.
    local_fallback: bool = True


@dataclasses.dataclass
class AutoscaleConfig:
    """Elastic generation-fleet autoscaling (system/autoscaler.py,
    docs/fault_tolerance.md §Autoscaling).

    Off by default. Enabled, the gserver manager hosts a slow control
    loop that computes a target fleet size from live telemetry signals
    (rollout capacity utilization, per-server queue depth, staleness
    gate, time-to-first-chunk SLO misses, weight-fanout ack latency,
    heartbeat ages) with hysteresis + cooldown, publishes the plan
    through name_resolve, and the launcher-side executor spawns
    supervised single-server workers to meet it. Scale-down and
    straggler defense go through the manager's **cordon** state: the
    server stops receiving leases, inflight rollouts drain (or fail
    over), then a WorkerControl-commanded exit reaps the process."""

    enabled: bool = False
    # Fleet-size bounds on the ROUTABLE server count. min_servers should
    # not exceed the baseline fleet unless scale-up capacity exists.
    min_servers: int = 1
    max_servers: int = 4
    # Decision cadence of the manager-side control loop.
    interval_secs: float = 5.0
    # ---- scale-up / scale-down pressure thresholds ----
    # Rollout capacity utilization (running / max_concurrent_rollouts).
    up_utilization: float = 0.85
    down_utilization: float = 0.25
    # Mean per-server decode queue depth (reported by /health).
    queue_high: float = 8.0
    queue_low: float = 1.0
    # Time-to-first-chunk SLO: a server whose recent TTFC EWMA exceeds
    # this is an SLO miss; scale up when >= slo_miss_fraction of the
    # fleet misses. 0 disables the SLO signal.
    slo_ttfc_secs: float = 0.0
    slo_miss_fraction: float = 0.5
    # Weight-fanout ack latency high-water (0 disables): a fleet too
    # busy to ack weight pushes promptly needs more capacity.
    fanout_ack_high_secs: float = 0.0
    # ---- hysteresis + cooldown (both directions move 1 server/step) ----
    up_consecutive: int = 2
    down_consecutive: int = 5
    scale_up_cooldown_secs: float = 30.0
    scale_down_cooldown_secs: float = 120.0
    # ---- cordon-and-drain ----
    # How long a cordoned server may drain its inflight rollouts before
    # the exit proceeds anyway (clients fail over via chunk replay).
    drain_timeout_secs: float = 120.0
    # ---- straggler defense (per-server decode-latency EWMAs) ----
    straggler_defense: bool = True
    # A server is "slow" when its decode EWMA exceeds factor x the
    # median of its peers (self excluded) for consecutive sweeps:
    # deprioritized after straggler_slow_sweeps, cordoned after
    # straggler_cordon_sweeps. Samples below floor_secs are noise.
    straggler_factor: float = 3.0
    straggler_min_probes: int = 5
    straggler_slow_sweeps: int = 2
    straggler_cordon_sweeps: int = 6
    straggler_floor_secs: float = 0.002
    # ---- overload backpressure ----
    # When the fleet is pinned at max_servers and still saturated,
    # /allocate_rollout capacity denials carry this Retry-After hint so
    # rollout workers slow prompt admission instead of hammering the
    # gate every 0.5s.
    backpressure_retry_secs: float = 2.0


@dataclasses.dataclass
class FaultToleranceConfig:
    """Launcher-level supervision + liveness (system/supervisor.py,
    docs/fault_tolerance.md).

    The supervisor classifies child death by failure domain: stateless
    workers (rollout workers, the gen-fleet process) are respawned in
    place with exponential backoff behind a crash-loop circuit breaker;
    stateful workers (trainer) escalate to the whole-experiment
    ``recover_mode=auto`` relaunch. Liveness is grounded in name-resolve
    keepalive leases: supervised workers register their advertisements
    with ``keepalive_ttl_secs`` and heartbeat them from a dedicated
    thread, so a SIGKILLed worker's ghost keys expire instead of being
    addressed forever."""

    # False restores the legacy behavior: ANY child death tears the
    # experiment down (run_experiment's relaunch loop still applies).
    supervise: bool = True
    # Crash-loop circuit breaker: more than this many restarts of one
    # worker inside the rolling window escalates to a full relaunch.
    max_restarts: int = 3
    restart_window_secs: float = 300.0
    # Respawn backoff (per worker, reset outside the window).
    backoff_base_secs: float = 0.5
    backoff_max_secs: float = 30.0
    backoff_multiplier: float = 2.0
    # Liveness lease on worker/stream advertisements (0 disables leases;
    # heartbeats default to ttl/3).
    keepalive_ttl_secs: float = 15.0
    heartbeat_interval_secs: float = 0.0
    # Graceful drain (SIGTERM): budget for pause -> out-of-band recover
    # checkpoint -> orderly exits before falling back to terminate().
    drain_timeout_secs: float = 60.0
    # Backoff between whole-experiment relaunch attempts
    # (run_experiment's recover_mode=auto/fault loop).
    relaunch_backoff_secs: float = 5.0
    relaunch_backoff_max_secs: float = 60.0


@dataclasses.dataclass
class DurabilityConfig:
    """Durable rollout→trainer sample delivery (system/sample_spool.py,
    docs/fault_tolerance.md §Data durability).

    Enabled, every accepted trajectory is fsynced to a per-rollout-worker
    append-only spool BEFORE its prompt is marked consumed, pushes carry
    ``(worker_index, spool_seqno)``, and the trainer acks a seqno back
    only once the sample is trained (optimizer step committed → the
    master's freed-id "clear" forwarding) or durably dropped (too-stale
    replay). A trainer/master death therefore costs replay, not samples:
    the worker re-sends unacked records and the trainer ingests them
    idempotently (dedup by sample id).

    Off by default: no spool is created, no ``_spool`` key is injected,
    and the push wire bytes are bit-identical to the non-durable format
    (pinned by tests/test_sample_spool.py)."""

    enabled: bool = False
    # Spool segment roll size; acked prefixes are deleted whole-segment.
    spool_segment_bytes: int = 8 * 1024 * 1024
    # Total on-disk (and in-memory mirror) cap per worker. Appends past
    # it block the submitting rollout — backpressure, not sample loss.
    spool_max_bytes: int = 256 * 1024 * 1024
    # A record unacked this long after its last send is re-sent with the
    # replay flag (covers trainer restarts and lost acks).
    resend_timeout_secs: float = 30.0
    # Replayed samples re-enter a staleness gate at the trainer: a
    # replay whose version_end lags the current trained version by more
    # than this many versions is durably dropped (and acked), counted in
    # spool/replay_stale_dropped. Negative disables the gate.
    replay_staleness_limit: int = 8
    # On clean worker exit, wait this long for in-flight acks so the
    # spool drains instead of replaying next incarnation.
    drain_timeout_secs: float = 5.0
    # Bounded-retry budget for a blocked ZMQ push (streams.ZmqPusher);
    # with durability on only the background sender ever blocks.
    push_block_secs: float = 120.0


@dataclasses.dataclass
class ExperimentSaveEvalControl:
    """Reference cli_args.py:702."""

    total_train_epochs: int = 1
    benchmark_steps: Optional[int] = None  # stop after N train steps
    save_freq_steps: Optional[int] = None
    ckpt_freq_steps: Optional[int] = None
    ckpt_freq_secs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
