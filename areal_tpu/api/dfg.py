"""Dataflow graph of Model Function Calls (MFCs).

Parity target: ``realhf/api/core/dfg.py:56,237`` — nodes are MFCs
(generate / inference / train_step on a named model role with declared
input/output data keys); edges are derived automatically from key
producer→consumer relations; hooks describe parameter reallocation /
offload / save around a node.

No networkx dependency: the graph is small (≤ ~10 nodes), plain dicts.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Set, Tuple

from areal_tpu.api.data import MicroBatchSpec


class MFCInterfaceType(enum.Enum):
    GENERATE = "generate"
    INFERENCE = "inference"
    TRAIN_STEP = "train_step"


@dataclasses.dataclass
class MFCHook:
    pass


@dataclasses.dataclass
class ParamReallocHook(MFCHook):
    """Sync params from/to another model role (EMA or weight publishing)."""

    source: Optional[str] = None
    target: Optional[str] = None
    eta: float = 1.0  # target := eta * source + (1-eta) * target


# NOTE: the reference also defines an OffloadHook (dfg.py:42) to evict
# model weights to host RAM between MFCs under GPU memory pressure. There
# is deliberately no TPU analogue: roles share chips through GSPMD
# sharding + buffer donation, and XLA owns HBM residency — an explicit
# offload hook would fight the compiler, not help it.


@dataclasses.dataclass
class WeightUpdateHook(MFCHook):
    """Publish trainer weights for the generation fleet (the disk/ICI
    weight-sync path; reference: gserver weight update in §3.5)."""

    role: str = "actor"


@dataclasses.dataclass
class ModelInterfaceAbstraction:
    type_: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class MFCDef:
    name: str
    model_name: str
    interface_type: MFCInterfaceType
    interface_impl: ModelInterfaceAbstraction
    input_keys: Tuple[str, ...] = ()
    output_keys: Tuple[str, ...] = ()
    input_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)
    output_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)
    n_seqs: int = 1
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    min_n_seqs_per_pass: float = 1.0
    balanced_dp: bool = False
    log_return_value: bool = False
    pre_hooks: List[MFCHook] = dataclasses.field(default_factory=list)
    post_hooks: List[MFCHook] = dataclasses.field(default_factory=list)

    # filled by build_graph
    _parents: List[str] = dataclasses.field(default_factory=list)
    _children: List[str] = dataclasses.field(default_factory=list)

    @property
    def is_src(self) -> bool:
        return not self._parents

    @property
    def is_dst(self) -> bool:
        return not self._children

    @property
    def parents(self) -> List[str]:
        return list(self._parents)

    @property
    def children(self) -> List[str]:
        return list(self._children)


@dataclasses.dataclass
class DataFlowGraph:
    nodes: Dict[str, MFCDef]
    edges: List[Tuple[str, str, Set[str]]]  # (producer, consumer, keys)

    def topological_order(self) -> List[str]:
        indeg = {n: len(self.nodes[n]._parents) for n in self.nodes}
        order = []
        ready = sorted([n for n, d in indeg.items() if d == 0])
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in sorted(set(self.nodes[n]._children)):
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
            ready.sort()
        if len(order) != len(self.nodes):
            raise ValueError("DFG has a cycle")
        return order

    @property
    def source_keys(self) -> Set[str]:
        """Keys that must come from the dataset (consumed but never produced)."""
        produced = set()
        for n in self.nodes.values():
            produced |= {n.output_key_remap.get(k, k) for k in n.output_keys}
        needed = set()
        for n in self.nodes.values():
            needed |= set(n.input_keys)
        return needed - produced

    @property
    def model_names(self) -> Set[str]:
        return {n.model_name for n in self.nodes.values()}


def build_graph(mfcs: List[MFCDef], verbose: bool = False) -> DataFlowGraph:
    """Derive edges from output-key → input-key matches (after remaps).

    A consumer depends on the producer of each of its input keys; keys with no
    producer are dataset keys. Mirrors reference dfg.py:237.
    """
    by_name = {m.name: m for m in mfcs}
    if len(by_name) != len(mfcs):
        raise ValueError("duplicate MFC names")
    producers: Dict[str, str] = {}
    for m in mfcs:
        for k in m.output_keys:
            k = m.output_key_remap.get(k, k)
            if k in producers:
                raise ValueError(
                    f"key {k} produced by both {producers[k]} and {m.name}"
                )
            producers[k] = m.name
    edges: Dict[Tuple[str, str], Set[str]] = {}
    for m in mfcs:
        m._parents.clear()
        m._children.clear()
    for m in mfcs:
        for k in m.input_keys:
            src = producers.get(k)
            if src is None or src == m.name:
                continue
            edges.setdefault((src, m.name), set()).add(k)
    for (src, dst), keys in edges.items():
        by_name[src]._children.append(dst)
        by_name[dst]._parents.append(src)
    g = DataFlowGraph(
        nodes=by_name,
        edges=[(s, d, k) for (s, d), k in sorted(edges.items())],
    )
    g.topological_order()  # raises on cycles
    return g
