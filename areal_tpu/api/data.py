"""SequenceSample — the packed variable-length batch container.

Functional parity target: the reference's ``realhf/api/core/data_api.py:105``
(SequenceSample): the single data contract between every pair of components —
datasets, the master buffer (metadata-only view), DP dispatch, interfaces,
and the rollout→trainer stream (JSON codec).

Design notes for TPU:
 - Host-side container is numpy (never jax) so the control plane touches no
   device. Device placement happens at the interface boundary where packed
   arrays are bucketed/padded to static shapes before ``jit``.
 - A sample may hold several sequences per key (grouped generation: n answers
   per prompt), hence ``seqlens[key]`` is a list (per sample) of lists (per
   sequence-in-group) of ints. Scalar-per-sequence keys (e.g. rewards) use
   seqlen == number of scalars.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from areal_tpu.base import datapack

__all__ = ["SequenceSample", "MicroBatchSpec"]


@dataclasses.dataclass
class MicroBatchSpec:
    """Micro-batch splitting spec (reference: realhf/api/cli_args.py:16).

    ``n_mbs`` is the minimum number of micro-batches; ``max_tokens_per_mb``
    additionally caps the token count of each micro-batch (FFD packing).
    """

    n_mbs: int = 1
    max_tokens_per_mb: Optional[int] = None


def _as_nested(seqlens) -> List[List[int]]:
    out = []
    for s in seqlens:
        if isinstance(s, (int, np.integer)):
            out.append([int(s)])
        else:
            out.append([int(x) for x in s])
    return out


@dataclasses.dataclass
class SequenceSample:
    ids: List[Hashable]
    keys: Set[str]
    seqlens: Dict[str, List[List[int]]]
    data: Optional[Dict[str, Optional[np.ndarray]]] = None
    metadata: Dict[str, List[Any]] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.keys = set(self.keys)
        self.ids = list(self.ids)
        bs = len(self.ids)
        if len(set(self.ids)) != bs:
            raise ValueError(f"duplicate sample ids: {self.ids}")
        for k in self.keys:
            if k not in self.seqlens:
                raise ValueError(f"missing seqlens for key {k}")
            self.seqlens[k] = _as_nested(self.seqlens[k])
            if len(self.seqlens[k]) != bs:
                raise ValueError(
                    f"seqlens[{k}] has {len(self.seqlens[k])} entries != bs {bs}"
                )
        if self.data is not None:
            for k in self.keys:
                v = self.data.get(k)
                if v is None:
                    continue
                v = np.asarray(v)
                total = sum(sum(s) for s in self.seqlens[k])
                if v.shape[0] != total:
                    raise ValueError(
                        f"data[{k}] has leading dim {v.shape[0]}, expected {total}"
                    )
                self.data[k] = v
        for k, v in self.metadata.items():
            if not isinstance(v, list) or len(v) != bs:
                raise ValueError(f"metadata[{k}] must be a list of len bs={bs}")

    # ------------ constructors ------------
    @classmethod
    def from_default(
        cls,
        ids: Sequence[Hashable],
        data: Dict[str, np.ndarray],
        seqlens: Sequence[int],
        metadata: Optional[Dict[str, List[Any]]] = None,
    ) -> "SequenceSample":
        """Build a sample where every 'token-shaped' key shares ``seqlens`` and
        every 'scalar-shaped' key (leading dim == batch size) gets seqlen 1.
        """
        ids = list(ids)
        bs = len(ids)
        seqlens = [int(s) for s in seqlens]
        total = sum(seqlens)
        sls: Dict[str, List[List[int]]] = {}
        datad: Dict[str, np.ndarray] = {}
        for k, v in data.items():
            v = np.asarray(v)
            if v.shape[0] == total:
                sls[k] = [[s] for s in seqlens]
            elif v.shape[0] == bs:
                sls[k] = [[1]] * bs
            else:
                raise ValueError(
                    f"cannot infer seqlens for key {k}: leading dim {v.shape[0]} "
                    f"is neither total tokens {total} nor bs {bs}"
                )
            datad[k] = v
        return cls(
            ids=ids,
            keys=set(data.keys()),
            seqlens=sls,
            data=datad,
            metadata=metadata or {},
        )

    # ------------ views ------------
    @property
    def bs(self) -> int:
        return len(self.ids)

    def total_lens(self, key: Optional[str] = None) -> np.ndarray:
        """Per-sample total length for a key (default: the main token key)."""
        key = key or self._main_key()
        return np.array([sum(s) for s in self.seqlens[key]], dtype=np.int64)

    def _main_key(self) -> str:
        for cand in ("packed_input_ids", "packed_prompts", "input_ids"):
            if cand in self.keys:
                return cand
        # fall back to the key with the largest token count
        return max(self.keys, key=lambda k: sum(sum(s) for s in self.seqlens[k]))

    def meta(self) -> "SequenceSample":
        """Metadata-only copy (what the master worker holds; reference
        data_api.py:160-168)."""
        return SequenceSample(
            ids=list(self.ids),
            keys=set(self.keys),
            seqlens={k: [list(s) for s in v] for k, v in self.seqlens.items()},
            data=None,
            metadata={k: list(v) for k, v in self.metadata.items()},
        )

    def offsets(self, key: str) -> np.ndarray:
        """Start offset of each sample's packed span for ``key``."""
        lens = [sum(s) for s in self.seqlens[key]]
        return np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)

    def cu_seqlens(self, key: Optional[str] = None) -> np.ndarray:
        """Cumulative *sequence* boundaries (flattening groups) for a key."""
        key = key or self._main_key()
        flat = [s for group in self.seqlens[key] for s in group]
        return np.concatenate([[0], np.cumsum(flat)]).astype(np.int64)

    # ------------ select / split / gather ------------
    def select_idx(self, idx: Sequence[int]) -> "SequenceSample":
        idx = list(idx)
        data = None
        if self.data is not None:
            data = {}
            for k in self.keys:
                v = self.data.get(k)
                if v is None:
                    data[k] = None
                    continue
                offs = self.offsets(k)
                lens = [sum(s) for s in self.seqlens[k]]
                parts = [v[offs[i] : offs[i] + lens[i]] for i in idx]
                data[k] = (
                    np.concatenate(parts) if parts else v[:0]
                )
        return SequenceSample(
            ids=[self.ids[i] for i in idx],
            keys=set(self.keys),
            seqlens={k: [self.seqlens[k][i] for i in idx] for k in self.keys},
            data=data,
            metadata={k: [v[i] for i in idx] for k, v in self.metadata.items()},
        )

    def select_ids(self, ids: Sequence[Hashable]) -> "SequenceSample":
        pos = {i: n for n, i in enumerate(self.ids)}
        return self.select_idx([pos[i] for i in ids])

    def split_groups(self, groups: List[List[int]]) -> List["SequenceSample"]:
        return [self.select_idx(g) for g in groups]

    def split(
        self, k: Optional[int] = None, mb_spec: Optional[MicroBatchSpec] = None
    ) -> Tuple[List["SequenceSample"], List[List[int]]]:
        """Token-balanced split. With ``k``, a non-contiguous balanced k-way
        partition (DP dispatch; reference model_function_call.py:276). With
        ``mb_spec``, FFD packing under max_tokens_per_mb with at least n_mbs
        groups (micro-batching). Returns (samples, index groups)."""
        sizes = self.total_lens()
        if k is not None:
            # Exactly k groups; empty groups possible when bs < k (DP ranks
            # must all be dispatched to, even with zero sequences).
            groups = datapack.balanced_groups(sizes, k)
        else:
            assert mb_spec is not None
            cap = mb_spec.max_tokens_per_mb or max(int(sizes.sum()), 1)
            groups = datapack.ffd_allocate(sizes, cap, min_groups=mb_spec.n_mbs)
        return self.split_groups(groups), groups

    @classmethod
    def gather(cls, samples: Sequence["SequenceSample"], keys=None) -> "SequenceSample":
        if not samples:
            raise ValueError("cannot gather zero samples")
        keys = set(keys) if keys is not None else set(samples[0].keys)
        ids = [i for s in samples for i in s.ids]
        seqlens = {
            k: [sl for s in samples for sl in s.seqlens[k]] for k in keys
        }
        data = None
        if all(s.data is not None for s in samples):
            data = {}
            for k in keys:
                parts = [s.data[k] for s in samples if s.data.get(k) is not None]
                data[k] = np.concatenate(parts) if parts else None
        md_keys = set().union(*[set(s.metadata) for s in samples])
        metadata = {
            k: [x for s in samples for x in s.metadata.get(k, [None] * s.bs)]
            for k in md_keys
        }
        return cls(ids=ids, keys=keys, seqlens=seqlens, data=data, metadata=metadata)

    # ------------ mutation ------------
    def update_(self, other: "SequenceSample") -> None:
        """Merge keys of ``other`` (same ids, any order) into self (the buffer
        amend operation; reference buffer.py:308)."""
        other = other.select_ids(self.ids)
        self.keys |= other.keys
        self.seqlens.update(other.seqlens)
        if self.data is not None and other.data is not None:
            self.data.update(other.data)
        for k, v in other.metadata.items():
            self.metadata[k] = v

    def remap_keys_(self, remap: Dict[str, str]) -> None:
        for src, dst in remap.items():
            if src not in self.keys:
                continue
            self.keys.discard(src)
            self.keys.add(dst)
            self.seqlens[dst] = self.seqlens.pop(src)
            if self.data is not None and src in self.data:
                self.data[dst] = self.data.pop(src)

    # ------------ codec (rollout → trainer ZMQ JSON) ------------
    def as_json_compatible(self) -> dict:
        assert self.data is not None
        return {
            "ids": list(self.ids),
            "keys": sorted(self.keys),
            "seqlens": {k: self.seqlens[k] for k in self.keys},
            "data": {
                k: (None if self.data.get(k) is None else self.data[k].tolist())
                for k in self.keys
            },
            "dtypes": {
                k: (None if self.data.get(k) is None else str(self.data[k].dtype))
                for k in self.keys
            },
            "metadata": self.metadata,
        }

    @classmethod
    def from_json_compatible(cls, d: dict) -> "SequenceSample":
        data = {
            k: (None if v is None else np.asarray(v, dtype=d["dtypes"][k]))
            for k, v in d["data"].items()
        }
        return cls(
            ids=d["ids"],
            keys=set(d["keys"]),
            seqlens=d["seqlens"],
            data=data,
            metadata=d.get("metadata", {}),
        )

    def __repr__(self):
        return (
            f"SequenceSample(bs={self.bs}, keys={sorted(self.keys)}, "
            f"meta_only={self.data is None})"
        )
