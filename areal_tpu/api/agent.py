"""Agent and environment abstractions for rollout workers.

Parity targets: ``realhf/api/core/agent_api.py:15`` (queue-based
``Agent.collect_trajectory(prompt, env, obs_queue, act_queue)``) and
``realhf/api/core/env_api.py:8`` (``EnvironmentService.step/reset``).
The queue indirection decouples agent logic from the inference transport:
the rollout worker feeds obs_queue → generation client, and generation
outputs → act_queue.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Tuple

from areal_tpu.api.data import SequenceSample


class EnvironmentService:
    async def reset(self, seed: int = 0) -> Any:
        return None

    async def step(self, action: Any) -> Tuple[Any, float, bool, dict]:
        raise NotImplementedError()


class NullEnvironment(EnvironmentService):
    async def step(self, action):
        return None, 0.0, True, {}


class Agent:
    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        """Put generation requests on obs_queue, await grouped outputs from
        act_queue, interact with env for rewards, return trajectory samples
        (possibly empty when filtered)."""
        raise NotImplementedError()
