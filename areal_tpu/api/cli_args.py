"""Experiment configuration tree + CLI/YAML merge.

Parity target: ``realhf/api/cli_args.py`` (1558 LoC) — the single-file
dataclass config tree that hydra merges YAML and dotted CLI overrides onto.
We have no hydra in the TPU image, so this module also implements the merge
itself: :func:`apply_overrides` walks dotted ``a.b.c=value`` assignments
onto a (nested) dataclass instance with field-type coercion and typo-safe
errors, and :func:`load_yaml`/:func:`to_yaml_dict` round-trip configs the
way the reference dumps ``config.yaml`` next to each run
(``training/main_async_ppo.py:40-50``).

Field names deliberately mirror the reference so launch commands like
``examples/run_async_ppo.sh`` port verbatim (that IS the compatibility
contract): ``allocation_mode=...``, ``actor.type._class=qwen3``,
``dataset.train_bs_n_seqs=32``, ``ppo.gen.max_new_tokens=4096``,
``actor_train.mb_spec.max_tokens_per_mb=32768``,
``max_head_offpolicyness=4`` …
"""

from __future__ import annotations

import dataclasses
import difflib
import typing
from typing import Any, Dict, List, Optional

from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.model import GenerationHyperparameters  # noqa: F401

# Re-exported so experiment configs can be built from this one module, the
# way everything in the reference imports from realhf.api.cli_args. These
# live in the dependency-free api.train_config so that parsing configs
# never drags in jax/optax (CPU-only children, `--help`).
from areal_tpu.api.train_config import (  # noqa: F401
    AutoscaleConfig,
    CompileWatchConfig,
    DurabilityConfig,
    ExperimentSaveEvalControl,
    FaultToleranceConfig,
    GoodputConfig,
    OptimizerConfig,
    RewardServiceConfig,
    SentinelConfig,
    ServingConfig,
    TelemetryConfig,
    WeightSyncConfig,
)


# --------------------------------------------------------------------------
# leaf config groups
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ModelFamily:
    """Reference cli_args.py:99. ``_class`` picks the HF family converter
    (llama/qwen2/qwen3/...), or "tiny" for fabricated test models."""

    _class: str = "qwen3"
    size: int = 0
    is_critic: bool = False


@dataclasses.dataclass
class ModelTrainEvalConfig:
    """One model role (reference cli_args.py:433).

    TPU notes: ``backend`` is the jax train/inference engine for every
    trainable role; Megatron-only knobs (ddp, overlap_grad_reduce, ...)
    have no analogue under GSPMD and are intentionally absent.
    """

    type: ModelFamily = dataclasses.field(default_factory=ModelFamily)
    path: str = ""  # HF checkpoint dir (or empty with init_from_scratch)
    init_from_scratch: bool = False
    gradient_checkpointing: bool = True
    bf16: bool = True
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=OptimizerConfig
    )
    backend: str = "jax_train"
    # Fabricated tiny model for CPU tests (reference base/testing.py models):
    # e.g. actor.tiny.vocab_size=258. Empty = use `path`.
    tiny: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class MFCConfig:
    """Per-MFC runtime knobs (reference cli_args.py:496)."""

    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)


@dataclasses.dataclass
class PromptOnlyDatasetConfig:
    """Reference cli_args.py:44 (PromptOnlyDatasetConfig)."""

    path: str = ""
    max_prompt_len: int = 1024
    train_bs_n_seqs: int = 256
    fill_to_max_length: bool = False


@dataclasses.dataclass
class PromptAnswerDatasetConfig:
    """SFT dataset (reference cli_args.py:58)."""

    path: str = ""
    max_seqlen: int = 1024
    train_bs_n_seqs: int = 256
    valid_bs_n_seqs: int = 256
    fill_to_max_length: bool = False


from areal_tpu.base.name_resolve import NameResolveConfig  # noqa: F401,E402


@dataclasses.dataclass
class ClusterSpecConfig:
    """Reference cli_args.py:896."""

    fileroot: str = "/tmp/areal_tpu/experiments"
    n_nodes: int = 1
    n_gpus_per_node: int = 8  # chips per host on TPU; name kept for parity
    name_resolve: NameResolveConfig = dataclasses.field(
        default_factory=NameResolveConfig
    )


@dataclasses.dataclass
class WandBConfig:
    """Reference cli_args.py:837 (subset; offline by default on TPU pods)."""

    mode: str = "disabled"
    entity: Optional[str] = None
    project: Optional[str] = None
    name: Optional[str] = None


@dataclasses.dataclass
class TensorBoardConfig:
    """Reference cli_args.py:863."""

    path: Optional[str] = None


@dataclasses.dataclass
class AutomaticEvaluatorConfig:
    """Reference cli_args.py:791 (AutomaticEvaluator)."""

    data_names: str = "aime24"
    max_gen_tokens: int = 32768
    max_concurrent_jobs: int = 1
    eval_job_image: Optional[str] = None
    initial_checkpoint_path: Optional[str] = None
    prompt_type: str = "math-cot"
    # pass@k sampling evaluation (apps/eval_ckpt.py, docs/rewards.md):
    # k>1 draws k temperature-sampled generations per prompt and the
    # evaluator publishes pass@1/pass@k/pass^k per task kind to
    # tensorboard for every saved checkpoint; k=1 keeps the legacy
    # greedy single-sample accuracy.
    eval_k: int = 1
    temperature: float = 0.6


# --------------------------------------------------------------------------
# experiment root
# --------------------------------------------------------------------------


@dataclasses.dataclass
class BaseExperimentConfig:
    """Reference cli_args.py:944 (BaseExperimentConfig).

    ``mode`` on TPU: "local" spawns every worker on this host (tests and
    single-host runs); "ray"/"slurm" are reserved words kept for CLI parity
    and raise until a cluster scheduler lands.
    """

    experiment_name: str = "areal-tpu"
    trial_name: str = ""
    mode: str = "local"
    backend: str = "tpu"  # accepted for parity with `--backend=tpu`
    debug: bool = True
    partition: str = "dev"
    schedule_strategy: str = "empty_first"
    recover_mode: str = "disabled"  # disabled | auto | resume | fault
    recover_retries: int = 1
    ignore_worker_error: bool = False
    allocation_mode: str = ""
    n_nodes: int = 1
    n_gpus_per_node: int = 8
    seed: int = 1
    cluster: ClusterSpecConfig = dataclasses.field(
        default_factory=ClusterSpecConfig
    )
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    wandb: WandBConfig = dataclasses.field(default_factory=WandBConfig)
    tensorboard: TensorBoardConfig = dataclasses.field(
        default_factory=TensorBoardConfig
    )
    auto_eval: bool = False
    auto_eval_config: AutomaticEvaluatorConfig = dataclasses.field(
        default_factory=AutomaticEvaluatorConfig
    )
    # Trainer→generation-fleet weight transport (docs/weight_sync.md):
    # `weight_sync.transport=disk` falls back to the checkpoint round-trip.
    weight_sync: WeightSyncConfig = dataclasses.field(
        default_factory=WeightSyncConfig
    )
    # Unified telemetry layer (docs/observability.md): off by default —
    # `telemetry.enabled=true` turns on cross-worker metric aggregation,
    # rollout trace spans, Prometheus /metrics, and profiler triggers.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Goodput ledger (docs/observability.md §Goodput): off by default —
    # `goodput.enabled=true` (with telemetry on) turns on per-worker
    # compute/comm/data_wait/idle time-in-state counters, live
    # achieved-TFLOP/s + MFU gauges on the trainer and generation
    # servers, and fleet-goodput stitching on the merged scrape.
    goodput: GoodputConfig = dataclasses.field(
        default_factory=GoodputConfig
    )
    # Training-health sentinel (docs/observability.md §Alerting): off by
    # default — `sentinel.enabled=true` (with telemetry on) arms the
    # master-hosted rule engine: streaming anomaly detection over fleet
    # telemetry + per-step training dynamics, alerts.jsonl +
    # areal_alerts_total on the merged scrape, automatic evidence capture
    # (flight dumps, pinned traces, optional profiler), autoscale-inhibit
    # on critical alerts, and opt-in master pause.
    sentinel: SentinelConfig = dataclasses.field(
        default_factory=SentinelConfig
    )
    # Compile & HBM observatory (docs/observability.md §Compile & memory):
    # off by default — `compile_watch.enabled=true` (with telemetry on)
    # wraps the fleet's jit entry points in compile-event tracing with
    # recompile-storm detection, samples per-device HBM gauges with
    # high-water marks around the big allocators, and arms the
    # recompile_storm / hbm_pressure / compile_stall sentinel rules.
    compile_watch: CompileWatchConfig = dataclasses.field(
        default_factory=CompileWatchConfig
    )
    # Generation-fleet serving engine (docs/serving.md): off by default —
    # `serving.enabled=true` turns on request-class admission control,
    # cross-request prefix-reuse KV, bounded compile-shape bucketing, and
    # per-class latency SLO histograms on the generation servers.
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    # Launcher-level supervision + liveness leases (docs/fault_tolerance.md):
    # per-worker respawn with backoff + crash-loop circuit breaker for the
    # stateless domain, graceful SIGTERM drain, keepalive heartbeats.
    fault_tolerance: FaultToleranceConfig = dataclasses.field(
        default_factory=FaultToleranceConfig
    )
    # Elastic generation-fleet autoscaling (docs/fault_tolerance.md
    # §Autoscaling): off by default — `autoscale.enabled=true` turns on
    # the gserver manager's scaling loop (telemetry-driven target size,
    # cordon-and-drain scale-down, straggler defense, overload
    # backpressure) and the launcher-side spawn executor.
    autoscale: AutoscaleConfig = dataclasses.field(
        default_factory=AutoscaleConfig
    )
    # Durable trajectory spool (docs/fault_tolerance.md §Data durability):
    # off by default — `durability.enabled=true` turns on at-least-once
    # rollout→trainer delivery: per-worker fsynced spool written before
    # the prompt is marked consumed, trainer acks on optimizer-step
    # commit (or durable drop), crash-replay with idempotent ingest.
    # Disabled = today's fire-and-forget path, bit-identical wire bytes.
    durability: DurabilityConfig = dataclasses.field(
        default_factory=DurabilityConfig
    )
    # Sandboxed reward service (docs/rewards.md): off by default —
    # `reward_service.enabled=true` spawns the reward-worker fleet and
    # switches rollout/trainer reward grading to HTTP fanout with retry
    # and local-fallback degradation; disabled = exact legacy local
    # grading, bit-identical outputs.
    reward_service: RewardServiceConfig = dataclasses.field(
        default_factory=RewardServiceConfig
    )
    torch_cache_mysophobia: bool = False  # parity no-op (no torch allocator)
    cache_clear_freq: Optional[int] = 10
    # Test-only: use the deterministic mock tokenizer instead of HF.
    mock_tokenizer: bool = False
    # Multi-host trainer: one SPMD process per host via jax.distributed
    # (reference global_comm.py:48). >1 makes the launcher spawn that many
    # trainer processes; with trainer_dist_devices_per_proc they run on the
    # CPU platform with that many virtual devices each (multi-process CPU
    # testing, SURVEY §4).
    trainer_dist_procs: int = 1
    trainer_dist_devices_per_proc: Optional[int] = None

    def resolve_trial_name(self) -> str:
        if not self.trial_name:
            import datetime

            self.trial_name = (
                "run" + datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
            )
        return self.trial_name


# --------------------------------------------------------------------------
# YAML + dotted-override machinery (the hydra replacement)
# --------------------------------------------------------------------------


def _field_map(obj) -> Dict[str, dataclasses.Field]:
    return {f.name: f for f in dataclasses.fields(obj)}


_HINT_CACHE: Dict[type, Dict[str, Any]] = {}


def _field_type(obj, name: str):
    """Resolved (non-string) annotation for a field — modules using
    ``from __future__ import annotations`` store them as strings."""
    cls = type(obj)
    if cls not in _HINT_CACHE:
        try:
            _HINT_CACHE[cls] = typing.get_type_hints(cls)
        except Exception:  # unresolvable forward refs: fall back per-field
            _HINT_CACHE[cls] = {}
    return _HINT_CACHE[cls].get(name, _field_map(obj)[name].type)


def _strip_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(value: str, tp) -> Any:
    """Parse a CLI string into the annotated field type."""
    tp = _strip_optional(tp)
    if value.lower() in ("null", "none"):
        return None
    if tp is bool or tp == "bool":
        if value.lower() in ("1", "true", "yes", "on"):
            return True
        if value.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"cannot parse bool from {value!r}")
    if tp is int or tp == "int":
        return int(value)
    if tp is float or tp == "float":
        return float(value)
    if tp is str or tp == "str":
        return value
    origin = typing.get_origin(tp)
    if origin in (list, List):
        (etp,) = typing.get_args(tp) or (str,)
        if not value:
            return []
        return [_coerce(v.strip(), etp) for v in value.split(",")]
    if origin in (dict, Dict):
        import json

        return json.loads(value)
    if tp is Any:
        import json

        try:
            return json.loads(value)
        except (ValueError, TypeError):
            return value
    raise ValueError(f"don't know how to parse {value!r} as {tp}")


class ConfigError(ValueError):
    pass


def _safe_set(obj, key: str, val):
    """setattr that tolerates frozen dataclasses; returns the (possibly
    new) object holding the assignment."""
    try:
        setattr(obj, key, val)
        return obj
    except dataclasses.FrozenInstanceError:
        return dataclasses.replace(obj, **{key: val})


def _assign(obj, parts: List[str], value: str, path: str):
    fm = _field_map(obj)
    key = parts[0]
    if key not in fm:
        raise ConfigError(_unknown_key_msg(obj, key, path))
    if len(parts) == 1:
        return _safe_set(obj, key, _coerce(value, _field_type(obj, key)))
    child = getattr(obj, key)
    if dataclasses.is_dataclass(child):
        return _safe_set(obj, key, _assign(child, parts[1:], value, path))
    if isinstance(child, dict):
        # dict leaf: remaining path becomes a (typed-by-json) dict key
        child[".".join(parts[1:])] = _coerce(value, Any)
        return obj
    raise ConfigError(f"'{key}' is a leaf; cannot descend into '{path}'")


def _set_dotted(obj, path: str, value: str) -> None:
    if _assign(obj, path.split("."), value, path) is not obj:
        raise ConfigError(
            f"top-level config {type(obj).__name__} must not be frozen"
        )


def _unknown_key_msg(obj, key: str, path: str) -> str:
    names = [f.name for f in dataclasses.fields(obj)]
    close = difflib.get_close_matches(key, names, n=3)
    hint = f" (did you mean: {', '.join(close)}?)" if close else ""
    return (
        f"unknown config key '{path}' on {type(obj).__name__}{hint}; "
        f"valid keys: {', '.join(sorted(names))}"
    )


def apply_overrides(cfg, overrides: List[str]):
    """Apply ``a.b.c=value`` assignments in order. Mutates and returns cfg."""
    for ov in overrides:
        if "=" not in ov:
            raise ConfigError(f"override {ov!r} is not of the form key=value")
        key, _, value = ov.partition("=")
        _set_dotted(cfg, key.strip(), value.strip())
    return cfg


# Launch modes this framework implements. "ray" is descoped (VERDICT #10):
# Ray is not in the TPU image, and the scheduler surface is SlurmClient +
# LocalLauncher — see docs/operations.md §Launching.
VALID_MODES = ("local", "slurm")

# MFC names the PPO experiment graph can schedule (ppo_math_exp.py);
# per-MFC allocation entries must name one of these.
KNOWN_MFCS = (
    "actor_train", "actor_gen", "actor_inf",
    "critic_train", "critic_inf",
    "ref_inf", "rew_inf", "fused_rew_ref_inf",
)


def validate_config(cfg) -> None:
    """Config-parse-time sanity checks, called right after overrides/YAML
    merge (training/_cli.py) and again by the launcher: a bad ``mode``
    must fail while the operator is still at the command line, not after
    workers have been spawned."""
    mode = getattr(cfg, "mode", "local")
    if mode == "ray":
        raise ConfigError(
            "mode='ray' is descoped: Ray is not in the TPU image and there "
            "is no Ray scheduler backend. Use mode=local (single host) or "
            "mode=slurm (cluster) — see docs/operations.md §Launching. A "
            "Ray backend would slot in at apps/launcher.py:run_experiment."
        )
    if mode not in VALID_MODES:
        raise ConfigError(
            f"mode={mode!r} is not supported: valid modes are "
            f"{', '.join(VALID_MODES)} (docs/operations.md §Launching)"
        )
    alloc_str = getattr(cfg, "allocation_mode", "") or ""
    if alloc_str:
        # Lazy import: parallel.mesh pulls in jax, which jax-free tool
        # entrypoints must not pay for unless an allocation is configured.
        from areal_tpu.parallel.mesh import AllocationMode

        try:
            alloc = AllocationMode.parse(alloc_str)
        except ValueError as e:
            raise ConfigError(
                f"invalid allocation_mode {alloc_str!r}: {e}"
            ) from None
        n_devices = (
            getattr(cfg, "n_nodes", 1) * getattr(cfg, "n_gpus_per_node", 8)
        )
        for mfc, spec in sorted(alloc.per_mfc.items()):
            if mfc not in KNOWN_MFCS:
                raise ConfigError(
                    f"allocation_mode names unknown MFC '{mfc}': known "
                    f"MFCs are {', '.join(KNOWN_MFCS)} "
                    f"(experiments/ppo_math_exp.py builds the graph)"
                )
            if spec.world_size > n_devices:
                raise ConfigError(
                    f"allocation_mode MFC '{mfc}': spec '{spec}' needs "
                    f"{spec.world_size} devices but the experiment has "
                    f"n_nodes×n_gpus_per_node = {n_devices}"
                )
        for label, spec in (("global", alloc.global_spec),
                            ("generation", alloc.gen_spec)):
            if spec is not None and spec.world_size > n_devices:
                raise ConfigError(
                    f"allocation_mode {label} spec '{spec}' needs "
                    f"{spec.world_size} devices but the experiment has "
                    f"n_nodes×n_gpus_per_node = {n_devices}"
                )
        # Generation-side specs never ring: the decode hot loop passes
        # allow_ring=False (models/transformer.py) so an sp axis there
        # would silently replicate work at server launch. Fail at parse
        # time with the fix instead.
        gen_specs = [("generation", alloc.gen_spec)]
        gen_specs += [(f"MFC '{m}'", s) for m, s in
                      sorted(alloc.per_mfc.items()) if m == "actor_gen"]
        for label, spec in gen_specs:
            if spec is not None and spec.sp > 1:
                raise ConfigError(
                    f"allocation_mode {label} spec '{spec}' sets sp="
                    f"{spec.sp}, but sequence (ring) parallelism only "
                    "applies to training: the decode hot loop never rings "
                    "(token-at-a-time attention has no sequence dim to "
                    "shard). Move the sp factor into dp or tp for the "
                    "generation fleet — e.g. sp2 -> d2 "
                    "(docs/parallelism.md §PP∘SP)."
                )
            if spec is not None and spec.ep > 1:
                raise ConfigError(
                    f"allocation_mode {label} spec '{spec}' sets ep="
                    f"{spec.ep}, but expert parallelism only applies to "
                    "training: the decode hot loop runs the replicated "
                    "einsum dispatch (models/moe.py never all-to-alls "
                    "under a KV cache). Move the ep factor into dp or tp "
                    "for the generation fleet — e.g. e2 -> d2 "
                    "(docs/parallelism.md §Expert parallelism)."
                )
        # Expert-parallel train specs need a MoE model whose expert count
        # divides over the axis; anything else silently replicates or
        # crashes inside shard_map at step time, so fail at parse time.
        moe_dict = getattr(getattr(cfg, "actor", None), "tiny", None)
        moe_dict = moe_dict.get("moe") if isinstance(moe_dict, dict) else None
        train_specs = [("global", alloc.global_spec)]
        train_specs += [(f"MFC '{m}'", s) for m, s in
                        sorted(alloc.per_mfc.items()) if m != "actor_gen"]
        for label, spec in train_specs:
            if spec is None or spec.ep <= 1:
                continue
            if not isinstance(moe_dict, dict):
                raise ConfigError(
                    f"allocation_mode {label} spec '{spec}' sets ep="
                    f"{spec.ep} but the model is dense (actor.tiny.moe is "
                    "unset): there are no experts to shard. Drop the ep "
                    "factor or configure actor.tiny.moe "
                    "(docs/parallelism.md §Expert parallelism)."
                )
            n_exp = int(moe_dict.get("num_experts", 8))
            if n_exp % spec.ep != 0:
                raise ConfigError(
                    f"allocation_mode {label} spec '{spec}' sets ep="
                    f"{spec.ep}, which does not divide "
                    f"actor.tiny.moe.num_experts={n_exp}: every ep shard "
                    "must own the same number of experts "
                    "(docs/parallelism.md §Expert parallelism)."
                )
    moe_dict = getattr(getattr(cfg, "actor", None), "tiny", None)
    moe_dict = moe_dict.get("moe") if isinstance(moe_dict, dict) else None
    if isinstance(moe_dict, dict):
        cf = float(moe_dict.get("capacity_factor", 2.0))
        if cf <= 0:
            raise ConfigError(
                f"actor.tiny.moe.capacity_factor={cf} must be > 0: the "
                "expert buffer is ceil(top_k * tokens * capacity_factor "
                "/ num_experts) slots, and a non-positive factor drops "
                "every routed token (models/moe.py capacity)."
            )
    nr = getattr(getattr(cfg, "cluster", None), "name_resolve", None)
    if nr is not None and getattr(nr, "type", "nfs") == "etcd3":
        # Same contract as the mode=ray rejection above: the descoped
        # backend must fail while the operator is still at the command
        # line, not as a NotImplementedError after workers spawned.
        raise ConfigError(
            "cluster.name_resolve.type='etcd3' is descoped: no etcd3 "
            "repository is implemented and the etcd3 client package is "
            "not in the TPU image. Use type=nfs (shared filesystem, the "
            "default, works across hosts) or type=memory (single-process "
            "tests). An etcd3 backend would slot in at "
            "base/name_resolve.py:reconfigure."
        )
    asc = getattr(cfg, "autoscale", None)
    if asc is not None and getattr(asc, "enabled", False):
        if asc.min_servers < 1:
            raise ConfigError(
                f"autoscale.min_servers={asc.min_servers} must be >= 1 "
                f"(the fleet can never scale to zero routable servers)"
            )
        if asc.max_servers < asc.min_servers:
            raise ConfigError(
                f"autoscale.max_servers={asc.max_servers} < "
                f"min_servers={asc.min_servers}"
            )
        if asc.interval_secs <= 0:
            raise ConfigError(
                f"autoscale.interval_secs={asc.interval_secs} must be > 0"
            )
        if not 0.0 <= asc.down_utilization < asc.up_utilization:
            raise ConfigError(
                f"autoscale utilization thresholds must satisfy "
                f"0 <= down ({asc.down_utilization}) < up "
                f"({asc.up_utilization}) — equal or inverted thresholds "
                f"make the fleet flap every interval"
            )
        if asc.straggler_defense and asc.straggler_factor <= 1.0:
            raise ConfigError(
                f"autoscale.straggler_factor={asc.straggler_factor} must "
                f"be > 1 (a server is only a straggler when it is slower "
                f"than its peers)"
            )
    serving = getattr(cfg, "serving", None)
    if serving is not None and getattr(serving, "enabled", False):
        # Bad serving bucket lists raise ValueError inside every spawned
        # generation server's __init__; surface them while the operator
        # is still at the command line. policy_from_config is pure
        # bookkeeping (no jax), and experiment_policy_kwargs is the SAME
        # experiment->policy mapping the async experiment wiring feeds
        # into GenerationServerConfig — so this is the exact construction
        # the servers will run, by sharing code rather than replicating
        # the numbers.
        from areal_tpu.system.serving import (
            experiment_policy_kwargs,
            policy_from_config,
        )

        try:
            policy_from_config(serving, **experiment_policy_kwargs(cfg))
        except ValueError as e:
            raise ConfigError(f"invalid serving config: {e}") from None
        share = float(getattr(serving, "min_rollout_share", 0.0))
        if not 0.0 <= share <= 1.0:
            raise ConfigError(
                f"serving.min_rollout_share={share} must be in [0, 1] "
                f"(fraction of each batch reserved for rollout traffic)"
            )
    gp = getattr(cfg, "goodput", None)
    if gp is not None and getattr(gp, "enabled", False):
        tel = getattr(cfg, "telemetry", None)
        if tel is None or not getattr(tel, "enabled", False):
            raise ConfigError(
                "goodput.enabled=true requires telemetry.enabled=true: "
                "the ledger exports through the telemetry registry and "
                "the fleet stitch lives in the master's aggregator — "
                "without telemetry there is nowhere to export "
                "(docs/observability.md §Goodput)"
            )
        if getattr(gp, "export_interval_secs", 1.0) <= 0:
            raise ConfigError(
                f"goodput.export_interval_secs="
                f"{gp.export_interval_secs} must be > 0"
            )
        if getattr(gp, "peak_flops_override", 0.0) < 0:
            raise ConfigError(
                f"goodput.peak_flops_override={gp.peak_flops_override} "
                f"must be >= 0 (0 = auto-detect from the device kind)"
            )
    cw = getattr(cfg, "compile_watch", None)
    if cw is not None and getattr(cw, "enabled", False):
        tel = getattr(cfg, "telemetry", None)
        if tel is None or not getattr(tel, "enabled", False):
            raise ConfigError(
                "compile_watch.enabled=true requires telemetry.enabled=true: "
                "compile events and HBM gauges export through the telemetry "
                "registry and roll up in the master's aggregator — without "
                "telemetry there is nowhere to record them "
                "(docs/observability.md §Compile & memory)"
            )
        if getattr(cw, "storm_warmup_calls", 16) < 1:
            raise ConfigError(
                f"compile_watch.storm_warmup_calls="
                f"{cw.storm_warmup_calls} must be >= 1 (a zero warmup "
                f"would flag every cold-start compile as a storm)"
            )
        if getattr(cw, "mem_sample_interval_secs", 10.0) < 0:
            raise ConfigError(
                f"compile_watch.mem_sample_interval_secs="
                f"{cw.mem_sample_interval_secs} must be >= 0"
            )
        serving = getattr(cfg, "serving", None)
        if serving is not None and getattr(serving, "enabled", False):
            # Unify compiled-shape accounting across serving and training:
            # the serving ShapeBucketPolicy caps its admitted grid set at
            # serving.max_compiled_shapes, but the trainer's microbatch
            # fill sweep contributes its own [R, L] shapes to the SAME
            # compile/distinct_shapes family. Cross-check the worst case
            # at parse time with the sweep's own bound (shared code, not
            # replicated numbers) so an operator who tightened
            # max_compiled_shapes learns which OTHER field defeats it.
            from areal_tpu.backend.microbatch import (
                worst_case_row_candidates,
            )

            max_shapes = int(getattr(serving, "max_compiled_shapes", 0))
            trainer_cands = worst_case_row_candidates()
            if 0 < max_shapes < trainer_cands:
                raise ConfigError(
                    f"serving.max_compiled_shapes={max_shapes} is below "
                    f"the trainer fill sweep's worst-case candidate count "
                    f"({trainer_cands}, from backend/microbatch.py "
                    f"worst_case_row_candidates): the trainer alone could "
                    f"exceed the shape budget the serving policy enforces. "
                    f"Raise serving.max_compiled_shapes to at least "
                    f"{trainer_cands}, or coarsen the trainer's "
                    f"fill_bucket (actor.backend fill_bucket) to shrink "
                    f"the sweep."
                )
    sn = getattr(cfg, "sentinel", None)
    if sn is not None and getattr(sn, "enabled", False):
        tel = getattr(cfg, "telemetry", None)
        if tel is None or not getattr(tel, "enabled", False):
            raise ConfigError(
                "sentinel.enabled=true requires telemetry.enabled=true: "
                "the sentinel lives inside the master's "
                "TelemetryAggregator and evaluates the merged fleet "
                "snapshots — without telemetry there is nothing to watch "
                "(docs/observability.md §Alerting)"
            )
        if getattr(sn, "eval_interval_secs", 1.0) <= 0:
            raise ConfigError(
                f"sentinel.eval_interval_secs="
                f"{sn.eval_interval_secs} must be > 0"
            )
        # Front-run the exact rule-pack construction the master will do:
        # unknown metric names, non-positive for:/cooldown durations, and
        # duplicate rule ids must fail at the command line, naming the
        # offending rule — not inside a spawned master worker.
        from areal_tpu.system.sentinel import rules_from_config

        try:
            rules_from_config(
                sn,
                durability_enabled=getattr(
                    getattr(cfg, "durability", None), "enabled", False
                ),
                compile_watch_enabled=getattr(
                    getattr(cfg, "compile_watch", None), "enabled", False
                ),
            )
        except ValueError as e:
            raise ConfigError(f"invalid sentinel rule pack: {e}") from None
    dur = getattr(cfg, "durability", None)
    if dur is not None and getattr(dur, "enabled", False):
        if dur.spool_segment_bytes <= 0:
            raise ConfigError(
                f"durability.spool_segment_bytes="
                f"{dur.spool_segment_bytes} must be > 0"
            )
        if dur.spool_max_bytes < dur.spool_segment_bytes:
            raise ConfigError(
                f"durability.spool_max_bytes={dur.spool_max_bytes} < "
                f"spool_segment_bytes={dur.spool_segment_bytes}: the "
                f"spool could never roll a full segment"
            )
        if dur.resend_timeout_secs <= 0:
            raise ConfigError(
                f"durability.resend_timeout_secs="
                f"{dur.resend_timeout_secs} must be > 0 (it is the only "
                f"recovery path for a lost ack)"
            )
        if dur.push_block_secs <= 0:
            raise ConfigError(
                f"durability.push_block_secs={dur.push_block_secs} must "
                f"be > 0 (a zero budget fails every send at the HWM)"
            )
    rs = getattr(cfg, "reward_service", None)
    if rs is not None and getattr(rs, "enabled", False):
        if rs.n_workers < 1:
            raise ConfigError(
                f"reward_service.n_workers={rs.n_workers} must be >= 1 "
                f"(an enabled fleet needs at least one sandbox worker)"
            )
        for knob in ("max_inflight", "pool_size", "max_concurrency"):
            if getattr(rs, knob) < 1:
                raise ConfigError(
                    f"reward_service.{knob}={getattr(rs, knob)} must be >= 1"
                )
        for knob in ("grade_timeout_secs", "request_timeout_secs"):
            if getattr(rs, knob) <= 0:
                raise ConfigError(
                    f"reward_service.{knob}={getattr(rs, knob)} must be > 0 "
                    f"(a reward grade must have a finite wall budget)"
                )
        if not rs.languages:
            raise ConfigError(
                "reward_service.languages is empty: an enabled fleet that "
                "grades no language returns 0.0 for every code task — "
                "list at least one of rewards/code_verify.py GRADERS "
                "(e.g. reward_service.languages=python)"
            )
        from areal_tpu.rewards.code_verify import GRADERS

        unknown = [l for l in rs.languages if l not in GRADERS]
        if unknown:
            raise ConfigError(
                f"reward_service.languages={rs.languages}: no grader is "
                f"registered for {unknown} (available: "
                f"{', '.join(sorted(GRADERS))}; new languages register in "
                f"rewards/code_verify.py GRADERS)"
            )


def merge_dict(cfg, d: Dict[str, Any], _path: str = ""):
    """Merge a (nested) plain dict — e.g. parsed YAML — onto a dataclass."""
    fm = _field_map(cfg)
    for k, v in d.items():
        path = f"{_path}.{k}" if _path else k
        if k not in fm:
            raise ConfigError(_unknown_key_msg(cfg, k, path))
        cur = getattr(cfg, k)
        if dataclasses.is_dataclass(cur) and isinstance(v, dict):
            cfg = _safe_set(cfg, k, merge_dict(cur, v, path))
        elif isinstance(v, str) and not isinstance(cur, str) \
                and not dataclasses.is_dataclass(cur):
            cfg = _safe_set(cfg, k, _coerce(v, _field_type(cfg, k)))
        else:
            cfg = _safe_set(cfg, k, v)
    return cfg


def load_yaml(cfg, path: str):
    import yaml

    with open(path) as f:
        d = yaml.safe_load(f) or {}
    return merge_dict(cfg, d)


def to_yaml_dict(cfg) -> Dict[str, Any]:
    """dataclass → plain dict safe for yaml.dump (reference dumps asdict)."""
    out = dataclasses.asdict(cfg)

    def clean(x):
        if isinstance(x, dict):
            return {k: clean(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [clean(v) for v in x]
        if isinstance(x, (str, int, float, bool)) or x is None:
            return x
        return repr(x)

    return clean(out)


def save_yaml(cfg, path: str) -> None:
    import os

    import yaml

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.dump(to_yaml_dict(cfg), f, default_flow_style=False,
                  sort_keys=False)


def print_config_help(cfg, _indent: int = 0) -> None:
    """Recursive ``--help`` printer (reference cli_args.py:1421)."""
    pad = "  " * _indent
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if dataclasses.is_dataclass(v):
            print(f"{pad}{f.name}:  ({type(v).__name__})")
            print_config_help(v, _indent + 1)
        else:
            print(f"{pad}{f.name} = {v!r}")


def get_log_path(cfg: BaseExperimentConfig) -> str:
    """<fileroot>/logs/<experiment>/<trial> (reference constants.get_log_path)."""
    import os

    return os.path.join(
        cfg.cluster.fileroot, "logs", cfg.experiment_name, cfg.trial_name
    )
