"""Host-side packing: SequenceSample (ragged 1-D) ⇄ fixed-shape [B, L] batches.

This is the jit boundary of the trainer. The reference feeds fully-dynamic
packed varlen tensors to flash-attn; on TPU that causes recompilation churn,
so areal_tpu bins sequences into a fixed [B, L] grid (FFD by length), with:
 - ``tokens [B, L]`` int32, right-padded rows of concatenated sequences,
 - ``segment_ids [B, L]`` — 1-based per-row document ids, 0 = padding,
 - ``positions [B, L]`` — restart at 0 at each document (RoPE positions),
and an index layout to scatter per-token device outputs back into the
original packed 1-D host order. Mirrors the role of MicroBatchSpec / FFD in
the reference (realhf/base/datapack.py:153-231), shaped for XLA instead.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.base import datapack


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclasses.dataclass
class PackLayout:
    """Placement of each input sequence in the [B, L] grid."""

    n_rows: int
    row_len: int
    # per sequence i (in input order): (row, start_col)
    placements: List[Tuple[int, int]]
    seqlens: List[int]

    @property
    def shape(self) -> Tuple[int, int]:
        return self.n_rows, self.row_len


def plan_packing(
    seqlens: Sequence[int],
    length_bucket: int = 128,
    row_len: Optional[int] = None,
    min_rows: int = 1,
    rows_multiple: int = 1,
) -> PackLayout:
    seqlens = [int(s) for s in seqlens]
    if row_len is None:
        row_len = round_up(max(seqlens), length_bucket)
    if max(seqlens) > row_len:
        raise ValueError(f"sequence of length {max(seqlens)} exceeds row_len {row_len}")
    groups = datapack.ffd_allocate(seqlens, row_len, min_groups=min_rows)
    n_rows = round_up(max(len(groups), min_rows), rows_multiple)
    placements: List[Tuple[int, int]] = [None] * len(seqlens)  # type: ignore
    for row, group in enumerate(groups):
        col = 0
        for i in group:
            placements[i] = (row, col)
            col += seqlens[i]
    return PackLayout(
        n_rows=n_rows, row_len=row_len, placements=placements, seqlens=seqlens
    )


def batch_from_packed(
    packed: np.ndarray,  # 1-D concatenation over sequences (input order)
    layout: PackLayout,
    fill=0,
) -> np.ndarray:
    B, L = layout.shape
    out = np.full((B, L) + packed.shape[1:], fill, dtype=packed.dtype)
    # Native fast path (csrc/interval_ops.cpp): one C call instead of one
    # Python slice assignment per sequence — this runs for every per-token
    # key of every micro-batch of every train step.
    if packed.ndim == 1 and packed.flags.c_contiguous:
        from areal_tpu.ops import native

        rows = [p[0] for p in layout.placements]
        cols = [p[1] for p in layout.placements]
        lens = list(layout.seqlens)
        offs = np.concatenate([[0], np.cumsum(lens)[:-1]])
        if native.scatter_intervals(packed, out, rows, cols, lens, offs):
            return out
    off = 0
    for (row, col), n in zip(layout.placements, layout.seqlens):
        out[row, col : col + n] = packed[off : off + n]
        off += n
    return out


def packed_from_batch(batch: np.ndarray, layout: PackLayout) -> np.ndarray:
    parts = []
    for (row, col), n in zip(layout.placements, layout.seqlens):
        parts.append(batch[row, col : col + n])
    return np.concatenate(parts, axis=0)


def make_grid(layout: PackLayout) -> Dict[str, np.ndarray]:
    """segment_ids / positions / loss-capable mask for a layout."""
    B, L = layout.shape
    seg = np.zeros((B, L), dtype=np.int32)
    pos = np.zeros((B, L), dtype=np.int32)
    row_doc_count = [0] * B
    for (row, col), n in zip(layout.placements, layout.seqlens):
        row_doc_count[row] += 1
        seg[row, col : col + n] = row_doc_count[row]
        pos[row, col : col + n] = np.arange(n)
    return {"segment_ids": seg, "positions": pos}
