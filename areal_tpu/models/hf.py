"""Bidirectional HF ↔ areal_tpu weight conversion.

Parity target: the reference's per-family converter registry
(``realhf/impl/model/conversion/hf_registry.py:32`` +
``realhf/api/from_hf/{llama,qwen2,qwen3,...}.py``). Families covered here:
llama, qwen2, qwen2.5 (same as qwen2), qwen3, mistral — all share the
rotate-half RoPE / RMSNorm / gated-SiLU skeleton and differ only in flags.

Weights are stacked on a leading layer axis (see models/transformer.py), so
conversion transposes HF's ``[out, in]`` linear layout to ``[in, out]`` and
stacks per-layer tensors.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from areal_tpu.base import logging
from areal_tpu.models.config import TransformerConfig

logger = logging.getLogger("models.hf")

HF_FAMILIES: Dict[str, Callable] = {}


def register_hf_family(name: str):
    def deco(fn):
        HF_FAMILIES[name] = fn
        return fn

    return deco


def config_from_hf(hf_config: Any) -> TransformerConfig:
    """Build a TransformerConfig from a transformers PretrainedConfig."""
    mt = getattr(hf_config, "model_type", "llama")
    if mt not in HF_FAMILIES:
        raise NotImplementedError(f"unsupported HF model family: {mt}")
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    return TransformerConfig(
        n_layers=hf_config.num_hidden_layers,
        hidden_dim=hf_config.hidden_size,
        n_q_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None)
        or hf_config.num_attention_heads,
        head_dim=head_dim,
        intermediate_dim=hf_config.intermediate_size,
        vocab_size=hf_config.vocab_size,
        rotary_base=getattr(hf_config, "rope_theta", 10000.0),
        rms_norm_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        sliding_window=getattr(hf_config, "sliding_window", None)
        if getattr(hf_config, "use_sliding_window", True)
        else None,
        use_attention_bias=mt in ("qwen2",),
        use_qk_norm=mt in ("qwen3",),
    )


for _fam in ("llama", "qwen2", "qwen3", "mistral"):
    register_hf_family(_fam)(config_from_hf)


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t)


def params_from_hf_state_dict(
    sd: Dict[str, Any], cfg: TransformerConfig, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF causal-LM state dict → stacked areal_tpu param pytree (numpy)."""

    def get(name):
        if name in sd:
            return _np(sd[name])
        raise KeyError(f"missing HF weight {name}; have e.g. {list(sd)[:5]}")

    def stack(fmt, transpose=True):
        ws = []
        for i in range(cfg.n_layers):
            w = _np(sd[fmt.format(i=i)])
            ws.append(w.T if transpose and w.ndim == 2 else w)
        return np.stack(ws).astype(dtype)

    layers: Dict[str, np.ndarray] = {
        "ln1": stack("model.layers.{i}.input_layernorm.weight", transpose=False),
        "ln2": stack(
            "model.layers.{i}.post_attention_layernorm.weight", transpose=False
        ),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
        "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
        "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
        "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
    }
    if cfg.use_attention_bias:
        layers["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias", transpose=False)
        layers["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias", transpose=False)
        layers["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias", transpose=False)
    if cfg.use_qk_norm:
        layers["q_norm"] = stack(
            "model.layers.{i}.self_attn.q_norm.weight", transpose=False
        )
        layers["k_norm"] = stack(
            "model.layers.{i}.self_attn.k_norm.weight", transpose=False
        )

    params: Dict[str, Any] = {
        "embedding": get("model.embed_tokens.weight").astype(dtype),
        "layers": layers,
        "final_ln": get("model.norm.weight").astype(dtype),
    }
    if cfg.is_critic:
        if "score.weight" in sd:
            params["value_head"] = get("score.weight").T.astype(dtype)
        else:
            params["value_head"] = np.zeros((cfg.hidden_dim, 1), dtype)
    elif not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T.astype(dtype)
    return params


def params_to_hf_state_dict(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Dict[str, np.ndarray]:
    """Inverse conversion (for publishing weights / HF-format checkpoints)."""

    def unstack(key, name_fmt, transpose=True):
        w = np.asarray(params["layers"][key])
        for i in range(cfg.n_layers):
            wi = w[i]
            yield name_fmt.format(i=i), (wi.T if transpose and wi.ndim == 2 else wi)

    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embedding"]),
        "model.norm.weight": np.asarray(params["final_ln"]),
    }
    mapping = [
        ("ln1", "model.layers.{i}.input_layernorm.weight", False),
        ("ln2", "model.layers.{i}.post_attention_layernorm.weight", False),
        ("wq", "model.layers.{i}.self_attn.q_proj.weight", True),
        ("wk", "model.layers.{i}.self_attn.k_proj.weight", True),
        ("wv", "model.layers.{i}.self_attn.v_proj.weight", True),
        ("wo", "model.layers.{i}.self_attn.o_proj.weight", True),
        ("w_gate", "model.layers.{i}.mlp.gate_proj.weight", True),
        ("w_up", "model.layers.{i}.mlp.up_proj.weight", True),
        ("w_down", "model.layers.{i}.mlp.down_proj.weight", True),
    ]
    if cfg.use_attention_bias:
        mapping += [
            ("bq", "model.layers.{i}.self_attn.q_proj.bias", False),
            ("bk", "model.layers.{i}.self_attn.k_proj.bias", False),
            ("bv", "model.layers.{i}.self_attn.v_proj.bias", False),
        ]
    if cfg.use_qk_norm:
        mapping += [
            ("q_norm", "model.layers.{i}.self_attn.q_norm.weight", False),
            ("k_norm", "model.layers.{i}.self_attn.k_norm.weight", False),
        ]
    for key, fmt, tr in mapping:
        for name, w in unstack(key, fmt, tr):
            sd[name] = w
    if cfg.is_critic:
        sd["score.weight"] = np.asarray(params["value_head"]).T
    elif not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"]).T
    return sd


def load_hf_model(path_or_model, is_critic: bool = False, dtype: str = "float32"):
    """Load (config, params, tokenizer) from an HF model directory or an
    in-memory transformers model (used by tests)."""
    if isinstance(path_or_model, str):
        import transformers

        hf_cfg = transformers.AutoConfig.from_pretrained(path_or_model)
        model = transformers.AutoModelForCausalLM.from_pretrained(path_or_model)
        try:
            tokenizer = transformers.AutoTokenizer.from_pretrained(path_or_model)
        except Exception:
            tokenizer = None
    else:
        model = path_or_model
        hf_cfg = model.config
        tokenizer = None
    import dataclasses

    cfg = dataclasses.replace(config_from_hf(hf_cfg), is_critic=is_critic)
    params = params_from_hf_state_dict(model.state_dict(), cfg, dtype)
    return cfg, params, tokenizer


def save_hf_checkpoint(params, cfg: TransformerConfig, save_dir: str, meta: Optional[dict] = None):
    """Publish weights in a layout consumable by the generation server and by
    HF tooling: one .npz of the HF-named state dict + a config json. (The
    disk weight-sync path; reference saves HF safetensor shards.)"""
    os.makedirs(save_dir, exist_ok=True)
    sd = params_to_hf_state_dict(params, cfg)
    np.savez(os.path.join(save_dir, "model.npz"), **sd)
    import dataclasses

    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(
            {"areal_tpu_config": dataclasses.asdict(cfg), "meta": meta or {}}, f
        )


def load_hf_checkpoint(load_dir: str):
    import dataclasses

    with open(os.path.join(load_dir, "config.json")) as f:
        d = json.load(f)
    from areal_tpu.models.config import MoEConfig

    cd = d["areal_tpu_config"]
    if cd.get("moe"):
        cd["moe"] = MoEConfig(**cd["moe"])
    cfg = TransformerConfig(**cd)
    sd = dict(np.load(os.path.join(load_dir, "model.npz")))
    params = params_from_hf_state_dict(sd, cfg, cfg.dtype)
    return cfg, params
