"""Bidirectional HF ↔ areal_tpu weight conversion + sharded safetensors IO.

Parity target: the reference's per-family converter registry
(``realhf/impl/model/conversion/hf_registry.py:32`` +
``realhf/api/from_hf/{llama,qwen2,qwen3,gemma,gpt2,mistral,mixtral}.py``).
Families covered: llama, qwen2 (qwen2.5), qwen3, mistral, gemma, gpt2,
mixtral, qwen3_moe.

Weights are stacked on a leading layer axis (see models/transformer.py), so
conversion transposes HF's ``[out, in]`` linear layout to ``[in, out]`` and
stacks per-layer tensors. Checkpoints are written as sharded safetensors
with an HF-style index (threaded writers, mirroring the reference's
``saveload_utils.py``) plus a genuine HF ``config.json`` so the output loads
directly in ``transformers.AutoModelForCausalLM``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.base import logging
from areal_tpu.models.config import MoEConfig, TransformerConfig

logger = logging.getLogger("models.hf")

HF_FAMILIES: Dict[str, Callable] = {}


def register_hf_family(name: str):
    def deco(fn):
        HF_FAMILIES[name] = fn
        return fn

    return deco


def _base_kwargs(hf_config: Any) -> Dict[str, Any]:
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    return dict(
        n_layers=hf_config.num_hidden_layers,
        hidden_dim=hf_config.hidden_size,
        n_q_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None)
        or hf_config.num_attention_heads,
        head_dim=head_dim,
        intermediate_dim=hf_config.intermediate_size,
        vocab_size=hf_config.vocab_size,
        rotary_base=getattr(hf_config, "rope_theta", 10000.0),
        rms_norm_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )


def _llama_like(hf_config: Any) -> TransformerConfig:
    mt = getattr(hf_config, "model_type", "llama")
    return TransformerConfig(
        **_base_kwargs(hf_config),
        sliding_window=getattr(hf_config, "sliding_window", None)
        if getattr(hf_config, "use_sliding_window", True)
        else None,
        use_attention_bias=mt in ("qwen2",),
        use_qk_norm=mt in ("qwen3", "qwen3_moe"),
        hf_family=mt,
    )


for _fam in ("llama", "qwen2", "qwen3", "mistral"):
    register_hf_family(_fam)(_llama_like)


@register_hf_family("gemma")
def _gemma_config(hf_config: Any) -> TransformerConfig:
    act = getattr(hf_config, "hidden_activation", None) or "gelu_pytorch_tanh"
    return TransformerConfig(
        **_base_kwargs(hf_config),
        hidden_act="gelu_tanh" if "tanh" in act else "gelu",
        scale_embeddings=True,
        hf_family="gemma",
    )


@register_hf_family("gpt2")
def _gpt2_config(hf_config: Any) -> TransformerConfig:
    d = hf_config.n_embd
    return TransformerConfig(
        n_layers=hf_config.n_layer,
        hidden_dim=d,
        n_q_heads=hf_config.n_head,
        n_kv_heads=hf_config.n_head,
        head_dim=d // hf_config.n_head,
        intermediate_dim=hf_config.n_inner or 4 * d,
        vocab_size=hf_config.vocab_size,
        rms_norm_eps=hf_config.layer_norm_epsilon,
        tie_word_embeddings=True,
        use_attention_bias=True,
        use_attn_output_bias=True,
        hidden_act="gelu_tanh",  # gelu_new
        mlp_type="plain",
        norm_type="layer",
        pos_embedding="learned",
        max_position_embeddings=hf_config.n_positions,
        hf_family="gpt2",
    )


@register_hf_family("mixtral")
def _mixtral_config(hf_config: Any) -> TransformerConfig:
    return TransformerConfig(
        **_base_kwargs(hf_config),
        sliding_window=getattr(hf_config, "sliding_window", None),
        moe=MoEConfig(
            num_experts=hf_config.num_local_experts,
            top_k=hf_config.num_experts_per_tok,
            aux_loss_coeff=getattr(hf_config, "router_aux_loss_coef", 1e-3),
            norm_topk_prob=True,
        ),
        hf_family="mixtral",
    )


@register_hf_family("qwen3_moe")
def _qwen3_moe_config(hf_config: Any) -> TransformerConfig:
    return TransformerConfig(
        **_base_kwargs(hf_config),
        use_qk_norm=True,
        moe=MoEConfig(
            num_experts=hf_config.num_experts,
            top_k=hf_config.num_experts_per_tok,
            routed_intermediate_dim=hf_config.moe_intermediate_size,
            aux_loss_coeff=getattr(hf_config, "router_aux_loss_coef", 1e-3),
            norm_topk_prob=getattr(hf_config, "norm_topk_prob", True),
        ),
        hf_family="qwen3_moe",
    )


def config_from_hf(hf_config: Any) -> TransformerConfig:
    """Build a TransformerConfig from a transformers PretrainedConfig."""
    mt = getattr(hf_config, "model_type", "llama")
    if mt not in HF_FAMILIES:
        raise NotImplementedError(f"unsupported HF model family: {mt}")
    return HF_FAMILIES[mt](hf_config)


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t)


# ---------------- family weight codecs ----------------
#
# Each codec maps between an HF state dict (flat names, [out, in] linears)
# and the stacked areal_tpu pytree. The llama-style codec covers every
# family except gpt2 (fused c_attn + Conv1D layout).


def _llama_mapping(cfg: TransformerConfig) -> List[tuple]:
    """(pytree key, HF name fmt, transpose) for per-layer 2-D/1-D weights."""
    m = [
        ("ln1", "model.layers.{i}.input_layernorm.weight", False),
        ("ln2", "model.layers.{i}.post_attention_layernorm.weight", False),
        ("wq", "model.layers.{i}.self_attn.q_proj.weight", True),
        ("wk", "model.layers.{i}.self_attn.k_proj.weight", True),
        ("wv", "model.layers.{i}.self_attn.v_proj.weight", True),
        ("wo", "model.layers.{i}.self_attn.o_proj.weight", True),
    ]
    if cfg.moe is None:
        m += [
            ("w_gate", "model.layers.{i}.mlp.gate_proj.weight", True),
            ("w_up", "model.layers.{i}.mlp.up_proj.weight", True),
            ("w_down", "model.layers.{i}.mlp.down_proj.weight", True),
        ]
    if cfg.use_attention_bias:
        m += [
            ("bq", "model.layers.{i}.self_attn.q_proj.bias", False),
            ("bk", "model.layers.{i}.self_attn.k_proj.bias", False),
            ("bv", "model.layers.{i}.self_attn.v_proj.bias", False),
        ]
    if cfg.use_qk_norm:
        m += [
            ("q_norm", "model.layers.{i}.self_attn.q_norm.weight", False),
            ("k_norm", "model.layers.{i}.self_attn.k_norm.weight", False),
        ]
    return m


def _moe_names(cfg: TransformerConfig) -> Dict[str, str]:
    if cfg.hf_family == "mixtral":
        return {
            "router": "model.layers.{i}.block_sparse_moe.gate.weight",
            "e_gate": "model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight",
            "e_up": "model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight",
            "e_down": "model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight",
        }
    # qwen3_moe layout
    return {
        "router": "model.layers.{i}.mlp.gate.weight",
        "e_gate": "model.layers.{i}.mlp.experts.{e}.gate_proj.weight",
        "e_up": "model.layers.{i}.mlp.experts.{e}.up_proj.weight",
        "e_down": "model.layers.{i}.mlp.experts.{e}.down_proj.weight",
    }


def _llama_from_sd(
    sd: Dict[str, Any], cfg: TransformerConfig, dtype: str
) -> Dict[str, Any]:
    def get(name):
        if name in sd:
            return _np(sd[name])
        raise KeyError(f"missing HF weight {name}; have e.g. {list(sd)[:5]}")

    def stack(fmt, transpose=True):
        ws = []
        for i in range(cfg.n_layers):
            w = _np(sd[fmt.format(i=i)])
            ws.append(w.T if transpose and w.ndim == 2 else w)
        return np.stack(ws).astype(dtype)

    layers: Dict[str, np.ndarray] = {}
    for key, fmt, tr in _llama_mapping(cfg):
        layers[key] = stack(fmt, transpose=tr)
    if cfg.moe is not None:
        names = _moe_names(cfg)
        E = cfg.moe.num_experts
        layers["router"] = stack(names["router"])  # [n, D, E]
        for key in ("e_gate", "e_up", "e_down"):
            per_layer = []
            for i in range(cfg.n_layers):
                per_layer.append(np.stack([
                    _np(sd[names[key].format(i=i, e=e)]).T for e in range(E)
                ]))
            layers[key] = np.stack(per_layer).astype(dtype)  # [n, E, ., .]
    if cfg.scale_embeddings:  # gemma stores norm weights as (w − 1)
        for k in ("ln1", "ln2"):
            layers[k] = (layers[k] + 1.0).astype(dtype)

    params: Dict[str, Any] = {
        "embedding": get("model.embed_tokens.weight").astype(dtype),
        "layers": layers,
        "final_ln": get("model.norm.weight").astype(dtype),
    }
    if cfg.scale_embeddings:
        params["final_ln"] = (params["final_ln"] + 1.0).astype(dtype)
    if cfg.is_critic:
        if "score.weight" in sd:
            params["value_head"] = get("score.weight").T.astype(dtype)
        else:
            params["value_head"] = np.zeros((cfg.hidden_dim, 1), dtype)
    elif not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T.astype(dtype)
    return params


def _llama_to_sd(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Dict[str, np.ndarray]:
    layers = {k: np.asarray(v) for k, v in params["layers"].items()}
    if cfg.scale_embeddings:  # undo the gemma (w + 1) fold
        layers = dict(layers)
        for k in ("ln1", "ln2"):
            layers[k] = layers[k] - 1.0
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embedding"]),
        "model.norm.weight": np.asarray(params["final_ln"])
        - (1.0 if cfg.scale_embeddings else 0.0),
    }
    for key, fmt, tr in _llama_mapping(cfg):
        w = layers[key]
        for i in range(cfg.n_layers):
            wi = w[i]
            sd[fmt.format(i=i)] = wi.T if tr and wi.ndim == 2 else wi
    if cfg.moe is not None:
        names = _moe_names(cfg)
        for i in range(cfg.n_layers):
            sd[names["router"].format(i=i)] = layers["router"][i].T
            for key in ("e_gate", "e_up", "e_down"):
                for e in range(cfg.moe.num_experts):
                    sd[names[key].format(i=i, e=e)] = layers[key][i, e].T
    if cfg.is_critic:
        sd["score.weight"] = np.asarray(params["value_head"]).T
    elif not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"]).T
    return sd


def _gpt2_from_sd(
    sd: Dict[str, Any], cfg: TransformerConfig, dtype: str
) -> Dict[str, Any]:
    """GPT-2: fused c_attn qkv, Conv1D layout ([in, out] — NO transpose),
    LayerNorm weights+biases, learned positions, 'transformer.' prefix
    (absent when loading from a bare GPT2Model state dict)."""
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    def get(name):
        return _np(sd[pfx + name])

    d = cfg.hidden_dim
    n = cfg.n_layers

    def stack(fmt):
        return np.stack([_np(sd[pfx + fmt.format(i=i)]) for i in range(n)])

    c_attn_w = stack("h.{i}.attn.c_attn.weight")  # [n, d, 3d] Conv1D
    c_attn_b = stack("h.{i}.attn.c_attn.bias")  # [n, 3d]
    layers = {
        "ln1": stack("h.{i}.ln_1.weight").astype(dtype),
        "ln1_b": stack("h.{i}.ln_1.bias").astype(dtype),
        "ln2": stack("h.{i}.ln_2.weight").astype(dtype),
        "ln2_b": stack("h.{i}.ln_2.bias").astype(dtype),
        "wq": c_attn_w[:, :, :d].astype(dtype),
        "wk": c_attn_w[:, :, d : 2 * d].astype(dtype),
        "wv": c_attn_w[:, :, 2 * d :].astype(dtype),
        "bq": c_attn_b[:, :d].astype(dtype),
        "bk": c_attn_b[:, d : 2 * d].astype(dtype),
        "bv": c_attn_b[:, 2 * d :].astype(dtype),
        "wo": stack("h.{i}.attn.c_proj.weight").astype(dtype),
        "bo": stack("h.{i}.attn.c_proj.bias").astype(dtype),
        "w_up": stack("h.{i}.mlp.c_fc.weight").astype(dtype),
        "b_up": stack("h.{i}.mlp.c_fc.bias").astype(dtype),
        "w_down": stack("h.{i}.mlp.c_proj.weight").astype(dtype),
        "b_down": stack("h.{i}.mlp.c_proj.bias").astype(dtype),
    }
    return {
        "embedding": get("wte.weight").astype(dtype),
        "pos_embedding": get("wpe.weight").astype(dtype),
        "layers": layers,
        "final_ln": get("ln_f.weight").astype(dtype),
        "final_ln_b": get("ln_f.bias").astype(dtype),
    }


def _gpt2_to_sd(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Dict[str, np.ndarray]:
    lp = {k: np.asarray(v) for k, v in params["layers"].items()}
    sd = {
        "transformer.wte.weight": np.asarray(params["embedding"]),
        "transformer.wpe.weight": np.asarray(params["pos_embedding"]),
        "transformer.ln_f.weight": np.asarray(params["final_ln"]),
        "transformer.ln_f.bias": np.asarray(params["final_ln_b"]),
    }
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = lp["ln1"][i]
        sd[p + "ln_1.bias"] = lp["ln1_b"][i]
        sd[p + "ln_2.weight"] = lp["ln2"][i]
        sd[p + "ln_2.bias"] = lp["ln2_b"][i]
        sd[p + "attn.c_attn.weight"] = np.concatenate(
            [lp["wq"][i], lp["wk"][i], lp["wv"][i]], axis=1
        )
        sd[p + "attn.c_attn.bias"] = np.concatenate(
            [lp["bq"][i], lp["bk"][i], lp["bv"][i]]
        )
        sd[p + "attn.c_proj.weight"] = lp["wo"][i]
        sd[p + "attn.c_proj.bias"] = lp["bo"][i]
        sd[p + "mlp.c_fc.weight"] = lp["w_up"][i]
        sd[p + "mlp.c_fc.bias"] = lp["b_up"][i]
        sd[p + "mlp.c_proj.weight"] = lp["w_down"][i]
        sd[p + "mlp.c_proj.bias"] = lp["b_down"][i]
    return sd


def params_from_hf_state_dict(
    sd: Dict[str, Any], cfg: TransformerConfig, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF causal-LM state dict → stacked areal_tpu param pytree (numpy)."""
    if cfg.hf_family == "gpt2":
        return _gpt2_from_sd(sd, cfg, dtype)
    return _llama_from_sd(sd, cfg, dtype)


def params_to_hf_state_dict(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Dict[str, np.ndarray]:
    """Inverse conversion (for publishing weights / HF-format checkpoints)."""
    if cfg.hf_family == "gpt2":
        return _gpt2_to_sd(params, cfg)
    return _llama_to_sd(params, cfg)


# ---------------- HF config.json emission ----------------

_HF_ARCH = {
    "llama": "LlamaForCausalLM",
    "qwen2": "Qwen2ForCausalLM",
    "qwen3": "Qwen3ForCausalLM",
    "mistral": "MistralForCausalLM",
    "gemma": "GemmaForCausalLM",
    "gpt2": "GPT2LMHeadModel",
    "mixtral": "MixtralForCausalLM",
    "qwen3_moe": "Qwen3MoeForCausalLM",
}


def hf_config_dict(cfg: TransformerConfig) -> Dict[str, Any]:
    """A transformers-loadable config.json dict for ``cfg``'s family."""
    fam = cfg.hf_family or "llama"
    if fam == "gpt2":
        return {
            "model_type": "gpt2",
            "architectures": ["GPT2LMHeadModel"],
            "n_layer": cfg.n_layers,
            "n_embd": cfg.hidden_dim,
            "n_head": cfg.n_q_heads,
            "n_positions": cfg.max_position_embeddings,
            "n_ctx": cfg.max_position_embeddings,
            "n_inner": cfg.intermediate_dim,
            "vocab_size": cfg.vocab_size,
            "layer_norm_epsilon": cfg.rms_norm_eps,
            "activation_function": "gelu_new",
            "tie_word_embeddings": True,
        }
    d: Dict[str, Any] = {
        "model_type": fam,
        "architectures": [_HF_ARCH.get(fam, "LlamaForCausalLM")],
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "rope_theta": cfg.rotary_base,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_word_embeddings,
        "max_position_embeddings": cfg.max_position_embeddings or 32768,
        "hidden_act": "gelu_pytorch_tanh"
        if cfg.hidden_act == "gelu_tanh" and fam == "gemma"
        else ("silu" if cfg.hidden_act == "silu" else cfg.hidden_act),
        "torch_dtype": "float32",
    }
    if fam == "gemma":
        d["hidden_activation"] = "gelu_pytorch_tanh"
    if cfg.sliding_window is not None:
        d["sliding_window"] = cfg.sliding_window
    if cfg.moe is not None:
        if fam == "mixtral":
            d["num_local_experts"] = cfg.moe.num_experts
            d["num_experts_per_tok"] = cfg.moe.top_k
            d["router_aux_loss_coef"] = cfg.moe.aux_loss_coeff
        else:
            d["num_experts"] = cfg.moe.num_experts
            d["num_experts_per_tok"] = cfg.moe.top_k
            d["moe_intermediate_size"] = (
                cfg.moe.routed_intermediate_dim or cfg.intermediate_dim
            )
            d["norm_topk_prob"] = cfg.moe.norm_topk_prob
            d["router_aux_loss_coef"] = cfg.moe.aux_loss_coeff
            d["decoder_sparse_step"] = 1
            d["mlp_only_layers"] = []
    return d


# ---------------- sharded safetensors IO ----------------

SHARD_BYTES = 4 * 1024**3  # ~4GB per shard, HF convention


def save_hf_state_dict(
    sd: Dict[str, np.ndarray], save_dir: str, shard_bytes: int = SHARD_BYTES,
    n_threads: int = 8,
) -> None:
    """Write ``sd`` as sharded safetensors + index (threaded, one writer per
    shard — parity: reference saveload_utils.py threaded safetensor save)."""
    from safetensors.numpy import save_file

    os.makedirs(save_dir, exist_ok=True)
    shards: List[Dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for k, v in sd.items():
        v = np.ascontiguousarray(v)
        nb = v.nbytes
        if sizes[-1] > 0 and sizes[-1] + nb > shard_bytes:
            shards.append({})
            sizes.append(0)
        shards[-1][k] = v
        sizes[-1] += nb
    n = len(shards)
    if n == 1:
        save_file(shards[0], os.path.join(save_dir, "model.safetensors"))
        return
    names = [
        f"model-{i + 1:05d}-of-{n:05d}.safetensors" for i in range(n)
    ]
    with ThreadPoolExecutor(max_workers=min(n_threads, n)) as ex:
        list(ex.map(
            lambda iv: save_file(
                shards[iv[0]], os.path.join(save_dir, iv[1])
            ),
            enumerate(names),
        ))
    index = {
        "metadata": {"total_size": int(sum(sizes))},
        "weight_map": {
            k: names[i] for i, shard in enumerate(shards) for k in shard
        },
    }
    with open(os.path.join(save_dir, "model.safetensors.index.json"), "w") as f:
        json.dump(index, f)


def load_hf_state_dict(load_dir: str, n_threads: int = 8) -> Dict[str, np.ndarray]:
    """Load a safetensors checkpoint dir (sharded or single-file); falls
    back to the legacy model.npz layout."""
    single = os.path.join(load_dir, "model.safetensors")
    index_path = os.path.join(load_dir, "model.safetensors.index.json")
    legacy = os.path.join(load_dir, "model.npz")
    from safetensors.numpy import load_file

    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        files = sorted(set(index["weight_map"].values()))
        out: Dict[str, np.ndarray] = {}
        with ThreadPoolExecutor(max_workers=min(n_threads, len(files))) as ex:
            for d in ex.map(
                lambda fn: load_file(os.path.join(load_dir, fn)), files
            ):
                out.update(d)
        return out
    if os.path.exists(single):
        return load_file(single)
    if os.path.exists(legacy):
        return dict(np.load(legacy))
    raise FileNotFoundError(f"no model.safetensors[.index.json] in {load_dir}")


# ---------------- high-level load/save ----------------


def load_hf_model(path_or_model, is_critic: bool = False, dtype: str = "float32"):
    """Load (config, params, tokenizer) from an HF model directory or an
    in-memory transformers model (used by tests)."""
    if isinstance(path_or_model, str):
        import transformers

        hf_cfg = transformers.AutoConfig.from_pretrained(path_or_model)
        model = transformers.AutoModelForCausalLM.from_pretrained(path_or_model)
        try:
            tokenizer = transformers.AutoTokenizer.from_pretrained(path_or_model)
        except Exception:
            tokenizer = None
    else:
        model = path_or_model
        hf_cfg = model.config
        tokenizer = None
    cfg = dataclasses.replace(config_from_hf(hf_cfg), is_critic=is_critic)
    params = params_from_hf_state_dict(model.state_dict(), cfg, dtype)
    return cfg, params, tokenizer


def save_hf_checkpoint(
    params, cfg: TransformerConfig, save_dir: str, meta: Optional[dict] = None
):
    """Publish weights in a layout consumable by BOTH the generation server
    (areal_tpu_config.json round-trip) and HF tooling (sharded safetensors +
    genuine config.json → transformers.AutoModelForCausalLM loads it).
    Replaces the r1/r2 npz layout (reference: hf_registry.py:32 save)."""
    os.makedirs(save_dir, exist_ok=True)
    sd = params_to_hf_state_dict(params, cfg)
    save_hf_state_dict(sd, save_dir)
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(hf_config_dict(cfg), f, indent=1)
    with open(os.path.join(save_dir, "areal_tpu_config.json"), "w") as f:
        json.dump(
            {"areal_tpu_config": dataclasses.asdict(cfg), "meta": meta or {}}, f
        )


def flatten_pytree(params, as_numpy: bool = False) -> Dict[str, Any]:
    """Nested-dict param pytree → flat {path: leaf} with '/'-joined keys.

    ``as_numpy=False`` keeps leaves verbatim (device arrays stay on
    device) — the weight-stream publisher/consumer use this so flattening
    a live tree never forces a d2h transfer; ``as_numpy=True`` converts
    for host serialization (checkpoint writers)."""
    out: Dict[str, Any] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            out[prefix] = np.asarray(node) if as_numpy else node

    walk("", params)
    return out


def unflatten_pytree(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        parts = path.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


# Back-compat aliases (pre-stream-sync private names).
def _flatten_pytree(params) -> Dict[str, np.ndarray]:
    return flatten_pytree(params, as_numpy=True)


_unflatten_pytree = unflatten_pytree


def save_native_checkpoint(
    params, cfg: TransformerConfig, save_dir: str, meta: Optional[dict] = None
):
    """The weight-SYNC format: the stacked param pytree saved verbatim as
    sharded safetensors — no HF-layout transposes, no re-stacking, dtype
    preserved (bf16 stays 2 bytes). The in-house generation server consumes
    this directly; HF layout (save_hf_checkpoint) is only needed for
    external-tooling interop. Replaces the reference's HF-format realloc
    dir (realhf/system/model_worker.py:1053 DISK path) with a layout that
    skips its conversion cost on both ends.

    ``areal_tpu_native.json`` is written LAST — it is the completeness
    sentinel consumers gate on."""
    os.makedirs(save_dir, exist_ok=True)
    save_hf_state_dict(_flatten_pytree(params), save_dir)
    with open(os.path.join(save_dir, "areal_tpu_native.json"), "w") as f:
        json.dump(
            {"areal_tpu_config": dataclasses.asdict(cfg), "meta": meta or {},
             "format": "native-pytree-v1"}, f
        )


def is_native_checkpoint(load_dir: str) -> bool:
    return os.path.exists(os.path.join(load_dir, "areal_tpu_native.json"))


def load_native_checkpoint(load_dir: str):
    with open(os.path.join(load_dir, "areal_tpu_native.json")) as f:
        d = json.load(f)
    cd = d["areal_tpu_config"]
    if cd.get("moe"):
        cd["moe"] = MoEConfig(**cd["moe"])
    cfg = TransformerConfig(**cd)
    params = _unflatten_pytree(load_hf_state_dict(load_dir))
    return cfg, params


def load_checkpoint_auto(load_dir: str):
    """Native if the dir is a weight-sync publish, else HF layout."""
    if is_native_checkpoint(load_dir):
        return load_native_checkpoint(load_dir)
    return load_hf_checkpoint(load_dir)


def load_hf_checkpoint(load_dir: str):
    acfg_path = os.path.join(load_dir, "areal_tpu_config.json")
    if not os.path.exists(acfg_path):
        # Legacy r2 layout kept config under config.json.
        acfg_path = os.path.join(load_dir, "config.json")
    with open(acfg_path) as f:
        d = json.load(f)
    cd = d["areal_tpu_config"]
    if cd.get("moe"):
        cd["moe"] = MoEConfig(**cd["moe"])
    cfg = TransformerConfig(**cd)
    sd = load_hf_state_dict(load_dir)
    params = params_from_hf_state_dict(sd, cfg, cfg.dtype)
    return cfg, params
